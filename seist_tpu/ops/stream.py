"""Continuous-record annotation: sliding-window inference + overlap stitch.

The reference can only score fixed 8192-sample windows one at a time
(demo_predict.py:59-97 — one window, one forward, a plot). Real
deployments pick phases over hours-long continuous records; this module
provides that as a first-class, TPU-friendly path:

    windows, offsets = sliding_windows(record, window, stride)   # host view
    probs = <jitted model forward over window batches>           # device
    curve = stitch_probs(probs, offsets, len(record))            # device
    picks = pick_peaks(curve[None, :, 1], ...)                   # device

* Windowing is offset-based; ``annotate`` slices windows per inference
  batch, so peak host memory is O(batch), independent of record length.
  The final window is right-aligned so the record tail is always covered.
* Stitching averages overlapping windows' probabilities (scatter-add of
  values and hit counts — XLA lowers this to fixed-shape ops, no host
  loop), which suppresses edge artifacts of any single window.
* ``annotate`` runs the whole thing: batches windows (padding the last
  batch so ONE compiled forward serves any record length), jits the
  forward, stitches, then reuses ops/postprocess.pick_peaks /
  detect_events for fixed-shape picking on the stitched curve.

CLI: ``tools/predict.py``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from seist_tpu.ops.postprocess import detect_events, pick_peaks


def window_offsets(record_len: int, window: int, stride: int) -> np.ndarray:
    """Window start offsets: advance by ``stride``; the last window is
    clamped to ``L - window`` (right-aligned) so the tail is always
    covered. Requires ``L >= window``."""
    if record_len < window:
        raise ValueError(f"record length {record_len} < window {window}")
    offsets = list(range(0, record_len - window + 1, stride))
    if offsets[-1] != record_len - window:
        offsets.append(record_len - window)
    return np.asarray(offsets, dtype=np.int32)


def sliding_windows(
    record: np.ndarray, window: int, stride: int
) -> Tuple[np.ndarray, np.ndarray]:
    """(L, C) record -> ((n, window, C) array, (n,) int offsets).

    Materializes all windows (copies ~window/stride x the record);
    :func:`annotate` instead slices per inference batch so peak host
    memory stays O(batch), independent of record length.
    """
    offsets = window_offsets(record.shape[0], window, stride)
    windows = np.stack([record[o : o + window] for o in offsets], axis=0)
    return windows, offsets


def stitch_probs(
    probs: jnp.ndarray,
    offsets: jnp.ndarray,
    total_len: int,
    combine: str = "mean",
) -> jnp.ndarray:
    """Combine overlapping window probabilities back onto the record.

    ``probs`` (n, window, C), ``offsets`` (n,) -> (total_len, C).
    ``combine='mean'`` averages the k covering windows (suppresses
    single-window noise); ``'max'`` takes their maximum (a pick near one
    window's edge is never attenuated by a neighbor that missed it — the
    usual choice for deployment pickers).
    """
    n, window, C = probs.shape
    pos = offsets[:, None] + jnp.arange(window)[None, :]  # (n, window)
    flat_pos = pos.reshape(-1)
    flat = probs.reshape(-1, C)
    if combine == "max":
        return jnp.zeros((total_len, C), probs.dtype).at[flat_pos].max(flat)
    if combine != "mean":
        raise ValueError(f"unknown combine {combine!r}")
    acc = jnp.zeros((total_len, C), probs.dtype).at[flat_pos].add(flat)
    hits = jnp.zeros((total_len,), probs.dtype).at[flat_pos].add(1.0)
    return acc / jnp.maximum(hits, 1.0)[:, None]


def annotate(
    apply_fn: Callable[[np.ndarray], Any],
    record: np.ndarray,
    *,
    window: int = 8192,
    stride: Optional[int] = None,
    batch_size: int = 32,
    sampling_rate: int = 50,
    ppk_threshold: float = 0.3,
    spk_threshold: float = 0.3,
    det_threshold: float = 0.5,
    min_peak_dist: float = 1.0,
    max_events: Optional[int] = None,
    combine: str = "mean",
    channel0: str,
    jitted: bool = False,
) -> Dict[str, np.ndarray]:
    """Pick P/S phases + detection intervals over a continuous record.

    ``apply_fn``: jittable forward mapping (N, window, C) float32 ->
    (N, window, 3) probabilities — a dpk-family model. ``channel0``
    (REQUIRED — a wrong guess silently inverts detections) names the
    first output channel's meaning: ``'non'`` (noise prob — phasenet,
    taskspec labels ("non","ppk","spk")) or ``'det'`` (event prob — the
    seist dpk family and eqtransformer, labels ("det","ppk","spk")); get
    it from ``taskspec.get_task_spec(model).labels[0][0]`` as
    tools/predict.py does.
    Detection strength is ``1 - curve0`` for 'non' and ``curve0`` for
    'det'. ``record``: (L, C) float32, raw (windows are z-normalized
    here, matching the reference's eval normalization,
    preprocess.py:224-242).

    ``max_events`` caps picks over the WHOLE record (pick_peaks keeps the
    topk tallest); default scales with record length (4 per window span)
    so long records aren't silently truncated.

    Under ``combine='max'`` every channel is combined in EVENT-EVIDENCE
    space (the 'non' channel via its complement): an elementwise max of
    'non' itself would let one event-missing window VETO its neighbor's
    detection — the exact edge artifact 'max' exists to prevent.

    Returns {"ppk": indices, "spk": indices, "det": (k, 2) intervals,
    "prob": (L, 3) stitched curve} with absolute sample positions;
    pick/interval arrays are unpadded. Peak host memory is O(batch_size),
    not O(record).

    ``jitted=True`` declares ``apply_fn`` already compiled (e.g. the serve
    model-pool's warm per-bucket forward) and skips the ``jax.jit`` wrap
    here — wrapping a fresh ``jax.jit`` per call would recompile the whole
    forward every time, which an online service cannot afford.
    """
    if channel0 not in ("non", "det"):
        raise ValueError(f"channel0 must be 'non' or 'det', got {channel0!r}")
    record = np.asarray(record, np.float32)
    if record.shape[0] == 0:
        raise ValueError("empty record")
    # Edge contract (pad-and-trim): a record SHORTER than one window is
    # zero right-padded to exactly one window (the pad joins the window's
    # z-normalization), scored, then trimmed — picks inside the pad are
    # dropped, detection intervals are clipped to the true last sample,
    # and "prob" is returned at the true record length. Non-stride-
    # multiple tails were already defined (right-aligned final window,
    # window_offsets). StreamSession.finish() replays this contract
    # bit-for-bit — the streaming parity pin needs it pinned here.
    true_len = record.shape[0]
    if true_len < window:
        record = np.concatenate(
            [record, np.zeros((window - true_len, record.shape[1]), np.float32)],
            axis=0,
        )
    stride = stride or window // 2
    offsets = window_offsets(record.shape[0], window, stride)
    if max_events is None:
        # Rounded up to a power of two: pick_peaks/detect_events jit on
        # static topk, so a raw 4*len(offsets) would compile a fresh
        # program per distinct record length; quantizing keeps it to
        # log-many programs. Extra capacity only adds padding slots,
        # which are stripped below.
        max_events = 1 << (max(32, 4 * len(offsets)) - 1).bit_length()

    # Function-level: importing data.preprocess executes the whole data
    # package (pandas, dataset registrations) — too heavy for a module
    # that otherwise needs only jax/numpy/postprocess.
    from seist_tpu.data.preprocess import normalize

    jit_apply = apply_fn if jitted else jax.jit(apply_fn)
    n = len(offsets)
    probs = []
    for i in range(0, n, batch_size):
        offs = offsets[i : i + batch_size]
        chunk = np.stack([record[o : o + window] for o in offs], axis=0)
        # Per-window z-normalization (ref preprocess.py:224-242, std mode);
        # time axis is 1 in the (N, window, C) chunk.
        chunk = normalize(chunk, "std", axis=1)
        pad = batch_size - chunk.shape[0]
        if pad:  # keep ONE compiled shape
            chunk = np.concatenate([chunk, chunk[-1:].repeat(pad, 0)], axis=0)
        # Stay on device: the per-chunk np.asarray readback this loop once
        # did cost one host sync PER CHUNK (the last jaxlint baseline
        # entry); outputs now accumulate as device arrays (the unpad slice
        # is a device op) and everything downstream of the concatenate —
        # stitch, pick, detect — consumes them device-side in one program
        # chain, with the single host transfer happening at the final
        # pick/detect np.asarray calls below.
        out = jit_apply(jnp.asarray(chunk))
        probs.append(out[: batch_size - pad] if pad else out)
    probs_arr = jnp.concatenate(probs, axis=0)

    invert0 = channel0 == "non"
    if combine == "max" and invert0:
        # Event-evidence space for the non channel (see docstring).
        ev = probs_arr.at[..., 0].set(1.0 - probs_arr[..., 0])
        stitched = stitch_probs(
            ev, jnp.asarray(offsets), record.shape[0], combine="max"
        )
        curve = stitched.at[..., 0].set(1.0 - stitched[..., 0])
    else:
        curve = stitch_probs(
            probs_arr, jnp.asarray(offsets), record.shape[0], combine=combine
        )

    dist = int(min_peak_dist * sampling_rate)
    ppk = np.asarray(
        pick_peaks(curve[None, :, 1], ppk_threshold, dist, max_events)
    )[0]
    spk = np.asarray(
        pick_peaks(curve[None, :, 2], spk_threshold, dist, max_events)
    )[0]
    det_strength = (1.0 - curve[:, 0]) if invert0 else curve[:, 0]
    det = np.asarray(
        detect_events(det_strength[None, :], det_threshold, max_events)
    )[0].reshape(-1, 2)
    ppk = ppk[ppk >= 0]
    spk = spk[spk >= 0]
    # >= keeps real single-sample events (on == off); the [1, 0]
    # padding pair has off < on and is stripped.
    det = det[det[:, 1] >= det[:, 0]]
    if true_len < record.shape[0]:  # trim the short-record pad back off
        ppk = ppk[ppk < true_len]
        spk = spk[spk < true_len]
        det = det[det[:, 0] < true_len]
        det = np.minimum(det, true_len - 1)
    return {
        "ppk": ppk,
        "spk": spk,
        "det": det,
        "prob": np.asarray(curve)[:true_len],
    }
