"""Test-time result accumulation -> CSV (ref training/postprocess.py:253-338).

``ResultSaver`` collects per-batch meta data, targets and processed results
and writes one CSV with ``<meta>``, ``pred_<task>`` and ``tgt_<task>``
columns — the same file contract as the reference's
``test_results_<dataset>.csv`` (validate.py:129-131).
"""

from __future__ import annotations

import json
import os
from collections import defaultdict
from typing import Any, Dict, List, Sequence, Tuple, Union

import numpy as np

from seist_tpu import taskspec
from seist_tpu.utils.logger import logger


class ResultSaver:
    def __init__(self, item_names: Sequence[str]):
        self._item_names = list(item_names)
        self._results_dict: Dict[str, list] = defaultdict(list)
        self._warned_unknown = False

    @staticmethod
    def _to_list(v: Any) -> list:
        if isinstance(v, (np.ndarray,)) or hasattr(v, "__array__"):
            v = np.asarray(v).tolist()
        if not isinstance(v, list):
            raise TypeError(f"Unknown data type: {type(v)}")
        return v

    def _convert_type(self, v: Any) -> list:
        """Flatten nested per-row lists to CSV-friendly cells
        (ref postprocess.py:258-274): [] -> '', [x] -> x, [a,b] -> 'a,b'."""
        v = self._to_list(v)
        for i in range(len(v)):
            if isinstance(v[i], list):
                if len(v[i]) == 0:
                    v[i] = ""
                elif len(v[i]) == 1:
                    v[i] = v[i][0]
                else:
                    v[i] = ",".join(str(x) for x in v[i])
        return v

    def _process_item(self, k: str, v: Any, prefix: str = "") -> Tuple[str, Any]:
        """One-hot -> argmax index; strip ppk/spk padding (> 0 kept)
        (ref postprocess.py:276-289)."""
        if k in taskspec.IO_ITEMS and taskspec.get_kind(k) == taskspec.ONEHOT:
            v = np.argmax(np.asarray(v), axis=-1)
        if k in ("ppk", "spk"):
            v = self._to_list(v)
            v = [[x for x in row if x > 0] for row in v]
        return f"{prefix}{k}", v

    def append(
        self,
        batch_meta_data: Dict[str, list],
        targets: Dict[str, Any],
        results: Dict[str, Any],
    ) -> None:
        """Append one batch of rows (ref postprocess.py:291-329)."""
        assert isinstance(batch_meta_data, dict), f"{type(batch_meta_data)}"
        known = set(results) | set(targets)
        unknown = known - set(self._item_names)
        missing = set(self._item_names) - known
        if unknown and not self._warned_unknown:
            logger.warning(
                f"[ResultSaver] unknown names in outputs: {unknown}, "
                f"expected: {self._item_names}"
            )
            self._warned_unknown = True
        if missing:
            raise AttributeError(
                f"[ResultSaver] not found names: {missing}, "
                f"expected: {self._item_names}"
            )

        for k, v in batch_meta_data.items():
            self._results_dict[k].extend(self._convert_type(list(v)))

        for k in self._item_names:
            pred_k, pred_v = self._process_item(k, results[k], prefix="pred_")
            self._results_dict[pred_k].extend(self._convert_type(pred_v))
            tgt_k, tgt_v = self._process_item(k, targets[k], prefix="tgt_")
            self._results_dict[tgt_k].extend(self._convert_type(tgt_v))

    def save_as_csv(self, path: str) -> None:
        import pandas as pd

        sdir = os.path.dirname(path)
        if sdir and not os.path.exists(sdir):
            os.makedirs(sdir, exist_ok=True)
        pd.DataFrame(self._results_dict).to_csv(path)


def catalog_rows(
    decoded: Dict[str, Dict[str, Any]],
    *,
    n_valid: int,
    row_ids: Sequence[int],
    keys: Union[Sequence[str], None] = None,
    stations: Union[Dict[str, Dict[str, Any]], None] = None,
) -> List[Dict[str, Any]]:
    """Batched head results -> JSON-able catalog rows, one per waveform
    (the repick engine's row builder; docs/DATA.md "Batch re-picking").

    ``decoded`` is ``{task: {name: HOST array}}`` from
    ``ops.postprocess.decode_head_batch`` AFTER the caller's single
    batched ``jax.device_get``; padding rows (``>= n_valid``, the static
    batch shape's tail fill) are dropped. Per name:

    * ``ppk``/``spk`` -> the kept pick sample indices (padding stripped);
    * ``det`` -> ``[onset, offset]`` sample pairs (empty pairs stripped);
    * ONEHOT labels -> ``{"class": argmax, "scores": [...]}``;
    * VALUE labels -> the scalar, rounded to 6 decimals (float repr is
      then deterministic — the catalog byte-identity contract).

    Label names are globally unique across the five task heads, so a
    group's heads flatten into one row without collisions.

    ``stations`` (optional ``{key: {"id", "network", "lat", "lon"}}``):
    station provenance looked up by each row's ``key`` and embedded as
    the row's ``station`` field — the same metadata block /predict and
    /stream carry, so a repick catalog can feed cross-station
    association without a sidecar join. Keys with no entry simply get
    no field (byte-identity for rows is preserved either way).
    """
    rows: List[Dict[str, Any]] = []
    host = {
        task: {k: np.asarray(v) for k, v in outs.items()}
        for task, outs in decoded.items()
    }
    for j in range(int(n_valid)):
        row: Dict[str, Any] = {"row": int(row_ids[j])}
        if keys is not None:
            row["key"] = str(keys[j])
            if stations is not None:
                st = stations.get(row["key"])
                if st is not None:
                    row["station"] = st
        for outs in host.values():
            for name, arr in outs.items():
                if name in ("ppk", "spk"):
                    idxs = arr[j]
                    row[name] = [int(i) for i in idxs[idxs >= 0]]
                elif name == "det":
                    pairs = arr[j].reshape(-1, 2)
                    pairs = pairs[pairs[:, 1] >= pairs[:, 0]]
                    row[name] = [[int(a), int(b)] for a, b in pairs]
                elif (
                    name in taskspec.IO_ITEMS
                    and taskspec.get_kind(name) == taskspec.ONEHOT
                ):
                    scores = arr[j].reshape(-1)
                    row[name] = {
                        "class": int(np.argmax(scores)),
                        "scores": [round(float(s), 6) for s in scores],
                    }
                else:
                    row[name] = round(float(arr[j].reshape(-1)[0]), 6)
        rows.append(row)
    return rows


def catalog_row_lines(rows: Sequence[Dict[str, Any]]) -> List[str]:
    """Serialize catalog rows to canonical JSONL lines (sorted keys,
    compact separators): the byte-identity contract's serialization half
    — same rows => same bytes, whatever process wrote them."""
    return [
        json.dumps(r, sort_keys=True, separators=(",", ":")) + "\n"
        for r in rows
    ]
