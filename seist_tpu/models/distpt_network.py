"""dist-PT network — causal dilated TCN for distance + P-travel-time.

Architecture parity with the reference ``models/distpt_network.py:37-181``
(Mousavi & Beroza 2020). Registered but config-disabled in the reference
(config.py:112-125) because DiTing lacks travel-time labels; kept here for
API-surface parity.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn

from seist_tpu.models import common
from seist_tpu.registry import register_model

Array = jnp.ndarray


class ResBlock(nn.Module):
    """Two causal dilated convs + 1x1 residual (ref: distpt_network.py:37-87).
    Returns (residual_out, pre_residual)."""

    out_channels: int
    kernel_size: int
    dilation: int
    drop_rate: float

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Tuple[Array, Array]:
        for i in range(2):
            x = common.causal_pad_1d(x, self.kernel_size, self.dilation)
            x = nn.Conv(
                self.out_channels,
                (self.kernel_size,),
                kernel_dilation=(self.dilation,),
                padding="VALID",
                name=f"conv{i}",
            )(x)
            x = common.make_norm("batch", use_running_average=not train, name=f"bn{i}")(x)
            x = nn.relu(x)
            # Dropout1d: drop whole channels (broadcast over the L axis)
            x = nn.Dropout(
                self.drop_rate, broadcast_dims=(1,), deterministic=not train
            )(x)
        x1 = x + nn.Dense(self.out_channels, name="conv_out")(x)
        return x1, x


class TemporalConvLayer(nn.Module):
    """1x1 in-proj + dilated ResBlocks, summed skip connections
    (ref: distpt_network.py:90-134)."""

    out_channels: int = 64
    kernel_size: int = 2
    num_conv_blocks: int = 1
    dilations: Sequence[int] = (1, 2, 4, 8, 16, 32)
    drop_rate: float = 0.0
    return_sequences: bool = False

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        x = nn.Dense(self.out_channels, name="conv_in")(x)
        shortcuts = []
        for b, dilation in enumerate(list(self.dilations) * self.num_conv_blocks):
            x, sc = ResBlock(
                out_channels=self.out_channels,
                kernel_size=self.kernel_size,
                dilation=dilation,
                drop_rate=self.drop_rate,
                name=f"block{b}",
            )(x, train)
            shortcuts.append(sc)
        x = sum(shortcuts)
        if not self.return_sequences:
            x = x[:, -1, :]
        return x


class DistPTNetwork(nn.Module):
    """(N, L, C) -> ((N, 2) dist, (N, 2) p-travel) (ref: distpt_network.py:137-181)."""

    in_channels: int = 3
    tcn_channels: int = 20
    kernel_size: int = 6
    num_conv_blocks: int = 1
    dilations: Sequence[int] = tuple(2**i for i in range(11))
    drop_rate: float = 0.1

    @nn.compact
    def __call__(self, x: Array, train: bool = False) -> Tuple[Array, Array]:
        x = TemporalConvLayer(
            out_channels=self.tcn_channels,
            kernel_size=self.kernel_size,
            num_conv_blocks=self.num_conv_blocks,
            dilations=self.dilations,
            drop_rate=self.drop_rate,
            name="tcn",
        )(x, train)
        do = nn.Dense(2, name="lin_dist")(x)
        po = nn.Dense(2, name="lin_ptrvl")(x)
        return do, po


@register_model
def distpt_network(**kwargs) -> DistPTNetwork:
    kwargs.pop("in_samples", None)
    kwargs = {k: v for k, v in kwargs.items() if k in DistPTNetwork.__dataclass_fields__}
    return DistPTNetwork(**kwargs)
