"""Loss library (8 losses), channel-last.

TPU-native re-design of the reference's ``models/loss.py:8-210``. Semantics
match the reference exactly — losses consume **probabilities** (models end in
softmax/sigmoid) with eps=1e-6 inside logs — but arrays are channels-last:
dense outputs are ``(N, L, C)`` and class outputs ``(N, Classes)``, so the
class/channel axis is always ``-1`` (the reference reduces dim=1 on
``(N, C, L)``; the reductions are equivalent).

Losses are plain callables usable inside ``jax.jit``/``jax.grad``.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

_EPS = 1e-6

Array = jnp.ndarray


def _as_weight(weight) -> Array:
    """Normalize a reference-style weight spec (possibly nested lists like
    ``[[0.5], [1], [1]]``) to a flat per-channel vector."""
    if weight is None:
        return jnp.asarray(1.0, dtype=jnp.float32)
    w = np.asarray(weight, dtype=np.float32).reshape(-1)
    return jnp.asarray(w)


class CELoss:
    """Cross entropy on probability outputs (ref: loss.py:8-29).

    Input shape: ``(N, L, C)`` or ``(N, Classes)``.
    """

    def __init__(self, weight=None):
        self.weight = _as_weight(weight)

    def __call__(self, preds: Array, targets: Array) -> Array:
        loss = -targets * jnp.log(preds + _EPS)
        loss = loss * self.weight
        return loss.sum(axis=-1).mean()


class BCELoss:
    """Binary cross entropy on probability outputs (ref: loss.py:32-56)."""

    def __init__(self, weight=None):
        self.weight = _as_weight(weight)

    def __call__(self, preds: Array, targets: Array) -> Array:
        loss = -(
            targets * jnp.log(preds + _EPS)
            + (1.0 - targets) * jnp.log(1.0 - preds + _EPS)
        )
        loss = loss * self.weight
        return loss.mean()


class FocalLoss:
    """Focal loss (ref: loss.py:59-92). ``has_softmax`` applies softmax over
    the class axis (the reference's dim=1 on logits)."""

    def __init__(self, gamma: float = 2.0, weight=None, has_softmax: bool = True):
        self.gamma = gamma
        self.weight = _as_weight(weight)
        self.has_softmax = has_softmax

    def __call__(self, preds: Array, targets: Array) -> Array:
        if self.has_softmax:
            preds = jnp.exp(preds - jnp.max(preds, axis=-1, keepdims=True))
            preds = preds / preds.sum(axis=-1, keepdims=True)
        loss = -targets * jnp.log(preds + _EPS)
        loss = loss * jnp.power(1.0 - preds, self.gamma)
        loss = loss * self.weight
        return loss.sum(axis=-1).mean()


class BinaryFocalLoss:
    """Binary focal loss on sigmoid outputs (ref: loss.py:95-130)."""

    def __init__(self, gamma: float = 2.0, alpha: float = 1.0, weight=None):
        self.gamma = gamma
        self.alpha = alpha
        self.weight = _as_weight(weight)

    def __call__(self, preds: Array, targets: Array) -> Array:
        loss = -(
            self.alpha
            * jnp.power(1.0 - preds, self.gamma)
            * targets
            * jnp.log(preds + _EPS)
            + (1.0 - self.alpha)
            * jnp.power(preds, self.gamma)
            * (1.0 - targets)
            * jnp.log(1.0 - preds + _EPS)
        )
        loss = loss * self.weight
        return loss.mean()


class MSELoss:
    """Mean squared error (ref: loss.py:133-152)."""

    def __init__(self, weight=None):
        self.weight = _as_weight(weight)

    def __call__(self, preds: Array, targets: Array) -> Array:
        loss = (preds - targets) ** 2
        loss = loss * self.weight
        return loss.mean()


class HuberLoss:
    """Huber loss, delta=1, mean reduction (torch.nn.HuberLoss parity;
    re-exported by the reference at loss.py:3)."""

    def __init__(self, delta: float = 1.0):
        self.delta = delta

    def __call__(self, preds: Array, targets: Array) -> Array:
        err = preds - targets
        abs_err = jnp.abs(err)
        quad = jnp.minimum(abs_err, self.delta)
        lin = abs_err - quad
        return (0.5 * quad**2 + self.delta * lin).mean()


class CombinationLoss:
    """Weighted sum of per-output losses for multi-task models
    (ref: loss.py:155-190)."""

    def __init__(
        self,
        losses: Sequence[Callable],
        losses_weights: Optional[Sequence[float]] = None,
    ):
        assert len(losses) > 0
        if len(losses) == 1:
            raise ValueError(
                "CombinationLoss requires at least two loss modules; "
                f"use {losses[0]} directly instead."
            )
        if losses_weights is not None:
            assert len(losses) == len(losses_weights)
            self.losses_weights = list(losses_weights)
        else:
            self.losses_weights = [1.0] * len(losses)
        self.losses = [L() for L in losses]

    @property
    def reduction(self) -> str:
        """'sum' if any component is sum-reduced (a weighted sum of sums is
        still a sum over the batch), else 'mean'."""
        return (
            "sum"
            if any(getattr(fn, "reduction", "mean") == "sum" for fn in self.losses)
            else "mean"
        )

    def __call__(self, preds: Tuple[Array, ...], targets: Tuple[Array, ...]) -> Array:
        total = 0.0
        for pred, target, loss_fn, w in zip(
            preds, targets, self.losses, self.losses_weights
        ):
            total = total + loss_fn(pred, target) * w
        return total


class MousaviLoss:
    """Heteroscedastic regression loss for MagNet / dist-PT
    (ref: loss.py:193-210). ``preds`` is ``(N, 2)``: (y_hat, log sigma^2).

    Sum-reduced over the batch (matching the reference's ``torch.sum``) —
    consumers that decompose losses per-sample (the masked eval step) check
    ``reduction`` to pick the right recombination.
    """

    reduction = "sum"

    def __call__(self, preds: Array, targets: Array) -> Array:
        y_hat = preds[:, 0].reshape(-1, 1)
        s = preds[:, 1].reshape(-1, 1)
        return jnp.sum(
            0.5 * jnp.exp(-1.0 * s) * jnp.square(jnp.abs(targets - y_hat)) + 0.5 * s
        )


__all__ = [
    "CELoss",
    "BCELoss",
    "FocalLoss",
    "BinaryFocalLoss",
    "MSELoss",
    "HuberLoss",
    "CombinationLoss",
    "MousaviLoss",
]
