"""Shared building blocks for the model zoo (channels-last, Flax linen).

Geometry parity helpers mirror the reference exactly (a stated hard part,
SURVEY.md §7): ``auto_pad_1d`` reproduces ``models/seist.py:12-48`` /
``magnet.py:16-33``; ceil-mode pooling reproduces torch's
``MaxPool1d/AvgPool1d(ceil_mode=True)`` including the partial-window divisor
of AvgPool; ``interpolate_linear`` reproduces ``F.interpolate(mode='linear',
align_corners=False)``.

All arrays are ``(N, L, C)``. All modules take ``train: bool`` and use the
'dropout' RNG stream for dropout and stochastic depth.
"""

from __future__ import annotations

import contextlib
import math
import os
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

Array = jnp.ndarray

# Default init mirroring the SeisT reference (trunc normal 0.02,
# seist.py:816-831). Other models use flax defaults (init distribution is not
# a behavior-parity surface).
trunc_normal_init = nn.initializers.truncated_normal(stddev=0.02)


# --------------------------------------------------------------------- padding
def auto_pad_amount(length: int, kernel_size: int, stride: int = 1) -> Tuple[int, int]:
    """'same'-style asymmetric padding so L_out = ceil(L/stride)
    (ref: seist.py:41-47)."""
    assert kernel_size >= stride, (
        f"`kernel_size` must be >= `stride`, got {kernel_size}, {stride}"
    )
    pds = (stride - (length % stride)) % stride + kernel_size - stride
    return pds // 2, pds - pds // 2


def auto_pad_1d(
    x: Array, kernel_size: int, stride: int = 1, padding_value: float = 0.0
) -> Array:
    """Pad the length axis (-2) of an (N, L, C) array (ref: seist.py:12-48)."""
    lp, rp = auto_pad_amount(x.shape[-2], kernel_size, stride)
    pads = [(0, 0)] * x.ndim
    pads[-2] = (lp, rp)
    return jnp.pad(x, pads, constant_values=padding_value)


def same_pad_amount(kernel_size: int) -> Tuple[int, int]:
    """torch-style static 'same' padding for stride-1 convs
    (ref: phasenet.py:45-48)."""
    return (kernel_size - 1) // 2, kernel_size - 1 - (kernel_size - 1) // 2


def same_pad_1d(x: Array, kernel_size: int, padding_value: float = 0.0) -> Array:
    lp, rp = same_pad_amount(kernel_size)
    pads = [(0, 0)] * x.ndim
    pads[-2] = (lp, rp)
    return jnp.pad(x, pads, constant_values=padding_value)


def causal_pad_1d(x: Array, kernel_size: int, dilation: int = 1) -> Array:
    """Left-only padding for causal TCNs (ref: distpt_network.py:17-34)."""
    pds = (kernel_size - 1) * dilation
    pads = [(0, 0)] * x.ndim
    pads[-2] = (pds, 0)
    return jnp.pad(x, pads)


def channel_pad_multiple() -> int:
    """``SEIST_CHANNEL_PAD``: round conv OUT-channel axes up to this
    multiple in the composed/fused dense-conv lowerings (0 = off,
    default). Candidate MFU lowering for the tiny-channel stems
    (out_dim 8-24 vs the TPU's 128-lane registers; VERDICT r4 #2
    escalation step 1): zero-padded out-channels compute zeros that are
    sliced away before BN, so values and the checkpoint tree are
    untouched — only XLA's layout/tiling choice changes. Promote or
    revert ON THE MEASURED A/B (tools/r4_silicon.sh iso_channel_pad);
    until then it is off everywhere."""
    return int(os.environ.get("SEIST_CHANNEL_PAD", "0"))


def pad_kernel_out_channels(kernel: Array) -> Tuple[Array, int]:
    """Zero-pad a conv kernel's trailing (out-channel) axis up to the
    SEIST_CHANNEL_PAD multiple. Returns (kernel, true_out_channels);
    slice the conv result back to ``true_out_channels`` channels."""
    out = kernel.shape[-1]
    mult = channel_pad_multiple()
    if mult <= 0 or out % mult == 0:
        return kernel, out
    pads = [(0, 0)] * (kernel.ndim - 1) + [(0, mult - out % mult)]
    return jnp.pad(kernel, pads), out


# --------------------------------------------------------------------- pooling
def ceil_len(length: int, stride: int) -> int:
    return -(-length // stride)


def max_pool_1d_ceil(x: Array, kernel_size: int) -> Array:
    """MaxPool1d(k, ceil_mode=True) parity: stride=k, right-pad with -inf."""
    L = x.shape[-2]
    pad_r = ceil_len(L, kernel_size) * kernel_size - L
    return jax.lax.reduce_window(
        x,
        -jnp.inf,
        jax.lax.max,
        window_dimensions=(1, kernel_size, 1),
        window_strides=(1, kernel_size, 1),
        padding=((0, 0), (0, pad_r), (0, 0)),
    )


def avg_pool_1d_ceil(x: Array, kernel_size: int) -> Array:
    """AvgPool1d(k, ceil_mode=True) parity: the partial last window divides by
    the count of *valid* elements (verified against torch)."""
    L = x.shape[-2]
    pad_r = ceil_len(L, kernel_size) * kernel_size - L
    sums = jax.lax.reduce_window(
        x,
        0.0,
        jax.lax.add,
        window_dimensions=(1, kernel_size, 1),
        window_strides=(1, kernel_size, 1),
        padding=((0, 0), (0, pad_r), (0, 0)),
    )
    # Valid-count divisor per output position (static, computed in Python).
    n_out = ceil_len(L, kernel_size)
    counts = jnp.full((n_out,), float(kernel_size))
    last_valid = L - (n_out - 1) * kernel_size
    counts = counts.at[-1].set(float(last_valid))
    return sums / counts[None, :, None].astype(x.dtype)


def max_pool_1d(x: Array, kernel_size: int) -> Array:
    """MaxPool1d(k) floor-mode parity (drops the trailing partial window)."""
    L = x.shape[-2]
    n_out = L // kernel_size
    return jax.lax.reduce_window(
        x[:, : n_out * kernel_size],
        -jnp.inf,
        jax.lax.max,
        window_dimensions=(1, kernel_size, 1),
        window_strides=(1, kernel_size, 1),
        padding="VALID",
    )


def global_avg_pool(x: Array) -> Array:
    """AdaptiveAvgPool1d(1) + flatten: (N, L, C) -> (N, C)."""
    return x.mean(axis=-2)


# ---------------------------------------------------------------- interpolate
def interpolate_linear(x: Array, out_size: int) -> Array:
    """F.interpolate(mode='linear', align_corners=False) parity for (N, L, C).

    src = (dst + 0.5) * L_in/L_out - 0.5, clamped; linear blend of the two
    nearest source samples (ref usage: seist.py:566, ditingmotion nearest uses
    interpolate_nearest below).

    Integer upscale factors (the dpk head's whole ladder) take a pure
    arithmetic path — per output phase j the source pair is a fixed
    (shift, weight), so the result is r weighted blends of two shifted
    copies, interleaved by reshape. No gather: TPU lowers this to plain
    vector ops instead of a gather HLO.
    """
    L_in = x.shape[-2]
    if L_in == out_size:
        return x
    if out_size % L_in == 0:
        return _interpolate_linear_intscale(x, out_size // L_in)
    scale = L_in / out_size
    dst = jnp.arange(out_size, dtype=jnp.float32)
    src = (dst + 0.5) * scale - 0.5
    src = jnp.clip(src, 0.0, L_in - 1)
    lo = jnp.floor(src).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, L_in - 1)
    w = (src - lo.astype(jnp.float32))[None, :, None].astype(x.dtype)
    return x[:, lo, :] * (1.0 - w) + x[:, hi, :] * w


def _interpolate_linear_intscale(x: Array, r: int) -> Array:
    """Gather-free linear upsampling by integer factor ``r``.

    For output index d = i*r + j: src = i + (j + 0.5 - r/2)/r, so phase j
    blends x[i] with its left (o_j < 0) or right (o_j > 0) neighbor with a
    static weight; edge clamping reproduces the gather path's jnp.clip.
    """
    x_prev = jnp.concatenate([x[:, :1], x[:, :-1]], axis=1)
    x_next = jnp.concatenate([x[:, 1:], x[:, -1:]], axis=1)
    phases = []
    for j in range(r):
        o = (j + 0.5 - r / 2.0) / r
        if o < 0:
            phases.append(x * (1.0 + o) + x_prev * (-o))
        elif o > 0:
            phases.append(x * (1.0 - o) + x_next * o)
        else:
            phases.append(x)
    out = jnp.stack(phases, axis=2)  # (N, L, r, C)
    n, l, _, c = out.shape
    return out.reshape(n, l * r, c)


def interpolate_nearest(x: Array, out_size: int) -> Array:
    """F.interpolate(mode='nearest') parity for (N, L, C).

    Integer upscale factors take the gather-free ``jnp.repeat`` path —
    ``floor(d * L/out)`` with ``out = r*L`` is exactly ``d // r`` — so the
    backward is a clean windowed reduce instead of a scatter (same
    motivation as the integer path of :func:`interpolate_linear`)."""
    L_in = x.shape[-2]
    if L_in == out_size:
        return x
    if out_size % L_in == 0:
        return jnp.repeat(x, out_size // L_in, axis=-2)
    idx = jnp.floor(jnp.arange(out_size, dtype=jnp.float32) * (L_in / out_size))
    return x[:, idx.astype(jnp.int32), :]


def upsample_x2(x: Array) -> Array:
    """nn.Upsample(scale_factor=2) (nearest) parity (ref: eqtransformer.py:384)."""
    return jnp.repeat(x, 2, axis=-2)


# --------------------------------------------------------------------- helpers
def make_divisible(v: int, divisor: int) -> int:
    """Channel rounding (ref: seist.py:51-60)."""
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


# --------------------------------------------------------------------- modules
class DepthwiseConv1D(nn.Module):
    """Depthwise conv1d with a TPU-friendly shift-FMA lowering.

    Param tree matches ``nn.Conv(features, (k,), feature_group_count=
    features)`` exactly — ``kernel`` of shape (k, 1, C) — so checkpoints and
    the torch converter are unaffected by the impl choice.

    Why not XLA's grouped conv: with the SeisT stem's tiny channel counts
    (8-24 vs the TPU's 128-wide lanes, seist.py presets) the grouped-conv
    lowering runs at <1% MFU and dominates the whole model's step time
    (BASELINE.md round-2 matrix: seist_s 121 ms/step vs phasenet 15 ms at
    comparable FLOPs). ``impl='shift'`` computes
    ``y[n,l,c] = sum_j x[n, l*s+j, c] * w[j,c]`` as k strided-slice
    multiply-adds — pure VPU elementwise work XLA fuses into one kernel.
    ``impl='grouped'`` keeps the lax.conv path (used off-TPU where grouped
    convs lower fine and for A/B benchmarking via SEIST_DWCONV_IMPL).
    """

    features: int
    kernel_size: int
    stride: int = 1
    kernel_init: Any = trunc_normal_init
    # None -> env SEIST_DWCONV_IMPL, else 'shift' on TPU / 'grouped' off-TPU
    impl: Optional[str] = None

    @nn.compact
    def __call__(self, x: Array) -> Array:
        kernel = self.param(
            "kernel", self.kernel_init, (self.kernel_size, 1, self.features)
        )
        impl = self.impl or os.environ.get("SEIST_DWCONV_IMPL") or (
            "shift" if jax.default_backend() == "tpu" else "grouped"
        )
        if impl not in ("shift", "grouped"):
            raise ValueError(f"unknown depthwise impl {impl!r}")
        if impl == "grouped":
            return jax.lax.conv_general_dilated(
                x,
                kernel.astype(x.dtype),
                window_strides=(self.stride,),
                padding="VALID",
                dimension_numbers=("NWC", "WIO", "NWC"),
                feature_group_count=self.features,
            )
        return depthwise_shift_fma(
            x, kernel[:, 0, :].astype(x.dtype), self.stride
        )


def depthwise_shift_fma(x: Array, w: Array, stride: int) -> Array:
    """VALID depthwise conv as k shifted multiply-adds.

    ``x`` is (N, L, C), ``w`` is (k, C); returns (N, L_out, C). Pure VPU
    elementwise work that XLA fuses into one kernel — the lowering behind
    :class:`DepthwiseConv1D` (impl='shift'), shared with the merged stem
    path in models/seist.py which runs it on a zero-padded multi-kernel
    bank.

    For ``stride > 1`` the taps are NOT taken as strided slices
    ``x[..., j:j+span:s, :]``: the transpose (gradient) of a strided slice
    lowers on TPU to generic scatter-adds with s32 index vectors and flips
    the activation layout to batch-minor with full-tensor copies — profiled
    at ~6 ms/step in each of SeisT's two stride-2 stems (the same pathology
    that sank the merged-stem lowering, BASELINE.md). Instead the length
    axis is phase-split by a reshape ``(N, L/s, s, C)``; tap ``j`` is then a
    *contiguous* slice of phase plane ``j % s`` shifted by ``j // s``, whose
    gradient is a plain zero-pad that XLA fuses (pad_add_fusion) like the
    stride-1 case."""
    k, s = int(w.shape[0]), stride
    out_len = (x.shape[-2] - k) // s + 1
    if s == 1:
        acc = x[..., 0:out_len, :] * w[0]
        for j in range(1, k):
            acc = acc + x[..., j : j + out_len, :] * w[j]
        return acc
    # Right-pad with zeros to a multiple of s covering every tap's window.
    # The padding is never read: tap j uses phase rows j//s .. j//s+out_len-1
    # and (out_len-1) + (k-1)//s < ceil(L/s) by construction.
    lead = x.shape[:-2]
    L, C = x.shape[-2], x.shape[-1]
    n_rows = -(-L // s)
    pads = [(0, 0)] * x.ndim
    pads[-2] = (0, n_rows * s - L)
    xp = jnp.pad(x, pads).reshape(*lead, n_rows, s, C)
    acc = None
    for phase in range(s):
        plane = xp[..., :, phase, :]
        taps = [j for j in range(k) if j % s == phase]
        if not taps:
            continue
        part = plane[..., taps[0] // s : taps[0] // s + out_len, :] * w[taps[0]]
        for j in taps[1:]:
            part = part + plane[..., j // s : j // s + out_len, :] * w[j]
        acc = part if acc is None else acc + part
    return acc


class GroupedConv1D(nn.Module):
    """Grouped conv1d with selectable TPU lowerings.

    Param tree matches ``nn.Conv(features, (k,), feature_group_count=G)``
    — ``kernel`` of shape (k, Cin/G, Cout), output feature o served by
    group ``o // (Cout/G)`` — so checkpoints/converters are unaffected.

    Lowerings (pick via ``impl`` or env SEIST_GCONV_IMPL; see
    DepthwiseConv1D for the small-channel TPU context):

    * ``grouped`` — XLA's native grouped conv.
    * ``einsum``  — k shifted batched matmuls
      ``y[n,l,g,e] = sum_j sum_d x[n, l*s+j, g, d] * w[j,d,g,e]``.
    * ``dense``   — expand to a block-diagonal DENSE kernel and run one
      ordinary conv: G× more FLOPs, but dense conv1d is the one shape XLA
      maps well onto the MXU at these sizes (phasenet's 4.1% vs SeisT's
      0.8% MFU, BASELINE.md) and the FLOPs are ~2% of peak anyway.
    """

    features: int
    group_count: int
    kernel_size: int
    stride: int = 1
    kernel_init: Any = trunc_normal_init
    # None -> env SEIST_GCONV_IMPL, else 'dense' on TPU / 'grouped' off-TPU
    impl: Optional[str] = None

    @nn.compact
    def __call__(self, x: Array) -> Array:
        cin = x.shape[-1]
        g = self.group_count
        if cin % g or self.features % g:
            raise ValueError(
                f"channels {cin}->{self.features} not divisible by {g} groups"
            )
        ci, co = cin // g, self.features // g
        kernel = self.param(
            "kernel", self.kernel_init, (self.kernel_size, ci, self.features)
        )
        impl = self.impl or os.environ.get("SEIST_GCONV_IMPL") or (
            "dense" if jax.default_backend() == "tpu" else "grouped"
        )
        if impl not in ("grouped", "einsum", "dense"):
            raise ValueError(f"unknown grouped impl {impl!r}")
        k, s = self.kernel_size, self.stride
        kern = kernel.astype(x.dtype)
        if impl == "grouped":
            return jax.lax.conv_general_dilated(
                x, kern,
                window_strides=(s,),
                padding="VALID",
                dimension_numbers=("NWC", "WIO", "NWC"),
                feature_group_count=g,
            )
        if impl == "einsum":
            n, L = x.shape[0], x.shape[1]
            out_len = (L - k) // s + 1
            span = (out_len - 1) * s + 1
            xg = x.reshape(n, L, g, ci)
            # o = grp*co + og  =>  (k, ci, g, co) with g the major O axis.
            wk = kern.reshape(k, ci, g, co)
            acc = jnp.einsum(
                "nlgd,dge->nlge", xg[:, 0:span:s], wk[0]
            )
            for j in range(1, k):
                acc = acc + jnp.einsum(
                    "nlgd,dge->nlge", xg[:, j : j + span : s], wk[j]
                )
            return acc.reshape(n, out_len, self.features)
        # dense: scatter the grouped kernel into a block-diagonal (k, Cin,
        # Cout) kernel; the masked positions are structural zeros, so
        # gradients to them vanish and the param stays exactly grouped.
        wg = kern.reshape(k, ci, g, co)
        dense = jnp.zeros((k, cin, self.features), x.dtype)
        for grp in range(g):
            dense = dense.at[
                :, grp * ci : (grp + 1) * ci, grp * co : (grp + 1) * co
            ].set(wg[:, :, grp])
        return jax.lax.conv_general_dilated(
            x, dense,
            window_strides=(s,),
            padding="VALID",
            dimension_numbers=("NWC", "WIO", "NWC"),
        )


# Cross-framework mask injection for DropPath (training-dynamics parity,
# tools/train_dynamics.py): when active, every train-mode DropPath call
# consumes the next row of a shared (max_calls, batch) uniform array
# instead of drawing from the flax 'dropout' stream, in call order — the
# torch reference's stubbed timm DropPath consumes the SAME rows in the
# same order, so both frameworks drop identical residual paths. The rows
# are uniforms (not thresholded masks) so each instance applies its OWN
# keep probability. The context is read at trace time; pass the uniforms
# as an argument of the jitted step so the compiled program threads them.
_DROPPATH_INJECT: Optional[dict] = None


@contextlib.contextmanager
def droppath_mask_injection(uniforms):
    """Route DropPath randomness to shared ``uniforms`` rows for the
    duration of the context (trace-time). Yields the injection record;
    after the traced/eager call its ``"i"`` holds the number of
    DropPath calls that consumed a row."""
    global _DROPPATH_INJECT
    prev = _DROPPATH_INJECT
    record = {"uniforms": uniforms, "i": 0}
    _DROPPATH_INJECT = record
    try:
        yield record
    finally:
        _DROPPATH_INJECT = prev


class DropPath(nn.Module):
    """Per-sample stochastic depth (timm DropPath parity, scale_by_keep)."""

    rate: float

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        if not train or self.rate <= 0.0:
            return x
        keep = 1.0 - self.rate
        shape = (x.shape[0],) + (1,) * (x.ndim - 1)
        if _DROPPATH_INJECT is not None:
            inj = _DROPPATH_INJECT
            u = inj["uniforms"][inj["i"]]
            inj["i"] += 1
            mask = (u < keep).reshape(shape)
        else:
            rng = self.make_rng("dropout")
            mask = jax.random.bernoulli(rng, keep, shape)
        return jnp.where(mask, x / keep, 0.0)


class ScaledActivation(nn.Module):
    """activation(x) * scale (ref: seist.py:63-70); bounds regression heads."""

    act: Callable[[Array], Array]
    scale_factor: float

    def __call__(self, x: Array) -> Array:
        return self.act(x) * self.scale_factor


def gelu(x: Array) -> Array:
    """Exact (erf) GELU — torch ``nn.GELU()`` parity. flax's ``nn.gelu``
    defaults to the tanh approximation, which drifts up to ~1e-3 per layer
    and breaks golden-parity comparison against the shipped checkpoints."""
    import jax

    return jax.nn.gelu(x, approximate=False)


# torch BatchNorm1d defaults (torch momentum 0.1 == flax-convention 0.9).
# Single source of truth for BOTH BatchNorm1dParity and merged lowerings
# that re-derive its math (models/seist.py StemBlock._merged_paths).
BN_MOMENTUM = 0.9
BN_EPSILON = 1e-5


class BatchNorm1dParity(nn.Module):
    """BatchNorm over (N, L, C) with exact torch ``BatchNorm1d`` semantics.

    Differences from ``flax.linen.BatchNorm`` that matter for parity
    (verified by the train-mode gradient/BN test in
    tests/test_golden_parity.py):

    * the running variance is updated with the UNBIASED batch variance
      (x N/(N-1)), while normalization uses the biased one — torch does
      exactly this; flax uses the biased variance for both.
    * statistics are always computed in fp32; under a bf16 precision
      policy only the *output* is cast down (fp32 running stats would
      otherwise promote every activation back to fp32 and undo mixed
      precision network-wide).

    Param/variable naming matches flax BatchNorm ('scale'/'bias',
    batch_stats 'mean'/'var') so checkpoints and the torch->flax converter
    are unaffected. Under global-view jit with a batch-sharded mesh the
    reductions below span the GLOBAL batch — the reference's SyncBatchNorm
    semantics (ref train.py:374) with zero extra code.
    """

    use_running_average: bool
    momentum: float = BN_MOMENTUM  # flax convention: new = m*old + (1-m)*batch
    epsilon: float = BN_EPSILON
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x: Array) -> Array:
        features = x.shape[-1]
        scale = self.param(
            "scale", nn.initializers.ones, (features,), jnp.float32
        )
        bias = self.param(
            "bias", nn.initializers.zeros, (features,), jnp.float32
        )
        ra_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((features,), jnp.float32)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((features,), jnp.float32)
        )

        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            xf = x.astype(jnp.float32)
            axes = tuple(range(x.ndim - 1))
            mean = jnp.mean(xf, axes)
            var = jnp.maximum(
                jnp.mean(jnp.square(xf), axes) - jnp.square(mean), 0.0
            )
            if not self.is_initializing():
                n = math.prod(x.shape[a] for a in axes)
                unbiased = var * (n / max(n - 1, 1))
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * unbiased

        inv = jax.lax.rsqrt(var + self.epsilon) * scale
        y = (x.astype(jnp.float32) - mean) * inv + bias
        return y.astype(self.dtype or x.dtype)


def make_norm(
    norm: str, *, use_running_average: bool, name: Optional[str] = None
) -> nn.Module:
    """Normalization factory. 'batch' matches torch BatchNorm1d exactly
    (momentum 0.1 -> our momentum 0.9, eps 1e-5, unbiased running-var
    update — see :class:`BatchNorm1dParity`). Under global-view jit with
    a batch-sharded mesh the batch statistics are computed over the
    *global* batch, which is exactly the reference's SyncBatchNorm
    semantics (train.py:374) with zero extra code.
    """
    from seist_tpu.train.precision import policy_dtype

    dtype = policy_dtype()
    if norm == "batch":
        return BatchNorm1dParity(
            use_running_average=use_running_average,
            momentum=BN_MOMENTUM,
            epsilon=BN_EPSILON,
            dtype=dtype,
            name=name,
        )
    if norm == "layer":
        return nn.LayerNorm(dtype=dtype, name=name)
    if norm == "group":
        return nn.GroupNorm(num_groups=8, dtype=dtype, name=name)
    raise NotImplementedError(f"Unknown norm '{norm}'")


def _lstm_unroll() -> int:
    """Scan unroll factor for LSTM recurrences (env SEIST_LSTM_UNROLL).

    The per-step matmuls are tiny (hidden 16-64), so a serial scan is
    latency-bound on TPU; unrolling the scan body lets XLA software-
    pipeline consecutive steps. Pure scheduling — the math is unchanged
    for any factor (lax.scan semantics)."""
    return int(os.environ.get("SEIST_LSTM_UNROLL", "8"))


class LSTM(nn.Module):
    """Unidirectional LSTM over (N, L, C) returning (outputs, final_h).

    torch ``nn.LSTM`` parity at the architecture level; the recurrence is a
    ``lax.scan`` per flax nn.RNN (SURVEY.md §7 'LSTM baselines on TPU'),
    unrolled by :func:`_lstm_unroll` steps per scan iteration.
    """

    hidden: int

    @nn.compact
    def __call__(self, x: Array) -> Tuple[Array, Array]:
        from seist_tpu.train.precision import policy_dtype, policy_param_dtype

        # Mixed-precision coverage (irlint f32-matmul-under-bf16-policy):
        # OptimizedLSTMCell initializes its (c, h) carry via param_dtype —
        # fp32 by default — and the fp32 h then PROMOTES every recurrent
        # matmul (and the whole decoder downstream) back to fp32 under the
        # bf16 policy. Pinning cell dtype + carry dtype to the trace-time
        # policy keeps the recurrence in the compute dtype; params are
        # already cast by the step-level policy (train/precision.py), and
        # at init time the policy is inactive so params still init fp32.
        cell = nn.OptimizedLSTMCell(
            features=self.hidden,
            dtype=policy_dtype(),
            param_dtype=policy_param_dtype(),
        )
        carry, outputs = nn.RNN(
            cell, return_carry=True, unroll=_lstm_unroll()
        )(x)
        # carry = (c, h) for OptimizedLSTMCell
        return outputs, carry[1]


class BiLSTM(nn.Module):
    """Bidirectional LSTM over (N, L, C); returns (outputs_2H, final_h_2H)."""

    hidden: int

    @nn.compact
    def __call__(self, x: Array) -> Tuple[Array, Array]:
        fwd_out, fwd_h = LSTM(self.hidden, name="fwd")(x)
        bwd_out, bwd_h = LSTM(self.hidden, name="bwd")(x[:, ::-1, :])
        outputs = jnp.concatenate([fwd_out, bwd_out[:, ::-1, :]], axis=-1)
        final = jnp.concatenate([fwd_h, bwd_h], axis=-1)
        return outputs, final
