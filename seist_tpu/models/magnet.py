"""MagNet — conv + BiLSTM magnitude estimator (channels-last Flax).

Architecture parity with the reference ``models/magnet.py:36-117``
(Mousavi & Beroza 2020): two conv-pool blocks, one BiLSTM, linear head
producing (magnitude, log-variance) consumed by MousaviLoss.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
from flax import linen as nn

from seist_tpu.models import common
from seist_tpu.registry import register_model

Array = jnp.ndarray


class ConvBlock(nn.Module):
    """conv -> dropout -> ceil-mode maxpool (ref: magnet.py:36-60)."""

    out_channels: int
    conv_kernel_size: int
    pool_kernel_size: int
    drop_rate: float

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        x = common.auto_pad_1d(x, self.conv_kernel_size)
        x = nn.Conv(
            self.out_channels, (self.conv_kernel_size,), padding="VALID", name="conv"
        )(x)
        x = nn.Dropout(self.drop_rate, deterministic=not train)(x)
        x = common.max_pool_1d_ceil(x, self.pool_kernel_size)
        return x


class MagNet(nn.Module):
    """(N, L, C) -> (N, 2): (y_hat, log sigma^2) (ref: magnet.py:63-110)."""

    in_channels: int = 3
    conv_channels: Sequence[int] = (64, 32)
    lstm_dim: int = 100
    drop_rate: float = 0.2

    @nn.compact
    def __call__(self, x: Array, train: bool = False) -> Array:
        for i, outc in enumerate(self.conv_channels):
            x = ConvBlock(
                out_channels=outc,
                conv_kernel_size=3,
                pool_kernel_size=4,
                drop_rate=self.drop_rate,
                name=f"conv{i}",
            )(x, train)
        _, h = common.BiLSTM(self.lstm_dim, name="bilstm")(x)
        return nn.Dense(2, name="lin")(h)


@register_model
def magnet(**kwargs) -> MagNet:
    kwargs.pop("in_samples", None)
    kwargs = {k: v for k, v in kwargs.items() if k in MagNet.__dataclass_fields__}
    return MagNet(**kwargs)
