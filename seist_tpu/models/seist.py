"""Seismogram Transformer (SeisT) — the flagship backbone, TPU-native.

Architecture parity with the reference ``models/seist.py:63-852`` (Li et al.,
IEEE TGRS 2024), re-designed channels-last for XLA/TPU:

* arrays are ``(N, L, C)``; 1x1 convs become ``nn.Dense`` (pure MXU matmuls,
  no transposes);
* pooled-K/V attention (``AttentionBlock``, ref :321-393) is an einsum pair
  that XLA fuses with the surrounding projections;
* ceil-mode pooling / asymmetric 'same' padding geometry matches torch
  exactly (see seist_tpu/models/common.py);
* optional per-stage rematerialization replaces torch.utils.checkpoint
  (ref :841-847) via ``nn.remat``.

15 registered variants: seist_{s,m,l}_{dpk,pmp,emg,baz,dis}
(ref :855-1170).
"""

from __future__ import annotations

import math
import os
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from seist_tpu.models import common
from seist_tpu.models.common import DropPath, make_divisible, trunc_normal_init
from seist_tpu.registry import register_model

Array = jnp.ndarray

_dense_kw = dict(kernel_init=trunc_normal_init)
_conv_kw = dict(kernel_init=trunc_normal_init)


def _active_seq_mesh():
    """The active mesh when its `seq` axis is sharded (--seq-shards > 1),
    else None. Trace-time lookup — see parallel.mesh.set_active_mesh."""
    from seist_tpu.parallel import mesh as mesh_lib

    m = mesh_lib.active_mesh()
    if m is not None and m.shape.get(mesh_lib.AXIS_SEQ, 1) > 1:
        return m
    return None


class LocalAwareAggregationBlock(nn.Module):
    """(avg+max pool, ceil mode) -> 1x1 proj -> norm (ref: seist.py:73-96).
    Used as stage downsampler and attention K/V downsampler."""

    out_dim: int
    kernel_size: int
    norm: str = "batch"

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        if self.kernel_size > 1:
            x = common.avg_pool_1d_ceil(x, self.kernel_size) + common.max_pool_1d_ceil(
                x, self.kernel_size
            )
        x = nn.Dense(self.out_dim, use_bias=False, name="proj", **_dense_kw)(x)
        x = common.make_norm(self.norm, use_running_average=not train, name="norm")(x)
        return x


class MLP(nn.Module):
    """1x1-conv feedforward (ref: seist.py:99-121)."""

    out_dim: int
    mlp_ratio: float
    bias: bool
    mlp_drop_rate: float
    act: Callable = common.gelu

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        ffwd_dim = int(x.shape[-1] * self.mlp_ratio)
        x = nn.Dense(ffwd_dim, use_bias=self.bias, name="lin0", **_dense_kw)(x)
        x = self.act(x)
        x = nn.Dense(self.out_dim, use_bias=self.bias, name="lin1", **_dense_kw)(x)
        x = nn.Dropout(self.mlp_drop_rate, deterministic=not train)(x)
        return x


def _triple_product_kernel(w_in: Array, w_d: Array, w_p: Array) -> Array:
    """The DSConv pipeline's exact collapse into one dense conv kernel:
    ``A[j,c,o] = sum_d w_in[c,d] * w_d[j,d] * w_p[d,o]`` (valid because the
    three stages have no bias and no nonlinearity between them). fp32
    accumulation regardless of the compute dtype — under bf16 policy this
    rounds ONCE (at the caller's cast) where the staged pipeline rounds
    after each of the three matmuls. Shared by DSConvNormAct._composed and
    StemBlock._fused_paths."""
    return jnp.einsum(
        "cd,jd,do->jco",
        w_in,
        w_d,
        w_p,
        preferred_element_type=jnp.float32,
    )


class DSConvNormAct(nn.Module):
    """Depthwise-separable conv (ref: seist.py:124-155).

    Two checkpoint-identical lowerings (``impl`` / env SEIST_DSCONV_IMPL):

    * ``'paths'`` — the literal pipeline: 1x1 in-proj -> depthwise k ->
      1x1 pconv (3 device passes over the activation).
    * ``'composed'`` (TPU default) — algebraic collapse: with no bias and
      no nonlinearity between the three stages, the pipeline is EXACTLY
      one dense conv whose kernel is the tap-wise triple product
      ``A[j,c,o] = sum_d Win[c,d] * w[j,d] * Wp[d,o]`` (tiny einsum over
      the weights, recomputed per step). One dense conv1d is the shape
      XLA maps best onto the MXU at these channel counts (BASELINE.md:
      phasenet 4.1% vs SeisT 0.8% MFU), and the activation is read and
      written ONCE in each direction instead of three times — the stems
      built from this block were 42% of the seist_l step before.
    """

    in_dim: int
    out_dim: int
    kernel_size: int
    stride: int
    norm: str = "batch"
    act: Callable = common.gelu
    # None -> env SEIST_DSCONV_IMPL, else 'composed' on TPU / 'paths' off
    impl: Optional[str] = None

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        impl = self.impl or os.environ.get("SEIST_DSCONV_IMPL") or (
            "composed" if jax.default_backend() == "tpu" else "paths"
        )
        if impl not in ("paths", "composed"):
            raise ValueError(f"unknown dsconv impl {impl!r}")
        if impl == "composed":
            x = self._composed(x)
        else:
            x = nn.Dense(
                self.in_dim, use_bias=False, name="in_proj", **_dense_kw
            )(x)
            x = common.auto_pad_1d(x, self.kernel_size, self.stride)
            # Shift-FMA depthwise lowering (same dconv/kernel param tree as
            # the grouped nn.Conv it replaces) — see common.DepthwiseConv1D
            # for why XLA's grouped conv is pathological at these channel
            # counts.
            x = common.DepthwiseConv1D(
                self.in_dim,
                self.kernel_size,
                stride=self.stride,
                name="dconv",
                **_conv_kw,
            )(x)
            x = nn.Dense(
                self.out_dim, use_bias=False, name="pconv", **_dense_kw
            )(x)
        x = common.make_norm(self.norm, use_running_average=not train, name="norm")(x)
        return self.act(x)

    def _composed(self, x: Array) -> Array:
        """in_proj∘dconv∘pconv as ONE dense conv (same param tree: the
        _Kernel twins declare the identical leaves the per-stage modules
        would). Padding commutes exactly: in_proj is 1x1 with no bias, so
        padding the input with zeros equals padding its output."""
        w_in = _Kernel((x.shape[-1], self.in_dim), name="in_proj")()
        w_d = _Kernel((self.kernel_size, 1, self.in_dim), name="dconv")()
        w_p = _Kernel((self.in_dim, self.out_dim), name="pconv")()
        kernel = _triple_product_kernel(w_in, w_d[:, 0, :], w_p).astype(x.dtype)
        # SEIST_CHANNEL_PAD (off by default): lane-multiple out channels,
        # zeros sliced away — values identical (common.py docstring).
        kernel, out = common.pad_kernel_out_channels(kernel)
        xp = common.auto_pad_1d(x, self.kernel_size, self.stride)
        h = jax.lax.conv_general_dilated(
            xp,
            kernel,
            window_strides=(self.stride,),
            padding="VALID",
            dimension_numbers=("NWC", "WIO", "NWC"),
        )
        return h[..., :out]


class _Kernel(nn.Module):
    """Declares one ``kernel`` param leaf (same name/shape/init as the
    nn.Dense / DepthwiseConv1D it twins) and returns it raw, so a parent
    module can compute a merged lowering over several paths' weights while
    the checkpoint tree stays identical to the per-path modules."""

    shape: Tuple[int, ...]

    @nn.compact
    def __call__(self) -> Array:
        return self.param("kernel", trunc_normal_init, self.shape)


class _BNLeaves(nn.Module):
    """Param/variable twin of :class:`common.BatchNorm1dParity` (same leaf
    names, shapes, inits). Returns (scale, bias, mean_ref, var_ref)."""

    features: int

    @nn.compact
    def __call__(self):
        scale = self.param(
            "scale", nn.initializers.ones, (self.features,), jnp.float32
        )
        bias = self.param(
            "bias", nn.initializers.zeros, (self.features,), jnp.float32
        )
        mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((self.features,), jnp.float32)
        )
        var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((self.features,), jnp.float32)
        )
        return scale, bias, mean, var


class _DSConvPathLeaves(nn.Module):
    """Param-tree twin of one :class:`DSConvNormAct` path: declares the
    exact same leaves (conv{i}/in_proj/kernel, dconv/kernel, pconv/kernel,
    norm/{scale,bias,mean,var}) without computing anything, for the merged
    StemBlock lowering below."""

    prev_dim: int
    in_dim: int
    out_dim: int
    kernel_size: int

    @nn.compact
    def __call__(self):
        w_in = _Kernel((self.prev_dim, self.in_dim), name="in_proj")()
        w_d = _Kernel((self.kernel_size, 1, self.in_dim), name="dconv")()
        w_p = _Kernel((self.in_dim, self.out_dim), name="pconv")()
        bn = _BNLeaves(self.out_dim, name="norm")()
        return w_in, w_d, w_p, bn


class StemBlock(nn.Module):
    """3 parallel DSConv paths with kernels k, k+4, k+8 (ref: seist.py:158-195).

    Two checkpoint-identical lowerings (``impl`` / env SEIST_STEM_IMPL):

    * ``'paths'`` (default) — the literal architecture: 3 independent
      DSConvNormAct calls.
    * ``'merged'`` — horizontal fusion of the 3 paths: one in-projection
      matmul on the concatenated kernels (the input is read once instead
      of 3x), one shift-FMA depthwise pass over a zero-padded multi-kernel
      bank, one block-diagonal pointwise matmul (3C lanes instead of C),
      and one merged BatchNorm whose per-channel stats are exactly the
      per-path norms'.
    * ``'fused'`` — ONE dense conv for all 3 paths: each path collapses
      to a dense kernel via the DSConvNormAct triple product, the three
      kernels are tap-centered into one (K, Cin, 3*Cout) bank, and the
      path concat becomes the conv's out-channel axis (see _fused_paths).

    ``'merged'`` is a measured NEGATIVE result on TPU v5e and therefore
    not the default: interleaved A/B on seist_l_dpk fp32 b256 gave
    1,613 wf/s merged vs 1,834/1,838 paths (-12%; BASELINE.md round 2).
    The fwd pass does get fewer passes, but XLA lowers the backward of
    the merged strided-slice FMA (stride-2 stems) to generic scatter-adds
    with s32 index vectors and flips the activation layout to {0,2,1},
    inserting full-tensor copies — costing more than the saved reads.
    Kept env-selectable for future XLA versions / other topologies.

    Both lowerings produce the same param/batch_stats tree and the same
    values up to fp reassociation (tested in tests/test_models.py).
    """

    in_dim: int
    out_dim: int
    kernel_size: int
    stride: int
    norm: str = "batch"
    act: Callable = common.gelu
    npath: int = 3
    # None -> env SEIST_STEM_IMPL, else 'paths' (see docstring: 'merged'
    # measured slower on v5e)
    impl: Optional[str] = None

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        impl = self.impl or os.environ.get("SEIST_STEM_IMPL") or "paths"
        if impl not in ("merged", "paths", "fused"):
            raise ValueError(f"unknown stem impl {impl!r}")
        if impl in ("merged", "fused") and self.norm != "batch":
            raise ValueError(
                f"SEIST_STEM_IMPL={impl} only supports norm='batch' "
                f"(got {self.norm!r}); use the 'paths' impl"
            )
        if impl == "merged":
            x = self._merged_paths(x, train)
        elif impl == "fused":
            x = self._fused_paths(x, train)
        else:
            outs = [
                DSConvNormAct(
                    self.in_dim,
                    self.out_dim,
                    self.kernel_size + 4 * dk,
                    self.stride,
                    self.norm,
                    self.act,
                    name=f"conv{dk}",
                )(x, train)
                for dk in range(self.npath)
            ]
            x = jnp.concatenate(outs, axis=-1)
        x = nn.Dense(self.out_dim, use_bias=False, name="out_proj", **_dense_kw)(x)
        x = common.make_norm(self.norm, use_running_average=not train, name="norm")(x)
        return x

    def _merged_paths(self, x: Array, train: bool) -> Array:
        """All 3 DSConvNormAct paths in 3 device passes instead of ~9."""
        P, C, O = self.npath, self.in_dim, self.out_dim
        ks = [self.kernel_size + 4 * dk for dk in range(P)]
        K = ks[-1]
        leaves = [
            _DSConvPathLeaves(x.shape[-1], C, O, k, name=f"conv{i}")()
            for i, k in enumerate(ks)
        ]
        # one in-projection matmul — x is streamed once for all paths
        w_in = jnp.concatenate([l[0] for l in leaves], axis=1)  # (Cin, P*C)
        h = x @ w_in
        # one depthwise pass over a zero-padded multi-kernel bank: path i's
        # k_i-tap kernel sits at tap offset (K - k_i)//2, which under the
        # K-kernel 'same' padding reproduces the path's own asymmetric
        # padding exactly (left-pad difference LP_K - lp_i == (K - k_i)//2
        # because kernel sizes differ by the even 4*dk; ref geometry:
        # seist.py:12-48).
        bank = jnp.zeros((K, P * C), dtype=h.dtype)
        for i, (k_i, l) in enumerate(zip(ks, leaves)):
            off = (K - k_i) // 2
            bank = bank.at[off : off + k_i, i * C : (i + 1) * C].set(
                l[1][:, 0, :].astype(h.dtype)
            )
        h = common.auto_pad_1d(h, K, self.stride)
        h = common.depthwise_shift_fma(h, bank, self.stride)
        # one block-diagonal pointwise matmul (P*C -> P*O)
        w_p = jax.scipy.linalg.block_diag(*[l[2] for l in leaves])
        h = h @ w_p
        return self._merged_bn_act(h, leaves, train, x.dtype)

    def _merged_bn_act(self, h: Array, leaves, train: bool, in_dtype) -> Array:
        """Merged BatchNorm1dParity (common.py) over path-concatenated
        channels: per-channel batch stats are identical to the per-path
        norms'; running stats are written back into each path's own
        batch_stats leaves. Shared by the 'merged' and 'fused' lowerings."""
        from seist_tpu.train.precision import policy_dtype

        O = self.out_dim
        scale = jnp.concatenate([l[3][0] for l in leaves])
        bias = jnp.concatenate([l[3][1] for l in leaves])
        if not train:
            mean = jnp.concatenate([l[3][2].value for l in leaves])
            var = jnp.concatenate([l[3][3].value for l in leaves])
        else:
            hf = h.astype(jnp.float32)
            mean = jnp.mean(hf, (0, 1))
            var = jnp.maximum(
                jnp.mean(jnp.square(hf), (0, 1)) - jnp.square(mean), 0.0
            )
            if not self.is_initializing():
                n = h.shape[0] * h.shape[1]
                unbiased = var * (n / max(n - 1, 1))
                m = common.BN_MOMENTUM
                for i, l in enumerate(leaves):
                    sl = slice(i * O, (i + 1) * O)
                    l[3][2].value = m * l[3][2].value + (1 - m) * mean[sl]
                    l[3][3].value = m * l[3][3].value + (1 - m) * unbiased[sl]
        inv = jax.lax.rsqrt(var + common.BN_EPSILON) * scale
        h = (h.astype(jnp.float32) - mean) * inv + bias
        h = h.astype(policy_dtype() or in_dtype)
        return self.act(h)

    def _fused_paths(self, x: Array, train: bool) -> Array:
        """All 3 paths as ONE dense conv. Composes DSConvNormAct._composed
        (per-path triple-product kernels A_i, exact — no bias and no
        nonlinearity inside a path) with the merged-stem tap geometry:
        path i's K_i-tap kernel sits at tap offset (K - k_i)//2 of the
        K-tap bank, which under K-kernel 'same' padding reproduces the
        path's own asymmetric padding exactly (even kernel-size deltas;
        see _merged_paths). The path concat disappears entirely — the
        conv's out-channel axis IS the concatenation — so the input is
        read once and one (N, L_out, P*O) tensor is written where 'paths'
        reads x three times and writes 3 tensors plus a concat copy.
        Unlike 'merged' (a measured -12%: shift-FMA strided-slice
        backward scatter), the dense conv's backward is XLA's native
        conv-transpose — no scatter, no layout flip."""
        P, C, O = self.npath, self.in_dim, self.out_dim
        ks = [self.kernel_size + 4 * dk for dk in range(P)]
        K = ks[-1]
        leaves = [
            _DSConvPathLeaves(x.shape[-1], C, O, k, name=f"conv{i}")()
            for i, k in enumerate(ks)
        ]
        cin = x.shape[-1]
        kern = jnp.zeros((K, cin, P * O), jnp.float32)
        for i, (k_i, l) in enumerate(zip(ks, leaves)):
            a = _triple_product_kernel(l[0], l[1][:, 0, :], l[2])
            off = (K - k_i) // 2
            kern = kern.at[off : off + k_i, :, i * O : (i + 1) * O].set(a)
        xp = common.auto_pad_1d(x, K, self.stride)
        # SEIST_CHANNEL_PAD (off by default): lane-multiple out channels,
        # zeros sliced away — values identical (common.py docstring).
        kern_p, out = common.pad_kernel_out_channels(kern.astype(x.dtype))
        h = jax.lax.conv_general_dilated(
            xp,
            kern_p,
            window_strides=(self.stride,),
            padding="VALID",
            dimension_numbers=("NWC", "WIO", "NWC"),
        )[..., :out]
        return self._merged_bn_act(h, leaves, train, x.dtype)


class GroupConvBlock(nn.Module):
    """Grouped conv + MLP, both with residual DropPath (ref: seist.py:198-256)."""

    io_dim: int
    groups: int
    kernel_size: int
    path_drop_rate: float
    mlp_drop_rate: float
    mlp_ratio: float
    mlp_bias: bool
    norm: str = "batch"
    act: Callable = common.gelu

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        x1 = common.auto_pad_1d(x, self.kernel_size, 1)
        # Selectable grouped-conv lowering (same conv/kernel param tree as
        # grouped nn.Conv) — see common.GroupedConv1D.
        x1 = common.GroupedConv1D(
            self.io_dim,
            self.groups,
            self.kernel_size,
            name="conv",
            **_conv_kw,
        )(x1)
        x1 = common.make_norm(self.norm, use_running_average=not train, name="norm0")(x1)
        x1 = self.act(x1)
        x1 = nn.Dense(self.io_dim, use_bias=False, name="proj", **_dense_kw)(x1)
        x = x + DropPath(self.path_drop_rate)(x1, train)

        x1 = common.make_norm(self.norm, use_running_average=not train, name="norm1")(x)
        x1 = MLP(
            self.io_dim, self.mlp_ratio, self.mlp_bias, self.mlp_drop_rate, self.act,
            name="mlp",
        )(x1, train)
        x = x + DropPath(self.path_drop_rate)(x1, train)
        return x


class MultiScaleMixedConv(nn.Module):
    """Channel-split parallel GroupConvBlocks at different kernel sizes
    (ref: seist.py:259-318)."""

    io_dim: int
    groups: int
    kernel_sizes: Sequence[int]
    path_drop_rate: float
    mlp_drop_rate: float
    mlp_ratio: float
    mlp_bias: bool
    norm: str = "batch"
    act: Callable = common.gelu

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        group_size = self.io_dim // self.groups
        dims_ = []
        outs = []
        for i, kernel_size in enumerate(self.kernel_sizes):
            dim = make_divisible(
                (self.io_dim - sum(dims_)) // (len(self.kernel_sizes) - len(dims_)),
                group_size,
            )
            assert dim > 0
            dims_.append(dim)
            xi = nn.Dense(dim, use_bias=False, name=f"proj{i}", **_dense_kw)(x)
            xi = common.make_norm(
                self.norm, use_running_average=not train, name=f"norm{i}"
            )(xi)
            xi = xi + GroupConvBlock(
                io_dim=dim,
                groups=dim // group_size,
                kernel_size=kernel_size,
                path_drop_rate=self.path_drop_rate,
                mlp_drop_rate=self.mlp_drop_rate,
                mlp_ratio=self.mlp_ratio,
                mlp_bias=self.mlp_bias,
                norm=self.norm,
                act=self.act,
                name=f"conv{i}",
            )(xi, train)
            outs.append(xi)
        x = jnp.concatenate(outs, axis=-1)
        x = common.make_norm(self.norm, use_running_average=not train, name="out_norm")(x)
        return x


class AttentionBlock(nn.Module):
    """MHA with K/V from a pooled sequence: full-length Q attends to L/r keys,
    cost L x (L/r) (ref: seist.py:321-393)."""

    io_dim: int
    head_dim: int
    qkv_bias: bool
    attn_drop_rate: float
    key_drop_rate: float
    proj_drop_rate: float
    attn_aggr_ratio: int
    norm: str = "batch"

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        N, L, C = x.shape
        num_heads = self.io_dim // self.head_dim
        E = C // num_heads

        q = nn.Dense(self.io_dim, use_bias=self.qkv_bias, name="q_proj", **_dense_kw)(x)
        q = q.reshape(N, L, num_heads, E)

        if self.attn_aggr_ratio > 1:
            x = LocalAwareAggregationBlock(
                self.io_dim, self.attn_aggr_ratio, self.norm, name="aggr"
            )(x, train)
            x = common.make_norm(self.norm, use_running_average=not train, name="norm")(x)

        k = nn.Dense(self.io_dim, use_bias=self.qkv_bias, name="k_proj", **_dense_kw)(x)
        v = nn.Dense(self.io_dim, use_bias=self.qkv_bias, name="v_proj", **_dense_kw)(x)
        M = x.shape[1]
        k = k.reshape(N, M, num_heads, E)
        v = v.reshape(N, M, num_heads, E)
        k = nn.Dropout(self.key_drop_rate, deterministic=not train)(k)

        rate = self.attn_drop_rate if train else 0.0
        seed = None
        if rate > 0.0:
            seed = jax.random.randint(
                self.make_rng("dropout"),
                (1,),
                0,
                jnp.iinfo(jnp.int32).max,
                dtype=jnp.int32,
            )
        mesh = _active_seq_mesh()
        if mesh is not None:
            # --seq-shards: sequence-parallel exact attention over the
            # mesh's `seq` axis (Q blocks resident, K/V rotating on ICI —
            # ops/ring_attention.py). Long-context path the reference lacks.
            # Probability dropout (ref seist.py:383-388) applies inside the
            # ring accumulation with the SAME counter-based mask as the
            # dense/fused paths, so seq-parallel training semantics match
            # single-device training exactly.
            from seist_tpu.ops.ring_attention import ring_attention

            out = ring_attention(
                q,
                k,
                v,
                mesh,
                batch_axis="data",
                scale=1.0 / math.sqrt(E),
                dropout_rate=rate,
                dropout_seed=seed,
            )
        else:
            # Fused Pallas kernel on TPU (qk + softmax + dropout + pv in
            # VMEM, no (N,H,L,M) HBM tensor); identical-math einsum fallback
            # elsewhere. Probability dropout (ref seist.py:383-388) runs
            # *inside* the kernel from a counter-based PRNG seeded off the
            # flax 'dropout' stream.
            from seist_tpu.ops.pallas_attention import fused_pooled_attention

            out = fused_pooled_attention(
                q,
                k,
                v,
                1.0 / math.sqrt(E),
                dropout_rate=rate,
                dropout_seed=seed,
            )
        out = out.reshape(N, L, C)

        out = nn.Dense(
            self.io_dim, use_bias=self.qkv_bias, name="out_proj", **_dense_kw
        )(out)
        out = nn.Dropout(self.proj_drop_rate, deterministic=not train)(out)
        return out


class MultiPathTransformerLayer(nn.Module):
    """Channel-split dual path: attention on ~attn_ratio of channels, grouped
    conv on the rest; shared MLP (ref: seist.py:396-504)."""

    io_dim: int
    path_drop_rate: float
    attn_aggr_ratio: int
    attn_ratio: float
    head_dim: int
    qkv_bias: bool
    mlp_ratio: float
    mlp_bias: bool
    attn_drop_rate: float
    key_drop_rate: float
    attn_out_drop_rate: float
    mlp_drop_rate: float
    norm: str = "batch"
    act: Callable = common.gelu

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        assert 0 <= self.attn_ratio <= 1
        attn_out_dim = (
            make_divisible(int(self.io_dim * self.attn_ratio), self.head_dim)
            if self.attn_ratio > 0
            else 0
        )
        conv_out_dim = max(self.io_dim - attn_out_dim, 0)

        outs = []
        if attn_out_dim > 0:
            x1 = nn.Dense(attn_out_dim, use_bias=False, name="attn_proj", **_dense_kw)(x)
            x1 = common.make_norm(self.norm, use_running_average=not train, name="norm0")(x1)
            a = AttentionBlock(
                io_dim=attn_out_dim,
                head_dim=self.head_dim,
                qkv_bias=self.qkv_bias,
                attn_drop_rate=self.attn_drop_rate,
                key_drop_rate=self.key_drop_rate,
                proj_drop_rate=self.attn_out_drop_rate,
                attn_aggr_ratio=self.attn_aggr_ratio,
                norm=self.norm,
                name="attention",
            )(x1, train)
            x1 = x1 + DropPath(self.path_drop_rate * self.attn_ratio)(a, train)
            outs.append(x1)

        if conv_out_dim > 0:
            x2 = nn.Dense(conv_out_dim, use_bias=False, name="conv_proj", **_dense_kw)(x)
            x2 = common.make_norm(self.norm, use_running_average=not train, name="norm1")(x2)
            g = GroupConvBlock(
                io_dim=conv_out_dim,
                groups=conv_out_dim // self.head_dim,
                kernel_size=3,
                path_drop_rate=self.path_drop_rate,
                mlp_drop_rate=self.mlp_drop_rate,
                mlp_ratio=self.mlp_ratio,
                mlp_bias=self.mlp_bias,
                norm=self.norm,
                act=self.act,
                name="gconv",
            )(x2, train)
            x2 = x2 + DropPath(self.path_drop_rate * (1 - self.attn_ratio))(g, train)
            outs.append(x2)

        x = jnp.concatenate(outs, axis=-1)
        x = common.make_norm(self.norm, use_running_average=not train, name="norm2")(x)
        m = MLP(
            self.io_dim, self.mlp_ratio, self.mlp_bias, self.mlp_drop_rate, self.act,
            name="mlp",
        )(x, train)
        x = x + DropPath(self.path_drop_rate)(m, train)
        return x


class HeadDetectionPicking(nn.Module):
    """Interpolate+conv upsampling ladder back to input length
    (ref: seist.py:507-572)."""

    layer_channels: Sequence[int]
    layer_kernel_sizes: Sequence[int]
    out_channels: int = 1
    out_act: Optional[Callable] = None
    norm: str = "batch"
    act: Callable = common.gelu

    def _upsampling_sizes(self, in_size: int, out_size: int) -> Sequence[int]:
        depth = len(self.layer_channels)
        sizes = [out_size] * depth
        factor = (out_size / in_size) ** (1 / depth)
        for i in range(depth - 2, -1, -1):
            sizes[i] = int(sizes[i + 1] / factor)
        return sizes

    @nn.compact
    def __call__(self, x: Array, x0: Array, train: bool) -> Array:
        assert len(self.layer_channels) == len(self.layer_kernel_sizes)
        out_chs = list(self.layer_channels[:-1]) + [self.out_channels * 2]
        up_sizes = self._upsampling_sizes(x.shape[-2], x0.shape[-2])
        for i, (outc, kers) in enumerate(zip(out_chs, self.layer_kernel_sizes)):
            x = common.interpolate_linear(x, up_sizes[i])
            x = common.auto_pad_1d(x, kers, 1)
            x = nn.Conv(outc, (kers,), padding="VALID", name=f"conv{i}", **_conv_kw)(x)
            x = common.make_norm(
                self.norm, use_running_average=not train, name=f"norm{i}"
            )(x)
            x = self.act(x)
        x = nn.Conv(
            self.out_channels, (7,), padding=[(3, 3)], name="out_conv", **_conv_kw
        )(x)
        if self.out_act is not None:
            x = self.out_act(x)
        return x


class HeadClassification(nn.Module):
    """GAP -> linear -> softmax (ref: seist.py:575-591)."""

    num_classes: int
    out_act: Optional[Callable] = None

    @nn.compact
    def __call__(self, x: Array, x0: Array, train: bool) -> Array:
        x = common.global_avg_pool(x)
        x = nn.Dense(self.num_classes, name="lin", **_dense_kw)(x)
        if self.out_act is not None:
            x = self.out_act(x)
        return x


class HeadRegression(nn.Module):
    """GAP -> linear -> scaled sigmoid (ref: seist.py:594-610)."""

    out_act: Optional[Callable] = None

    @nn.compact
    def __call__(self, x: Array, x0: Array, train: bool) -> Array:
        x = common.global_avg_pool(x)
        x = nn.Dense(1, name="lin", **_dense_kw)(x)
        if self.out_act is not None:
            x = self.out_act(x)
        return x


class SeismogramTransformer(nn.Module):
    """Stem -> 4 stages (aggregation + MSMC/MPTL blocks) -> task head
    (ref: seist.py:613-852)."""

    in_channels: int = 3
    stem_channels: Sequence[int] = (16, 8, 16, 16)
    stem_kernel_sizes: Sequence[int] = (11, 5, 5, 7)
    stem_strides: Sequence[int] = (2, 1, 1, 2)
    layer_blocks: Sequence[int] = (2, 3, 6, 2)
    layer_channels: Sequence[int] = (24, 32, 64, 96)
    attn_blocks: Sequence[int] = (1, 1, 2, 1)
    stage_aggr_ratios: Sequence[int] = (2, 2, 2, 2)
    attn_aggr_ratios: Sequence[int] = (8, 4, 2, 1)
    head_dims: Sequence[int] = (8, 8, 16, 32)
    msmc_kernel_sizes: Sequence[int] = (3, 5)
    path_drop_rate: float = 0.2
    attn_drop_rate: float = 0.1
    key_drop_rate: float = 0.1
    mlp_drop_rate: float = 0.2
    other_drop_rate: float = 0.1
    attn_ratio: float = 0.6
    mlp_ratio: float = 2.0
    qkv_bias: bool = True
    mlp_bias: bool = True
    norm: str = "batch"
    act: Callable = common.gelu
    use_checkpoint: bool = False
    head_type: str = "dpk"  # dpk | cls | reg
    head_out_channels: int = 3
    head_num_classes: int = 2
    head_scale: float = 1.0

    @nn.compact
    def __call__(
        self,
        x: Array,
        train: bool = False,
        *,
        mode: str = "full",
        features: Optional[Array] = None,
    ) -> Array:
        """Forward pass, optionally split at the trunk/head boundary.

        ``mode`` selects what runs (a static Python switch — jit callers
        close over it):

        * ``'full'`` (default) — stem + stages + task head, byte-identical
          to the pre-split behavior.
        * ``'backbone'`` — stem + stages only; returns the (N, L/64, C')
          trunk features every task head consumes. The trunk is the ~90%
          of serving FLOPs the paper's five task heads share — the serve
          pool runs it ONCE per trace and fans out (serve/pool.py).
        * ``'head'`` — task head only; ``features`` is a trunk output and
          ``x`` is the ORIGINAL model input (the dpk upsampling ladder
          needs its length to rebuild full resolution).

        The param tree is identical in all modes (all submodules carry
        explicit names), so one checkpoint serves all three; head-only
        application simply never reads the trunk leaves.
        """
        if mode not in ("full", "backbone", "head"):
            raise ValueError(f"unknown mode {mode!r}")
        if mode == "head":
            if features is None:
                raise ValueError("mode='head' requires features")
            return self._head(features, x, train)
        feats = self._backbone(x, train)
        if mode == "backbone":
            return feats
        return self._head(feats, x, train)

    def _backbone(self, x: Array, train: bool) -> Array:
        """Stem + 4 stages — the shared trunk (ref: seist.py:686-770)."""
        assert (
            len(self.stem_channels)
            == len(self.stem_kernel_sizes)
            == len(self.stem_strides)
        )
        assert (
            len(self.layer_blocks)
            == len(self.layer_channels)
            == len(self.stage_aggr_ratios)
            == len(self.attn_aggr_ratios)
            == len(self.attn_blocks)
            == len(self.head_dims)
        )

        # Stem: 4 StemBlocks, strides [2,1,1,2] => L/4 (ref: seist.py:686-703)
        stem_in = [self.in_channels] + list(self.stem_channels[:-1])
        for i, (inc, outc, kers, strd) in enumerate(
            zip(stem_in, self.stem_channels, self.stem_kernel_sizes, self.stem_strides)
        ):
            x = StemBlock(
                inc, outc, kers, strd, self.norm, self.act, name=f"stem{i}"
            )(x, train)

        # Stochastic-depth schedule over all blocks (ref: seist.py:705)
        total_blocks = sum(self.layer_blocks)
        pdprs = [
            self.path_drop_rate * i / max(total_blocks - 1, 1)
            for i in range(total_blocks)
        ]

        for i, num_blocks in enumerate(self.layer_blocks):
            lc = self.layer_channels[i]

            def stage_fn(mdl_self, x, train, _i=i, _lc=lc, _nb=num_blocks):
                x = LocalAwareAggregationBlock(
                    _lc, mdl_self.stage_aggr_ratios[_i], mdl_self.norm,
                    name=f"stage{_i}_aggr",
                )(x, train)
                for j in range(_nb):
                    pdpr = pdprs[sum(self.layer_blocks[:_i]) + j]
                    if j >= _nb - mdl_self.attn_blocks[_i]:
                        x = MultiPathTransformerLayer(
                            io_dim=_lc,
                            path_drop_rate=pdpr,
                            attn_aggr_ratio=mdl_self.attn_aggr_ratios[_i],
                            attn_ratio=mdl_self.attn_ratio,
                            head_dim=mdl_self.head_dims[_i],
                            qkv_bias=mdl_self.qkv_bias,
                            mlp_ratio=mdl_self.mlp_ratio,
                            mlp_bias=mdl_self.mlp_bias,
                            attn_drop_rate=mdl_self.attn_drop_rate,
                            key_drop_rate=mdl_self.key_drop_rate,
                            attn_out_drop_rate=mdl_self.other_drop_rate,
                            mlp_drop_rate=mdl_self.mlp_drop_rate,
                            norm=mdl_self.norm,
                            act=mdl_self.act,
                            name=f"stage{_i}_block{j}",
                        )(x, train)
                    else:
                        x = MultiScaleMixedConv(
                            io_dim=_lc,
                            groups=_lc // mdl_self.head_dims[_i],
                            kernel_sizes=mdl_self.msmc_kernel_sizes,
                            path_drop_rate=pdpr,
                            mlp_drop_rate=mdl_self.mlp_drop_rate,
                            mlp_ratio=mdl_self.mlp_ratio,
                            mlp_bias=mdl_self.mlp_bias,
                            norm=mdl_self.norm,
                            act=mdl_self.act,
                            name=f"stage{_i}_block{j}",
                        )(x, train)
                return x

            if self.use_checkpoint:
                # Rematerialize the stage to trade FLOPs for HBM
                # (replaces torch.utils.checkpoint, ref: seist.py:841-847).
                x = nn.remat(stage_fn, static_argnums=(2,))(self, x, train)
            else:
                x = stage_fn(self, x, train)
        return x

    def _head(self, x: Array, x_input: Array, train: bool) -> Array:
        # Output head (ref: seist.py:773-812)
        if self.head_type == "dpk":
            out_layer_channels = []
            out_layer_kernel_sizes = []
            for channel, kernel, stride in zip(
                [self.in_channels]
                + list(self.stem_channels)
                + list(self.layer_channels[:-1]),
                list(self.stem_kernel_sizes)
                + [max(self.msmc_kernel_sizes)] * len(self.layer_channels),
                list(self.stem_strides) + list(self.stage_aggr_ratios),
            ):
                if stride > 1:
                    out_layer_channels.insert(0, channel)
                    out_layer_kernel_sizes.insert(0, kernel)
            return HeadDetectionPicking(
                layer_channels=out_layer_channels,
                layer_kernel_sizes=out_layer_kernel_sizes,
                out_channels=self.head_out_channels,
                out_act=nn.sigmoid,
                norm=self.norm,
                act=self.act,
                name="out_head",
            )(x, x_input, train)
        if self.head_type == "cls":
            return HeadClassification(
                num_classes=self.head_num_classes,
                out_act=lambda v: nn.softmax(v, axis=-1),
                name="out_head",
            )(x, x_input, train)
        if self.head_type == "reg":
            scale = self.head_scale
            return HeadRegression(
                out_act=lambda v: nn.sigmoid(v) * scale, name="out_head"
            )(x, x_input, train)
        raise NotImplementedError(f"Unknown head_type '{self.head_type}'")


# ------------------------------------------------------- trunk/head split API
def supports_trunk_split(model: Any) -> bool:
    """True when ``model`` exposes the backbone/head apply modes (the
    SeisT family); other registered models (phasenet, eqtransformer, ...)
    are single-task and serve through the plain forward."""
    return isinstance(model, SeismogramTransformer)


def backbone_apply(model: Any, variables: Any, x: Array) -> Array:
    """Run ONLY the shared trunk (stem + stages): (N, L, C) waveforms ->
    (N, L/64, C') features. Inference-mode (train=False), jittable."""
    return model.apply(variables, x, train=False, mode="backbone")


def head_apply(model: Any, variables: Any, features: Array, x_input: Array) -> Array:
    """Run ONLY the task head on trunk ``features``. ``x_input`` is the
    original waveform batch — the dpk upsampling ladder reads its length
    (never its values) to rebuild full-resolution picks; cls/reg heads
    ignore it. Head-only application reads just the ``out_head`` subtree
    of ``variables``; unused trunk leaves are ignored by flax."""
    return model.apply(
        variables, x_input, train=False, mode="head", features=features
    )


# ---------------------------------------------------------------- size presets
_PRESET_S = dict(
    stem_channels=(16, 8, 16, 16),
    stem_kernel_sizes=(11, 5, 5, 7),
    stem_strides=(2, 1, 1, 2),
    layer_blocks=(2, 2, 3, 2),
    layer_channels=(16, 24, 32, 64),
    attn_blocks=(1, 1, 1, 1),
    stage_aggr_ratios=(2, 2, 2, 2),
    attn_aggr_ratios=(8, 4, 2, 1),
    head_dims=(8, 8, 8, 16),
    msmc_kernel_sizes=(5, 7),
    path_drop_rate=0.1,
    attn_drop_rate=0.1,
    key_drop_rate=0.1,
    mlp_drop_rate=0.1,
    other_drop_rate=0.1,
    attn_ratio=0.6,
    mlp_ratio=2.0,
)

_PRESET_M = dict(
    stem_channels=(16, 8, 16, 16),
    stem_kernel_sizes=(11, 5, 5, 7),
    stem_strides=(2, 1, 1, 2),
    layer_blocks=(2, 3, 6, 2),
    layer_channels=(24, 32, 64, 96),
    attn_blocks=(1, 1, 1, 1),
    stage_aggr_ratios=(2, 2, 2, 2),
    attn_aggr_ratios=(8, 4, 2, 1),
    head_dims=(8, 8, 16, 32),
    msmc_kernel_sizes=(5, 7),
    path_drop_rate=0.1,
    attn_drop_rate=0.1,
    key_drop_rate=0.1,
    mlp_drop_rate=0.1,
    other_drop_rate=0.1,
    attn_ratio=0.6,
    mlp_ratio=2.0,
)

_PRESET_L = dict(
    stem_channels=(16, 8, 16, 16),
    stem_kernel_sizes=(11, 5, 5, 7),
    stem_strides=(2, 1, 1, 2),
    layer_blocks=(2, 3, 6, 3),
    layer_channels=(32, 32, 64, 128),
    attn_blocks=(1, 1, 2, 1),
    stage_aggr_ratios=(2, 2, 2, 2),
    attn_aggr_ratios=(8, 4, 2, 1),
    head_dims=(8, 8, 16, 32),
    msmc_kernel_sizes=(3, 5, 7, 11),
    path_drop_rate=0.2,
    attn_drop_rate=0.2,
    key_drop_rate=0.1,
    mlp_drop_rate=0.2,
    other_drop_rate=0.1,
    attn_ratio=0.6,
    mlp_ratio=3.0,
)

_PRESETS = {"s": _PRESET_S, "m": _PRESET_M, "l": _PRESET_L}


def _drops(rate: float) -> dict:
    return dict(
        path_drop_rate=rate,
        attn_drop_rate=rate,
        key_drop_rate=rate,
        mlp_drop_rate=rate,
        other_drop_rate=rate,
    )


def _build(size: str, head: dict, overrides: dict, **kwargs) -> SeismogramTransformer:
    args = dict(_PRESETS[size])
    args.update(overrides)
    args.update(head)
    kwargs.pop("in_samples", None)
    args.update(
        {k: v for k, v in kwargs.items()
         if k in SeismogramTransformer.__dataclass_fields__}
    )
    return SeismogramTransformer(**args)


_HEAD_DPK = dict(head_type="dpk", head_out_channels=3)
_HEAD_PMP = dict(head_type="cls", head_num_classes=2)


def _head_reg(scale: float) -> dict:
    return dict(head_type="reg", head_scale=scale)


# Per-task drop-rate overrides mirror the registered ctors
# (ref: seist.py:940-1170).
@register_model
def seist_s_dpk(**kw):
    """Detection and phase picking (small)."""
    return _build("s", _HEAD_DPK, {}, **kw)


@register_model
def seist_m_dpk(**kw):
    """Detection and phase picking (medium)."""
    return _build("m", _HEAD_DPK, _drops(0.2), **kw)


@register_model
def seist_l_dpk(**kw):
    """Detection and phase picking (large)."""
    return _build("l", _HEAD_DPK, _drops(0.3), **kw)


@register_model
def seist_s_pmp(**kw):
    """First-motion polarity classification (small)."""
    return _build("s", _HEAD_PMP, _drops(0.2), **kw)


@register_model
def seist_m_pmp(**kw):
    """First-motion polarity classification (medium)."""
    return _build("m", _HEAD_PMP, _drops(0.25), **kw)


@register_model
def seist_l_pmp(**kw):
    """First-motion polarity classification (large)."""
    return _build("l", _HEAD_PMP, _drops(0.3), **kw)


@register_model
def seist_s_emg(**kw):
    """Magnitude estimation (small): sigmoid x 8."""
    return _build("s", _head_reg(8.0), {}, **kw)


@register_model
def seist_m_emg(**kw):
    """Magnitude estimation (medium)."""
    return _build("m", _head_reg(8.0), {}, **kw)


@register_model
def seist_l_emg(**kw):
    """Magnitude estimation (large)."""
    return _build("l", _head_reg(8.0), {}, **kw)


@register_model
def seist_s_baz(**kw):
    """Back-azimuth estimation (small): sigmoid x 360."""
    return _build("s", _head_reg(360.0), {}, **kw)


@register_model
def seist_m_baz(**kw):
    """Back-azimuth estimation (medium)."""
    return _build("m", _head_reg(360.0), {}, **kw)


@register_model
def seist_l_baz(**kw):
    """Back-azimuth estimation (large)."""
    return _build("l", _head_reg(360.0), {}, **kw)


@register_model
def seist_s_dis(**kw):
    """Epicentral distance estimation (small): sigmoid x 500."""
    return _build("s", _head_reg(500.0), {}, **kw)


@register_model
def seist_m_dis(**kw):
    """Epicentral distance estimation (medium)."""
    return _build("m", _head_reg(500.0), {}, **kw)


@register_model
def seist_l_dis(**kw):
    """Epicentral distance estimation (large)."""
    return _build("l", _head_reg(500.0), {}, **kw)
