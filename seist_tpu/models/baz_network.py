"""BAZ network — back-azimuth from single-station waveforms (channels-last).

Architecture parity with the reference ``models/baz_network.py:17-121``
(Mousavi & Beroza 2020): conv stack + covariance/eigen feature branch ->
(cos, sin) outputs trained with dual MSE.

TPU note: the reference uses ``torch.linalg.eig`` on the (symmetric)
covariance under no_grad (baz_network.py:79-86). General eig is not lowered
on TPU; the covariance is symmetric so ``jnp.linalg.eigh`` is exact, real,
and TPU-native — we use it under ``stop_gradient``.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from seist_tpu.models import common
from seist_tpu.registry import register_model

Array = jnp.ndarray


def _cov_features(x: Array) -> Array:
    """Covariance + eigen features, (N, L, C) -> (N, 2C+1, C)
    (ref: baz_network.py:67-101, transposed for channels-last)."""
    N, L, C = x.shape
    diff = x - x.mean(axis=1, keepdims=True)
    cov = jnp.einsum("nlc,nld->ncd", diff, diff) / (L - 1)
    eig_values, eig_vectors = jnp.linalg.eigh(cov)
    eig_values = eig_values[..., None]  # (N, C, 1)
    eig_values = eig_values / jnp.max(eig_values, axis=(-2, -1), keepdims=True)
    cov = cov / jnp.max(jnp.abs(cov), axis=(-2, -1), keepdims=True)
    feat = jnp.concatenate([cov, eig_values, eig_vectors], axis=-1)  # (N, C, 2C+1)
    return jax.lax.stop_gradient(jnp.swapaxes(feat, -1, -2))  # (N, 2C+1, C)


class BAZNetwork(nn.Module):
    """(N, L, C) -> ((N, 1) cos, (N, 1) sin) (ref: baz_network.py:17-121)."""

    in_channels: int = 3
    conv_channels: Sequence[int] = (20, 32, 64, 20)
    kernel_size: int = 3
    pool_size: int = 2
    lin_hidden_dim: int = 100
    drop_rate: float = 0.3

    @nn.compact
    def __call__(self, x: Array, train: bool = False) -> Tuple[Array, Array]:
        x1 = _cov_features(x)

        p = (self.kernel_size - 1) // 2
        for i, outc in enumerate(self.conv_channels):
            x = nn.Conv(
                outc, (self.kernel_size,), padding=[(p, p)], name=f"wave_conv{i}"
            )(x)
            x = nn.relu(x)
            x = nn.Dropout(self.drop_rate, deterministic=not train)(x)
            x = common.max_pool_1d_ceil(x, self.pool_size)
        x = x.reshape(x.shape[0], -1)

        x1 = nn.Dense(self.conv_channels[-1], name="conv1")(x1)  # 1x1 conv
        x1 = nn.relu(x1)
        x1 = x1.reshape(x1.shape[0], -1)

        x = jnp.concatenate([x, x1], axis=-1)
        x = nn.Dense(self.lin_hidden_dim, name="lin0")(x)
        x = nn.relu(x)
        x = nn.Dropout(self.drop_rate, deterministic=not train)(x)
        x = nn.Dense(2, name="lin1")(x)
        return x[:, :1], x[:, 1:]


@register_model
def baz_network(**kwargs) -> BAZNetwork:
    kwargs.pop("in_samples", None)
    kwargs = {k: v for k, v in kwargs.items() if k in BAZNetwork.__dataclass_fields__}
    return BAZNetwork(**kwargs)
