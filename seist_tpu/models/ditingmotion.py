"""DiTingMotion — dense multi-branch CNN with side-output fusion.

Architecture parity with the reference ``models/ditingmotion.py:38-341``
(Zhao et al. 2023): CombConv layers with dense concats, per-block side
layers for clarity/polarity, sigmoid fuse heads, final output = average of
all side outputs + fuse output.

Input is ``(N, L, 2)``: vertical channel + its first difference
(io-items ["z", "dz"], config.py:129).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn

from seist_tpu.models import common
from seist_tpu.registry import register_model

Array = jnp.ndarray


class CombConvLayer(nn.Module):
    """Parallel convs at several kernel sizes, dense concat with the input,
    then an out conv (ref: ditingmotion.py:38-80)."""

    out_channels: int
    kernel_sizes: Sequence[int]
    out_kernel_size: int
    drop_rate: float

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        outs = [x]
        for i, kers in enumerate(self.kernel_sizes):
            xi = common.auto_pad_1d(x, kers)
            xi = nn.Conv(self.out_channels, (kers,), padding="VALID", name=f"conv{i}")(xi)
            outs.append(nn.relu(xi))
        x = jnp.concatenate(outs, axis=-1)
        x = nn.Dropout(self.drop_rate, deterministic=not train)(x)
        x = common.auto_pad_1d(x, self.out_kernel_size)
        x = nn.Conv(
            self.out_channels, (self.out_kernel_size,), padding="VALID", name="out_conv"
        )(x)
        return nn.relu(x)


class BasicBlock(nn.Module):
    """CombConv stack + dense concat + floor-mode maxpool
    (ref: ditingmotion.py:83-116)."""

    layer_channels: Sequence[int]
    comb_kernel_sizes: Sequence[int]
    comb_out_kernel_size: int
    drop_rate: float
    pool_size: int

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        x1 = x
        for i, outc in enumerate(self.layer_channels):
            x1 = CombConvLayer(
                out_channels=outc,
                kernel_sizes=self.comb_kernel_sizes,
                out_kernel_size=self.comb_out_kernel_size,
                drop_rate=self.drop_rate,
                name=f"comb{i}",
            )(x1, train)
        x1 = jnp.concatenate([x, x1], axis=-1)
        return common.max_pool_1d(x1, self.pool_size)


class SideLayer(nn.Module):
    """CombConv + flatten + 2-layer MLP with sigmoid
    (ref: ditingmotion.py:119-171). Returns (features, hidden, probs)."""

    conv_out_channels: int
    comb_kernel_sizes: Sequence[int]
    comb_out_kernel_size: int
    drop_rate: float
    linear_in_dim: int
    linear_hidden_dim: int
    linear_out_dim: int

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Tuple[Array, Array, Array]:
        x = CombConvLayer(
            out_channels=self.conv_out_channels,
            kernel_sizes=self.comb_kernel_sizes,
            out_kernel_size=self.comb_out_kernel_size,
            drop_rate=self.drop_rate,
            name="conv_layer",
        )(x, train)
        N, L, C = x.shape
        if C * L != self.linear_in_dim:
            # Official model is fixed to L=128 inputs; interpolate to adapt
            # (ref: ditingmotion.py:157-161).
            target = self.linear_in_dim // self.conv_out_channels
            x = common.interpolate_nearest(x, target)
        # Flatten CHANNEL-major to match torch's Flatten over (N, C, L)
        # (ref: ditingmotion.py:141,163): lin0/fuse weights consume features
        # in [c0 l0..l{L-1}, c1 l0..] order, so a channels-last reshape
        # without the transpose would permute their input columns (caught
        # by the gradient-parity test with converted weights).
        x1 = jnp.swapaxes(x, 1, 2).reshape(N, -1)
        x2 = nn.relu(nn.Dense(self.linear_hidden_dim, name="lin0")(x1))
        x3 = nn.sigmoid(nn.Dense(self.linear_out_dim, name="lin1")(x2))
        return x1, x2, x3


class DiTingMotion(nn.Module):
    """(N, L, 2) -> ((N, 2) clarity, (N, 2) polarity)
    (ref: ditingmotion.py:174-335)."""

    in_channels: int = 2
    blocks_layer_channels: Sequence[Sequence[int]] = (
        (8, 8),
        (8, 8),
        (8, 8, 8),
        (8, 8, 8),
        (8, 8, 8),
    )
    side_layer_conv_channels: int = 2
    blocks_sidelayer_linear_in_dims: Sequence[Optional[int]] = (None, None, 32, 16, 16)
    blocks_sidelayer_linear_hidden_dims: Sequence[Optional[int]] = (None, None, 8, 8, 8)
    comb_kernel_sizes: Sequence[int] = (3, 3, 5, 5)
    comb_out_kernel_size: int = 3
    pool_size: int = 2
    drop_rate: float = 0.2
    fuse_hidden_dim: int = 8
    num_polarity_classes: int = 2
    num_clarity_classes: int = 2

    @nn.compact
    def __call__(self, x: Array, train: bool = False) -> Tuple[Array, Array]:
        clarity_to_fuse: List[Array] = []
        polarity_to_fuse: List[Array] = []
        clarity_outs: List[Array] = []
        polarity_outs: List[Array] = []

        for b, (layer_channels, lin_in, lin_hidden) in enumerate(
            zip(
                self.blocks_layer_channels,
                self.blocks_sidelayer_linear_in_dims,
                self.blocks_sidelayer_linear_hidden_dims,
            )
        ):
            x = BasicBlock(
                layer_channels=layer_channels,
                comb_kernel_sizes=self.comb_kernel_sizes,
                comb_out_kernel_size=self.comb_out_kernel_size,
                drop_rate=self.drop_rate,
                pool_size=self.pool_size,
                name=f"block{b}",
            )(x, train)

            if lin_in is not None:
                c0, _, c2 = SideLayer(
                    conv_out_channels=self.side_layer_conv_channels,
                    comb_kernel_sizes=self.comb_kernel_sizes,
                    comb_out_kernel_size=self.comb_out_kernel_size,
                    drop_rate=self.drop_rate,
                    linear_in_dim=lin_in,
                    linear_hidden_dim=lin_hidden,
                    linear_out_dim=self.num_clarity_classes,
                    name=f"clarity_side{b}",
                )(x, train)
                clarity_to_fuse.append(c0)
                clarity_outs.append(c2)

                _, p1, p2 = SideLayer(
                    conv_out_channels=self.side_layer_conv_channels,
                    comb_kernel_sizes=self.comb_kernel_sizes,
                    comb_out_kernel_size=self.comb_out_kernel_size,
                    drop_rate=self.drop_rate,
                    linear_in_dim=lin_in,
                    linear_hidden_dim=lin_hidden,
                    linear_out_dim=self.num_polarity_classes,
                    name=f"polarity_side{b}",
                )(x, train)
                polarity_to_fuse.append(p1)
                polarity_outs.append(p2)

        c = jnp.concatenate(clarity_to_fuse, axis=-1)
        c = nn.Dense(self.fuse_hidden_dim, name="fuse_clarity0")(c)
        c = nn.Dense(self.num_clarity_classes, name="fuse_clarity1")(c)
        clarity_outs.append(nn.sigmoid(c))

        p = jnp.concatenate(polarity_to_fuse, axis=-1)
        p = nn.Dense(self.fuse_hidden_dim, name="fuse_polarity0")(p)
        p = nn.Dense(self.num_polarity_classes, name="fuse_polarity1")(p)
        polarity_outs.append(nn.sigmoid(p))

        final_clarity = sum(clarity_outs) / len(clarity_outs)
        final_polarity = sum(polarity_outs) / len(polarity_outs)
        return final_clarity, final_polarity


@register_model
def ditingmotion(**kwargs) -> DiTingMotion:
    kwargs.pop("in_samples", None)
    kwargs = {k: v for k, v in kwargs.items() if k in DiTingMotion.__dataclass_fields__}
    return DiTingMotion(**kwargs)
