"""Model construction / initialization helpers.

Counterpart of the reference's ``models/_factory.py:41-56`` ``create_model``;
checkpoint save/load lives in seist_tpu/models/checkpoint.py.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from seist_tpu.registry import MODELS


def create_model(model_name: str, in_channels: int = 3, in_samples: int = 8192, **kwargs):
    """Instantiate a registered model module."""
    return MODELS.create(
        model_name, in_channels=in_channels, in_samples=in_samples, **kwargs
    )


def init_variables(
    model,
    seed: int = 0,
    in_samples: int = 8192,
    in_channels: int = 3,
    batch_size: int = 1,
) -> Dict[str, Any]:
    """Initialize model variables ({'params', 'batch_stats', ...}).

    The whole init is jitted: flax init executed op-by-op compiles hundreds of
    tiny XLA programs; one fused program is ~50x faster.
    """
    x = jnp.zeros((batch_size, in_samples, in_channels), dtype=jnp.float32)
    key = jax.random.PRNGKey(seed)

    @jax.jit
    def _init(key, x):
        pk, dk = jax.random.split(key)
        return model.init({"params": pk, "dropout": dk}, x, train=False)

    return _init(key, x)


def param_shapes(
    model, in_samples: int = 8192, in_channels: int = 3
) -> Dict[str, Any]:
    """Shape-only init (no compute) — for counting/inspection."""
    x = jax.ShapeDtypeStruct((1, in_samples, in_channels), jnp.float32)
    key = jax.ShapeDtypeStruct((2,), jnp.uint32)
    return jax.eval_shape(
        lambda k, x: model.init({"params": k, "dropout": k}, x, train=False), key, x
    )


def count_params(tree) -> int:
    import numpy as np

    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(tree))
