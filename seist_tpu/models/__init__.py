"""Model zoo. Importing this package registers all 21 models (the reference
does the same in models/__init__.py:2-10)."""

from seist_tpu.models.losses import (  # noqa: F401
    BCELoss,
    BinaryFocalLoss,
    CELoss,
    CombinationLoss,
    FocalLoss,
    HuberLoss,
    MousaviLoss,
    MSELoss,
)

# Import model modules for their registration side effects.
from seist_tpu.models import (  # noqa: F401
    baz_network,
    distpt_network,
    ditingmotion,
    eqtransformer,
    magnet,
    phasenet,
    seist,
)
from seist_tpu.models.api import (  # noqa: F401
    count_params,
    create_model,
    init_variables,
    param_shapes,
)
