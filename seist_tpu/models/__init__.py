"""Model zoo. Importing this package registers all models (the reference does
the same in models/__init__.py:2-10)."""

from seist_tpu.models.losses import (  # noqa: F401
    BCELoss,
    BinaryFocalLoss,
    CELoss,
    CombinationLoss,
    FocalLoss,
    HuberLoss,
    MousaviLoss,
    MSELoss,
)
