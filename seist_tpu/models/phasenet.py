"""PhaseNet — 1-D U-Net for phase picking (channels-last Flax).

TPU-native re-implementation with architecture parity to the reference
``models/phasenet.py:17-275`` (Zhu & Beroza 2019): stride-4 down/up x5,
skip concats with asymmetric crop, softmax over the 3 class channels.

Input ``(N, L, 3)`` -> output probabilities ``(N, L, 3)`` (non/ppk/spk).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn

from seist_tpu.models import common
from seist_tpu.registry import register_model

Array = jnp.ndarray


class ConvBlock(nn.Module):
    """Optional stride conv + same conv (ref: phasenet.py:17-80)."""

    in_channels: int
    out_channels: int
    kernel_size: int
    stride: int
    drop_rate: float
    has_stride_conv: bool = True

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        if self.has_stride_conv:
            # Dynamic pad so L_out = ceil(L/stride) (ref: phasenet.py:60-67).
            x = common.auto_pad_1d(x, self.kernel_size, self.stride)
            x = nn.Conv(
                self.in_channels,
                (self.kernel_size,),
                strides=(self.stride,),
                padding="VALID",
                use_bias=False,
                name="conv0",
            )(x)
            x = common.make_norm("batch", use_running_average=not train, name="bn0")(x)
            x = nn.relu(x)
            x = nn.Dropout(self.drop_rate, deterministic=not train)(x)

        x = common.same_pad_1d(x, self.kernel_size)
        x = nn.Conv(
            self.out_channels,
            (self.kernel_size,),
            padding="VALID",
            use_bias=False,
            name="conv1",
        )(x)
        x = common.make_norm("batch", use_running_average=not train, name="bn1")(x)
        x = nn.relu(x)
        x = nn.Dropout(self.drop_rate, deterministic=not train)(x)
        return x


class ConvTransBlock(nn.Module):
    """Optional same conv (on concat) + transposed conv
    (ref: phasenet.py:83-149)."""

    in_channels: int
    out_channels: int
    kernel_size: int
    stride: int
    drop_rate: float
    has_conv_same: bool = True
    has_conv_trans: bool = True

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        if self.has_conv_same:
            x = common.same_pad_1d(x, self.kernel_size)
            x = nn.Conv(
                self.in_channels,
                (self.kernel_size,),
                padding="VALID",
                use_bias=False,
                name="conv0",
            )(x)
            x = common.make_norm("batch", use_running_average=not train, name="bn0")(x)
            x = nn.relu(x)
        if self.has_conv_trans:
            x = nn.Dropout(self.drop_rate, deterministic=not train)(x)
            # torch ConvTranspose1d(pad=0): L_out = (L-1)*s + k; flax 'VALID'
            # transposed conv matches for k >= s.
            x = nn.ConvTranspose(
                self.out_channels,
                (self.kernel_size,),
                strides=(self.stride,),
                padding="VALID",
                use_bias=False,
                name="convt",
            )(x)
            x = common.make_norm("batch", use_running_average=not train, name="bn1")(x)
            x = nn.relu(x)
        if self.has_conv_same:
            x = nn.Dropout(self.drop_rate, deterministic=not train)(x)
        return x


class PhaseNet(nn.Module):
    """U-Net over (N, L, C) (ref: phasenet.py:152-267)."""

    in_channels: int = 3
    kernel_size: int = 7
    stride: int = 4
    conv_channels: Sequence[int] = (8, 16, 32, 64, 128)
    drop_rate: float = 0.1

    @nn.compact
    def __call__(self, x: Array, train: bool = False) -> Array:
        ch = list(self.conv_channels)
        depth = len(ch)

        x = common.same_pad_1d(x, self.kernel_size)
        x = nn.Conv(ch[0], (self.kernel_size,), padding="VALID", name="conv_in")(x)
        x = common.make_norm("batch", use_running_average=not train, name="bn_in")(x)
        x = nn.relu(x)
        x = nn.Dropout(self.drop_rate, deterministic=not train)(x)

        # Down path (ref: phasenet.py:194-210, 244-249)
        down_in = ch[:1] + ch[:-1]
        shortcuts = []
        for i in range(depth - 1):
            x = ConvBlock(
                in_channels=down_in[i],
                out_channels=ch[i],
                kernel_size=self.kernel_size,
                stride=self.stride,
                drop_rate=self.drop_rate,
                has_stride_conv=(i != 0),
                name=f"down{i}",
            )(x, train)
            shortcuts.append(x)
        x = ConvBlock(
            in_channels=down_in[-1],
            out_channels=ch[-1],
            kernel_size=self.kernel_size,
            stride=self.stride,
            drop_rate=self.drop_rate,
            has_stride_conv=True,
            name=f"down{depth - 1}",
        )(x, train)

        # Up path (ref: phasenet.py:213-230, 251-262)
        up_in = ch[::-1]
        up_out = ch[-2::-1] + [ch[0]]  # last block has no trans conv
        rev_i = list(range(depth))[::-1]
        for j in range(depth - 1):
            x = ConvTransBlock(
                in_channels=up_in[j],
                out_channels=up_out[j],
                kernel_size=self.kernel_size,
                stride=self.stride,
                drop_rate=self.drop_rate,
                has_conv_same=(rev_i[j] < depth - 1),
                has_conv_trans=(rev_i[j] > 0),
                name=f"up{j}",
            )(x, train)
            shortcut = shortcuts[-(j + 1)]
            # Crop the transposed-conv overhang then concat the skip
            # (ref: phasenet.py:253-260).
            p = common.auto_pad_amount(
                shortcut.shape[-2], self.kernel_size, self.stride
            )
            lp, rp = p
            x = jnp.concatenate([shortcut, x[:, lp : x.shape[-2] - rp, :]], axis=-1)
        x = ConvTransBlock(
            in_channels=up_in[-1],
            out_channels=up_out[-1],
            kernel_size=self.kernel_size,
            stride=self.stride,
            drop_rate=self.drop_rate,
            has_conv_same=True,
            has_conv_trans=False,
            name=f"up{depth - 1}",
        )(x, train)

        x = nn.Conv(3, (1,), name="conv_out")(x)
        return nn.softmax(x, axis=-1)


@register_model
def phasenet(**kwargs) -> PhaseNet:
    kwargs.pop("in_samples", None)
    kwargs = {k: v for k, v in kwargs.items() if k in PhaseNet.__dataclass_fields__}
    return PhaseNet(**kwargs)
