"""EQTransformer — conv + ResCNN + BiLSTM + transformer encoder with three
upsampling decoders (det / P / S).

Architecture parity with the reference ``models/eqtransformer.py:18-614``
(Mousavi et al. 2020). Channels-last Flax. Notes:

* The reference's L1 regularization of first-stage conv weights is
  implemented via grad hooks (eqtransformer.py:43-51,388-396); here it is a
  training-side optax gradient transform (seist_tpu/train/optim.py:
  ``l1_sign_decay``) scoped to the first conv stage — the constructor alphas
  default to 0.0 in both frameworks.
* The additive single-head attention with optional banded mask reproduces
  ``AttentionLayer`` (eqtransformer.py:135-198) including the
  exp/max-shift/eps-sum softmax.
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn

from seist_tpu.models import common
from seist_tpu.registry import register_model

Array = jnp.ndarray

_EPS = 1e-6


class ConvBlock(nn.Module):
    """same conv -> relu -> odd-length pad -> maxpool/2
    (ref: eqtransformer.py:18-59)."""

    out_channels: int
    kernel_size: int

    @nn.compact
    def __call__(self, x: Array) -> Array:
        x = common.same_pad_1d(x, self.kernel_size)
        x = nn.Conv(self.out_channels, (self.kernel_size,), padding="VALID", name="conv")(x)
        x = nn.relu(x)
        if x.shape[-2] % 2:
            pads = [(0, 0)] * x.ndim
            pads[-2] = (0, 1)
            x = jnp.pad(x, pads, constant_values=-1.0 / _EPS)
        return common.max_pool_1d(x, 2)


class ResConvBlock(nn.Module):
    """Pre-norm residual conv pair with channel dropout
    (ref: eqtransformer.py:62-102)."""

    kernel_size: int
    drop_rate: float

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        C = x.shape[-1]
        x1 = x
        for i in range(2):
            x1 = common.make_norm("batch", use_running_average=not train, name=f"bn{i}")(x1)
            x1 = nn.relu(x1)
            x1 = nn.Dropout(
                self.drop_rate, broadcast_dims=(1,), deterministic=not train
            )(x1)
            x1 = common.same_pad_1d(x1, self.kernel_size)
            x1 = nn.Conv(C, (self.kernel_size,), padding="VALID", name=f"conv{i}")(x1)
        return x + x1


class BiLSTMBlock(nn.Module):
    """BiLSTM -> dropout -> 1x1 conv -> BN (ref: eqtransformer.py:105-132)."""

    out_channels: int
    drop_rate: float

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        x, _ = common.BiLSTM(self.out_channels, name="bilstm")(x)
        x = nn.Dropout(self.drop_rate, deterministic=not train)(x)
        x = nn.Dense(self.out_channels, name="conv")(x)
        x = common.make_norm("batch", use_running_average=not train, name="bn")(x)
        return x


class AttentionLayer(nn.Module):
    """Additive single-head attention, optional banded mask
    (ref: eqtransformer.py:135-198)."""

    d_model: int
    attn_width: int | None = None

    @nn.compact
    def __call__(self, x: Array) -> Tuple[Array, Array]:
        # x: (N, L, C)
        C = x.shape[-1]
        Wx = self.param("Wx", nn.initializers.xavier_uniform(), (C, self.d_model))
        Wt = self.param("Wt", nn.initializers.xavier_uniform(), (C, self.d_model))
        bh = self.param("bh", nn.initializers.zeros, (self.d_model,))
        Wa = self.param("Wa", nn.initializers.xavier_uniform(), (self.d_model, 1))
        ba = self.param("ba", nn.initializers.zeros, (1,))

        q = (x @ Wt)[:, :, None, :]  # (N, L, 1, d)
        k = (x @ Wx)[:, None, :, :]  # (N, 1, L, d)
        h = jnp.tanh(q + k + bh)  # (N, L, L, d)
        e = (h @ Wa)[..., 0] + ba  # (N, L, L)
        e = jnp.exp(e - jnp.max(e, axis=-1, keepdims=True))

        if self.attn_width is not None:
            L = x.shape[1]
            i = jnp.arange(L)[:, None]
            j = jnp.arange(L)[None, :]
            # tril(w//2 - 1) & triu(-w//2). Note the reference's `-w // 2` is
            # (-w)//2 (floor division of the *negated* width), so odd w=3
            # gives a lower bound of j - i >= -2, not -1.
            mask = (j - i <= self.attn_width // 2 - 1) & (
                j - i >= (-self.attn_width) // 2
            )
            e = jnp.where(mask, e, 0.0)

        s = jnp.sum(e, axis=-1, keepdims=True)
        a = e / (s + _EPS)
        v = jnp.einsum("nlm,nmc->nlc", a, x)
        return v, a


class FeedForward(nn.Module):
    """2-layer MLP (ref: eqtransformer.py:201-229)."""

    feedforward_dim: int
    drop_rate: float

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        C = x.shape[-1]
        x = nn.Dense(
            self.feedforward_dim,
            kernel_init=nn.initializers.xavier_uniform(),
            name="lin0",
        )(x)
        x = nn.relu(x)
        x = nn.Dropout(self.drop_rate, deterministic=not train)(x)
        x = nn.Dense(C, kernel_init=nn.initializers.xavier_uniform(), name="lin1")(x)
        return x


class TransformerLayer(nn.Module):
    """attn + LN + FF + LN (ref: eqtransformer.py:232-266)."""

    d_model: int
    feedforward_dim: int
    drop_rate: float
    attn_width: int | None = None

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Tuple[Array, Array]:
        x1, w = AttentionLayer(self.d_model, self.attn_width, name="attn")(x)
        x2 = nn.LayerNorm(name="ln0")(x1 + x)
        x3 = FeedForward(self.feedforward_dim, self.drop_rate, name="ff")(x2, train)
        x4 = nn.LayerNorm(name="ln1")(x3 + x2)
        return x4, w


class Encoder(nn.Module):
    """Conv x7 + ResConv x5 + BiLSTM x3 + Transformer x2
    (ref: eqtransformer.py:269-359)."""

    conv_channels: Sequence[int]
    conv_kernels: Sequence[int]
    resconv_kernels: Sequence[int]
    num_lstm_blocks: int
    num_transformer_layers: int
    transformer_io_channels: int
    transformer_d_model: int
    feedforward_dim: int
    drop_rate: float

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        for i, (outc, kers) in enumerate(zip(self.conv_channels, self.conv_kernels)):
            x = ConvBlock(outc, kers, name=f"conv{i}")(x)
        for i, kers in enumerate(self.resconv_kernels):
            x = ResConvBlock(kers, self.drop_rate, name=f"resconv{i}")(x, train)
        for i in range(self.num_lstm_blocks):
            x = BiLSTMBlock(
                self.transformer_io_channels, self.drop_rate, name=f"bilstm{i}"
            )(x, train)
        for i in range(self.num_transformer_layers):
            x, w = TransformerLayer(
                self.transformer_d_model,
                self.feedforward_dim,
                self.drop_rate,
                name=f"transformer{i}",
            )(x, train)
        return x


class UpSamplingBlock(nn.Module):
    """x2 nearest upsample -> crop -> same conv -> relu
    (ref: eqtransformer.py:362-405)."""

    out_channels: int
    out_samples: int
    kernel_size: int

    @nn.compact
    def __call__(self, x: Array) -> Array:
        x = common.upsample_x2(x)
        x = x[:, : self.out_samples, :]
        x = common.same_pad_1d(x, self.kernel_size)
        x = nn.Conv(self.out_channels, (self.kernel_size,), padding="VALID", name="conv")(x)
        return nn.relu(x)


class Decoder(nn.Module):
    """Optional LSTM + local-attn transformer, then 7 upsampling blocks
    (ref: eqtransformer.py:421-513)."""

    conv_channels: Sequence[int]
    conv_kernels: Sequence[int]
    transformer_io_channels: int
    transformer_d_model: int
    feedforward_dim: int
    drop_rate: float
    out_samples: int
    has_lstm: bool = True
    has_local_attn: bool = True
    local_attn_width: int = 3

    @nn.compact
    def __call__(self, x: Array, train: bool) -> Array:
        if self.has_lstm:
            x, _ = common.LSTM(self.transformer_io_channels, name="lstm")(x)
            x = nn.Dropout(self.drop_rate, deterministic=not train)(x)
        if self.has_local_attn:
            x, _ = TransformerLayer(
                self.transformer_d_model,
                self.feedforward_dim,
                self.drop_rate,
                attn_width=self.local_attn_width,
                name="transformer",
            )(x, train)

        crop_sizes = [self.out_samples]
        for _ in range(len(self.conv_kernels) - 1):
            crop_sizes.insert(0, math.ceil(crop_sizes[0] / 2))

        for i, (outc, crop, kers) in enumerate(
            zip(self.conv_channels, crop_sizes, self.conv_kernels)
        ):
            x = UpSamplingBlock(outc, crop, kers, name=f"up{i}")(x)

        x = nn.Conv(1, (11,), padding=[(5, 5)], name="conv_out")(x)
        return nn.sigmoid(x)


class EQTransformer(nn.Module):
    """(N, L, 3) -> (N, L, 3) probabilities [det, ppk, spk]
    (ref: eqtransformer.py:516-614)."""

    in_channels: int = 3
    in_samples: int = 8192
    conv_channels: Sequence[int] = (8, 16, 16, 32, 32, 64, 64)
    conv_kernels: Sequence[int] = (11, 9, 7, 7, 5, 5, 3)
    resconv_kernels: Sequence[int] = (3, 3, 3, 2, 2)
    num_lstm_blocks: int = 3
    num_transformer_layers: int = 2
    transformer_io_channels: int = 16
    transformer_d_model: int = 32
    feedforward_dim: int = 128
    local_attention_width: int = 3
    drop_rate: float = 0.1
    decoder_with_attn_lstm: Sequence[bool] = (False, True, True)

    @nn.compact
    def __call__(self, x: Array, train: bool = False) -> Array:
        feature = Encoder(
            conv_channels=self.conv_channels,
            conv_kernels=self.conv_kernels,
            resconv_kernels=self.resconv_kernels,
            num_lstm_blocks=self.num_lstm_blocks,
            num_transformer_layers=self.num_transformer_layers,
            transformer_io_channels=self.transformer_io_channels,
            transformer_d_model=self.transformer_d_model,
            feedforward_dim=self.feedforward_dim,
            drop_rate=self.drop_rate,
            name="encoder",
        )(x, train)

        outputs = []
        for d, has_attn_lstm in enumerate(self.decoder_with_attn_lstm):
            outputs.append(
                Decoder(
                    conv_channels=self.conv_channels[::-1],
                    conv_kernels=self.conv_kernels[::-1],
                    transformer_io_channels=self.transformer_io_channels,
                    transformer_d_model=self.transformer_d_model,
                    feedforward_dim=self.feedforward_dim,
                    drop_rate=self.drop_rate,
                    out_samples=self.in_samples,
                    has_lstm=has_attn_lstm,
                    has_local_attn=has_attn_lstm,
                    local_attn_width=self.local_attention_width,
                    name=f"decoder{d}",
                )(feature, train)
            )
        return jnp.concatenate(outputs, axis=-1)


def l1_param_mask(params, kind: str):
    """Bool pytree selecting the params the reference L1-regularizes via
    gradient hooks (ref eqtransformer.py:43-51,388-396): the encoder
    ConvBlock convs (``encoder/conv{i}/conv``) and the decoder Upsampling
    convs (``decoder{d}/up{i}/conv``). ``kind`` is 'kernel' or 'bias'.

    Feed to ``train.optim.l1_sign_decay`` (the optax equivalent of the
    reference's grad hooks) via ``build_optimizer``'s l1 arguments.
    """
    import re

    import jax

    assert kind in ("kernel", "bias"), kind
    pat = re.compile(r"^/(encoder/conv\d+|decoder\d+/up\d+)/conv$")

    def sel(path, _):
        keys = [str(getattr(k, "key", k)) for k in path]
        return keys[-1] == kind and bool(pat.match("/" + "/".join(keys[:-1])))

    return jax.tree_util.tree_map_with_path(sel, params)


@register_model
def eqtransformer(**kwargs) -> EQTransformer:
    kwargs = {k: v for k, v in kwargs.items() if k in EQTransformer.__dataclass_fields__}
    return EQTransformer(**kwargs)
