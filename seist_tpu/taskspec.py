"""Task specifications: io-item catalog + per-model-family task configs.

This is the TPU-native replacement for the reference's ``config.py`` (see
/root/reference/config.py:20-435). The reference keys its model configs by
regex and stores loss constructors via ``functools.partial``; here the same
information is typed data:

* :class:`IOItem` — one entry of the io-item catalog
  (/root/reference/config.py:207-264).
* :class:`TaskSpec` — loss factory, input/label/eval lists and optional
  transforms for one model family (/root/reference/config.py:64-186).

Data layout convention: this framework is **channels-last** — waveforms are
``(N, L, C)`` and dense outputs are ``(N, L, C)`` — the layout XLA prefers on
TPU. The reference is channels-first ``(N, C, L)``; transposition happens only
in parity tooling (tools/torch2flax.py).
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp

# ---------------------------------------------------------------------------
# io-item catalog
# ---------------------------------------------------------------------------

SOFT = "soft"
VALUE = "value"
ONEHOT = "onehot"
_IO_KINDS = (SOFT, VALUE, ONEHOT)

AVAILABLE_METRICS = (
    "precision",
    "recall",
    "f1",
    "mean",
    "rmse",
    "mae",
    "mape",
    "r2",
)


@dataclass(frozen=True)
class IOItem:
    """One io-item (model input or label). Ref: config.py:207-264."""

    name: str
    kind: str
    metrics: Tuple[str, ...] = ()
    num_classes: Optional[int] = None

    def __post_init__(self):
        if self.kind not in _IO_KINDS:
            raise ValueError(f"Unknown io-item kind '{self.kind}' for '{self.name}'")
        unknown = set(self.metrics) - set(AVAILABLE_METRICS)
        if unknown:
            raise ValueError(f"Unknown metrics {unknown} for io-item '{self.name}'")
        if self.kind == ONEHOT and not self.num_classes:
            raise ValueError(f"onehot io-item '{self.name}' needs num_classes")


_WAVE_METRICS = ("mean", "rmse", "mae")
_PICK_METRICS = ("precision", "recall", "f1", "mean", "rmse", "mae", "mape")
_VALUE_METRICS = ("mean", "rmse", "mae", "mape", "r2")
_REGR_METRICS = ("mean", "rmse", "mae", "r2")
_CLS_METRICS = ("precision", "recall", "f1")

IO_ITEMS: Dict[str, IOItem] = {
    item.name: item
    for item in [
        IOItem("z", SOFT, _WAVE_METRICS),
        IOItem("n", SOFT, _WAVE_METRICS),
        IOItem("e", SOFT, _WAVE_METRICS),
        IOItem("dz", SOFT, _WAVE_METRICS),
        IOItem("dn", SOFT, _WAVE_METRICS),
        IOItem("de", SOFT, _WAVE_METRICS),
        IOItem("non", SOFT, ()),
        IOItem("det", SOFT, _CLS_METRICS),
        IOItem("ppk", SOFT, _PICK_METRICS),
        IOItem("spk", SOFT, _PICK_METRICS),
        IOItem("ppk+", SOFT, ()),
        IOItem("spk+", SOFT, ()),
        IOItem("det+", SOFT, ()),
        IOItem("ppks", VALUE, _VALUE_METRICS),
        IOItem("spks", VALUE, _VALUE_METRICS),
        IOItem("emg", VALUE, _REGR_METRICS),
        IOItem("smg", VALUE, _REGR_METRICS),
        IOItem("baz", VALUE, _REGR_METRICS),
        IOItem("dis", VALUE, _REGR_METRICS),
        IOItem("pmp", ONEHOT, _CLS_METRICS, num_classes=2),
        IOItem("clr", ONEHOT, _CLS_METRICS, num_classes=2),
    ]
}


def get_io_items(kind: Optional[str] = None) -> List[str]:
    if kind is None:
        return list(IO_ITEMS)
    return [k for k, v in IO_ITEMS.items() if v.kind == kind]


def get_kind(name: str) -> str:
    return IO_ITEMS[name].kind


def get_num_classes(name: str) -> int:
    item = IO_ITEMS[name]
    if item.kind != ONEHOT:
        raise ValueError(f"io-item '{name}' is '{item.kind}', not onehot")
    return int(item.num_classes)


def get_metrics(name: str) -> List[str]:
    if name not in IO_ITEMS:
        raise KeyError(f"Unknown io-item '{name}', supported: {list(IO_ITEMS)}")
    return list(IO_ITEMS[name].metrics)


# ---------------------------------------------------------------------------
# Task specs
# ---------------------------------------------------------------------------

IOName = Union[str, Tuple[str, ...]]


def _deg2rad(x):
    return x * (math.pi / 180.0)


def _baz_targets_to_cos_sin(x):
    """baz scalar degrees -> (cos, sin) pair. Ref: config.py:102-105."""
    r = _deg2rad(x)
    return (jnp.cos(r), jnp.sin(r))


def _baz_outputs_to_deg(x):
    """(cos, sin) pair -> degrees via atan2. Ref: config.py:107-109."""
    return jnp.arctan2(x[1], x[0]) * (180.0 / math.pi)


def _magnet_results(x):
    """Keep only the mean prediction (drop log-variance). Ref: config.py:94."""
    return x[:, 0].reshape(-1, 1)


def _softmax_each(xs):
    """Softmax every element of a tuple of outputs. Ref: config.py:134."""
    return [jnp.asarray(jnp.exp(x) / jnp.sum(jnp.exp(x), axis=-1, keepdims=True)) for x in xs]


@dataclass(frozen=True)
class TaskSpec:
    """Task configuration for one model family. Ref: config.py:64-186.

    ``loss`` is a zero-arg factory returning a loss callable
    ``loss(preds, targets) -> scalar`` (see seist_tpu/models/losses.py).
    """

    pattern: str
    loss: Callable[[], Any]
    inputs: Tuple[IOName, ...]
    labels: Tuple[IOName, ...]
    eval: Tuple[str, ...]
    targets_transform_for_loss: Optional[Callable] = None
    outputs_transform_for_loss: Optional[Callable] = None
    outputs_transform_for_results: Optional[Callable] = None

    def matches(self, model_name: str) -> bool:
        return bool(re.findall(self.pattern, model_name))


def _build_task_specs() -> List[TaskSpec]:
    # Imported lazily to avoid a models <-> taskspec import cycle.
    from seist_tpu.models import losses as L

    ws = lambda w: tuple(w)  # noqa: E731  (readability for loss weights)

    return [
        # ------------------------------------------------ PhaseNet (config.py:67-75)
        TaskSpec(
            pattern="phasenet",
            loss=lambda: L.CELoss(weight=[1.0, 1.0, 1.0]),
            inputs=(("z", "n", "e"),),
            labels=(("non", "ppk", "spk"),),
            eval=("ppk", "spk"),
        ),
        # ------------------------------------------- EQTransformer (config.py:77-85)
        TaskSpec(
            pattern="eqtransformer",
            loss=lambda: L.BCELoss(weight=[0.5, 1.0, 1.0]),
            inputs=(("z", "n", "e"),),
            labels=(("det", "ppk", "spk"),),
            eval=("det", "ppk", "spk"),
        ),
        # -------------------------------------------------- MagNet (config.py:87-95)
        TaskSpec(
            pattern="magnet",
            loss=L.MousaviLoss,
            inputs=(("z", "n", "e"),),
            labels=("emg",),
            eval=("emg",),
            outputs_transform_for_results=_magnet_results,
        ),
        # --------------------------------------------- BAZ Network (config.py:97-110)
        TaskSpec(
            pattern="baz_network",
            loss=lambda: L.CombinationLoss(losses=[L.MSELoss, L.MSELoss]),
            inputs=(("z", "n", "e"),),
            labels=("baz",),
            eval=("baz",),
            targets_transform_for_loss=_baz_targets_to_cos_sin,
            outputs_transform_for_results=_baz_outputs_to_deg,
        ),
        # ------------------------------------------- DiTingMotion (config.py:127-135)
        TaskSpec(
            pattern="ditingmotion",
            loss=lambda: L.CombinationLoss(losses=[L.FocalLoss, L.FocalLoss]),
            inputs=(("z", "dz"),),
            labels=("clr", "pmp"),
            eval=("pmp",),
            outputs_transform_for_results=_softmax_each,
        ),
        # ------------------------------------------- SeisT dpk (config.py:137-145)
        TaskSpec(
            pattern="seist_.*?_dpk.*",
            loss=lambda: L.BCELoss(weight=[0.5, 1.0, 1.0]),
            inputs=(("z", "n", "e"),),
            labels=(("det", "ppk", "spk"),),
            eval=("det", "ppk", "spk"),
        ),
        # ------------------------------------------- SeisT pmp (config.py:147-155)
        TaskSpec(
            pattern="seist_.*?_pmp",
            loss=lambda: L.CELoss(weight=[1.0, 1.0]),
            inputs=(("z", "n", "e"),),
            labels=("pmp",),
            eval=("pmp",),
        ),
        # ------------------------------------------- SeisT emg (config.py:157-165)
        TaskSpec(
            pattern="seist_.*?_emg",
            loss=L.HuberLoss,
            inputs=(("z", "n", "e"),),
            labels=("emg",),
            eval=("emg",),
        ),
        # ------------------------------------------- SeisT baz (config.py:167-175)
        TaskSpec(
            pattern="seist_.*?_baz",
            loss=L.HuberLoss,
            inputs=(("z", "n", "e"),),
            labels=("baz",),
            eval=("baz",),
        ),
        # ------------------------------------------- SeisT dis (config.py:177-185)
        TaskSpec(
            pattern="seist_.*?_dis",
            loss=L.HuberLoss,
            inputs=(("z", "n", "e"),),
            labels=("dis",),
            eval=("dis",),
        ),
    ]


_TASK_SPECS: Optional[List[TaskSpec]] = None


def task_specs() -> List[TaskSpec]:
    global _TASK_SPECS
    if _TASK_SPECS is None:
        _TASK_SPECS = _build_task_specs()
    return _TASK_SPECS


def get_task_spec(model_name: str) -> TaskSpec:
    """Resolve the unique TaskSpec for a model name. Ref: config.py:352-376."""
    from seist_tpu.registry import MODELS

    if len(MODELS) and model_name not in MODELS:
        raise KeyError(
            f"Unknown model: '{model_name}', registered: {MODELS.names()}"
        )
    hits = [s for s in task_specs() if s.matches(model_name)]
    if not hits:
        raise KeyError(f"Missing task spec for model '{model_name}'")
    if len(hits) > 1:
        raise KeyError(
            f"Model '{model_name}' matches multiple task specs: "
            f"{[s.pattern for s in hits]}"
        )
    return hits[0]


def flatten_io_names(names: Sequence[IOName]) -> List[str]:
    """Expand grouped io-names into a flat list. Ref: config.py:292-294."""
    out: List[str] = []
    for n in names:
        if isinstance(n, (tuple, list)):
            out.extend(n)
        else:
            out.append(n)
    return out


def get_num_inchannels(model_name: str) -> int:
    """Number of waveform input channels. Ref: config.py:396-408."""
    spec = get_task_spec(model_name)
    for inp in spec.inputs:
        if isinstance(inp, (tuple, list)) and IO_ITEMS[inp[0]].kind == SOFT:
            return len(inp)
    raise ValueError(f"Incorrect input channels for model '{model_name}': {spec.inputs}")


def make_loss(model_name: str):
    """Instantiate the loss for a model. Ref: config.py:421-432."""
    return get_task_spec(model_name).loss()


def validate(strict_models: bool = True) -> None:
    """Cross-check specs against the io-item catalog and the model registry.

    Mirrors the reference's import-time ``Config.check_and_init``
    (config.py:267-325). Called from ``seist_tpu.__init__`` after model
    registration so a bad spec fails fast.
    """
    from seist_tpu.registry import MODELS

    for spec in task_specs():
        for group_name, group in (("labels", spec.labels), ("inputs", spec.inputs)):
            unknown = set(flatten_io_names(group)) - set(IO_ITEMS)
            if unknown:
                raise NotImplementedError(
                    f"Task '{spec.pattern}': unknown {group_name}: {unknown}"
                )
        unknown_tasks = set(spec.eval) - set(IO_ITEMS)
        if unknown_tasks:
            raise NotImplementedError(
                f"Task '{spec.pattern}': unknown eval tasks: {unknown_tasks}"
            )

    if strict_models and len(MODELS):
        unused = [
            s.pattern
            for s in task_specs()
            if not any(s.matches(m) for m in MODELS.names())
        ]
        if unused:
            # Parity with the reference, which only warns (config.py:284-285).
            print(f"Useless task specs: {unused}")
