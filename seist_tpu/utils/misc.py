"""Seeds, paths, SNR and small helpers.

Replaces the reference's ``utils/misc.py`` grab-bag. The NCCL helpers
(misc.py:103-172) have **no equivalent here by design**: collectives are
emitted by XLA from sharded jit programs (see seist_tpu/parallel/).
"""

from __future__ import annotations

import os
import random
import time
from typing import Any, Dict

import jax
import numpy as np


def setup_seed(seed: int) -> jax.Array:
    """Seed host-side RNGs and return the root JAX PRNG key.

    The reference seeds torch/cuda/numpy/random and forces cuDNN determinism
    (utils/misc.py:14-21). In JAX, device-side randomness is explicit: all
    on-device sampling flows from the returned key; numpy/random cover the
    host-side input pipeline.
    """
    random.seed(seed)
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)


def get_time_str() -> str:
    return time.strftime("%Y-%m-%d-%H-%M-%S", time.localtime())


def get_safe_path(path: str) -> str:
    """Dedupe a path by appending ``_new`` recursively (ref: misc.py:41-52)."""
    if not os.path.exists(path):
        return path
    base, ext = os.path.splitext(path)
    return get_safe_path(f"{base}_new{ext}")


def strftimedelta(seconds: float) -> str:
    seconds = int(seconds)
    h, rem = divmod(seconds, 3600)
    m, s = divmod(rem, 60)
    return f"{h:d}:{m:02d}:{s:02d}"


def count_params(params) -> int:
    """Total number of elements in a parameter pytree."""
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(params))


def cal_snr(data: np.ndarray, pat: int, window: int = 500) -> np.ndarray:
    """Per-channel SNR (dB) around the P arrival (ref: utils/misc.py:228-248).

    Args:
        data: ``(C, L)`` waveform.
        pat: P-arrival sample index.
        window: half-window length in samples.
    """
    data = np.asarray(data)
    snr = np.zeros(data.shape[0], dtype=np.float32)
    if pat - window < 0 or pat + window > data.shape[-1]:
        return snr
    for c in range(data.shape[0]):
        signal = data[c, pat : pat + window]
        noise = data[c, pat - window : pat]
        ps = np.sum(signal.astype(np.float64) ** 2) / max(len(signal), 1)
        pn = np.sum(noise.astype(np.float64) ** 2) / max(len(noise), 1)
        if pn > 0 and ps > 0:
            snr[c] = 10.0 * np.log10(ps / pn)
    return snr


def dump_namespace(args: Any) -> str:
    """Render args (argparse.Namespace or dict) for startup logging
    (ref: misc.py:206-221)."""
    if hasattr(args, "__dict__"):
        d: Dict[str, Any] = vars(args)
    else:
        d = dict(args)
    lines = [f"  {k} = {v!r}" for k, v in sorted(d.items())]
    return "Arguments:\n" + "\n".join(lines)


def enable_compile_cache(
    verbose: bool = False, min_compile_seconds: int = 10
) -> None:
    """Persistent XLA compilation cache (large models cost minutes per
    compile on TPU; identical programs across runs hit the disk cache —
    measured 3x on CPU test-sized programs too, which is why conftest.py
    enables it for the tier-1 suite with a low threshold).

    Dir from ``JAX_COMPILATION_CACHE_DIR`` (empty value = disabled),
    default ``~/.cache/seist_tpu_xla``. Best-effort: failures never block
    a run. Shared by the CLI (cli.main_worker), bench.py, and tests.
    """
    cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "seist_tpu_xla"),
    )
    if not cache_dir:
        return  # explicit opt-out
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            int(min_compile_seconds),
        )
    except Exception as e:  # noqa: BLE001 - cache is best-effort
        if verbose:
            import sys

            print(f"compilation cache unavailable: {e!r}", file=sys.stderr)
