"""Profiling / tracing — a first-class subsystem the reference lacks
(SURVEY.md §5: only coarse epoch timing + TensorBoard scalars).

* :func:`trace` — context manager around ``jax.profiler`` writing a
  TensorBoard-loadable trace (XLA ops, fusion, HBM traffic) to the log dir.
* :func:`device_memory_stats` — per-device HBM usage snapshot.
* :class:`ThroughputMeter` — waveforms/sec with warmup skip, the number
  BASELINE.md's north-star metric is quoted in.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Dict, Iterator, List, Optional


def trace_start(logdir: str) -> None:
    """Begin a jax.profiler trace (pair with :func:`trace_stop`) — the
    non-contextmanager form for capture windows that span loop iterations
    (the worker's --profile-steps path)."""
    import jax

    jax.profiler.start_trace(logdir)


def trace_stop() -> None:
    import jax

    jax.profiler.stop_trace()


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """``with trace(dir):`` profiles everything inside; view with
    TensorBoard's profile plugin or Perfetto."""
    trace_start(logdir)
    try:
        yield
    finally:
        trace_stop()


@contextlib.contextmanager
def stopwatch() -> Iterator[Callable[[], float]]:
    """``with stopwatch() as elapsed:`` — ``elapsed()`` returns seconds
    since entry (monotonic), both inside the block and after it exits.
    Used by serve warmup/handlers so timing reads the same everywhere.

    Thin re-export of the obs bus's timing primitive (obs/bus.py): every
    interval in the repo reads ONE monotonic clock, so the span API, this
    stopwatch and :class:`StepTimeSplit` can never drift apart."""
    from seist_tpu.obs.bus import stopwatch as _stopwatch

    with _stopwatch() as elapsed:
        yield elapsed


def device_memory_stats() -> List[Dict[str, float]]:
    """Per-device memory stats (bytes). Empty list on backends without
    memory_stats support (CPU)."""
    import jax

    out = []
    for d in jax.devices():
        stats = getattr(d, "memory_stats", lambda: None)()
        if stats:
            out.append({"device": str(d), **{k: float(v) for k, v in stats.items()}})
    return out


class StepTimeSplit:
    """Per-step host-wait vs device-compute split.

    ``host_wait`` is the time the step loop spends BEFORE the device can
    start — fetching/stacking the batch and staging it to the device;
    ``device_time`` is dispatch-to-block_until_ready. The
    ``input_bound_fraction`` (host / (host + device)) is the number that
    says whether training is input-bound: ~0 means the chip sets the
    pace, ~1 means it idles behind the input pipeline. Recorded per step
    so bench.py can emit the raw split; the first ``skip_first`` steps
    (jit compile / warmup) are excluded from the summary.
    """

    def __init__(self, skip_first: int = 1):
        self.skip_first = int(skip_first)
        self.host_s: List[float] = []
        self.device_s: List[float] = []
        self._pending_host: Optional[float] = None

    def step(self, host_s: float, device_s: float) -> None:
        self.host_s.append(float(host_s))
        self.device_s.append(float(device_s))

    @contextlib.contextmanager
    def host(self) -> Iterator[None]:
        """Time the host half of one step (batch fetch/stack/stage) on
        the shared obs stopwatch; pair with :meth:`device`, which records
        the completed (host, device) step."""
        with stopwatch() as elapsed:
            yield
        self._pending_host = elapsed()

    @contextlib.contextmanager
    def device(self) -> Iterator[None]:
        """Time the device half (dispatch→block_until_ready) and record
        the step with the pending host time from :meth:`host`."""
        with stopwatch() as elapsed:
            yield
        self.step(self._pending_host or 0.0, elapsed())
        self._pending_host = None

    def summary(self) -> Dict[str, object]:
        h = self.host_s[self.skip_first :]
        d = self.device_s[self.skip_first :]
        if not h:
            return {
                "steps": 0,
                "host_wait_ms_per_step": None,
                "device_time_ms_per_step": None,
                "input_bound_fraction": None,
                "per_step_host_wait_ms": [],
                "per_step_device_time_ms": [],
            }
        hm = sum(h) / len(h)
        dm = sum(d) / len(d)
        return {
            "steps": len(h),
            "host_wait_ms_per_step": round(hm * 1e3, 3),
            "device_time_ms_per_step": round(dm * 1e3, 3),
            "input_bound_fraction": round(hm / max(hm + dm, 1e-12), 4),
            "per_step_host_wait_ms": [round(x * 1e3, 3) for x in h],
            "per_step_device_time_ms": [round(x * 1e3, 3) for x in d],
        }


class ThroughputMeter:
    """Waveforms/sec over a sliding run, skipping compile-time warmup steps."""

    def __init__(self, warmup_steps: int = 2):
        self._warmup = warmup_steps
        self._count = 0
        self._items = 0
        self._start: Optional[float] = None

    def step(self, n_items: int) -> None:
        self._count += 1
        if self._count == self._warmup + 1:
            self._start = time.perf_counter()
            self._items = 0
        if self._count > self._warmup:
            self._items += n_items

    @property
    def items_per_sec(self) -> float:
        if self._start is None or self._items == 0:
            return 0.0
        dt = time.perf_counter() - self._start
        return self._items / dt if dt > 0 else 0.0
