"""JAX platform override helper shared by every entry script.

The sandbox's sitecustomize registers the TPU backend at interpreter
start, so the ``JAX_PLATFORMS`` env var alone is NOT honored; each entry
point must force it through ``jax.config`` BEFORE any device query, or a
dead TPU tunnel hangs backend init for minutes. This helper keeps that
invariant in one place — call it first thing in ``main()``, before
anything that could touch devices.
"""

from __future__ import annotations

import os


def honor_jax_platforms() -> None:
    """Apply JAX_PLATFORMS from the environment via jax.config (no-op when
    unset). Safe to call any time before the first device query."""
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
