"""Scalar logging: TensorBoard event files with a JSONL fallback.

The reference writes per-step/per-epoch scalars through
``torch.utils.tensorboard.SummaryWriter`` (train.py:166-173,420-442). Here
the writer is pluggable: if the tensorboard package is importable we emit
real event files (same dashboards work); otherwise scalars append to
``scalars.jsonl`` in the log dir — machine-readable either way.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional


class ScalarWriter:
    def __init__(self, logdir: str):
        self._logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        self._tb = None
        try:
            from torch.utils.tensorboard import SummaryWriter

            self._tb = SummaryWriter(logdir)
            mode = "tensorboard event files"
        # tensorboard is optional: ANY import/init failure (missing
        # package, protobuf version clash, unwritable event file) must
        # degrade to the JSONL sink, never kill a training run over a
        # diagnostics writer.
        except Exception:
            self._jsonl = open(os.path.join(logdir, "scalars.jsonl"), "a")
            mode = "JSONL fallback (tensorboard unavailable)"
        from seist_tpu.utils.logger import logger

        logger.info(f"ScalarWriter: {mode} -> {logdir}")

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        if self._tb is not None:
            self._tb.add_scalar(tag, value, step)
        else:
            self._jsonl.write(
                json.dumps(
                    {"tag": tag, "value": float(value), "step": int(step), "ts": time.time()}
                )
                + "\n"
            )

    def add_scalars(self, prefix: str, values: Dict[str, float], step: int) -> None:
        for k, v in values.items():
            self.add_scalar(f"{prefix}/{k}", v, step)

    def flush(self) -> None:
        if self._tb is not None:
            self._tb.flush()
        else:
            self._jsonl.flush()

    def close(self) -> None:
        if self._tb is not None:
            self._tb.close()
        else:
            self._jsonl.close()
