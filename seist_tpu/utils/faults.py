"""Fault-injection harness for robustness testing.

Long unattended TPU runs die in exactly three ways — a preemption signal,
a hard kill (spot VM reclaim, OOM-killer, `tools/tpu_outage_r4.log`), or
numerically (a NaN loss poisoning the params) — and none of them can be
unit-tested without a way to *cause* them on demand. This module is that
way: a tiny, env/flag-driven injector the train worker consults at every
step boundary, so the kill/resume, preempt, and bad-update-guard paths in
``train/worker.py`` are exercised end-to-end by real faults rather than
mocks (tests/test_fault_tolerance_e2e.py).

Knobs (all opt-in; absent means "never fire"). Steps are GLOBAL batch
indices (``epoch * steps_per_epoch + step``), matching the checkpoint
step numbering, so "kill at step k" and "resume loses at most
``save_interval_steps`` of work" talk about the same counter::

    SEIST_FAULT_NAN_STEP      corrupt the input batch to NaN at this step
    SEIST_FAULT_NAN_COUNT     ...and the following COUNT-1 steps (default 1)
    SEIST_FAULT_KILL_STEP     SIGKILL the process at this step (hard crash:
                              no handlers run, simulates VM reclaim)
    SEIST_FAULT_SIGTERM_STEP  SIGTERM self at this step (graceful preempt)
    SEIST_FAULT_SLOW_MS       sleep this long at each step start
    SEIST_FAULT_SLOW_STEP     ...restricted to this one step (default: all)
    SEIST_FAULT_STAMP         path of a stamp file recording which faults
                              already fired — each fault fires AT MOST ONCE
                              across process restarts. Without it, a
                              relaunched run replays the same global step
                              and dies in a crash loop, which is sometimes
                              exactly what a test wants (supervise retry
                              budget) and sometimes not (resume e2e).

I/O-plane knobs (consumed by the data plane via
``seist_tpu/data/io_guard.py``; sample indices are RAW dataset indices —
the post-split index space the Loader shuffles over)::

    SEIST_FAULT_IO_FLAKY_P      probability in [0, 1] that a sample read
                                raises a transient OSError; deterministic
                                per sample (hash of the index), so a flaky
                                sample is flaky on EVERY epoch/attempt
                                window — and always succeeds once the
                                retry budget outlasts IO_FLAKY_FAILS
    SEIST_FAULT_IO_FLAKY_FAILS  consecutive attempts that fail for a
                                flaky-selected read (default 1; keep it
                                below the retry budget for the
                                transient-faults-are-invisible contract)
    SEIST_FAULT_IO_CORRUPT      comma list of raw sample indices whose
                                decoded waveform turns non-finite
                                (permanent corruption -> quarantine)
    SEIST_FAULT_IO_STALL_BATCH  the Loader sleeps before producing this
                                batch index (stall-watchdog e2e)
    SEIST_FAULT_IO_STALL_SEC    stall duration in seconds (default 3600)

Serving-plane knobs (consumed by ``seist_tpu/serve/server.py``; request
numbers are 1-based per-process /predict ordinals, so "kill at request
k" is deterministic under any client concurrency)::

    SEIST_FAULT_SERVE_KILL_REQ        SIGKILL the replica when its k-th
                                      /predict request arrives (mid-load
                                      hard crash; the router must retry
                                      the in-flight failures elsewhere)
    SEIST_FAULT_SERVE_SLOW_MS         sleep this long inside the model
                                      forward for every flush (forces the
                                      504 deadline path; per-replica slow)
    SEIST_FAULT_SERVE_BLACKHOLE_AFTER accept requests after the k-th but
                                      never answer them (hold the socket
                                      open) — the failure mode health
                                      probes CANNOT see, which only a
                                      request-path circuit breaker
                                      catches
    SEIST_FAULT_SERVE_BLACKHOLE_COUNT ...for this many requests, then
                                      recover (default: forever). A
                                      finite count lets the breaker's
                                      half-open probes find the recovery
                                      and close the circuit.
    SEIST_FAULT_SERVE_BAD_CANDIDATE   model VERSION that is deliberately
                                      bad: /admin/reload to it fails its
                                      parity gate, and a replica serving
                                      it 500s every /predict — the knob
                                      that makes reload-rollback and
                                      canary auto-rollback exercisable
                                      in chaos runs
    SEIST_FAULT_SERVE_REPLICA         only fire in the replica whose
                                      SEIST_SERVE_REPLICA index (set by
                                      tools/supervise_fleet.py) matches;
                                      -1/absent = fire in any replica
    SEIST_FAULT_STAMP                 shared with the train plane: the
                                      serve kill fires at most once
                                      across replica relaunches

Streaming-plane knobs (consumed by ``serve/server.py``'s /stream path
and ``stream/journal.py``; packet fates are deterministic per
(station, seq) hash, so a dropped packet is dropped on every replay of
the same schedule — chaos runs are reproducible)::

    SEIST_FAULT_STREAM_DROP_P       probability a packet is silently
                                    swallowed server-side (the client
                                    sees success; the session sees a
                                    sequence gap on the next packet)
    SEIST_FAULT_STREAM_DUP_P        probability a packet is fed twice
                                    (the second feed is a duplicate seq
                                    — the mux must drop it idempotently)
    SEIST_FAULT_STREAM_REORDER_P    probability a packet is held and
                                    delivered after the station's NEXT
                                    packet. The stream plane does not
                                    reassemble: the late packet arrives
                                    as a stale seq and is dropped, so
                                    reorder degrades to gap+duplicate —
                                    the documented semantics, now
                                    exercised
    SEIST_FAULT_STREAM_KILL_PACKET  SIGKILL the replica when its k-th
                                    (1-based) /stream packet arrives —
                                    the mid-mainshock crash the journal
                                    + re-home + WAL machinery exists
                                    for; scoped by
                                    SEIST_FAULT_SERVE_REPLICA, stamped
                                    once via SEIST_FAULT_STAMP
    SEIST_FAULT_STREAM_JOURNAL_CORRUPT_P
                                    probability (per station, one
                                    verdict per station id) that every
                                    journal write for that station is
                                    truncated mid-blob — restore must
                                    detect the torn file and fall back
                                    to a fresh session (gap-stitch
                                    re-warm), never resurrect garbage

Batch-fleet knobs (consumed by ``seist_tpu/batch/fleet.py``'s guarded
lease store and by the fleet worker loop in ``tools/repick_archive.py``;
unit ordinals are 1-based per-process lease-acquisition counts, so
"kill at unit K" is deterministic under work-stealing)::

    SEIST_FAULT_BATCH_LEASE_LATENCY_MS  sleep this long before every
                                        lease-store operation (a slow
                                        coordination plane; exercises
                                        the op-timeout budget)
    SEIST_FAULT_BATCH_LEASE_ERROR_P     probability a lease-store op
                                        raises a transient OSError;
                                        deterministic per op ordinal,
                                        so the retry ladder sees the
                                        same fault schedule every run
    SEIST_FAULT_BATCH_PARTITION_AFTER_S start of a full lease-store
                                        partition window, in seconds
                                        after this worker's FIRST store
                                        op (every op raises; workers
                                        must finish held leases while
                                        locally valid, then park)
    SEIST_FAULT_BATCH_PARTITION_FOR_S   partition duration (default 0;
                                        the store heals afterwards and
                                        parked workers re-acquire)
    SEIST_FAULT_BATCH_KILL_UNIT         SIGKILL the worker when it
                                        acquires its k-th (1-based)
                                        lease — hard crash mid-unit;
                                        the lease expires and a peer
                                        reclaims at the next fence
    SEIST_FAULT_BATCH_PREEMPT_UNIT      SIGTERM self at the k-th lease
                                        acquisition — the graceful
                                        exit-75 preemption contract
                                        (drain segment, release lease,
                                        rejoin later)
    SEIST_FAULT_BATCH_WORKER            only fire in the worker whose
                                        SEIST_BATCH_WORKER index (set
                                        by tools/supervise_repick.py)
                                        matches; -1/absent = any worker
    SEIST_FAULT_STAMP                   shared stamp file: kill/preempt
                                        fire at most once across worker
                                        relaunches

The injector is deliberately dependency-free above numpy/jax tree utils:
it must be importable (and inert) in every entry point that might train.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from dataclasses import dataclass
from typing import Any, Mapping, Optional, Set

import numpy as np

from seist_tpu.utils.logger import logger


def _env_int(env: Mapping[str, str], key: str, default: int) -> int:
    raw = env.get(key, "")
    try:
        return int(raw) if raw else default
    except ValueError as e:
        raise ValueError(f"{key} must be an integer, got {raw!r}") from e


def _env_float(env: Mapping[str, str], key: str, default: float) -> float:
    raw = env.get(key, "")
    try:
        return float(raw) if raw else default
    except ValueError as e:
        raise ValueError(f"{key} must be a number, got {raw!r}") from e


@dataclass(frozen=True)
class FaultPlan:
    """Parsed fault schedule. ``-1`` step values mean "never"."""

    nan_step: int = -1
    nan_count: int = 1
    kill_step: int = -1
    sigterm_step: int = -1
    slow_ms: float = 0.0
    slow_step: int = -1
    stamp_path: str = ""

    @classmethod
    def from_env(cls, env: Optional[Mapping[str, str]] = None) -> "FaultPlan":
        env = os.environ if env is None else env
        return cls(
            nan_step=_env_int(env, "SEIST_FAULT_NAN_STEP", -1),
            nan_count=max(1, _env_int(env, "SEIST_FAULT_NAN_COUNT", 1)),
            kill_step=_env_int(env, "SEIST_FAULT_KILL_STEP", -1),
            sigterm_step=_env_int(env, "SEIST_FAULT_SIGTERM_STEP", -1),
            slow_ms=_env_float(env, "SEIST_FAULT_SLOW_MS", 0.0),
            slow_step=_env_int(env, "SEIST_FAULT_SLOW_STEP", -1),
            stamp_path=env.get("SEIST_FAULT_STAMP", ""),
        )

    @property
    def enabled(self) -> bool:
        return (
            self.nan_step >= 0
            or self.kill_step >= 0
            or self.sigterm_step >= 0
            or self.slow_ms > 0
        )


@dataclass(frozen=True)
class IoFaultPlan:
    """Parsed data-plane fault schedule (all inert by default)."""

    flaky_p: float = 0.0
    flaky_fails: int = 1
    corrupt: frozenset = frozenset()
    stall_batch: int = -1
    stall_sec: float = 3600.0

    @classmethod
    def from_env(cls, env: Optional[Mapping[str, str]] = None) -> "IoFaultPlan":
        env = os.environ if env is None else env
        raw_corrupt = env.get("SEIST_FAULT_IO_CORRUPT", "")
        try:
            corrupt = frozenset(
                int(tok) for tok in raw_corrupt.split(",") if tok.strip()
            )
        except ValueError as e:
            raise ValueError(
                "SEIST_FAULT_IO_CORRUPT must be a comma list of ints, got "
                f"{raw_corrupt!r}"
            ) from e
        return cls(
            flaky_p=_env_float(env, "SEIST_FAULT_IO_FLAKY_P", 0.0),
            flaky_fails=max(1, _env_int(env, "SEIST_FAULT_IO_FLAKY_FAILS", 1)),
            corrupt=corrupt,
            stall_batch=_env_int(env, "SEIST_FAULT_IO_STALL_BATCH", -1),
            stall_sec=_env_float(env, "SEIST_FAULT_IO_STALL_SEC", 3600.0),
        )

    @property
    def enabled(self) -> bool:
        return (
            self.flaky_p > 0 or bool(self.corrupt) or self.stall_batch >= 0
        )


class IoFaultInjector:
    """Data-plane fault driver, consulted by the guarded read path
    (``io_guard.read_with_retry``) and the Loader.

    Flakiness is a pure function of the sample index — NOT of wall clock
    or call order — so a run with injected transient faults consumes the
    exact same byte stream as a clean run once retries succeed (the
    bit-identical-params chaos contract), regardless of worker-thread
    scheduling."""

    def __init__(self, plan: Optional[IoFaultPlan] = None):
        self.plan = plan or IoFaultPlan()
        self._stalled = False

    @classmethod
    def from_env(cls, env: Optional[Mapping[str, str]] = None) -> "IoFaultInjector":
        return cls(IoFaultPlan.from_env(env))

    @property
    def enabled(self) -> bool:
        return self.plan.enabled

    def _is_flaky(self, key: int) -> bool:
        p = self.plan.flaky_p
        if p <= 0:
            return False
        u = np.random.default_rng(
            np.random.SeedSequence([0x10FA_17, int(key)])
        ).random()
        return bool(u < p)

    def maybe_flaky_read(self, key: int, attempt: int) -> None:
        """Raise a transient OSError when sample ``key`` is flaky-selected
        and ``attempt`` (0-based) is still within the injected failure
        run. The retry loop calls this before every real read attempt."""
        if attempt < self.plan.flaky_fails and self._is_flaky(key):
            raise OSError(
                f"[faults] injected flaky read (sample {key}, "
                f"attempt {attempt})"
            )

    def is_corrupt(self, key: int) -> bool:
        return int(key) in self.plan.corrupt

    def maybe_stall(self, batch_index: int) -> None:
        """Sleep (once) before producing batch ``stall_batch`` — simulates
        a wedged loader for the pipeline stall watchdog."""
        if self.plan.stall_batch < 0 or self._stalled:
            return
        if batch_index >= self.plan.stall_batch:
            self._stalled = True
            logger.warning(
                f"[faults] loader stall injected at batch {batch_index} "
                f"({self.plan.stall_sec}s)"
            )
            time.sleep(self.plan.stall_sec)


class _Stamps:
    """Fired-fault bookkeeping, optionally persisted to a stamp file so a
    fault fires at most once across process relaunches. The stamp is read
    at construction and appended to just before the fault fires, fsynced
    — even a SIGKILL cannot outrun it."""

    def __init__(self, path: str = ""):
        self.path = path
        self._fired: Set[str] = set()
        if path and os.path.exists(path):
            with open(path) as f:
                self._fired = {line.strip() for line in f if line.strip()}

    def armed(self, name: str) -> bool:
        return name not in self._fired

    def mark(self, name: str) -> None:
        self._fired.add(name)
        if self.path:
            with open(self.path, "a") as f:
                f.write(name + "\n")
                f.flush()
                os.fsync(f.fileno())


class FaultInjector:
    """Step-boundary fault driver. ``on_step`` fires process-level faults
    (kill / sigterm / slow); ``corrupt_inputs`` handles the numeric one.

    Each named fault fires once per process; with a stamp file, once per
    *run* (surviving relaunches — see :class:`_Stamps`)."""

    def __init__(self, plan: Optional[FaultPlan] = None):
        self.plan = plan or FaultPlan()
        self._stamps = _Stamps(self.plan.stamp_path)

    @classmethod
    def from_env(cls, env: Optional[Mapping[str, str]] = None) -> "FaultInjector":
        return cls(FaultPlan.from_env(env))

    @property
    def enabled(self) -> bool:
        return self.plan.enabled

    # ------------------------------------------------------------- internals
    def _armed(self, name: str) -> bool:
        return self._stamps.armed(name)

    def _mark(self, name: str) -> None:
        """Record a firing BEFORE acting on it: SIGKILL never returns, so
        the stamp write must precede the kill or relaunches loop forever."""
        self._stamps.mark(name)

    # ------------------------------------------------------------- step hook
    def on_step(self, step: int, n_steps: int = 1) -> None:
        """Fire any process-level fault scheduled inside the global-step
        window ``[step, step + n_steps)``. Call at the START of the step
        (or packed call — the packed train paths only visit kpack
        boundaries, so a fault scheduled mid-call must still fire),
        before dispatching compute."""
        p = self.plan

        def hit(target: int) -> bool:
            return step <= target < step + n_steps

        if p.slow_ms > 0 and (p.slow_step < 0 or hit(p.slow_step)):
            time.sleep(p.slow_ms / 1000.0)
        if p.sigterm_step >= 0 and hit(p.sigterm_step) and self._armed("sigterm"):
            self._mark("sigterm")
            logger.warning(f"[faults] SIGTERM self at step {p.sigterm_step}")
            os.kill(os.getpid(), signal.SIGTERM)
        if p.kill_step >= 0 and hit(p.kill_step) and self._armed("kill"):
            self._mark("kill")
            logger.warning(f"[faults] SIGKILL self at step {p.kill_step}")
            os.kill(os.getpid(), signal.SIGKILL)

    # ------------------------------------------------------- numeric faults
    def nan_active(self, step: int) -> bool:
        p = self.plan
        return (
            p.nan_step >= 0
            and p.nan_step <= step < p.nan_step + p.nan_count
            and self._armed(f"nan@{step}")
        )

    def corrupt_inputs(self, step: int, inputs: Any, n_steps: int = 1) -> Any:
        """Return ``inputs`` with every array turned to NaN when any of the
        global steps ``[step, step + n_steps)`` falls in the NaN window
        (``n_steps > 1`` covers the packed train paths, where one call
        consumes several batches). The corruption flows through forward +
        backward, so the non-finite loss/gradient the bad-update guard
        must catch arises exactly the way a real numeric blow-up does."""
        hits = [s for s in range(step, step + n_steps) if self.nan_active(s)]
        if not hits:
            return inputs
        for s in hits:
            self._mark(f"nan@{s}")
        logger.warning(f"[faults] NaN batch injected at step(s) {hits}")
        import jax

        return jax.tree.map(lambda x: x * np.float32("nan"), inputs)


# --------------------------------------------------------------- serve plane
@dataclass(frozen=True)
class ServeFaultPlan:
    """Parsed serving-plane fault schedule (inert by default). Request
    numbers are 1-based per-process /predict ordinals."""

    kill_req: int = -1
    slow_ms: float = 0.0
    blackhole_after: int = -1
    blackhole_count: int = 1 << 30  # default: never recovers
    blackhole_hold_s: float = 3600.0
    bad_candidate_version: int = -1  # model version that serves "wrong"
    replica: int = -1  # only fire in this SEIST_SERVE_REPLICA; -1 = any
    stamp_path: str = ""

    @classmethod
    def from_env(
        cls, env: Optional[Mapping[str, str]] = None
    ) -> "ServeFaultPlan":
        env = os.environ if env is None else env
        return cls(
            kill_req=_env_int(env, "SEIST_FAULT_SERVE_KILL_REQ", -1),
            slow_ms=_env_float(env, "SEIST_FAULT_SERVE_SLOW_MS", 0.0),
            blackhole_after=_env_int(
                env, "SEIST_FAULT_SERVE_BLACKHOLE_AFTER", -1
            ),
            blackhole_count=max(
                1, _env_int(env, "SEIST_FAULT_SERVE_BLACKHOLE_COUNT", 1 << 30)
            ),
            blackhole_hold_s=_env_float(
                env, "SEIST_FAULT_SERVE_BLACKHOLE_HOLD_S", 3600.0
            ),
            bad_candidate_version=_env_int(
                env, "SEIST_FAULT_SERVE_BAD_CANDIDATE", -1
            ),
            replica=_env_int(env, "SEIST_FAULT_SERVE_REPLICA", -1),
            stamp_path=env.get("SEIST_FAULT_STAMP", ""),
        )

    @property
    def enabled(self) -> bool:
        return (
            self.kill_req >= 0
            or self.slow_ms > 0
            or self.blackhole_after >= 0
            or self.bad_candidate_version >= 0
        )


class ServeFaultInjector:
    """Serving-plane fault driver, consulted by ``ServeService``.

    ``on_request(n)`` runs at request arrival (kill / black-hole);
    ``forward_delay()`` runs inside the batcher's forward closure (slow
    model — the flush thread sleeps, so queued requests age exactly as
    they would behind a genuinely slow accelerator). Faults can be scoped
    to one replica of a fleet: tools/supervise_fleet.py exports
    ``SEIST_SERVE_REPLICA=<index>`` per replica, and a plan with
    ``replica >= 0`` only fires where the two match."""

    def __init__(
        self,
        plan: Optional[ServeFaultPlan] = None,
        replica_index: Optional[int] = None,
    ):
        self.plan = plan or ServeFaultPlan()
        if replica_index is None:
            replica_index = _env_int(os.environ, "SEIST_SERVE_REPLICA", -1)
        self.replica_index = replica_index
        self._stamps = _Stamps(self.plan.stamp_path)
        self._lock = threading.Lock()
        self._blackholed = 0

    @classmethod
    def from_env(
        cls, env: Optional[Mapping[str, str]] = None
    ) -> "ServeFaultInjector":
        return cls(ServeFaultPlan.from_env(env))

    @property
    def enabled(self) -> bool:
        """True when any fault is scheduled AND targets this replica."""
        if not self.plan.enabled:
            return False
        return self.plan.replica < 0 or self.plan.replica == self.replica_index

    # ---------------------------------------------------------- request hook
    def on_request(self, n: int) -> None:
        """Fire request-arrival faults for the ``n``-th (1-based) /predict
        request. Kill is >= (not ==) so concurrent arrivals can't skip
        past the trigger; the stamp makes it fire once across relaunches."""
        if not self.enabled:
            return
        p = self.plan
        if p.kill_req >= 0 and n >= p.kill_req and self._stamps.armed(
            "serve_kill"
        ):
            self._stamps.mark("serve_kill")
            logger.warning(f"[faults] serve SIGKILL at request {n}")
            os.kill(os.getpid(), signal.SIGKILL)
        if p.blackhole_after >= 0 and n > p.blackhole_after:
            with self._lock:
                fire = self._blackholed < p.blackhole_count
                if fire:
                    self._blackholed += 1
                    n_holed = self._blackholed
            if fire:
                logger.warning(
                    f"[faults] serve black-hole: request {n} accepted, "
                    f"never answered ({n_holed}/{p.blackhole_count})"
                )
                # Hold the handler thread (and the client's socket) open:
                # the request is accepted but no bytes ever come back —
                # exactly what a wedged replica looks like from outside.
                time.sleep(p.blackhole_hold_s)

    # ---------------------------------------------------------- forward hook
    def forward_delay(self) -> None:
        """Sleep inside the model forward (batcher flush thread)."""
        if self.enabled and self.plan.slow_ms > 0:
            time.sleep(self.plan.slow_ms / 1000.0)

    # ------------------------------------------------------- rollout faults
    def is_bad_candidate(self, version: int) -> bool:
        """SEIST_FAULT_SERVE_BAD_CANDIDATE=<version>: that model version
        is deliberately "bad" — (a) a /admin/reload TO it fails its
        parity gate (the replica-local rollback path), and (b) a replica
        SERVING it errors every /predict (the elevated-error-rate signal
        the router's canary auto-rollback drains on). Scoped by
        SEIST_FAULT_SERVE_REPLICA like every serve fault."""
        return (
            self.enabled
            and self.plan.bad_candidate_version >= 0
            and int(version) == self.plan.bad_candidate_version
        )


# -------------------------------------------------------------- stream plane
@dataclass(frozen=True)
class StreamFaultPlan:
    """Parsed streaming-plane fault schedule (inert by default). Packet
    ordinals are 1-based per-process /stream counts; per-packet fates
    hash (station_id, seq) so a schedule replays identically."""

    drop_p: float = 0.0
    dup_p: float = 0.0
    reorder_p: float = 0.0
    kill_packet: int = -1
    journal_corrupt_p: float = 0.0
    replica: int = -1  # only fire in this SEIST_SERVE_REPLICA; -1 = any
    stamp_path: str = ""

    @classmethod
    def from_env(
        cls, env: Optional[Mapping[str, str]] = None
    ) -> "StreamFaultPlan":
        env = os.environ if env is None else env
        return cls(
            drop_p=_env_float(env, "SEIST_FAULT_STREAM_DROP_P", 0.0),
            dup_p=_env_float(env, "SEIST_FAULT_STREAM_DUP_P", 0.0),
            reorder_p=_env_float(env, "SEIST_FAULT_STREAM_REORDER_P", 0.0),
            kill_packet=_env_int(
                env, "SEIST_FAULT_STREAM_KILL_PACKET", -1
            ),
            journal_corrupt_p=_env_float(
                env, "SEIST_FAULT_STREAM_JOURNAL_CORRUPT_P", 0.0
            ),
            replica=_env_int(env, "SEIST_FAULT_SERVE_REPLICA", -1),
            stamp_path=env.get("SEIST_FAULT_STAMP", ""),
        )

    @property
    def enabled(self) -> bool:
        return (
            self.drop_p > 0
            or self.dup_p > 0
            or self.reorder_p > 0
            or self.kill_packet >= 0
            or self.journal_corrupt_p > 0
        )


class StreamFaultInjector:
    """Streaming-plane fault driver.

    ``ServeService.stream`` consults :meth:`on_packet` (kill) and
    :meth:`packet_fate` (drop / dup / reorder) per arriving packet;
    ``stream/journal.py`` consults :meth:`corrupt_journal` per journal
    write. Fates are deterministic: ``packet_fate`` hashes
    (station_id, seq) and ``corrupt_journal`` hashes the station id, so
    the same scenario schedule produces the same faults on every run —
    the chaos lane's gates can be exact, not statistical. Replica
    scoping rides SEIST_FAULT_SERVE_REPLICA exactly like the serve
    plane."""

    def __init__(
        self,
        plan: Optional[StreamFaultPlan] = None,
        replica_index: Optional[int] = None,
    ):
        self.plan = plan or StreamFaultPlan()
        if replica_index is None:
            replica_index = _env_int(os.environ, "SEIST_SERVE_REPLICA", -1)
        self.replica_index = replica_index
        self._stamps = _Stamps(self.plan.stamp_path)

    @classmethod
    def from_env(
        cls, env: Optional[Mapping[str, str]] = None
    ) -> "StreamFaultInjector":
        return cls(StreamFaultPlan.from_env(env))

    @property
    def enabled(self) -> bool:
        """True when any fault is scheduled AND targets this replica."""
        if not self.plan.enabled:
            return False
        return self.plan.replica < 0 or self.plan.replica == self.replica_index

    @staticmethod
    def _uniform(*key: int) -> float:
        return float(
            np.random.default_rng(
                np.random.SeedSequence([0x57F4_17, *[int(k) for k in key]])
            ).random()
        )

    @staticmethod
    def _station_key(station_id: str) -> int:
        import hashlib

        digest = hashlib.sha1(str(station_id).encode()).digest()
        return int.from_bytes(digest[:8], "big")

    # --------------------------------------------------------- packet hooks
    def on_packet(self, n: int) -> None:
        """Fire packet-arrival faults for the ``n``-th (1-based) /stream
        packet. Kill is >= (not ==) so concurrent arrivals can't skip
        past the trigger; the stamp makes it fire once across
        relaunches."""
        if not self.enabled:
            return
        p = self.plan
        if p.kill_packet >= 0 and n >= p.kill_packet and self._stamps.armed(
            "stream_kill"
        ):
            self._stamps.mark("stream_kill")
            logger.warning(f"[faults] stream SIGKILL at packet {n}")
            os.kill(os.getpid(), signal.SIGKILL)

    def packet_fate(self, station_id: str, seq: Optional[int]) -> str:
        """-> 'ok' | 'drop' | 'dup' | 'reorder' for this packet.

        One uniform draw per (station, seq) checked against the three
        rates in fixed order, so fates are mutually exclusive and each
        fires at ~its configured rate. Packets without a seq are never
        faulted (there is no duplicate/gap semantics to exercise)."""
        if not self.enabled or seq is None:
            return "ok"
        p = self.plan
        if p.drop_p <= 0 and p.dup_p <= 0 and p.reorder_p <= 0:
            return "ok"
        u = self._uniform(self._station_key(station_id), int(seq))
        if u < p.drop_p:
            return "drop"
        if u < p.drop_p + p.dup_p:
            return "dup"
        if u < p.drop_p + p.dup_p + p.reorder_p:
            return "reorder"
        return "ok"

    # -------------------------------------------------------- journal hook
    def corrupt_journal(self, station_id: str) -> bool:
        """One verdict per station (hash of its id): EVERY journal write
        for a corrupt-selected station is truncated, so its failover
        restore reliably exercises the torn-file -> fresh-session
        path."""
        if not self.enabled or self.plan.journal_corrupt_p <= 0:
            return False
        u = self._uniform(self._station_key(station_id), 0x0C0_44)
        return u < self.plan.journal_corrupt_p


# --------------------------------------------------------------- batch fleet
@dataclass(frozen=True)
class BatchFaultPlan:
    """Parsed batch-fleet fault schedule (inert by default). Unit
    ordinals are 1-based per-process lease-acquisition counts; partition
    windows are seconds after this worker's first lease-store op."""

    lease_latency_ms: float = 0.0
    lease_error_p: float = 0.0
    partition_after_s: float = -1.0
    partition_for_s: float = 0.0
    kill_unit: int = -1
    preempt_unit: int = -1
    worker: int = -1  # only fire in this SEIST_BATCH_WORKER; -1 = any
    stamp_path: str = ""

    @classmethod
    def from_env(
        cls, env: Optional[Mapping[str, str]] = None
    ) -> "BatchFaultPlan":
        env = os.environ if env is None else env
        return cls(
            lease_latency_ms=_env_float(
                env, "SEIST_FAULT_BATCH_LEASE_LATENCY_MS", 0.0
            ),
            lease_error_p=_env_float(
                env, "SEIST_FAULT_BATCH_LEASE_ERROR_P", 0.0
            ),
            partition_after_s=_env_float(
                env, "SEIST_FAULT_BATCH_PARTITION_AFTER_S", -1.0
            ),
            partition_for_s=_env_float(
                env, "SEIST_FAULT_BATCH_PARTITION_FOR_S", 0.0
            ),
            kill_unit=_env_int(env, "SEIST_FAULT_BATCH_KILL_UNIT", -1),
            preempt_unit=_env_int(env, "SEIST_FAULT_BATCH_PREEMPT_UNIT", -1),
            worker=_env_int(env, "SEIST_FAULT_BATCH_WORKER", -1),
            stamp_path=env.get("SEIST_FAULT_STAMP", ""),
        )

    @property
    def enabled(self) -> bool:
        return (
            self.lease_latency_ms > 0
            or self.lease_error_p > 0
            or self.partition_after_s >= 0
            or self.kill_unit >= 0
            or self.preempt_unit >= 0
        )


class BatchFaultInjector:
    """Batch-fleet fault driver.

    The guarded lease store calls :meth:`store_op` before every raw
    store attempt (latency / transient error / partition window); the
    fleet worker calls :meth:`on_unit` after each lease acquisition
    (SIGKILL / exit-75 preempt via SIGTERM). The partition clock is
    anchored at this worker's FIRST store op — not process start — so
    the window lands on lease traffic regardless of how long model
    warm-up took. Transient errors are deterministic per store-op
    ordinal, so a retry ladder sees the same fault schedule every run.
    Worker scoping rides ``SEIST_BATCH_WORKER`` (exported per worker by
    tools/supervise_repick.py) exactly like the serve plane's replica
    scoping."""

    def __init__(
        self,
        plan: Optional[BatchFaultPlan] = None,
        worker_index: Optional[int] = None,
    ):
        self.plan = plan or BatchFaultPlan()
        if worker_index is None:
            worker_index = _env_int(os.environ, "SEIST_BATCH_WORKER", -1)
        self.worker_index = worker_index
        self._stamps = _Stamps(self.plan.stamp_path)
        self._lock = threading.Lock()
        self._t0: Optional[float] = None  # monotonic anchor, first store op
        self._op_ordinal = 0

    @classmethod
    def from_env(
        cls, env: Optional[Mapping[str, str]] = None
    ) -> "BatchFaultInjector":
        return cls(BatchFaultPlan.from_env(env))

    @property
    def enabled(self) -> bool:
        """True when any fault is scheduled AND targets this worker."""
        if not self.plan.enabled:
            return False
        return self.plan.worker < 0 or self.plan.worker == self.worker_index

    # ------------------------------------------------------------- store hook
    def store_op(self, op: str) -> None:
        """Fire lease-store faults for one raw attempt: latency sleep,
        then the partition window (every op inside it raises), then the
        per-ordinal transient error draw. Called by the guarded store
        BEFORE the real operation, so an injected failure costs no real
        I/O."""
        if not self.enabled:
            return
        p = self.plan
        with self._lock:
            if self._t0 is None:
                self._t0 = time.monotonic()
            t = time.monotonic() - self._t0
            self._op_ordinal += 1
            ordinal = self._op_ordinal
        if p.lease_latency_ms > 0:
            time.sleep(p.lease_latency_ms / 1000.0)
        if (
            p.partition_after_s >= 0
            and p.partition_after_s <= t < p.partition_after_s + p.partition_for_s
        ):
            raise OSError(
                f"[faults] injected lease-store partition ({op} at "
                f"t={t:.2f}s, window [{p.partition_after_s:.1f}, "
                f"{p.partition_after_s + p.partition_for_s:.1f})s)"
            )
        if p.lease_error_p > 0:
            u = np.random.default_rng(
                np.random.SeedSequence([0xBA7C_17, int(ordinal)])
            ).random()
            if u < p.lease_error_p:
                raise OSError(
                    f"[faults] injected transient lease-store error "
                    f"({op}, op #{ordinal})"
                )

    # -------------------------------------------------------------- unit hook
    def on_unit(self, ordinal: int) -> None:
        """Fire process-level faults when the ``ordinal``-th (1-based)
        lease is acquired. ``>=`` (not ``==``) so work-stealing can't
        skip past the trigger; the stamp makes each fire once across
        worker relaunches (mark-before-kill: SIGKILL never returns)."""
        if not self.enabled:
            return
        p = self.plan
        if (
            p.preempt_unit >= 0
            and ordinal >= p.preempt_unit
            and self._stamps.armed("batch_preempt")
        ):
            self._stamps.mark("batch_preempt")
            logger.warning(
                f"[faults] batch SIGTERM (preempt) at unit #{ordinal}"
            )
            os.kill(os.getpid(), signal.SIGTERM)
        if (
            p.kill_unit >= 0
            and ordinal >= p.kill_unit
            and self._stamps.armed("batch_kill")
        ):
            self._stamps.mark("batch_kill")
            logger.warning(f"[faults] batch SIGKILL at unit #{ordinal}")
            os.kill(os.getpid(), signal.SIGKILL)


_BATCH_FAULTS: Optional[BatchFaultInjector] = None


def batch_faults() -> BatchFaultInjector:
    """Process-wide batch injector, parsed from env once. The guarded
    lease store and the fleet worker share the same instance, so the
    partition clock and the kill stamp are consistent across both."""
    global _BATCH_FAULTS
    if _BATCH_FAULTS is None:
        _BATCH_FAULTS = BatchFaultInjector.from_env()
    return _BATCH_FAULTS


_STREAM_FAULTS: Optional[StreamFaultInjector] = None


def stream_faults() -> StreamFaultInjector:
    """Process-wide stream injector, parsed from env once. journal.py
    consults this (it has no handle on the server's injector); the
    server uses the same instance so the kill stamp is shared."""
    global _STREAM_FAULTS
    if _STREAM_FAULTS is None:
        _STREAM_FAULTS = StreamFaultInjector.from_env()
    return _STREAM_FAULTS
