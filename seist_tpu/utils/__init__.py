"""Shared utilities. Re-exports resolve lazily (PEP 562): ``misc``
imports jax at module level, and an eager pull here would drag jax into
the jax-free serving front tier (serve/router.py imports
utils.logger, which executes this package __init__)."""

_LAZY = {
    "logger": ("seist_tpu.utils.logger", "logger"),
    "AverageMeter": ("seist_tpu.utils.meters", "AverageMeter"),
    "ProgressMeter": ("seist_tpu.utils.meters", "ProgressMeter"),
    "misc": ("seist_tpu.utils.misc", None),
}


def __getattr__(name: str):
    try:
        module_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module 'seist_tpu.utils' has no attribute {name!r}"
        ) from None
    import importlib

    module = importlib.import_module(module_name)
    obj = module if attr is None else getattr(module, attr)
    globals()[name] = obj
    return obj


def __dir__():
    return sorted(list(globals()) + list(_LAZY))
