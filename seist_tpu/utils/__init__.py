from seist_tpu.utils.logger import logger  # noqa: F401
from seist_tpu.utils.meters import AverageMeter, ProgressMeter  # noqa: F401
from seist_tpu.utils import misc  # noqa: F401
