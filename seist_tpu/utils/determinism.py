"""Determinism-contract markers (see docs/STATIC_ANALYSIS.md
"Determinism analysis").

This module must stay dependency-free: it is imported by det-critical
data/stream modules that the jax-free serving front tier also reaches.
"""

from __future__ import annotations

from typing import Callable, TypeVar

_F = TypeVar("_F", bound=Callable)

__all__ = ["telemetry_only"]


def telemetry_only(fn: _F) -> _F:
    """Mark a function in a det-critical module as telemetry-only.

    The marked function may read wall-clock (``time.time()``,
    ``datetime.now()``) without tripping detlint's
    ``wallclock-in-deterministic-path`` rule. The decoration is a
    CONTRACT, not a mechanism: the author asserts the value never
    reaches shard bytes, catalog rows, journal state, or IDs — only
    logs, meters, and progress reporting. detlint recognizes the
    decorator by name, so the assertion is reviewable at the def site
    instead of buried in a suppression comment per call.
    """
    fn.__telemetry_only__ = True
    return fn
