"""Process-global multi-file logger.

TPU-native counterpart of the reference's ``utils/logger.py:5-89`` singleton:
per-phase log files (``global.log`` / ``train.log`` / ``test.log``) plus
console output, with attribute proxying so ``logger.info(...)`` works
module-level. In multi-host runs only process 0 logs to console by default.
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Dict, Optional


class _Logger:
    _FMT = "%(asctime)s | %(levelname)s | %(message)s"

    def __init__(self):
        self._logdir: Optional[str] = None
        self._loggers: Dict[str, logging.Logger] = {}
        self._active: str = "global"
        self._console_enabled = True
        self._ensure("global")

    def _ensure(self, name: str) -> logging.Logger:
        if name in self._loggers:
            return self._loggers[name]
        lg = logging.getLogger(f"seist_tpu.{name}")
        lg.setLevel(logging.INFO)
        lg.propagate = False
        # logging.getLogger returns process-cached instances — drop any
        # handlers from a previous configuration so set_logdir /
        # enable_console rebuilds never duplicate output.
        for h in list(lg.handlers):
            lg.removeHandler(h)
            h.close()
        if self._console_enabled:
            h = logging.StreamHandler(sys.stdout)
            h.setFormatter(logging.Formatter(self._FMT))
            lg.addHandler(h)
        if self._logdir is not None:
            fh = logging.FileHandler(os.path.join(self._logdir, f"{name}.log"))
            fh.setFormatter(logging.Formatter(self._FMT))
            lg.addHandler(fh)
        self._loggers[name] = lg
        return lg

    def set_logdir(self, logdir: str) -> None:
        os.makedirs(logdir, exist_ok=True)
        self._logdir = logdir
        # Re-attach file handlers for existing loggers.
        names = list(self._loggers)
        self._loggers.clear()
        for n in names:
            self._ensure(n)

    def set_logger(self, name: str) -> None:
        self._active = name
        self._ensure(name)

    def logdir(self) -> str:
        """Active log dir (ref logger.py usage at validate.py:130); defaults
        to ./logs if never set."""
        if self._logdir is None:
            self.set_logdir(os.path.abspath("./logs"))
        return self._logdir

    def enable_console(self, enabled: bool) -> None:
        self._console_enabled = enabled
        names = list(self._loggers)
        self._loggers.clear()
        for n in names:
            self._ensure(n)

    def __getattr__(self, attr):
        # Proxy info/warning/error/... to the active logger (ref logger.py:73-84).
        return getattr(self._ensure(self._active), attr)


logger = _Logger()
