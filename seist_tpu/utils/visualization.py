"""Waveform / prediction plotting (ref utils/visualization.py:18-186).

Same two figures as the reference — a stacked waveform/pred/target panel and
the phase-picking figure (channels + probability curves with true-pick
vlines). matplotlib is imported lazily with the Agg backend so headless TPU
hosts never need a display.
"""

from __future__ import annotations

import datetime
import os
from typing import List, Optional, Sequence

import numpy as np


def _plt():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


def _timestamp() -> str:
    return datetime.datetime.now().strftime("%Y-%m-%d_%H-%M-%S")


def vis_waves_preds_targets(
    waveforms: np.ndarray,
    preds: np.ndarray,
    targets: np.ndarray,
    sampling_rate: Optional[int] = None,
    save_dir: str = "./",
    format: str = "png",
) -> str:
    """Stacked rows: each waveform channel, each pred curve, each target
    curve (ref visualization.py:18-101). Returns the saved path."""
    plt = _plt()
    waveforms, preds, targets = (
        np.asarray(waveforms),
        np.asarray(preds),
        np.asarray(targets),
    )
    groups = [("Channel", waveforms), ("Pred", preds), ("Target", targets)]
    num_row = sum(g.shape[0] for _, g in groups)
    fig, axes = plt.subplots(num_row, 1, figsize=(8, 1.2 * num_row), squeeze=False)
    row = 0
    for label, group in groups:
        for idx, curve in enumerate(group):
            ax = axes[row][0]
            x = (
                np.arange(len(curve)) / sampling_rate
                if sampling_rate
                else np.arange(len(curve))
            )
            ax.plot(x, curve, "-", color="k", linewidth=0.15, alpha=0.8)
            ax.text(
                0.001,
                0.95,
                f"{label}-{idx}",
                ha="left",
                va="top",
                transform=ax.transAxes,
                fontsize="small",
            )
            ax.set_ylim(-1, 1)
            ax.set_yticks([])
            row += 1
    os.makedirs(save_dir, exist_ok=True)
    path = os.path.join(save_dir, f"{_timestamp()}.{format}")
    fig.savefig(path, dpi=300)
    plt.close(fig)
    return path


def vis_phase_picking(
    waveforms: np.ndarray,
    waveforms_labels: Sequence[str],
    preds: np.ndarray,
    true_phase_idxs: Sequence[float],
    true_phase_labels: Sequence[str],
    pred_phase_labels: Sequence[str],
    sampling_rate: Optional[int] = None,
    save_name: str = "",
    save_dir: str = "./",
    formats: Sequence[str] = ("png",),
) -> List[str]:
    """Channels with true P/S vlines + a probability-curve row
    (ref visualization.py:104-186). Returns the saved paths."""
    plt = _plt()
    waveforms = np.asarray(waveforms)
    preds = np.asarray(preds)
    x = (
        np.arange(waveforms.shape[-1]) / sampling_rate
        if sampling_rate
        else np.arange(waveforms.shape[-1])
    )
    num_row = waveforms.shape[0] + 1
    lo, hi = float(np.min(waveforms)), float(np.max(waveforms))
    fig, axes = plt.subplots(
        num_row, 1, figsize=(10 / 2.54, 10 / 2.54), squeeze=False
    )
    for idx, wave in enumerate(waveforms):
        ax = axes[idx][0]
        ax.plot(x, wave, "-", color="k", linewidth=1, alpha=0.8,
                label=waveforms_labels[idx])
        if idx == 0 and len(true_phase_idxs):
            colors = ["C1", "C5"]
            for i, (pidx, plabel) in enumerate(
                zip(true_phase_idxs, true_phase_labels)
            ):
                # pick indices arrive in samples; the x axis is seconds
                # whenever sampling_rate is given
                ax.vlines(
                    x=[pidx / sampling_rate if sampling_rate else pidx],
                    ymin=lo * 1.1,
                    ymax=hi * 1.1,
                    colors=[colors[i % 2]],
                    linestyles="solid",
                    label=plabel,
                )
        ax.set_ylim(lo * 1.2, hi * 1.2)
        ax.set_ylabel("Amplitude")
        ax.set_yticks([])
        ax.set_xticks([])
        ax.legend(loc="upper right", fontsize=8)
    ax = axes[-1][0]
    styles = ["-.C0", "--C1", "--C5"]
    for i, label in enumerate(pred_phase_labels):
        ax.plot(x, preds[i], styles[i % 3], linewidth=1, alpha=0.8, label=label)
    ax.set_ylabel("Probability")
    ax.set_xlabel("Time (s)" if sampling_rate else "Samples")
    ax.legend(loc="upper right", fontsize=8)
    fig.tight_layout()

    os.makedirs(save_dir, exist_ok=True)
    if isinstance(formats, str):
        formats = [formats]
    paths = []
    stem = os.path.join(save_dir, _timestamp() + save_name)
    for fmt in formats:
        p = f"{stem}.{fmt}"
        fig.savefig(p, dpi=400)
        paths.append(p)
    plt.close(fig)
    return paths
