"""Running-average meters, latency histograms and progress strings
(ref: utils/meters.py:4-45; the histogram backs serve's /metrics)."""

from __future__ import annotations

import bisect
import threading
from typing import Dict, Iterable, List, Sequence


class AverageMeter:
    """Tracks current value, running average, sum and count."""

    def __init__(self, name: str, fmt: str = ":f"):
        self.name = name
        self.fmt = fmt
        self.reset()

    def reset(self) -> None:
        self.val = 0.0
        self.avg = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val: float, n: int = 1) -> None:
        self.val = float(val)
        self.sum += float(val) * n
        self.count += n
        self.avg = self.sum / max(self.count, 1)

    def __str__(self) -> str:
        fmtstr = "{name} {val" + self.fmt + "} ({avg" + self.fmt + "})"
        return fmtstr.format(**self.__dict__)


#: Default latency buckets (ms): roughly log-spaced from sub-ms dispatch to
#: multi-second compiles, the range an online inference service spans.
LATENCY_BOUNDS_MS = (
    1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0,
    500.0, 1000.0, 2000.0, 5000.0, 10000.0,
)


class LatencyHistogram:
    """Fixed-bucket histogram with percentile estimates — O(1) observe,
    O(buckets) quantile, bounded memory regardless of traffic volume (the
    property an always-on /metrics endpoint needs; storing raw samples
    would grow without bound).

    Thread-safe: serve handler threads observe concurrently with /metrics
    reads. Percentiles are estimated by linear interpolation inside the
    owning bucket (upper-bounded by bucket width); exact values above the
    last bound are clamped to it.
    """

    def __init__(self, bounds: Sequence[float] = LATENCY_BOUNDS_MS):
        self._bounds = [float(b) for b in bounds]
        if self._bounds != sorted(self._bounds):
            raise ValueError(f"bounds must be sorted, got {bounds}")
        self._counts = [0] * (len(self._bounds) + 1)  # last = overflow
        self._sum = 0.0
        self._count = 0
        self._max = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        i = bisect.bisect_left(self._bounds, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1
            if value > self._max:
                self._max = value

    def percentile(self, q: float) -> float:
        """Estimate the ``q``-quantile (q in [0, 1])."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        with self._lock:
            counts = list(self._counts)
            total = self._count
            mx = self._max
        return self._percentile_from(q, counts, total, mx)

    def _percentile_from(
        self, q: float, counts: List[int], total: int, mx: float
    ) -> float:
        """Quantile over an already-taken snapshot (no locking)."""
        if total == 0:
            return 0.0
        rank = q * total
        seen = 0.0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if seen + c >= rank:
                lo = self._bounds[i - 1] if i > 0 else 0.0
                hi = self._bounds[i] if i < len(self._bounds) else mx
                frac = (rank - seen) / c
                est = lo + (hi - lo) * min(max(frac, 0.0), 1.0)
                # An estimate can't exceed the largest observed value.
                return min(est, mx)
            seen += c
        return mx

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def mean(self) -> float:
        with self._lock:
            return self._sum / self._count if self._count else 0.0

    def buckets(self) -> "tuple[List[float], List[int], int, float]":
        """Consistent snapshot ``(bounds, counts, count, sum)`` — counts
        has ``len(bounds) + 1`` entries (last = overflow). The raw-bucket
        view the obs bus renders as cumulative Prometheus ``_bucket``
        series (seist_tpu/obs/bus.py)."""
        with self._lock:
            return list(self._bounds), list(self._counts), self._count, self._sum

    def summary(self) -> Dict[str, float]:
        """{count, mean, p50, p90, p99, max} — the /metrics payload.
        Computed from ONE locked snapshot so the fields are mutually
        consistent even with concurrent observes (count and p99 over the
        same histogram state)."""
        with self._lock:
            counts = list(self._counts)
            total, sm, mx = self._count, self._sum, self._max
        return {
            "count": float(total),
            "mean": sm / total if total else 0.0,
            "p50": self._percentile_from(0.50, counts, total, mx),
            "p90": self._percentile_from(0.90, counts, total, mx),
            "p99": self._percentile_from(0.99, counts, total, mx),
            "max": mx,
        }


class ProgressMeter:
    """Formats a progress line over a set of meters."""

    def __init__(self, num_batches: int, meters: Iterable[AverageMeter], prefix: str = ""):
        self.batch_fmtstr = self._get_batch_fmtstr(num_batches)
        self.meters = list(meters)
        self.prefix = prefix

    def get_str(self, batch: int) -> str:
        entries = [self.prefix + self.batch_fmtstr.format(batch)]
        entries += [str(meter) for meter in self.meters]
        return "  ".join(entries)

    @staticmethod
    def _get_batch_fmtstr(num_batches: int) -> str:
        num_digits = len(str(num_batches // 1))
        fmt = "{:" + str(num_digits) + "d}"
        return "[" + fmt + "/" + fmt.format(num_batches) + "]"
