"""Running-average meters and progress strings (ref: utils/meters.py:4-45)."""

from __future__ import annotations

from typing import Iterable, List


class AverageMeter:
    """Tracks current value, running average, sum and count."""

    def __init__(self, name: str, fmt: str = ":f"):
        self.name = name
        self.fmt = fmt
        self.reset()

    def reset(self) -> None:
        self.val = 0.0
        self.avg = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val: float, n: int = 1) -> None:
        self.val = float(val)
        self.sum += float(val) * n
        self.count += n
        self.avg = self.sum / max(self.count, 1)

    def __str__(self) -> str:
        fmtstr = "{name} {val" + self.fmt + "} ({avg" + self.fmt + "})"
        return fmtstr.format(**self.__dict__)


class ProgressMeter:
    """Formats a progress line over a set of meters."""

    def __init__(self, num_batches: int, meters: Iterable[AverageMeter], prefix: str = ""):
        self.batch_fmtstr = self._get_batch_fmtstr(num_batches)
        self.meters = list(meters)
        self.prefix = prefix

    def get_str(self, batch: int) -> str:
        entries = [self.prefix + self.batch_fmtstr.format(batch)]
        entries += [str(meter) for meter in self.meters]
        return "  ".join(entries)

    @staticmethod
    def _get_batch_fmtstr(num_batches: int) -> str:
        num_digits = len(str(num_batches // 1))
        fmt = "{:" + str(num_digits) + "d}"
        return "[" + fmt + "/" + fmt.format(num_batches) + "]"
