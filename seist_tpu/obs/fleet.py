"""Fleet metrics aggregation: merge N replicas' bus snapshots (plus the
router's) into one pane — ``GET /fleet/metrics`` on the fleet supervisor.

PR 7 made serving a fleet; its observability stayed per-process: N
``/metrics`` endpoints nobody aggregates. The ROADMAP's autoscaling
control plane and the canary-rollback path both need ONE signal source
(fleet-wide queue delay, per-replica error deltas) — this module is that
single pane:

* :class:`FleetAggregator` — named *sources* (a replica base URL whose
  ``/metrics.json`` is scraped, or a callable returning a bus snapshot
  for the in-process router), scraped periodically on a background
  thread and on demand when a read finds the view stale.
* **Merging** — counters and gauges sum across live sources; histograms
  merge **bucket-wise** (summing per-bucket counts, then re-deriving
  percentiles from the merged distribution — averaging per-replica p99s
  would be statistically meaningless, which is why ``bus.snapshot()``
  ships raw ``bounds``/``bucket_counts``). The per-source breakdown is
  retained verbatim next to the aggregate.
* **Exposition** — ``merged()`` is the JSON view
  (``/fleet/metrics.json``); :meth:`render_prometheus` emits every
  sample with a ``replica`` label (``replica="fleet"`` for the
  aggregate, the source name for the breakdown) plus
  ``seist_fleet_source_up{source=...}`` liveness.

Stdlib + obs only — no jax: the aggregator runs in the (jax-free)
supervisor/router process. A failed scrape marks the source down and
excludes it from the aggregate (no ghost counters from a dead replica);
it rejoins on the next successful scrape.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from seist_tpu.obs.bus import _escape, _fmt, _sanitize, monotonic
from seist_tpu.utils.logger import logger
from seist_tpu.utils.meters import LatencyHistogram

Source = Union[str, Callable[[], Dict[str, Any]]]


def _split_key(key: str) -> Tuple[str, Dict[str, str]]:
    """``'serve_batcher_submitted{model=phasenet}'`` ->
    ``('serve_batcher_submitted', {'model': 'phasenet'})`` — the inverse
    of ``bus._label_suffix``."""
    if "{" not in key:
        return key, {}
    name, _, rest = key.partition("{")
    labels: Dict[str, str] = {}
    for part in rest.rstrip("}").split(","):
        if "=" in part:
            k, _, v = part.partition("=")
            labels[k] = v
    return name, labels


class FleetAggregator:
    """See module docstring. Thread-safe; scrapes never hold the data
    lock across network I/O (lockgraph-clean: results are swapped in
    under the lock only after every fetch returned)."""

    def __init__(self, interval_s: float = 5.0, timeout_s: float = 2.0):
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self._sources: Dict[str, Source] = {}
        self._lock = threading.Lock()
        self._results: Dict[str, Dict[str, Any]] = {}
        self._last_scrape = 0.0  # monotonic; 0 = never
        self._scrapes = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- sources
    def add_source(self, name: str, target: Source) -> None:
        """Register a source: a replica base URL (``host:port`` or
        ``http://host:port`` — ``/metrics.json`` is appended) or a
        callable returning a bus snapshot (the in-process router)."""
        with self._lock:
            self._sources[name] = target

    def remove_source(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)
            self._results.pop(name, None)

    # ------------------------------------------------------------- scraping
    def _fetch(self, target: Source) -> Dict[str, Any]:
        if callable(target):
            return target()
        hostport = str(target).split("://", 1)[-1].rstrip("/")
        conn = http.client.HTTPConnection(hostport, timeout=self.timeout_s)
        try:
            conn.request("GET", "/metrics.json")
            resp = conn.getresponse()
            payload = resp.read()
            if resp.status != 200:
                raise OSError(f"/metrics.json -> {resp.status}")
            snap = json.loads(payload.decode())
            if not isinstance(snap, dict):
                raise ValueError("snapshot is not a JSON object")
            return snap
        finally:
            conn.close()

    def scrape_once(self) -> None:
        """Pull every source once; store per-source result. No lock is
        held while fetching (network I/O), so concurrent scrapes are
        allowed and last-write-wins — the merge reads one consistent
        stored set either way."""
        with self._lock:
            sources = dict(self._sources)
        results: Dict[str, Dict[str, Any]] = {}
        for name, target in sources.items():
            try:
                snap = self._fetch(target)
                results[name] = {"up": True, "snapshot": snap, "error": ""}
            except (OSError, ValueError, http.client.HTTPException) as e:
                results[name] = {
                    "up": False, "snapshot": None,
                    "error": f"{type(e).__name__}: {e}",
                }
        with self._lock:
            # Keep results only for sources still registered (a source
            # removed mid-scrape must not resurrect).
            self._results = {
                n: r for n, r in results.items() if n in self._sources
            }
            self._last_scrape = monotonic()
            self._scrapes += 1

    def _refresh_if_stale(self) -> None:
        with self._lock:
            stale = (
                self._last_scrape == 0.0
                or monotonic() - self._last_scrape > self.interval_s
            )
        if stale:
            self.scrape_once()

    # ----------------------------------------------------------- background
    def start(self) -> None:
        """Periodic scraping on a daemon thread (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-aggregator", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _loop(self) -> None:
        # A dead aggregator silently freezes the fleet pane the
        # autoscaler reads; say so loudly (threadlint thread-target-raises).
        try:
            while not self._stop.is_set():
                try:
                    self.scrape_once()
                except Exception as e:  # noqa: BLE001 — one bad cycle
                    # must not end aggregation forever
                    logger.warning(f"[fleet] scrape cycle failed: {e!r}")
                self._stop.wait(self.interval_s)
        except BaseException:
            logger.exception(
                "[fleet] aggregator thread died — /fleet/metrics is "
                "frozen until the supervisor restarts"
            )
            raise

    # -------------------------------------------------------------- merging
    def merged(self, refresh: bool = True) -> Dict[str, Any]:
        """The ``/fleet/metrics.json`` payload: aggregate + per-source
        breakdown + liveness. ``refresh`` scrapes first when the stored
        view is older than the scrape interval."""
        if refresh:
            self._refresh_if_stale()
        with self._lock:
            results = {
                n: dict(r) for n, r in self._results.items()
            }
            scrapes = self._scrapes
        aggregate: Dict[str, Dict[str, Any]] = {
            "counters": {}, "gauges": {}, "histograms": {},
            "collectors": {},
        }
        skipped: List[str] = []
        for name, res in results.items():
            snap = res.get("snapshot")
            if not res.get("up") or not isinstance(snap, dict):
                continue
            for family in ("counters", "gauges", "collectors"):
                for key, value in (snap.get(family) or {}).items():
                    if isinstance(value, (int, float)) and not isinstance(
                        value, bool
                    ):
                        agg = aggregate[family]
                        agg[key] = agg.get(key, 0.0) + float(value)
            for key, entry in (snap.get("histograms") or {}).items():
                if not isinstance(entry, dict):
                    continue
                merged = _merge_histogram(
                    aggregate["histograms"].get(key), entry
                )
                if merged is None:
                    skipped.append(f"{name}:{key}")
                else:
                    aggregate["histograms"][key] = merged
        for entry in aggregate["histograms"].values():
            _finalize_histogram(entry)
        return {
            "scraped_at": round(time.time(), 3),
            "scrapes": scrapes,
            "sources": {
                n: {"up": r.get("up", False), "error": r.get("error", "")}
                for n, r in results.items()
            },
            "up": sum(1 for r in results.values() if r.get("up")),
            "aggregate": aggregate,
            "replicas": {
                n: r.get("snapshot") for n, r in results.items()
            },
            # Bucket-ladder mismatches cannot merge bucket-wise; they are
            # reported, never silently averaged.
            "skipped_histograms": skipped,
        }

    # ----------------------------------------------------------- exposition
    def render_prometheus(self, refresh: bool = True) -> str:
        """Prometheus text exposition of the fleet: every sample labeled
        ``replica="<source>"`` plus the aggregate as ``replica="fleet"``
        (so ``sum()`` over the breakdown and the pre-merged series never
        double-count under one unlabeled name)."""
        view = self.merged(refresh=refresh)
        lines: List[str] = []
        typed: Dict[str, str] = {}

        def sample(name: str, labels: Dict[str, str], value: float,
                   extra: str = "") -> None:
            """One sample line, no metadata (histogram component series
            must NOT get their own # TYPE lines — same shape as
            bus.render_prometheus)."""
            parts = [
                f'{_sanitize(k)}="{_escape(str(v))}"'
                for k, v in sorted(labels.items())
            ]
            if extra:
                parts.append(extra)
            label_str = "{" + ",".join(parts) + "}" if parts else ""
            lines.append(
                f"seist_{_sanitize(name)}{label_str} {_fmt(float(value))}"
            )

        def emit(name: str, mtype: str, labels: Dict[str, str],
                 value: float, extra: str = "") -> None:
            full = f"seist_{_sanitize(name)}"
            if typed.get(full) is None:
                lines.append(f"# TYPE {full} {mtype}")
                typed[full] = mtype
            sample(name, labels, value, extra)

        def emit_snapshot(snap: Dict[str, Any], replica: str) -> None:
            for key, value in (snap.get("counters") or {}).items():
                name, labels = _split_key(key)
                labels["replica"] = replica
                emit(name + "_total", "counter", labels, value)
            for key, value in (snap.get("gauges") or {}).items():
                name, labels = _split_key(key)
                labels["replica"] = replica
                emit(name, "gauge", labels, value)
            for key, entry in (snap.get("histograms") or {}).items():
                if not isinstance(entry, dict):
                    continue
                bounds = entry.get("bounds")
                counts = entry.get("bucket_counts")
                name, labels = _split_key(key)
                labels["replica"] = replica
                if not bounds or not counts:
                    emit(name + "_count", "untyped", labels,
                         entry.get("count", 0.0))
                    continue
                full = f"seist_{_sanitize(name)}"
                if typed.get(full) is None:
                    lines.append(f"# TYPE {full} histogram")
                    typed[full] = "histogram"
                cum = 0
                for bound, c in zip(bounds, counts[:-1]):
                    cum += c
                    sample(name + "_bucket", labels, cum,
                           extra='le="' + _fmt(float(bound)) + '"')
                total = int(sum(counts))
                sample(name + "_bucket", labels, total, extra='le="+Inf"')
                sample(name + "_sum", labels, entry.get("sum", 0.0))
                sample(name + "_count", labels, total)
            for key, value in (snap.get("collectors") or {}).items():
                if isinstance(value, bool) or not isinstance(
                    value, (int, float)
                ):
                    continue
                name, labels = _split_key(key)
                labels["replica"] = replica
                emit(name, "untyped", labels, value)

        for name, res in view["sources"].items():
            emit("fleet_source_up", "gauge", {"source": name},
                 1.0 if res["up"] else 0.0)
        emit("fleet_sources", "gauge", {}, len(view["sources"]))
        emit_snapshot(view["aggregate"], "fleet")
        for name, snap in view["replicas"].items():
            if isinstance(snap, dict):
                emit_snapshot(snap, name)
        return "\n".join(lines) + "\n"


def _merge_histogram(
    acc: Optional[Dict[str, Any]], entry: Dict[str, Any]
) -> Optional[Dict[str, Any]]:
    """Fold one source's histogram entry into the accumulator.
    Bucket-wise when both sides carry matching bucket ladders; count /
    sum / max stay mergeable regardless. Returns None (skip) on a
    bucket-ladder mismatch."""
    fresh = {
        "count": float(entry.get("count", 0.0)),
        "sum": float(entry.get("sum",
                               entry.get("mean", 0.0)
                               * entry.get("count", 0.0))),
        "max": float(entry.get("max", 0.0)),
        "bounds": list(entry.get("bounds") or []),
        "bucket_counts": list(entry.get("bucket_counts") or []),
    }
    if acc is None:
        return fresh
    if acc["bounds"] != fresh["bounds"]:
        return None
    acc["count"] += fresh["count"]
    acc["sum"] += fresh["sum"]
    acc["max"] = max(acc["max"], fresh["max"])
    if acc["bucket_counts"] and fresh["bucket_counts"]:
        acc["bucket_counts"] = [
            a + b
            for a, b in zip(acc["bucket_counts"], fresh["bucket_counts"])
        ]
    return acc


def _finalize_histogram(entry: Dict[str, Any]) -> None:
    """Re-derive the summary fields of a merged histogram from its
    merged buckets (the whole point of bucket-wise merging: fleet p99 is
    computed over the union distribution, never averaged)."""
    total = int(entry.get("count", 0))
    entry["mean"] = entry["sum"] / total if total else 0.0
    bounds = entry.get("bounds") or []
    counts = entry.get("bucket_counts") or []
    if bounds and counts:
        h = LatencyHistogram(bounds)
        for q, key in ((0.50, "p50"), (0.90, "p90"), (0.99, "p99")):
            entry[key] = h._percentile_from(
                q, counts, total, entry.get("max", 0.0)
            )
