"""Opt-in metrics HTTP endpoint for the train worker (``--metrics-port``).

A days-long supervised run becomes observable without attaching a
debugger: Prometheus scrapes ``/metrics``, a human curls
``/metrics.json`` or ``/flight``, and ``POST /profile`` asks the train
loop for an on-demand ``jax.profiler`` capture window (same machinery as
``--profile-steps`` and SIGUSR2 — the loop polls the trigger at step
boundaries, so the capture starts on a clean step edge).

Endpoints::

    GET  /metrics        Prometheus text exposition (bus + collectors)
    GET  /metrics.json   JSON snapshot of the bus
    GET  /flight         live flight-recorder ring (no file written)
    GET  /traces         distributed-trace index (obs/trace.py ring)
    GET  /traces/<id>    one trace's span segments (this process)
    POST /profile[?steps=N]  request a profiler capture (default 5 steps)
    GET  /healthz        {"status": "ok"} liveness

Stdlib ``http.server`` only (the serve front-end set the precedent); the
server runs on a daemon thread and binds loopback by default — metrics
are unauthenticated, do not bind a public interface.
"""

from __future__ import annotations

import threading
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

from seist_tpu.obs import bus as bus_mod
from seist_tpu.obs import flight as flight_mod
from seist_tpu.obs.bus import MetricsBus, render_prometheus
from seist_tpu.utils.logger import logger

DEFAULT_PROFILE_STEPS = 5


class ProfileTrigger:
    """Request box for an on-demand profiler capture. HTTP and SIGUSR2
    call :meth:`request`; the train loop calls :meth:`consume` at step
    boundaries and starts a capture when it returns > 0 (several pending
    requests coalesce into one capture, last-requested width wins).

    Deliberately lock-free (threadlint signal-handler-unsafe audit):
    :meth:`request` runs inside the SIGUSR2 handler, which interrupts the
    main thread at an arbitrary bytecode boundary — if that thread were
    inside a locked :meth:`consume` at that moment, a lock here would
    self-deadlock the process. ``deque.append`` and ``deque.popleft``
    are each one GIL-atomic operation, so a request landing at any point
    during :meth:`consume` is either drained by it or sits intact for
    the next step-boundary poll — nothing is ever consumed-and-dropped
    (the maxlen bounds pathological signal storms; overflow discards
    oldest, and consume takes the newest anyway).
    """

    def __init__(self) -> None:
        self._requests: "deque[int]" = deque(maxlen=64)

    def request(self, steps: int = DEFAULT_PROFILE_STEPS) -> None:
        self._requests.append(max(1, int(steps)))

    def consume(self) -> int:
        if not self._requests:  # cheap per-step fast path
            return 0
        steps = 0
        while True:
            try:
                steps = self._requests.popleft()
            except IndexError:
                return steps


def _json_bytes(payload) -> bytes:
    import json

    return json.dumps(payload, default=str).encode()


class _Handler(BaseHTTPRequestHandler):
    server_version = "seist-obs/0.1"
    protocol_version = "HTTP/1.1"

    def log_message(self, format: str, *args) -> None:
        logger.debug(f"[obs] {self.address_string()} {format % args}")

    def _reply(self, status: int, body: bytes, ctype: str) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    @property
    def _bus(self) -> MetricsBus:
        return self.server.bus  # type: ignore[attr-defined]

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        try:
            parsed = urlparse(self.path)
            if parsed.path == "/metrics":
                self._reply(
                    200,
                    render_prometheus(self._bus).encode(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            elif parsed.path == "/metrics.json":
                self._reply(
                    200, _json_bytes(self._bus.snapshot()), "application/json"
                )
            elif parsed.path == "/flight":
                rec = flight_mod.get()
                if rec is None:
                    self._reply(
                        404,
                        _json_bytes({"error": "no flight recorder installed"}),
                        "application/json",
                    )
                else:
                    self._reply(
                        200,
                        _json_bytes(rec.payload("live")),
                        "application/json",
                    )
            elif parsed.path.startswith("/traces"):
                from seist_tpu.obs import trace as trace_mod

                routed = trace_mod.handle_traces_path(self.path)
                if routed is None:
                    self._reply(
                        404, _json_bytes({"error": "not_found"}),
                        "application/json",
                    )
                else:
                    status, payload = routed
                    self._reply(
                        status, _json_bytes(payload), "application/json"
                    )
            elif parsed.path == "/healthz":
                self._reply(200, _json_bytes({"status": "ok"}), "application/json")
            else:
                self._reply(
                    404, _json_bytes({"error": "not_found"}), "application/json"
                )
        except Exception as e:  # noqa: BLE001 - a scrape bug must not kill
            # the handler thread (and 500 is the right scrape outcome)
            try:
                self._reply(500, _json_bytes({"error": repr(e)}), "application/json")
            except OSError:
                pass

    def do_POST(self) -> None:  # noqa: N802
        try:
            parsed = urlparse(self.path)
            # Drain any body so keep-alive connections stay in sync.
            length = int(self.headers.get("Content-Length") or 0)
            if length:
                self.rfile.read(min(length, 1 << 16))
            if parsed.path == "/profile":
                trigger = self.server.profile_trigger  # type: ignore[attr-defined]
                if trigger is None:
                    self._reply(
                        404,
                        _json_bytes(
                            {"error": "no profile trigger (not a train run?)"}
                        ),
                        "application/json",
                    )
                    return
                q = parse_qs(parsed.query)
                steps = int(q.get("steps", [DEFAULT_PROFILE_STEPS])[0])
                trigger.request(steps)
                self._reply(
                    200,
                    _json_bytes({"requested_steps": max(1, steps)}),
                    "application/json",
                )
            else:
                self._reply(
                    404, _json_bytes({"error": "not_found"}), "application/json"
                )
        except Exception as e:  # noqa: BLE001 - same contract as do_GET
            try:
                self._reply(500, _json_bytes({"error": repr(e)}), "application/json")
            except OSError:
                pass


class MetricsHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    # Scrape bursts are mild next to serve traffic, but socketserver's
    # backlog-5 default drops SYNs whenever a dashboard + operator curl +
    # Prometheus collide; same contract as ServeHTTPServer/RouterHTTPServer
    # (threadlint http-server-backlog).
    request_queue_size = 1024

    def __init__(
        self,
        addr: Tuple[str, int],
        bus: MetricsBus,
        profile_trigger: Optional[ProfileTrigger] = None,
    ):
        super().__init__(addr, _Handler)
        self.bus = bus
        self.profile_trigger = profile_trigger


def start_metrics_server(
    port: int,
    bus: Optional[MetricsBus] = None,
    profile_trigger: Optional[ProfileTrigger] = None,
    host: str = "127.0.0.1",
) -> MetricsHTTPServer:
    """Bind + serve on a daemon thread; ``port=0`` binds an ephemeral
    port (read it back from ``server.server_address``). The bound port is
    logged so an operator can find it in the run log."""
    server = MetricsHTTPServer(
        (host, int(port)), bus if bus is not None else bus_mod.BUS,
        profile_trigger,
    )
    thread = threading.Thread(
        target=server.serve_forever, name="obs-metrics-http", daemon=True
    )
    thread.start()
    bound = server.server_address[1]
    logger.info(f"[obs] metrics endpoint: http://{host}:{bound}/metrics")
    return server
