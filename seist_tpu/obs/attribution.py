"""Per-op step-time attribution: where does the next 10 ms go?

The ROADMAP's MFU campaign is blocked on visibility — ``bench.py``
reports ONE aggregate step time, so nobody can say whether the gap to
the hardware is attention FLOPs, padding waste, or data movement. The
pjit/TPUv4 scaling report (arXiv:2204.06514) treats per-op profiling as
the precondition for every step-time win it describes; this module is
the always-available analytic half of that story (an on-demand
``jax.profiler`` capture — ``--profile-steps`` / SIGUSR2 / ``POST
/profile`` — is the measured half, viewed in TensorBoard/Perfetto).

The model: walk the step function's jaxpr (recursing through pjit /
scan / cond / custom-diff calls, multiplying scan bodies by their trip
count) and charge every equation analytic FLOPs (exact for
``dot_general`` / ``conv_general_dilated``, element-count for vector
ops) and bytes moved (operand + result aval bytes — an un-fused upper
bound; XLA fusion keeps intermediates in registers, which is exactly why
the ``model_vs_xla`` ratio against the compiled executable's
``cost_analysis()`` is reported alongside). Per-op time shares come from
a roofline charge ``max(flops/peak, bytes/bw)``; multiplied by the
measured step time they attribute real milliseconds per op class.

Everything here is deterministic and backend-free (tested on CPU); the
BENCH ``step_breakdown`` section is built from it (bench.py).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# Generic roofline for share computation when the device peak/bandwidth
# are unknown (CPU debug runs): ridge intensity 10 FLOP/byte — only the
# RELATIVE shares matter there, and a ridge in the 5-50 range barely
# moves them for this workload.
_GENERIC_PEAK = 1e12
_GENERIC_BW = 1e11

#: Op classes for the MFU decomposition. Anything not listed is "other".
_MATMUL_PRIMS = frozenset(("dot_general", "conv_general_dilated"))
_REDUCE_PRIMS = frozenset(
    (
        "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
        "reduce_and", "reduce_or", "argmax", "argmin",
        "reduce_precision", "cumsum", "cummax", "cummin", "cumprod",
    )
)
_DATA_PRIMS = frozenset(
    (
        "transpose", "reshape", "broadcast_in_dim", "concatenate",
        "slice", "dynamic_slice", "dynamic_update_slice", "pad",
        "gather", "scatter", "scatter_add", "rev", "squeeze",
        "convert_element_type", "select_n", "copy", "device_put",
        "split", "iota",
    )
)


def classify(prim_name: str) -> str:
    if prim_name in _MATMUL_PRIMS:
        return "matmul"
    if prim_name in _REDUCE_PRIMS:
        return "reduce"
    if prim_name in _DATA_PRIMS:
        return "data_movement"
    return "elementwise"


def _aval_bytes(v) -> int:
    aval = getattr(v, "aval", None)
    size = getattr(aval, "size", None)
    dtype = getattr(aval, "dtype", None)
    if size is None or dtype is None:
        return 0
    return int(size) * int(dtype.itemsize)


def _aval_size(v) -> int:
    return int(getattr(getattr(v, "aval", None), "size", 0) or 0)


def _shape_str(v) -> str:
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None:
        return "?"
    dt = str(dtype) if dtype is not None else "?"
    short = {"float32": "f32", "bfloat16": "bf16", "float16": "f16",
             "int32": "i32", "int64": "i64", "bool": "pred"}.get(dt, dt)
    return f"{short}[{','.join(str(d) for d in shape)}]"


def _dot_flops(eqn) -> int:
    (lhs_c, rhs_c), (lhs_b, _rhs_b) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    batch = math.prod(lhs[i] for i in lhs_b) if lhs_b else 1
    k = math.prod(lhs[i] for i in lhs_c) if lhs_c else 1
    m = math.prod(
        d for i, d in enumerate(lhs) if i not in lhs_c and i not in lhs_b
    )
    n = math.prod(
        d for i, d in enumerate(rhs) if i not in rhs_c and i not in _rhs_b
    )
    return 2 * batch * m * n * k


def _conv_flops(eqn) -> int:
    out = eqn.outvars[0].aval.shape
    kernel = eqn.invars[1].aval.shape
    dnums = eqn.params.get("dimension_numbers")
    # rhs_spec[0] indexes the kernel's output-feature dim; MACs =
    # batch*out_spatial*out_ch*(in_ch/groups)*kernel_spatial =
    # (prod(out)/out_ch) * prod(kernel).
    out_ch = kernel[dnums.rhs_spec[0]] if dnums is not None else 1
    batch_count = eqn.params.get("batch_group_count", 1) or 1
    return 2 * (math.prod(out) // max(out_ch, 1)) * math.prod(kernel) // max(
        batch_count, 1
    )


def _sub_jaxprs(eqn) -> List[Tuple[Any, int, bool]]:
    """(jaxpr, multiplier, exclusive) sub-jaxprs of a call-like eqn.
    ``exclusive`` marks cond branches (charge the max, not the sum)."""
    name = eqn.primitive.name
    params = eqn.params
    if name == "scan":
        return [(params["jaxpr"], int(params.get("length", 1)), False)]
    if name == "while":
        # Trip count is data-dependent; charge one iteration (documented
        # lower bound — the repo's steps are scan/pjit shaped anyway).
        return [(params["body_jaxpr"], 1, False), (params["cond_jaxpr"], 1, False)]
    if name == "cond":
        return [(b, 1, True) for b in params["branches"]]
    subs = []
    for v in params.values():
        if hasattr(v, "eqns") or hasattr(v, "jaxpr"):  # Jaxpr | ClosedJaxpr
            subs.append((v, 1, False))
    return subs


def _inner(jaxpr) -> Any:
    return jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr


def _walk(jaxpr, scale: int, acc: Dict[str, Dict[str, Any]]) -> None:
    for eqn in _inner(jaxpr).eqns:
        subs = _sub_jaxprs(eqn)
        if subs:
            exclusive = [s for s in subs if s[2]]
            if exclusive:
                # cond: charge the most expensive branch only.
                best: Dict[str, Dict[str, Any]] = {}
                best_cost = -1.0
                for sub, mult, _ in exclusive:
                    trial: Dict[str, Dict[str, Any]] = {}
                    _walk(sub, scale * mult, trial)
                    cost = sum(r["flops"] + r["bytes"] for r in trial.values())
                    if cost > best_cost:
                        best_cost, best = cost, trial
                _merge(acc, best)
            for sub, mult, excl in subs:
                if not excl:
                    _walk(sub, scale * mult, acc)
            continue
        name = eqn.primitive.name
        try:
            if name == "dot_general":
                flops = _dot_flops(eqn)
            elif name == "conv_general_dilated":
                flops = _conv_flops(eqn)
            elif name in _REDUCE_PRIMS:
                flops = sum(_aval_size(v) for v in eqn.invars)
            elif name in _DATA_PRIMS:
                flops = 0
            else:
                flops = max(
                    max((_aval_size(v) for v in eqn.outvars), default=0),
                    max((_aval_size(v) for v in eqn.invars), default=0),
                )
        except (AttributeError, KeyError, TypeError, IndexError):
            # Unmodeled primitive layout — charge element count, never die:
            # attribution is diagnostics for EVERY step variant.
            flops = max((_aval_size(v) for v in eqn.outvars), default=0)
        nbytes = sum(_aval_bytes(v) for v in eqn.invars) + sum(
            _aval_bytes(v) for v in eqn.outvars
        )
        rec = acc.setdefault(
            name,
            {
                "op": name,
                "class": classify(name),
                "count": 0,
                "flops": 0,
                "bytes": 0,
                "example": None,
            },
        )
        rec["count"] += scale
        rec["flops"] += flops * scale
        rec["bytes"] += nbytes * scale
        if rec["example"] is None:
            ins = " ".join(_shape_str(v) for v in eqn.invars[:2])
            rec["example"] = f"{ins} -> {_shape_str(eqn.outvars[0])}"


def _merge(acc: Dict[str, Dict[str, Any]], other: Dict[str, Dict[str, Any]]) -> None:
    for name, rec in other.items():
        dst = acc.setdefault(name, dict(rec, count=0, flops=0, bytes=0))
        dst["count"] += rec["count"]
        dst["flops"] += rec["flops"]
        dst["bytes"] += rec["bytes"]
        if dst.get("example") is None:
            dst["example"] = rec.get("example")


# Public aliases for the IR analyzer (tools/irlint): the lint coverage
# fractions and the BENCH ``step_breakdown`` must agree about what a
# matmul costs, so there is exactly ONE dot/conv FLOP accounting and one
# call-graph walk — this one.
dot_flops = _dot_flops
conv_flops = _conv_flops
sub_jaxprs = _sub_jaxprs
inner_jaxpr = _inner


def jaxpr_op_costs(closed_jaxpr) -> List[Dict[str, Any]]:
    """Per-primitive analytic cost records for a (Closed)Jaxpr, summed
    over every call site (scan bodies multiplied by trip count)."""
    acc: Dict[str, Dict[str, Any]] = {}
    _walk(closed_jaxpr, 1, acc)
    return sorted(acc.values(), key=lambda r: -(r["flops"] + r["bytes"]))


def attribute_step(
    fn: Callable,
    args: Sequence[Any],
    *,
    peak_flops: Optional[float] = None,
    hbm_bw: Optional[float] = None,
    measured_step_ms: Optional[float] = None,
    top_k: int = 10,
) -> Dict[str, Any]:
    """The BENCH ``step_breakdown`` core: trace ``fn(*args)`` (jitted
    callables trace through their pjit wrapper) and return top-k ops by
    roofline-modeled time with FLOPs, bytes and an MFU decomposition.

    With ``measured_step_ms``, model time shares are converted into
    attributed milliseconds of the real step; with ``peak_flops``, the
    overall and matmul-only MFU are computed from the analytic FLOPs.
    """
    import jax

    jaxpr = jax.make_jaxpr(fn)(*args)
    ops = jaxpr_op_costs(jaxpr)
    peak = float(peak_flops or 0.0) or _GENERIC_PEAK
    bw = float(hbm_bw or 0.0) or _GENERIC_BW
    for r in ops:
        r["time_model_s"] = max(r["flops"] / peak, r["bytes"] / bw)
    t_total = sum(r["time_model_s"] for r in ops) or 1e-30

    flops_total = sum(r["flops"] for r in ops)
    bytes_total = sum(r["bytes"] for r in ops)
    classes: Dict[str, Dict[str, float]] = {}
    for r in ops:
        c = classes.setdefault(
            r["class"], {"flops": 0, "bytes": 0, "time_model_s": 0.0}
        )
        c["flops"] += r["flops"]
        c["bytes"] += r["bytes"]
        c["time_model_s"] += r["time_model_s"]

    def _ms(share: float) -> Optional[float]:
        if measured_step_ms is None:
            return None
        return round(share * measured_step_ms, 3)

    top = []
    for r in ops[: max(1, int(top_k))]:
        share = r["time_model_s"] / t_total
        top.append(
            {
                "op": r["op"],
                "class": r["class"],
                "count": r["count"],
                "flops": int(r["flops"]),
                "bytes_accessed": int(r["bytes"]),
                "time_frac": round(share, 4),
                "est_ms": _ms(share),
                "bound": (
                    "compute"
                    if r["flops"] / peak >= r["bytes"] / bw
                    else "memory"
                ),
                "example": r["example"],
            }
        )

    decomposition = {}
    for cname, c in sorted(classes.items()):
        share = c["time_model_s"] / t_total
        decomposition[cname] = {
            "flops": int(c["flops"]),
            "flops_frac": round(c["flops"] / max(flops_total, 1), 4),
            "time_frac": round(share, 4),
            "est_ms": _ms(share),
        }

    out: Dict[str, Any] = {
        "top_ops": top,
        "n_op_kinds": len(ops),
        "flops_total": int(flops_total),
        "bytes_total": int(bytes_total),
        "arithmetic_intensity": round(flops_total / max(bytes_total, 1), 3),
        "mfu_decomposition": decomposition,
        "roofline_basis": {
            "peak_flops": peak,
            "hbm_bw": bw,
            "generic": peak_flops is None or not peak_flops,
        },
    }
    if measured_step_ms is not None and peak_flops:
        mfu = flops_total / (measured_step_ms / 1e3 * peak_flops)
        out["mfu_model"] = round(mfu, 4)
        mm_ms = decomposition.get("matmul", {}).get("est_ms") or 0.0
        mm_flops = classes.get("matmul", {}).get("flops", 0)
        if mm_ms:
            # MFU of the matmul-attributed milliseconds alone: how close
            # the MXU-shaped work is to peak once everything else is
            # carved out — the ceiling the fusion/padding work chases.
            out["mfu_matmul_attributed"] = round(
                mm_flops / (mm_ms / 1e3 * peak_flops), 4
            )
    return out
