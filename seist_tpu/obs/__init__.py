"""Unified telemetry plane (docs/OBSERVABILITY.md).

Three pillars, one import:

* **Metrics bus** (:mod:`~seist_tpu.obs.bus`): process-wide counters /
  gauges / histograms + the span API every timing path in the repo is
  deduplicated onto; Prometheus text exposition + JSONL event log.
* **Per-op attribution** (:mod:`~seist_tpu.obs.attribution`): analytic
  jaxpr walk + roofline time shares behind BENCH's ``step_breakdown``.
* **Flight recorder** (:mod:`~seist_tpu.obs.flight`): ring buffer of the
  last N steps' metrics/spans, dumped to JSON on every death path.

``obs/http.py`` serves the bus on the train worker's ``--metrics-port``.
"""

from seist_tpu.obs import flight
from seist_tpu.obs.attribution import attribute_step, jaxpr_op_costs
from seist_tpu.obs.bus import (
    BUS,
    EventLog,
    MetricsBus,
    register_default_collectors,
    render_prometheus,
    stopwatch,
    timed_iter,
)
from seist_tpu.obs.flight import FlightRecorder
from seist_tpu.obs.http import (
    MetricsHTTPServer,
    ProfileTrigger,
    start_metrics_server,
)

__all__ = [
    "BUS",
    "EventLog",
    "FlightRecorder",
    "MetricsBus",
    "MetricsHTTPServer",
    "ProfileTrigger",
    "attribute_step",
    "flight",
    "jaxpr_op_costs",
    "register_default_collectors",
    "render_prometheus",
    "start_metrics_server",
    "stopwatch",
    "timed_iter",
]
