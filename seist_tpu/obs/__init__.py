"""Unified telemetry plane (docs/OBSERVABILITY.md).

Three pillars, one import:

* **Metrics bus** (:mod:`~seist_tpu.obs.bus`): process-wide counters /
  gauges / histograms + the span API every timing path in the repo is
  deduplicated onto; Prometheus text exposition + JSONL event log.
* **Per-op attribution** (:mod:`~seist_tpu.obs.attribution`): analytic
  jaxpr walk + roofline time shares behind BENCH's ``step_breakdown``.
* **Flight recorder** (:mod:`~seist_tpu.obs.flight`): ring buffer of the
  last N steps' metrics/spans, dumped to JSON on every death path.
* **Distributed request tracing** (:mod:`~seist_tpu.obs.trace`):
  W3C-``traceparent`` IDs propagated across the serving fleet, per-process
  span rings with tail-based retention, ``GET /traces`` exposition.
* **Fleet metrics aggregation** (:mod:`~seist_tpu.obs.fleet`): merge N
  replicas' bus snapshots into one ``GET /fleet/metrics`` pane.

``obs/http.py`` serves the bus on the train worker's ``--metrics-port``.
"""

from seist_tpu.obs import flight, trace
from seist_tpu.obs.attribution import attribute_step, jaxpr_op_costs
from seist_tpu.obs.bus import (
    BUS,
    EventLog,
    MetricsBus,
    register_default_collectors,
    render_prometheus,
    stopwatch,
    timed_iter,
)
from seist_tpu.obs.flight import FlightRecorder
from seist_tpu.obs.http import (
    MetricsHTTPServer,
    ProfileTrigger,
    start_metrics_server,
)
from seist_tpu.obs.trace import RequestTrace, TraceBuffer

__all__ = [
    "BUS",
    "EventLog",
    "FlightRecorder",
    "MetricsBus",
    "MetricsHTTPServer",
    "ProfileTrigger",
    "RequestTrace",
    "TraceBuffer",
    "attribute_step",
    "flight",
    "jaxpr_op_costs",
    "register_default_collectors",
    "render_prometheus",
    "start_metrics_server",
    "stopwatch",
    "timed_iter",
    "trace",
]
