"""Process-wide metrics bus: counters / gauges / histograms, a span API
for timing phases, Prometheus text exposition, and a structured JSONL
event log.

PR 2-5 grew five disconnected telemetry surfaces (worker epoch log lines,
``io_guard.COUNTERS``, serve's JSON ``/metrics``, BENCH sections, the
quarantine report). This module is the ONE registry they all publish to,
in the shape a production JAX training stack needs (t5x's metrics/summary
bus, arXiv:2203.17189, is the blueprint):

* :class:`MetricsBus` — name+label keyed :class:`Counter` / :class:`Gauge`
  / :class:`Histogram` registry. ``BUS`` is the process singleton.
* **Span API** — ``with BUS.span("checkpoint/save"):`` times a phase on
  ``time.monotonic()`` (NTP-step safe), feeds a ``<name>_ms`` histogram,
  and fans out to registered sinks (the flight recorder rides this).
  ``BUS.begin(name)`` is the explicit-stop form for phases that don't
  nest as a ``with`` block (epoch timing in the worker loop). This is THE
  repo's interval-timing primitive: ``utils/profiling.stopwatch`` and
  ``StepTimeSplit`` delegate here, and jaxlint's ``wallclock-interval``
  rule keeps ad-hoc ``time.time()`` pairs from growing back.
* **Collectors** — scrape-time callables (io_guard counters, serve
  batcher stats, loader counters) so sources that already keep their own
  thread-safe state publish without double bookkeeping.
* :func:`render_prometheus` — text exposition (version 0.0.4) of the
  whole bus, served by ``obs/http.py`` on the train worker's
  ``--metrics-port`` and by serve's ``/metrics?format=prometheus``.
* :class:`EventLog` — append-only JSONL of structured events (epoch
  summaries, rollbacks, quarantines, deaths) for reconstructing a
  days-long supervised run after the fact.

Hot-path cost: one span is two ``monotonic()`` calls, one dict lookup and
one locked histogram observe — single-digit microseconds, benched in the
BENCH ``step_breakdown.telemetry`` section at <1% of step time.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from seist_tpu.utils.meters import LATENCY_BOUNDS_MS, LatencyHistogram

#: Default histogram bounds for span durations (ms) — reuse the serve
#: latency ladder; spans range from sub-ms host waits to multi-second
#: checkpoint saves, the same span.
SPAN_BOUNDS_MS = LATENCY_BOUNDS_MS

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, Any]) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def monotonic() -> float:
    """The bus clock. One indirection point so every interval in the repo
    reads the same monotonic source (jaxlint wallclock-interval rationale:
    a wall-clock step must never corrupt a measured duration)."""
    return time.monotonic()


@contextlib.contextmanager
def stopwatch() -> Iterator[Callable[[], float]]:
    """``with stopwatch() as elapsed:`` — ``elapsed()`` returns seconds
    since entry, inside the block and after exit. The primitive behind
    ``utils/profiling.stopwatch`` (kept importable from there) and the
    span API; not registered on any bus."""
    t0 = monotonic()
    done: List[float] = []

    def elapsed() -> float:
        return (done[0] if done else monotonic()) - t0

    try:
        yield elapsed
    finally:
        done.append(monotonic())


class Counter:
    """Monotonic counter (Prometheus ``counter``)."""

    __slots__ = ("name", "labels", "_value", "_lock")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = labels
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins instantaneous value (Prometheus ``gauge``)."""

    __slots__ = ("name", "labels", "_value", "_lock")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = labels
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram(LatencyHistogram):
    """Bus-registered fixed-bucket histogram. The implementation IS
    ``utils.meters.LatencyHistogram`` (serve's /metrics payload keeps its
    exact shape); this subclass only adds the registry identity and the
    cumulative-bucket view Prometheus exposition needs."""

    def __init__(
        self,
        name: str,
        labels: Dict[str, str],
        bounds: Sequence[float] = SPAN_BOUNDS_MS,
    ):
        super().__init__(bounds=bounds)
        self.name = name
        self.labels = labels


class Span:
    """One timed phase. Context manager (``with bus.span(...)``) or
    explicit form (``s = bus.begin(...)``, later ``s.end()``).
    ``duration_s`` is available after exit/end."""

    __slots__ = ("name", "labels", "_bus", "_t0", "duration_s")

    def __init__(self, bus: "MetricsBus", name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = labels
        self._bus = bus
        self._t0 = monotonic()
        self.duration_s: Optional[float] = None

    def end(self) -> float:
        """Stop the clock, record on the bus, return elapsed seconds.
        Idempotent: a second end() returns the first duration."""
        if self.duration_s is None:
            self.duration_s = monotonic() - self._t0
            self._bus._record_span(self)
        return self.duration_s

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.end()


class MetricsBus:
    """Name+label keyed metric registry + span fan-out + collectors."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, _LabelKey], Any] = {}
        self._collectors: Dict[str, Callable[[], Dict[str, Any]]] = {}
        self._span_sinks: List[Callable[[Span], None]] = []

    # ------------------------------------------------------------ metrics
    def _get(self, cls, name: str, labels: Dict[str, Any], **kw) -> Any:
        key = (name, _label_key(labels))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, {k: str(v) for k, v in labels.items()}, **kw)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric '{name}' already registered as "
                    f"{type(m).__name__}, not {cls.__name__}"
                )
            return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(
        self, name: str, bounds: Sequence[float] = SPAN_BOUNDS_MS, **labels
    ) -> Histogram:
        return self._get(Histogram, name, labels, bounds=bounds)

    # -------------------------------------------------------------- spans
    def span(self, name: str, **labels) -> Span:
        """Start a span now; use as a context manager."""
        return Span(self, name, labels)

    # Alias for the explicit begin/end form (same object, reads better at
    # call sites that can't nest a with-block around the phase).
    begin = span

    def _record_span(self, span: Span) -> None:
        self.histogram(f"{span.name}_ms", **span.labels).observe(
            (span.duration_s or 0.0) * 1e3
        )
        # Snapshot under the lock: install()/remove_span_sink mutate the
        # list from other threads (flight-recorder swap on a death path),
        # and iterating a list being resized raises mid-span.
        with self._lock:
            sinks = list(self._span_sinks)
        for sink in sinks:
            try:
                sink(span)
            except Exception:  # noqa: BLE001 - a sick sink (e.g. a closed
                # flight recorder) must never break the timed code path
                pass

    def add_span_sink(self, sink: Callable[[Span], None]) -> None:
        with self._lock:
            if sink not in self._span_sinks:
                self._span_sinks.append(sink)

    def remove_span_sink(self, sink: Callable[[Span], None]) -> None:
        with self._lock:
            if sink in self._span_sinks:
                self._span_sinks.remove(sink)

    # --------------------------------------------------------- collectors
    def register_collector(
        self,
        key: str,
        fn: Callable[[], Dict[str, Any]],
        name: Optional[str] = None,
        **labels,
    ) -> None:
        """Register a scrape-time source. ``fn`` returns a (possibly
        nested) dict of numbers; keys re-registering replace the previous
        collector (a fresh serve batcher supersedes a drained one).
        ``name`` overrides the metric-name prefix (default: the key), so
        per-instance keys can share one metric family distinguished by
        ``labels`` (serve batchers: one family, ``model=...`` labels)."""
        with self._lock:
            self._collectors[key] = (
                fn,
                {k: str(v) for k, v in labels.items()},
                name or key,
            )

    def unregister_collector(
        self, key: str, fn: Optional[Callable[[], Dict[str, Any]]] = None
    ) -> None:
        """Remove a collector. With ``fn``, remove only if the registered
        callable is still that one — a replaced instance's late shutdown
        must not tear down its successor's registration."""
        with self._lock:
            cur = self._collectors.get(key)
            if cur is None:
                return
            if fn is not None and cur[0] != fn:
                return
            self._collectors.pop(key, None)

    def _collect(self) -> List[Tuple[str, Dict[str, str], float]]:
        """Flattened collector samples: (name, labels, value)."""
        with self._lock:
            collectors = dict(self._collectors)
        out: List[Tuple[str, Dict[str, str], float]] = []
        for key, (fn, labels, name) in collectors.items():
            try:
                data = fn()
            except Exception:  # noqa: BLE001 - one sick collector must not
                # take down the whole scrape
                continue
            for sample_name, value in _flatten(name, data):
                out.append((sample_name, labels, value))
        return out

    # ----------------------------------------------------------- snapshot
    def snapshot(self) -> Dict[str, Any]:
        """JSON-able view of everything on the bus (the /metrics.json
        payload and the flight recorder's final-state stamp). Histogram
        entries carry their raw buckets (``bounds`` + ``bucket_counts``)
        on top of the summary so the fleet aggregator (obs/fleet.py) can
        merge replicas' histograms bucket-wise instead of averaging
        percentiles (which is statistically meaningless)."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: Dict[str, Any] = {"counters": {}, "gauges": {}, "histograms": {}}
        for m in metrics:
            label_sfx = _label_suffix(m.labels)
            if isinstance(m, Counter):
                out["counters"][m.name + label_sfx] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][m.name + label_sfx] = m.value
            elif isinstance(m, Histogram):
                entry = m.summary()
                bounds, counts, _, total_sum = m.buckets()
                entry["bounds"] = bounds
                entry["bucket_counts"] = counts
                entry["sum"] = total_sum
                out["histograms"][m.name + label_sfx] = entry
        out["collectors"] = {
            name + _label_suffix(labels): value
            for name, labels, value in self._collect()
        }
        return out

    def reset(self) -> None:
        """Drop every metric, collector and sink — test isolation only."""
        with self._lock:
            self._metrics.clear()
            self._collectors.clear()
            self._span_sinks.clear()


def _flatten(prefix: str, data: Any) -> List[Tuple[str, float]]:
    out: List[Tuple[str, float]] = []
    if isinstance(data, dict):
        for k, v in data.items():
            out.extend(_flatten(f"{prefix}_{k}", v))
    elif isinstance(data, bool):
        out.append((prefix, 1.0 if data else 0.0))
    elif isinstance(data, (int, float)):
        out.append((prefix, float(data)))
    # non-numeric leaves (strings, lists) are dropped: Prometheus samples
    # are numbers; the JSON snapshot keeps structure via the collectors'
    # own surfaces.
    return out


# ------------------------------------------------------------- exposition
def _sanitize(name: str) -> str:
    return "".join(
        c if (c.isalnum() or c == "_") else "_" for c in name
    ).strip("_") or "metric"


def _label_suffix(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _prom_labels(labels: Dict[str, str], extra: str = "") -> str:
    parts = [
        f'{_sanitize(k)}="{_escape(v)}"' for k, v in sorted(labels.items())
    ]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def render_prometheus(bus: MetricsBus, prefix: str = "seist") -> str:
    """Prometheus text exposition (format 0.0.4) of the whole bus:
    registered metrics plus scrape-time collector samples. Histograms
    emit cumulative ``_bucket{le=...}`` series, ``_sum`` and ``_count``
    per the exposition format."""
    lines: List[str] = []
    typed: Dict[str, str] = {}

    def emit(name: str, mtype: str, labels: Dict[str, str], value: float,
             extra_label: str = "") -> None:
        full = f"{prefix}_{_sanitize(name)}"
        if typed.get(full) is None:
            lines.append(f"# TYPE {full} {mtype}")
            typed[full] = mtype
        lines.append(f"{full}{_prom_labels(labels, extra_label)} {_fmt(value)}")

    with bus._lock:
        metrics = list(bus._metrics.values())
    for m in metrics:
        if isinstance(m, Counter):
            emit(m.name + "_total", "counter", m.labels, m.value)
        elif isinstance(m, Gauge):
            emit(m.name, "gauge", m.labels, m.value)
    for m in metrics:
        if not isinstance(m, Histogram):
            continue
        bounds, counts, total, total_sum = m.buckets()
        full = f"{prefix}_{_sanitize(m.name)}"
        if typed.get(full) is None:
            lines.append(f"# TYPE {full} histogram")
            typed[full] = "histogram"
        cum = 0
        for bound, c in zip(bounds, counts[:-1]):
            cum += c
            le = 'le="' + _fmt(bound) + '"'
            lines.append(f"{full}_bucket{_prom_labels(m.labels, le)} {cum}")
        inf = 'le="+Inf"'
        lines.append(f"{full}_bucket{_prom_labels(m.labels, inf)} {total}")
        lines.append(f"{full}_sum{_prom_labels(m.labels)} {_fmt(total_sum)}")
        lines.append(f"{full}_count{_prom_labels(m.labels)} {total}")
    # Collector samples are untyped (source decides semantics; most are
    # monotonic counters already named *_total-compatible).
    for name, labels, value in bus._collect():
        full = f"{prefix}_{_sanitize(name)}"
        if typed.get(full) is None:
            lines.append(f"# TYPE {full} untyped")
            typed[full] = "untyped"
        lines.append(f"{full}{_prom_labels(labels)} {_fmt(value)}")
    return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


# --------------------------------------------------------------- event log
class EventLog:
    """Append-only JSONL of structured events. One line per event:
    ``{"t": <unix seconds>, "event": <kind>, ...fields}`` — ``t`` is a
    reported timestamp (wall clock is correct here; intervals come from
    spans). Writes are line-buffered and fsync-free: the log is forensic
    context, not a durability contract (the flight recorder dump is the
    crash artifact)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock = threading.Lock()
        self._f = open(path, "a", buffering=1)

    def emit(self, event: str, **fields) -> None:
        rec = {"t": round(time.time(), 3), "event": event}
        rec.update(fields)
        try:
            line = json.dumps(rec, default=str)
        except (TypeError, ValueError):
            line = json.dumps({"t": rec["t"], "event": event,
                               "error": "unserializable fields"})
        with self._lock:
            if not self._f.closed:
                self._f.write(line + "\n")

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


def timed_iter(iterator, name: str, bus: Optional[MetricsBus] = None, **labels):
    """Wrap an iterator so every ``next()`` is a recorded span — the
    worker loops' host-wait measurement (``host_wait_ms``), replacing the
    ad-hoc ``time.monotonic()`` pairs. Composes outside
    ``io_guard.watch`` (the watchdog arms inside; its arm/disarm costs
    nanoseconds against a real batch wait)."""
    bus = bus if bus is not None else BUS
    it = iter(iterator)
    while True:
        sp = bus.span(name, **labels)
        try:
            item = next(it)
        except StopIteration:
            return  # the end-of-iterator probe is not a batch wait
        sp.end()
        yield item


# ------------------------------------------------------------- process bus
BUS = MetricsBus()


def register_default_collectors(bus: Optional[MetricsBus] = None) -> None:
    """Attach the repo's standing sources to ``bus`` (idempotent): the
    data-plane I/O-guard counters (via ``ops.metrics.data_plane_counters``
    so there is ONE reader of ``io_guard.COUNTERS``)."""
    bus = bus if bus is not None else BUS

    def _data_plane() -> Dict[str, int]:
        from seist_tpu.ops.metrics import data_plane_counters

        return data_plane_counters()

    bus.register_collector("data_plane", _data_plane)
