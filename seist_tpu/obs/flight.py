"""Crash flight recorder: a fixed-size ring of the last N steps' metrics
and span events, dumped to JSON from every death path.

"The run died at step 48k" is not a forensic record; the loader stacks at
the stall, the last 256 steps' host-wait/dispatch spans, the loss trend
into a rollback, and the guard counters at death are. The recorder is
always on in the train worker (a deque append per step — priced with the
rest of the telemetry in BENCH ``step_breakdown.telemetry``), and every
existing death path dumps it:

================================  =======================================
death path                        dump reason
================================  =======================================
bad-update rollback               ``bad_update_rollback`` (run continues)
``io_guard.hard_exit``            ``hard_exit``
stall-watchdog trip               ``stall_watchdog``
SIGTERM preempt exit              ``preempt``
quarantine overflow               ``quarantine_overflow``
loader death                      (reaches ``hard_exit``)
uncaught train-worker exception   ``exception``
================================  =======================================

Dumps land in ``<logdir>/flight/flight_<reason>_<pid>_<seq>.json`` —
pid+seq keeps relaunched supervise attempts from clobbering each other's
record (same contract as the --profile-steps trace dirs). The module
keeps ONE installed recorder (``install``/``get``); death paths call
:func:`dump_on_death`, a no-op when nothing is installed, so library code
(io_guard) stays usable without the obs plane.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Deque, Dict, List, Optional

from seist_tpu.utils.logger import logger


class FlightRecorder:
    """Ring buffer of step records + span events + discrete events.

    ``record_step`` is the per-iteration hot call: one lock, one deque
    append. Spans arrive via the bus sink (:meth:`on_span`) tagged with
    the step current at the time they END, so a dump shows exactly which
    phases the final steps spent their time in.
    """

    def __init__(self, capacity: int = 256):
        if capacity <= 0:
            raise ValueError(f"capacity must be > 0, got {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._steps: Deque[Dict[str, Any]] = collections.deque(maxlen=capacity)
        # Spans outnumber steps (host-wait + dispatch + saves per step);
        # scale the span ring so it covers at least the step window.
        self._spans: Deque[Dict[str, Any]] = collections.deque(
            maxlen=8 * capacity
        )
        self._events: Deque[Dict[str, Any]] = collections.deque(maxlen=128)
        self._current_step: Optional[int] = None
        self._dump_seq = 0

    # ------------------------------------------------------------ record
    def record_step(self, step: int, **fields) -> None:
        # jaxlint: disable=impure-call-in-jit -- never traced: the _step
        # suffix names a ring-buffer record method on the host-side
        # recorder, not a jitted step function; monotonic() must run per
        # call here.
        rec = {"step": int(step), "t_mono": round(time.monotonic(), 6)}
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        with self._lock:
            self._current_step = int(step)
            self._steps.append(rec)

    def on_span(self, span) -> None:
        """Bus span sink (``BUS.add_span_sink(recorder.on_span)``).

        Tagged with the step current when the span ENDS; the worker
        records step N before N's spans close, so dispatch/save spans
        carry their own step. The one convention: the host wait BETWEEN
        steps N-1 and N ends before ``record_step(N)`` runs and is
        tagged N-1 — read host_wait as "the wait after this step"."""
        with self._lock:
            self._spans.append(
                {
                    "name": span.name,
                    "step": self._current_step,
                    "dur_ms": round((span.duration_s or 0.0) * 1e3, 3),
                    **({"labels": span.labels} if span.labels else {}),
                }
            )

    def record_event(self, kind: str, message: str = "", **fields) -> None:
        rec: Dict[str, Any] = {
            "t": round(time.time(), 3),
            "kind": kind,
        }
        if message:
            rec["message"] = message
        with self._lock:
            rec["step"] = self._current_step
        rec.update(fields)
        with self._lock:
            self._events.append(rec)

    # -------------------------------------------------------------- dump
    def payload(self, reason: str, **fields) -> Dict[str, Any]:
        """The dump dict (also served live by the /flight endpoint)."""
        with self._lock:
            steps = list(self._steps)
            spans = list(self._spans)
            events = list(self._events)
            last_step = self._current_step
        out: Dict[str, Any] = {
            "reason": reason,
            "dumped_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "pid": os.getpid(),
            "last_step": last_step,
            "capacity": self.capacity,
            "steps": steps,
            "spans": spans,
            "events": events,
        }
        out.update(fields)
        try:
            from seist_tpu.obs.bus import BUS

            out["metrics"] = BUS.snapshot()
        except Exception as e:  # noqa: BLE001 - the ring is the payload;
            # a sick collector must not lose the crash record
            out["metrics"] = {"error": repr(e)}
        return out

    def dump(
        self, reason: str, path: Optional[str] = None, **fields
    ) -> Optional[str]:
        """Write the JSON dump; returns the path (None when the write
        itself failed — death paths must still exit)."""
        if path is None:
            # The replica ordinal (SEIST_SERVE_REPLICA) disambiguates N
            # fleet members sharing one --logdir; pid+seq already keeps
            # relaunched attempts apart.
            from seist_tpu.obs.trace import replica_suffix

            d = os.path.join(logger.logdir(), "flight")
            with self._lock:
                self._dump_seq += 1
                seq = self._dump_seq
            path = os.path.join(
                d,
                f"flight_{_slug(reason)}{replica_suffix()}"
                f"_{os.getpid()}_{seq}.json",
            )
        try:
            d = os.path.dirname(path) or "."
            os.makedirs(d, exist_ok=True)
            # Atomic publish: serialize into a dotfile (invisible to
            # flight_* globs) and rename into place — a watcher polling
            # for the dump must never read a half-written payload, and
            # the snapshot can be large enough late in a long run for
            # that window to be real.
            tmp = os.path.join(d, "." + os.path.basename(path) + ".tmp")
            with open(tmp, "w") as f:
                json.dump(self.payload(reason, **fields), f, default=str)
            os.replace(tmp, path)
        except OSError as e:
            try:
                logger.error(f"[obs] flight-recorder dump failed: {e!r}")
            except Exception:  # noqa: BLE001 - dying process, best effort
                pass
            return None
        try:
            logger.warning(f"[obs] flight recorder dumped: {path} ({reason})")
        except Exception:  # noqa: BLE001 - dying process, best effort
            pass
        return path


def _slug(s: str) -> str:
    return "".join(c if (c.isalnum() or c in "-_") else "_" for c in s)[:64]


# ------------------------------------------------------- installed recorder
_INSTALLED: Optional[FlightRecorder] = None
_INSTALL_LOCK = threading.Lock()

#: Paths written by dump_on_death this process (newest last) — lets tests
#: and the worker's exit logs point at the artifact.
DUMPED: List[str] = []

_LAST_DUMP_MONO: Optional[float] = None


def install(recorder: Optional[FlightRecorder]) -> Optional[FlightRecorder]:
    """Install ``recorder`` as the process flight recorder (None to
    uninstall); returns the previous one. The train worker installs at
    startup; death paths anywhere in the process then reach it via
    :func:`dump_on_death`.

    Also swaps the recorder in as THE bus span sink: a replaced recorder
    is unhooked, so back-to-back train runs in one process (tests, the
    train→test CLI mode) never stack stale sinks."""
    global _INSTALLED
    with _INSTALL_LOCK:
        prev = _INSTALLED
        _INSTALLED = recorder
    from seist_tpu.obs.bus import BUS

    if prev is not None:
        BUS.remove_span_sink(prev.on_span)
    if recorder is not None:
        BUS.add_span_sink(recorder.on_span)
    return prev


def get() -> Optional[FlightRecorder]:
    return _INSTALLED


def dump_on_death(
    reason: str, dedup_s: float = 0.0, arm_dedup: bool = True, **fields
) -> Optional[str]:
    """Dump the installed recorder (no-op without one). Never raises:
    every caller is a death path where the exit matters more than the
    artifact. ``dedup_s > 0`` skips when another FATAL dump landed
    within that window — the ``hard_exit`` funnel passes it so a path
    that already dumped with a richer reason (stall trip with thread
    stacks) doesn't leave a second, poorer file for the same death.

    ``arm_dedup=False`` marks a NON-fatal dump (bad-update rollback —
    the run continues): it never suppresses a later fatal dump. Without
    this, a rollback followed within seconds by the crash it caused
    would swallow the crash record — the one file carrying the actual
    error."""
    global _LAST_DUMP_MONO
    rec = _INSTALLED
    if rec is None:
        return None
    now = time.monotonic()
    if (
        dedup_s > 0
        and _LAST_DUMP_MONO is not None
        and now - _LAST_DUMP_MONO < dedup_s
    ):
        return None
    if "path" in fields:
        # ``path`` is :meth:`FlightRecorder.dump`'s file-location
        # parameter — a payload field of that name would silently
        # redirect the dump file to an arbitrary location. Remap it.
        fields["path_field"] = fields.pop("path")
    try:
        path = rec.dump(reason, **fields)
    except Exception:  # noqa: BLE001 - death path: the exit must proceed
        return None
    if arm_dedup:
        _LAST_DUMP_MONO = now
    if path:
        DUMPED.append(path)
    return path
