"""Per-request distributed tracing: W3C-``traceparent``-shaped IDs minted
at the edge, span segments recorded in every process a request crosses,
bounded rings with tail-based retention, and ``GET /traces`` exposition.

PR 6 gave every *process* spans; PR 7/10 made serving a *fleet* — and a
900 ms request became unexplainable: nothing tied the router's
retry/hedge attempts to the replica's shed verdict, the batcher's queue
wait and the device program that finally ran. This module is the Dapper
design on top of the existing bus machinery:

* **IDs** — ``00-<32 hex trace-id>-<16 hex span-id>-01`` (the W3C
  ``traceparent`` wire shape). Minted by the first hop that sees the
  request (bench_serve's client, else the router, else the replica) and
  propagated downstream in the ``traceparent`` HTTP header; each hop
  re-parents: the header's span-id becomes the parent of that hop's
  root span.
* :class:`RequestTrace` — one request's span recorder in one process:
  a root span plus children (``with rt.span("parse"):`` or the
  computed-duration form ``rt.add_child("queue_wait", dur_ms, ...)``).
  Segments also render as a ``Server-Timing``-style response header so
  a client sees the breakdown without fetching the trace.
* :class:`TraceBuffer` — the per-process bounded ring (``BUFFER`` is
  the process singleton). Retention is **tail-based**: traces flagged
  ``error`` / ``shed`` / ``retried`` / ``hedged`` / ``slo_breach`` are
  always kept (and evicted last); the rest are down-sampled by a
  **deterministic hash of the trace id** — every process keeps the SAME
  subset, so a sampled-in trace stitches across the whole fleet
  (``SEIST_TRACE_SAMPLE``, default 1.0: keep all, the ring bounds
  memory; drop it for high-QPS fleets).
* **Flush scope** — the micro-batcher serves many requests with ONE
  device forward; :func:`flush_scope` carries the flush's member traces
  through the forward on a thread-local so ``serve/pool.py`` can
  annotate the shared span (program key, AOT-hit, variant) without any
  plumbing through model code.
* ``GET /traces`` (index: ids + flags) and ``GET /traces/<id>`` (the
  span segments) are served by every obs HTTP shim — the train worker's
  ``--metrics-port``, the serve replica and the router.
  ``tools/trace_report.py`` stitches the per-process segments into one
  cross-process tree.

Hot-path cost: one span is two ``monotonic()`` calls and one locked
list append; a full /predict trace (root + ~5 children + commit) is
single-digit microseconds, test-pinned far under 1% of serve-smoke p50
(tests/test_trace.py).
"""

from __future__ import annotations

import contextlib
import os
import re
import secrets
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from seist_tpu.obs.bus import monotonic

#: The propagation header (W3C Trace Context name; we use its 00-...-01
#: shape but do not implement the full spec's tracestate).
TRACEPARENT_HEADER = "traceparent"

#: Tail-retention flags: a trace carrying any of these is always kept
#: and evicted last (docs/OBSERVABILITY.md "Distributed tracing").
#: ``canary_rollback`` marks the request whose settle tripped a canary
#: auto-rollback (serve/router.py) — the rollout post-mortem handle.
FLAGS = ("error", "shed", "retried", "hedged", "slo_breach",
         "canary_rollback")

_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$"
)


def _new_trace_id() -> str:
    return secrets.token_hex(16)


def _new_span_id() -> str:
    return secrets.token_hex(8)


def mint_traceparent() -> str:
    """A fresh edge-minted traceparent (sampled flag always 01 — the
    retention decision is tail-based, per buffer, not head-based)."""
    return f"00-{_new_trace_id()}-{_new_span_id()}-01"


def parse_traceparent(header: Optional[str]) -> Optional[Tuple[str, str]]:
    """-> (trace_id, span_id) or None for a missing/malformed header
    (a malformed header starts a fresh trace rather than erroring the
    request — tracing must never fail traffic)."""
    if not header or not isinstance(header, str):
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    trace_id, span_id = m.group(1), m.group(2)
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None  # all-zero ids are invalid per the W3C shape
    return trace_id, span_id


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


# --------------------------------------------------------- process identity
def replica_ordinal() -> Optional[int]:
    """The fleet ordinal the supervisor assigned this process
    (``SEIST_SERVE_REPLICA``), or None outside a fleet."""
    raw = os.environ.get("SEIST_SERVE_REPLICA", "")
    try:
        return int(raw)
    except ValueError:
        return None


def replica_suffix() -> str:
    """``"_r<N>"`` inside a fleet, else ``""`` — the disambiguator for
    per-replica observability artifacts sharing one ``--logdir``
    (``events_r0.jsonl``, ``flight_<reason>_r0_<pid>_<seq>.json``):
    N replicas must never interleave or clobber one another's files."""
    n = replica_ordinal()
    return f"_r{n}" if n is not None else ""


def process_label() -> str:
    """Default ``process`` tag on recorded spans: ``replica-<N>`` in a
    fleet, else ``proc-<pid>`` (the router overrides with ``router``)."""
    n = replica_ordinal()
    return f"replica-{n}" if n is not None else f"proc-{os.getpid()}"


# --------------------------------------------------------------- the buffer
class _Entry:
    __slots__ = ("spans", "flags", "committed", "created")

    def __init__(self) -> None:
        self.spans: List[Dict[str, Any]] = []
        self.flags: set = set()
        self.committed = False
        self.created = monotonic()


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


class TraceBuffer:
    """Bounded per-process ring of trace span segments with tail-based
    retention. Thread-safe: handler threads, the batcher flush thread and
    scrape threads all touch it concurrently."""

    def __init__(
        self,
        capacity: Optional[int] = None,
        sample: Optional[float] = None,
        max_spans_per_trace: int = 64,
    ):
        if capacity is None:
            capacity = int(_env_float("SEIST_TRACE_CAPACITY", 256))
        if sample is None:
            sample = _env_float("SEIST_TRACE_SAMPLE", 1.0)
        self.capacity = max(1, int(capacity))
        self.sample = min(1.0, max(0.0, float(sample)))
        self.max_spans_per_trace = int(max_spans_per_trace)
        self.process = process_label()
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, _Entry]" = OrderedDict()
        self._kept = 0
        self._dropped = 0
        self._evicted = 0

    # ------------------------------------------------------------ recording
    def add_span(self, trace_id: str, span: Dict[str, Any]) -> None:
        span.setdefault("process", self.process)
        with self._lock:
            entry = self._traces.get(trace_id)
            if entry is None:
                entry = _Entry()
                self._traces[trace_id] = entry
                self._evict_locked()
            if len(entry.spans) < self.max_spans_per_trace:
                entry.spans.append(span)

    def flag(self, trace_id: str, *flags: str) -> None:
        """Flags decide retention, so flagging must work before any span
        was recorded (the router flags 'retried' mid-loop, the handler
        flags 'shed' before the root span closes) — a missing entry is
        created."""
        with self._lock:
            entry = self._traces.get(trace_id)
            if entry is None:
                entry = _Entry()
                self._traces[trace_id] = entry
                self._evict_locked()
            entry.flags.update(flags)

    def flags(self, trace_id: str) -> frozenset:
        with self._lock:
            entry = self._traces.get(trace_id)
            return frozenset(entry.flags) if entry is not None else frozenset()

    def sampled(self, trace_id: str) -> bool:
        """Deterministic keep-verdict from the trace id alone, so every
        process in the fleet keeps the SAME unflagged subset and a kept
        trace always stitches end to end."""
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        try:
            frac = int(trace_id[:8], 16) / float(0xFFFFFFFF)
        except ValueError:
            return False
        return frac < self.sample

    def commit(self, trace_id: str) -> bool:
        """The request is over: decide retention. Flagged traces are
        always kept; unflagged ones survive only the deterministic
        sample. Returns whether the trace was kept."""
        with self._lock:
            entry = self._traces.get(trace_id)
            if entry is None:
                return False
            entry.committed = True
            if not entry.flags and not self.sampled(trace_id):
                del self._traces[trace_id]
                self._dropped += 1
                return False
            self._kept += 1
            self._evict_locked()
            return True

    def _evict_locked(self) -> None:
        while len(self._traces) > self.capacity:
            victim = None
            # Oldest committed-unflagged first, then oldest committed
            # (flagged), then — only if everything is still in flight —
            # the oldest open entry (bounds a leak of never-committed
            # traces).
            for tid, e in self._traces.items():
                if e.committed and not e.flags:
                    victim = tid
                    break
            if victim is None:
                for tid, e in self._traces.items():
                    if e.committed:
                        victim = tid
                        break
            if victim is None:
                victim = next(iter(self._traces))
            del self._traces[victim]
            self._evicted += 1

    # ----------------------------------------------------------- exposition
    def index(self) -> List[Dict[str, Any]]:
        """Newest-first trace index (the GET /traces payload body)."""
        with self._lock:
            items = [
                (tid, list(e.spans), sorted(e.flags), e.committed)
                for tid, e in self._traces.items()
            ]
        out = []
        for tid, spans, flags, committed in reversed(items):
            t0s = [s["t0"] for s in spans]
            ends = [s["t0"] + s["dur_ms"] / 1e3 for s in spans]
            out.append({
                "trace_id": tid,
                "flags": flags,
                "spans": len(spans),
                "committed": committed,
                "t0": min(t0s) if t0s else 0.0,
                "dur_ms": round((max(ends) - min(t0s)) * 1e3, 3)
                if t0s else 0.0,
            })
        return out

    def get(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """The GET /traces/<id> payload: this process's segments."""
        with self._lock:
            entry = self._traces.get(trace_id)
            if entry is None:
                return None
            spans = [dict(s) for s in entry.spans]
            flags = sorted(entry.flags)
        return {
            "trace_id": trace_id,
            "process": self.process,
            "flags": flags,
            "spans": spans,
        }

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "open": sum(
                    1 for e in self._traces.values() if not e.committed
                ),
                "resident": len(self._traces),
                "kept": self._kept,
                "dropped": self._dropped,
                "evicted": self._evicted,
            }

    def reset(self) -> None:
        """Test isolation only."""
        with self._lock:
            self._traces.clear()
            self._kept = self._dropped = self._evicted = 0


#: Process singleton every serve/obs surface records into and every
#: /traces endpoint reads from.
BUFFER = TraceBuffer()


def register_trace_collector(bus=None) -> None:
    """Publish the buffer's retention counters on the metrics bus
    (``seist_trace_*``). Called by the serve/router/train entry points
    (not at import: importing the module must not mutate the bus)."""
    if bus is None:
        from seist_tpu.obs.bus import BUS as bus
    bus.register_collector("trace", BUFFER.stats)


# ----------------------------------------------------------- request traces
def _sanitize_token(name: str) -> str:
    out = "".join(c if (c.isalnum() or c in "_-") else "_" for c in name)
    return out or "span"


class _SpanHandle:
    """Yielded by :meth:`RequestTrace.span`; ``annotate`` adds fields to
    the span while it is open."""

    __slots__ = ("name", "annotations")

    def __init__(self, name: str, annotations: Dict[str, Any]):
        self.name = name
        self.annotations = annotations

    def annotate(self, **fields: Any) -> None:
        self.annotations.update(fields)


class RequestTrace:
    """One request's span recorder in one process.

    Created from the upstream ``traceparent`` header (or minting a fresh
    trace when there is none); the header's span-id becomes this
    process's root-span parent. Children append to the process
    :data:`BUFFER` immediately; :meth:`finish` closes the root span,
    applies status-derived flags and makes the tail-retention decision.
    Thread-safe (the batcher flush thread records children concurrently
    with the handler thread)."""

    def __init__(
        self,
        traceparent: Optional[str] = None,
        name: str = "request",
        buffer: Optional[TraceBuffer] = None,
        process: Optional[str] = None,
        slo_ms: Optional[float] = None,
    ):
        parsed = parse_traceparent(traceparent)
        if parsed is not None:
            self.trace_id, self.upstream_span_id = parsed
            self.minted_here = False
        else:
            self.trace_id = _new_trace_id()
            self.upstream_span_id = None
            self.minted_here = True
        self.root_span_id = _new_span_id()
        self.name = name
        self._buffer = buffer if buffer is not None else BUFFER
        self._process = process
        self._slo_ms = (
            slo_ms
            if slo_ms is not None
            else _env_float("SEIST_TRACE_SLO_MS", 0.0)
        )
        self._lock = threading.Lock()
        self._segments: List[Tuple[str, float]] = []
        self._annotations: Dict[str, Any] = {}
        self._finished = False
        self.dur_ms: Optional[float] = None
        self._t0_mono = monotonic()
        self._t0_wall = time.time()  # timestamp only; intervals are mono

    # ------------------------------------------------------------- identity
    @property
    def traceparent(self) -> str:
        """The header value identifying THIS hop (echoed on responses so
        a client that didn't mint can still fetch the trace)."""
        return format_traceparent(self.trace_id, self.root_span_id)

    def child_header(self) -> str:
        """The header to send downstream: same trace, this hop's root
        span as the parent."""
        return self.traceparent

    # ------------------------------------------------------------ recording
    @contextlib.contextmanager
    def span(self, name: str, **annotations: Any) -> Iterator[_SpanHandle]:
        """Time a child span; exceptions still close (and annotate) it
        before propagating — a shed verdict is exactly an exception path
        we want on the trace."""
        handle = _SpanHandle(name, dict(annotations))
        t0_wall = time.time()
        t0 = monotonic()
        try:
            yield handle
        except BaseException as e:
            handle.annotations.setdefault("error", type(e).__name__)
            raise
        finally:
            self._record(name, (monotonic() - t0) * 1e3, t0_wall,
                         handle.annotations)

    def add_child(
        self,
        name: str,
        dur_ms: float,
        span_id: Optional[str] = None,
        **annotations: Any,
    ) -> None:
        """Record a child span whose duration was measured elsewhere
        (the batcher's queue wait / flush forward). The wall start stamp
        is back-dated by the measured duration. ``span_id`` lets a
        caller that pre-minted the id (the router, whose attempt span id
        went downstream as the replica's parent) keep it."""
        # jaxlint: disable=wallclock-interval -- back-dating a wall-clock
        # TIMESTAMP by a monotonic-measured duration; no interval is ever
        # derived from wall-clock readings here.
        self._record(name, float(dur_ms), time.time() - dur_ms / 1e3,
                     dict(annotations), span_id=span_id)

    def _record(
        self,
        name: str,
        dur_ms: float,
        t0_wall: float,
        annotations: Dict[str, Any],
        span_id: Optional[str] = None,
    ) -> None:
        with self._lock:
            if self._finished:
                # A straggler (an abandoned batcher item flushing after
                # the caller already timed out and finished the trace):
                # the retention verdict is in; drop the late segment.
                return
            self._segments.append((name, dur_ms))
        span = {
            "span_id": span_id or _new_span_id(),
            "parent_id": self.root_span_id,
            "name": name,
            "t0": round(t0_wall, 6),
            "dur_ms": round(dur_ms, 3),
        }
        if annotations:
            span["annotations"] = annotations
        if self._process:
            span["process"] = self._process
        self._buffer.add_span(self.trace_id, span)

    def annotate(self, **fields: Any) -> None:
        with self._lock:
            self._annotations.update(fields)

    def flag(self, *flags: str) -> None:
        with self._lock:
            if self._finished:
                # The retention verdict is in; a late flag (hedge-drain
                # straggler) must not resurrect a dropped trace.
                return
        self._buffer.flag(self.trace_id, *flags)

    # ------------------------------------------------------------- finishing
    def finish(self, status: Optional[int] = None) -> float:
        """Close the root span, derive flags from ``status`` (0/5xx ->
        ``error`` unless the trace is a deliberate ``shed``), check the
        SLO-breach threshold, and commit the retention decision.
        Idempotent."""
        with self._lock:
            if self._finished:
                return self.dur_ms or 0.0
            self._finished = True
            dur_ms = (monotonic() - self._t0_mono) * 1e3
            self.dur_ms = dur_ms
            annotations = dict(self._annotations)
        if status is not None:
            annotations["status"] = int(status)
        span = {
            "span_id": self.root_span_id,
            "parent_id": self.upstream_span_id,
            "name": self.name,
            "t0": round(self._t0_wall, 6),
            "dur_ms": round(dur_ms, 3),
            "root": True,
        }
        if annotations:
            span["annotations"] = annotations
        if self._process:
            span["process"] = self._process
        self._buffer.add_span(self.trace_id, span)
        if status is not None and (status == 0 or status >= 500):
            # A shed 503 is a deliberate policy verdict, not a failure;
            # it keeps its own flag.
            if "shed" not in self._buffer.flags(self.trace_id):
                self._buffer.flag(self.trace_id, "error")
        if self._slo_ms > 0 and dur_ms > self._slo_ms:
            self._buffer.flag(self.trace_id, "slo_breach")
        self._buffer.commit(self.trace_id)
        return dur_ms

    def server_timing(self) -> str:
        """``Server-Timing``-style header value: ``total`` plus every
        recorded child segment, millisecond durations."""
        with self._lock:
            segments = list(self._segments)
            total = (
                self.dur_ms
                if self.dur_ms is not None
                else (monotonic() - self._t0_mono) * 1e3
            )
        parts = [f"total;dur={total:.1f}"]
        parts.extend(
            f"{_sanitize_token(name)};dur={dur:.1f}"
            for name, dur in segments
        )
        return ", ".join(parts)


class NullTrace:
    """No-op stand-in so instrumented call sites never branch on ``if
    trace is not None`` (offline tools, tests, untraced requests)."""

    trace_id = ""
    root_span_id = ""
    minted_here = False

    @contextlib.contextmanager
    def span(self, name: str, **annotations: Any) -> Iterator[_SpanHandle]:
        yield _SpanHandle(name, {})

    def add_child(self, name: str, dur_ms: float, **annotations) -> None:
        pass

    def annotate(self, **fields: Any) -> None:
        pass

    def flag(self, *flags: str) -> None:
        pass

    def finish(self, status: Optional[int] = None) -> float:
        return 0.0

    def server_timing(self) -> str:
        return ""

    def child_header(self) -> str:
        return ""


NULL = NullTrace()


def ensure(trace: Optional[RequestTrace]) -> Any:
    """``trace or NULL`` with the type spelled out at call sites."""
    return trace if trace is not None else NULL


# -------------------------------------------------------------- flush scope
class _FlushScope:
    """One micro-batch flush's trace set + shared annotations (filled by
    serve/pool.py while the forward runs)."""

    __slots__ = ("traces", "annotations")

    def __init__(self, traces: Sequence[Any]):
        self.traces = [t for t in traces if t is not None]
        self.annotations: Dict[str, Any] = {}


_TLS = threading.local()


@contextlib.contextmanager
def flush_scope(traces: Sequence[Any]) -> Iterator[_FlushScope]:
    """Carry a flush's member traces through the batched forward on a
    thread-local, so device-side code (pool programs) can annotate the
    shared span without threading trace objects through model code.
    Nests (an /annotate window loop inside a flush keeps the outer
    scope on exit)."""
    scope = _FlushScope(traces)
    prev = getattr(_TLS, "scope", None)
    _TLS.scope = scope
    try:
        yield scope
    finally:
        _TLS.scope = prev


def annotate_flush(**fields: Any) -> None:
    """Attach fields to the current flush's shared forward span (no-op
    outside a flush — warm-up, offline tools, the train plane)."""
    scope = getattr(_TLS, "scope", None)
    if scope is not None:
        scope.annotations.update(fields)


def in_flush() -> bool:
    return getattr(_TLS, "scope", None) is not None


# ------------------------------------------------------------ HTTP payloads
def handle_traces_path(
    path: str, buffer: Optional[TraceBuffer] = None
) -> Optional[Tuple[int, Dict[str, Any]]]:
    """Shared routing for the ``/traces`` endpoints across the three HTTP
    shims (serve replica, router, train ``--metrics-port``): returns
    ``(status, json_payload)`` for a trace route, ``None`` when ``path``
    is not one. Query strings are stripped uniformly — one place decides
    the trace-id parse, so the shims cannot drift."""
    p = path.split("?", 1)[0]
    if p == "/traces":
        return 200, index_payload(buffer)
    if p.startswith("/traces/"):
        payload = trace_payload(p[len("/traces/"):], buffer)
        if payload is None:
            return 404, {"error": "unknown_trace", "message": p}
        return 200, payload
    return None


def index_payload(buffer: Optional[TraceBuffer] = None) -> Dict[str, Any]:
    buffer = buffer if buffer is not None else BUFFER
    return {
        "process": buffer.process,
        "sample": buffer.sample,
        "capacity": buffer.capacity,
        "stats": buffer.stats(),
        "traces": buffer.index(),
    }


def trace_payload(
    trace_id: str, buffer: Optional[TraceBuffer] = None
) -> Optional[Dict[str, Any]]:
    buffer = buffer if buffer is not None else BUFFER
    return buffer.get(trace_id)
