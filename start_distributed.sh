#!/usr/bin/env bash
# Multi-host launch (ref start_distributed.sh, which used torchrun).
#
# On Cloud TPU pods, run the same command on every worker VM; JAX discovers
# the topology from TPU metadata:
#   python main.py --model-name seist_m_dpk ...
#
# Off-TPU (or forcing an explicit rendezvous), set the env contract of
# seist_tpu/parallel/dist.py on each process:
#   COORDINATOR_ADDRESS=host0:1234 NUM_PROCESSES=2 PROCESS_ID=$i \
#     python main.py ...
set -e
: "${NUM_PROCESSES:?set NUM_PROCESSES (and COORDINATOR_ADDRESS, PROCESS_ID per worker)}"
python main.py "$@"
