#!/usr/bin/env bash
# Single-host launch (ref start.sh). All visible TPU chips join the data mesh.
nohup python main.py "$@" > /dev/null 2>&1 &
