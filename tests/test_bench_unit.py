"""Pure-unit tests for bench.py's measurement/replay machinery.

The cached-replay path has lost rounds before (round 1: in-process hang;
round 3: the only recorded number WAS a replay), so its attribution rules
— a cached number must never be replayed for a different configuration —
are locked here. No backend is touched: bench.py's module level imports
only the stdlib.
"""

import importlib
import json
import os
import time

import pytest


@pytest.fixture()
def bench(tmp_path, monkeypatch):
    import bench as bench_mod

    bench_mod = importlib.reload(bench_mod)
    # Redirect both cache locations into the sandbox.
    write = str(tmp_path / "logs" / "last_bench.json")
    monkeypatch.setattr(bench_mod, "_CACHE_WRITE", write)
    monkeypatch.setattr(bench_mod, "_CACHE_READ", (write,))
    return bench_mod


def _emitted(capsys):
    return json.loads(capsys.readouterr().out.strip().splitlines()[-1])


def test_emit_and_cache_is_metric_keyed(bench, capsys):
    bench._emit_and_cache({"metric": "a_train_throughput", "value": 1.0})
    bench._emit_and_cache({"metric": "a_eval_throughput", "value": 2.0})
    with open(bench._CACHE_WRITE) as f:
        entries = json.load(f)
    # An eval run must not evict the train entry (round-3 regression).
    assert set(entries) == {"a_train_throughput", "a_eval_throughput"}


def test_fail_replays_only_matching_config(bench, capsys):
    payload = {
        "metric": "m_train_throughput",
        "value": 123.0,
        "unit": "waveforms/sec/chip",
        "dtype": "bf16",
        "batch": 512,
        "in_samples": 8192,
        "steps_per_call": 1,
    }
    bench._emit_and_cache(payload)
    capsys.readouterr()

    # Same config -> replay, marked cached with the error attached.
    bench._fail(
        "m_train_throughput",
        "waveforms/sec/chip",
        "backend unavailable",
        config={"dtype": "bf16", "batch": 512, "in_samples": 8192,
                "steps_per_call": 1},
    )
    out = _emitted(capsys)
    assert out["value"] == 123.0
    assert out["cached"] is True
    assert out["error"] == "backend unavailable"

    # ANY differing key (dtype here) -> no replay, honest zero — stamped
    # with an EXPLICIT cached=False (schema v2: absence of the marker
    # must never read as freshness).
    bench._fail(
        "m_train_throughput",
        "waveforms/sec/chip",
        "backend unavailable",
        config={"dtype": "fp32", "batch": 512, "in_samples": 8192,
                "steps_per_call": 1},
    )
    out = _emitted(capsys)
    assert out["value"] == 0 and out["cached"] is False
    assert out["schema_version"] == bench._SCHEMA_VERSION


def test_fail_stream_config_includes_stride_and_record(bench, capsys):
    # Stream payloads carry stride/record_seconds; a replay for a run at a
    # different stride would misattribute throughput (stride halving
    # nearly doubles the windows per record-second).
    bench._emit_and_cache(
        {
            "metric": "m_stream_throughput",
            "value": 900.0,
            "unit": "record-seconds/sec",
            "batch": 32,
            "in_samples": 8192,
            "stride": 4096,
            "record_seconds": 600,
        }
    )
    capsys.readouterr()
    bench._fail(
        "m_stream_throughput",
        "record-seconds/sec",
        "down",
        config={"batch": 32, "in_samples": 8192, "stride": 512,
                "record_seconds": 600},
    )
    assert _emitted(capsys)["value"] == 0
    bench._fail(
        "m_stream_throughput",
        "record-seconds/sec",
        "down",
        config={"batch": 32, "in_samples": 8192, "stride": 4096,
                "record_seconds": 600},
    )
    out = _emitted(capsys)
    assert out["value"] == 900.0 and out["cached"] is True


def test_peak_flops_non_tpu_is_zero(bench):
    # A CPU debug run must not fabricate an MFU against a TPU peak.
    assert bench._peak_flops("cpu") == 0.0
    assert bench._peak_flops("TPU v5 lite") == 197e12
    assert bench._peak_flops("TPU v4") == 275e12
    assert bench._peak_flops("some new TPU kind") == 197e12  # conservative


def test_roofline_context(bench):
    # seist_l-ish numbers: 870 GFLOP/step, 30 GB accessed -> intensity 29
    # vs v5e ridge 240 -> memory-bound, MFU ceiling ~12%.
    r = bench._roofline(8.7e11, 3.0e10, "TPU v5 lite")
    assert r["memory_bound"] is True
    assert r["arithmetic_intensity"] == 29.0
    assert 0.1 < r["mfu_bound"] < 0.15
    # Compute-bound case caps at 1.0.
    r = bench._roofline(1e12, 1e9, "TPU v5 lite")
    assert r["memory_bound"] is False and r["mfu_bound"] == 1.0
    # Unavailable inputs (CPU debug run, no cost analysis) -> None.
    assert bench._roofline(0.0, 3e10, "TPU v5 lite") is None
    assert bench._roofline(8.7e11, 3.0e10, "cpu") is None


def test_replay_rekeyed_to_current_schema(bench, capsys, monkeypatch):
    # VERDICT r4 #4: a cached replay recorded under an OLD schema must be
    # re-emitted under the current one — anchor-based vs_baseline, a
    # kernel_status placeholder, and a staleness marker — never the
    # retired torch-CPU ratio.
    old_entry = {
        "metric": "seist_l_dpk_train_throughput",
        "value": 2799.32,
        "unit": "waveforms/sec/chip",
        "vs_baseline": 287.7,  # retired torch-CPU-1core ratio
        "flops_per_waveform": 1698576640,
        "mfu": 0.0241,
        "dtype": "bf16",
        "batch": 512,
        "in_samples": 8192,
        "steps_per_call": 1,
        "measured_at": "2026-07-31T04:28:44Z",
    }
    bench._emit_and_cache(dict(old_entry))
    capsys.readouterr()
    bench._fail(
        "seist_l_dpk_train_throughput",
        "waveforms/sec/chip",
        "backend unavailable",
        config={"dtype": "bf16", "batch": 512, "in_samples": 8192,
                "steps_per_call": 1},
    )
    out = _emitted(capsys)
    assert out["cached"] is True and out["value"] == 2799.32
    # Recomputed against the frozen A100 anchor: wfs*flops/anchor ~ 0.508.
    want = round(2799.32 * 1698576640 / bench._A100_ANCHOR_FLOPS, 3)
    assert out["vs_baseline"] == want and 0.4 < want < 0.6
    assert out["kernel_status"] == "unknown(cached)"
    assert out["stale_since"] == "2026-07-31T04:28:44Z"
    assert out["age_hours"] > 0
    assert out["a100_analytical_wfs"] is not None


def test_replay_nulls_unrecomputable_ratio(bench, capsys):
    # An old-schema entry with NO flops_per_waveform cannot be re-anchored;
    # the retired ratio must be moved aside, never left leading.
    bench._emit_and_cache(
        {
            "metric": "m_train_throughput",
            "value": 100.0,
            "unit": "waveforms/sec/chip",
            "vs_baseline": 287.7,
            "batch": 512,
        }
    )
    capsys.readouterr()
    bench._fail(
        "m_train_throughput", "waveforms/sec/chip", "down",
        config={"batch": 512},
    )
    out = _emitted(capsys)
    assert out["vs_baseline"] is None
    assert out["vs_baseline_legacy"] == 287.7


def test_config_keyed_entry_survives_sweep_overwrite(bench, capsys):
    # VERDICT r4 #5: a later sweep at another batch must not evict the
    # headline entry — the (metric, config) key preserves it.
    headline_cfg = {"dtype": "bf16", "batch": 512, "in_samples": 8192,
                    "steps_per_call": 1}
    sweep_cfg = dict(headline_cfg, batch=256)
    bench._emit_and_cache(
        {"metric": "m_train_throughput", "value": 100.0, "unit": "u",
         **headline_cfg},
        config=headline_cfg,
    )
    bench._emit_and_cache(
        {"metric": "m_train_throughput", "value": 55.0, "unit": "u",
         **sweep_cfg},
        config=sweep_cfg,
    )
    capsys.readouterr()
    bench._fail("m_train_throughput", "u", "down", config=headline_cfg)
    out = _emitted(capsys)
    assert out["value"] == 100.0 and out["batch"] == 512
    bench._fail("m_train_throughput", "u", "down", config=sweep_cfg)
    assert _emitted(capsys)["value"] == 55.0


def test_lowering_override_gets_own_cache_key(bench, monkeypatch):
    # A sweep that forces a non-default lowering (SEIST_CHANNEL_PAD,
    # SEIST_GCONV_IMPL, ...) compiles a DIFFERENT program; it must write
    # under its own cache key, never the default-lowering headline's
    # (observed live 2026-08-02: iso_chanpad_128 overwrote the headline).
    monkeypatch.delenv("SEIST_CHANNEL_PAD", raising=False)
    plain = bench.env_config()
    assert plain["lowering_overrides"] == {}
    monkeypatch.setenv("SEIST_CHANNEL_PAD", "128")
    padded = bench.env_config()
    assert padded["lowering_overrides"] == {"SEIST_CHANNEL_PAD": "128"}
    key = bench._config_key
    assert key("m", plain) != key("m", padded)
    # stream-mode config carries the overrides too
    assert bench.stream_config()["lowering_overrides"] == {
        "SEIST_CHANNEL_PAD": "128"
    }


def test_degraded_flag_and_enforcement(bench, monkeypatch, capsys):
    # VERDICT r4 #5: an einsum fallback on TPU must be loud, not a silent
    # -105% in the number.
    fused = {"overall": "fused", "signatures": {}}
    fallen = {"overall": "einsum-fallback", "signatures": {}}
    unprobed = {"overall": "unprobed", "signatures": {}}
    assert bench._degraded("TPU v5 lite", fallen) is True
    assert bench._degraded("TPU v5 lite", fused) is False
    # attention-free models never probe; that is not a degradation
    assert bench._degraded("TPU v5 lite", unprobed) is False
    assert bench._degraded("cpu", fallen) is False

    bench._enforce_fused({"degraded": False})  # no-op
    monkeypatch.setenv("BENCH_REQUIRE_FUSED", "1")
    with pytest.raises(SystemExit) as exc:
        bench._enforce_fused({"degraded": True, "kernel_status": fallen})
    assert exc.value.code == 3
    monkeypatch.delenv("BENCH_REQUIRE_FUSED")
    bench._enforce_fused({"degraded": True, "kernel_status": fallen})  # warns only


def test_tunnel_known_down_collapses_probe_ladder(
    bench, tmp_path, monkeypatch
):
    # VERDICT r4 #9: a fresh 'probe N down' line in a watcher log must
    # collapse the 3x180s ladder to one fast attempt.
    tools_dir = tmp_path / "tools"
    tools_dir.mkdir(exist_ok=True)
    monkeypatch.setattr(bench, "_REPO", str(tmp_path))
    assert bench._tunnel_known_down() is False  # no logs at all
    iso = "%Y-%m-%dT%H:%M:%SZ"
    now_z = time.strftime(iso, time.gmtime())
    old_z = time.strftime(iso, time.gmtime(time.time() - 3600))
    log = tools_dir / "r5_watch.log"
    log.write_text(f"probe 1 down {old_z}\nprobe 2 down {now_z}\n")
    assert bench._tunnel_known_down() is True
    # A stale log (old mtime) is no signal.
    old = time.time() - 3600
    os.utime(log, (old, old))
    assert bench._tunnel_known_down() is False
    # Fresh mtime (e.g. a git checkout of the tracked log) but an OLD line
    # timestamp is no signal either — the line's own clock must agree.
    log.write_text(f"probe 1 down {old_z}\n")
    assert bench._tunnel_known_down() is False
    # Legacy HH:MM:SS-only stamps are never trusted: the same wall-clock
    # window recurs every day, so they cannot prove freshness.
    log.write_text("probe 1 down " + time.strftime("%H:%M:%SZ", time.gmtime()))
    assert bench._tunnel_known_down() is False
    # A log whose last line is the probe loop's TUNNEL UP is no signal.
    log.write_text(f"probe 1 down {old_z}\nTUNNEL UP {now_z}\n")
    assert bench._tunnel_known_down() is False
    # Probe honors the signal unless BENCH_PROBE_* is explicit.
    log.write_text(f"probe 9 down {now_z}\n")
    calls = {}

    def fake_run(cmd, **kw):
        calls["timeout"] = kw.get("timeout")
        calls["n"] = calls.get("n", 0) + 1

        class R:
            returncode = 1
            stdout = ""
            stderr = "down"

        return R()

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.delenv("BENCH_PROBE_ATTEMPTS", raising=False)
    monkeypatch.delenv("BENCH_PROBE_TIMEOUT", raising=False)
    assert bench.probe_backend() is None
    assert calls == {"timeout": 60, "n": 1}
    monkeypatch.setenv("BENCH_PROBE_ATTEMPTS", "2")
    monkeypatch.setenv("BENCH_PROBE_TIMEOUT", "5")
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    calls.clear()
    assert bench.probe_backend() is None
    assert calls == {"timeout": 5, "n": 2}


def test_vs_baseline_rejects_mismatched_length(bench, tmp_path, monkeypatch):
    tools_dir = tmp_path / "tools"
    tools_dir.mkdir()
    (tools_dir / "reference_baseline.json").write_text(
        json.dumps(
            {
                "per_model": {
                    "m": {"waveforms_per_sec": 10.0, "in_samples": 8192}
                }
            }
        )
    )
    monkeypatch.setattr(bench, "_REPO", str(tmp_path))
    # wf/s scales inversely with length: an 8192-sample baseline must not
    # be compared against a 512-sample run.
    assert bench._vs_baseline(100.0, "m", 8192) == 10.0
    assert bench._vs_baseline(100.0, "m", 512) == 0.0


def test_ab_summary_parses_runner_log(tmp_path):
    # tools/ab_summary.py: the promote-or-revert view of a silicon log.
    sys_path_hack = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    import sys

    if sys_path_hack not in sys.path:
        sys.path.insert(0, sys_path_hack)
    from tools.ab_summary import summarize

    log = tmp_path / "ab.log"
    log.write_text(
        "r4_silicon start 2026-08-01T10:00:00Z HEAD=abc\n"
        "=== headline 2026-08-01T10:00:01Z\n"
        '{"metric": "m", "value": 3100.5, "unit": "wf/s", '
        '"kernel_status": {"overall": "fused"}, "batch": 512}\n'
        "STATUS ok headline\n"
        "STATUS skip iso_y\n"
        "=== iso_x 2026-08-01T10:08:21Z\n"
        '{"metric": "m", "value": 10.0, "unit": "wf/s", "cached": true, '
        '"degraded": true}\n'
        "STATUS fail iso_x rc=3\n"
        "=== matrix 2026-08-01T10:10:21Z\n"
        '{"metric": "a", "value": 1.0, "unit": "wf/s"}\n'
        '{"metric": "b", "value": 2.0, "unit": "wf/s"}\n'
        "STATUS ok matrix\n"
        "R4 ALL DONE 2026-08-01T10:30:00Z\n"
        # A later append-mode run must not inherit durations from run 1.
        "r4_silicon start 2026-08-02T09:00:00Z HEAD=def\n"
        "=== headline 2026-08-02T09:00:05Z\n"
        "STATUS ok headline\n"
    )
    rows = summarize(str(log))
    assert [r["tag"] for r in rows] == [
        "headline", "iso_y", "iso_x", "matrix", "headline"
    ]
    head, skip, iso, matrix, head2 = rows
    assert head["status"] == "ok" and head["value"] == 3100.5
    assert head["kernel"] == "fused" and head["seconds"] == 500
    # Skipped steps are VISIBLE (distinguishable from never-reached).
    assert skip["status"] == "skip" and skip["value"] is None
    assert iso["status"] == "fail"
    assert iso["cached"] is True and iso["degraded"] is True
    # Multi-JSON (matrix) sections surface the count, show the last.
    assert matrix["json_count"] == 2 and matrix["value"] == 2.0
    # Duration bounded by the ALL DONE boundary, not the next run.
    assert matrix["seconds"] == (30 - 10) * 60 - 21
    # Final step of the log: no end marker -> honest blank, never the
    # next day's run.
    assert head2["seconds"] is None


# ------------------------------------------------ probe-vs-cache (ISSUE 10)
def _seed_train_cache(bench, capsys, monkeypatch):
    """Cache a successful run for the CURRENT env_config so main() can
    resolve a replay before probing."""
    for var in list(os.environ):
        if var.startswith("BENCH_"):
            monkeypatch.delenv(var, raising=False)
    config = {k: v for k, v in bench.env_config().items() if k != "model"}
    metric = f"{bench.env_config()['model']}_train_throughput"
    payload = {"metric": metric, "value": 42.0,
               "unit": "waveforms/sec/chip", **config}
    bench._emit_and_cache(payload, config=config)
    capsys.readouterr()
    return metric


def test_probe_skipped_entirely_when_cached_and_tunnel_down(
    bench, capsys, monkeypatch
):
    # BENCH_r04 burned 3x180 s probe timeouts + backoff to emit a cached
    # payload: with a replay in hand AND a fresh tunnel-down signal, the
    # probe must not run AT ALL.
    _seed_train_cache(bench, capsys, monkeypatch)
    monkeypatch.setattr(bench, "_tunnel_known_down", lambda *a, **k: True)
    monkeypatch.setattr(
        bench, "probe_backend",
        lambda *a, **k: pytest.fail("probe ran despite cached replay"),
    )
    bench.main()
    out = _emitted(capsys)
    assert out["cached"] is True and out["value"] == 42.0
    assert "probe skipped" in out["error"]


def test_probe_ladder_collapses_to_one_short_attempt_when_cached(
    bench, capsys, monkeypatch
):
    # Replay available but no down-signal: still try for a fresh number,
    # with ONE short attempt instead of the 3x180 s ladder.
    _seed_train_cache(bench, capsys, monkeypatch)
    monkeypatch.setattr(bench, "_tunnel_known_down", lambda *a, **k: False)
    seen = {}

    def fake_probe(attempts=None, timeout=None):
        seen["args"] = (attempts, timeout)
        fake_probe.last_attempts = attempts
        return None

    monkeypatch.setattr(bench, "probe_backend", fake_probe)
    bench.main()
    out = _emitted(capsys)
    assert seen["args"] == (1, 60)
    assert out["cached"] is True and out["value"] == 42.0
    # Explicit BENCH_PROBE_* env always wins over the collapse: main()
    # hands the ladder back to probe_backend's own env handling.
    monkeypatch.setenv("BENCH_PROBE_ATTEMPTS", "3")
    seen.clear()
    bench.main()
    capsys.readouterr()
    assert seen["args"] == (None, None)


def test_no_cache_keeps_full_probe_ladder(bench, capsys, monkeypatch):
    for var in list(os.environ):
        if var.startswith("BENCH_"):
            monkeypatch.delenv(var, raising=False)
    monkeypatch.setattr(bench, "_tunnel_known_down", lambda *a, **k: False)
    seen = {}

    def fake_probe(attempts=None, timeout=None):
        seen["args"] = (attempts, timeout)
        fake_probe.last_attempts = attempts or 3
        return None

    monkeypatch.setattr(bench, "probe_backend", fake_probe)
    bench.main()
    out = _emitted(capsys)
    assert seen["args"] == (None, None)  # default ladder untouched
    assert out["cached"] is False and out["value"] == 0


# ------------------------------------- stale-watcher quarantine (ISSUE 10)
def test_stale_watcher_warns_once_then_quarantines(
    bench, tmp_path, capsys
):
    stale = tmp_path / "ab_results.log"
    stale.write_text("runner start Thu Jul 30\n| row |\n")
    done = tmp_path / "ab_done.log"
    done.write_text("watcher start\nALL DONE\n")
    fresh = tmp_path / "ab_fresh.log"
    fresh.write_text("watcher start\n")
    old = time.time() - 3600
    os.utime(stale, (old, old))
    os.utime(done, (old, old))

    bench._warn_stale_watcher_queues(str(tmp_path))
    err = capsys.readouterr().err
    assert "stale watcher queue" in err and "quarantined" in err
    # In-band quarantine: the file stays put (consumers read it by name,
    # and renaming would race a watcher that was merely slow) with an
    # appended ABANDONED terminal marker; content preserved; the
    # finished and the fresh (mid-run) logs untouched.
    text = stale.read_text()
    assert "| row |" in text and "ABANDONED" in text
    assert "ALL DONE" in done.read_text().splitlines()[-1]
    assert fresh.read_text() == "watcher start\n"

    # Second run: the marker terminates the last start — noise is gone.
    old = time.time() - 3600
    os.utime(stale, (old, old))
    bench._warn_stale_watcher_queues(str(tmp_path))
    assert "stale watcher queue" not in capsys.readouterr().err

    # A NEW watcher appending a fresh `start` re-arms detection.
    with open(stale, "a") as f:
        f.write("watcher start again\n")
    os.utime(stale, (old, old))
    bench._warn_stale_watcher_queues(str(tmp_path))
    assert "stale watcher queue" in capsys.readouterr().err


def test_explicit_probe_env_beats_replay_shortcuts(bench, capsys, monkeypatch):
    # An operator forcing a fresh measurement (BENCH_PROBE_*) must get
    # the full ladder even when a replay exists AND the tunnel is known
    # down — neither shortcut may swallow the explicit request.
    _seed_train_cache(bench, capsys, monkeypatch)
    monkeypatch.setenv("BENCH_PROBE_ATTEMPTS", "5")
    monkeypatch.setattr(bench, "_tunnel_known_down", lambda *a, **k: True)
    seen = {}

    def fake_probe(attempts=None, timeout=None):
        seen["args"] = (attempts, timeout)
        fake_probe.last_attempts = attempts or 5
        return None

    monkeypatch.setattr(bench, "probe_backend", fake_probe)
    bench.main()
    capsys.readouterr()
    assert seen["args"] == (None, None)  # probe ran, env-driven ladder
