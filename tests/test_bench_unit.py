"""Pure-unit tests for bench.py's measurement/replay machinery.

The cached-replay path has lost rounds before (round 1: in-process hang;
round 3: the only recorded number WAS a replay), so its attribution rules
— a cached number must never be replayed for a different configuration —
are locked here. No backend is touched: bench.py's module level imports
only the stdlib.
"""

import importlib
import json
import os

import pytest


@pytest.fixture()
def bench(tmp_path, monkeypatch):
    import bench as bench_mod

    bench_mod = importlib.reload(bench_mod)
    # Redirect both cache locations into the sandbox.
    write = str(tmp_path / "logs" / "last_bench.json")
    monkeypatch.setattr(bench_mod, "_CACHE_WRITE", write)
    monkeypatch.setattr(bench_mod, "_CACHE_READ", (write,))
    return bench_mod


def _emitted(capsys):
    return json.loads(capsys.readouterr().out.strip().splitlines()[-1])


def test_emit_and_cache_is_metric_keyed(bench, capsys):
    bench._emit_and_cache({"metric": "a_train_throughput", "value": 1.0})
    bench._emit_and_cache({"metric": "a_eval_throughput", "value": 2.0})
    with open(bench._CACHE_WRITE) as f:
        entries = json.load(f)
    # An eval run must not evict the train entry (round-3 regression).
    assert set(entries) == {"a_train_throughput", "a_eval_throughput"}


def test_fail_replays_only_matching_config(bench, capsys):
    payload = {
        "metric": "m_train_throughput",
        "value": 123.0,
        "unit": "waveforms/sec/chip",
        "dtype": "bf16",
        "batch": 512,
        "in_samples": 8192,
        "steps_per_call": 1,
    }
    bench._emit_and_cache(payload)
    capsys.readouterr()

    # Same config -> replay, marked cached with the error attached.
    bench._fail(
        "m_train_throughput",
        "waveforms/sec/chip",
        "backend unavailable",
        config={"dtype": "bf16", "batch": 512, "in_samples": 8192,
                "steps_per_call": 1},
    )
    out = _emitted(capsys)
    assert out["value"] == 123.0
    assert out["cached"] is True
    assert out["error"] == "backend unavailable"

    # ANY differing key (dtype here) -> no replay, honest zero.
    bench._fail(
        "m_train_throughput",
        "waveforms/sec/chip",
        "backend unavailable",
        config={"dtype": "fp32", "batch": 512, "in_samples": 8192,
                "steps_per_call": 1},
    )
    out = _emitted(capsys)
    assert out["value"] == 0 and "cached" not in out


def test_fail_stream_config_includes_stride_and_record(bench, capsys):
    # Stream payloads carry stride/record_seconds; a replay for a run at a
    # different stride would misattribute throughput (stride halving
    # nearly doubles the windows per record-second).
    bench._emit_and_cache(
        {
            "metric": "m_stream_throughput",
            "value": 900.0,
            "unit": "record-seconds/sec",
            "batch": 32,
            "in_samples": 8192,
            "stride": 4096,
            "record_seconds": 600,
        }
    )
    capsys.readouterr()
    bench._fail(
        "m_stream_throughput",
        "record-seconds/sec",
        "down",
        config={"batch": 32, "in_samples": 8192, "stride": 512,
                "record_seconds": 600},
    )
    assert _emitted(capsys)["value"] == 0
    bench._fail(
        "m_stream_throughput",
        "record-seconds/sec",
        "down",
        config={"batch": 32, "in_samples": 8192, "stride": 4096,
                "record_seconds": 600},
    )
    out = _emitted(capsys)
    assert out["value"] == 900.0 and out["cached"] is True


def test_peak_flops_non_tpu_is_zero(bench):
    # A CPU debug run must not fabricate an MFU against a TPU peak.
    assert bench._peak_flops("cpu") == 0.0
    assert bench._peak_flops("TPU v5 lite") == 197e12
    assert bench._peak_flops("TPU v4") == 275e12
    assert bench._peak_flops("some new TPU kind") == 197e12  # conservative


def test_roofline_context(bench):
    # seist_l-ish numbers: 870 GFLOP/step, 30 GB accessed -> intensity 29
    # vs v5e ridge 240 -> memory-bound, MFU ceiling ~12%.
    r = bench._roofline(8.7e11, 3.0e10, "TPU v5 lite")
    assert r["memory_bound"] is True
    assert r["arithmetic_intensity"] == 29.0
    assert 0.1 < r["mfu_bound"] < 0.15
    # Compute-bound case caps at 1.0.
    r = bench._roofline(1e12, 1e9, "TPU v5 lite")
    assert r["memory_bound"] is False and r["mfu_bound"] == 1.0
    # Unavailable inputs (CPU debug run, no cost analysis) -> None.
    assert bench._roofline(0.0, 3e10, "TPU v5 lite") is None
    assert bench._roofline(8.7e11, 3.0e10, "cpu") is None


def test_vs_baseline_rejects_mismatched_length(bench, tmp_path, monkeypatch):
    tools_dir = tmp_path / "tools"
    tools_dir.mkdir()
    (tools_dir / "reference_baseline.json").write_text(
        json.dumps(
            {
                "per_model": {
                    "m": {"waveforms_per_sec": 10.0, "in_samples": 8192}
                }
            }
        )
    )
    monkeypatch.setattr(bench, "_REPO", str(tmp_path))
    # wf/s scales inversely with length: an 8192-sample baseline must not
    # be compared against a 512-sample run.
    assert bench._vs_baseline(100.0, "m", 8192) == 10.0
    assert bench._vs_baseline(100.0, "m", 512) == 0.0
