"""Tests for seist_tpu.ops.postprocess against hand fixtures and an
independent numpy re-derivation of the reference algorithms
(training/postprocess.py:15-158 semantics)."""

import numpy as np
import pytest

from seist_tpu.ops import postprocess as pp

PAD = pp.PAD_VALUE


def ref_peaks(x, mph, mpd, topk):
    """Host-side re-derivation of the BMC detect_peaks subset the pipeline
    uses (edge='rising', threshold=0, kpsh=False, valley=False; ref
    postprocess.py:51-111)."""
    x = np.asarray(x, dtype=np.float32)
    dx = x[1:] - x[:-1]
    dxn = np.concatenate([dx, [0.0]])
    dxp = np.concatenate([[0.0], dx])
    ind = np.where((dxn <= 0) & (dxp > 0))[0]
    if ind.size and ind[0] == 0:
        ind = ind[1:]
    if ind.size and ind[-1] == x.size - 1:
        ind = ind[:-1]
    if ind.size:
        ind = ind[x[ind] >= mph]
    if ind.size and mpd > 1:
        ind = ind[np.argsort(x[ind], kind="stable")][::-1]
        ind = ind[:topk]
        idel = np.zeros(ind.size, dtype=bool)
        for i in range(ind.size):
            if not idel[i]:
                idel = idel | (ind >= ind[i] - mpd) & (ind <= ind[i] + mpd)
                idel[i] = False
        ind = np.sort(ind[~idel])
    out = np.full(topk, PAD, dtype=np.int64)
    out[: min(ind.size, topk)] = ind[:topk]
    return out


def ref_events(x, thr, topk):
    """Maximal runs of x > thr, sorted by duration desc (stable),
    truncated/padded to topk with [1, 0] (ref postprocess.py:114-158 with
    obspy trigger_onset equal-threshold semantics)."""
    x = np.asarray(x)
    above = x > thr
    pairs = []
    i = 0
    while i < len(x):
        if above[i]:
            j = i
            while j + 1 < len(x) and above[j + 1]:
                j += 1
            pairs.append([i, j])
            i = j + 1
        else:
            i += 1
    pairs.sort(key=lambda v: v[1] - v[0], reverse=True)
    pairs = pairs[:topk]
    pairs += [[1, 0]] * (topk - len(pairs))
    return np.asarray(pairs, dtype=np.int64).reshape(-1)


class TestPickPeaks:
    def test_simple_peak(self):
        x = np.zeros((1, 16), dtype=np.float32)
        x[0, 5] = 1.0
        out = np.asarray(pp.pick_peaks(x, 0.3, 2, 2))
        assert out.tolist() == [[5, PAD]]

    def test_plateau_keeps_rising_edge(self):
        x = np.zeros((1, 16), dtype=np.float32)
        x[0, 5:8] = 1.0
        out = np.asarray(pp.pick_peaks(x, 0.3, 2, 1))
        assert out.tolist() == [[5]]

    def test_below_threshold_dropped(self):
        x = np.zeros((1, 16), dtype=np.float32)
        x[0, 5] = 0.2
        out = np.asarray(pp.pick_peaks(x, 0.3, 2, 1))
        assert out.tolist() == [[PAD]]

    def test_min_peak_dist_suppression(self):
        x = np.zeros((1, 32), dtype=np.float32)
        x[0, 10] = 1.0
        x[0, 13] = 0.8  # within mpd=5 of the taller peak -> suppressed
        x[0, 20] = 0.6
        out = np.asarray(pp.pick_peaks(x, 0.3, 5, 3))
        assert out.tolist() == [[10, 20, PAD]]

    def test_first_last_excluded(self):
        x = np.zeros((1, 8), dtype=np.float32)
        x[0, 0] = 1.0
        x[0, 7] = 1.0
        out = np.asarray(pp.pick_peaks(x, 0.3, 1, 2))
        assert out.tolist() == [[PAD, PAD]]

    @pytest.mark.parametrize("seed", range(6))
    def test_random_parity_with_reference_algorithm(self, seed):
        rng = np.random.default_rng(seed)
        # Smooth-ish random prob curves with distinct values (ties are the
        # one documented divergence).
        x = rng.random((4, 256)).astype(np.float32)
        k = np.ones(9) / 9
        x = np.stack([np.convolve(r, k, mode="same") for r in x]).astype(np.float32)
        got = np.asarray(pp.pick_peaks(x, 0.45, 20, 3))
        want = np.stack([ref_peaks(r, 0.45, 20, 3) for r in x])
        np.testing.assert_array_equal(got, want)


class TestDetectEvents:
    def test_single_run(self):
        x = np.zeros((1, 16), dtype=np.float32)
        x[0, 4:9] = 0.9
        out = np.asarray(pp.detect_events(x, 0.5, 2))
        assert out.tolist() == [[4, 8, 1, 0]]

    def test_sorted_by_duration(self):
        x = np.zeros((1, 32), dtype=np.float32)
        x[0, 2:4] = 0.9  # len 1
        x[0, 10:20] = 0.9  # len 9
        out = np.asarray(pp.detect_events(x, 0.5, 2))
        assert out.tolist() == [[10, 19, 2, 3]]

    def test_run_to_edge(self):
        x = np.zeros((1, 16), dtype=np.float32)
        x[0, 12:] = 0.9
        out = np.asarray(pp.detect_events(x, 0.5, 1))
        assert out.tolist() == [[12, 15]]

    def test_no_events_padding(self):
        x = np.zeros((2, 16), dtype=np.float32)
        out = np.asarray(pp.detect_events(x, 0.5, 2))
        assert out.tolist() == [[1, 0, 1, 0], [1, 0, 1, 0]]

    def test_strictly_greater(self):
        x = np.full((1, 8), 0.5, dtype=np.float32)
        out = np.asarray(pp.detect_events(x, 0.5, 1))
        assert out.tolist() == [[1, 0]]

    @pytest.mark.parametrize("seed", range(6))
    def test_random_parity_with_reference_algorithm(self, seed):
        rng = np.random.default_rng(100 + seed)
        x = (rng.random((4, 128)) > 0.6).astype(np.float32)
        got = np.asarray(pp.detect_events(x, 0.5, 3))
        want = np.stack([ref_events(r, 0.5, 3) for r in x])
        np.testing.assert_array_equal(got, want)


class TestProcessOutputs:
    def test_dpk_group(self):
        n, length = 2, 64
        out = np.zeros((n, length, 3), dtype=np.float32)
        out[:, 20:30, 0] = 0.9  # det
        out[0, 24, 1] = 0.8  # ppk
        out[1, 40, 2] = 0.7  # spk
        res = pp.process_outputs(
            out,
            [("det", "ppk", "spk")],
            sampling_rate=10,
            min_peak_dist=1.0,
            max_detect_event_num=2,
        )
        assert set(res) == {"det", "ppk", "spk"}
        assert np.asarray(res["det"]).shape == (n, 4)
        assert np.asarray(res["ppk"])[0, 0] == 24
        assert np.asarray(res["spk"])[1, 0] == 40

    def test_scalar_group_passthrough(self):
        out = np.full((3, 1), 4.2, dtype=np.float32)
        res = pp.process_outputs(out, ["emg"], sampling_rate=50)
        np.testing.assert_allclose(np.asarray(res["emg"]), out)

    def test_tuple_outputs(self):
        outs = (
            np.full((2, 1), 1.0, dtype=np.float32),
            np.full((2, 1), 2.0, dtype=np.float32),
        )
        res = pp.process_outputs(outs, ["emg", "smg"], sampling_rate=50)
        assert np.asarray(res["smg"])[0, 0] == 2.0
