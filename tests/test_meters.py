"""Edge-case coverage for utils/meters.LatencyHistogram (the histogram
backing serve's /metrics AND the obs bus exposition) and the
ops/metrics.Metrics.to_dict single-batched-transfer contract."""

import numpy as np
import pytest

from seist_tpu.utils.meters import LATENCY_BOUNDS_MS, AverageMeter, LatencyHistogram


# ------------------------------------------------------- LatencyHistogram
def test_empty_histogram_percentiles_and_summary():
    h = LatencyHistogram()
    assert h.count == 0
    assert h.mean == 0.0
    for q in (0.0, 0.5, 0.99, 1.0):
        assert h.percentile(q) == 0.0
    s = h.summary()
    assert s == {"count": 0.0, "mean": 0.0, "p50": 0.0, "p90": 0.0,
                 "p99": 0.0, "max": 0.0}


def test_single_sample_percentiles_clamped_to_observed():
    h = LatencyHistogram()
    h.observe(3.0)
    # Every quantile of a single observation IS that observation; the
    # in-bucket interpolation must clamp to the observed max.
    for q in (0.0, 0.5, 1.0):
        assert h.percentile(q) <= 3.0
    assert h.percentile(1.0) == 3.0
    assert h.summary()["max"] == 3.0
    assert h.summary()["count"] == 1.0


def test_overflow_bucket_above_last_bound():
    h = LatencyHistogram(bounds=(1.0, 10.0))
    h.observe(5.0)
    h.observe(999.0)  # overflow bucket
    bounds, counts, count, total = h.buckets()
    assert bounds == [1.0, 10.0]
    assert counts == [0, 1, 1]  # last entry = overflow
    assert count == 2 and total == pytest.approx(1004.0)
    # Quantiles inside the overflow bucket interpolate toward the max.
    assert h.percentile(1.0) == 999.0
    assert h.percentile(0.99) <= 999.0
    assert h.summary()["max"] == 999.0


def test_exactly_on_bound_goes_to_lower_bucket():
    h = LatencyHistogram(bounds=(1.0, 10.0))
    h.observe(1.0)  # bisect_left: lands in the <=1.0 bucket
    _, counts, _, _ = h.buckets()
    assert counts == [1, 0, 0]


def test_unsorted_bounds_rejected():
    with pytest.raises(ValueError):
        LatencyHistogram(bounds=(10.0, 1.0))


def test_percentile_out_of_range_rejected():
    h = LatencyHistogram()
    with pytest.raises(ValueError):
        h.percentile(1.5)
    with pytest.raises(ValueError):
        h.percentile(-0.1)


def test_buckets_snapshot_is_consistent_copy():
    h = LatencyHistogram(bounds=(1.0,))
    h.observe(0.5)
    bounds, counts, _, _ = h.buckets()
    counts[0] = 999  # mutating the snapshot must not touch the histogram
    assert h.buckets()[1] == [1, 0]


def test_default_bounds_sorted_and_nonempty():
    assert list(LATENCY_BOUNDS_MS) == sorted(LATENCY_BOUNDS_MS)
    assert len(LATENCY_BOUNDS_MS) >= 5


def test_average_meter_running_stats():
    m = AverageMeter("x", ":.2f")
    m.update(1.0)
    m.update(3.0, n=3)
    assert m.val == 3.0
    assert m.count == 4
    assert m.avg == pytest.approx((1.0 + 9.0) / 4)


# ---------------------------------------- Metrics.to_dict transfer contract
def test_metrics_to_dict_single_batched_device_get(monkeypatch):
    """to_dict must fetch ALL counters in ONE jax.device_get (the old
    per-key .item() loop was one device sync per counter — jaxlint's
    host-sync catch, PR 4)."""
    import jax

    from seist_tpu.ops.metrics import Metrics

    m = Metrics(
        task="ppk", metric_names=("precision", "recall", "f1", "mean",
                                  "rmse", "mae", "mape"),
        sampling_rate=100, time_threshold=0.1, num_samples=1000,
    )
    t = np.array([[100], [200], [300]], np.int32)
    p = np.array([[105], [500], [-1]], np.int32)
    m.compute(t, p)
    m.compute(t, t)

    calls = []
    real = jax.device_get

    def counting(x):
        calls.append(x)
        return real(x)

    monkeypatch.setattr(jax, "device_get", counting)
    out = m.to_dict()
    assert len(calls) == 1  # ONE batched transfer of the counter dict
    assert isinstance(calls[0], dict)
    # Counters + finalized metrics both present.
    assert {"tp", "predp", "possp", "precision", "recall"} <= set(out)
    for v in out.values():  # host scalars (data_size stays int)
        assert isinstance(v, (int, float)) and not hasattr(v, "device")


def test_metrics_to_dict_empty_counters():
    from seist_tpu.ops.metrics import Metrics

    m = Metrics(
        task="ppk", metric_names=("precision",), sampling_rate=100,
        time_threshold=0.1, num_samples=1000,
    )
    out = m.to_dict()  # never computed a batch: finalized zeros only
    assert out["precision"] == 0.0
