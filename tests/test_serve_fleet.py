"""tools/supervise_fleet.py + bench_serve accounting: the fast (model-
free) half of the serving-resilience story. Real subprocess replicas, but
stand-ins (tests/_fake_serve_replica.py) — the jax-loaded end-to-end runs
live in tests/test_serve_chaos.py (`make serve-chaos`).

Pins, in one place, the three copies of the preemption exit code (train
checkpoint plane, serve replica, fleet supervisor) — docs/FAULT_TOLERANCE.md
promises they are one contract.
"""

import http.client
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, os.path.join(REPO, "tools"))

FAKE_REPLICA = os.path.join(HERE, "_fake_serve_replica.py")
SUPERVISE_FLEET = os.path.join(REPO, "tools", "supervise_fleet.py")


# ------------------------------------------------------------ contract pins
def test_preempt_exit_code_pinned_across_planes():
    """75 (EX_TEMPFAIL) is ONE contract: train worker, serve replica and
    both supervisors must agree, or a clean drain gets billed as a crash."""
    import supervise as train_supervise
    import supervise_fleet

    from seist_tpu.serve.server import PREEMPT_EXIT_CODE as serve_code
    from seist_tpu.train.checkpoint import PREEMPT_EXIT_CODE as train_code

    assert (
        train_supervise.PREEMPT_EXIT_CODE
        == supervise_fleet.PREEMPT_EXIT_CODE
        == serve_code
        == train_code
        == 75
    )


# --------------------------------------------------------------- fleet e2e
def _free_port_base() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _drain_pipe(pipe, buf):
    for line in pipe:
        buf.append(line)


def _start_fleet(env_extra=None, replicas=2, extra_args=()):
    base = _free_port_base()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(env_extra or {})
    proc = subprocess.Popen(
        [
            sys.executable, SUPERVISE_FLEET,
            "--replicas", str(replicas),
            "--base-port", str(base),
            "--router-port", "0",
            "--probe-interval-s", "0.2",
            "--backoff", "0.4",
            "--drain-timeout-s", "10",
            *extra_args,
            "--",
            sys.executable, FAKE_REPLICA,
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        env=env,
        text=True,
    )
    # Drain stderr from the start (and stdout once the ROUTER line is
    # found): the replicas inherit these fds, and a pipe that fills the
    # 64 KB kernel buffer blocks every writer in the fleet — including
    # the supervisor's own monitor loop mid-log-line.
    proc.fleet_err = []
    err_thread = threading.Thread(
        target=_drain_pipe, args=(proc.stderr, proc.fleet_err), daemon=True
    )
    err_thread.start()
    proc.fleet_err_thread = err_thread
    # The ROUTER= line is printed once the (ephemeral) front tier is up;
    # the seist logger may interleave INFO lines on stdout before it.
    seen = []
    for _ in range(20):
        line = proc.stdout.readline()
        if not line:
            break
        seen.append(line)
        m = re.search(r"ROUTER=http://([\d.]+):(\d+)", line)
        if m:
            threading.Thread(
                target=_drain_pipe, args=(proc.stdout, []), daemon=True
            ).start()
            return proc, m.group(1), int(m.group(2))
    proc.kill()
    raise AssertionError(f"no ROUTER line from supervisor: {seen!r}")


def _router_get(host, port, path, timeout=5.0):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def _predict(host, port, timeout=5.0):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        body = json.dumps({"data": [[0.0] * 3], "options": {}}).encode()
        conn.request("POST", "/predict", body,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


def _wait_ready(host, port, n, timeout_s=60.0):
    """Wait for n replicas with a PROBED-ok state (a just-registered
    replica is optimistically routable before its process has even bound
    the port, so /healthz ready_replicas alone races the spawn). The
    budget is deliberately generous: under full-suite contention on a
    1-core host, spawning N interpreters that each import jax can
    overshoot 20 s without anything being wrong."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            status, payload = _router_get(host, port, "/router/replicas")
            ok = sum(
                1
                for r in payload.get("replicas", [])
                if r["probe_state"] == "ok"
            )
            if status == 200 and ok >= n:
                return
        except OSError:
            pass
        time.sleep(0.1)
    raise AssertionError(f"fleet never reached {n} probed-ready replicas")


def _stop(proc, expect_rc=0, timeout=20):
    proc.send_signal(signal.SIGTERM)
    try:
        rc = proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        raise
    proc.fleet_err_thread.join(timeout=10)
    err = "".join(proc.fleet_err)
    assert rc == expect_rc, f"supervisor rc={rc}\n{err}"
    return err


class TestFleetSupervisor:
    def test_sigterm_drains_replicas_via_exit_75(self):
        proc, host, port = _start_fleet()
        try:
            _wait_ready(host, port, 2)
            status, _ = _predict(host, port)
            assert status == 200
        finally:
            err = _stop(proc, expect_rc=0)
        # Both replicas drained on SIGTERM with the preempt code —
        # billed as managed, not crash.
        assert err.count("drained (rc=75)") == 2, err

    def test_crashed_replica_restarts_and_requests_survive(self, tmp_path):
        """One replica hard-crashes mid-run: the supervisor must pull it
        from rotation, relaunch it after backoff, and the router must keep
        every client request at 200 throughout."""
        stamp = str(tmp_path / "crash.stamp")
        proc, host, port = _start_fleet(
            env_extra={
                "FAKE_CRASH_AFTER_S": "1.0",
                "FAKE_CRASH_REPLICA": "0",
                "FAKE_CRASH_STAMP": stamp,
            }
        )
        try:
            _wait_ready(host, port, 2)
            failures = []
            stop = threading.Event()

            def client():
                while not stop.is_set():
                    try:
                        status, _ = _predict(host, port)
                        if status != 200:
                            failures.append(status)
                    except OSError as e:
                        failures.append(repr(e))
                    time.sleep(0.02)

            t = threading.Thread(target=client)
            t.start()
            # Crash fires at ~1s; backoff 0.4s; relaunch + probe ~0.5s.
            # Watch the registry for the full arc: crash observed (the
            # slot leaves probed-ok) then recovery (back to 2 ok).
            deadline = time.monotonic() + 20.0
            seen_down = False
            recovered = False
            while time.monotonic() < deadline:
                try:
                    _, payload = _router_get(
                        host, port, "/router/replicas"
                    )
                except OSError:
                    time.sleep(0.05)
                    continue
                states = [
                    r["probe_state"] for r in payload.get("replicas", [])
                ]
                if any(s != "ok" for s in states):
                    seen_down = True
                if (
                    seen_down
                    and os.path.exists(stamp)
                    and states.count("ok") == 2
                ):
                    recovered = True
                    break
                time.sleep(0.05)
            # Keep the client hammering a moment past recovery.
            time.sleep(0.5)
            stop.set()
            t.join(timeout=5)
            assert os.path.exists(stamp), "scripted crash never fired"
            assert seen_down, (
                "crashed replica never observed leaving rotation"
            )
            assert recovered, (
                "crashed replica was not restarted into rotation"
            )
            assert not failures, (
                f"client saw failures during crash+restart: {failures[:5]}"
            )
        finally:
            err = _stop(proc, expect_rc=0)
        assert re.search(r"replica 0 crashed rc=3; relaunch", err), err

    def test_budget_exhausted_slot_retired_supervisor_exits_1(self):
        """A replica that keeps crashing burns its budget and is retired;
        when every slot is gone the supervisor exits 1 (distinct from the
        operator-initiated rc=0)."""
        proc, host, port = _start_fleet(
            env_extra={"FAKE_CRASH_AFTER_S": "0.3"},  # all replicas, always
            replicas=1,
            extra_args=("--retries", "1", "--backoff", "0.2"),
        )
        try:
            rc = proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            raise
        proc.fleet_err_thread.join(timeout=10)
        err = "".join(proc.fleet_err)
        assert rc == 1, f"rc={rc}\n{err}"
        assert "budget exhausted" in err and "slot retired" in err, err


# ------------------------------------------------------ rolling restart
class TestFleetRollingRestart:
    """SIGHUP + --rollout-file roll the fleet one replica at a time
    (model-free fake replicas; the under-load real-model roll is the
    serve-chaos flywheel scenario)."""

    def test_sighup_rolls_fleet_one_at_a_time(self, tmp_path):
        spec = tmp_path / "rollout.json"
        proc, host, port = _start_fleet(
            replicas=2,
            extra_args=("--rollout-file", str(spec),
                        "--rollout-ready-timeout-s", "30"),
        )
        try:
            _wait_ready(host, port, 2)
            _, out = _predict(host, port)
            assert out["model_version"] == 1  # pre-roll baseline
            spec.write_text(json.dumps({"version": 7}))
            proc.send_signal(signal.SIGHUP)
            # Convergence: both replicas probed-ok AND reporting v7 —
            # while capacity never observably dips below N-1.
            min_ready = 2
            deadline = time.monotonic() + 30.0
            converged = False
            while time.monotonic() < deadline:
                _, payload = _router_get(host, port, "/router/replicas")
                reps = payload.get("replicas", [])
                ok = [r for r in reps if r["probe_state"] == "ok"]
                min_ready = min(min_ready, len(ok))
                if len(ok) == 2 and all(
                    r.get("versions", {}).get("fake") == 7 for r in ok
                ):
                    converged = True
                    break
                time.sleep(0.05)
            assert converged, "fleet never converged on version 7"
            assert min_ready >= 1, (
                f"capacity dipped below N-1 during the roll ({min_ready})"
            )
            _, out = _predict(host, port)
            assert out["model_version"] == 7
            # The router's prober converges a beat before the roll state
            # machine's own tick confirms; give it a couple of monitor
            # ticks to log completion before tearing the fleet down.
            time.sleep(1.0)
        finally:
            err = _stop(proc, expect_rc=0)
        # The roll is visible, one replica at a time, in order:
        # drain(0) -> ready(0) -> drain(1) -> ready(1) -> complete.
        assert "rollout started: version 7 over 2 replica(s)" in err, err
        for i in (0, 1):
            assert f"rollout: draining replica {i}" in err, err
            assert re.search(
                rf"rollout: replica {i} ready \+ re-registered "
                rf"\(version 7\)", err
            ), err
        assert err.index(
            "rollout: replica 0 ready"
        ) < err.index("rollout: draining replica 1"), (
            "replica 1 was touched before replica 0 converged"
        )
        assert "rollout complete: version 7" in err, err
        # Drains were clean preempts (exit 75), not crashes.
        assert "clean preempt (rc=75)" in err, err
        assert "crashed" not in err, err

    def test_subset_roll_is_the_canary_stage(self, tmp_path):
        """'replicas': [0] rolls one member only — the canary-staging
        primitive; the fleet ends mixed-version by design."""
        spec = tmp_path / "rollout.json"
        proc, host, port = _start_fleet(
            replicas=2,
            extra_args=("--rollout-file", str(spec),
                        "--rollout-ready-timeout-s", "30"),
        )
        try:
            _wait_ready(host, port, 2)
            spec.write_text(json.dumps({"version": 2, "replicas": [0]}))
            proc.send_signal(signal.SIGHUP)
            deadline = time.monotonic() + 30.0
            versions = []
            while time.monotonic() < deadline:
                _, payload = _router_get(host, port, "/router/replicas")
                reps = payload.get("replicas", [])
                versions = sorted(
                    r.get("versions", {}).get("fake", 0)
                    for r in reps
                    if r["probe_state"] == "ok"
                )
                if versions == [1, 2]:
                    break
                time.sleep(0.05)
            assert versions == [1, 2], versions
            time.sleep(1.0)  # let the roll state machine log completion
        finally:
            err = _stop(proc, expect_rc=0)
        assert "rollout complete: version 2 on replica(s) [0]" in err, err
        assert "draining replica 1" not in err, err

    def test_sighup_without_rollout_file_is_ignored(self, tmp_path):
        proc, host, port = _start_fleet(replicas=1)
        try:
            _wait_ready(host, port, 1)
            proc.send_signal(signal.SIGHUP)
            time.sleep(0.6)
            _, out = _predict(host, port)
            assert out["model_version"] == 1
        finally:
            err = _stop(proc, expect_rc=0)
        assert "no --rollout-file configured" in err, err


# ------------------------------------------------------- bench accounting
class TestBenchServeAccounting:
    """Satellite: bench_serve must account per-request errors instead of
    aborting, and gate on the SLO."""

    def _fake_target(self, script):
        """A live HTTP /predict endpoint whose responses follow
        ``script`` (a list of (status, error_code)); cycles past the end
        with 200s."""
        from http.server import (
            BaseHTTPRequestHandler,
            ThreadingHTTPServer,
        )

        hits = {"n": 0}

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                self.rfile.read(n)
                i = hits["n"]
                hits["n"] += 1
                status, code = (
                    script[i] if i < len(script) else (200, "")
                )
                body = json.dumps(
                    {"error": code} if code else {"ok": True}
                ).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        server = ThreadingHTTPServer(("127.0.0.1", 0), H)
        server.daemon_threads = True
        threading.Thread(target=server.serve_forever, daemon=True).start()
        return server, server.server_address[1]

    def _run(self, port, tmp_path, *extra):
        import bench_serve

        out = str(tmp_path / "bench.json")
        rc = bench_serve.main([
            "--url", f"http://127.0.0.1:{port}",
            "--requests", "10",
            "--concurrency", "2",
            "--window", "8",
            "--output", out,
            *extra,
        ])
        with open(out) as f:
            return rc, json.load(f)

    def test_errors_counted_not_aborting(self, tmp_path):
        script = [(429, "queue_full"), (503, "shed"), (504, "deadline")]
        server, port = self._fake_target(script)
        try:
            rc, result = self._run(port, tmp_path)
        finally:
            server.shutdown()
            server.server_close()
        assert rc == 0  # no gate requested: errors reported, not fatal
        assert result["ok"] == 7 and result["errors"] == 3
        assert result["error_rate"] == pytest.approx(0.3)
        assert result["by_status"] == {
            "200": 7, "429": 1, "503": 1, "504": 1
        }
        assert result["by_error_code"] == {
            "deadline": 1, "queue_full": 1, "shed": 1
        }

    def test_slo_gate_trips_on_errors_and_passes_clean(self, tmp_path):
        server, port = self._fake_target([(503, "shed")])
        try:
            rc, result = self._run(
                port, tmp_path, "--slo-p99-ms", "60000"
            )
            assert rc == 3  # SLO_EXIT_CODE: error budget (default 0) blown
            assert result["slo_violations"]
            # Same target, tolerant error budget: the gate passes.
            rc2, result2 = self._run(
                port, tmp_path, "--slo-p99-ms", "60000",
                "--max-error-rate", "0.5",
            )
            assert rc2 == 0 and result2["slo_violations"] == []
        finally:
            server.shutdown()
            server.server_close()

    def test_slo_gate_trips_on_p99(self, tmp_path):
        server, port = self._fake_target([])
        try:
            rc, result = self._run(
                port, tmp_path, "--slo-p99-ms", "0.000001"
            )
        finally:
            server.shutdown()
            server.server_close()
        assert rc == 3
        assert any("p99" in v for v in result["slo_violations"])

    def test_open_loop_reports_client_overruns(self, tmp_path):
        """Open-loop arrivals beyond the in-flight cap must be counted as
        status-0 client_overrun errors, not silently skipped."""
        import bench_serve

        calls = {"n": 0}

        def slow_one(i):
            calls["n"] += 1
            time.sleep(0.5)

        stats = bench_serve._Stats()
        bench_serve._drive_open_loop(
            slow_one, n_requests=30, arrival_rps=500.0, max_inflight=1,
            stats=stats,
        )
        # cap = 4 in flight; at 500 rps vs 0.5 s service, most arrivals
        # overrun the client.
        assert stats.by_code.get("client_overrun", 0) > 0
        assert calls["n"] + stats.by_code["client_overrun"] == 30


# -------------------------------------------------- fleet metrics plane
class TestFleetMetricsPlane:
    """ISSUE 11: GET /fleet/metrics[.json] on the supervisor aggregates
    >= 2 replicas + the router — counters summed, histograms merged
    bucket-wise, per-replica breakdown retained."""

    def test_fleet_metrics_aggregates_replicas_and_router(self):
        proc, host, port = _start_fleet(
            extra_args=("--fleet-scrape-interval-s", "0.3"),
        )
        try:
            _wait_ready(host, port, 2)
            for _ in range(5):
                status, _ = _predict(host, port)
                assert status == 200
            deadline = time.monotonic() + 15.0
            view = None
            while time.monotonic() < deadline:
                status, view = _router_get(
                    host, port, "/fleet/metrics.json", timeout=10.0
                )
                assert status == 200
                agg = view["aggregate"]
                if (
                    view.get("up", 0) >= 3
                    and agg["counters"].get(
                        "fake_requests{path=predict}", 0) >= 5
                ):
                    break
                time.sleep(0.2)
            assert view is not None and view["up"] >= 3, view
            agg = view["aggregate"]
            # Counters summed across the fleet...
            assert agg["counters"]["fake_requests{path=predict}"] == 5
            # ...histograms merged bucket-wise (one merged distribution,
            # not averaged percentiles)...
            h = agg["histograms"]["fake_latency_ms"]
            assert h["count"] == 5 and h["bucket_counts"][0] == 5
            assert h["mean"] == pytest.approx(1.0)
            # ...per-replica breakdown retained verbatim...
            per = {
                name: (snap or {}).get("counters", {}).get(
                    "fake_requests{path=predict}", 0)
                for name, snap in view["replicas"].items()
                if name.startswith("replica-")
            }
            assert len(per) == 2 and sum(per.values()) == 5, per
            # ...and the router's own bus is a source too.
            assert view["sources"]["router"]["up"]
            assert any(
                k.startswith("router_requests")
                for k in view["replicas"]["router"]["counters"]
            )

            # Prometheus exposition: aggregate under replica="fleet",
            # breakdown under the source name.
            conn = http.client.HTTPConnection(host, port, timeout=10)
            try:
                conn.request("GET", "/fleet/metrics")
                resp = conn.getresponse()
                text = resp.read().decode()
                assert resp.status == 200
                assert resp.getheader("Content-Type", "").startswith(
                    "text/plain")
            finally:
                conn.close()
            assert ('seist_fake_requests_total{path="predict",'
                    'replica="fleet"} 5') in text
            assert 'replica="replica-0"' in text
            assert 'seist_fleet_source_up{source="replica-1"} 1' in text
            assert "seist_fake_latency_ms_bucket" in text
        finally:
            _stop(proc, expect_rc=0)

    def test_bare_router_reports_no_fleet(self):
        """/fleet/metrics without a supervisor-attached aggregator is an
        explicit 404, not a crash."""
        from seist_tpu.serve.router import Router, start_router_server

        router = Router()
        server = start_router_server(router, port=0)
        try:
            host, port = server.server_address[:2]
            status, payload = _router_get(host, port, "/fleet/metrics.json")
            assert status == 404 and payload["error"] == "no_fleet"
        finally:
            server.shutdown()
            router.stop()
