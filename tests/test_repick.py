"""Batch re-picking engine (seist_tpu/batch) + tools/repick_archive.py:

* deterministic work-unit planning + segment math + resume scan;
* plan-identity guard (geometry changes refuse to resume);
* catalog row schema + canonical serialization;
* engine e2e: serial == map-reduce == kill/resume, BYTE-identical;
* zero XLA compiles after warm-up (CompileBudget gate);
* SIGTERM-style preemption at segment boundaries + exact-offset resume;
* variant parity gate wiring (refuse on divergence).
"""

import glob
import json
import os

import numpy as np
import pytest

import seist_tpu
from seist_tpu.batch import catalog
from seist_tpu.ops.results import catalog_row_lines, catalog_rows

seist_tpu.load_all()

TRACE = 256
BATCH = 4
BPC = 2  # batches per call
ROWS_PER_CALL = BATCH * BPC


# ----------------------------------------------------------------- planning
def test_plan_units_contiguous_ranges():
    shards = np.array([0, 0, 0, 2, 2, 5])
    units = catalog.plan_units(shards)
    assert [(u.unit_id, u.row_lo, u.row_hi) for u in units] == [
        (0, 0, 3), (2, 3, 5), (5, 5, 6),
    ]
    assert catalog.plan_units(np.array([])) == []


def test_plan_units_refuses_reordered_index():
    with pytest.raises(ValueError, match="pack order"):
        catalog.plan_units(np.array([1, 0, 1]))


def test_segment_math():
    unit = catalog.WorkUnit(0, 0, 22)  # 22 rows, 8 rows/call -> 3 calls
    assert catalog.calls_per_unit(unit, 8) == 3
    assert catalog.segments_per_unit(unit, 8, 2) == 2
    assert catalog.segments_per_unit(unit, 8, 1) == 3
    empty_tail = catalog.WorkUnit(1, 22, 24)
    assert catalog.calls_per_unit(empty_tail, 8) == 1


def test_resume_scan_finds_first_hole(tmp_path):
    unit = catalog.WorkUnit(3, 0, 30)  # 4 calls at 8/call -> 4 segs at 1
    out = str(tmp_path)
    assert catalog.first_missing_segment(out, unit, 8, 1) == 0
    catalog.commit_segment(out, 3, 0, ["a\n"])
    catalog.commit_segment(out, 3, 1, ["b\n"])
    assert catalog.first_missing_segment(out, unit, 8, 1) == 2
    # a hole before a committed later segment resumes AT the hole
    catalog.commit_segment(out, 3, 3, ["d\n"])
    assert catalog.first_missing_segment(out, unit, 8, 1) == 2


def test_plan_identity_guard(tmp_path):
    out = str(tmp_path)
    plan = {"batch_size": 4, "model": "phasenet", "variant": "fp32"}
    catalog.write_or_check_plan(out, plan)
    catalog.write_or_check_plan(out, dict(plan))  # same plan: fine
    with pytest.raises(ValueError, match="different plan"):
        catalog.write_or_check_plan(out, {**plan, "batch_size": 8})


def test_merge_refuses_missing_segments(tmp_path):
    out = str(tmp_path)
    units = [catalog.WorkUnit(0, 0, 8), catalog.WorkUnit(1, 8, 16)]
    catalog.commit_segment(out, 0, 0, ['{"row":0}\n'])
    with pytest.raises(FileNotFoundError, match="unit 1 seg 0"):
        catalog.merge_catalog(out, units, 8, 1)
    catalog.commit_segment(out, 1, 0, ['{"row":8}\n'])
    meta = catalog.merge_catalog(out, units, 8, 1, meta={"x": 1})
    assert meta["n_rows"] == 2 and meta["x"] == 1
    assert os.path.exists(os.path.join(out, "catalog_meta.json"))


# -------------------------------------------------------------- row schema
def test_catalog_rows_schema_and_determinism():
    decoded = {
        "dpk": {
            "ppk": np.array([[5, -1, -1], [7, 9, -1]]),
            "spk": np.array([[-1, -1, -1], [11, -1, -1]]),
            "det": np.array([[3, 8, 1, 0], [2, 6, 7, 9]]),
        },
        "emg": {"emg": np.array([[1.23456789], [2.5]])},
        "pmp": {"pmp": np.array([[0.1, 0.9], [0.8, 0.2]])},
    }
    rows = catalog_rows(
        decoded, n_valid=2, row_ids=[10, 11], keys=["a", "b"]
    )
    assert rows[0] == {
        "row": 10, "key": "a", "ppk": [5], "spk": [],
        "det": [[3, 8]], "emg": 1.234568,
        "pmp": {"class": 1, "scores": [0.1, 0.9]},
    }
    assert rows[1]["ppk"] == [7, 9] and rows[1]["det"] == [[2, 6], [7, 9]]
    # Padding rows (>= n_valid) dropped.
    assert len(catalog_rows(decoded, n_valid=1, row_ids=[10])) == 1
    # Canonical serialization: sorted keys, compact, newline-terminated.
    lines = catalog_row_lines(rows)
    assert lines[0].endswith("\n")
    assert lines == catalog_row_lines(
        catalog_rows(decoded, n_valid=2, row_ids=[10, 11], keys=["a", "b"])
    )
    assert json.loads(lines[0]) == rows[0]


def test_catalog_rows_station_provenance():
    """--station-meta passthrough: rows whose key has station metadata
    carry it verbatim; rows without stay byte-identical to before."""
    decoded = {"dpk": {"ppk": np.array([[5, -1], [7, -1]])}}
    stations = {"a": {"id": "CI.ABC", "network": "CI",
                      "lat": 35.0, "lon": -117.0}}
    rows = catalog_rows(
        decoded, n_valid=2, row_ids=[0, 1], keys=["a", "b"],
        stations=stations,
    )
    assert rows[0]["station"] == stations["a"]
    assert "station" not in rows[1]
    # No keys -> stations ignored (nothing to join on).
    plain = catalog_rows(decoded, n_valid=2, row_ids=[0, 1],
                         stations=stations)
    assert all("station" not in r for r in plain)
    json.loads(catalog_row_lines(rows)[0])  # still canonical JSONL


def test_decode_head_batch_drops_dense_channels():
    import jax.numpy as jnp

    from seist_tpu import taskspec
    from seist_tpu.ops.postprocess import decode_head_batch

    spec = taskspec.get_task_spec("phasenet")  # labels (("non","ppk","spk"),)
    out = jnp.zeros((2, 64, 3))
    res = decode_head_batch(
        spec, out, is_picker=True, sampling_rate=50
    )
    assert set(res) == {"ppk", "spk"}  # 'non' (dense) not catalog content

    mspec = taskspec.get_task_spec("magnet")  # VALUE head w/ transform
    vres = decode_head_batch(
        mspec, jnp.array([[3.0, -1.0], [2.0, 0.5]]), is_picker=False,
        sampling_rate=50,
    )
    assert set(vres) == {"emg"}
    np.testing.assert_allclose(np.asarray(vres["emg"]).ravel(), [3.0, 2.0])


# ------------------------------------------------------------- engine e2e
N_EVENTS = 22
SPS = 10  # 3 shards: 10 + 10 + 2 (partial tail)


@pytest.fixture(scope="module")
def repick_archive_dir(tmp_path_factory):
    from seist_tpu.data.packed import PackSource, pack_sources

    root = tmp_path_factory.mktemp("repick_arch")
    return pack_sources(
        [PackSource(
            name="synthetic",
            dataset_kwargs={
                "num_events": N_EVENTS, "trace_samples": TRACE,
                "cache": False,
            },
        )],
        str(root),
        samples_per_shard=SPS,
    )["out"]


def _repick(archive, out, *extra):
    from tools.repick_archive import main

    return main([
        "--archive", archive, "--out", out, "--model", "phasenet",
        "--batch-size", str(BATCH), "--batches-per-call", str(BPC),
        "--commit-every", "1", *extra,
    ])


def _merge_only(archive, out):
    """The model-free reduce. Deliberately passes NO geometry flags (and
    a default --commit-every that DIFFERS from the map phase's): the
    merge must take segment geometry from repick_plan.json, not from
    this invocation — a flag-derived geometry under-counts segments and
    silently drops rows (review-pinned)."""
    from tools.repick_archive import main

    return main(["--archive", archive, "--out", out, "--merge-only"])


@pytest.fixture(scope="module")
def serial_catalog(repick_archive_dir, tmp_path_factory):
    out = str(tmp_path_factory.mktemp("serial"))
    assert _repick(repick_archive_dir, out, "--compile-gate") == 0
    with open(os.path.join(out, "catalog.jsonl"), "rb") as f:
        return f.read()


def test_serial_catalog_covers_archive(serial_catalog):
    rows = [json.loads(x) for x in serial_catalog.splitlines()]
    assert len(rows) == N_EVENTS
    assert [r["row"] for r in rows] == list(range(N_EVENTS))
    assert all("ppk" in r and "spk" in r and "key" in r for r in rows)


def test_zero_compiles_after_warmup(
    repick_archive_dir, tmp_path, capsys
):
    """ISSUE acceptance: CompileBudget records zero compiles after the
    worker's warm-up — the whole unit loop runs AOT executables only."""
    assert _repick(
        repick_archive_dir, str(tmp_path), "--compile-gate"
    ) == 0
    verdicts = [
        json.loads(line)
        for line in capsys.readouterr().out.splitlines()
        if line.startswith("{")
    ]
    worker = next(v for v in verdicts if v.get("role") == "worker")
    assert worker["compiles_after_warmup"] == 0
    assert worker["xla_compiles_after_warmup"] == 0
    assert worker["rows"] == N_EVENTS


def test_two_worker_kill_resume_byte_identical(
    repick_archive_dir, serial_catalog, tmp_path
):
    """Map-reduce over 2 workers with a simulated mid-shard kill (a
    later segment deleted = work lost after a SIGKILL): the resumed
    worker restarts at its exact segment offset and the merged catalog
    is byte-identical to the serial run."""
    out = str(tmp_path)
    w = ["--worker-index", "0", "--num-workers", "2", "--no-merge"]
    assert _repick(repick_archive_dir, out, *w) == 0
    # Simulate the kill: drop worker 0's LAST committed segment.
    segs = sorted(glob.glob(os.path.join(out, "unit_00002.seg_*.jsonl")))
    assert segs, "expected worker 0 to own unit 2"
    os.unlink(segs[-1])
    assert _repick(repick_archive_dir, out, *w) == 0  # exact-offset resume
    assert _repick(
        repick_archive_dir, out, "--worker-index", "1",
        "--num-workers", "2", "--no-merge",
    ) == 0
    assert _merge_only(repick_archive_dir, out) == 0
    with open(os.path.join(out, "catalog.jsonl"), "rb") as f:
        assert f.read() == serial_catalog
    meta = json.load(open(os.path.join(out, "catalog_meta.json")))
    # Identity/geometry in the merged meta come from the PLAN, not the
    # merge invocation's (absent) flags.
    assert meta["model"] == "phasenet"
    assert meta["plan"]["commit_every"] == 1
    assert meta["n_rows"] == N_EVENTS


def test_fleet_workers_byte_identical_with_fence_audit(
    repick_archive_dir, serial_catalog, tmp_path, monkeypatch, capsys
):
    """Fleet mode (work-unit leases + fencing tokens, batch/fleet.py)
    over the same archive: worker 0 work-steals every unit, worker 1
    joins late and finds only done markers, the merge audits each
    segment's fence sidecar against the done-fence ledger — and the
    catalog is byte-identical to the serial run. The lease plane costs
    zero bytes."""
    from tools.repick_archive import main as repick_main

    out = str(tmp_path)
    lease_dir = os.path.join(out, "leases")
    monkeypatch.setenv("SEIST_LEASE_TTL_S", "10.0")
    fl = [
        "--fleet", "--lease-dir", lease_dir, "--lease-store", "dir",
        "--no-merge",
    ]
    assert _repick(
        repick_archive_dir, out, *fl, "--worker-index", "0",
        "--worker-id", "w0",
    ) == 0
    assert _repick(
        repick_archive_dir, out, *fl, "--worker-index", "1",
        "--worker-id", "w1",
    ) == 0
    verdicts = [
        json.loads(line)
        for line in capsys.readouterr().out.splitlines()
        if line.startswith("{")
    ]
    w0 = next(v for v in verdicts if v.get("owner") == "w0")
    w1 = next(v for v in verdicts if v.get("owner") == "w1")
    assert w0["role"] == "fleet-worker" and w0["all_done"]
    assert w0["units_done"] >= 1 and w0["lease"]["double_commits"] == 0
    assert w1["all_done"] and w1["units_done"] == 0  # only done markers
    assert repick_main([
        "--archive", repick_archive_dir, "--out", out, "--merge-only",
        "--lease-dir", lease_dir,
    ]) == 0
    merge = next(
        json.loads(line)
        for line in capsys.readouterr().out.splitlines()
        if line.startswith("{") and json.loads(line).get("role") == "merge"
    )
    audit = merge["fence_audit"]
    assert audit["fenced_segments"] >= 1
    assert audit["stale_fence_segments"] == 0
    assert len(audit["done_fences"]) == w0["units_done"]
    with open(os.path.join(out, "catalog.jsonl"), "rb") as f:
        assert f.read() == serial_catalog


def test_resume_refuses_changed_geometry(repick_archive_dir, tmp_path):
    out = str(tmp_path)
    assert _repick(repick_archive_dir, out, "--no-merge") == 0
    from tools.repick_archive import main

    with pytest.raises(ValueError, match="different plan"):
        main([
            "--archive", repick_archive_dir, "--out", out,
            "--model", "phasenet", "--batch-size", str(BATCH * 2),
            "--batches-per-call", str(BPC), "--commit-every", "1",
            "--no-merge",
        ])


def _make_engine(archive, **kw):
    from seist_tpu.batch.engine import RepickEngine
    from seist_tpu.data import pipeline
    from seist_tpu.data.ingest import PackedRawStore, packed_dataset_of
    from seist_tpu.serve.pool import load_model_entry

    sds = pipeline.SeismicDataset(
        "packed", "train", seed=0, data_dir=archive,
        input_names=[], label_names=[], task_names=[],
        in_samples=TRACE, augmentation=False, shuffle=False,
        data_split=False,
    )
    store = PackedRawStore.build(sds, batch_size=ROWS_PER_CALL)
    keys = packed_dataset_of(sds)._meta_data["key"].to_numpy()
    entry = load_model_entry("phasenet", "", window=TRACE)
    return RepickEngine(
        entry, store, sampling_rate=50, batch_size=BATCH,
        batches_per_call=BPC, keys=keys, **kw,
    ), store


def test_preemption_commits_segment_then_stops(
    repick_archive_dir, serial_catalog, tmp_path
):
    """The SIGTERM contract at engine level: stop_event set -> the
    in-flight segment commits, the unit reports preempted, and a resume
    finishes from the exact offset with byte-identical output."""
    import threading

    engine, store = _make_engine(repick_archive_dir)
    units = catalog.plan_units(store._shards)
    out = str(tmp_path)
    catalog.write_or_check_plan(out, {"t": 1})

    # A stop that lands before ANY work: nothing commits, and the unit
    # must still report preempted (not silently look complete).
    pre = threading.Event()
    pre.set()
    stats0 = engine.run_units(units, out, commit_every=1, stop_event=pre)
    assert stats0["preempted"] is True and stats0["segments"] == 0

    # A stop raised at the first segment commit: that segment lands,
    # everything after stays a hole.
    stop = threading.Event()
    real_commit = catalog.commit_segment

    def commit_then_stop(*a, **k):
        path = real_commit(*a, **k)
        stop.set()
        return path

    import seist_tpu.batch.engine as engine_mod

    orig = engine_mod.catalog.commit_segment
    engine_mod.catalog.commit_segment = commit_then_stop
    try:
        stats = engine.run_units(
            units, out, commit_every=1, stop_event=stop
        )
    finally:
        engine_mod.catalog.commit_segment = orig
    assert stats["preempted"] is True
    assert stats["segments"] == 1
    # Resume with a fresh engine: finishes every unit.
    stats2 = engine.run_units(units, out, commit_every=1)
    assert stats2["preempted"] is False
    total_segs = sum(
        catalog.segments_per_unit(u, ROWS_PER_CALL, 1) for u in units
    )
    assert stats["segments"] + stats2["segments"] + stats2[
        "segments_skipped"
    ] >= total_segs
    merged = catalog.merge_catalog(out, units, ROWS_PER_CALL, 1)
    assert merged["n_rows"] == N_EVENTS
    with open(os.path.join(out, "catalog.jsonl"), "rb") as f:
        assert f.read() == serial_catalog


def test_variant_gate_refuses_divergence(
    repick_archive_dir, monkeypatch
):
    from seist_tpu.serve import aot

    engine, _ = _make_engine(repick_archive_dir, variant="bf16")
    monkeypatch.setattr(
        aot, "variant_parity", lambda *a, **k: (False, 1.0)
    )
    with pytest.raises(RuntimeError, match="parity gate"):
        engine.warmup()


def test_variant_gate_pass_runs_variant_program(
    repick_archive_dir, tmp_path, monkeypatch
):
    from seist_tpu.serve import aot

    monkeypatch.setattr(
        aot, "variant_parity", lambda *a, **k: (True, 0.0)
    )
    engine, store = _make_engine(repick_archive_dir, variant="bf16")
    engine.warmup()
    assert engine.warmup_report["program"].endswith("/bf16")
    out = str(tmp_path)
    catalog.write_or_check_plan(out, {"t": "bf16"})
    units = catalog.plan_units(store._shards)
    stats = engine.run_units(units[:1], out, commit_every=1)
    assert stats["rows"] == SPS


def test_variant_gate_uses_model_head_scale(
    repick_archive_dir, monkeypatch
):
    """Single-task entries carry head_scale on the MODEL (groups on the
    TaskHead); the gate must normalize VALUE-head error by it
    (review-pinned: the entry itself has no head_scale attribute, so a
    naive getattr silently used 1.0)."""
    from seist_tpu.batch.engine import RepickEngine
    from seist_tpu.data import pipeline
    from seist_tpu.data.ingest import PackedRawStore
    from seist_tpu.serve import aot
    from seist_tpu.serve.pool import load_model_entry

    sds = pipeline.SeismicDataset(
        "packed", "train", seed=0, data_dir=repick_archive_dir,
        input_names=[], label_names=[], task_names=[],
        in_samples=TRACE, augmentation=False, shuffle=False,
        data_split=False,
    )
    store = PackedRawStore.build(sds, batch_size=ROWS_PER_CALL)
    entry = load_model_entry("seist_s_emg", "", window=TRACE)
    expected = float(getattr(entry.model, "head_scale", 1.0) or 1.0)
    assert expected != 1.0, "test needs a scaled regression head"
    seen = {}

    def spy(ref, out, variant, *, kind, scale=1.0):
        seen["kind"], seen["scale"] = kind, scale
        return True, 0.0

    monkeypatch.setattr(aot, "variant_parity", spy)
    engine = RepickEngine(
        entry, store, sampling_rate=50, batch_size=BATCH,
        batches_per_call=BPC, variant="bf16",
    )
    engine.warmup()
    assert seen["kind"] == "value"
    assert seen["scale"] == expected


def test_engine_refuses_window_mismatch(repick_archive_dir):
    from seist_tpu.batch.engine import RepickEngine
    from seist_tpu.data import pipeline
    from seist_tpu.data.ingest import PackedRawStore
    from seist_tpu.serve.pool import load_model_entry

    sds = pipeline.SeismicDataset(
        "packed", "train", seed=0, data_dir=repick_archive_dir,
        input_names=[], label_names=[], task_names=[],
        in_samples=TRACE, augmentation=False, shuffle=False,
        data_split=False,
    )
    store = PackedRawStore.build(sds, batch_size=8)
    entry = load_model_entry("phasenet", "", window=TRACE * 2)
    with pytest.raises(ValueError, match="window"):
        RepickEngine(entry, store, sampling_rate=50)
