"""Golden parity + executor tests for device-side augmentation
(seist_tpu/data/device_aug.py).

The parity suite injects the SAME random draws into both implementations:
the device pipeline derives named draws from its (seed, epoch, idx) key;
``build_replay_script`` translates them into the numpy
``DataPreprocessor``'s consumption order and a ``ScriptedRNG`` feeds them
to the REAL numpy code. Outputs must match within float32 tolerance —
per-op and end-to-end through ``process()`` + label synthesis.
"""

import copy

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import seist_tpu
from seist_tpu import taskspec
from seist_tpu.data import device_aug as da
from seist_tpu.data import pipeline as pl
from seist_tpu.data.preprocess import DataPreprocessor

seist_tpu.load_all()

C, L, W = 3, 600, 512
TOL = dict(rtol=2e-4, atol=2e-4)


def make_event(seed, ppks=(120,), spks=(200,)):
    rng = np.random.default_rng(seed)
    return {
        "data": rng.standard_normal((C, L)).astype(np.float32),
        "ppks": list(ppks),
        "spks": list(spks),
        "emg": [3.5],
        "snr": np.full(C, 20.0, np.float32),
    }


def make_pre(**over):
    kw = dict(
        data_channels=["z", "n", "e"],
        sampling_rate=50,
        in_samples=W,
        coda_ratio=2.0,  # f32-exact so coda truncation can't split (see
        # device_aug module docstring's tolerated-deviation list)
        norm_mode="std",
        add_event_rate=0.9,
        max_event_num=2,
        shift_event_rate=0.9,
        add_noise_rate=0.9,
        add_gap_rate=0.9,
        drop_channel_rate=0.9,
        scale_amplitude_rate=0.9,
        pre_emphasis_rate=0.9,
        generate_noise_rate=0.05,
        min_event_gap_sec=0.1,
        soft_label_shape="gaussian",
        soft_label_width=40,
    )
    kw.update(over)
    return DataPreprocessor(**kw)


def make_cfg(pre, seed=0, phase_slots=4, raw_len=L):
    return da.AugConfig.from_preprocessor(
        pre, seed=seed, raw_len=raw_len, phase_slots=phase_slots
    )


def get_draws(cfg, epoch, idx):
    return jax.device_get(da.draw_all(cfg, da.sample_key(cfg.seed, epoch, idx)))


def phase_arrays(ppks, spks, P=4):
    arr = lambda v: jnp.asarray(  # noqa: E731
        list(v) + [da._BIG] * (P - len(v)), jnp.int32
    )
    return arr(ppks), jnp.int32(len(ppks)), arr(spks), jnp.int32(len(spks))


# --------------------------------------------------------------- per-op parity
class TestPerOpParity:
    def test_normalize_modes(self):
        rng = np.random.default_rng(0)
        data = rng.standard_normal((C, W)).astype(np.float32) * 7.0
        from seist_tpu.data.preprocess import normalize as np_normalize

        for mode in ("std", "max", ""):
            ours = np.asarray(da.normalize(jnp.asarray(data), mode))
            ref = np_normalize(data.copy(), mode, axis=1)
            np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-6)

    def test_normalize_zero_scale(self):
        data = np.zeros((C, 16), np.float32)
        out = np.asarray(da.normalize(jnp.asarray(data), "max"))
        assert np.all(np.isfinite(out))

    def test_shift_event(self):
        pre = make_pre()
        ev = make_event(1, ppks=(100, 300), spks=(180, 420))
        shift = 217
        d_np, p_np, s_np = pre._shift_event(
            ev["data"].copy(), list(ev["ppks"]), list(ev["spks"]),
            da.ScriptedRNG([("integers", shift)]),
        )
        pp, npp, ss, nss = phase_arrays(ev["ppks"], ev["spks"])
        d_d, pp2, npp2, ss2, nss2 = da.shift_event(
            jnp.asarray(ev["data"]), pp, npp, ss, nss, shift
        )
        np.testing.assert_allclose(np.asarray(d_d), d_np, **TOL)
        assert list(np.asarray(pp2)[: int(npp2)]) == p_np
        assert list(np.asarray(ss2)[: int(nss2)]) == s_np

    def test_add_event(self):
        pre = make_pre(min_event_gap_sec=0.1)
        ev = make_event(2, ppks=(50,), spks=(90,))
        cfg = make_cfg(pre)
        u_t, u_pos, u_scale = 0.3, 0.55, 0.77
        # scripted numpy draws computed with the SAME u->int formula
        target = da.u2i_np(u_t, 1)
        ppk, spk = 50, 90
        ce = int(spk + pre.coda_ratio * (spk - ppk))
        left, right = ce + pre.min_event_gap, L - (spk - ppk) - pre.min_event_gap
        pos = left + da.u2i_np(u_pos, right - left)
        d_np, p_np, s_np = pre._add_event(
            ev["data"].copy(), [ppk], [spk], pre.min_event_gap,
            da.ScriptedRNG(
                [("integers", target), ("integers", pos), ("random", u_scale)]
            ),
        )
        pp, npp, ss, nss = phase_arrays([ppk], [spk])
        d_d, pp2, npp2, ss2, nss2 = da.add_event_once(
            cfg, jnp.asarray(ev["data"]), pp, npp, ss, nss,
            jnp.float32(u_t), jnp.float32(u_pos), jnp.float32(u_scale),
            jnp.bool_(True),
        )
        np.testing.assert_allclose(np.asarray(d_d), d_np, **TOL)
        assert list(np.asarray(pp2)[: int(npp2)]) == p_np
        assert list(np.asarray(ss2)[: int(nss2)]) == s_np

    def test_generate_noise(self):
        pre = make_pre()
        cfg = make_cfg(pre)
        ev = make_event(3, ppks=(100, 220), spks=(150, 260))
        field = np.random.default_rng(9).standard_normal((C, L)).astype(
            np.float32
        )
        script = []
        for ppk, spk in zip(ev["ppks"], ev["spks"]):
            ce = int(np.clip(int(spk + pre.coda_ratio * (spk - ppk)), 0, L))
            if ppk < ce:
                script.append(("normal", field[:, ppk:ce]))
        d_np, p_np, s_np = pre._generate_noise_data(
            ev["data"].copy(), list(ev["ppks"]), list(ev["spks"]),
            da.ScriptedRNG(script),
        )
        pp, npp, ss, nss = phase_arrays(ev["ppks"], ev["spks"])
        d_d = da.generate_noise(
            cfg, jnp.asarray(ev["data"]), pp, npp, ss, nss, jnp.asarray(field)
        )
        np.testing.assert_allclose(np.asarray(d_d), d_np, **TOL)
        assert p_np == [] and s_np == []

    def test_drop_channel_and_adjust(self):
        ev = make_event(4)
        u_num, u_ch = 0.9, np.array([0.1, 0.8], np.float32)
        drop_num = 1 + da.u2i_np(u_num, C - 1)
        cands = list(range(C))
        script = [("choice", drop_num)]
        for i in range(drop_num):
            c = cands[da.u2i_np(u_ch[i], len(cands))]
            script.append(("choice", c))
            cands.remove(c)
        pre = make_pre()
        d_np = pre._adjust_amplitude(
            pre._drop_channel(ev["data"].copy(), da.ScriptedRNG(script))
        )
        d_d = da.adjust_amplitude(
            da.drop_channel(
                jnp.asarray(ev["data"]), jnp.float32(u_num), jnp.asarray(u_ch)
            )
        )
        np.testing.assert_allclose(np.asarray(d_d), d_np, **TOL)

    def test_scale_pre_emphasis_noise_gaps(self):
        pre = make_pre()
        ev = make_event(5, ppks=(100,), spks=(200,))
        # scale
        d_np = pre._scale_amplitude(
            ev["data"].copy(),
            da.ScriptedRNG([("uniform", 0.7), ("uniform", 1.0 + 2 * 0.4)]),
        )
        d_d = da.scale_amplitude(
            jnp.asarray(ev["data"]), jnp.float32(0.7), jnp.float32(0.4)
        )
        np.testing.assert_allclose(np.asarray(d_d), d_np, **TOL)
        # pre-emphasis
        d_np = pre._pre_emphasis(ev["data"].copy(), 0.97)
        d_d = da.pre_emphasis(jnp.asarray(ev["data"]), 0.97)
        np.testing.assert_allclose(np.asarray(d_d), d_np, **TOL)
        # SNR noise
        u_snr = np.array([0.2, 0.5, 0.9], np.float32)
        field = np.random.default_rng(6).standard_normal((C, L)).astype(
            np.float32
        )
        script = []
        for c in range(C):
            script.append(("integers", 10 + da.u2i_np(u_snr[c], 40)))
            script.append(("normal", field[c]))
        d_np = pre._add_noise(ev["data"].copy(), da.ScriptedRNG(script))
        d_d = da.add_noise(
            jnp.asarray(ev["data"]), jnp.asarray(u_snr), jnp.asarray(field)
        )
        np.testing.assert_allclose(np.asarray(d_d), d_np, rtol=2e-3, atol=2e-3)
        # gaps
        u1, u2, u3 = 0.3, 0.6, 0.8
        phases = sorted(ev["ppks"] + ev["spks"]) + [L - 1]
        phases = sorted(set(phases))
        ip = da.u2i_np(u1, len(phases) - 1)
        sgt = phases[ip] + da.u2i_np(u2, phases[ip + 1] - phases[ip])
        egt = sgt + da.u2i_np(u3, phases[ip + 1] - sgt)
        d_np = pre._add_gaps(
            ev["data"].copy(), list(ev["ppks"]), list(ev["spks"]),
            da.ScriptedRNG(
                [("integers", ip), ("integers", sgt), ("integers", egt)]
            ),
        )
        pp, npp, ss, nss = phase_arrays(ev["ppks"], ev["spks"])
        d_d = da.add_gaps(
            jnp.asarray(ev["data"]), pp, npp, ss, nss,
            jnp.float32(u1), jnp.float32(u2), jnp.float32(u3),
        )
        np.testing.assert_allclose(np.asarray(d_d), d_np, **TOL)

    def test_cut_window(self):
        pre = make_pre()
        cfg = make_cfg(pre)
        ev = make_event(7, ppks=(120, 400), spks=(200, 470))
        u = 0.63
        bound = max(min(list(ev["ppks"]) + [L - W]) - pre.min_event_gap, 1)
        c_l = da.u2i_np(u, bound)
        d_np, p_np, s_np = pre._cut_window(
            ev["data"].copy(), list(ev["ppks"]), list(ev["spks"]), W,
            da.ScriptedRNG([("integers", c_l)]),
        )
        pp, npp, ss, nss = phase_arrays(ev["ppks"], ev["spks"])
        d_d, pp2, npp2, ss2, nss2 = da.cut_window(
            cfg, jnp.asarray(ev["data"]), pp, npp, ss, nss, jnp.float32(u)
        )
        np.testing.assert_allclose(np.asarray(d_d), d_np, **TOL)
        assert list(np.asarray(pp2)[: int(npp2)]) == p_np
        assert list(np.asarray(ss2)[: int(nss2)]) == s_np

    @pytest.mark.parametrize("shape", ["gaussian", "triangle", "box"])
    def test_soft_labels(self, shape):
        pre = make_pre(soft_label_shape=shape)
        cfg = make_cfg(pre)
        from seist_tpu.data.preprocess import make_soft_window

        window = jnp.asarray(make_soft_window(40, shape), jnp.float32)
        # edge placements: left-clipped, middle, right-clipped, out-of-range
        ev = {"data": np.zeros((C, W), np.float32),
              "ppks": [3, 250], "spks": [40, W - 2], "snr": [20.0] * C}
        for name in ("ppk", "spk", "non", "det"):
            ref = pre._generate_soft_label(name, ev)
            pp, npp, ss, nss = phase_arrays(ev["ppks"], ev["spks"])
            proc = {"ppks": pp, "np_p": npp, "spks": ss, "np_s": nss,
                    "win": jnp.asarray(ev["data"]), "gen_fired": jnp.bool_(False)}
            ours = np.asarray(da._soft_item(cfg, name, proc, window))
            np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-5)

    def test_pad_phases_matches_reference(self):
        from seist_tpu.data.preprocess import pad_phases

        cases = [
            ([10, 50], [30, 80]),          # matched
            ([10, 50, 90], [30, 80]),      # trailing unmatched P
            ([50], [30]),                  # inverted pair
            ([], []),
        ]
        for ppks, spks in cases:
            ref_p, ref_s = pad_phases(list(ppks), list(spks), 40, W)
            pp, npp, ss, nss = phase_arrays(ppks, spks)
            dp, ds, n = da.pad_phases_dev(pp, npp, ss, nss, 40, W)
            n = int(n)
            assert list(np.asarray(dp)[:n]) == ref_p, (ppks, spks)
            assert list(np.asarray(ds)[:n]) == ref_s, (ppks, spks)


# --------------------------------------------------------- composed parity
_JITTED_PROCS = {}  # (cfg, names-repr) -> jitted row processor (compile once)


def _device_outputs(cfg, pre, event, input_names, label_names, epoch, idx,
                    augment=True):
    row = da.host_prepare(pre, event, cfg.phase_slots)
    row.pop("is_noise")
    key = (cfg, repr(input_names), repr(label_names))
    proc_fn = _JITTED_PROCS.get(key)
    if proc_fn is None:
        proc_fn = _JITTED_PROCS[key] = jax.jit(
            da.make_row_processor(cfg, input_names, label_names)
        )
    rows = jax.tree.map(lambda a: np.asarray(a)[None], row)
    return proc_fn(
        rows, jnp.asarray([idx], jnp.int32),
        jnp.asarray([augment]), jnp.int32(epoch),
    )


class TestComposedParity:
    @pytest.mark.parametrize("seed,epoch,idx", [
        (0, 1, 0), (1, 2, 3), (2, 3, 7), (3, 0, 11), (4, 5, 2),
    ])
    def test_dpk_end_to_end(self, seed, epoch, idx):
        """Every-op-armed config through process() + dpk labels. One
        shared cfg (seed=0) so the jitted processor compiles once; the
        event and the (epoch, idx) draw stream vary per case."""
        pre = make_pre()
        cfg = make_cfg(pre, seed=0)
        event = make_event(seed)
        draws = get_draws(cfg, epoch, idx)

        ev = copy.deepcopy(event)
        rng = da.make_replay_rng(pre, ev, draws, augmentation=True)
        ev = pre.process(ev, augmentation=True, rng=rng)
        rng.assert_exhausted()
        ref_in = pre.get_inputs(ev, [["z", "n", "e"]])
        ref_y = pre.get_targets_for_loss(ev, [["det", "ppk", "spk"]])
        ref_non = pre.get_io_item("non", ev)

        inputs, targets = _device_outputs(
            cfg, pre, event, [["z", "n", "e"]],
            [["det", "ppk", "spk"], "non"], epoch, idx,
        )
        np.testing.assert_allclose(np.asarray(inputs)[0], ref_in, **TOL)
        np.testing.assert_allclose(np.asarray(targets[0])[0], ref_y, **TOL)
        np.testing.assert_allclose(np.asarray(targets[1])[0], ref_non, **TOL)

    def test_generate_noise_branch(self):
        pre = make_pre(generate_noise_rate=1.0)
        cfg = make_cfg(pre, seed=5)
        event = make_event(5)
        draws = get_draws(cfg, 0, 0)
        ev = copy.deepcopy(event)
        rng = da.make_replay_rng(pre, ev, draws)
        ev = pre.process(ev, augmentation=True, rng=rng)
        rng.assert_exhausted()
        ref_in = pre.get_inputs(ev, [["z", "n", "e"]])
        inputs, targets = _device_outputs(
            cfg, pre, event, [["z", "n", "e"]], [["det", "ppk", "spk"]], 0, 0
        )
        np.testing.assert_allclose(np.asarray(inputs)[0], ref_in, **TOL)
        # labels cleared: det/ppk/spk all zero
        assert float(np.abs(np.asarray(targets)[0]).max()) == 0.0

    def test_no_augmentation_path(self):
        """idx < size samples: crop + normalize only (2x-epoch raw half).
        Shares the dpk test's cfg + label set so the compile is reused."""
        pre = make_pre()
        cfg = make_cfg(pre, seed=0)
        event = make_event(6)
        draws = get_draws(cfg, 2, 4)
        ev = copy.deepcopy(event)
        rng = da.make_replay_rng(pre, ev, draws, augmentation=False)
        ev = pre.process(ev, augmentation=False, rng=rng)
        rng.assert_exhausted()
        ref_in = pre.get_inputs(ev, [["z", "n", "e"]])
        inputs, _ = _device_outputs(
            cfg, pre, event, [["z", "n", "e"]],
            [["det", "ppk", "spk"], "non"], 2, 4, augment=False,
        )
        np.testing.assert_allclose(np.asarray(inputs)[0], ref_in, **TOL)

    def test_noise_trace_cleared(self):
        """_is_noise traces (inverted picks) lose their labels at upload."""
        pre = make_pre()
        cfg = make_cfg(pre, seed=0)
        event = make_event(7, ppks=(300,), spks=(100,))  # ppk >= spk
        draws = get_draws(cfg, 0, 1)
        ev = copy.deepcopy(event)
        rng = da.make_replay_rng(pre, ev, draws)
        ev = pre.process(ev, augmentation=True, rng=rng)
        rng.assert_exhausted()
        ref_y = pre.get_targets_for_loss(ev, [["det", "ppk", "spk"]])
        _, targets = _device_outputs(
            cfg, pre, event, [["z", "n", "e"]],
            [["det", "ppk", "spk"], "non"], 0, 1,
        )
        np.testing.assert_allclose(np.asarray(targets[0])[0], ref_y, **TOL)

    def test_value_and_max_norm(self):
        """VALUE labels (emg) + signed-max normalization parity."""
        pre = make_pre(norm_mode="max", generate_noise_rate=0.0)
        cfg = make_cfg(pre, seed=8)
        event = make_event(8)
        draws = get_draws(cfg, 1, 9)
        ev = copy.deepcopy(event)
        rng = da.make_replay_rng(pre, ev, draws)
        ev = pre.process(ev, augmentation=True, rng=rng)
        rng.assert_exhausted()
        ref_in = pre.get_inputs(ev, [["z", "n", "e"]])
        ref_emg = pre.get_targets_for_loss(ev, ["emg"])
        row = da.host_prepare(pre, event, cfg.phase_slots)
        row.pop("is_noise")
        row["values"] = {"emg": np.asarray(event["emg"], np.float32)}
        proc_fn = da.make_row_processor(cfg, [["z", "n", "e"]], ["emg"])
        rows = jax.tree.map(lambda a: np.asarray(a)[None], row)
        inputs, targets = jax.jit(proc_fn)(
            rows, jnp.asarray([9], jnp.int32), jnp.asarray([True]),
            jnp.int32(1),
        )
        np.testing.assert_allclose(np.asarray(inputs)[0], ref_in, **TOL)
        np.testing.assert_allclose(np.asarray(targets)[0], ref_emg, **TOL)


# ------------------------------------------------------ RNG / resume stability
class TestRngStability:
    def test_draws_are_order_free_and_stable(self):
        pre = make_pre()
        cfg = make_cfg(pre, seed=11)
        a = get_draws(cfg, 3, 17)
        # different call order / fresh process state: same values
        _ = get_draws(cfg, 9, 1)
        b = get_draws(cfg, 3, 17)
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])

    def test_distinct_keys_across_epoch_and_index(self):
        pre = make_pre()
        cfg = make_cfg(pre, seed=11)
        a = get_draws(cfg, 3, 17)
        for epoch, idx in [(4, 17), (3, 18)]:
            other = get_draws(cfg, epoch, idx)
            assert not np.allclose(a["gen_field"], other["gen_field"])


# ----------------------------------------------------------- executor parity
@pytest.fixture(scope="module")
def tiny():
    """Shared tiny training setup (phasenet @ 128 samples, batch 4) —
    module-scoped so the executor tests pay the dataset/store build once."""
    from seist_tpu.models import api
    from seist_tpu.train import build_optimizer, create_train_state

    in_samples, batch = 128, 4
    spec = taskspec.get_task_spec("phasenet")
    loss_fn = taskspec.make_loss("phasenet")
    sds = pl.from_task_spec(
        spec, "synthetic", "train", seed=3, in_samples=in_samples,
        augmentation=True, data_split=False, shuffle=True,
        shift_event_rate=0.5, add_noise_rate=0.5, add_gap_rate=0.5,
        drop_channel_rate=0.5, scale_amplitude_rate=0.5,
        pre_emphasis_rate=0.5, generate_noise_rate=0.1, add_event_rate=0.5,
        max_event_num=2,
        dataset_kwargs={"num_events": 8, "trace_samples": 192},
    )
    store = pl.RawStore.build(sds)
    cache = pl.DeviceEpochCache(store)
    cfg = da.AugConfig.from_preprocessor(
        sds.preprocessor, seed=3, raw_len=store.raw_len,
        phase_slots=store.phase_slots,
    )
    proc = da.make_cache_processor(
        cfg, sds.input_names, sds.label_names,
        n_raw=store.n_raw, augmentation=store.augmentation,
    )
    model = api.create_model("phasenet", in_samples=in_samples)
    variables = api.init_variables(
        model, in_samples=in_samples, batch_size=batch
    )

    def new_state():
        fresh = jax.tree.map(jnp.array, variables)
        # SGD, not Adam: the restart test compares trained params across
        # two runs of the same program, and XLA CPU's threaded reductions
        # can wiggle gradients at the ~1e-7 level under suite load —
        # Adam's v-normalization amplifies that to ~1e-3 within two
        # steps (observed in-suite), while SGD keeps it at lr*noise.
        return create_train_state(model, fresh, build_optimizer("sgd", 1e-2))

    def chunks(k, start=0, cache_=None):
        return list(
            (cache_ or cache).epoch_index_chunks(
                0, seed=3, shuffle=True, batch_size=batch,
                steps_per_call=k, start_batch=start,
            )
        )

    return dict(
        sds=sds, store=store, cache=cache, cfg=cfg, proc=proc,
        spec=spec, loss_fn=loss_fn, new_state=new_state, chunks=chunks,
        batch=batch,
    )


class TestCachedExecutor:
    def test_resume_through_restart_is_bit_exact(self, tiny):
        """Two steps of an uninterrupted run == one step, then a simulated
        preempt/restore (store re-decoded, cache re-uploaded, epoch order
        recomputed from the restored (epoch, batch) position), then the
        second step: the augmentation stream must not diverge. The jitted
        executable is reused across the restart — the XLA program is a
        pure function of the config, so a real restart recompiles the
        identical program; the fresh arrays prove the upload itself is
        deterministic."""
        from seist_tpu.train import jit_cached_call, make_cached_train_call

        sds, cache, proc = tiny["sds"], tiny["cache"], tiny["proc"]
        spec, loss_fn = tiny["spec"], tiny["loss_fn"]
        rng = jax.random.PRNGKey(0)

        call1 = jit_cached_call(
            make_cached_train_call(spec, loss_fn, proc, steps_per_call=1),
            None, cache.arrays,
        )
        chunks = tiny["chunks"](1)
        s_a = tiny["new_state"]()
        for c in chunks[:2]:  # uninterrupted
            s_a, _, _ = call1(
                s_a, cache.arrays, jnp.asarray(c), jnp.int32(0), rng
            )

        s_b = tiny["new_state"]()
        s_b, _, _ = call1(
            s_b, cache.arrays, jnp.asarray(chunks[0]), jnp.int32(0), rng
        )
        store2 = pl.RawStore.build(sds)  # the restart
        cache2 = pl.DeviceEpochCache(store2)
        chunk2 = tiny["chunks"](1, start=1, cache_=cache2)[0]
        # The augmentation stream itself must be BIT-exact across the
        # restart: same epoch order, same re-decoded store, same
        # processed (inputs, targets) for the resumed chunk.
        np.testing.assert_array_equal(chunk2, chunks[1])
        for a, b in zip(
            jax.tree.leaves(cache.arrays), jax.tree.leaves(cache2.arrays)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        x1, y1 = jax.jit(proc)(cache.arrays, jnp.asarray(chunk2[0]), jnp.int32(0))
        x2, y2 = jax.jit(proc)(cache2.arrays, jnp.asarray(chunk2[0]), jnp.int32(0))
        np.testing.assert_array_equal(np.asarray(x1), np.asarray(x2))
        np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
        s_b, _, _ = call1(
            s_b, cache2.arrays, jnp.asarray(chunk2), jnp.int32(0), rng
        )
        # Trained params: tight tolerance rather than bit-equality — XLA
        # CPU's threaded reductions may wiggle gradients ~1e-7 under
        # load; with SGD that stays at lr*noise, while a genuine stream
        # divergence would show at the 1e-3 scale.
        for a, b in zip(
            jax.tree.leaves(s_a.params), jax.tree.leaves(s_b.params)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=0, atol=1e-6
            )

    def test_chunking_is_pure_reshape_of_epoch_order(self, tiny):
        """steps_per_call only chunks the (shared) epoch order — k=2
        chunks are exactly the k=1 chunks stacked pairwise, so packing
        cannot change which sample lands in which step."""
        c1 = np.concatenate([c for c in tiny["chunks"](1)])
        c2 = np.concatenate([c for c in tiny["chunks"](2)])
        np.testing.assert_array_equal(c1[: len(c2)], c2)

    def test_cache_and_row_processors_agree(self, tiny):
        """The cached gather path and the host-fed row path build
        bit-identical (inputs, targets) for the same epoch indices."""
        sds, store, cache = tiny["sds"], tiny["store"], tiny["cache"]
        idx = tiny["chunks"](1)[0][0]
        x_c, y_c = jax.jit(tiny["proc"])(
            cache.arrays, jnp.asarray(idx), jnp.int32(0)
        )
        proc_rows = da.make_row_processor(
            tiny["cfg"], sds.input_names, sds.label_names
        )
        rows, sel, aug = next(
            pl.iter_raw_batches(
                store, 0, seed=3, shuffle=True, batch_size=tiny["batch"]
            )
        )
        np.testing.assert_array_equal(sel, idx)
        x_r, y_r = jax.jit(proc_rows)(
            jax.tree.map(jnp.asarray, rows), jnp.asarray(sel),
            jnp.asarray(aug), jnp.int32(0),
        )
        np.testing.assert_array_equal(np.asarray(x_c), np.asarray(x_r))
        np.testing.assert_array_equal(np.asarray(y_c), np.asarray(y_r))

    def test_epoch_order_matches_host_loader(self, tiny):
        """Device executors consume the exact global sample sequence the
        host Loader would (pipeline.epoch_indices is shared)."""
        loader = pl.Loader(
            tiny["sds"], batch_size=tiny["batch"], shuffle=True, seed=3
        )
        loader.set_epoch(0)
        host_order = loader._indices()
        dev_order = np.concatenate(
            [c.reshape(-1) for c in tiny["chunks"](1)]
        )
        np.testing.assert_array_equal(host_order[: len(dev_order)], dev_order)


# ------------------------------------------------------- input-split bench
class TestInputSplit:
    def test_step_time_split_math(self):
        from seist_tpu.utils.profiling import StepTimeSplit

        s = StepTimeSplit(skip_first=1)
        s.step(9.0, 9.0)  # compile step — excluded
        s.step(0.003, 0.001)
        s.step(0.001, 0.003)
        out = s.summary()
        assert out["steps"] == 2
        assert out["host_wait_ms_per_step"] == 2.0
        assert out["device_time_ms_per_step"] == 2.0
        assert out["input_bound_fraction"] == 0.5
        assert len(out["per_step_host_wait_ms"]) == 2
        assert StepTimeSplit().summary()["input_bound_fraction"] is None

    @pytest.mark.slow  # two extra jit compiles; bench.py runs this live
    def test_measure_input_split_cached_removes_host_stacking(self):
        """The acceptance claim on the CPU microbench: the cached
        device-aug path's per-step host wait is measurably below the
        host path's (which pays per-sample numpy augmentation + Python
        stacking + device_put), in the SAME run."""
        import bench as bench_mod

        spec = taskspec.get_task_spec("phasenet")
        loss_fn = taskspec.make_loss("phasenet")
        cfg = {
            "model": "phasenet",
            "batch": 4,
            "in_samples": 256,
            "dtype": "fp32",
            "steps_per_call": 1,
            "lowering_overrides": {},
        }
        split = bench_mod.measure_input_split(spec, loss_fn, cfg, steps=3)
        host = split["host_path"]
        cached = split["device_aug_cached"]
        assert host["input_bound_fraction"] is not None
        assert cached["input_bound_fraction"] is not None
        assert split["host_stack_removed"]
        assert (
            cached["host_wait_ms_per_step"] < host["host_wait_ms_per_step"]
        )
        assert len(host["per_step_host_wait_ms"]) == 3


# ------------------------------------------------------- fallback selection
class TestFallbackSelection:
    def test_select_modes(self):
        sel = da.select_device_aug_mode
        assert sel("off", 0, 100, []) == ("off", "")
        assert sel("cached", 50, 100, [])[0] == "cached"
        mode, why = sel("cached", 200, 100, [])
        assert mode == "step" and "budget" in why
        mode, why = sel("cached", 50, 100, ["mask_percent"])
        assert mode == "off" and "mask_percent" in why
        mode, why = sel("step", 10**12, 100, [])
        assert mode == "step"
        with pytest.raises(ValueError):
            sel("bogus", 0, 0, [])

    def test_unsupported_reasons(self):
        pre = make_pre(mask_percent=10)
        assert da.unsupported_reasons(pre, [["z", "n", "e"]], [["det"]])
        pre = make_pre()
        assert da.unsupported_reasons(pre, [["z", "n", "e"]], [["det", "ppk", "spk"]]) == []
        # generate_noise + VALUE label is the host-crash case: refused
        pre = make_pre(generate_noise_rate=0.1)
        assert any(
            "emg" in r
            for r in da.unsupported_reasons(pre, [["z", "n", "e"]], ["emg"])
        )
        # p_position_ratio mode is host-only
        pre = make_pre(p_position_ratio=0.5)
        assert da.unsupported_reasons(pre, [["z", "n", "e"]], [["det"]])

    def test_hbm_budget_explicit(self):
        assert da.hbm_budget_bytes(2.0) == 2 << 30
        assert da.hbm_budget_bytes(0.0) > 0

    def test_store_estimate_close_to_actual(self):
        sds = pl.from_task_spec(
            taskspec.get_task_spec("phasenet"), "synthetic", "train",
            seed=0, in_samples=256, augmentation=False, data_split=False,
            dataset_kwargs={"num_events": 6, "trace_samples": 300},
        )
        est = pl.RawStore.estimate_bytes(sds)
        store = pl.RawStore.build(sds)
        assert est <= store.nbytes <= est * 1.5

    def test_store_rejects_ragged_lengths(self):
        class Ragged:
            pass

        sds = pl.from_task_spec(
            taskspec.get_task_spec("phasenet"), "synthetic", "train",
            seed=0, in_samples=256, augmentation=False, data_split=False,
            dataset_kwargs={"num_events": 4, "trace_samples": 300},
        )
        orig = sds.raw_event

        def ragged(idx):
            ev, meta = orig(idx)
            if idx == 2:
                ev = dict(ev, data=ev["data"][:, :-7])
            return ev, meta

        sds.raw_event = ragged
        with pytest.raises(ValueError, match="uniform raw trace"):
            pl.RawStore.build(sds)
