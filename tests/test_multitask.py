"""Shared-backbone multi-task serving (ISSUE 10): trunk/head split,
trunk-once fan-out, AOT zero-compile request path, quantized variants.

The acceptance pins live here:

* a multi-task /predict answers ALL requested heads from ONE trunk run
  (trunk-application counter == 1 per trace);
* compiled cost_analysis FLOPs of a 3-task fan-out <= 0.5x the sum of
  three single-task calls;
* after warm-up, a CompileBudget window over a mixed single-/multi-task
  request storm records ZERO traces/compiles;
* bf16 variant picks identical to fp32 post-decode (parity gate);
* the PR 1 single-task wire format is unchanged against the rewired
  pool (tests/test_serve.py runs its full e2e on the same pool code).
"""

import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from seist_tpu.serve import aot
from seist_tpu.serve.batcher import BatcherConfig, MicroBatcher, _slice_outputs
from seist_tpu.serve.protocol import BadRequest, PredictOptions, parse_tasks

WINDOW = 256
TASKS = ("dpk", "emg", "dis")


# ------------------------------------------------------------ model split
def test_trunk_head_split_parity():
    """full forward == head(backbone) bit-for-bit, per head family."""
    import jax
    import jax.numpy as jnp

    import seist_tpu
    from seist_tpu.models import api
    from seist_tpu.models.seist import (
        backbone_apply,
        head_apply,
        supports_trunk_split,
    )

    seist_tpu.load_all()
    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.standard_normal((2, 128, 3)).astype(np.float32)
    )
    for name in ("seist_s_dpk", "seist_s_emg", "seist_s_pmp"):
        model = api.create_model(name, in_channels=3, in_samples=128)
        assert supports_trunk_split(model)
        variables = api.init_variables(
            model, seed=0, in_samples=128, in_channels=3
        )
        full = model.apply(variables, x, train=False)
        feats = backbone_apply(model, variables, x)
        assert feats.shape[1] == 128 // 64  # stem /4, 4 stages /2 each
        split = head_apply(model, variables, feats, x)
        assert jax.tree_util.tree_all(
            jax.tree_util.tree_map(
                lambda a, b: jnp.array_equal(a, b), full, split
            )
        )


def test_split_unknown_mode_and_missing_features_rejected():
    import seist_tpu
    from seist_tpu.models import api
    from seist_tpu.models.seist import supports_trunk_split

    seist_tpu.load_all()
    model = api.create_model("seist_s_emg", in_channels=3, in_samples=128)
    variables = api.init_variables(
        model, seed=0, in_samples=128, in_channels=3
    )
    x = np.zeros((1, 128, 3), np.float32)
    with pytest.raises(ValueError, match="unknown mode"):
        model.apply(variables, x, train=False, mode="sideways")
    with pytest.raises(ValueError, match="requires features"):
        model.apply(variables, x, train=False, mode="head")
    # phasenet has no split: groups must refuse it at load.
    assert not supports_trunk_split(
        api.create_model("phasenet", in_channels=3, in_samples=128)
    )


# ------------------------------------------------------------ group pool
@pytest.fixture(scope="module")
def group_service():
    """One pool serving a 3-task seist_s group (fp32+bf16) AND a plain
    phasenet entry — the mixed fleet the storm test exercises."""
    from seist_tpu.serve.pool import ModelPool
    from seist_tpu.serve.server import ServeService

    pool = ModelPool(
        [("phasenet", "")],
        groups=[("seist_s", [(t, "") for t in TASKS])],
        window=WINDOW,
        variants=("fp32", "bf16"),
    )
    svc = ServeService(
        pool, BatcherConfig(max_batch=2, max_delay_ms=5.0, max_queue=64)
    )
    yield svc, pool
    svc.shutdown()


@pytest.fixture(scope="module")
def trace():
    rng = np.random.default_rng(7)
    return rng.standard_normal((WINDOW, 3)).astype(np.float32).tolist()


def test_trunk_weights_shared_across_heads(group_service):
    _, pool = group_service
    entry = pool.get("seist_s")
    trunk_leaves = entry.trunk_variables["params"]
    for task in TASKS:
        hv = entry.heads[task].variables["params"]
        for key, val in trunk_leaves.items():
            assert hv[key] is val  # same arrays, not copies
        assert "out_head" in hv


def test_multitask_predict_one_trunk_run_all_heads(group_service, trace):
    svc, pool = group_service
    entry = pool.get("seist_s")
    before = entry.fanout_stats()
    res = svc.predict(trace, model="seist_s", tasks=list(TASKS))
    after = entry.fanout_stats()
    # All requested heads answered, from ONE trunk execution.
    assert sorted(res["tasks"]) == sorted(TASKS)
    assert res["trunk_runs"] == 1
    assert after["trunk_runs"] - before["trunk_runs"] == 1
    for t in TASKS:
        assert after["head_runs"][t] - before["head_runs"].get(t, 0) == 1
    assert res["tasks"]["dpk"]["task"] == "picking"
    assert res["tasks"]["emg"]["task"] == "regression"
    # Amortization is accounted: 2 extra heads' trunk FLOPs were saved.
    assert (
        after["trunk_flops_saved"] > before["trunk_flops_saved"]
    )


def test_default_tasks_is_all_and_subset_respected(group_service, trace):
    svc, _ = group_service
    res = svc.predict(trace, model="seist_s")  # no tasks field
    assert sorted(res["tasks"]) == sorted(TASKS)
    res = svc.predict(trace, model="seist_s", tasks=["emg"])
    assert list(res["tasks"]) == ["emg"]


def test_unknown_task_and_single_task_model_rejected(group_service, trace):
    svc, pool = group_service
    with pytest.raises(BadRequest, match="does not serve tasks"):
        svc.predict(trace, model="seist_s", tasks=["baz"])
    with pytest.raises(BadRequest, match="single-task"):
        svc.predict(trace, model="phasenet", tasks=["dpk"])
    # and the resolve contract directly:
    assert pool.get("seist_s").resolve_tasks(None) == TASKS


def test_single_task_wire_format_unchanged(group_service, trace):
    """PR 1 shape on the rewired pool: flat result, model key, no tasks
    envelope."""
    svc, _ = group_service
    res = svc.predict(
        trace, model="phasenet",
        options={"ppk_threshold": 0.05, "spk_threshold": 0.05},
    )
    assert res["model"] == "phasenet"
    assert res["task"] == "picking"
    assert "tasks" not in res and "trunk_runs" not in res


def test_fanout_flops_at_most_half_of_three_singles(group_service):
    """The headline acceptance: compiled cost_analysis FLOPs of the
    3-task fan-out (trunk + 3 heads) vs three single-task calls (each
    trunk + head)."""
    _, pool = group_service
    entry = pool.get("seist_s")
    trunk = entry.programs[("fp32", "trunk", 1)].flops
    heads = {t: entry.programs[("fp32", t, 1)].flops for t in TASKS}
    assert trunk > 0 and all(f > 0 for f in heads.values())
    fanout_flops = trunk + sum(heads.values())
    three_singles = sum(trunk + h for h in heads.values())
    assert fanout_flops <= 0.5 * three_singles, (
        f"fan-out {fanout_flops:.3g} > 0.5x singles {three_singles:.3g}"
    )


def test_storm_after_warmup_compiles_nothing(group_service, trace):
    """AOT acceptance: a mixed single-/multi-task, fp32/bf16 request
    storm after warm-up triggers ZERO jax traces — every forward is a
    pre-compiled executable, every decode a warm program."""
    from tools.jaxlint.runtime import CompileBudget

    svc, _ = group_service
    # Settle anything the fixture's own construction left pending.
    svc.predict(trace, model="seist_s")
    svc.predict(trace, model="phasenet")
    svc.predict(trace, model="seist_s", options={"variant": "bf16"})

    reqs = [
        lambda: svc.predict(trace, model="seist_s", tasks=["dpk", "emg"]),
        lambda: svc.predict(trace, model="seist_s", tasks=["emg"]),
        lambda: svc.predict(trace, model="phasenet"),
        lambda: svc.predict(trace, model="seist_s"),
        lambda: svc.predict(
            trace, model="seist_s", tasks=["dis"],
            options={"variant": "bf16"},
        ),
    ] * 3
    with CompileBudget() as budget:
        with ThreadPoolExecutor(4) as ex:
            results = [f.result() for f in [ex.submit(r) for r in reqs]]
    assert len(results) == len(reqs)
    assert budget.compiles == {}, (
        f"request path compiled after warm-up: {budget.compiles}"
    )


def test_bf16_variant_picks_identical_post_decode(group_service, trace):
    """Quantized-variant parity acceptance: bf16 answers the same
    decoded picks (and regression values within float noise) as fp32."""
    svc, pool = group_service
    entry = pool.get("seist_s")
    assert entry.variant_tasks["bf16"] == TASKS  # gate passed at load
    r32 = svc.predict(trace, model="seist_s")
    r16 = svc.predict(trace, model="seist_s", options={"variant": "bf16"})
    assert r16["variant"] == "bf16"
    for kind in ("ppk", "spk", "det"):
        assert r32["tasks"]["dpk"].get(kind) == r16["tasks"]["dpk"].get(kind)
    for t in ("emg", "dis"):
        v32 = r32["tasks"][t][t]
        v16 = r16["tasks"][t][t]
        assert v16 == pytest.approx(v32, abs=1e-2)


def test_variant_not_loaded_or_gated_is_400(group_service, trace):
    svc, pool = group_service
    with pytest.raises(BadRequest, match="variant 'int8' is not loaded"):
        svc.predict(trace, model="seist_s", options={"variant": "int8"})
    # A gate failure disables a loaded variant the same way:
    entry = pool.get("seist_s")
    saved = entry.variant_tasks["bf16"]
    try:
        entry.variant_tasks["bf16"] = ("emg",)  # dpk/dis "failed" parity
        with pytest.raises(BadRequest, match="variant 'bf16'"):
            svc.predict(
                trace, model="seist_s", tasks=["dpk"],
                options={"variant": "bf16"},
            )
        svc.predict(  # still served where it passed
            trace, model="seist_s", tasks=["emg"],
            options={"variant": "bf16"},
        )
    finally:
        entry.variant_tasks["bf16"] = saved


def test_loaded_variant_served_during_warmup_window(group_service, trace):
    """A loaded variant must not bounce 400 while the async warm-up is
    still computing parity gates — the pre-warm fallback contract fp32
    gets applies to every LOADED variant (review finding: fleet rolls
    were 400ing bf16 clients for the whole warm-up window). An UNLOADED
    variant stays a 400 even then (it has no batcher at all)."""
    svc, pool = group_service
    entry = pool.get("seist_s")
    saved_tasks = dict(entry.variant_tasks)
    try:
        svc._warming = True
        entry.variant_tasks.pop("bf16", None)  # gates "not yet computed"
        res = svc.predict(
            trace, model="seist_s", tasks=["emg"],
            options={"variant": "bf16"},
        )
        assert res["variant"] == "bf16"
        with pytest.raises(BadRequest, match="not loaded"):
            svc.predict(trace, model="seist_s", options={"variant": "int8"})
    finally:
        svc._warming = False
        entry.variant_tasks.clear()
        entry.variant_tasks.update(saved_tasks)


def test_warmup_probes_do_not_inflate_fanout_accounting():
    """trunk_runs / flops-saved counters measure SERVED traffic: a
    freshly warmed group starts at zero (review finding: warm-up +
    parity-gate probes were pre-charging the amortization stats that
    bench_serve copies into its JSON)."""
    from seist_tpu.serve.pool import load_group_entry

    entry = load_group_entry(
        "seist_s", [("emg", ""), ("dis", "")], window=128,
        variants=("fp32", "bf16"),
    )
    entry.build_programs([1], [])  # includes the parity-gate probes
    stats = entry.fanout_stats()
    assert stats["trunk_runs"] == 0
    assert stats["head_runs"] == {}
    assert stats["trunk_flops_saved"] == 0.0
    entry.fanout(np.zeros((1, 128, 3), np.float32), ("emg",))
    assert entry.fanout_stats()["trunk_runs"] == 1


def test_annotate_rejects_variant_selection(group_service):
    svc, _ = group_service
    rng = np.random.default_rng(5)
    record = rng.standard_normal((WINDOW * 2, 3)).astype(np.float32)
    with pytest.raises(BadRequest, match="/predict-only"):
        svc.annotate(
            record.tolist(), model="seist_s", options={"variant": "bf16"}
        )


def test_annotate_streams_through_group_trunk(group_service):
    """/annotate on a group: sliding windows through trunk+dpk AOT."""
    svc, pool = group_service
    entry = pool.get("seist_s")
    rng = np.random.default_rng(3)
    record = rng.standard_normal((WINDOW * 3, 3)).astype(np.float32)
    before = entry.fanout_stats()["trunk_runs"]
    res = svc.annotate(record.tolist(), model="seist_s")
    assert res["model"] == "seist_s"
    assert res["windows"] >= 5
    assert entry.fanout_stats()["trunk_runs"] > before


def test_aot_compile_gauge_and_healthz_report(group_service):
    from seist_tpu.obs.bus import BUS

    svc, pool = group_service
    assert BUS.gauge("serve_aot_compile_ms", model="seist_s").value > 0
    assert BUS.gauge("serve_aot_programs", model="seist_s").value >= 16
    # warm-up report carries per-program compile entries + decode warms
    programs = [
        r for r in pool.warmup_report
        if r["model"] == "seist_s" and "program" in r
    ]
    assert len(programs) == 2 * 2 * (1 + len(TASKS))  # buckets x variants
    decodes = [
        r for r in pool.warmup_report
        if str(r.get("batch", "")).startswith("decode")
    ]
    assert len(decodes) == len(TASKS) + 1  # per group task + phasenet
    # and the metrics surface exposes the fan-out accounting
    m = svc.metrics()
    assert "seist_s" in m["fanout"]
    assert set(m["models"]) >= {
        "phasenet", "seist_s", "seist_s@bf16", "phasenet@bf16",
    }


# ------------------------------------------------------- batcher fan-out
def test_batcher_unions_tasks_and_slices_dict_outputs():
    """Task-blind batching: concurrent requests wanting different heads
    coalesce into ONE forward over the UNION of their tasks."""
    seen = []
    release = threading.Event()

    def forward(batch, tasks=None):
        seen.append((batch.shape[0], tasks))
        return {t: np.full((batch.shape[0], 2), ord(t[0])) for t in tasks}

    b = MicroBatcher(
        forward,
        BatcherConfig(max_batch=2, max_delay_ms=40.0),
        name="union-test",
    )
    try:
        with ThreadPoolExecutor(2) as ex:
            release.set()
            f1 = ex.submit(
                b.submit, np.zeros((4, 1)), 2000.0, 1, frozenset({"aa"})
            )
            f2 = ex.submit(
                b.submit, np.zeros((4, 1)), 2000.0, 1, frozenset({"bb"})
            )
            r1, r2 = f1.result(), f2.result()
        # One coalesced forward saw the union (or, under unlucky timing,
        # two flushes each saw their own task set — never a mixed-up one).
        for n, tasks in seen:
            assert tasks is not None and tasks <= {"aa", "bb"}
        for r in (r1, r2):
            for t in r:
                assert r[t].shape == (1, 2)
        total = b.stats()
        assert total["completed"] == 2
    finally:
        b.shutdown()


def test_slice_outputs_handles_dicts():
    out = {
        "dpk": np.arange(12).reshape(3, 4),
        "pmp": (np.arange(6).reshape(3, 2), np.arange(3).reshape(3, 1)),
    }
    s = _slice_outputs(out, 1)
    assert s["dpk"].shape == (1, 4) and s["dpk"][0, 0] == 4
    assert s["pmp"][0].shape == (1, 2) and s["pmp"][1].shape == (1, 1)


# ----------------------------------------------------------- aot units
def test_aot_compile_returns_flops_and_runs():
    import jax.numpy as jnp

    prog = aot.aot_compile(
        "unit/matmul", lambda x: x @ x.T, [((8, 16), jnp.float32)],
        model="unit",
    )
    assert prog.flops > 0
    out = prog(np.ones((8, 16), np.float32))
    assert np.asarray(out).shape == (8, 8)
    assert prog.compile_ms > 0


def test_quantize_int8_roundtrip_and_structure():
    rng = np.random.default_rng(0)
    variables = {
        "params": {
            "w": rng.standard_normal((16, 8)).astype(np.float32) * 3.0,
            "b": rng.standard_normal((8,)).astype(np.float32),
        }
    }
    packed = aot.quantize_int8(variables)
    q = packed["params"]["w"]
    assert set(q) == {"__int8__", "scale"}
    assert np.asarray(q["__int8__"]).dtype == np.int8
    # 1-D leaves stay fp32 untouched
    assert np.array_equal(
        np.asarray(packed["params"]["b"]), variables["params"]["b"]
    )
    restored = aot.dequantize(packed)
    w = variables["params"]["w"]
    # symmetric per-out-channel quant: error <= one step = scale
    step = np.abs(w).max(axis=0) / 127.0
    assert np.all(
        np.abs(np.asarray(restored["params"]["w"]) - w) <= step + 1e-7
    )


def test_make_variant_apply_is_eager_and_casts_outputs():
    import jax.numpy as jnp

    w = np.full((4, 4), 2.0, np.float32)
    calls = []

    def apply_fn(variables, x):
        calls.append(jnp.asarray(variables["w"]).dtype)
        return x @ variables["w"]

    x = np.ones((2, 4), np.float32)
    for variant, want_dtype in (
        ("fp32", jnp.float32),
        ("bf16", jnp.bfloat16),
        ("int8", jnp.float32),  # weight-only: dequantized to f32 compute
    ):
        fn = aot.make_variant_apply(apply_fn, {"w": w}, variant)
        out = fn(jnp.asarray(x))
        assert out.dtype == jnp.float32  # decode is variant-blind
        assert np.allclose(np.asarray(out), x @ w, atol=0.1)
        assert calls[-1] == want_dtype
    with pytest.raises(ValueError, match="unknown variant"):
        aot.make_variant_apply(apply_fn, {"w": w}, "fp8")


def test_variant_parity_gate_decisions():
    a = np.zeros((1, 32, 3), np.float32)
    a[0, :, 0] = 0.9  # clear channel-0 winner
    ok, _ = aot.variant_parity(a, a + 1e-3, "bf16", kind="soft")
    assert ok
    flipped = a.copy()
    flipped[0, :, 1] = 1.5  # argmax flips everywhere
    ok, _ = aot.variant_parity(a, flipped, "bf16", kind="soft")
    assert not ok
    big = a + 0.5  # same argmax, but way past abs tolerance
    ok, _ = aot.variant_parity(a, big, "bf16", kind="soft")
    assert not ok
    # onehot: any argmax change fails
    c = np.asarray([[0.2, 0.8]], np.float32)
    assert aot.variant_parity(c, c + 1e-4, "int8", kind="onehot")[0]
    assert not aot.variant_parity(
        c, c[:, ::-1], "int8", kind="onehot"
    )[0]
    # value: relative to the head's output scale
    v = np.asarray([[180.0]], np.float32)
    assert aot.variant_parity(
        v, v + 1.0, "bf16", kind="value", scale=360.0
    )[0]
    assert not aot.variant_parity(
        v, v + 30.0, "bf16", kind="value", scale=360.0
    )[0]


# ------------------------------------------------------- protocol units
def test_parse_tasks_validation():
    assert parse_tasks(None) is None
    assert parse_tasks(["dpk", "emg"]) == ("dpk", "emg")
    for bad in ("dpk", [], [1], ["dpk", "dpk"], {"dpk": 1}):
        with pytest.raises(BadRequest):
            parse_tasks(bad)


def test_variant_option_validated():
    assert PredictOptions.from_dict({"variant": "bf16"}).variant == "bf16"
    with pytest.raises(BadRequest, match="variant"):
        PredictOptions.from_dict({"variant": "fp8"})
    with pytest.raises(BadRequest):
        PredictOptions.from_dict({"variant": 16})


def test_parse_group_flags():
    import argparse

    from seist_tpu.serve.server import parse_group_flags

    ns = argparse.Namespace(
        model_group=["seist_s=dpk:ck1,emg", "seist_l=dis:ck2"]
    )
    assert parse_group_flags(ns) == [
        ("seist_s", [("dpk", "ck1"), ("emg", "")]),
        ("seist_l", [("dis", "ck2")]),
    ]
    for bad in (["seist_s"], ["=dpk"], ["seist_s="], ["seist_s=dpk,,"]):
        with pytest.raises(SystemExit):
            parse_group_flags(argparse.Namespace(model_group=bad))


def test_group_loader_validation(monkeypatch):
    from seist_tpu.serve import pool as pool_mod

    with pytest.raises(ValueError, match="unknown task"):
        pool_mod.load_group_entry("seist_s", [("xyz", "")], window=128)
    with pytest.raises(ValueError, match="at least one task"):
        pool_mod.load_group_entry("seist_s", [], window=128)
    with pytest.raises(ValueError, match="duplicate task"):
        pool_mod.load_group_entry(
            "seist_s", [("emg", ""), ("emg", "")], window=128
        )

    # A model family without the trunk/head split must be refused at
    # load, not crash at serve time: splice phasenet in as 'the model'.
    def fake_parts(model_name, checkpoint, *, window, seed):
        import seist_tpu
        from seist_tpu import taskspec
        from seist_tpu.models import api

        seist_tpu.load_all()
        model = api.create_model(
            "phasenet", in_channels=3, in_samples=window
        )
        return (
            model,
            {"params": {}},
            taskspec.get_task_spec("phasenet"),
            3,
            "non",
        )

    monkeypatch.setattr(pool_mod, "_load_parts", fake_parts)
    with pytest.raises(ValueError, match="no trunk/head split"):
        pool_mod.load_group_entry("seist_s", [("dpk", "")], window=128)


def test_check_variants_normalization():
    from seist_tpu.serve.pool import _check_variants

    assert _check_variants(("bf16",)) == ("fp32", "bf16")
    assert _check_variants(("fp32", "fp32", "int8")) == ("fp32", "int8")
    with pytest.raises(ValueError, match="unknown variants"):
        _check_variants(("fp4",))
