"""Tests for dataset readers, split logic, and the input pipeline."""

import json

import numpy as np
import pytest

import seist_tpu
from seist_tpu.data import pipeline
from seist_tpu.data.diting import convert_to_ml, normalize_key
from seist_tpu.data.pnw import parse_trace_name
from seist_tpu.data.synthetic import Synthetic
from seist_tpu import taskspec

seist_tpu.load_all()


class TestFormatQuirks:
    def test_diting_key_padding(self):
        assert normalize_key("123.45") == "000123.4500"
        assert normalize_key("123456.7890") == "123456.7890"

    def test_mag_conversion(self):
        assert convert_to_ml(2.0, "ml") == 2.0
        assert convert_to_ml(2.0, "ms") == pytest.approx((2.0 + 1.08) / 1.13)
        assert convert_to_ml(2.0, "mb") == pytest.approx((1.17 * 2.0 + 0.67) / 1.13)
        with pytest.raises(ValueError):
            convert_to_ml(2.0, "mw")

    def test_pnw_trace_name(self):
        assert parse_trace_name("bucket3$42,:3,:15001") == ("bucket3", 42)


class TestSplit:
    def test_split_disjoint_and_seeded(self):
        parts = {}
        for mode in ("train", "val", "test"):
            ds = Synthetic(
                seed=7, mode=mode, num_events=100, trace_samples=2000
            )
            parts[mode] = set(int(ds._meta_data.iloc[i]["idx"]) for i in range(len(ds)))
        assert len(parts["train"]) == 80
        assert len(parts["val"]) == 10
        assert len(parts["test"]) == 10
        assert not (parts["train"] & parts["val"])
        assert not (parts["train"] & parts["test"])
        # Same seed -> same split
        ds2 = Synthetic(seed=7, mode="val", num_events=100, trace_samples=2000)
        assert set(int(ds2._meta_data.iloc[i]["idx"]) for i in range(len(ds2))) == parts["val"]
        # Different seed -> different membership (overwhelmingly likely)
        ds3 = Synthetic(seed=8, mode="val", num_events=100, trace_samples=2000)
        assert set(int(ds3._meta_data.iloc[i]["idx"]) for i in range(len(ds3))) != parts["val"]


def make_sds(mode="train", augmentation=False, n=24, in_samples=1024):
    spec = taskspec.get_task_spec("seist_s_dpk")
    return pipeline.from_task_spec(
        spec,
        "synthetic",
        mode,
        seed=3,
        in_samples=in_samples,
        augmentation=augmentation,
        dataset_kwargs={"num_events": n, "trace_samples": 4 * in_samples},
    )


class TestSeismicDataset:
    def test_item_contract(self):
        sds = make_sds()
        inputs, loss_targets, metrics_targets, meta = sds[0]
        assert inputs.shape == (1024, 3)  # channels-last (L, C)
        assert loss_targets.shape == (1024, 3)  # (non, ppk, spk) soft labels
        assert set(metrics_targets) == {"det", "ppk", "spk"}
        assert metrics_targets["ppk"].shape == (1,)
        assert metrics_targets["det"].shape == (2,)
        json.loads(meta)

    def test_augmentation_doubles_epoch(self):
        plain = make_sds(augmentation=False)
        aug = make_sds(augmentation=True)
        assert len(aug) == 2 * len(plain)

    def test_augmentation_off_for_val(self):
        sds = make_sds(mode="val", augmentation=True)
        assert len(sds) == len(sds._dataset)

    def test_deterministic(self):
        a = make_sds(augmentation=True)
        b = make_sds(augmentation=True)
        idx = len(a) - 1  # augmented half
        ia, la, _, _ = a[idx]
        ib, lb, _, _ = b[idx]
        np.testing.assert_array_equal(ia, ib)
        np.testing.assert_array_equal(la, lb)


class TestLoader:
    def test_batches_fixed_shape(self):
        sds = make_sds(n=20)
        loader = pipeline.Loader(sds, batch_size=8, drop_last=False, num_workers=2)
        batches = list(loader)
        assert len(batches) == 2  # 16 train events (80% of 20) -> 2 batches of 8
        for b in batches:
            assert b.inputs.shape == (8, 1024, 3)
            assert b.mask.shape == (8,)
        assert batches[0].mask.sum() == 8

    def test_drop_last(self):
        sds = make_sds(n=20)  # 16 train events
        loader = pipeline.Loader(sds, batch_size=5, drop_last=True)
        assert len(loader) == 3
        assert len(list(loader)) == 3

    def test_shard_partition(self):
        sds = make_sds(n=30)
        all_meta = []
        for shard in range(2):
            loader = pipeline.Loader(
                sds, batch_size=4, num_shards=2, shard_index=shard
            )
            for b in loader:
                all_meta.extend(m for i, m in enumerate(b.meta) if b.mask[i] > 0)
        # Each event appears exactly once across the two shards.
        assert len(all_meta) == len(sds)
        assert len(set(all_meta)) == len(sds)

    def test_uneven_shards_equal_batch_counts(self):
        # 19 train events (80% of 24), 2 shards, batch 3: without wrap
        # padding host0 gets 10 rows / host1 9 -> different batch counts ->
        # multi-host collective deadlock (code-review finding).
        sds = make_sds(n=24)
        lens = set()
        for shard in range(2):
            loader = pipeline.Loader(
                sds, batch_size=3, num_shards=2, shard_index=shard, drop_last=True
            )
            lens.add(len(loader))
            assert len(list(loader)) == len(loader)
        assert len(lens) == 1

    def test_epoch_reshuffle(self):
        sds = make_sds(n=30)
        loader = pipeline.Loader(sds, batch_size=8, shuffle=True, drop_last=True)
        loader.set_epoch(0)
        first = [b.meta for b in loader]
        loader.set_epoch(1)
        second = [b.meta for b in loader]
        assert first != second

    def test_prefetch_to_device(self):
        import jax
        from seist_tpu.parallel.mesh import make_mesh

        sds = make_sds(n=20)
        loader = pipeline.Loader(sds, batch_size=8, drop_last=True)
        mesh = make_mesh(data=8)
        out = list(pipeline.prefetch_to_device(iter(loader), mesh))
        assert len(out) == 2
        assert isinstance(out[0].inputs, jax.Array)
        assert out[0].inputs.sharding.spec[0] == "data"


class TestBenchBatchBuilder:
    """bench.py builds its batches through this pipeline for ANY registered
    model; guard the k-stacking and per-task label shapes it relies on."""

    @pytest.mark.parametrize(
        "name,tgt_shape",
        [("seist_s_dpk", (2, 256, 3)), ("seist_m_pmp", (2, 2)),
         ("seist_l_emg", (2, 1))],
    )
    def test_shapes(self, name, tgt_shape):
        import bench

        spec = taskspec.get_task_spec(name)
        x, y = bench._synthetic_batch(spec, batch=2, in_samples=256)
        assert x.shape == (2, 256, 3)
        assert y.shape == tgt_shape

    def test_k_stacking_distinct(self):
        import bench

        spec = taskspec.get_task_spec("seist_s_dpk")
        x, y = bench._synthetic_batch(spec, batch=2, in_samples=256, k=3)
        assert x.shape == (3, 2, 256, 3) and y.shape == (3, 2, 256, 3)
        # k micro-batches must be distinct events, not copies.
        assert not np.allclose(np.asarray(x[0]), np.asarray(x[1]))


class TestPackedPrefetch:
    def test_groups_and_drops_tail(self):
        sds = make_sds(n=24)  # train split: int(0.8*24) = 19 samples
        loader = pipeline.Loader(sds, batch_size=4, drop_last=True)
        assert len(loader) == 4  # 19 // 4
        packed = list(
            pipeline.prefetch_packed_to_device(iter(loader), None, 3)
        )
        # 4 batches // 3 per call = 1 full group; trailing 1 batch dropped.
        assert len(packed) == 1
        xk, yk = packed[0]
        assert xk.shape[0] == 3 and xk.shape[1] == 4

    def test_sharded_placement(self):
        import jax
        from seist_tpu.parallel.mesh import make_mesh

        sds = make_sds(n=24)  # train split 19 -> 2 full batches of 8
        loader = pipeline.Loader(sds, batch_size=8, drop_last=True)
        mesh = make_mesh(data=8)
        xk, yk = next(
            pipeline.prefetch_packed_to_device(iter(loader), mesh, 2)
        )
        assert isinstance(xk, jax.Array)
        assert xk.sharding.spec[:2] == (None, "data")


class TestProcessWorkers:
    def test_process_pool_batches_match_threads(self):
        """worker_processes must produce bit-identical batches to the
        thread pool (per-sample RNG is (seed, epoch, idx)-derived)."""
        sds_a = make_sds(n=12, augmentation=True)
        sds_b = make_sds(n=12, augmentation=True)
        lt = pipeline.Loader(sds_a, batch_size=4, num_workers=2)
        lp = pipeline.Loader(sds_b, batch_size=4, worker_processes=2)
        try:
            lt.set_epoch(1)
            lp.set_epoch(1)
            for bt, bp in zip(lt, lp):
                np.testing.assert_array_equal(bt.inputs, bp.inputs)
                np.testing.assert_array_equal(bt.loss_targets, bp.loss_targets)
                for k in bt.metrics_targets:
                    np.testing.assert_array_equal(
                        bt.metrics_targets[k], bp.metrics_targets[k]
                    )
        finally:
            lt.close()
            lp.close()


class TestH5HandleCache:
    def test_lru_caps_open_files(self, tmp_path):
        import h5py

        from seist_tpu.data import base

        paths = []
        for i in range(base._H5Handles.MAX_OPEN + 4):
            p = tmp_path / f"f{i}.h5"
            with h5py.File(p, "w") as f:
                f.create_dataset("g/x", data=[i])
            paths.append(str(p))

        # Fresh thread => fresh thread-local cache, isolated from other tests.
        import threading

        result = {}

        def run():
            for p in paths:
                base.open_h5(p, group="g")
            cache = base._h5_local.handles
            result["n"] = len(cache)
            result["evicted_closed"] = not cache.get(paths[0], (None,))[0]
            # Evicted-and-reopened path must work (and re-cache).
            f = base.open_h5(paths[0], group="g")
            result["reopened"] = bool(f)

        t = threading.Thread(target=run)
        t.start()
        t.join()
        assert result["n"] <= base._H5Handles.MAX_OPEN + 1
        assert result["evicted_closed"]  # oldest handle was closed, not leaked
        assert result["reopened"]


class TestDeviceAugStore:
    """RawStore / epoch_indices — the host half of --device-aug."""

    def test_epoch_indices_matches_loader(self):
        sds = make_sds(n=20, augmentation=True)
        for shards, rank in [(1, 0), (2, 1), (3, 2)]:
            loader = pipeline.Loader(
                sds, batch_size=4, shuffle=True, seed=3,
                num_shards=shards, shard_index=rank,
            )
            loader.set_epoch(5)
            np.testing.assert_array_equal(
                loader._indices(),
                pipeline.epoch_indices(
                    len(sds), seed=3, epoch=5, shuffle=True,
                    num_shards=shards, shard_index=rank,
                ),
            )

    def test_raw_store_matches_host_prepare(self):
        from seist_tpu.data import device_aug as da

        sds = make_sds(n=8, augmentation=True)
        store = pipeline.RawStore.build(sds)
        assert len(store) == 2 * sds.raw_size
        assert store.raw_len == 4 * 1024
        for i in (0, sds.raw_size - 1):
            event, _ = sds.raw_event(i)
            row = da.host_prepare(sds.preprocessor, event, store.phase_slots)
            np.testing.assert_array_equal(store.arrays["data"][i], row["data"])
            np.testing.assert_array_equal(store.arrays["ppks"][i], row["ppks"])
            np.testing.assert_array_equal(store.arrays["spks"][i], row["spks"])
            assert store.arrays["np_p"][i] == row["np_p"]
            assert store.arrays["np_s"][i] == row["np_s"]

    def test_iter_raw_batches_contract(self):
        sds = make_sds(n=10, augmentation=True)
        store = pipeline.RawStore.build(sds)
        batches = list(
            pipeline.iter_raw_batches(
                store, 2, seed=3, shuffle=True, batch_size=4
            )
        )
        assert len(batches) == len(store) // 4  # drop-last
        order = pipeline.epoch_indices(
            len(store), seed=3, epoch=2, shuffle=True
        )
        seen = np.concatenate([idx for _, idx, _ in batches])
        np.testing.assert_array_equal(seen, order[: len(seen)])
        rows, idx, aug = batches[0]
        assert rows["data"].shape == (4, 3, store.raw_len)
        # aug flag is exactly the 2x-epoch rule
        np.testing.assert_array_equal(aug, idx >= store.n_raw)
        # rows are the raw-index gather of the store
        np.testing.assert_array_equal(
            rows["data"], store.arrays["data"][idx % store.n_raw]
        )

    def test_device_epoch_cache_upload_roundtrip(self):
        sds = make_sds(n=5, augmentation=False)
        store = pipeline.RawStore.build(sds)
        cache = pipeline.DeviceEpochCache(store)
        assert cache.nbytes >= store.nbytes
        np.testing.assert_array_equal(
            np.asarray(cache.arrays["data"]), store.arrays["data"]
        )

    def test_device_epoch_cache_sharded_upload(self):
        import jax

        if len(jax.devices()) < 2:
            pytest.skip("needs multi-device mesh")
        from seist_tpu.parallel import make_mesh

        mesh = make_mesh()
        sds = make_sds(n=5, augmentation=False)  # 5 % 8 != 0 -> padded
        store = pipeline.RawStore.build(sds)
        cache = pipeline.DeviceEpochCache(store, mesh)
        data = np.asarray(cache.arrays["data"])
        assert data.shape[0] % mesh.shape["data"] == 0
        np.testing.assert_array_equal(
            data[: store.n_raw], store.arrays["data"]
        )

    def test_store_refuses_fabricated_value_labels(self):
        """A noise-classified trace under a VALUE-label task crashes the
        host path; the device store must refuse it loudly instead of
        zero-filling a label (review finding)."""
        sds = pipeline.from_task_spec(
            taskspec.get_task_spec("magnet"), "synthetic", "train",
            seed=0, in_samples=1024, augmentation=False, data_split=False,
            dataset_kwargs={"num_events": 4, "trace_samples": 4096},
        )
        orig = sds.raw_event

        def noisy(idx):
            ev, meta = orig(idx)
            if idx == 1:  # inverted picks -> _is_noise
                ev = dict(ev, ppks=[ev["spks"][0] + 10])
            return ev, meta

        sds.raw_event = noisy
        with pytest.raises(ValueError, match="fabricate"):
            pipeline.RawStore.build(sds)
