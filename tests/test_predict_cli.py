"""tools/predict.py end-to-end: checkpoint -> continuous record -> CSV.

Uses a freshly-initialized phasenet at a tiny window so the whole CLI
path (checkpoint restore, task-spec channel0 resolution, windowed
forward, stitch, picking, CSV) runs in seconds. Marked slow: one jit
compile of the forward dominates.
"""

import os
import subprocess
import sys

import numpy as np
import pandas as pd
import pytest

import seist_tpu
from seist_tpu.models import api
from seist_tpu.train import build_optimizer, create_train_state, save_checkpoint

seist_tpu.load_all()

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_predict_cli_end_to_end(tmp_path):
    model = api.create_model("phasenet", in_samples=256)
    variables = api.init_variables(model, in_samples=256)
    state = create_train_state(model, variables, build_optimizer("adam", 1e-3))
    ckpt = save_checkpoint(str(tmp_path / "checkpoints"), state, 0, 1.0)

    rng = np.random.default_rng(0)
    rec = rng.standard_normal((1024, 3)).astype(np.float32)
    np.savez(tmp_path / "rec.npz", data=rec)
    out_csv = tmp_path / "picks.csv"

    r = subprocess.run(
        [
            sys.executable, os.path.join(_REPO, "tools", "predict.py"),
            "--model-name", "phasenet",
            "--checkpoint", ckpt,
            "--input", str(tmp_path / "rec.npz"),
            "--output", str(out_csv),
            "--window", "256",
            "--batch-size", "4",
            # Random init -> probs near uniform; thresholds low enough that
            # SOMETHING is emitted, exercising the CSV writer rows.
            "--ppk-threshold", "0.05",
            "--det-threshold", "0.05",
        ],
        capture_output=True,
        text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        timeout=500,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    df = pd.read_csv(out_csv)
    assert set(df.columns) >= {"kind", "sample", "time_s"}
    assert (df["sample"] >= 0).all() and (df["sample"] < 1024).all()


def test_predict_cli_rejects_non_dpk_model(tmp_path):
    r = subprocess.run(
        [
            sys.executable, os.path.join(_REPO, "tools", "predict.py"),
            "--model-name", "magnet",
            "--checkpoint", "/nonexistent",
            "--input", "/nonexistent.npz",
        ],
        capture_output=True,
        text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        timeout=300,
    )
    assert r.returncode != 0
    assert "dpk-family" in (r.stderr + r.stdout)
