"""Task-spec and io-item catalog tests (ref semantics: config.py:20-435)."""

import pytest

from seist_tpu import taskspec
from seist_tpu.models import losses as L


def test_io_item_catalog_complete():
    # The 20 io-items of the reference catalog (config.py:207-264).
    expected = {
        "z", "n", "e", "dz", "dn", "de", "non", "det", "ppk", "spk",
        "ppk+", "spk+", "det+", "ppks", "spks", "emg", "smg", "baz",
        "dis", "pmp", "clr",
    }
    assert set(taskspec.IO_ITEMS) == expected


def test_io_item_kinds():
    assert taskspec.get_kind("ppk") == "soft"
    assert taskspec.get_kind("emg") == "value"
    assert taskspec.get_kind("pmp") == "onehot"
    assert taskspec.get_num_classes("pmp") == 2
    with pytest.raises(ValueError):
        taskspec.get_num_classes("emg")


def test_get_io_items_by_kind():
    assert "ppks" in taskspec.get_io_items("value")
    assert "det" in taskspec.get_io_items("soft")
    assert set(taskspec.get_io_items()) == set(taskspec.IO_ITEMS)


@pytest.mark.parametrize(
    "model,pattern",
    [
        ("phasenet", "phasenet"),
        ("eqtransformer", "eqtransformer"),
        ("magnet", "magnet"),
        ("baz_network", "baz_network"),
        ("ditingmotion", "ditingmotion"),
        ("seist_s_dpk", "seist_.*?_dpk.*"),
        ("seist_m_dpk", "seist_.*?_dpk.*"),
        ("seist_l_dpk", "seist_.*?_dpk.*"),
        ("seist_s_pmp", "seist_.*?_pmp"),
        ("seist_m_emg", "seist_.*?_emg"),
        ("seist_l_baz", "seist_.*?_baz"),
        ("seist_l_dis", "seist_.*?_dis"),
    ],
)
def test_spec_resolution_unique(model, pattern):
    spec = taskspec.get_task_spec(model)
    assert spec.pattern == pattern


def test_unknown_model_spec():
    with pytest.raises(KeyError):
        taskspec.get_task_spec("unknown_model_xyz")


def test_num_inchannels():
    assert taskspec.get_num_inchannels("phasenet") == 3
    assert taskspec.get_num_inchannels("seist_l_dpk") == 3
    assert taskspec.get_num_inchannels("ditingmotion") == 2


def test_loss_instantiation():
    assert isinstance(taskspec.make_loss("phasenet"), L.CELoss)
    assert isinstance(taskspec.make_loss("seist_s_dpk"), L.BCELoss)
    assert isinstance(taskspec.make_loss("seist_s_emg"), L.HuberLoss)
    assert isinstance(taskspec.make_loss("magnet"), L.MousaviLoss)
    assert isinstance(taskspec.make_loss("baz_network"), L.CombinationLoss)


def test_baz_transforms_roundtrip():
    import jax.numpy as jnp
    import numpy as np

    spec = taskspec.get_task_spec("baz_network")
    deg = jnp.asarray([[0.0], [90.0], [180.0], [250.0]])
    cos, sin = spec.targets_transform_for_loss(deg)
    out = spec.outputs_transform_for_results((cos, sin))
    # atan2 wraps to (-180, 180]; compare as angles modulo 360
    diff = (np.asarray(out) - np.asarray(deg)) % 360.0
    diff = np.minimum(diff, 360.0 - diff)
    np.testing.assert_allclose(diff, 0.0, atol=1e-3)


def test_validate_passes():
    taskspec.validate(strict_models=False)


def test_flatten_io_names():
    assert taskspec.flatten_io_names((("z", "n", "e"), "emg")) == ["z", "n", "e", "emg"]
