"""True multi-process tests: 2 simulated hosts x 4 virtual CPU devices.

Spawns two python processes that rendezvous through jax.distributed on a
localhost coordinator and run tests/_multihost_worker.py — the only way to
exercise make_array_from_process_local_data, cross-host metric sync, and
broadcast_object for real (the in-process suite runs single-host). The
reference framework has no equivalent capability (its multi-node path needs
actual torchrun, SURVEY.md §4).
"""

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # two 540s-timeout process rendezvous

HERE = os.path.dirname(__file__)
WORKER = os.path.join(HERE, "_multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_host_simulation():
    port = _free_port()
    repo = os.path.abspath(os.path.join(HERE, ".."))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # skip the TPU-tunnel sitecustomize
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), "2", str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
            cwd=os.path.join(HERE, ".."),
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=540)
            outs.append(out.decode())
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multi-host workers timed out:\n" + "\n".join(outs))
    for i, p in enumerate(procs):
        assert p.returncode == 0, f"worker {i} failed:\n{outs[i]}"
        assert f"worker {i}: OK" in outs[i]


@pytest.mark.parametrize("device_aug", ["off", "cached"])
def test_two_host_training(tmp_path, device_aug):
    """Full train_worker epoch across 2 simulated hosts: sharded loaders,
    global eval loss, synced metrics, multi-host orbax checkpoint.

    device_aug='cached' additionally pins the multi-host epoch cache
    (per-host addressable-slice placement + host-sharded index chunks) —
    the contract that let PR 14 remove the cached->step multi-host
    fallback."""
    port = _free_port()
    repo = os.path.abspath(os.path.join(HERE, ".."))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    worker = os.path.join(HERE, "_multihost_train_worker.py")
    procs = [
        subprocess.Popen(
            [
                sys.executable, worker, str(i), "2", str(port),
                str(tmp_path), device_aug,
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
            cwd=repo,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            # Generous: both workers compile on the same single CPU core.
            out, _ = p.communicate(timeout=900)
            outs.append(out.decode())
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multi-host train workers timed out:\n" + "\n".join(outs))
    for i, p in enumerate(procs):
        assert p.returncode == 0, f"train worker {i} failed:\n{outs[i][-3000:]}"
        assert f"train worker {i}: OK" in outs[i]
