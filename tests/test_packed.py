"""Packed-shard dataset (seist_tpu/data/packed.py): conversion fidelity,
split contract, and pipeline integration.

SURVEY §7's offline input-pipeline mitigation: tools/pack_dataset.py
repacks an HDF5 dataset into contiguous binary shards + columnar index;
the ``packed`` dataset then serves the identical Event dicts through a
memmap slice instead of h5py's per-sample group walk (the measured ~30%
read tax, BASELINE.md §Input pipeline).
"""

import os

import numpy as np
import pytest

import seist_tpu
from seist_tpu.data.packed import PackedDataset, pack_dataset
from seist_tpu.registry import DATASETS

seist_tpu.load_all()

N_EVENTS = 24
L_TRACE = 1024


@pytest.fixture(scope="module")
def packed_pair(tmp_path_factory):
    """(source diting_light dataset, packed dir) over the same fixture.
    Tiny shard budget forces multiple shards (multi-shard indexing
    covered, not just the single-file happy path)."""
    from tests.conftest import make_packed_dir

    return make_packed_dir(
        tmp_path_factory,
        n_events=N_EVENTS,
        trace_samples=L_TRACE,
        shard_mb=0.05,
    )


def test_pack_roundtrip_events_identical(packed_pair):
    src, out = packed_pair
    dst = PackedDataset(
        seed=0, mode="train", data_dir=out, shuffle=False, data_split=False
    )
    assert len(dst) == len(src) == N_EVENTS
    n_shards = len(
        [f for f in os.listdir(out) if f.startswith("shard_")]
    )
    assert n_shards > 1  # shard_mb=1 must have rolled over
    for i in range(len(src)):
        ev_s, _ = src[i]
        ev_p, row_p = dst[i]
        np.testing.assert_array_equal(ev_p["data"], ev_s["data"])
        assert ev_p["data"].dtype == np.float32
        for f in ("ppks", "spks", "emg", "smg", "pmp", "clr", "baz", "dis"):
            got, want = ev_p[f], ev_s[f]
            assert len(got) == len(want), (i, f, got, want)
            if want:
                np.testing.assert_allclose(got[0], want[0], rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(ev_p["snr"], float),
            np.asarray(ev_s["snr"], float),
            rtol=1e-6,
        )
        assert "key" in row_p  # ResultSaver metadata passthrough


def test_packed_split_matches_source_split(packed_pair):
    # Pack order == source metadata order, and both readers apply the
    # SAME seeded shuffle-then-contiguous-split (data/base.py) — so for
    # a given seed the packed train split serves the same events as the
    # source train split, event for event.
    src_dir = packed_pair[0]._data_dir
    _, out = packed_pair
    for mode in ("train", "val", "test"):
        a = DATASETS.create(
            "diting_light", seed=11, mode=mode, data_dir=src_dir
        )
        b = DATASETS.create("packed", seed=11, mode=mode, data_dir=out)
        assert len(a) == len(b) > 0
        ev_a, _ = a[0]
        ev_b, _ = b[0]
        np.testing.assert_array_equal(ev_b["data"], ev_a["data"])


def test_packed_through_pipeline(packed_pair):
    from seist_tpu import taskspec
    from seist_tpu.data import pipeline

    _, out = packed_pair
    spec = taskspec.get_task_spec("seist_s_dpk")
    ds = pipeline.from_task_spec(
        spec,
        "packed",
        "train",
        seed=0,
        in_samples=512,
        augmentation=True,
        data_dir=out,
    )
    assert ds.sampling_rate() == 50
    loader = pipeline.Loader(ds, batch_size=8, shuffle=True, num_workers=2)
    try:
        batch = next(iter(loader))
    finally:
        loader.close()
    assert batch.inputs.shape == (8, 512, 3)
    assert np.isfinite(batch.inputs).all()


def test_pack_rejects_multi_event_windows(tmp_path):
    class TwoPick:
        def __init__(self):
            self._rows = [0]

        def __len__(self):
            return 1

        def __getitem__(self, i):
            return (
                {
                    "data": np.zeros((3, 64), np.float32),
                    "ppks": [1, 2],  # two picks: not representable
                    "spks": [],
                    "snr": np.zeros(3),
                },
                {"key": "k"},
            )

        def name(self):
            return "twopick"

        def channels(self):
            return ["z", "n", "e"]

        def sampling_rate(self):
            return 50

    with pytest.raises(ValueError, match="one event per window"):
        pack_dataset(TwoPick(), str(tmp_path / "out"))
