"""Packed-shard dataset (seist_tpu/data/packed.py): conversion fidelity,
split contract, and pipeline integration.

SURVEY §7's offline input-pipeline mitigation: tools/pack_dataset.py
repacks an HDF5 dataset into contiguous binary shards + columnar index;
the ``packed`` dataset then serves the identical Event dicts through a
memmap slice instead of h5py's per-sample group walk (the measured ~30%
read tax, BASELINE.md §Input pipeline).
"""

import json
import os

import numpy as np
import pytest

import seist_tpu
from seist_tpu.data.packed import (
    PackedDataset,
    PackSource,
    pack_dataset,
    pack_sources,
    sidecar_path,
    shard_path,
)
from seist_tpu.registry import DATASETS

seist_tpu.load_all()

N_EVENTS = 24
L_TRACE = 1024


@pytest.fixture(scope="module")
def packed_pair(tmp_path_factory):
    """(source diting_light dataset, packed dir) over the same fixture.
    Tiny shard budget forces multiple shards (multi-shard indexing
    covered, not just the single-file happy path)."""
    from tests.conftest import make_packed_dir

    return make_packed_dir(
        tmp_path_factory,
        n_events=N_EVENTS,
        trace_samples=L_TRACE,
        shard_mb=0.05,
    )


def test_pack_roundtrip_events_identical(packed_pair):
    src, out = packed_pair
    dst = PackedDataset(
        seed=0, mode="train", data_dir=out, shuffle=False, data_split=False
    )
    assert len(dst) == len(src) == N_EVENTS
    n_shards = len(
        [f for f in os.listdir(out) if f.startswith("shard_")]
    )
    assert n_shards > 1  # shard_mb=1 must have rolled over
    for i in range(len(src)):
        ev_s, _ = src[i]
        ev_p, row_p = dst[i]
        np.testing.assert_array_equal(ev_p["data"], ev_s["data"])
        assert ev_p["data"].dtype == np.float32
        for f in ("ppks", "spks", "emg", "smg", "pmp", "clr", "baz", "dis"):
            got, want = ev_p[f], ev_s[f]
            assert len(got) == len(want), (i, f, got, want)
            if want:
                np.testing.assert_allclose(got[0], want[0], rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(ev_p["snr"], float),
            np.asarray(ev_s["snr"], float),
            rtol=1e-6,
        )
        assert "key" in row_p  # ResultSaver metadata passthrough


def test_packed_split_matches_source_split(packed_pair):
    # Pack order == source metadata order, and both readers apply the
    # SAME seeded shuffle-then-contiguous-split (data/base.py) — so for
    # a given seed the packed train split serves the same events as the
    # source train split, event for event.
    src_dir = packed_pair[0]._data_dir
    _, out = packed_pair
    for mode in ("train", "val", "test"):
        a = DATASETS.create(
            "diting_light", seed=11, mode=mode, data_dir=src_dir
        )
        b = DATASETS.create("packed", seed=11, mode=mode, data_dir=out)
        assert len(a) == len(b) > 0
        ev_a, _ = a[0]
        ev_b, _ = b[0]
        np.testing.assert_array_equal(ev_b["data"], ev_a["data"])


def test_packed_through_pipeline(packed_pair):
    from seist_tpu import taskspec
    from seist_tpu.data import pipeline

    _, out = packed_pair
    spec = taskspec.get_task_spec("seist_s_dpk")
    ds = pipeline.from_task_spec(
        spec,
        "packed",
        "train",
        seed=0,
        in_samples=512,
        augmentation=True,
        data_dir=out,
    )
    assert ds.sampling_rate() == 50
    loader = pipeline.Loader(ds, batch_size=8, shuffle=True, num_workers=2)
    try:
        batch = next(iter(loader))
    finally:
        loader.close()
    assert batch.inputs.shape == (8, 512, 3)
    assert np.isfinite(batch.inputs).all()


# ------------------------------------------------ parallel / resume / mixture
def _synthetic_source(n_events=30, trace_samples=512):
    return PackSource(
        name="synthetic",
        dataset_kwargs={
            "num_events": n_events,
            "trace_samples": trace_samples,
            "cache": False,
        },
    )


def _dir_fingerprint(root):
    """Byte content of every shard bin + the index/sidecar ARRAY contents
    (npz zip bytes carry timestamps, so the arrays are the identity)."""
    out = {}
    for f in sorted(os.listdir(root)):
        p = os.path.join(root, f)
        if f.endswith(".bin"):
            with open(p, "rb") as fh:
                out[f] = fh.read()
        elif f.endswith(".npz"):
            with np.load(p, allow_pickle=False) as z:
                out[f] = {k: z[k].tolist() for k in sorted(z.files)}
    return out


def test_parallel_pack_bit_identical_to_serial(tmp_path):
    """A 2-worker pack must produce byte-identical shards and an
    identical index to a 1-worker pack: the shard partition is a pure
    function of the plan, never of worker count (ISSUE acceptance)."""
    a, b = str(tmp_path / "serial"), str(tmp_path / "par")
    s1 = pack_sources([_synthetic_source()], a, samples_per_shard=7)
    s2 = pack_sources(
        [_synthetic_source()], b, num_workers=2, samples_per_shard=7
    )
    assert s1["shards"] == s2["shards"] > 1
    assert s1["samples"] == s2["samples"] == 30
    assert _dir_fingerprint(a) == _dir_fingerprint(b)


def test_pack_resume_skips_complete_shards(tmp_path):
    """Interrupted pack: kill after some shards -> the re-run re-plans
    identically, skips every complete shard, and the result is identical
    to an uninterrupted pack."""
    full, part = str(tmp_path / "full"), str(tmp_path / "part")
    pack_sources([_synthetic_source()], full, samples_per_shard=7)
    pack_sources([_synthetic_source()], part, samples_per_shard=7)
    # Simulate the interruption: no meta/index yet, shard 1 half-written
    # (bin exists, sidecar missing), shard 2 gone entirely.
    os.unlink(os.path.join(part, "meta.json"))
    os.unlink(os.path.join(part, "index.npz"))
    os.unlink(sidecar_path(part, 1))
    os.unlink(shard_path(part, 2))
    os.unlink(sidecar_path(part, 2))
    stats = pack_sources([_synthetic_source()], part, samples_per_shard=7)
    assert stats["shards_skipped"] == stats["shards"] - 2
    assert stats["samples_packed"] == 7 + 7  # only the two holes re-read
    assert _dir_fingerprint(full) == _dir_fingerprint(part)


def test_mixture_pack_provenance_and_roundtrip(tmp_path):
    """--mixture: two sources in one directory, consecutive shard
    ranges, a source_id column on every row, and events identical to
    reading each source directly."""
    out = str(tmp_path / "mix")
    src_a = _synthetic_source(n_events=10, trace_samples=256)
    src_b = _synthetic_source(n_events=17, trace_samples=256)
    stats = pack_sources(
        [src_a, src_b], out, samples_per_shard=4, num_workers=0
    )
    assert stats["samples"] == 27
    with open(os.path.join(out, "meta.json")) as f:
        meta = json.load(f)
    assert [s["n_events"] for s in meta["sources"]] == [10, 17]
    assert meta["source"].startswith("mixture:")

    ds = PackedDataset(
        seed=0, mode="train", data_dir=out, shuffle=False, data_split=False
    )
    sids = ds.source_ids()
    assert sids is not None and sids.shape == (27,)
    assert (sids[:10] == 0).all() and (sids[10:] == 1).all()
    # Row 10+j of the mixture == source B's own event j.
    b = src_b.create()
    for j in (0, 16):
        ev_mix, row = ds[10 + j]
        ev_src, _ = b[j]
        np.testing.assert_array_equal(ev_mix["data"], ev_src["data"])
        assert int(row["source_id"]) == 1
    # Single-source packs expose no source ids (mixture sampler stays off).
    single = PackedDataset(
        seed=0,
        mode="train",
        data_dir=pack_sources(
            [_synthetic_source(8, 256)], str(tmp_path / "one"),
            samples_per_shard=4,
        )["out"],
        shuffle=False,
        data_split=False,
    )
    assert single.source_ids() is None


def test_mixture_rejects_mismatched_sources(tmp_path):
    class OtherRate:
        def __len__(self):
            return 1

        def __getitem__(self, i):
            return {"data": np.zeros((3, 64), np.float32), "snr": np.zeros(3)}, {}

        def name(self):
            return "other"

        def channels(self):
            return ["z", "n", "e"]

        def sampling_rate(self):
            return 100  # != synthetic's 50

    with pytest.raises(ValueError, match="sampling rate"):
        pack_sources(
            [_synthetic_source(4, 128), PackSource(dataset=OtherRate())],
            str(tmp_path / "bad"),
        )


def test_pack_rejects_multi_event_windows(tmp_path):
    class TwoPick:
        def __init__(self):
            self._rows = [0]

        def __len__(self):
            return 1

        def __getitem__(self, i):
            return (
                {
                    "data": np.zeros((3, 64), np.float32),
                    "ppks": [1, 2],  # two picks: not representable
                    "spks": [],
                    "snr": np.zeros(3),
                },
                {"key": "k"},
            )

        def name(self):
            return "twopick"

        def channels(self):
            return ["z", "n", "e"]

        def sampling_rate(self):
            return 50

    with pytest.raises(ValueError, match="one event per window"):
        pack_dataset(TwoPick(), str(tmp_path / "out"))
