"""Direct tests for parallel/dist.broadcast_object's two transports.

PR 7 moved the primary transport to the coordination-service KV store
(jaxlib 0.4.37's gloo allreduce corrupts back-to-back differently-shaped
broadcasts on CPU) but kept the legacy two-phase collective as the
fallback for runtimes without the private client API — and only the KV
path was exercised (by test_multihost's real worker processes). These
units pin BOTH paths' semantics process-locally with fake transports, so
a regression in either shows up in the smoke lane instead of only on a
multi-host launch."""

import pickle

import numpy as np
import pytest

import jax

from seist_tpu.parallel import dist


@pytest.fixture(autouse=True)
def _reset_seq():
    prev = dist._broadcast_seq
    dist._broadcast_seq = 0
    yield
    dist._broadcast_seq = prev


class _FakeKVClient:
    """In-memory stand-in for the jax coordination-service client."""

    def __init__(self, store=None):
        self.store = store if store is not None else {}
        self.barriers = []
        self.deleted = []

    def key_value_set_bytes(self, key, value):
        self.store[key] = value

    def blocking_key_value_get_bytes(self, key, timeout_ms):
        try:
            return self.store[key]
        except KeyError:
            raise TimeoutError(f"key {key} never published") from None

    def wait_at_barrier(self, name, timeout_ms):
        self.barriers.append(name)

    def key_value_delete(self, key):
        self.deleted.append(key)
        self.store.pop(key, None)


def _fake_multiprocess(monkeypatch, index, count=2):
    monkeypatch.setattr(jax, "process_count", lambda: count)
    monkeypatch.setattr(jax, "process_index", lambda: index)


def test_single_process_passthrough():
    obj = {"a": 1}
    assert dist.broadcast_object(obj) is obj


def test_kv_path_rank0_publishes_and_cleans_up(monkeypatch):
    _fake_multiprocess(monkeypatch, index=0)
    client = _FakeKVClient()
    monkeypatch.setattr(dist, "_coordination_client", lambda: client)
    obj = {"ckpt": "/path/step_120", "step": 120}
    assert dist.broadcast_object(obj) == obj
    # sequenced key, read barrier, then the key is deleted (a relaunched
    # incarnation restarting its sequence must not read stale values)
    assert client.barriers == ["seist_tpu/broadcast_object/0/read"]
    assert client.deleted == ["seist_tpu/broadcast_object/0"]
    assert client.store == {}


def test_kv_path_rank1_reads_rank0_payload(monkeypatch):
    _fake_multiprocess(monkeypatch, index=1)
    obj = ["eval", 0.25, np.float64(3.5)]
    store = {"seist_tpu/broadcast_object/0": pickle.dumps(obj)}
    client = _FakeKVClient(store)
    monkeypatch.setattr(dist, "_coordination_client", lambda: client)
    assert dist.broadcast_object(None) == obj
    # non-zero ranks wait at the barrier but never delete (rank 0 owns it)
    assert client.barriers == ["seist_tpu/broadcast_object/0/read"]
    assert client.deleted == []


def test_kv_path_sequences_successive_broadcasts(monkeypatch):
    _fake_multiprocess(monkeypatch, index=0)
    client = _FakeKVClient()
    monkeypatch.setattr(dist, "_coordination_client", lambda: client)
    dist.broadcast_object("first")
    dist.broadcast_object("second")
    assert client.deleted == [
        "seist_tpu/broadcast_object/0",
        "seist_tpu/broadcast_object/1",
    ]


class _FakeCollective:
    """Stand-in for multihost_utils.broadcast_one_to_all: echoes rank 0's
    value. For rank 0 that is the argument itself; for other ranks the
    test provides what rank 0 'sent' for the payload phase."""

    def __init__(self, rank0_payload=None):
        self.calls = []
        self._rank0_payload = rank0_payload

    def __call__(self, value):
        self.calls.append(np.asarray(value).copy())
        arr = np.asarray(value)
        if self._rank0_payload is None:
            return arr  # rank 0: input IS the broadcast value
        if arr.ndim == 0:  # length phase
            return np.int64(self._rank0_payload.size)
        return self._rank0_payload  # buffer phase


def test_legacy_collective_fallback_rank0(monkeypatch):
    """No coordination client -> the two-phase length+buffer collective."""
    from jax.experimental import multihost_utils

    _fake_multiprocess(monkeypatch, index=0)
    monkeypatch.setattr(dist, "_coordination_client", lambda: None)
    fake = _FakeCollective()
    monkeypatch.setattr(multihost_utils, "broadcast_one_to_all", fake)
    obj = {"resume": True, "epoch": 3}
    assert dist.broadcast_object(obj) == obj
    # exactly two collectives: scalar length, then the uint8 pickle buffer
    assert len(fake.calls) == 2
    assert fake.calls[0].ndim == 0
    assert fake.calls[1].dtype == np.uint8
    assert int(fake.calls[0]) == fake.calls[1].size


def test_legacy_collective_fallback_rank1(monkeypatch):
    """A non-zero rank must reconstruct the object purely from what the
    collective returns (its own buffer contribution is zeros)."""
    from jax.experimental import multihost_utils

    _fake_multiprocess(monkeypatch, index=1)
    monkeypatch.setattr(dist, "_coordination_client", lambda: None)
    obj = ("ckpt", 120, [1.5, 2.5])
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    fake = _FakeCollective(rank0_payload=payload)
    monkeypatch.setattr(multihost_utils, "broadcast_one_to_all", fake)
    assert dist.broadcast_object(None) == obj
    # rank 1 contributed a zero buffer of the broadcast length — the
    # result came from the collective, not local state
    assert len(fake.calls) == 2
    assert not fake.calls[1].any()


def test_legacy_fallback_engages_when_client_api_gone(monkeypatch):
    """_coordination_client returning None (private API changed/removed)
    must route to the fallback rather than crash."""
    from jax.experimental import multihost_utils

    _fake_multiprocess(monkeypatch, index=0)

    def _broken_client():
        raise AssertionError("must go through dist._coordination_client")

    # simulate the private-API import failing inside the helper
    monkeypatch.setattr(dist, "_coordination_client", lambda: None)
    fake = _FakeCollective()
    monkeypatch.setattr(multihost_utils, "broadcast_one_to_all", fake)
    assert dist.broadcast_object([1, 2]) == [1, 2]
    assert len(fake.calls) == 2
