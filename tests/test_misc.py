"""Direct unit tests for the utils/misc parity helpers (ref utils/misc.py:
get_safe_path :41-52, cal_snr :228-248, setup_seed :14-21). These were
previously covered only transitively (SOS reader, worker CSV paths)."""

import numpy as np
import pytest

from seist_tpu.utils.misc import (
    cal_snr,
    count_params,
    get_safe_path,
    setup_seed,
    strftimedelta,
)


class TestCalSnr:
    def test_hand_computed_value(self):
        # signal amplitude 2x the noise -> SNR = 10*log10(4) per channel
        L, w, pat = 2000, 500, 1000
        data = np.ones((3, L), np.float32)
        data[:, pat : pat + w] = 2.0
        snr = cal_snr(data, pat, window=w)
        np.testing.assert_allclose(snr, 10 * np.log10(4.0), rtol=1e-6)

    def test_out_of_bounds_window_returns_zeros(self):
        data = np.ones((3, 600), np.float32)
        np.testing.assert_array_equal(cal_snr(data, 100, window=500), 0.0)
        np.testing.assert_array_equal(cal_snr(data, 200, window=500), 0.0)

    def test_silent_channel_returns_zero(self):
        data = np.zeros((1, 2000), np.float32)
        np.testing.assert_array_equal(cal_snr(data, 1000, window=500), 0.0)


class TestGetSafePath:
    def test_passthrough_when_free(self, tmp_path):
        p = str(tmp_path / "results.csv")
        assert get_safe_path(p) == p

    def test_recursive_new_suffix(self, tmp_path):
        # ref misc.py:41-52: existing paths dedupe by appending _new
        p = tmp_path / "results.csv"
        p.write_text("x")
        first = get_safe_path(str(p))
        assert first == str(tmp_path / "results_new.csv")
        (tmp_path / "results_new.csv").write_text("y")
        assert get_safe_path(str(p)) == str(tmp_path / "results_new_new.csv")


def test_setup_seed_determinism():
    import random

    k1 = setup_seed(123)
    a_np, a_py = np.random.rand(3), random.random()
    k2 = setup_seed(123)
    b_np, b_py = np.random.rand(3), random.random()
    np.testing.assert_array_equal(a_np, b_np)
    assert a_py == b_py
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(k2))


@pytest.mark.parametrize(
    "seconds,expect",
    [(0, "0:00:00"), (61, "0:01:01"), (3723.9, "1:02:03"), (86400, "24:00:00")],
)
def test_strftimedelta(seconds, expect):
    assert strftimedelta(seconds) == expect


def test_count_params():
    tree = {"a": np.zeros((2, 3)), "b": {"c": np.zeros((5,))}}
    assert count_params(tree) == 11
