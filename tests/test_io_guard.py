"""Units for the self-healing data plane (seist_tpu/data/io_guard.py +
the SEIST_FAULT_IO_* injector in utils/faults.py): retry/backoff
classification, ingest validation, deterministic quarantine fallback,
h5-handle eviction, the stall watchdog, and the Loader's death wrapping.
Chaos e2e (real training runs under injected faults) lives in
tests/test_data_plane_chaos.py."""

import threading
import time

import numpy as np
import pytest

import seist_tpu
from seist_tpu import taskspec
from seist_tpu.data import io_guard, pipeline
from seist_tpu.utils.faults import IoFaultInjector, IoFaultPlan

seist_tpu.load_all()

pytestmark = pytest.mark.faults


# ------------------------------------------------------------ exit-code pin
def test_preempt_code_matches_trainer():
    """io_guard duplicates PREEMPT_EXIT_CODE (importing train.checkpoint
    would drag orbax into every data-plane import); pin them together."""
    from seist_tpu.train.checkpoint import PREEMPT_EXIT_CODE

    assert io_guard.PREEMPT_EXIT_CODE == PREEMPT_EXIT_CODE == 75


# ------------------------------------------------------------------- retries
def _policy(attempts=3):
    return io_guard.RetryPolicy(
        attempts=attempts, backoff_base_s=0.01, backoff_cap_s=0.08
    )


def test_retry_succeeds_after_transient_failures():
    naps = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("blip")
        return "payload"

    before = io_guard.COUNTERS.snapshot()["retries"]
    out = io_guard.read_with_retry(
        flaky, policy=_policy(), sleep=naps.append
    )
    assert out == "payload" and calls["n"] == 3
    assert io_guard.COUNTERS.snapshot()["retries"] - before == 2
    # Exponential backoff with jitter: each sleep within [0.5, 1.5]x of
    # min(base * 2^k, cap).
    assert len(naps) == 2
    for k, s in enumerate(naps):
        base = min(0.01 * 2**k, 0.08)
        assert 0.5 * base <= s <= 1.5 * base


def test_retry_backoff_is_capped():
    p = _policy(attempts=10)
    assert p.sleep_s(9) <= 0.08 * 1.5


def test_corrupt_sample_is_not_retried():
    calls = {"n": 0}

    def corrupt():
        calls["n"] += 1
        raise io_guard.CorruptSampleError("bad bytes")

    with pytest.raises(io_guard.CorruptSampleError):
        io_guard.read_with_retry(corrupt, policy=_policy(), sleep=lambda s: None)
    assert calls["n"] == 1


def test_unexpected_exception_is_not_absorbed():
    calls = {"n": 0}

    def bug():
        calls["n"] += 1
        raise RuntimeError("a bug, not a fault")

    with pytest.raises(RuntimeError):
        io_guard.read_with_retry(bug, policy=_policy(), sleep=lambda s: None)
    assert calls["n"] == 1


def test_exhausted_retries_promote_to_permanent():
    def always_down():
        raise OSError("still down")

    with pytest.raises(io_guard.RetriesExhaustedError) as ei:
        io_guard.read_with_retry(
            always_down, policy=_policy(), sleep=lambda s: None
        )
    # Quarantine treats it like corruption.
    assert isinstance(ei.value, io_guard.CorruptSampleError)


def test_injected_flakiness_rides_the_retry_loop():
    """The injector fails attempt 0 of a flaky-selected key; the retry
    loop absorbs it and the payload is unchanged."""
    inj = IoFaultInjector(IoFaultPlan(flaky_p=1.0, flaky_fails=1))
    out = io_guard.read_with_retry(
        lambda: "payload", fault_key=7, injector=inj,
        policy=_policy(), sleep=lambda s: None,
    )
    assert out == "payload"
    # Deterministic per key: the same key is flaky on every call.
    with pytest.raises(OSError):
        inj.maybe_flaky_read(7, attempt=0)
    inj.maybe_flaky_read(7, attempt=1)  # past flaky_fails: clean


# ---------------------------------------------------------------- validation
def _event(data):
    return {"data": data}


def test_validate_event_accepts_clean_and_int_data():
    io_guard.validate_event(_event(np.random.randn(3, 64).astype(np.float32)))
    io_guard.validate_event(_event(np.zeros((1, 8), np.int32)))


@pytest.mark.parametrize(
    "bad",
    [
        np.full((3, 16), np.nan, np.float32),
        np.r_[np.zeros(15, np.float32), np.inf].reshape(1, 16),
        np.zeros((16,), np.float32),  # wrong ndim
        np.zeros((3, 0), np.float32),  # empty
        np.array([[None, "x"]], dtype=object),  # non-numeric
    ],
)
def test_validate_event_rejects_corruption(bad):
    with pytest.raises(io_guard.CorruptSampleError):
        io_guard.validate_event(_event(bad))


def test_validate_event_rejects_missing_data_field():
    with pytest.raises(io_guard.CorruptSampleError):
        io_guard.validate_event({"ppks": [1]})
    with pytest.raises(io_guard.CorruptSampleError):
        io_guard.validate_event(None)


# ---------------------------------------------------------------- quarantine
def test_quarantine_candidates_deterministic_and_exclusive():
    q1 = io_guard.Quarantine(100, max_frac=0.5)
    q2 = io_guard.Quarantine(100, max_frac=0.5)
    a = list(q1.candidates(7, seed=3, epoch=2, idx=107))
    b = list(q2.candidates(7, seed=3, epoch=2, idx=107))
    assert a == b  # pure function of (seed, epoch, idx)
    assert a[0] == 7  # the sample itself first
    assert 7 not in a[1:]  # never falls back to itself
    # Different key -> different fallback stream (overwhelmingly).
    c = list(q1.candidates(7, seed=3, epoch=3, idx=107))
    assert a[1:] != c[1:]


def test_quarantine_skips_known_bad_candidates():
    q = io_guard.Quarantine(50, max_frac=0.5)
    seq = list(q.candidates(5, seed=0, epoch=0, idx=5))
    q.add(5, "corrupt")
    q.add(seq[1], "also corrupt")
    seq2 = list(q.candidates(5, seed=0, epoch=0, idx=5))
    assert seq2[0] == seq[2]  # self and first fallback both benched
    assert 5 not in seq2 and seq[1] not in seq2


def test_quarantine_overflow_aborts():
    q = io_guard.Quarantine(10, max_frac=0.1)
    q.add(0, "bad")  # 1/10 == max, not over
    with pytest.raises(io_guard.QuarantineOverflowError):
        q.add(1, "bad")  # 2/10 > 0.1


def test_quarantine_report_and_pickle_roundtrip():
    import pickle

    q = io_guard.Quarantine(20, max_frac=0.5)
    q.add(3, "nan burst")
    r = q.report()
    assert r["quarantined"] == [3] and r["n_total"] == 20
    assert r["frac"] == pytest.approx(0.05)
    q2 = pickle.loads(pickle.dumps(q))
    assert 3 in q2 and q2.active and q2.max_frac == 0.5


# ------------------------------------------------------------- injector plan
def test_io_fault_plan_parsing_and_defaults():
    assert not IoFaultPlan.from_env({}).enabled
    plan = IoFaultPlan.from_env({
        "SEIST_FAULT_IO_FLAKY_P": "0.25",
        "SEIST_FAULT_IO_FLAKY_FAILS": "2",
        "SEIST_FAULT_IO_CORRUPT": "3, 7",
        "SEIST_FAULT_IO_STALL_BATCH": "5",
        "SEIST_FAULT_IO_STALL_SEC": "12.5",
    })
    assert plan.enabled and plan.flaky_p == 0.25 and plan.flaky_fails == 2
    assert plan.corrupt == frozenset({3, 7})
    assert plan.stall_batch == 5 and plan.stall_sec == 12.5
    with pytest.raises(ValueError):
        IoFaultPlan.from_env({"SEIST_FAULT_IO_CORRUPT": "soon"})


def test_injector_stall_fires_once(monkeypatch):
    import seist_tpu.utils.faults as faults_mod

    naps = []
    monkeypatch.setattr(faults_mod.time, "sleep", lambda s: naps.append(s))
    inj = IoFaultInjector(IoFaultPlan(stall_batch=2, stall_sec=9.0))
    inj.maybe_stall(0)
    inj.maybe_stall(1)
    assert naps == []
    inj.maybe_stall(2)
    assert naps == [9.0]
    inj.maybe_stall(3)  # once only
    assert naps == [9.0]


# ----------------------------------------------- dataset-level wiring (fast)
def _make_sds(monkeypatch=None, **over):
    kwargs = dict(
        seed=1,
        in_samples=256,
        augmentation=False,
        dataset_kwargs={"num_events": 20, "trace_samples": 1024},
    )
    kwargs.update(over)
    return pipeline.from_task_spec(
        taskspec.get_task_spec("phasenet"), "synthetic", "train", **kwargs
    )


def test_corrupt_injection_quarantines_exactly_and_deterministically(
    monkeypatch,
):
    monkeypatch.setenv("SEIST_FAULT_IO_CORRUPT", "2,5")
    a = _make_sds(max_quarantine_frac=0.5)
    items_a = [a[i][0] for i in range(len(a))]
    assert a.quarantine_report()["quarantined"] == [2, 5]
    # Same faults, fresh dataset -> same replacement content.
    b = _make_sds(max_quarantine_frac=0.5)
    items_b = [b[i][0] for i in range(len(b))]
    for x, y in zip(items_a, items_b):
        np.testing.assert_array_equal(x, y)
    # Quarantined indices were replaced, not dropped: shapes intact.
    assert all(x.shape == items_a[0].shape for x in items_a)


def test_flaky_reads_are_invisible_after_retries(monkeypatch):
    clean = [_make_sds()[i][0] for i in range(16)]
    monkeypatch.setenv("SEIST_FAULT_IO_FLAKY_P", "0.5")
    before = io_guard.COUNTERS.snapshot()["retries"]
    flaky_sds = _make_sds()
    flaky = [flaky_sds[i][0] for i in range(16)]
    assert io_guard.COUNTERS.snapshot()["retries"] - before > 0
    for x, y in zip(clean, flaky):
        np.testing.assert_array_equal(x, y)
    assert len(flaky_sds.quarantine) == 0  # transient != corrupt


def test_guard_disabled_bypasses_wrapping(monkeypatch):
    sds = _make_sds()
    with io_guard.disabled():
        x = sds[0][0]
    np.testing.assert_array_equal(x, sds[0][0])


def test_epoch_keyed_fallback_changes_across_epochs(monkeypatch):
    """The replacement is keyed by (seed, epoch, idx): a new epoch draws a
    fresh fallback for the same quarantined index (no sample is
    permanently over-represented)."""
    monkeypatch.setenv("SEIST_FAULT_IO_CORRUPT", "2")
    sds = _make_sds(max_quarantine_frac=0.5)
    sds.set_epoch(0)
    e0 = sds[2][0]
    sds.set_epoch(1)
    e1 = sds[2][0]
    assert not np.array_equal(e0, e1)


def test_raw_store_probe_refuses_corrupt_sample_zero(monkeypatch):
    """estimate_bytes probes raw sample 0 through the guarded path: a
    permanently-corrupt first sample must surface as the ValueError the
    worker's device-aug selection catches (-> host-path fallback), not
    crash with an unclassified error."""
    monkeypatch.setenv("SEIST_FAULT_IO_CORRUPT", "0")
    sds = _make_sds(max_quarantine_frac=0.5)
    with pytest.raises(ValueError, match="host path"):
        pipeline.RawStore.estimate_bytes(sds)
    with pytest.raises(ValueError, match="host path"):
        pipeline.RawStore.build(sds)


def test_loader_reuses_dataset_injector():
    sds = _make_sds()
    loader = pipeline.Loader(sds, batch_size=4)
    assert loader._io_faults is sds.io_faults
    loader.close()


# ------------------------------------------------------------- h5 eviction
def test_evict_h5_closes_and_reopens(tmp_path):
    import h5py

    from seist_tpu.data import base

    p = str(tmp_path / "f.h5")
    with h5py.File(p, "w") as f:
        f.create_dataset("g/x", data=[1, 2, 3])

    result = {}

    def run():
        f1 = base.open_h5(p)
        result["evicted"] = base.evict_h5(p)
        result["closed"] = not bool(f1)
        result["evict_empty"] = base.evict_h5(p)  # nothing cached now
        f2 = base.open_h5(p, group="g")
        result["reopened"] = bool(f2)

    t = threading.Thread(target=run)  # fresh thread-local cache
    t.start()
    t.join()
    assert result["evicted"] is True
    assert result["closed"] is True
    assert result["evict_empty"] is False
    assert result["reopened"] is True


# ---------------------------------------------------------- stall watchdog
def test_watchdog_trips_on_armed_timeout():
    exits = []
    wd = io_guard.StallWatchdog(
        0.05, exit_fn=exits.append, poll_s=0.01
    ).start()
    try:
        wd.arm()
        deadline = time.monotonic() + 2.0
        while not exits and time.monotonic() < deadline:
            time.sleep(0.01)
        assert exits == [io_guard.PREEMPT_EXIT_CODE]
        assert wd.tripped
    finally:
        wd.stop()


def test_watchdog_disarmed_never_trips():
    exits = []
    wd = io_guard.StallWatchdog(
        0.05, exit_fn=exits.append, poll_s=0.01
    ).start()
    try:
        for _ in range(6):  # repeatedly armed but always fed in time
            wd.arm()
            time.sleep(0.01)
            wd.disarm()
        time.sleep(0.15)  # disarmed: idle time never counts
        assert exits == [] and not wd.tripped
    finally:
        wd.stop()


def test_watchdog_rejects_nonpositive_timeout():
    with pytest.raises(ValueError):
        io_guard.StallWatchdog(0)


def test_watch_passthrough_and_on_death():
    assert list(io_guard.watch(iter([1, 2, 3]), None)) == [1, 2, 3]

    def dying():
        yield 1
        raise io_guard.LoaderDeathError("thread gone")

    seen = []
    with pytest.raises(io_guard.LoaderDeathError):
        for item in io_guard.watch(dying(), None, on_death=seen.append):
            assert item == 1
    assert len(seen) == 1


# ------------------------------------------------- loader death (satellite)
def test_loader_worker_raise_surfaces_as_loader_death():
    """A worker thread raising mid-epoch (a bug, not a sample fault) was
    previously undefined behavior; it must now surface as
    LoaderDeathError — the signal train/worker.py converts into a
    checkpoint + clean-preempt exit instead of a hang or opaque crash."""
    sds = _make_sds()
    calls = {"n": 0}
    orig = type(sds).__getitem__

    def dying(self, idx):
        calls["n"] += 1
        if calls["n"] > 6:
            raise RuntimeError("loader bug")
        return orig(self, idx)

    sds.__class__ = type("DyingSDS", (type(sds),), {"__getitem__": dying})
    loader = pipeline.Loader(sds, batch_size=4, num_workers=2)
    before = io_guard.COUNTERS.snapshot()["loader_deaths"]
    try:
        with pytest.raises(io_guard.LoaderDeathError):
            list(loader)
    finally:
        loader.close()
    assert io_guard.COUNTERS.snapshot()["loader_deaths"] - before == 1


def test_loader_passes_quarantine_overflow_through():
    """The deliberate abort must NOT be converted into a preemptable
    loader death (a relaunch loop would burn the supervise budget on a
    rotted dataset)."""
    sds = _make_sds()

    def overflowing(self, idx):
        raise io_guard.QuarantineOverflowError("rotted")

    sds.__class__ = type(
        "OverflowSDS", (type(sds),), {"__getitem__": overflowing}
    )
    loader = pipeline.Loader(sds, batch_size=4, num_workers=2)
    try:
        with pytest.raises(io_guard.QuarantineOverflowError):
            list(loader)
    finally:
        loader.close()


# ------------------------------------------------------------ ops surfacing
def test_counters_surface_through_ops_metrics():
    from seist_tpu.ops import data_plane_counters

    before = data_plane_counters()
    io_guard.COUNTERS.inc("retries")
    after = data_plane_counters()
    assert after["retries"] == before["retries"] + 1
    assert set(after) >= {
        "reads", "retries", "reopens", "quarantined",
        "fallback_reads", "stall_trips", "loader_deaths",
    }
