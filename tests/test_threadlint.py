"""threadlint tests: every rule catches its seeded violation and stays
quiet on the clean twin; the threadlint suppression tag (shared grammar
with jaxlint, disjoint namespace); CLI exit codes on seeded fixtures for
EVERY rule in the catalog; the LockGraph runtime lane (order-cycle
detection, Condition-over-RLock compatibility, held-across-blocking,
nesting, overhead bound); and the pinned request_queue_size regression
for the PR 7 SYN-drop root cause."""

import textwrap
import threading
import time

import pytest

# repo root is put on sys.path by tests/conftest.py
from tools.threadlint import __main__ as threadlint_cli  # noqa: E402
from tools.threadlint.engine import lint_source  # noqa: E402
from tools.threadlint.runtime import LockGraph, active_graph  # noqa: E402


def rules_of(src, path="seist_tpu/serve/example.py"):
    return [f.rule for f in lint_source(textwrap.dedent(src), path)]


# ------------------------------------------------------------ unguarded-attr
def test_unguarded_read_flagged():
    src = """
    import threading

    class Stats:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def inc(self):
            with self._lock:
                self._n += 1

        def peek(self):
            return self._n
    """
    assert rules_of(src) == ["unguarded-attr"]


def test_unguarded_write_flagged():
    src = """
    import threading

    class Stats:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def inc(self):
            with self._lock:
                self._n += 1

        def reset(self):
            self._n = 0
    """
    assert rules_of(src) == ["unguarded-attr"]


def test_annotated_lock_assignment_recognized():
    # `self._lock: threading.Lock = threading.Lock()` must count exactly
    # like the unannotated form — a typing-hygiene edit must not turn
    # lock-discipline inference off for the class.
    src = """
    import threading

    class Stats:
        def __init__(self):
            self._lock: threading.Lock = threading.Lock()
            self._n = 0

        def inc(self):
            with self._lock:
                self._n += 1

        def peek(self):
            return self._n
    """
    assert rules_of(src) == ["unguarded-attr"]


def test_annotated_event_wait_no_timeout_flagged():
    src = """
    import threading

    class W:
        def __init__(self):
            self._ev: threading.Event = threading.Event()

        def block(self):
            self._ev.wait()
    """
    assert rules_of(src) == ["wait-no-timeout"]


def test_wrong_lock_access_still_flagged():
    # Holding A lock is not holding THE lock: self.n is written under
    # self._a, so reading it under self._b is still a race.
    src = """
    import threading

    class C:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()
            self.n = 0

        def inc(self):
            with self._a:
                self.n += 1

        def peek(self):
            with self._b:
                return self.n
    """
    assert rules_of(src) == ["unguarded-attr"]


def test_guarded_everywhere_ok():
    src = """
    import threading

    class Stats:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def inc(self):
            with self._lock:
                self._n += 1

        def peek(self):
            with self._lock:
                return self._n
    """
    assert rules_of(src) == []


def test_locked_suffix_convention_ok():
    # CircuitBreaker's idiom: *_locked methods run with the lock held.
    src = """
    import threading

    class Breaker:
        def __init__(self):
            self._lock = threading.Lock()
            self._state = "closed"

        def trip(self):
            with self._lock:
                self._open_locked()

        def _open_locked(self):
            self._state = "open"
    """
    assert rules_of(src) == []


def test_setstate_is_construction_context():
    src = """
    import threading

    class Q:
        def __init__(self):
            self._lock = threading.Lock()
            self._bad = {}

        def add(self, k):
            with self._lock:
                self._bad[k] = 1

        def __setstate__(self, state):
            self.__init__()
            self._bad.update(state)
    """
    assert rules_of(src) == []


def test_container_mutation_counts_as_write():
    src = """
    import threading

    class Sinks:
        def __init__(self):
            self._lock = threading.Lock()
            self._sinks = []

        def add(self, s):
            with self._lock:
                self._sinks.append(s)

        def fire(self):
            for s in self._sinks:
                s()
    """
    assert rules_of(src) == ["unguarded-attr"]


def test_condition_guards_like_a_lock():
    src = """
    import threading

    class B:
        def __init__(self):
            self._cond = threading.Condition()
            self._q = []

        def put(self, x):
            with self._cond:
                self._q.append(x)

        def depth(self):
            with self._cond:
                return len(self._q)
    """
    assert rules_of(src) == []


def test_unrelated_attr_never_flagged():
    src = """
    import threading

    class S:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0
            self.name = "x"

        def inc(self):
            with self._lock:
                self._n += 1

        def label(self):
            return self.name
    """
    assert rules_of(src) == []


# ----------------------------------------------------- signal-handler-unsafe
def test_handler_logging_flagged():
    src = """
    import signal

    def install(logger):
        def _term(signum, frame):
            logger.warning("going down")
        signal.signal(signal.SIGTERM, _term)
    """
    assert rules_of(src) == ["signal-handler-unsafe"]


def test_handler_flag_flip_and_set_ok():
    src = """
    import signal
    import threading

    def install(state):
        stop = threading.Event()

        def _term(signum, frame):
            state["rc"] = 75
            stop.set()
        signal.signal(signal.SIGTERM, _term)
        signal.signal(signal.SIGINT, _term)
        return stop
    """
    assert rules_of(src) == []


def test_handler_shared_for_two_signals_flagged_once():
    src = """
    import signal

    def install(logger):
        def _term(signum, frame):
            logger.warning("bye")
        signal.signal(signal.SIGTERM, _term)
        signal.signal(signal.SIGINT, _term)
    """
    assert rules_of(src) == ["signal-handler-unsafe"]


def test_handler_hard_exit_funnel_ok():
    src = """
    import os
    import signal
    from seist_tpu.data.io_guard import hard_exit

    def install():
        def _die(signum, frame):
            hard_exit(75)
        signal.signal(signal.SIGTERM, _die)
    """
    assert rules_of(src) == []


def test_lambda_handler_call_flagged():
    # The lambda body IS the offending call — it must not be skipped.
    src = """
    import signal

    def install(logger):
        signal.signal(signal.SIGTERM, lambda s, f: logger.warning("bye"))
    """
    assert rules_of(src) == ["signal-handler-unsafe"]


def test_lambda_handler_event_set_ok():
    src = """
    import signal
    import threading

    def install():
        stop = threading.Event()
        signal.signal(signal.SIGTERM, lambda s, f: stop.set())
        return stop
    """
    assert rules_of(src) == []


# ------------------------------------------------------------- thread-no-join
def test_non_daemon_thread_without_join_flagged():
    src = """
    import threading

    def spawn(fn):
        t = threading.Thread(target=fn)
        t.start()
    """
    assert rules_of(src) == ["thread-no-join"]


def test_non_daemon_thread_with_join_ok():
    src = """
    import threading

    def spawn(fn):
        t = threading.Thread(target=fn)
        t.start()
        t.join(timeout=5.0)
    """
    assert rules_of(src) == []


def test_daemon_thread_needs_no_join():
    src = """
    import threading

    def spawn(fn):
        threading.Thread(target=fn, daemon=True).start()
    """
    assert rules_of(src) == []


def test_self_attr_thread_join_in_other_method_ok():
    src = """
    import threading

    class W:
        def start(self, fn):
            self._t = threading.Thread(target=fn)
            self._t.start()

        def stop(self):
            self._t.join(timeout=2.0)
    """
    assert rules_of(src) == []


# -------------------------------------------------------- thread-target-raises
def test_unshielded_target_flagged():
    src = """
    import threading

    def _loop():
        while True:
            do_work()

    def start():
        threading.Thread(target=_loop, daemon=True).start()
    """
    assert rules_of(src) == ["thread-target-raises"]


def test_try_wrapped_target_ok():
    src = """
    import threading

    def _loop():
        try:
            while True:
                do_work()
        except Exception:
            record_death()

    def start():
        threading.Thread(target=_loop, daemon=True).start()
    """
    assert rules_of(src) == []


def test_try_finally_without_except_still_flagged():
    # finally releases resources but the exception still escapes the
    # top frame — the death is still silent.
    src = """
    import threading

    def _loop(sem):
        try:
            do_work()
        finally:
            sem.release()

    def start(sem):
        threading.Thread(target=_loop, args=(sem,), daemon=True).start()
    """
    assert rules_of(src) == ["thread-target-raises"]


def test_self_method_target_resolved():
    src = """
    import threading

    class W:
        def _run(self):
            spin()

        def start(self):
            self._t = threading.Thread(target=self._run, daemon=True)
            self._t.start()
    """
    assert rules_of(src) == ["thread-target-raises"]


def test_external_bound_method_target_skipped():
    src = """
    import threading

    def serve(server):
        threading.Thread(target=server.serve_forever, daemon=True).start()
    """
    assert rules_of(src) == []


def test_annotated_thread_binding_join_credited():
    # A typing-hygiene annotation on the binding must not hide the join.
    src = """
    import threading

    class W:
        def start(self):
            self._t: threading.Thread = threading.Thread(target=self._run)
            self._t.start()

        def stop(self):
            self._t.join()
    """
    assert rules_of(src) == []


# ------------------------------------------------------------ wait-no-timeout
def test_untimed_event_wait_flagged():
    src = """
    import threading

    def main():
        stop = threading.Event()
        stop.wait()
    """
    assert rules_of(src) == ["wait-no-timeout"]


def test_timed_wait_ok():
    src = """
    import threading

    def main():
        stop = threading.Event()
        while not stop.wait(0.5):
            poll()
    """
    assert rules_of(src) == []


def test_untimed_condition_attr_wait_flagged():
    src = """
    import threading

    class B:
        def __init__(self):
            self._cond = threading.Condition()

        def park(self):
            with self._cond:
                self._cond.wait()
    """
    assert rules_of(src) == ["wait-no-timeout"]


def test_wait_with_none_timeout_flagged():
    # wait(None) / wait(timeout=None) are the same forever-park as
    # wait() and must not slip the rule.
    src = """
    import threading

    def main():
        stop = threading.Event()
        stop.wait(None)
        stop.wait(timeout=None)
    """
    assert rules_of(src) == ["wait-no-timeout", "wait-no-timeout"]


def test_unknown_receiver_wait_skipped():
    # proc.wait() (subprocess) must not be mistaken for an Event wait.
    src = """
    def reap(proc):
        proc.wait()
    """
    assert rules_of(src) == []


# -------------------------------------------------------- http-server-backlog
def test_server_subclass_without_backlog_flagged():
    src = """
    from http.server import ThreadingHTTPServer

    class MyServer(ThreadingHTTPServer):
        daemon_threads = True
    """
    assert rules_of(src) == ["http-server-backlog"]


def test_server_subclass_with_backlog_ok():
    src = """
    from http.server import ThreadingHTTPServer

    class MyServer(ThreadingHTTPServer):
        daemon_threads = True
        request_queue_size = 1024
    """
    assert rules_of(src) == []


def test_bare_backlog_annotation_not_pinned():
    # `request_queue_size: int` with no value assigns nothing — the
    # backlog silently stays at socketserver's 5.
    src = """
    from http.server import ThreadingHTTPServer

    class MyServer(ThreadingHTTPServer):
        request_queue_size: int
    """
    assert rules_of(src) == ["http-server-backlog"]


def test_annotated_backlog_assignment_pinned_ok():
    src = """
    from http.server import ThreadingHTTPServer

    class MyServer(ThreadingHTTPServer):
        request_queue_size: int = 1024
    """
    assert rules_of(src) == []


def test_plain_class_not_a_server():
    src = """
    class MyServer:
        pass
    """
    assert rules_of(src) == []


# ------------------------------------------------------- exit-outside-funnel
def test_os_exit_outside_funnel_flagged():
    src = """
    import os

    def die():
        os._exit(1)
    """
    assert rules_of(src) == ["exit-outside-funnel"]


def test_os_exit_inside_hard_exit_funnel_ok():
    src = """
    import os

    def hard_exit(code):
        os._exit(code)
    """
    assert rules_of(src) == []


def test_undocumented_exit_code_flagged():
    src = """
    import sys

    def main():
        sys.exit(7)
    """
    assert rules_of(src) == ["exit-outside-funnel"]


def test_contract_exit_codes_ok():
    src = """
    import sys
    from seist_tpu.train.checkpoint import PREEMPT_EXIT_CODE

    def a():
        sys.exit(0)

    def b():
        sys.exit(1)

    def c():
        sys.exit(PREEMPT_EXIT_CODE)

    if __name__ == "__main__":
        sys.exit(a())
    """
    assert rules_of(src) == []


def test_wrong_uppercase_exit_constant_flagged():
    src = """
    import sys

    MY_SPECIAL_CODE = 42

    def main():
        sys.exit(MY_SPECIAL_CODE)
    """
    assert rules_of(src) == ["exit-outside-funnel"]


def test_exit_with_message_string_ok():
    # sys.exit("msg") is the stdlib print-to-stderr-and-exit-1 idiom.
    src = """
    import sys

    def main():
        sys.exit("config file missing")
    """
    assert rules_of(src) == []


def test_exit_with_negative_literal_flagged():
    # -1 parses as UnaryOp(USub, Constant(1)); the rule must fold it —
    # sys.exit(-1) (process rc 255) is the classic non-contract exit.
    src = """
    import sys

    def main():
        sys.exit(-1)
    """
    assert rules_of(src) == ["exit-outside-funnel"]


def test_exit_with_bool_flagged():
    # bools are ints (True == 1) but sys.exit(True) is a bug, not the
    # contract — must not slip through the 0/1/2 check.
    src = """
    import sys

    def main(failed):
        sys.exit(failed)
        sys.exit(True)
    """
    assert rules_of(src) == ["exit-outside-funnel"]


# ------------------------------------------------- suppressions & tag hygiene
def test_threadlint_suppression_with_rationale():
    src = """
    import threading

    def main():
        stop = threading.Event()
        # threadlint: disable=wait-no-timeout -- main thread; signal
        # handlers interrupt the wait.
        stop.wait()
    """
    assert rules_of(src) == []


def test_rationale_less_suppression_is_void_and_flagged():
    src = """
    import threading

    def main():
        stop = threading.Event()
        stop.wait()  # threadlint: disable=wait-no-timeout
    """
    assert sorted(rules_of(src)) == [
        "suppression-missing-rationale",
        "wait-no-timeout",
    ]


def test_jaxlint_tag_cannot_silence_threadlint():
    src = """
    import threading

    def main():
        stop = threading.Event()
        stop.wait()  # jaxlint: disable=wait-no-timeout -- wrong tag
    """
    assert rules_of(src) == ["wait-no-timeout"]


def test_unused_threadlint_suppression_reported():
    src = """
    def fine():
        # threadlint: disable=wait-no-timeout -- nothing to silence here
        return 1
    """
    assert rules_of(src) == ["unused-suppression"]


# --------------------------------------------------------------- CLI contract
_SEEDED_FIXTURES = {
    "unguarded-attr": """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def inc(self):
                with self._lock:
                    self._n += 1

            def peek(self):
                return self._n
    """,
    "signal-handler-unsafe": """
        import signal

        def install(logger):
            def _term(signum, frame):
                logger.warning("bye")
            signal.signal(signal.SIGTERM, _term)
    """,
    "thread-no-join": """
        import threading

        def spawn(fn):
            threading.Thread(target=fn).start()
    """,
    "thread-target-raises": """
        import threading

        def _loop():
            spin()

        def spawn():
            threading.Thread(target=_loop, daemon=True).start()
    """,
    "wait-no-timeout": """
        import threading

        def main():
            threading.Event().wait
            stop = threading.Event()
            stop.wait()
    """,
    "http-server-backlog": """
        from http.server import ThreadingHTTPServer

        class S(ThreadingHTTPServer):
            pass
    """,
    "exit-outside-funnel": """
        import sys

        def main():
            sys.exit(9)
    """,
}


@pytest.mark.parametrize("rule", sorted(_SEEDED_FIXTURES))
def test_cli_exits_nonzero_on_seeded_violation(rule, tmp_path):
    """Acceptance: `python -m tools.threadlint` exits nonzero on a seeded
    violation fixture for every rule in the catalog."""
    mod = tmp_path / "seeded.py"
    mod.write_text(textwrap.dedent(_SEEDED_FIXTURES[rule]))
    rc = threadlint_cli.main(
        ["seeded.py", "--root", str(tmp_path),
         "--baseline", str(tmp_path / "baseline.json")]
    )
    assert rc == 1
    found = [
        f.rule for f in lint_source(
            textwrap.dedent(_SEEDED_FIXTURES[rule]), "seeded.py"
        )
    ]
    assert rule in found


def test_cli_repo_gate_is_green():
    """The shipped tree lints clean with ZERO grandfathered entries —
    every introduction-time finding was fixed or carries a rationale'd
    suppression."""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rc = threadlint_cli.main(["seist_tpu", "tools", "--root", repo])
    assert rc == 0
    import json

    with open(os.path.join(repo, "tools", "threadlint_baseline.json")) as f:
        assert json.load(f)["accepted"] == {}


def test_cli_unknown_path_exits_2(tmp_path):
    assert threadlint_cli.main(
        ["no_such_dir", "--root", str(tmp_path)]
    ) == 2


# ------------------------------------------------------------------ LockGraph
def test_lockgraph_detects_seeded_cycle():
    with LockGraph() as g:
        a = threading.Lock()
        b = threading.Lock()

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        t1 = threading.Thread(target=ab)
        t1.start()
        t1.join()
        t2 = threading.Thread(target=ba)
        t2.start()
        t2.join()
    cycles = g.cycles()
    assert cycles, g.report()
    with pytest.raises(AssertionError, match="CYCLE"):
        g.assert_clean()


def test_lockgraph_consistent_order_is_clean():
    with LockGraph() as g:
        a = threading.Lock()
        b = threading.Lock()

        def ab():
            with a:
                with b:
                    pass

        for _ in range(2):
            t = threading.Thread(target=ab)
            t.start()
            t.join()
    assert g.cycles() == []
    g.assert_clean()


def test_lockgraph_condition_wait_notify_works():
    """threading.Condition built on the instrumented RLock must keep its
    full wait/notify semantics (the private _release_save protocol)."""
    with LockGraph() as g:
        cond = threading.Condition()
        box = []

        def consumer():
            with cond:
                while not box:
                    cond.wait(2.0)

        t = threading.Thread(target=consumer)
        t.start()
        time.sleep(0.05)
        with cond:
            box.append(1)
            cond.notify_all()
        t.join(timeout=5.0)
        assert not t.is_alive()
        # reentrant with-blocks must not self-edge (RLock recursion)
        with cond:
            with cond:
                pass
    g.assert_clean()


def test_lockgraph_held_across_blocking_violation():
    with LockGraph() as g:
        lock = threading.Lock()
        with lock:
            g.check_blocking("model_forward")
    assert g.violations
    assert g.violations[0]["blocking"] == "model_forward"
    with pytest.raises(AssertionError, match="HELD-ACROSS-BLOCKING"):
        g.assert_clean()


def test_lockgraph_blocking_outside_lock_is_clean():
    with LockGraph() as g:
        lock = threading.Lock()
        with lock:
            pass
        g.check_blocking("model_forward")
    assert not g.violations
    g.assert_clean()


def test_lockgraph_lock_outliving_its_graph_reattaches():
    """A lock created in an earlier (now done) graph window must report
    to the CURRENTLY active graph — a process-wide singleton constructed
    by the first test of a --lock-graph lane stays auditable for the
    rest of the lane instead of recording into a dead graph."""
    with LockGraph():
        a = threading.Lock()
        b = threading.Lock()
    with LockGraph() as g2:
        with a:
            with b:
                pass
        with b:
            with a:
                pass
    assert g2.cycles(), "cycle through locks born in a dead graph was lost"


def test_lockgraph_paused_graph_keeps_held_bookkeeping():
    """A nested graph pauses the outer one's RECORDING, but the outer
    graph's locks keep getting acquired/released inside the inner
    window — the held stacks must track that, or a lock released while
    paused stays 'held' forever (phantom edges + false violations after
    resume), and one acquired while paused is invisibly held."""
    with LockGraph() as outer:
        lock = threading.Lock()
        lock.acquire()
        with LockGraph():
            lock.release()  # released while outer is PAUSED
        outer.check_blocking("after_resume")  # must see nothing held
    assert not outer.violations, outer.violations
    outer.assert_clean()

    with LockGraph() as outer2:
        lock2 = threading.Lock()
        with LockGraph():
            lock2.acquire()  # acquired while outer2 is PAUSED
        outer2.check_blocking("resumed_held")  # hold must be visible
        lock2.release()
        outer2.check_blocking("resumed_released")
    assert [v["blocking"] for v in outer2.violations] == ["resumed_held"]


def test_lockgraph_condition_wait_preserves_rlock_depth():
    """Condition.wait at RLock recursion depth 2: wait fully releases
    and restores the RLock, and the graph entry must come back at the
    SAME depth — otherwise exiting the inner `with` pops the entry while
    the outer `with` still really holds the lock, and blocking calls /
    ordering edges there go unseen."""
    with LockGraph() as g:
        cond = threading.Condition()

        def waker():
            time.sleep(0.1)
            with cond:
                cond.notify_all()

        t = threading.Thread(target=waker)
        t.start()
        with cond:
            with cond:
                cond.wait(timeout=5.0)
            # inner with exited; the OUTER with still holds the RLock
            g.check_blocking("still_held")
        t.join(timeout=5.0)
        assert not t.is_alive()
    assert g.violations, "outer with-block hold was lost across wait()"
    assert g.violations[0]["blocking"] == "still_held"


def test_lockgraph_cross_thread_release_leaves_no_stale_held():
    """A primitive Lock may legally be released by another thread (the
    one-shot handoff idiom). The holder's bookkeeping entry must clear,
    or the acquiring thread looks locked forever — false ordering edges
    and spurious HELD-ACROSS-BLOCKING violations for the rest of the
    graph window."""
    with LockGraph() as g:
        handoff = threading.Lock()
        parked = threading.Event()
        released = threading.Event()

        def worker():
            handoff.acquire()  # released by the MAIN thread below
            parked.set()
            assert released.wait(timeout=5.0)
            g.check_blocking("after_handoff")  # must see nothing held

        t = threading.Thread(target=worker)
        t.start()
        assert parked.wait(timeout=5.0)
        handoff.release()  # cross-thread release
        released.set()
        t.join(timeout=5.0)
        assert not t.is_alive()
    assert not g.violations, g.violations
    g.assert_clean()


def test_lockgraph_nests():
    """An explicit LockGraph inside a --lock-graph lane: the outer graph
    pauses, the inner one records, factories restore LIFO. Under the
    lane itself there is already an ambient graph — everything must
    restore to IT, which is exactly the property being tested."""
    ambient = active_graph()  # the lane's graph under --lock-graph
    ambient_factory = threading.Lock
    with LockGraph() as outer:
        with LockGraph() as inner:
            assert active_graph() is inner
            lock = threading.Lock()
            with lock:
                inner.check_blocking("x")
        assert active_graph() is outer
        assert inner.violations and not outer.violations
    assert active_graph() is ambient
    assert threading.Lock is ambient_factory  # prior factory restored


def test_lockgraph_locks_survive_the_window():
    with LockGraph():
        stale = threading.Lock()
    with stale:  # must still work (and record nothing) after exit
        pass
    assert not stale.locked()


def test_lockgraph_overhead_bound():
    """The instrumentation costs one dict op per acquire/release. Bound
    it at 50us/pair — two orders of magnitude looser than the measured
    ~1-2us, yet still guaranteeing <5% of even a 10ms serve-smoke
    request at the ~50 lock ops a request performs (the gate
    docs/STATIC_ANALYSIS.md documents)."""
    n = 5000
    with LockGraph():
        lock = threading.Lock()
        t0 = time.perf_counter()
        for _ in range(n):
            lock.acquire()
            lock.release()
        per_pair = (time.perf_counter() - t0) / n
    assert per_pair < 50e-6, f"lock instrumentation too slow: {per_pair*1e6:.1f}us/pair"


# ------------------------------------------------- PR 7 regression: backlogs
def test_http_servers_pin_request_queue_size():
    """The PR 7 root cause, pinned: every HTTP tier keeps an explicit
    1024 listen backlog (socketserver's default of 5 silently dropped
    SYNs under conn-per-request load)."""
    from seist_tpu.obs.http import MetricsHTTPServer
    from seist_tpu.serve.router import RouterHTTPServer
    from seist_tpu.serve.server import ServeHTTPServer

    for cls in (ServeHTTPServer, RouterHTTPServer, MetricsHTTPServer):
        # the attribute must be pinned ON the class, not inherited from
        # socketserver's default
        assert "request_queue_size" in vars(cls), cls
        assert cls.request_queue_size == 1024, cls
