"""Subprocess body for tests/test_multihost.py — one simulated host.

Run as: python _multihost_worker.py <process_id> <num_processes> <port>
Each process gets 4 virtual CPU devices; together they form one 8-device
JAX runtime, exercising the real multi-host code paths (global array
assembly from local shards, counter/target sync, object broadcast).
Exit code 0 = all checks passed.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import numpy as np  # noqa: E402

proc_id, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# The CPU backend refuses multiprocess computations ("Multiprocess
# computations aren't implemented on the CPU backend") unless a CPU
# collectives implementation is selected — gloo ships in jaxlib. This was
# the seed test_multihost failure (ROADMAP burn-down): the rendezvous
# succeeded, the first cross-process collective crashed.
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(
    coordinator_address=f"localhost:{port}",
    num_processes=nprocs,
    process_id=proc_id,
)
assert jax.process_count() == nprocs, jax.process_count()
assert len(jax.devices()) == 4 * nprocs, len(jax.devices())

import jax.numpy as jnp  # noqa: E402

from seist_tpu.ops.metrics import Metrics  # noqa: E402
from seist_tpu.parallel.dist import broadcast_object  # noqa: E402
from seist_tpu.parallel.mesh import make_mesh, shard_batch, to_local  # noqa: E402

# --- 1. object broadcast (checkpoint-path use case) -------------------------
obj = {"path": "checkpoints/model-7", "loss": 0.25} if proc_id == 0 else None
got = broadcast_object(obj)
assert got == {"path": "checkpoints/model-7", "loss": 0.25}, got

# --- 2. global array from per-host shards + jitted global reduction ---------
mesh = make_mesh()  # (8, 1, 1) over both processes
local = np.full((4, 3), float(proc_id + 1), dtype=np.float32)  # host rows
gbl = shard_batch(mesh, local)
assert gbl.shape == (8, 3), gbl.shape  # global batch = 2 hosts x 4

total = float(jax.jit(jnp.sum)(gbl))
assert total == (1.0 * 12 + 2.0 * 12), total

# --- 3. to_local returns exactly this host's rows ---------------------------
back = to_local(gbl)
np.testing.assert_array_equal(back, local)

# --- 4. metrics sync with UNEQUAL per-host row counts (r2 target gather) ----
m = Metrics("emg", ["mae", "r2"], sampling_rate=50, time_threshold=0.1,
            num_samples=8192)
if proc_id == 0:
    t = np.array([[1.0], [2.0], [3.0]])
    p = np.array([[1.5], [2.0], [2.0]])
else:
    t = np.array([[4.0], [6.0]])
    p = np.array([[5.0], [6.0]])
m.compute(t, p)
m.synchronize_between_processes()
r = m.get_all_metrics()

t_all = np.array([[1.0], [2.0], [3.0], [4.0], [6.0]])
p_all = np.array([[1.5], [2.0], [2.0], [5.0], [6.0]])
res = t_all - p_all
mae_want = np.abs(res).mean()
tc = t_all - t_all.mean()
r2_want = 1 - (res**2).mean(-1).sum() / ((tc**2).mean(-1).sum() + 1e-6)
assert abs(r["mae"] - mae_want) < 1e-5, (r["mae"], mae_want)
assert abs(r["r2"] - r2_want) < 1e-5, (r["r2"], r2_want)

print(f"worker {proc_id}: OK")
