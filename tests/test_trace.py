"""obs/trace.py distributed-tracing plane + obs/fleet.py aggregation +
tools/trace_report.py stitching: the per-request observability spine
(ISSUE 11). Pure units — no jax, no model; the cross-process e2e lives
in `make trace-smoke` and the serve-chaos trace acceptance test.

Also pins the tracing overhead bound: a full request-trace lifecycle
must cost far under 1% of serve-smoke's p50 (the PR 6 telemetry-overhead
style gate).
"""

import json
import threading
import time

import pytest

from seist_tpu.obs import trace as T
from seist_tpu.obs.fleet import FleetAggregator, _split_key
from seist_tpu.obs.bus import MetricsBus


@pytest.fixture(autouse=True)
def _fresh_buffer():
    """Tests that go through module-level helpers must not leak traces
    into the process singleton."""
    T.BUFFER.reset()
    yield
    T.BUFFER.reset()


# ------------------------------------------------------------ traceparent
class TestTraceparent:
    def test_mint_parse_roundtrip(self):
        header = T.mint_traceparent()
        parsed = T.parse_traceparent(header)
        assert parsed is not None
        tid, sid = parsed
        assert len(tid) == 32 and len(sid) == 16
        assert T.format_traceparent(tid, sid) == header

    def test_malformed_headers_start_fresh(self):
        for bad in (None, "", "garbage", "00-zz-yy-01", 42,
                    "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # zero tid
                    "00-" + "1" * 32 + "-" + "0" * 16 + "-01"):  # zero sid
            assert T.parse_traceparent(bad) is None

    def test_case_and_whitespace_tolerant(self):
        tid, sid = "ab" * 16, "cd" * 8
        header = f"  00-{tid.upper()}-{sid.upper()}-01 "
        assert T.parse_traceparent(header) == (tid, sid)

    def test_minted_ids_unique(self):
        assert len({T.mint_traceparent() for _ in range(64)}) == 64


# ------------------------------------------------------------ RequestTrace
class TestRequestTrace:
    def test_spans_parent_to_root_and_root_to_upstream(self):
        buf = T.TraceBuffer(capacity=8, sample=1.0)
        header = T.mint_traceparent()
        tid, upstream = T.parse_traceparent(header)
        rt = T.RequestTrace(header, name="server:/predict", buffer=buf)
        with rt.span("parse") as sp:
            sp.annotate(bytes=100)
        rt.add_child("queue_wait", 12.0, flush=3, bucket=4)
        rt.finish(200)
        payload = buf.get(tid)
        spans = {s["name"]: s for s in payload["spans"]}
        root = spans["server:/predict"]
        assert root["parent_id"] == upstream
        assert root["span_id"] == rt.root_span_id
        assert root["annotations"]["status"] == 200
        assert spans["parse"]["parent_id"] == rt.root_span_id
        assert spans["parse"]["annotations"] == {"bytes": 100}
        assert spans["queue_wait"]["dur_ms"] == 12.0

    def test_minted_when_no_header(self):
        buf = T.TraceBuffer(capacity=8)
        rt = T.RequestTrace(None, name="router:/predict", buffer=buf,
                            process="router")
        assert rt.minted_here
        rt.finish(200)
        payload = buf.get(rt.trace_id)
        assert payload["spans"][0]["parent_id"] is None
        assert payload["spans"][0]["process"] == "router"

    def test_span_exception_annotates_and_propagates(self):
        buf = T.TraceBuffer(capacity=8)
        rt = T.RequestTrace(None, buffer=buf)
        with pytest.raises(ValueError):
            with rt.span("admission"):
                raise ValueError("shed")
        rt.finish(503)
        spans = buf.get(rt.trace_id)["spans"]
        assert spans[0]["annotations"]["error"] == "ValueError"

    def test_error_flag_from_status_but_not_when_shed(self):
        buf = T.TraceBuffer(capacity=8)
        rt = T.RequestTrace(None, buffer=buf)
        rt.finish(500)
        assert "error" in buf.get(rt.trace_id)["flags"]
        rt2 = T.RequestTrace(None, buffer=buf)
        rt2.flag("shed")
        rt2.finish(503)
        assert buf.get(rt2.trace_id)["flags"] == ["shed"]

    def test_slo_breach_flag(self):
        buf = T.TraceBuffer(capacity=8)
        rt = T.RequestTrace(None, buffer=buf, slo_ms=0.0001)
        time.sleep(0.002)
        rt.finish(200)
        assert "slo_breach" in buf.get(rt.trace_id)["flags"]

    def test_finish_idempotent_and_straggler_dropped(self):
        buf = T.TraceBuffer(capacity=8)
        rt = T.RequestTrace(None, buffer=buf)
        d1 = rt.finish(200)
        assert rt.finish(200) == d1
        # A batcher straggler recording after the retention verdict must
        # not resurrect or grow the committed trace.
        rt.add_child("queue_wait", 5.0)
        assert len(buf.get(rt.trace_id)["spans"]) == 1

    def test_server_timing_header_shape(self):
        rt = T.RequestTrace(None, buffer=T.TraceBuffer(capacity=4))
        with rt.span("parse"):
            pass
        rt.add_child("queue wait/odd", 3.25)
        rt.finish(200)
        st = rt.server_timing()
        assert st.startswith("total;dur=")
        assert "parse;dur=" in st
        # names are sanitized into header-safe tokens
        assert "queue_wait_odd;dur=3.2" in st

    def test_pre_minted_span_id_kept(self):
        """The router pre-mints an attempt's span id (it went downstream
        as the replica's parent) — add_child must keep it."""
        buf = T.TraceBuffer(capacity=4)
        rt = T.RequestTrace(None, buffer=buf)
        sid = T._new_span_id()
        rt.add_child("attempt", 7.0, span_id=sid, replica="r0")
        rt.finish(200)
        spans = buf.get(rt.trace_id)["spans"]
        assert spans[0]["span_id"] == sid

    def test_null_trace_is_inert(self):
        n = T.NULL
        with n.span("x") as sp:
            sp.annotate(a=1)
        n.add_child("y", 1.0)
        n.flag("error")
        assert n.finish(200) == 0.0
        assert n.server_timing() == ""
        assert T.ensure(None) is T.NULL
        rt = T.RequestTrace(None, buffer=T.TraceBuffer(capacity=4))
        assert T.ensure(rt) is rt


# ------------------------------------------------------- retention policy
class TestTailRetention:
    def test_flagged_always_kept_unflagged_sampled(self):
        buf = T.TraceBuffer(capacity=64, sample=0.0)  # keep flagged ONLY
        kept, dropped = [], []
        for i in range(16):
            rt = T.RequestTrace(None, buffer=buf)
            if i % 4 == 0:
                rt.flag("retried")
                kept.append(rt.trace_id)
            else:
                dropped.append(rt.trace_id)
            rt.finish(200)
        for tid in kept:
            assert buf.get(tid) is not None
        for tid in dropped:
            assert buf.get(tid) is None
        stats = buf.stats()
        assert stats["kept"] == 4 and stats["dropped"] == 12

    def test_sampling_deterministic_across_buffers(self):
        """Two processes with the same rate keep the SAME subset — the
        property that makes a sampled-in trace stitch fleet-wide."""
        b1 = T.TraceBuffer(capacity=512, sample=0.5)
        b2 = T.TraceBuffer(capacity=512, sample=0.5)
        ids = [T._new_trace_id() for _ in range(256)]
        verdicts1 = [b1.sampled(t) for t in ids]
        verdicts2 = [b2.sampled(t) for t in ids]
        assert verdicts1 == verdicts2
        assert 32 < sum(verdicts1) < 224  # actually samples, both ways

    def test_eviction_prefers_unflagged(self):
        buf = T.TraceBuffer(capacity=4, sample=1.0)
        flagged, unflagged = [], []
        for i in range(8):
            rt = T.RequestTrace(None, buffer=buf)
            if i < 2:
                rt.flag("error")
                flagged.append(rt.trace_id)
            else:
                unflagged.append(rt.trace_id)
            rt.finish(None)
        # capacity 4: the 2 flagged survive; only unflagged were evicted
        # beyond that.
        for tid in flagged:
            assert buf.get(tid) is not None, "flagged trace was evicted"
        assert sum(1 for t in unflagged if buf.get(t)) == 2
        assert buf.stats()["evicted"] == 4

    def test_open_traces_bounded(self):
        """Never-committed traces (a wedged handler) must not leak past
        the ring bound."""
        buf = T.TraceBuffer(capacity=4, sample=1.0)
        for _ in range(12):
            rt = T.RequestTrace(None, buffer=buf)
            rt.add_child("x", 1.0)  # open, never finished
        assert buf.stats()["resident"] <= 4


# ----------------------------------------------------------- flush scope
class TestFlushScope:
    def test_annotations_reach_every_member_trace(self):
        buf = T.TraceBuffer(capacity=8)
        rts = [T.RequestTrace(None, buffer=buf) for _ in range(3)]
        with T.flush_scope(rts + [None]) as scope:
            assert T.in_flush()
            T.annotate_flush(program="m/full/b4/fp32", aot=True)
        assert not T.in_flush()
        assert scope.annotations == {"program": "m/full/b4/fp32",
                                     "aot": True}
        for rt in rts:
            rt.add_child("forward", 9.0, **scope.annotations)
            rt.finish(200)
            spans = buf.get(rt.trace_id)["spans"]
            fwd = [s for s in spans if s["name"] == "forward"][0]
            assert fwd["annotations"]["program"] == "m/full/b4/fp32"

    def test_annotate_outside_flush_is_noop(self):
        T.annotate_flush(program="zzz")  # must not raise or leak

    def test_scopes_nest(self):
        with T.flush_scope([]) as outer:
            with T.flush_scope([]):
                T.annotate_flush(inner=1)
            T.annotate_flush(outer=1)
        assert outer.annotations == {"outer": 1}


# ------------------------------------------------- batcher trace spans
class TestBatcherTracing:
    def test_queue_wait_and_forward_spans_with_annotations(self):
        import numpy as np

        from seist_tpu.serve.batcher import BatcherConfig, MicroBatcher

        buf = T.TraceBuffer(capacity=16)

        def forward(batch):
            T.annotate_flush(program="fake/full/b4/fp32", aot=True)
            return batch

        b = MicroBatcher(forward, BatcherConfig(max_batch=4,
                                                max_delay_ms=5.0),
                         name="tr")
        rt = T.RequestTrace(None, buffer=buf)
        b.submit(np.zeros((2, 3), np.float32), timeout_ms=5000, trace=rt)
        rt.finish(200)
        b.shutdown()
        spans = {s["name"]: s for s in buf.get(rt.trace_id)["spans"]}
        qw = spans["queue_wait"]
        assert qw["annotations"]["bucket"] == 1
        assert qw["annotations"]["flush"] == 1
        fwd = spans["forward"]
        assert fwd["annotations"]["program"] == "fake/full/b4/fp32"
        assert fwd["annotations"]["aot"] is True
        assert fwd["annotations"]["occupancy"] == 1.0

    def test_forward_error_recorded_on_trace(self):
        import numpy as np

        from seist_tpu.serve.batcher import BatcherConfig, MicroBatcher
        from seist_tpu.serve.protocol import ServeError

        buf = T.TraceBuffer(capacity=16)

        def forward(batch):
            raise RuntimeError("device boom")

        b = MicroBatcher(forward, BatcherConfig(max_batch=2,
                                                max_delay_ms=5.0),
                         name="tr2")
        rt = T.RequestTrace(None, buffer=buf)
        with pytest.raises(ServeError):
            b.submit(np.zeros((2,), np.float32), timeout_ms=3000, trace=rt)
        rt.finish(500)
        b.shutdown()
        spans = {s["name"]: s for s in buf.get(rt.trace_id)["spans"]}
        assert spans["forward"]["annotations"]["error"] == "RuntimeError"
        assert "error" in buf.get(rt.trace_id)["flags"]


# ------------------------------------------------------- router tracing
class TestRouterTracing:
    def _fake_replica(self, status=200, body=None):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        seen = {"traceparent": []}

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                self.rfile.read(n)
                seen["traceparent"].append(
                    self.headers.get("traceparent")
                )
                payload = json.dumps(body or {"ok": True}).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        server = ThreadingHTTPServer(("127.0.0.1", 0), H)
        server.daemon_threads = True
        threading.Thread(target=server.serve_forever, daemon=True).start()
        return server, f"127.0.0.1:{server.server_address[1]}", seen

    def test_attempt_span_and_downstream_propagation(self):
        from seist_tpu.serve.router import Router, RouterConfig

        server, url, seen = self._fake_replica()
        router = Router(config=RouterConfig(retries=1))
        try:
            router.registry.add(url)
            header = T.mint_traceparent()
            tid, _ = T.parse_traceparent(header)
            status, headers, _ = router.forward(
                "/predict", b"{}", traceparent=header
            )
            assert status == 200
            # Response carries the router's identity + a timing total.
            assert headers["traceparent"].split("-")[1] == tid
            assert headers["Server-Timing"].startswith("router;dur=")
            # Downstream got the SAME trace id with the attempt span as
            # parent — and that attempt span is in the router's ring.
            sent = seen["traceparent"][0]
            s_tid, s_parent = T.parse_traceparent(sent)
            assert s_tid == tid
            payload = T.BUFFER.get(tid)
            attempts = [s for s in payload["spans"]
                        if s["name"] == "attempt"]
            assert len(attempts) == 1
            assert attempts[0]["span_id"] == s_parent
            ann = attempts[0]["annotations"]
            assert ann["replica"] == url
            assert ann["class"] == "ok" and ann["status"] == 200
            assert ann["breaker"] == "closed"
        finally:
            router.stop()
            server.shutdown()
            server.server_close()

    def test_retry_flags_trace_and_records_both_attempts(self):
        import socket

        from seist_tpu.serve.router import Router, RouterConfig

        # A dead port + a live replica: the first attempt fails, the
        # retry succeeds — the trace must show both.
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_url = f"127.0.0.1:{s.getsockname()[1]}"
        s.close()
        server, live_url, _ = self._fake_replica()
        router = Router(config=RouterConfig(retries=2,
                                            request_timeout_s=2.0))
        try:
            router.registry.add(dead_url)
            router.registry.add(live_url)
            # Route until we hit the dead-then-live shape.
            for _ in range(4):
                status, headers, _ = router.forward("/predict", b"{}")
                assert status == 200
            traces = T.BUFFER.index()
            retried = [t for t in traces if "retried" in t["flags"]]
            assert retried, traces
            payload = T.BUFFER.get(retried[-1]["trace_id"])
            attempts = [s for s in payload["spans"]
                        if s["name"] == "attempt"]
            assert len(attempts) >= 2
            classes = {a["annotations"]["class"] for a in attempts}
            assert "net_error" in classes and "ok" in classes
        finally:
            router.stop()
            server.shutdown()
            server.server_close()

    def test_shed_flagged_not_error(self):
        from seist_tpu.serve.router import Router, RouterConfig

        server, url, _ = self._fake_replica(
            status=503, body={"error": "shed", "retry_after_s": 1.0}
        )
        router = Router(config=RouterConfig(retries=2))
        try:
            router.registry.add(url)
            status, _, _ = router.forward("/predict", b"{}")
            assert status == 503
            traces = T.BUFFER.index()
            assert traces and traces[0]["flags"] == ["shed"]
            spans = T.BUFFER.get(traces[0]["trace_id"])["spans"]
            attempt = [s for s in spans if s["name"] == "attempt"][0]
            assert attempt["annotations"]["class"] == "shed_not_retried"
        finally:
            router.stop()
            server.shutdown()
            server.server_close()


# ------------------------------------------------------------- stitching
class TestStitcher:
    def _segments(self):
        tid = T._new_trace_id()
        router_root, attempt, server_root = (T._new_span_id()
                                             for _ in range(3))
        router_seg = {
            "trace_id": tid, "process": "router", "flags": ["retried"],
            "spans": [
                {"span_id": router_root, "parent_id": None,
                 "name": "router:/predict", "t0": 100.0, "dur_ms": 50.0,
                 "root": True, "process": "router"},
                {"span_id": attempt, "parent_id": router_root,
                 "name": "attempt", "t0": 100.001, "dur_ms": 48.0,
                 "annotations": {"replica": "r1", "class": "ok"},
                 "process": "router"},
            ],
        }
        replica_seg = {
            "trace_id": tid, "process": "replica-1", "flags": [],
            "spans": [
                {"span_id": server_root, "parent_id": attempt,
                 "name": "server:/predict", "t0": 100.002, "dur_ms": 46.0,
                 "root": True, "process": "replica-1"},
                {"span_id": T._new_span_id(), "parent_id": server_root,
                 "name": "queue_wait", "t0": 100.003, "dur_ms": 10.0,
                 "process": "replica-1"},
                {"span_id": T._new_span_id(), "parent_id": server_root,
                 "name": "forward", "t0": 100.013, "dur_ms": 30.0,
                 "annotations": {"program": "m/full/b4/fp32"},
                 "process": "replica-1"},
            ],
        }
        return tid, router_seg, replica_seg

    def test_tree_assembly_total_and_format(self):
        from tools.trace_report import stitch

        tid, router_seg, replica_seg = self._segments()
        st = stitch([router_seg, None, replica_seg])
        assert st.trace_id == tid
        assert st.total_ms == 50.0
        assert st.flags == ["retried"]
        assert st.processes() == ["replica-1", "router"]
        assert len(st.roots) == 1  # server root parents INTO the attempt
        text = st.format()
        assert "router:/predict" in text and "queue_wait" in text
        assert "program=m/full/b4/fp32" in text
        # The cross-process edge: server span nested under the attempt.
        assert st.children[router_seg["spans"][1]["span_id"]][0][
            "name"] == "server:/predict"

    def test_orphans_surface_as_roots(self):
        from tools.trace_report import stitch

        _, router_seg, replica_seg = self._segments()
        st = stitch([replica_seg])  # router segment lost (restart)
        assert len(st.roots) == 1
        assert st.roots[0]["name"] == "server:/predict"
        assert st.total_ms == 46.0

    def test_duplicate_span_ids_dedup(self):
        from tools.trace_report import stitch

        _, router_seg, replica_seg = self._segments()
        st = stitch([router_seg, router_seg, replica_seg])
        assert len(st.spans) == 5


# ----------------------------------------------------- fleet aggregation
class TestFleetAggregator:
    def _bus(self, n):
        b = MetricsBus()
        b.counter("reqs", path="predict").inc(n)
        b.gauge("depth").set(n)
        h = b.histogram("lat_ms")
        for v in range(n):
            h.observe(10.0 * (v + 1))
        return b

    def test_counters_summed_histograms_bucketwise_breakdown_kept(self):
        agg = FleetAggregator(interval_s=60)
        agg.add_source("replica-0", self._bus(3).snapshot)
        agg.add_source("replica-1", self._bus(5).snapshot)
        view = agg.merged()
        a = view["aggregate"]
        assert a["counters"]["reqs{path=predict}"] == 8.0
        assert a["gauges"]["depth"] == 8.0
        h = a["histograms"]["lat_ms"]
        assert h["count"] == 8 and h["max"] == 50.0
        # Bucket-wise: fleet p99 derives from the MERGED distribution.
        assert h["p90"] > h["p50"] > 0
        assert sum(h["bucket_counts"]) == 8
        # Per-replica breakdown retained verbatim.
        assert view["replicas"]["replica-0"]["counters"][
            "reqs{path=predict}"] == 3.0
        assert view["up"] == 2

    def test_down_source_excluded_and_reported(self):
        agg = FleetAggregator(interval_s=60, timeout_s=0.2)
        agg.add_source("replica-0", self._bus(3).snapshot)
        agg.add_source("dead", "127.0.0.1:1")
        view = agg.merged()
        assert view["up"] == 1
        assert not view["sources"]["dead"]["up"]
        assert view["sources"]["dead"]["error"]
        assert view["aggregate"]["counters"]["reqs{path=predict}"] == 3.0

    def test_bucket_ladder_mismatch_skipped_not_averaged(self):
        b1, b2 = MetricsBus(), MetricsBus()
        b1.histogram("lat_ms", bounds=(1.0, 10.0)).observe(5.0)
        b2.histogram("lat_ms", bounds=(2.0, 20.0)).observe(5.0)
        agg = FleetAggregator(interval_s=60)
        agg.add_source("a", b1.snapshot)
        agg.add_source("b", b2.snapshot)
        view = agg.merged()
        assert view["skipped_histograms"]  # reported, never averaged
        assert view["aggregate"]["histograms"]["lat_ms"]["count"] == 1

    def test_prometheus_rendering_with_replica_labels(self):
        agg = FleetAggregator(interval_s=60)
        agg.add_source("replica-0", self._bus(3).snapshot)
        agg.add_source("router", self._bus(1).snapshot)
        text = agg.render_prometheus()
        assert ('seist_reqs_total{path="predict",replica="fleet"} 4'
                in text)
        assert ('seist_reqs_total{path="predict",replica="replica-0"} 3'
                in text)
        assert 'seist_fleet_source_up{source="router"} 1' in text
        assert 'le="+Inf"' in text
        # One TYPE line per family, first wins.
        assert text.count("# TYPE seist_reqs_total counter") == 1

    def test_background_scrape_and_stop(self):
        agg = FleetAggregator(interval_s=0.05)
        agg.add_source("a", self._bus(1).snapshot)
        agg.start()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if agg.merged(refresh=False)["scrapes"] >= 2:
                break
            time.sleep(0.02)
        agg.stop()
        assert agg.merged(refresh=False)["scrapes"] >= 2

    def test_split_key(self):
        assert _split_key("plain") == ("plain", {})
        assert _split_key("a{m=x,task=dpk}") == (
            "a", {"m": "x", "task": "dpk"}
        )


# ------------------------------------------------- per-replica artifacts
class TestReplicaDisambiguation:
    def test_suffix_follows_env(self, monkeypatch):
        monkeypatch.delenv("SEIST_SERVE_REPLICA", raising=False)
        assert T.replica_suffix() == ""
        monkeypatch.setenv("SEIST_SERVE_REPLICA", "1")
        assert T.replica_suffix() == "_r1"
        assert T.replica_ordinal() == 1
        assert T.process_label() == "replica-1"
        monkeypatch.setenv("SEIST_SERVE_REPLICA", "junk")
        assert T.replica_suffix() == ""

    def test_two_replicas_one_logdir_distinct_artifacts(
        self, tmp_path, monkeypatch
    ):
        """Regression (ISSUE 11 satellite): two fleet replicas sharing a
        --logdir must produce DISTINCT events files and flight dumps —
        before the ordinal suffix they interleaved one events.jsonl and
        could clobber same-pid-seq flight files."""
        import os

        from seist_tpu.obs import flight
        from seist_tpu.obs.bus import EventLog
        from seist_tpu.utils.logger import logger

        monkeypatch.setattr(logger, "_logdir", str(tmp_path),
                            raising=False)
        paths = {}
        for ordinal in ("0", "1"):
            monkeypatch.setenv("SEIST_SERVE_REPLICA", ordinal)
            # The naming recipe serve/server.py main() uses.
            ev = EventLog(os.path.join(
                str(tmp_path), f"events{T.replica_suffix()}.jsonl"
            ))
            ev.emit("serve_state", state="ok", replica=ordinal)
            ev.close()
            rec = flight.FlightRecorder(capacity=4)
            dump = rec.dump("preempt")
            paths[ordinal] = dump
            assert f"_r{ordinal}_" in os.path.basename(dump)
        assert paths["0"] != paths["1"]
        assert (tmp_path / "events_r0.jsonl").exists()
        assert (tmp_path / "events_r1.jsonl").exists()
        for ordinal in ("0", "1"):
            lines = (
                tmp_path / f"events_r{ordinal}.jsonl"
            ).read_text().splitlines()
            assert len(lines) == 1
            assert json.loads(lines[0])["replica"] == ordinal


# ------------------------------------------------------- overhead bound
class TestOverhead:
    def test_full_request_trace_far_under_serve_smoke_budget(self, request):
        if request.config.getoption("--lock-graph", default=False):
            pytest.skip(
                "overhead gate measures production cost; LockGraph "
                "instrumentation adds ~2.4 us per acquire/release pair"
            )
        """A complete traced request (mint -> root + 5 children ->
        finish/commit) must cost well under 1% of serve-smoke's p50
        (~tens of ms on the CPU lane; 1% >= 300 us). Pin a 150 us/request
        ceiling — typical is single-digit us — min-of-3 passes so a noisy
        scheduler can't flake the gate."""
        buf = T.TraceBuffer(capacity=256, sample=1.0)
        n = 400

        def one_pass():
            t0 = time.perf_counter()
            for _ in range(n):
                header = T.mint_traceparent()
                rt = T.RequestTrace(header, name="server:/predict",
                                    buffer=buf)
                with rt.span("admission", tier="interactive"):
                    pass
                with rt.span("parse"):
                    pass
                rt.add_child("queue_wait", 1.0, flush=1, bucket=4)
                rt.add_child("forward", 2.0, program="m/full/b4/fp32",
                             aot=True)
                with rt.span("decode"):
                    pass
                rt.finish(200)
            return (time.perf_counter() - t0) / n * 1e6  # us/request

        per_request_us = min(one_pass() for _ in range(3))
        assert per_request_us < 150.0, (
            f"tracing costs {per_request_us:.1f} us/request — "
            "over the serve-smoke <1% p50 budget"
        )


# --------------------------------------------------------- HTTP payloads
class TestHttpPayloads:
    def test_index_and_get_payload_shapes(self):
        buf = T.TraceBuffer(capacity=8)
        rt = T.RequestTrace(None, buffer=buf)
        rt.flag("hedged")
        rt.finish(200)
        idx = T.index_payload(buf)
        assert idx["capacity"] == 8
        assert idx["traces"][0]["trace_id"] == rt.trace_id
        assert idx["traces"][0]["flags"] == ["hedged"]
        assert T.trace_payload(rt.trace_id, buf)["spans"]
        assert T.trace_payload("not-a-trace", buf) is None

    def test_obs_http_serves_traces(self):
        import http.client

        from seist_tpu.obs.http import start_metrics_server

        rt = T.RequestTrace(None)  # process BUFFER — what the shim reads
        rt.finish(200)
        server = start_metrics_server(0)
        try:
            host, port = server.server_address[:2]
            conn = http.client.HTTPConnection(host, port, timeout=10)
            try:
                conn.request("GET", "/traces")
                idx = json.loads(conn.getresponse().read())
                assert any(
                    t["trace_id"] == rt.trace_id for t in idx["traces"]
                )
                conn.request("GET", f"/traces/{rt.trace_id}")
                payload = json.loads(conn.getresponse().read())
                assert payload["spans"][0]["span_id"] == rt.root_span_id
                conn.request("GET", "/traces/deadbeef")
                resp = conn.getresponse()
                assert resp.status == 404
                resp.read()
            finally:
                conn.close()
        finally:
            server.shutdown()
            server.server_close()

    def test_handle_traces_path_shared_routing(self):
        """The ONE route helper all three HTTP shims use — query strings
        are stripped uniformly (a /traces/<id>?pretty=1 must resolve the
        same everywhere), non-trace paths return None."""
        buf = T.TraceBuffer(capacity=4)
        rt = T.RequestTrace(None, buffer=buf)
        rt.finish(200)
        status, payload = T.handle_traces_path("/traces?limit=5", buf)
        assert status == 200 and payload["traces"]
        status, payload = T.handle_traces_path(
            f"/traces/{rt.trace_id}?pretty=1", buf
        )
        assert status == 200 and payload["trace_id"] == rt.trace_id
        status, payload = T.handle_traces_path("/traces/deadbeef", buf)
        assert status == 404 and payload["error"] == "unknown_trace"
        assert T.handle_traces_path("/metrics", buf) is None

    def test_fleet_prometheus_histogram_metadata_clean(self):
        """Histogram component series (_bucket/_sum/_count) must not get
        their own # TYPE lines (OpenMetrics validity — review finding)."""
        bus = MetricsBus()
        bus.histogram("lat_ms").observe(5.0)
        agg = FleetAggregator(interval_s=60)
        agg.add_source("r0", bus.snapshot)
        text = agg.render_prometheus()
        assert "# TYPE seist_lat_ms histogram" in text
        for bad in ("# TYPE seist_lat_ms_bucket",
                    "# TYPE seist_lat_ms_sum",
                    "# TYPE seist_lat_ms_count"):
            assert bad not in text, text

    def test_collector_registration(self):
        bus = MetricsBus()
        T.register_trace_collector(bus)
        rt = T.RequestTrace(None)  # process BUFFER feeds the collector
        rt.finish(200)
        snap = bus.snapshot()
        assert "trace_kept" in snap["collectors"]
