"""Continuous-record annotation (ops/stream.py): windowing geometry,
overlap-average stitching, and end-to-end picking on a synthetic record.

No reference counterpart — the reference scores single fixed windows only
(ref demo_predict.py:59-97); contracts are pinned against hand math.
"""

import numpy as np
import pytest

from seist_tpu.ops.stream import annotate, sliding_windows, stitch_probs


class TestWindows:
    def test_covers_whole_record_right_aligned(self):
        rec = np.arange(25, dtype=np.float32).reshape(25, 1)
        w, offs = sliding_windows(rec, window=10, stride=8)
        assert list(offs) == [0, 8, 15]  # last clamped to L - window
        np.testing.assert_array_equal(w[2, :, 0], np.arange(15, 25))

    def test_exact_fit_single_window(self):
        rec = np.zeros((10, 3), np.float32)
        w, offs = sliding_windows(rec, 10, 4)
        assert w.shape == (1, 10, 3) and list(offs) == [0]

    def test_too_short_raises(self):
        with pytest.raises(ValueError):
            sliding_windows(np.zeros((5, 3), np.float32), 10, 4)


class TestStitch:
    def test_overlap_mean(self):
        # Two windows of length 4, stride 2, over length 6: positions 2-3
        # are covered by both -> mean of the two values.
        probs = np.zeros((2, 4, 1), np.float32)
        probs[0] += 1.0
        probs[1] += 3.0
        out = np.asarray(stitch_probs(probs, np.array([0, 2]), 6))[:, 0]
        np.testing.assert_allclose(out, [1, 1, 2, 2, 3, 3])

    def test_full_cover_identity(self):
        rng = np.random.default_rng(0)
        probs = rng.uniform(size=(1, 8, 3)).astype(np.float32)
        out = np.asarray(stitch_probs(probs, np.array([0]), 8))
        np.testing.assert_allclose(out, probs[0], rtol=1e-6)


class TestAnnotate:
    def test_picks_synthetic_events(self):
        """A fake 'model' that thresholds the raw amplitude must recover
        the planted event positions through windowing + stitching."""
        fs = 50
        L = 4000
        rec = np.zeros((L, 3), np.float32)
        events = [800, 2500]
        for e in events:
            rec[e : e + 5] = 50.0  # spike the planted P onsets

        def fake_apply(x):
            import jax.numpy as jnp

            # P prob = normalized |z|; S channel silent; non = 1 - P.
            a = jnp.abs(x[..., 0])
            p = a / (a.max(axis=1, keepdims=True) + 1e-9)
            s = jnp.zeros_like(p)
            return jnp.stack([1.0 - p, p, s], axis=-1)

        picks = annotate(
            fake_apply, rec, window=1024, stride=512, batch_size=4,
            sampling_rate=fs, ppk_threshold=0.5, min_peak_dist=2.0,
            channel0="non",
        )
        assert picks["spk"].size == 0
        assert len(picks["ppk"]) == len(events)
        for e, got in zip(events, sorted(picks["ppk"])):
            assert abs(int(got) - e) <= 5
        assert picks["prob"].shape == (L, 3)

    def test_batch_padding_consistency(self):
        """Results must not depend on batch_size (last-batch padding)."""
        rng = np.random.default_rng(1)
        rec = rng.standard_normal((3000, 3)).astype(np.float32)
        rec[1000:1005] *= 30

        def fake_apply(x):
            import jax.numpy as jnp

            a = jnp.abs(x[..., 0])
            p = a / (a.max(axis=1, keepdims=True) + 1e-9)
            return jnp.stack([1.0 - p, p, jnp.zeros_like(p)], axis=-1)

        a = annotate(fake_apply, rec, window=1024, stride=512, batch_size=2, channel0="non")
        b = annotate(fake_apply, rec, window=1024, stride=512, batch_size=7, channel0="non")
        np.testing.assert_allclose(a["prob"], b["prob"], atol=1e-6)
        np.testing.assert_array_equal(a["ppk"], b["ppk"])


class TestCombineMax:
    def test_max_keeps_peak_missed_by_neighbor(self):
        # Window 0 sees a strong peak at pos 3; window 1 (covering the same
        # position) misses it entirely. mean halves it; max keeps it.
        probs = np.zeros((2, 4, 1), np.float32)
        probs[0, 3, 0] = 0.9
        offs = np.array([0, 2])
        mean = np.asarray(stitch_probs(probs, offs, 6, combine="mean"))
        mx = np.asarray(stitch_probs(probs, offs, 6, combine="max"))
        assert mean[3, 0] == pytest.approx(0.45)
        assert mx[3, 0] == pytest.approx(0.9)

    def test_unknown_combine_raises(self):
        with pytest.raises(ValueError):
            stitch_probs(np.zeros((1, 4, 1), np.float32), np.array([0]), 4,
                         combine="median")


class TestMaxNonChannelSemantics:
    def test_event_missing_window_cannot_veto_detection(self):
        """combine='max': a window that misses an event must not suppress
        the neighbor's detection via the non channel."""
        # Window A sees an event at overlap positions (non=0.1); window B
        # misses it (non=0.95). The stitched det strength must stay high.
        probs = np.full((2, 4, 3), 0.0, np.float32)
        probs[..., 0] = 0.95  # mostly noise everywhere
        probs[0, 2:, 0] = 0.1  # window A: event in its last 2 samples
        probs[0, 2:, 1] = 0.9

        def fake_apply(x):  # not used; we test via annotate's stitch branch
            raise AssertionError

        import jax.numpy as jnp
        from seist_tpu.ops.stream import stitch_probs

        ev = jnp.asarray(probs).at[..., 0].set(1.0 - probs[..., 0])
        st = stitch_probs(ev, np.array([0, 2]), 6, combine="max")
        # annotate computes det strength as 1 - curve_non == st[..., 0].
        det_strength = np.asarray(st)[:, 0]
        # Overlap positions 2-3: event evidence survives the max combine
        # (a plain max over the raw non channel would give 0.95 -> 0.05).
        assert det_strength[2] == pytest.approx(0.9)
        assert det_strength[3] == pytest.approx(0.9)

    def test_single_sample_detection_kept(self):
        from seist_tpu.ops.stream import annotate

        rec = np.zeros((64, 3), np.float32)

        def fake_apply(x):
            import jax.numpy as jnp

            # exactly one sample of event evidence at position 10
            p = jnp.zeros(x.shape[:2])
            p = p.at[:, 10].set(0.9)
            return jnp.stack([1.0 - p, p, jnp.zeros_like(p)], axis=-1)

        picks = annotate(
            fake_apply, rec, window=64, stride=64, batch_size=1,
            det_threshold=0.5, channel0="non",
        )
        assert picks["det"].shape[0] == 1
        on, off = picks["det"][0]
        assert on == off == 10


class TestShortRecordPadAndTrim:
    """annotate's edge contract: a record shorter than one window is zero
    right-padded to exactly one window, scored, and trimmed back — picks
    in the pad dropped, detections clipped, prob at the true length."""

    @staticmethod
    def _spike_apply(x):
        import jax.numpy as jnp

        a = jnp.abs(x[..., 0])
        p = a / (a.max(axis=1, keepdims=True) + 1e-9)
        return jnp.stack([1.0 - p, p, jnp.zeros_like(p)], axis=-1)

    def test_short_record_scores_and_trims(self):
        rec = np.zeros((40, 3), np.float32)
        rec[10:13, 0] = 30.0
        out = annotate(
            self._spike_apply, rec, window=64, stride=32, batch_size=1,
            ppk_threshold=0.5, min_peak_dist=0.1, channel0="non",
        )
        assert out["prob"].shape == (40, 3)  # trimmed to the true length
        assert len(out["ppk"]) >= 1
        assert all(0 <= p < 40 for p in out["ppk"])
        assert all(on < 40 and off < 40 for on, off in out["det"])

    def test_pad_region_pick_dropped(self):
        """A peak the padded tail manufactures must not escape the trim."""
        rec = np.zeros((20, 3), np.float32)
        rec[-3:, 0] = 25.0  # ramp ends AT the pad boundary
        out = annotate(
            self._spike_apply, rec, window=64, stride=32, batch_size=1,
            ppk_threshold=0.3, min_peak_dist=0.1, channel0="non",
        )
        assert all(p < 20 for p in out["ppk"])
        assert all(off <= 19 for _, off in out["det"])

    def test_empty_record_raises(self):
        with pytest.raises(ValueError):
            annotate(
                self._spike_apply, np.zeros((0, 3), np.float32),
                window=64, channel0="non",
            )

    def test_exact_window_unaffected(self):
        """L == window takes the normal path (no pad, no trim)."""
        rng = np.random.default_rng(4)
        rec = rng.standard_normal((64, 3)).astype(np.float32)
        out = annotate(
            self._spike_apply, rec, window=64, stride=32, batch_size=1,
            channel0="non",
        )
        assert out["prob"].shape == (64, 3)

    def test_nonmultiple_tail_right_aligned(self):
        """Non-stride-multiple tails: the final window is right-aligned
        (window_offsets clamps to L - window) — pinned explicitly as the
        tail half of the edge contract."""
        from seist_tpu.ops.stream import window_offsets

        offs = list(window_offsets(150, 64, 32))
        assert offs == [0, 32, 64, 86]  # 86 == 150 - 64, not 96
        out = annotate(
            self._spike_apply,
            np.zeros((150, 3), np.float32), window=64, stride=32,
            batch_size=2, channel0="non",
        )
        assert out["prob"].shape == (150, 3)


class TestDetChannelSemantics:
    def test_det_channel0(self):
        """seist-dpk/eqtransformer convention: channel 0 IS event prob
        (taskspec labels ("det","ppk","spk")) — detection intervals must
        come from curve0 directly, not its complement."""
        from seist_tpu.ops.stream import annotate

        rec = np.zeros((64, 3), np.float32)

        def det_model(x):
            import jax.numpy as jnp

            d = jnp.zeros(x.shape[:2])
            d = d.at[:, 20:30].set(0.9)  # event in progress
            return jnp.stack([d, jnp.zeros_like(d), jnp.zeros_like(d)], axis=-1)

        picks = annotate(
            det_model, rec, window=64, stride=64, batch_size=1,
            det_threshold=0.5, channel0="det",
        )
        assert picks["det"].shape[0] == 1
        on, off = picks["det"][0]
        assert (on, off) == (20, 29)
        # The same model read with channel0='non' would invert: everything
        # EXCEPT 20-30 looks like an event.
        wrong = annotate(
            det_model, rec, window=64, stride=64, batch_size=1,
            det_threshold=0.5, channel0="non",
        )
        assert wrong["det"].shape[0] >= 1
        assert tuple(wrong["det"][0]) != (20, 29)


@pytest.mark.slow  # real-model compile (~1-2 min on 1 core)
def test_annotate_with_real_eqtransformer():
    """The continuous-record path serves the EQTransformer family too:
    its output contract is the same (N, L, 3) (det, ppk, spk)
    probability stack as the seist dpk family (ref eqtransformer.py's 3
    decoders), so `BENCH_MODE=stream BENCH_MODEL=eqtransformer` and
    tools/predict.py work unchanged."""
    import jax

    import seist_tpu
    from seist_tpu import taskspec
    from seist_tpu.models import api

    seist_tpu.load_all()
    window, fs = 512, 100
    spec = taskspec.get_task_spec("eqtransformer")
    assert spec.labels[0][0] == "det"
    model = api.create_model("eqtransformer", in_samples=window)
    variables = api.init_variables(model, in_samples=window, batch_size=4)

    def apply_fn(x):
        return model.apply(variables, x, train=False)

    rng = np.random.default_rng(0)
    record = rng.standard_normal((30 * fs, 3)).astype(np.float32)
    out = annotate(
        apply_fn,
        record,
        window=window,
        stride=window // 2,
        batch_size=4,
        sampling_rate=fs,
        channel0="det",
    )
    # Untrained net: no pick-quality claim, just the full contract —
    # finite prob curves over the whole record and well-formed pick
    # arrays (sample indices inside the record).
    assert out["prob"].shape[0] == record.shape[0]
    assert np.isfinite(out["prob"]).all()
    for key in ("ppk", "spk"):
        picks = np.asarray(out[key])
        assert picks.ndim == 1
        if picks.size:
            assert ((picks >= 0) & (picks < record.shape[0])).all()
