"""Model-free stand-in replica for fleet-supervisor tests.

Speaks just enough of the serve protocol for tools/supervise_fleet.py and
seist_tpu/serve/router.py: ``GET /healthz/ready`` (200 once "warm"),
``POST /predict`` (200 echo). Honors the replica exit-code contract:
SIGTERM -> drain -> exit 75. Crash behavior is scripted by env:

    FAKE_CRASH_AFTER_S   exit 3 after this many seconds — but only when
                         FAKE_CRASH_STAMP does not exist yet (the stamp is
                         written first, so the relaunch runs clean: one
                         crash per fleet, like a real one-off fault)
    FAKE_CRASH_STAMP     stamp-file path gating the crash
    SEIST_SERVE_REPLICA  only the matching FAKE_CRASH_REPLICA crashes
"""

import argparse
import json
import os
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

PREEMPT_EXIT_CODE = 75

COUNTS = {"predict": 0}
COUNTS_LOCK = threading.Lock()
#: set from --model-version in main(); reported like a real replica so
#: rolling-restart tests can watch the fleet converge.
MODEL_VERSION = 1


class Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _reply(self, status, payload):
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path in ("/healthz", "/healthz/live", "/healthz/ready"):
            # versions rides the ready payload exactly like the real
            # replica (serve/server.py): the router's prober and the
            # fleet supervisor's rollout read it from here.
            self._reply(200, {
                "status": "ok", "ready": True,
                "versions": {"fake": MODEL_VERSION},
            })
        elif self.path == "/metrics.json":
            # The bus-snapshot shape the fleet aggregator scrapes
            # (obs/fleet.py): counters sum, histograms merge bucket-wise.
            with COUNTS_LOCK:
                n = COUNTS["predict"]
            self._reply(200, {
                "counters": {
                    "fake_requests{path=predict}": n,
                },
                "gauges": {
                    "fake_replica_ordinal": float(
                        os.environ.get("SEIST_SERVE_REPLICA", "0") or 0
                    ),
                },
                "histograms": {
                    "fake_latency_ms": {
                        "count": float(n), "mean": 1.0, "max": 2.0,
                        "sum": float(n),
                        "bounds": [1.0, 10.0],
                        "bucket_counts": [n, 0, 0],
                    },
                },
                "collectors": {},
            })
        else:
            self._reply(404, {"error": "not_found"})

    def do_POST(self):
        n = int(self.headers.get("Content-Length") or 0)
        self.rfile.read(n)
        if self.path == "/predict":
            with COUNTS_LOCK:
                COUNTS["predict"] += 1
            self._reply(
                200,
                {"ok": True,
                 "model_version": MODEL_VERSION,
                 "replica": os.environ.get("SEIST_SERVE_REPLICA", "?")},
            )
        else:
            self._reply(404, {"error": "not_found"})


def main() -> int:
    global MODEL_VERSION
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument(
        "--model-version", type=int,
        default=int(os.environ.get("SEIST_MODEL_VERSION", "") or 1),
    )
    args, _ = ap.parse_known_args()
    MODEL_VERSION = args.model_version

    server = ThreadingHTTPServer((args.host, args.port), Handler)
    server.daemon_threads = True
    threading.Thread(target=server.serve_forever, daemon=True).start()

    stop = threading.Event()
    rc = {"code": 0}

    def _term(signum, frame):
        if signum == signal.SIGTERM:
            rc["code"] = PREEMPT_EXIT_CODE
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)

    crash_after = float(os.environ.get("FAKE_CRASH_AFTER_S", "0") or 0)
    stamp = os.environ.get("FAKE_CRASH_STAMP", "")
    target = os.environ.get("FAKE_CRASH_REPLICA", "")
    me = os.environ.get("SEIST_SERVE_REPLICA", "")
    crash_armed = (
        crash_after > 0
        and (not target or target == me)
        and (not stamp or not os.path.exists(stamp))
    )
    deadline = time.monotonic() + crash_after if crash_armed else None
    while not stop.is_set():
        if deadline is not None and time.monotonic() >= deadline:
            if stamp:
                with open(stamp, "w") as f:
                    f.write("crashed\n")
            os._exit(3)  # hard crash, no drain
        stop.wait(0.05)
    server.shutdown()
    return rc["code"]


if __name__ == "__main__":
    sys.exit(main())
