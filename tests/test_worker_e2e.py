"""End-to-end orchestration tests: train_worker -> checkpoint -> test_worker
on the synthetic dataset (the workflow of ref main.py --mode train_test),
plus eval-masking semantics."""

import os
from types import SimpleNamespace

import jax
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # full train->ckpt->test runs

import seist_tpu
from seist_tpu import taskspec
from seist_tpu.utils.logger import logger

seist_tpu.load_all()


def make_args(**over):
    d = dict(
        mode="train_test",
        model_name="phasenet",
        checkpoint="",
        seed=1,
        log_base="",
        log_step=100,
        use_tensorboard=False,
        save_test_results=True,
        data="",
        dataset_name="synthetic",
        data_split=True,
        train_size=0.8,
        val_size=0.1,
        shuffle=True,
        workers=2,
        in_samples=1024,
        label_width=0.5,
        label_shape="gaussian",
        coda_ratio=2.0,
        norm_mode="std",
        min_snr=-float("inf"),
        p_position_ratio=-1,
        augmentation=False,
        add_event_rate=0.0,
        max_event_num=1,
        shift_event_rate=0.0,
        add_noise_rate=0.0,
        add_gap_rate=0.0,
        min_event_gap=0.5,
        drop_channel_rate=0.0,
        scale_amplitude_rate=0.0,
        pre_emphasis_rate=0.0,
        pre_emphasis_ratio=0.97,
        generate_noise_rate=0.0,
        mask_percent=0,
        noise_percent=0,
        epochs=1,
        patience=30,
        steps=0,
        start_epoch=0,
        batch_size=8,
        optim="Adam",
        momentum=0.9,
        weight_decay=0.0,
        use_lr_scheduler=True,
        lr_scheduler_mode="exp_range",
        base_lr=8e-5,
        max_lr=1e-3,
        warmup_steps=2000,
        down_steps=3000,
        time_threshold=0.1,
        min_peak_dist=1.0,
        ppk_threshold=0.3,
        spk_threshold=0.3,
        det_threshold=0.5,
        max_detect_event_num=1,
        dataset_kwargs={"num_events": 40, "trace_samples": 4096},
    )
    d.update(over)
    return SimpleNamespace(**d)


@pytest.fixture(scope="module")
def e2e_run(tmp_path_factory):
    from seist_tpu.train.worker import test_worker, train_worker

    logdir = str(tmp_path_factory.mktemp("e2e_logs"))
    logger.set_logdir(logdir)
    args = make_args()
    ckpt = train_worker(args)
    assert ckpt and os.path.exists(ckpt)
    args.checkpoint = ckpt
    loss = test_worker(args)
    return logdir, ckpt, loss


def test_train_then_test(e2e_run):
    logdir, ckpt, loss = e2e_run
    assert np.isfinite(loss)


@pytest.mark.slow  # ~2 min incl. compile: 30-epoch learning regression
def test_training_learns_p_picks(tmp_path_factory):
    """Training must actually LEARN, not merely keep the loss finite: 30
    constant-LR epochs of phasenet on the synthetic dataset reach P-pick
    F1 0.75 on the held-out test split (~2 min incl. compile on this
    host). Guards against silent optimizer / label / postprocess /
    metric-wiring regressions the loss-only e2e can't see."""
    import json

    from seist_tpu.train.worker import test_worker, train_worker

    logdir = str(tmp_path_factory.mktemp("learn_logs"))
    logger.set_logdir(logdir)
    # Dataset left at its defaults (256 events, 12000-sample traces): this
    # matches the CLI calibration run; smaller fixtures train noisily.
    args = make_args(
        in_samples=512,
        batch_size=32,
        epochs=30,
        use_lr_scheduler=False,
        max_lr=1e-3,
        patience=1000,
        dataset_kwargs={},
    )
    ckpt = train_worker(args)
    assert ckpt and os.path.exists(ckpt)
    args.checkpoint = ckpt
    test_worker(args)
    metrics_json = os.path.join(logdir, "test_metrics_synthetic.json")
    assert os.path.exists(metrics_json), os.listdir(logdir)
    with open(metrics_json) as f:
        payload = json.load(f)
    # Measured 0.75 at this exact seeded config (27 test events; chance is
    # ~0); 0.6 leaves margin for legitimate augmentation/label changes
    # while still failing hard on a model that didn't learn.
    f1 = payload["metrics"]["ppk"]["f1"]
    assert f1 >= 0.6, payload["metrics"]


def test_results_csv_written(e2e_run):
    logdir, _, _ = e2e_run
    csvs = [f for f in os.listdir(logdir) if f.startswith("test_results_")]
    assert csvs, os.listdir(logdir)
    import pandas as pd

    df = pd.read_csv(os.path.join(logdir, csvs[0]))
    # 40 events * 10% test split = 4 rows; pred/tgt columns present per task.
    assert len(df) == 4
    for col in ("pred_ppk", "tgt_ppk", "pred_spk", "tgt_spk"):
        assert col in df.columns


def test_loss_curves_saved(e2e_run):
    logdir, _, _ = e2e_run
    assert os.path.exists(os.path.join(logdir, "train_losses.npy"))
    assert os.path.exists(os.path.join(logdir, "val_losses.npy"))


def test_eval_mask_excludes_padding(rng):
    """Padded rows must not change the eval loss (code-review finding)."""
    from seist_tpu.models import api
    from seist_tpu.train import (
        build_optimizer,
        create_train_state,
        make_eval_step,
    )

    spec = taskspec.get_task_spec("phasenet")
    loss_fn = spec.loss()
    model = api.create_model("phasenet", in_channels=3, in_samples=1024)
    variables = api.init_variables(model, in_samples=1024, in_channels=3)
    state = create_train_state(model, variables, build_optimizer("adam", 1e-3))
    estep = jax.jit(make_eval_step(spec, loss_fn))

    x = rng.normal(size=(4, 1024, 3)).astype(np.float32)
    y = np.abs(rng.normal(size=(4, 1024, 3))).astype(np.float32)
    y /= y.sum(-1, keepdims=True)

    half_mask = np.array([1, 1, 0, 0], dtype=np.float32)

    # Replace masked rows with garbage — the loss must not move at all.
    x2 = x.copy()
    x2[2:] = 999.0
    loss_masked, _ = estep(state, x2, y, half_mask)
    loss_ref, _ = estep(state, x, y, half_mask)
    assert float(loss_masked) == pytest.approx(float(loss_ref), rel=1e-5)


def test_train_with_grad_accum(tmp_path):
    """--grad-accum-steps e2e: the worker routes k loader batches into one
    scanned update (step.py make_accum_train_step) and still produces a
    loadable checkpoint + test metrics."""
    from seist_tpu.train.worker import test_worker, train_worker

    logger.set_logdir(str(tmp_path))
    args = make_args(grad_accum_steps=2, epochs=1)
    ckpt = train_worker(args)
    assert ckpt and os.path.exists(ckpt)
    args.checkpoint = ckpt
    loss = test_worker(args)
    assert np.isfinite(loss)


@pytest.mark.parametrize("mode", ["cached", "step"])
def test_train_with_device_aug(tmp_path, mode):
    """--device-aug e2e: augmentation + label synthesis inside the jitted
    step (step mode: host-fed raw rows; cached mode: HBM-resident epochs
    + scan executor), through the full worker path to a loadable
    checkpoint and finite test loss."""
    from seist_tpu.train.worker import test_worker, train_worker

    logger.set_logdir(str(tmp_path))
    args = make_args(
        mode="train_test",
        epochs=1,
        device_aug=mode,
        augmentation=True,
        shift_event_rate=0.3,
        add_noise_rate=0.3,
        add_gap_rate=0.3,
        drop_channel_rate=0.3,
        scale_amplitude_rate=0.3,
        pre_emphasis_rate=0.3,
        generate_noise_rate=0.05,
        add_event_rate=0.3,
        max_event_num=2,
        dataset_kwargs={"num_events": 24, "trace_samples": 1536},
    )
    ckpt = train_worker(args)
    assert ckpt and os.path.exists(ckpt)
    args.checkpoint = ckpt
    loss = test_worker(args)
    assert np.isfinite(loss)


def test_device_aug_unsupported_config_falls_back(tmp_path):
    """mask_percent is host-only: the worker must fall back to the host
    path (and still train) instead of crashing or silently changing
    semantics."""
    from seist_tpu.train.worker import train_worker

    logger.set_logdir(str(tmp_path))
    args = make_args(
        mode="train",
        epochs=1,
        device_aug="cached",
        augmentation=True,
        mask_percent=10,
        dataset_kwargs={"num_events": 16, "trace_samples": 1536},
    )
    ckpt = train_worker(args)
    assert ckpt and os.path.exists(ckpt)


def test_train_then_test_on_packed_dataset(tmp_path_factory):
    """The packed-shard dataset through the FULL worker path (train ->
    checkpoint -> test -> metrics), the integration a reference user
    hits with `--dataset-name packed` (docs/MIGRATING.md)."""
    from tests.conftest import make_packed_dir

    from seist_tpu.train.worker import test_worker, train_worker

    _, packed_dir = make_packed_dir(
        tmp_path_factory, n_events=40, trace_samples=4096, n_parts=1
    )

    logdir = str(tmp_path_factory.mktemp("e2e_packed_logs"))
    logger.set_logdir(logdir)
    args = make_args(
        dataset_name="packed", data=packed_dir, dataset_kwargs={}
    )
    ckpt = train_worker(args)
    assert ckpt and os.path.exists(ckpt)
    args.checkpoint = ckpt
    loss = test_worker(args)
    assert np.isfinite(loss)
    assert os.path.exists(
        os.path.join(logdir, "test_metrics_packed.json")
    )


def test_train_packed_direct_ingest(tmp_path_factory):
    """--device-aug step + --ingest direct on a packed dataset: the raw
    rows stream straight off the shard memmaps (data/ingest.py), the
    strict flag proves the fast path actually engaged (it errors on any
    silent fallback), and training completes to a checkpoint."""
    from tests.conftest import make_packed_dir

    from seist_tpu.train.worker import train_worker

    _, packed_dir = make_packed_dir(
        tmp_path_factory, n_events=40, trace_samples=1536, n_parts=1
    )
    logdir = str(tmp_path_factory.mktemp("e2e_direct_logs"))
    logger.set_logdir(logdir)
    args = make_args(
        dataset_name="packed",
        data=packed_dir,
        dataset_kwargs={},
        device_aug="step",
        ingest="direct",
        augmentation=True,
        in_samples=1024,
    )
    ckpt = train_worker(args)
    assert ckpt and os.path.exists(ckpt)
    with open(os.path.join(logdir, "global.log")) as f:
        log = f.read()
    assert "packed direct ingest" in log
    assert "device-aug step" in log


def test_train_mixture_pack_with_temperature(tmp_path_factory):
    """Temperature-weighted mixture training end to end: two packed
    sources, --mixture-temperature on the host path; loss stays finite
    and the run checkpoints."""
    from seist_tpu.data.packed import PackSource, pack_sources
    from seist_tpu.train.worker import train_worker

    out = str(tmp_path_factory.mktemp("e2e_mix_pack"))
    pack_sources(
        [
            PackSource(
                name="synthetic",
                dataset_kwargs={
                    "num_events": n, "trace_samples": 1536, "cache": False,
                },
            )
            for n in (30, 10)
        ],
        out,
        samples_per_shard=8,
    )
    logdir = str(tmp_path_factory.mktemp("e2e_mix_logs"))
    logger.set_logdir(logdir)
    args = make_args(
        dataset_name="packed",
        data=out,
        dataset_kwargs={},
        mixture_temperature=2.0,
        in_samples=1024,
    )
    ckpt = train_worker(args)
    assert ckpt and os.path.exists(ckpt)
