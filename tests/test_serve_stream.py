"""POST /stream serve integration (seist_tpu/serve/server.py): the
long-lived streaming plane against a REAL phasenet pool — session
lifecycle over the wire shape, streaming<->/annotate parity through the
actual micro-batcher, station metadata validation + /predict echo, and
the metrics/alerts surfaces."""

import json

import numpy as np
import pytest

from seist_tpu.serve.protocol import BadRequest, parse_station

WINDOW = 256


@pytest.fixture(scope="module")
def service():
    from seist_tpu.serve import BatcherConfig as BC
    from seist_tpu.serve import ModelPool, ServeService

    pool = ModelPool([("phasenet", "")], window=WINDOW)
    svc = ServeService(
        pool,
        BC(max_batch=4, max_delay_ms=5.0, max_queue=64),
        stream_config={
            "assoc_min_stations": 3,
            "assoc_window_s": 60.0,
            "assoc_tolerance_s": 3.0,
            "max_stations": 64,
        },
    )
    yield svc
    svc.shutdown()


@pytest.fixture(scope="module")
def fake_service():
    """ServeService over a deterministic batch-invariant picker entry:
    probabilities depend only on each window's own samples, so bucket-1
    (stream) and bucket-4 (annotate) programs are bitwise identical and
    the serve-plane parity pin can be EXACT. (The real-model fixture's
    bucket programs differ in float fusion order — borderline threshold
    crossers flip; real-model parity is tolerance-gated in the stream
    smoke instead.)"""
    from types import SimpleNamespace

    from seist_tpu.serve import BatcherConfig as BC
    from seist_tpu.serve import ServeService

    def run(x, variant="fp32"):
        import jax.numpy as jnp

        x = jnp.asarray(x)
        a = jnp.abs(x[..., 0])
        p = a / (a.max(axis=1, keepdims=True) + 1e-9)
        s = jnp.clip(jnp.abs(x[..., 1]) / 3.0, 0.0, 1.0)
        return jnp.stack([1.0 - p, p, s], axis=-1)

    entry = SimpleNamespace(
        name="envpick", window=WINDOW, in_channels=3, channel0="non",
        is_picker=True, is_group=False, version=1, variants=("fp32",),
        run=run,
    )

    class Pool:
        warmup_report = []

        def names(self):
            return ["envpick"]

        def get(self, name=None):
            return entry

        def warmup(self, buckets):
            pass

    svc = ServeService(Pool(), BC(max_batch=4, max_delay_ms=5.0,
                                  max_queue=64))
    yield svc
    svc.shutdown()


# All /stream requests in this module share one options set: the mux
# (and its session config) freezes on the FIRST stream request.
# record_max_events keeps /annotate's pick capacity from binding (the
# session side is unbounded — parity holds modulo that cap, see
# seist_tpu/stream/session.py).
OPTS = {"ppk_threshold": 0.05, "spk_threshold": 0.05, "det_threshold": 0.05,
        "combine": "max", "record_max_events": 350}


def _record(length, seed=0):
    return np.random.default_rng(seed).standard_normal(
        (length, 3)).astype(np.float32)


def _stream_record(service, station, rec, packet=97, model="phasenet"):
    """Feed one record through /stream in packets; return merged picks +
    the per-request responses."""
    out = {"ppk": [], "spk": [], "det": []}
    responses = []
    pos = 0
    seq = 0
    while pos < len(rec):
        seq += 1
        r = service.stream({
            "model": model,
            "station": station,
            "data": rec[pos : pos + packet].tolist(),
            "seq": seq,
            "options": OPTS,
        })
        responses.append(r)
        out["ppk"] += [p["sample"] for p in r["ppk"]]
        out["spk"] += [p["sample"] for p in r["spk"]]
        out["det"] += [(d["onset"], d["offset"]) for d in r["det"]]
        pos += packet
    r = service.stream({
        "model": model, "station": station, "end": True,
        "seq": seq + 1, "options": OPTS,
    })
    responses.append(r)
    out["ppk"] += [p["sample"] for p in r["ppk"]]
    out["spk"] += [p["sample"] for p in r["spk"]]
    out["det"] += [(d["onset"], d["offset"]) for d in r["det"]]
    assert r["closed"] is True
    return out, responses


class TestStreamEndpoint:
    def test_stream_matches_annotate(self, fake_service):
        """The serve-plane parity pin: a record streamed in packets
        through the real batcher yields the same picks as one POST
        /annotate of the concatenated record."""
        rec = _record(700, seed=1)
        got, responses = _stream_record(
            fake_service, {"id": "PAR1"}, rec, packet=97,
            model="envpick",
        )
        offline = fake_service.annotate(rec.tolist(), options=OPTS)
        assert sorted(got["ppk"]) == sorted(
            p["sample"] for p in offline["ppk"]
        )
        assert sorted(got["spk"]) == sorted(
            p["sample"] for p in offline["spk"]
        )
        assert sorted(got["det"]) == sorted(
            (d["onset"], d["offset"]) for d in offline["det"]
        )
        # Total windows match the offline count; picks came out along
        # the way, not all in the final flush.
        assert sum(r["windows"] for r in responses) == offline["windows"]
        assert responses[-1]["n_samples"] == 700

    def test_duplicate_packet_dropped(self, service):
        st = {"id": "DUP1"}
        rec = _record(WINDOW, seed=2)
        service.stream({"model": "phasenet", "station": st,
                        "data": rec.tolist(), "seq": 7, "options": OPTS})
        r = service.stream({"model": "phasenet", "station": st,
                           "data": rec.tolist(), "seq": 7, "options": OPTS})
        assert r["duplicate"] is True and r["windows"] == 0
        service.stream({"model": "phasenet", "station": st, "end": True,
                        "seq": 8, "options": OPTS})

    def test_station_required_and_validated(self, service):
        rec = _record(32, seed=3)
        with pytest.raises(BadRequest, match="station"):
            service.stream({"model": "phasenet", "data": rec.tolist(),
                            "options": OPTS})
        with pytest.raises(BadRequest, match="lat"):
            service.stream({
                "model": "phasenet",
                "station": {"id": "X", "lat": 35.0},  # lon missing
                "data": rec.tolist(), "options": OPTS,
            })
        with pytest.raises(BadRequest, match="seq"):
            service.stream({
                "model": "phasenet", "station": {"id": "X"},
                "data": rec.tolist(), "seq": "one", "options": OPTS,
            })
        with pytest.raises(BadRequest, match="data"):
            service.stream({"model": "phasenet", "station": {"id": "X"},
                            "options": OPTS})

    def test_network_codetection_alerts(self, service):
        """Co-located stations streaming the SAME record pick the same
        times -> the associator must raise exactly one network alert."""
        rec = _record(600, seed=4)
        geometry = [
            {"id": "EW1", "network": "CI", "lat": 35.00, "lon": -117.00},
            {"id": "EW2", "network": "CI", "lat": 35.05, "lon": -117.05},
            {"id": "EW3", "network": "CI", "lat": 35.02, "lon": -116.95},
        ]
        alerts = []
        for st in geometry:
            _, responses = _stream_record(service, st, rec, packet=200)
            for r in responses:
                alerts.extend(r["alerts"])
        assert len(alerts) >= 1
        assert alerts[0]["n_stations"] >= 3
        assert "sample_to_alert" in alerts[0]["latency_ms"]
        recent = service.stream_alerts()
        assert recent["models"]["phasenet"]["alerts"], (
            "alert must be retained for GET /stream/alerts"
        )

    def test_metrics_surface(self, service):
        m = service.metrics()
        assert m["requests"]["stream"] > 0
        s = m["stream"]["phasenet"]
        assert s["windows"] > 0 and s["packets"] > 0
        # Bus collector half must not double-publish mux counters.
        assert "stream" not in service._bus_metrics()


class TestPredictStationEcho:
    def test_predict_echoes_station(self, service):
        trace = _record(WINDOW, seed=5)
        st = {"id": "CI.ABC", "network": "CI", "lat": 35.0, "lon": -117.0}
        r = service.predict(trace.tolist(), station=st,
                            options={"ppk_threshold": 0.05})
        assert r["station"] == st

    def test_predict_without_station_unchanged(self, service):
        trace = _record(WINDOW, seed=6)
        r = service.predict(trace.tolist(), options={"ppk_threshold": 0.05})
        assert "station" not in r


class TestParseStation:
    def test_normalizes(self):
        got = parse_station({"id": "A", "lat": 1, "lon": 2.5})
        assert got == {"id": "A", "network": "", "lat": 1.0, "lon": 2.5}

    def test_absent_ok_unless_required(self):
        assert parse_station(None) is None
        with pytest.raises(BadRequest):
            parse_station(None, required=True)

    @pytest.mark.parametrize("bad", [
        {"id": ""}, {"id": 3}, {"network": "CI"},
        {"id": "A", "lat": 95.0, "lon": 0.0},
        {"id": "A", "lat": float("nan"), "lon": 0.0},
        {"id": "A", "lat": True, "lon": 0.0},
        {"id": "A", "unknown": 1}, "CI.ABC",
    ])
    def test_rejects(self, bad):
        with pytest.raises(BadRequest):
            parse_station(bad)


class TestStreamBench:
    """tools/bench_serve.py --stream-stations: the high-fan-in client."""

    def test_stream_bench_json_contract(self, tmp_path):
        import tools.bench_serve as bench_serve

        out = tmp_path / "bench.json"
        rc = bench_serve.main([
            "--model-name", "phasenet", "--window", "256",
            "--stream-stations", "6", "--concurrency", "3",
            "--duration-s", "1.0", "--stream-cadence-s", "0.2",
            "--output", str(out),
        ])
        assert rc == 0
        got = json.loads(out.read_text())
        assert got["metric"] == "serve_stream_latency"
        assert got["mode"] == "stream-open-loop"
        assert got["stations"] == 6
        assert got["errors"] == 0 and got["ok"] > 0
        assert got["p99_ms"] > 0 and got["windows"] > 0
        # Per-station accounting: every station reported, worst list
        # is real station ids.
        assert got["stations_reporting"] == 6
        assert got["station_mean_ms"]["max"] >= got["station_mean_ms"]["p50"]
        assert all(w["id"].startswith("BN") for w in got["worst_stations"])
        # The service-side counters rode along.
        assert got["stream_stats"]["sessions_opened"] == 6.0
        assert got["stream_stats"]["windows_dropped"] == 0.0

    def test_stream_bench_slo_gate_trips(self, tmp_path):
        import tools.bench_serve as bench_serve

        rc = bench_serve.main([
            "--model-name", "phasenet", "--window", "256",
            "--stream-stations", "2", "--concurrency", "2",
            "--duration-s", "0.6", "--stream-cadence-s", "0.2",
            "--slo-p99-ms", "0.001",
        ])
        assert rc == bench_serve.SLO_EXIT_CODE
