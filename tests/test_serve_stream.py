"""POST /stream serve integration (seist_tpu/serve/server.py): the
long-lived streaming plane against a REAL phasenet pool — session
lifecycle over the wire shape, streaming<->/annotate parity through the
actual micro-batcher, station metadata validation + /predict echo, and
the metrics/alerts surfaces."""

import json

import numpy as np
import pytest

from seist_tpu.serve.protocol import BadRequest, parse_station

WINDOW = 256


@pytest.fixture(scope="module")
def service():
    from seist_tpu.serve import BatcherConfig as BC
    from seist_tpu.serve import ModelPool, ServeService

    pool = ModelPool([("phasenet", "")], window=WINDOW)
    svc = ServeService(
        pool,
        BC(max_batch=4, max_delay_ms=5.0, max_queue=64),
        stream_config={
            "assoc_min_stations": 3,
            "assoc_window_s": 60.0,
            "assoc_tolerance_s": 3.0,
            "max_stations": 64,
        },
    )
    yield svc
    svc.shutdown()


@pytest.fixture(scope="module")
def fake_service():
    """ServeService over a deterministic batch-invariant picker entry:
    probabilities depend only on each window's own samples, so bucket-1
    (stream) and bucket-4 (annotate) programs are bitwise identical and
    the serve-plane parity pin can be EXACT. (The real-model fixture's
    bucket programs differ in float fusion order — borderline threshold
    crossers flip; real-model parity is tolerance-gated in the stream
    smoke instead.)"""
    from types import SimpleNamespace

    from seist_tpu.serve import BatcherConfig as BC
    from seist_tpu.serve import ServeService

    def run(x, variant="fp32"):
        import jax.numpy as jnp

        x = jnp.asarray(x)
        a = jnp.abs(x[..., 0])
        p = a / (a.max(axis=1, keepdims=True) + 1e-9)
        s = jnp.clip(jnp.abs(x[..., 1]) / 3.0, 0.0, 1.0)
        return jnp.stack([1.0 - p, p, s], axis=-1)

    entry = SimpleNamespace(
        name="envpick", window=WINDOW, in_channels=3, channel0="non",
        is_picker=True, is_group=False, version=1, variants=("fp32",),
        run=run,
    )

    class Pool:
        warmup_report = []

        def names(self):
            return ["envpick"]

        def get(self, name=None):
            return entry

        def warmup(self, buckets):
            pass

    svc = ServeService(Pool(), BC(max_batch=4, max_delay_ms=5.0,
                                  max_queue=64))
    yield svc
    svc.shutdown()


# All /stream requests in this module share one options set: the mux
# (and its session config) freezes on the FIRST stream request.
# record_max_events keeps /annotate's pick capacity from binding (the
# session side is unbounded — parity holds modulo that cap, see
# seist_tpu/stream/session.py).
OPTS = {"ppk_threshold": 0.05, "spk_threshold": 0.05, "det_threshold": 0.05,
        "combine": "max", "record_max_events": 350}


def _record(length, seed=0):
    return np.random.default_rng(seed).standard_normal(
        (length, 3)).astype(np.float32)


def _stream_record(service, station, rec, packet=97, model="phasenet"):
    """Feed one record through /stream in packets; return merged picks +
    the per-request responses."""
    out = {"ppk": [], "spk": [], "det": []}
    responses = []
    pos = 0
    seq = 0
    while pos < len(rec):
        seq += 1
        r = service.stream({
            "model": model,
            "station": station,
            "data": rec[pos : pos + packet].tolist(),
            "seq": seq,
            "options": OPTS,
        })
        responses.append(r)
        out["ppk"] += [p["sample"] for p in r["ppk"]]
        out["spk"] += [p["sample"] for p in r["spk"]]
        out["det"] += [(d["onset"], d["offset"]) for d in r["det"]]
        pos += packet
    r = service.stream({
        "model": model, "station": station, "end": True,
        "seq": seq + 1, "options": OPTS,
    })
    responses.append(r)
    out["ppk"] += [p["sample"] for p in r["ppk"]]
    out["spk"] += [p["sample"] for p in r["spk"]]
    out["det"] += [(d["onset"], d["offset"]) for d in r["det"]]
    assert r["closed"] is True
    return out, responses


class TestStreamEndpoint:
    def test_stream_matches_annotate(self, fake_service):
        """The serve-plane parity pin: a record streamed in packets
        through the real batcher yields the same picks as one POST
        /annotate of the concatenated record."""
        rec = _record(700, seed=1)
        got, responses = _stream_record(
            fake_service, {"id": "PAR1"}, rec, packet=97,
            model="envpick",
        )
        offline = fake_service.annotate(rec.tolist(), options=OPTS)
        assert sorted(got["ppk"]) == sorted(
            p["sample"] for p in offline["ppk"]
        )
        assert sorted(got["spk"]) == sorted(
            p["sample"] for p in offline["spk"]
        )
        assert sorted(got["det"]) == sorted(
            (d["onset"], d["offset"]) for d in offline["det"]
        )
        # Total windows match the offline count; picks came out along
        # the way, not all in the final flush.
        assert sum(r["windows"] for r in responses) == offline["windows"]
        assert responses[-1]["n_samples"] == 700

    def test_duplicate_packet_dropped(self, service):
        st = {"id": "DUP1"}
        rec = _record(WINDOW, seed=2)
        service.stream({"model": "phasenet", "station": st,
                        "data": rec.tolist(), "seq": 7, "options": OPTS})
        r = service.stream({"model": "phasenet", "station": st,
                           "data": rec.tolist(), "seq": 7, "options": OPTS})
        assert r["duplicate"] is True and r["windows"] == 0
        service.stream({"model": "phasenet", "station": st, "end": True,
                        "seq": 8, "options": OPTS})

    def test_station_required_and_validated(self, service):
        rec = _record(32, seed=3)
        with pytest.raises(BadRequest, match="station"):
            service.stream({"model": "phasenet", "data": rec.tolist(),
                            "options": OPTS})
        with pytest.raises(BadRequest, match="lat"):
            service.stream({
                "model": "phasenet",
                "station": {"id": "X", "lat": 35.0},  # lon missing
                "data": rec.tolist(), "options": OPTS,
            })
        with pytest.raises(BadRequest, match="seq"):
            service.stream({
                "model": "phasenet", "station": {"id": "X"},
                "data": rec.tolist(), "seq": "one", "options": OPTS,
            })
        with pytest.raises(BadRequest, match="data"):
            service.stream({"model": "phasenet", "station": {"id": "X"},
                            "options": OPTS})

    def test_network_codetection_alerts(self, service):
        """Co-located stations streaming the SAME record pick the same
        times -> the associator must raise exactly one network alert."""
        rec = _record(600, seed=4)
        geometry = [
            {"id": "EW1", "network": "CI", "lat": 35.00, "lon": -117.00},
            {"id": "EW2", "network": "CI", "lat": 35.05, "lon": -117.05},
            {"id": "EW3", "network": "CI", "lat": 35.02, "lon": -116.95},
        ]
        alerts = []
        for st in geometry:
            _, responses = _stream_record(service, st, rec, packet=200)
            for r in responses:
                alerts.extend(r["alerts"])
        assert len(alerts) >= 1
        assert alerts[0]["n_stations"] >= 3
        assert "sample_to_alert" in alerts[0]["latency_ms"]
        recent = service.stream_alerts()
        assert recent["models"]["phasenet"]["alerts"], (
            "alert must be retained for GET /stream/alerts"
        )

    def test_metrics_surface(self, service):
        m = service.metrics()
        assert m["requests"]["stream"] > 0
        s = m["stream"]["phasenet"]
        assert s["windows"] > 0 and s["packets"] > 0
        # Bus collector half must not double-publish mux counters.
        assert "stream" not in service._bus_metrics()


class TestPredictStationEcho:
    def test_predict_echoes_station(self, service):
        trace = _record(WINDOW, seed=5)
        st = {"id": "CI.ABC", "network": "CI", "lat": 35.0, "lon": -117.0}
        r = service.predict(trace.tolist(), station=st,
                            options={"ppk_threshold": 0.05})
        assert r["station"] == st

    def test_predict_without_station_unchanged(self, service):
        trace = _record(WINDOW, seed=6)
        r = service.predict(trace.tolist(), options={"ppk_threshold": 0.05})
        assert "station" not in r


class TestParseStation:
    def test_normalizes(self):
        got = parse_station({"id": "A", "lat": 1, "lon": 2.5})
        assert got == {"id": "A", "network": "", "lat": 1.0, "lon": 2.5}

    def test_absent_ok_unless_required(self):
        assert parse_station(None) is None
        with pytest.raises(BadRequest):
            parse_station(None, required=True)

    @pytest.mark.parametrize("bad", [
        {"id": ""}, {"id": 3}, {"network": "CI"},
        {"id": "A", "lat": 95.0, "lon": 0.0},
        {"id": "A", "lat": float("nan"), "lon": 0.0},
        {"id": "A", "lat": True, "lon": 0.0},
        {"id": "A", "unknown": 1}, "CI.ABC",
    ])
    def test_rejects(self, bad):
        with pytest.raises(BadRequest):
            parse_station(bad)


class TestStreamBench:
    """tools/bench_serve.py --stream-stations: the high-fan-in client."""

    def test_stream_bench_json_contract(self, tmp_path):
        import tools.bench_serve as bench_serve

        out = tmp_path / "bench.json"
        rc = bench_serve.main([
            "--model-name", "phasenet", "--window", "256",
            "--stream-stations", "6", "--concurrency", "3",
            "--duration-s", "1.0", "--stream-cadence-s", "0.2",
            "--output", str(out),
        ])
        assert rc == 0
        got = json.loads(out.read_text())
        assert got["metric"] == "serve_stream_latency"
        assert got["mode"] == "stream-open-loop"
        assert got["stations"] == 6
        assert got["errors"] == 0 and got["ok"] > 0
        assert got["p99_ms"] > 0 and got["windows"] > 0
        # Per-station accounting: every station reported, worst list
        # is real station ids.
        assert got["stations_reporting"] == 6
        assert got["station_mean_ms"]["max"] >= got["station_mean_ms"]["p50"]
        assert all(w["id"].startswith("BN") for w in got["worst_stations"])
        # The service-side counters rode along.
        assert got["stream_stats"]["sessions_opened"] == 6.0
        assert got["stream_stats"]["windows_dropped"] == 0.0

    def test_stream_bench_slo_gate_trips(self, tmp_path):
        import tools.bench_serve as bench_serve

        rc = bench_serve.main([
            "--model-name", "phasenet", "--window", "256",
            "--stream-stations", "2", "--concurrency", "2",
            "--duration-s", "0.6", "--stream-cadence-s", "0.2",
            "--slo-p99-ms", "0.001",
        ])
        assert rc == bench_serve.SLO_EXIT_CODE


# ----------------------------------------------------- durability plane
def _envpick_service(stream_config=None):
    """A fresh ServeService over the deterministic envpick entry (the
    fake_service recipe, but per-test so journal_dir/stream_config can
    vary). Caller owns shutdown()."""
    from types import SimpleNamespace

    from seist_tpu.serve import BatcherConfig as BC
    from seist_tpu.serve import ServeService

    def run(x, variant="fp32"):
        import jax.numpy as jnp

        x = jnp.asarray(x)
        a = jnp.abs(x[..., 0])
        p = a / (a.max(axis=1, keepdims=True) + 1e-9)
        s = jnp.clip(jnp.abs(x[..., 1]) / 3.0, 0.0, 1.0)
        return jnp.stack([1.0 - p, p, s], axis=-1)

    entry = SimpleNamespace(
        name="envpick", window=WINDOW, in_channels=3, channel0="non",
        is_picker=True, is_group=False, version=1, variants=("fp32",),
        run=run,
    )

    class Pool:
        warmup_report = []

        def names(self):
            return ["envpick"]

        def get(self, name=None):
            return entry

        def warmup(self, buckets):
            pass

    return ServeService(
        Pool(), BC(max_batch=4, max_delay_ms=5.0, max_queue=64),
        stream_config=stream_config,
    )


def _feed(svc, station, rec, lo, hi, seq0, packet=97, end_at=None):
    """Stream rec[lo:hi] in packets; -> (picks, last_response, next_seq)."""
    picks = []
    pos, seq = lo, seq0
    r = None
    while pos < hi:
        seq += 1
        r = svc.stream({
            "model": "envpick", "station": station,
            "data": rec[pos : pos + packet].tolist(),
            "seq": seq, "options": OPTS,
        })
        picks += [("p", p["sample"]) for p in r["ppk"]]
        picks += [("s", p["sample"]) for p in r["spk"]]
        pos += packet
    if end_at is not None and pos >= end_at:
        seq += 1
        r = svc.stream({"model": "envpick", "station": station,
                        "end": True, "seq": seq, "options": OPTS})
        picks += [("p", p["sample"]) for p in r["ppk"]]
        picks += [("s", p["sample"]) for p in r["spk"]]
    return picks, r, seq


class TestStreamDurability:
    """The failover contract end-to-end through ServeService: journal
    restore mid-record, WAL-seeded dedup across restart, fault knobs."""

    def test_replica_restart_resumes_from_journal(self, tmp_path):
        """Kill a journaled service mid-record; a successor over the
        same journal dir continues the pick stream exactly where the
        reference (uninterrupted) session would be."""
        rec = _record(700, seed=11)
        st = {"id": "FO1"}

        ref = _envpick_service()
        try:
            ref_picks, _, _ = _feed(ref, st, rec, 0, 700, 0, end_at=700)
        finally:
            ref.shutdown()

        jd = str(tmp_path / "j")
        a = _envpick_service({"journal_dir": jd, "journal_every_s": 0.0})
        try:
            got, _, seq = _feed(a, st, rec, 0, 97 * 3, 0)
        finally:
            a.shutdown(drain=True)  # journals final state (the handoff)
        b = _envpick_service({"journal_dir": jd, "journal_every_s": 0.0})
        try:
            more, last, _ = _feed(b, st, rec, 97 * 3, 700, seq,
                                  end_at=700)
            got += more
            assert last["n_samples"] == 700, "session resumed, not reset"
            assert b.metrics()["stream"]["envpick"]["restores"] == 1.0
        finally:
            b.shutdown()
        assert got == ref_picks

    def test_alert_wal_seeds_dedup_across_restart(self, tmp_path):
        """An alert emitted before a crash must not re-alert when the
        successor re-forms the same hypothesis (exactly-once for the
        consumer, via WAL replay into the dedup window)."""
        import glob

        geometry = [
            {"id": "WA1", "network": "CI", "lat": 35.00, "lon": -117.00},
            {"id": "WA2", "network": "CI", "lat": 35.05, "lon": -117.05},
            {"id": "WA3", "network": "CI", "lat": 35.02, "lon": -116.95},
        ]
        rec = _record(600, seed=12)
        jd = str(tmp_path / "j")
        sc = {"journal_dir": jd, "journal_every_s": 0.0,
              "assoc_min_stations": 3, "assoc_window_s": 60.0,
              "assoc_tolerance_s": 3.0}

        def run_once(svc):
            alerts = []
            for st in geometry:
                _, responses = _stream_record(svc, st, rec, packet=200,
                                              model="envpick")
                for r in responses:
                    alerts.extend(r["alerts"])
            return alerts

        a = _envpick_service(sc)
        try:
            first = run_once(a)
            assert first, "scenario must alert at least once"
            assert all(al["alert_id"] for al in first)
            wals = glob.glob(f"{jd}/envpick/alerts*.wal")
            assert wals, "every emitted alert is WAL'd before visibility"
            n_walled = sum(1 for _ in open(wals[0]))
            assert n_walled == len(first)
        finally:
            a.shutdown(drain=True)

        b = _envpick_service(sc)
        try:
            second = run_once(b)  # identical replay = re-formed hypothesis
            assert second == [], "WAL-seeded dedup must suppress replays"
            s = b.metrics()["stream"]["envpick"]
            assert s["alerts_deduped"] >= len(first)
        finally:
            b.shutdown()

    def test_mux_closed_maps_to_shutting_down(self):
        from seist_tpu.serve.protocol import ShuttingDown

        svc = _envpick_service()
        try:
            st = {"id": "MC1"}
            svc.stream({"model": "envpick", "station": st,
                        "data": _record(97, seed=13).tolist(),
                        "seq": 1, "options": OPTS})
            svc._stream_muxes["envpick"].close_all()
            # 503 shutting_down: the router's cue to re-home this
            # station onto a survivor (NOT 500 — that would open the
            # breaker on a deliberate drain).
            with pytest.raises(ShuttingDown):
                svc.stream({"model": "envpick", "station": st,
                            "data": _record(97, seed=13).tolist(),
                            "seq": 2, "options": OPTS})
        finally:
            svc.shutdown()


class TestStreamFaultKnobs:
    """SEIST_FAULT_STREAM_* through the serving stack (unit-level fate
    logic lives in tests/test_faults.py)."""

    @staticmethod
    def _faulted_service(monkeypatch, **env):
        from seist_tpu.utils import faults as faults_mod

        for k, v in env.items():
            monkeypatch.setenv(k, v)
        monkeypatch.setattr(faults_mod, "_STREAM_FAULTS", None)
        svc = _envpick_service()
        return svc

    def teardown_method(self, method):
        # The singleton was re-parsed under fault env; reset so later
        # tests (and modules) see the inert default again.
        from seist_tpu.utils import faults as faults_mod

        faults_mod._STREAM_FAULTS = None

    def test_drop_swallows_server_side_after_200(self, monkeypatch):
        svc = self._faulted_service(
            monkeypatch, SEIST_FAULT_STREAM_DROP_P="1.0"
        )
        try:
            r = svc.stream({"model": "envpick", "station": {"id": "DR1"},
                            "data": _record(97, seed=14).tolist(),
                            "seq": 1, "options": OPTS})
            # The client sees success; the session saw nothing.
            assert r["n_samples"] == 0 and r["windows"] == 0
            assert svc.metrics()["stream"]["envpick"]["packets"] == 0.0
        finally:
            svc.shutdown()

    def test_dup_feeds_twice_second_is_idempotent(self, monkeypatch):
        svc = self._faulted_service(
            monkeypatch, SEIST_FAULT_STREAM_DUP_P="1.0"
        )
        try:
            r = svc.stream({"model": "envpick", "station": {"id": "DU1"},
                            "data": _record(97, seed=15).tolist(),
                            "seq": 1, "options": OPTS})
            assert r["duplicate"] is False  # first copy is the real one
            s = svc.metrics()["stream"]["envpick"]
            assert s["packets"] == 2.0 and s["duplicates"] == 1.0
        finally:
            svc.shutdown()

    def test_reorder_holds_then_delivers_stream_completes(self, monkeypatch):
        svc = self._faulted_service(
            monkeypatch, SEIST_FAULT_STREAM_REORDER_P="1.0"
        )
        try:
            rec = _record(300, seed=16)
            st = {"id": "RE1"}
            for i, lo in enumerate(range(0, 300, 100)):
                svc.stream({"model": "envpick", "station": st,
                            "data": rec[lo : lo + 100].tolist(),
                            "seq": i + 1, "options": OPTS})
            r = svc.stream({"model": "envpick", "station": st,
                            "end": True, "seq": 4, "options": OPTS})
            # Every packet was held + delivered one late (the last via
            # the pre-end flush): nothing is lost, order degrades to the
            # session's duplicate/gap stitching.
            assert r["closed"] is True
            assert r["n_samples"] == 300
        finally:
            svc.shutdown()


class TestShedFinalExemption:
    def test_end_packet_admitted_while_shedding(self):
        from seist_tpu.serve.protocol import Overloaded
        from seist_tpu.serve.shed import AdmissionController, ShedConfig

        # Streams ride the alert tier, which defaults to never-shed; a
        # finite threshold makes the exemption observable.
        ctl = AdmissionController(
            lambda: 10_000.0,
            ShedConfig(alert_delay_ms=500.0),
            model="envpick",
        )
        try:
            with pytest.raises(Overloaded):
                ctl.admit("alert")
            # end=true RELEASES capacity: always admitted, counted.
            ctl.admit("alert", final=True)
            tier = ctl.stats()["tiers"]["alert"]
            assert tier["shedding"] is True
            assert tier["final_exempt"] == 1
            assert tier["admitted"] == 1
        finally:
            ctl.close()
