"""Compile-budget regression on the REAL jitted train step (jaxlint
runtime audit lane, docs/STATIC_ANALYSIS.md).

The invariant ROADMAP's "as fast as the hardware allows" depends on:
the train step compiles once per shape bucket, then every identical-shape
step is a pure cache hit. A retrace on identical shapes (fresh jit wrap
per step, non-hashable static, weak-type churn) silently turns a ~100 ms
step into a multi-second one — here it turns into a failing assertion.

Kept out of the pure-unit smoke lane (model compiles dominate); runs in
tier-1 (`-m 'not slow'`). CompileBudget mechanics on tiny programs are
covered in tests/test_jaxlint.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import seist_tpu
from seist_tpu import taskspec
from seist_tpu.models import api
from seist_tpu.train import (
    build_optimizer,
    create_train_state,
    jit_step,
    make_train_step,
)

# repo root is put on sys.path by tests/conftest.py
from tools.jaxlint.runtime import CompileBudget  # noqa: E402

seist_tpu.load_all()

L = 256
BATCH = 4


@pytest.fixture(autouse=True, scope="module")
def _no_persistent_cache():
    """Opt this module out of the persistent XLA compile cache: on jax
    0.4.37 CPU, executables DESERIALIZED from the disk cache intermittently
    corrupt donated outputs in unsynchronized donated step chains
    (state.step reads back float bits, ~20-40% of runs — see the ROADMAP
    open item; reproduced with zero jaxlint code). These tests assert on
    state after exactly such chains, so they must run on fresh-compiled
    executables, whose aliasing is correct."""
    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    yield
    jax.config.update("jax_compilation_cache_dir", prev)


def _setup():
    model = api.create_model("phasenet", in_samples=L)
    variables = api.init_variables(model, in_samples=L, batch_size=BATCH)
    tx = build_optimizer("adam", 1e-3)
    state = create_train_state(model, variables, tx)
    spec = taskspec.get_task_spec("phasenet")
    return state, spec, taskspec.make_loss("phasenet")


def _batch(rng):
    x = rng.standard_normal((BATCH, L, 3)).astype(np.float32)
    ppk = np.zeros((BATCH, L), np.float32)
    ppk[:, 64] = 1.0
    spk = np.zeros((BATCH, L), np.float32)
    spk[:, 128] = 1.0
    y = np.stack([1.0 - ppk - spk, ppk, spk], axis=-1)
    return jnp.asarray(x), jnp.asarray(y)


def test_train_step_steady_state_never_recompiles(rng):
    """The first call compiles OUTSIDE the budget window (cold-cache
    first compiles can re-lower under load, which is noise, not the
    regression); the guarded property is steady state: once warm, steps
    of identical shape must trace exactly zero times."""
    state, spec, loss_fn = _setup()
    # donate_state=False: the guarded property here is the COMPILE
    # count, which donation cannot change — while ANY donated chain on
    # jax 0.4.37 CPU is exposed to the open use-after-reuse hazard
    # (ROADMAP): PR 5 saw state.step read float bits once on a
    # fresh-compiled UNsynced chain, and PR 6's tier-1 caught it on a
    # fresh-compiled PER-STEP-SYNCED chain (two reads of the same Array
    # differed), so neither the compile cache nor missing sync is
    # necessary. The donated-chain repro lives in
    # tests/test_donation_cache.py; this test stays about retraces.
    step = jit_step(make_train_step(spec, loss_fn), donate_state=False)
    key = jax.random.PRNGKey(0)
    x, y = _batch(rng)
    state, loss, _ = step(state, x, y, key)  # warm-up compile
    jax.block_until_ready((state, loss))
    with CompileBudget() as budget:
        for _ in range(4):
            x, y = _batch(rng)  # fresh values, identical shapes/dtypes
            state, loss, _ = step(state, x, y, key)
            jax.block_until_ready((state, loss))
    # No identical-shape retrace, and at most one stray re-lowering
    # (observed once under heavy concurrent load; a real regression —
    # e.g. a fresh wrap per call — traces every step and trips both).
    assert budget.retraces("train_step") == [], budget.compiles
    assert budget.total("train_step") <= 1, budget.compiles
    assert int(state.step) == 5


def test_budget_fails_when_step_is_made_to_retrace(rng):
    """Negative control (the acceptance criterion): re-wrapping the step
    per call — the exact hazard jaxlint's jit-in-loop rule targets —
    must trip the budget's identical-shape retrace assertion."""
    state, spec, loss_fn = _setup()
    key = jax.random.PRNGKey(0)
    x, y = _batch(rng)
    with CompileBudget() as budget:
        for _ in range(2):
            # donate_state=False, like the steady-state test above: this
            # file asserts COMPILE counts only, and a donated chain
            # through freshly re-jitted executables is the most exposed
            # shape of the open jax-0.4.37-CPU use-after-reuse hazard
            # (ROADMAP) — it segfaulted a tier-1 run in PR 6. Donation
            # coverage lives in tests/test_donation_cache.py.
            step = jit_step(
                make_train_step(spec, loss_fn), donate_state=False
            )  # fresh closure
            state, loss, _ = step(state, x, y, key)
        jax.block_until_ready((state, loss))
    assert budget.retraces("train_step"), "expected an identical-shape retrace"
    with pytest.raises(AssertionError, match="retrace on identical shapes"):
        budget.assert_compiles_once("train_step")
