"""detlint tests: every rule catches its seeded violation and stays
quiet on the clean twin; det-path gating (the same code is clean outside
DET_PATH_GLOBS); the local-dataflow exemption for assigned enumerations;
the detlint suppression tag (shared grammar with jaxlint/threadlint,
disjoint namespace); CLI exit codes on seeded fixtures for EVERY rule in
the catalog plus the refuse-empty --update-baseline contract; the
replay-lane runtime helpers (digest/relink); the reversed-listdir
resume/restore regressions; and the replay-smoke e2e under
PYTHONHASHSEED x worker-count perturbation."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

# repo root is put on sys.path by tests/conftest.py
from tools.detlint import __main__ as detlint_cli  # noqa: E402
from tools.detlint.engine import lint_source  # noqa: E402
from tools.detlint.runtime import (  # noqa: E402
    combine,
    digest_tree,
    relink_tree,
)

DET_PATH = "seist_tpu/data/example.py"
PLAIN_PATH = "seist_tpu/obs/example.py"


def rules_of(src, path=PLAIN_PATH):
    return [f.rule for f in lint_source(textwrap.dedent(src), path)]


# ------------------------------------------------- unsorted-dir-enumeration
def test_listdir_iteration_flagged():
    src = """
    import os

    def scan(d):
        for f in os.listdir(d):
            process(f)
    """
    assert rules_of(src) == ["unsorted-dir-enumeration"]


def test_sorted_listdir_clean():
    src = """
    import os

    def scan(d):
        for f in sorted(os.listdir(d)):
            process(f)
    """
    assert rules_of(src) == []


def test_listdir_emptiness_and_len_clean():
    src = """
    import os

    def probe(d):
        if os.listdir(d):
            return len(os.listdir(d))
        return 0
    """
    assert rules_of(src) == []


def test_listdir_membership_clean():
    src = """
    import os

    def has_meta(d):
        return "meta.json" in os.listdir(d)
    """
    assert rules_of(src) == []


def test_assigned_listdir_consumed_in_sorted_clean():
    # The journal.py idiom: names = os.listdir(...) later wrapped in
    # sorted() — every use order-insensitive, so the assignment is exempt.
    src = """
    import os

    def station_ids(root):
        names = os.listdir(root)
        return sorted(n for n in names if n.endswith(".npz"))
    """
    assert rules_of(src) == []


def test_assigned_glob_indexed_flagged():
    # The obs_smoke bug shape: dumps[0] on an unsorted glob picks a
    # machine-dependent file.
    src = """
    import glob

    def first_dump(pat):
        dumps = glob.glob(pat)
        return dumps[0]
    """
    assert rules_of(src) == ["unsorted-dir-enumeration"]


def test_iterdir_flagged_sorted_genexp_clean():
    src = """
    from pathlib import Path

    def walk(p):
        for f in Path(p).iterdir():
            yield f

    def walk_sorted(p):
        return sorted(f.name for f in Path(p).iterdir())
    """
    assert rules_of(src) == ["unsorted-dir-enumeration"]


# ------------------------------------------------------------- unseeded-rng
def test_global_np_random_draw_flagged():
    src = """
    import numpy as np

    def jiggle(x):
        return x + np.random.uniform(-1, 1)
    """
    assert rules_of(src) == ["unseeded-rng"]


def test_zero_arg_default_rng_flagged_seeded_clean():
    src = """
    import numpy as np

    def bad():
        return np.random.default_rng()

    def good(seed):
        return np.random.default_rng(seed)
    """
    assert rules_of(src) == ["unseeded-rng"]


def test_seed_plumbing_clean():
    src = """
    import random
    import numpy as np

    def seed_everything(seed):
        random.seed(seed)
        np.random.seed(seed)
    """
    assert rules_of(src) == []


def test_stdlib_random_draw_flagged():
    src = """
    import random

    def jitter(base):
        return base * random.uniform(0.5, 1.5)
    """
    assert rules_of(src) == ["unseeded-rng"]


def test_jax_random_alias_not_mistaken_for_stdlib():
    # `from jax import random` makes random.uniform a KEYED jax draw —
    # deterministic by construction, none of stdlib's business.
    src = """
    from jax import random

    def noise(key, shape):
        return random.uniform(key, shape)
    """
    assert rules_of(src) == []


def test_prngkey_from_wallclock_flagged_seed_clean():
    src = """
    import time
    import jax

    def bad():
        return jax.random.PRNGKey(int(time.time()))

    def good(seed):
        return jax.random.PRNGKey(seed)
    """
    assert rules_of(src) == ["unseeded-rng"]


# -------------------------------------------- wallclock-in-deterministic-path
def test_wallclock_in_det_path_flagged():
    src = """
    import time

    def stamp_row(row):
        row["t"] = time.time()
        return row
    """
    assert rules_of(src, DET_PATH) == ["wallclock-in-deterministic-path"]


def test_wallclock_outside_det_path_clean():
    src = """
    import time

    def stamp_row(row):
        row["t"] = time.time()
        return row
    """
    assert rules_of(src, PLAIN_PATH) == []


def test_telemetry_only_decorator_exempts():
    src = """
    import time

    from seist_tpu.utils.determinism import telemetry_only

    @telemetry_only
    def log_progress(n):
        logger.info(f"{n} at {time.time()}")
    """
    assert rules_of(src, DET_PATH) == []


def test_monotonic_interval_clean_in_det_path():
    src = """
    import time

    def timed(fn):
        t0 = time.monotonic()
        fn()
        return time.monotonic() - t0
    """
    assert rules_of(src, DET_PATH) == []


def test_datetime_now_in_det_path_flagged():
    src = """
    from datetime import datetime

    def tag():
        return datetime.now().isoformat()
    """
    assert rules_of(src, DET_PATH) == ["wallclock-in-deterministic-path"]


# --------------------------------------------- set-or-dict-order-dependence
def test_set_iteration_flagged():
    src = """
    def emit_all(emit):
        for x in {"a", "b", "c"}:
            emit(x)
    """
    assert rules_of(src) == ["set-or-dict-order-dependence"]


def test_list_of_set_flagged_sorted_clean():
    src = """
    def dedup_bad(xs):
        return list(set(xs))

    def dedup_good(xs):
        return sorted(set(xs))
    """
    assert rules_of(src) == ["set-or-dict-order-dependence"]


def test_set_membership_clean():
    src = """
    def is_vowel(c):
        return c in {"a", "e", "i", "o", "u"}
    """
    assert rules_of(src) == []


def test_dict_keys_join_flagged_sorted_clean():
    src = """
    def ident_bad(d):
        return ",".join(d.keys())

    def ident_good(d):
        return ",".join(sorted(d.keys()))
    """
    assert rules_of(src) == ["set-or-dict-order-dependence"]


# ------------------------------------------------------ float-reduction-order
def test_float_sum_in_det_path_flagged():
    src = """
    def mean_origin(times):
        return sum(t / 2.0 for t in times) / len(times)
    """
    assert rules_of(src, DET_PATH) == ["float-reduction-order"]


def test_int_sum_in_det_path_clean():
    src = """
    def total_rows(shards):
        return sum(len(s) for s in shards)
    """
    assert rules_of(src, DET_PATH) == []


def test_fsum_clean_and_non_det_path_clean():
    src = """
    import math

    def mean_origin(times):
        return math.fsum(t / 2.0 for t in times) / len(times)
    """
    assert rules_of(src, DET_PATH) == []
    bad = """
    def score(rs):
        return sum(1.0 - r for r in rs)
    """
    assert rules_of(bad, DET_PATH) == ["float-reduction-order"]
    assert rules_of(bad, PLAIN_PATH) == []


# ------------------------------------------------------- env-dependent-default
def test_unregistered_env_read_in_det_path_flagged():
    src = """
    import os

    def knob():
        return os.environ.get("MY_SECRET_KNOB", "1")
    """
    assert rules_of(src, DET_PATH) == ["env-dependent-default"]


def test_registered_env_reads_clean():
    src = """
    import os

    def knobs():
        a = os.environ.get("SEIST_FAULT_REPICK_SLOW_MS", "0")
        b = os.environ.get("SEIST_IO_GUARD", "1")
        c = os.getenv("PYTHONHASHSEED")
        return a, b, c
    """
    assert rules_of(src, DET_PATH) == []


def test_env_subscript_and_nonliteral_flagged():
    src = """
    import os

    def bad(name):
        return os.environ["MY_OTHER_KNOB"], os.environ.get(name)
    """
    assert rules_of(src, DET_PATH) == [
        "env-dependent-default",
        "env-dependent-default",
    ]


def test_env_read_outside_det_path_clean():
    src = """
    import os

    def knob():
        return os.environ.get("MY_SECRET_KNOB", "1")
    """
    assert rules_of(src, PLAIN_PATH) == []


# --------------------------------------------------------------- suppressions
def test_suppression_with_rationale_silences():
    src = """
    import os

    def scan(d):
        # detlint: disable=unsorted-dir-enumeration -- consumer dedups
        for f in os.listdir(d):
            process(f)
    """
    assert rules_of(src) == []


def test_suppression_without_rationale_is_void():
    src = """
    import os

    def scan(d):
        for f in os.listdir(d):  # detlint: disable=unsorted-dir-enumeration
            process(f)
    """
    assert sorted(rules_of(src)) == [
        "suppression-missing-rationale",
        "unsorted-dir-enumeration",
    ]


def test_jaxlint_tag_cannot_silence_detlint():
    src = """
    import os

    def scan(d):
        # jaxlint: disable=unsorted-dir-enumeration -- wrong tag
        for f in os.listdir(d):
            process(f)
    """
    assert rules_of(src) == ["unsorted-dir-enumeration"]


def test_unused_suppression_flagged():
    src = """
    def clean():
        # detlint: disable=unseeded-rng -- nothing here draws
        return 1
    """
    assert rules_of(src) == ["unused-suppression"]


# ------------------------------------------------------------------------ CLI
#: rule -> (relpath under --root, seeded source). Det-path-only rules get
#: a path inside DET_PATH_GLOBS so the fixture actually fires.
_SEEDED_FIXTURES = {
    "unsorted-dir-enumeration": ("pkg/scan.py", """
        import os

        def scan(d):
            for f in os.listdir(d):
                process(f)
    """),
    "unseeded-rng": ("pkg/rng.py", """
        import numpy as np

        def jiggle(x):
            return x + np.random.uniform(-1, 1)
    """),
    "wallclock-in-deterministic-path": ("seist_tpu/data/stamp.py", """
        import time

        def stamp(row):
            row["t"] = time.time()
            return row
    """),
    "set-or-dict-order-dependence": ("pkg/order.py", """
        def dedup(xs):
            return list(set(xs))
    """),
    "float-reduction-order": ("seist_tpu/batch/red.py", """
        def mean(ts):
            return sum(t / 2.0 for t in ts) / len(ts)
    """),
    "env-dependent-default": ("seist_tpu/data/knob.py", """
        import os

        def knob():
            return os.environ.get("MY_SECRET_KNOB", "1")
    """),
}


@pytest.mark.parametrize("rule", sorted(_SEEDED_FIXTURES))
def test_cli_exits_nonzero_on_seeded_violation(rule, tmp_path):
    """Acceptance: `python -m tools.detlint` exits nonzero on a seeded
    violation fixture for every rule in the catalog."""
    rel, src = _SEEDED_FIXTURES[rule]
    mod = tmp_path / rel
    mod.parent.mkdir(parents=True, exist_ok=True)
    mod.write_text(textwrap.dedent(src))
    rc = detlint_cli.main(
        [rel, "--root", str(tmp_path),
         "--baseline", str(tmp_path / "baseline.json")]
    )
    assert rc == 1
    found = [f.rule for f in lint_source(textwrap.dedent(src), rel)]
    assert rule in found


def test_cli_repo_gate_is_green():
    """Acceptance: a bare `python -m tools.detlint` (default paths) exits
    0 on this repo, and its shipped baseline is EMPTY by construction."""
    assert detlint_cli.main([]) == 0
    with open(detlint_cli._DEFAULT_BASELINE) as f:
        assert json.load(f)["accepted"] == {}


def test_cli_refuses_update_of_empty_baseline(tmp_path):
    baseline = tmp_path / "detlint_baseline.json"
    baseline.write_text('{"accepted": {}}\n')
    before = baseline.read_text()
    rc = detlint_cli.main(
        ["--update-baseline", "--root", str(tmp_path.parent),
         "--baseline", str(baseline)]
    )
    assert rc == 2
    assert baseline.read_text() == before


def test_cli_unknown_path_exits_2(tmp_path):
    rc = detlint_cli.main(
        ["no/such/dir", "--root", str(tmp_path),
         "--baseline", str(tmp_path / "b.json")]
    )
    assert rc == 2


def test_cli_list_rules_names_full_catalog(capsys):
    from tools.detlint.rules import RULES

    assert detlint_cli.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert len(RULES) >= 6
    for rule in RULES:
        assert rule.name in out


# ------------------------------------------------------------- runtime lane
def test_digest_tree_and_relink_preserve_bytes(tmp_path):
    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "a.bin").write_bytes(b"alpha")
    (src / "z.bin").write_bytes(b"omega")
    (src / "sub" / "m.npz").write_bytes(b"middle")
    (src / ".tmp.partial").write_bytes(b"torn write")  # must be ignored
    d1 = digest_tree(str(src))
    assert set(d1) == {"a.bin", "z.bin", "sub/m.npz"}

    dst = tmp_path / "dst"
    n = relink_tree(str(src), str(dst))
    assert n == 3  # dotfile excluded
    assert digest_tree(str(dst)) == d1
    assert combine(digest_tree(str(dst))) == combine(d1)


def test_combine_is_insertion_order_invariant():
    a = {"x": "1", "y": "2"}
    b = {"y": "2", "x": "1"}
    assert combine(a) == combine(b)


def test_journal_restore_survives_reversed_listing(tmp_path):
    """Reversed-listdir regression (journal side): a journal directory
    re-materialized with reversed entry-creation order restores the
    SAME station set and the SAME states, byte for byte."""
    from tools.replay_smoke import _journal_digest, _journal_exercise

    result = _journal_exercise(str(tmp_path))
    assert result["journal_rev_identical"]
    # and independently: a fresh reversed copy digests identical too
    jroot = str(tmp_path / "journal")
    jrev2 = str(tmp_path / "journal_rev2")
    relink_tree(jroot, jrev2)
    assert _journal_digest(jrev2) == result["journal"]


def test_pack_resume_survives_reversed_listing(tmp_path):
    """Reversed-listdir regression (pack side): deleting the commit
    point + last sidecar and RESUMING inside a reversed-relink copy of
    the archive reproduces the original tree byte-identically."""
    import seist_tpu
    from tools.replay_smoke import _pack, _resume_exercise

    seist_tpu.load_all()
    archive = str(tmp_path / "archive")
    _pack(archive, workers=1)
    assert _resume_exercise(archive, workers=1, relink=True)


@pytest.mark.smoke
def test_replay_smoke_e2e_perturbed():
    """Replay-lane e2e (cheap phases): 2 hash seeds x 2 worker counts ->
    byte-identical pack/journal/WAL digests, reversed-listdir included.
    The repick (model) phase rides the slow-marked twin below and the
    `make replay-smoke` lane."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.replay_smoke", "--skip-repick"],
        stdout=subprocess.PIPE, text=True, timeout=900,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stdout[-2000:]
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["ok"]
    assert verdict["identical"] == {
        "pack": True, "catalog": True, "journal": True, "wal": True,
    }
    seeds = {p["hashseed"] for p in verdict["perturbations"]}
    workers = {p["workers"] for p in verdict["perturbations"]}
    assert seeds == {0, 1} and workers == {1, 2}
    assert any(p["relink"] for p in verdict["perturbations"])
    assert verdict["resume_identical"]
    assert verdict["reversed_listdir_identical"]


@pytest.mark.slow
def test_replay_smoke_e2e_full():
    """The full lane including the repick (model) phase — identical
    catalog bytes across serial and 2-worker map-reduce under different
    PYTHONHASHSEED."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.replay_smoke"],
        stdout=subprocess.PIPE, text=True, timeout=1800,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stdout[-2000:]
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["ok"]
    assert verdict["digests"]["catalog"]
