"""Serving chaos lane (`make serve-chaos`): REAL replica processes
(`main.py serve`, phasenet fresh-init, CPU) under injected faults — the
ISSUE 7 acceptance runs.

* SIGKILL one of two replicas mid-load: the fleet supervisor restarts it,
  the router retries the in-flight failures, and the client's own
  accounting (bench_serve --url) shows ZERO failed well-formed requests.
* Black-holed replica (accepts, answers health probes, never answers
  /predict): the request-path circuit opens within a bounded number of
  probes and closes after the injected fault clears.
* Overload at ~2x the sustainable arrival rate: the batch tier is shed
  with the distinct 503 'shed' (not the queue-full 429) while the alert
  tier's p99 passes its SLO gate — both verdicts from bench_serve.
* Live-model flywheel (ISSUE 13): a 3-replica fleet rolled to a new
  model version under sustained open-loop load — zero failed requests,
  zero stale-version responses after convergence, the roll visible
  drain -> relaunch -> ready per replica.
* Canary auto-rollback: an injected bad candidate version
  (SEIST_FAULT_SERVE_BAD_CANDIDATE) is drained back to 0% by the
  router's cohort-delta budget while retries keep clients green.

Replica warm-up is compile-bound; the serve CLI enables the persistent
XLA cache, so replicas after the first (and every supervisor relaunch)
re-enter rotation in seconds.
"""

import http.client
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time

import pytest

pytestmark = [pytest.mark.slow, pytest.mark.chaos]

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, os.path.join(REPO, "tools"))

SUPERVISE_FLEET = os.path.join(REPO, "tools", "supervise_fleet.py")
MAIN = os.path.join(REPO, "main.py")
WINDOW = 256

REPLICA_CMD = [
    sys.executable, MAIN, "serve",
    "--model", "phasenet=",
    "--window", str(WINDOW),
    "--max-batch", "4",
    "--max-delay-ms", "5",
]
#: generous: first-ever run pays the phasenet bucket compiles
WARM_TIMEOUT_S = 300.0


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _drain_pipe(pipe, buf):
    for line in pipe:
        buf.append(line)


def _start_fleet(tmp_path, env_extra=None, replicas=2, fleet_args=(),
                 replica_args=()):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    proc = subprocess.Popen(
        [
            sys.executable, SUPERVISE_FLEET,
            "--replicas", str(replicas),
            "--base-port", str(_free_port()),
            "--router-port", "0",
            "--probe-interval-s", "0.3",
            "--backoff", "0.5",
            "--drain-timeout-s", "20",
            *fleet_args,
            "--",
            *REPLICA_CMD, *replica_args,
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        env=env,
        text=True,
    )
    # Drain both pipes on background threads for the whole fleet
    # lifetime: the replicas inherit these fds, and an undrained pipe
    # that hits the 64 KB kernel buffer blocks EVERY fleet process on
    # its next write — a silent way to wedge the supervisor's monitor
    # loop mid-test. Draining also means a failure report carries the
    # complete fleet log, not whatever fit in the buffer.
    proc.fleet_err = []
    err_thread = threading.Thread(
        target=_drain_pipe, args=(proc.stderr, proc.fleet_err), daemon=True
    )
    err_thread.start()
    proc.fleet_err_thread = err_thread
    router = None
    for _ in range(50):
        line = proc.stdout.readline()
        if not line:
            break
        m = re.search(r"ROUTER=http://([\d.]+):(\d+)", line)
        if m:
            router = (m.group(1), int(m.group(2)))
            break
    if router is None:
        proc.kill()
        raise AssertionError("no ROUTER line from supervise_fleet")
    proc.fleet_out = []
    threading.Thread(
        target=_drain_pipe, args=(proc.stdout, proc.fleet_out), daemon=True
    ).start()
    return proc, router[0], router[1]


def _get(host, port, path, timeout=5.0):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        raw = resp.read()
        try:
            return resp.status, json.loads(raw)
        except ValueError:
            return resp.status, raw.decode()
    finally:
        conn.close()


def _wait_probed_ready(host, port, n, timeout_s=WARM_TIMEOUT_S):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            _, payload = _get(host, port, "/router/replicas")
            states = [
                r["probe_state"] for r in payload.get("replicas", [])
            ]
            if states.count("ok") >= n:
                return
        except OSError:
            pass
        time.sleep(0.25)
    raise AssertionError(
        f"fleet never reached {n} probed-ready replicas in {timeout_s}s"
    )


def _stop_fleet(proc, timeout=60):
    proc.send_signal(signal.SIGTERM)
    try:
        rc = proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        raise
    proc.fleet_err_thread.join(timeout=10)
    return rc, "".join(proc.fleet_err)


def _bench(url, tmp_path, tag, *extra):
    """Run bench_serve in-process against a live url; return (rc, json)."""
    import bench_serve

    out = str(tmp_path / f"bench_{tag}.json")
    rc = bench_serve.main([
        "--url", url,
        "--window", str(WINDOW),
        "--model-name", "phasenet",
        "--output", out,
        *extra,
    ])
    with open(out) as f:
        return rc, json.load(f)


def _read_trace_log(path):
    with open(path) as f:
        return {
            rec["trace_id"]: rec
            for rec in (json.loads(line) for line in f if line.strip())
        }


def test_sigkill_mid_load_zero_failed_requests(tmp_path):
    """Acceptance: 2 replicas under closed-loop load, one SIGKILLed by the
    fault injector at its 8th request. supervise_fleet restarts it, the
    router retries the severed in-flight requests on the survivor, and the
    client-side accounting ends with error_rate == 0."""
    stamp = str(tmp_path / "kill.stamp")
    proc, host, port = _start_fleet(
        tmp_path,
        env_extra={
            "SEIST_FAULT_SERVE_KILL_REQ": "8",
            "SEIST_FAULT_SERVE_REPLICA": "0",
            "SEIST_FAULT_STAMP": stamp,
        },
        fleet_args=("--router-retries", "3", "--request-timeout-s", "30"),
    )
    try:
        _wait_probed_ready(host, port, 2)
        tlog = str(tmp_path / "kill_traces.jsonl")
        rc, result = _bench(
            f"http://{host}:{port}", tmp_path, "kill",
            "--requests", "48",
            "--concurrency", "6",
            "--timeout-ms", "60000",
            "--trace-log", tlog,
        )
        assert os.path.exists(stamp), (
            "kill fault never fired — the run proved nothing"
        )
        assert rc == 0
        assert result["errors"] == 0 and result["ok"] == 48, result
        assert result["error_rate"] == 0.0
        # The rescue is visible on the router's own metrics plane.
        _, text = _get(host, port, "/metrics")
        assert "seist_router_retries" in text

        # --- ISSUE 11 acceptance: the rescue is visible on the TRACE
        # plane too. A request that survived the SIGKILL via router
        # retry must stitch (tools/trace_report.py) into one tree
        # showing both attempts (failed + succeeded), the surviving
        # replica's queue wait and device program span — and the span
        # tree's total must be within 10% of what the CLIENT measured
        # for that same request.
        import trace_report

        client_lat = _read_trace_log(tlog)
        assert len(client_lat) == 48
        _, idx = _get(host, port, "/traces")
        retried = [
            t for t in idx["traces"]
            if "retried" in t["flags"] and t["trace_id"] in client_lat
            and client_lat[t["trace_id"]]["status"] == 200
        ]
        assert retried, (
            f"no retried trace on the router: {idx['traces'][:5]}"
        )
        _, reg = _get(host, port, "/router/replicas")
        endpoints = [f"http://{host}:{port}"] + [
            r["url"] for r in reg["replicas"]
        ]
        # ANY surviving retried request must satisfy the acceptance —
        # walk them slowest-first (relative client-side overhead is
        # smallest there) and keep the verdicts for the failure report.
        verdicts = []
        passed = None
        for cand in sorted(
            retried,
            key=lambda t: client_lat[t["trace_id"]]["latency_ms"],
            reverse=True,
        ):
            st = trace_report.stitch_from_endpoints(
                cand["trace_id"], endpoints
            )
            attempts = st.find("attempt")
            classes = [
                (s.get("annotations") or {}).get("class")
                for s in attempts
            ]
            fwd = st.find("forward")
            client_ms = client_lat[cand["trace_id"]]["latency_ms"]
            rel = (
                abs(st.total_ms - client_ms) / client_ms
                if client_ms else 1.0
            )
            ok = (
                len(attempts) >= 2
                and any(c in ("net_error", "server_error")
                        for c in classes)
                and "ok" in classes
                and bool(st.find("queue_wait"))
                and any(
                    "phasenet" in str(
                        (s.get("annotations") or {}).get("program"))
                    for s in fwd
                )
                and "replica" in ",".join(st.processes())
                and rel <= 0.10
            )
            verdicts.append({
                "trace_id": cand["trace_id"],
                "attempts": len(attempts), "classes": classes,
                "client_ms": client_ms,
                "total_ms": round(st.total_ms, 1),
                "rel": round(rel, 3), "ok": ok,
            })
            if ok:
                passed = st
                break
        assert passed is not None, (
            "no retried trace satisfied the stitched-trace acceptance "
            f"(both attempts + queue wait + device program span + total "
            f"within 10% of client latency): {verdicts}"
        )
        print(passed.format(), file=sys.stderr, flush=True)

        # The killed replica comes back (stamped: the relaunch stays up).
        _wait_probed_ready(host, port, 2, timeout_s=120.0)
    finally:
        rc, err = _stop_fleet(proc)
    assert rc == 0, err
    assert re.search(r"replica 0 crashed rc=-9; relaunch", err), err


def test_blackhole_circuit_opens_then_closes(tmp_path):
    """Acceptance: a black-holed replica (accepts + answers probes, never
    answers requests) is routed around via its circuit breaker within a
    bounded number of probes, and the circuit closes after recovery —
    while every client request still succeeds via the healthy replica."""
    proc, host, port = _start_fleet(
        tmp_path,
        env_extra={
            "SEIST_FAULT_SERVE_BLACKHOLE_AFTER": "2",
            "SEIST_FAULT_SERVE_BLACKHOLE_COUNT": "4",
            "SEIST_FAULT_SERVE_BLACKHOLE_HOLD_S": "120",
            "SEIST_FAULT_SERVE_REPLICA": "0",
        },
        fleet_args=(
            "--router-retries", "2",
            "--request-timeout-s", "1.5",
            "--breaker-failures", "2",
            "--breaker-cooldown-s", "0.3",
        ),
    )
    try:
        _wait_probed_ready(host, port, 2)
        body = json.dumps({
            "data": [[0.0, 0.0, 0.0]] * WINDOW,
            "options": {"timeout_ms": 30000.0},
        }).encode()
        failures, opens_seen, closed_after_open = [], False, False
        deadline = time.monotonic() + 90.0
        blackholed_url = None
        while time.monotonic() < deadline:
            conn = http.client.HTTPConnection(host, port, timeout=35)
            try:
                conn.request("POST", "/predict", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                if resp.status != 200:
                    failures.append(resp.status)
            except OSError as e:
                failures.append(repr(e))
            finally:
                conn.close()
            _, payload = _get(host, port, "/router/replicas")
            snap = {
                r["url"]: r["breaker"] for r in payload["replicas"]
            }
            for url, breaker in snap.items():
                if breaker["state"] != "closed":
                    opens_seen = True
                    blackholed_url = url
            if (
                opens_seen
                and blackholed_url is not None
                and snap[blackholed_url]["state"] == "closed"
                and snap[blackholed_url]["opens"] >= 1
            ):
                closed_after_open = True
                break
            time.sleep(0.1)
        assert opens_seen, "circuit never opened on the black-holed replica"
        assert closed_after_open, (
            "circuit never closed after the black-hole recovered"
        )
        assert not failures, (
            f"client saw failures despite the breaker: {failures[:5]}"
        )
    finally:
        rc, err = _stop_fleet(proc)
    assert rc == 0, err


def test_rollout_flywheel_zero_downtime(tmp_path):
    """Acceptance (ISSUE 13): roll a 3-replica fleet to a new model
    version under sustained open-loop load — ZERO failed requests
    (error_rate 0.0), ZERO stale-version responses after convergence
    (bench_serve's --expect-version gate), with the roll visible per
    replica (drain -> relaunch -> ready) in the supervisor log."""
    spec = tmp_path / "rollout.json"
    proc, host, port = _start_fleet(
        tmp_path,
        replicas=3,
        fleet_args=(
            "--router-retries", "3",
            "--request-timeout-s", "30",
            "--rollout-file", str(spec),
            "--rollout-ready-timeout-s", "240",
        ),
    )
    try:
        _wait_probed_ready(host, port, 3)
        url = f"http://{host}:{port}"
        results = {}

        def run_bench():
            results["bench"] = _bench(
                url, tmp_path, "flywheel",
                "--arrival-rps", "5",
                "--duration-s", "150",
                "--concurrency", "32",
                "--timeout-ms", "30000",
                "--expect-version", "2",
            )

        bench_thread = threading.Thread(target=run_bench)
        bench_thread.start()
        time.sleep(3.0)  # load flowing against version 1 first
        spec.write_text(json.dumps({"version": 2}))
        proc.send_signal(signal.SIGHUP)
        bench_thread.join(timeout=400)
        assert not bench_thread.is_alive(), "bench never finished"
        rc, res = results["bench"]
        # Zero downtime: every request of the sustained run succeeded.
        assert res["errors"] == 0 and res["error_rate"] == 0.0, res
        # The run really spanned the roll: both versions answered...
        assert res["by_version"].get("1", 0) > 0, res
        assert res["by_version"].get("2", 0) > 0, res
        # ...the fleet converged during it, and afterwards not one
        # response carried the old version.
        assert res["converged_at_s"] > 0, res
        assert res["stale_after_convergence"] == 0, res
        assert rc == 0, res  # the bench's own rollout gate agrees
    finally:
        rc, err = _stop_fleet(proc, timeout=120)
    assert rc == 0, err
    # The roll is visible per replica, strictly one at a time.
    for i in range(3):
        assert f"rollout: draining replica {i}" in err, err
        assert re.search(
            rf"rollout: replica {i} ready \+ re-registered \(version 2\)",
            err,
        ), err
    assert err.index("rollout: replica 0 ready") < err.index(
        "rollout: draining replica 1"
    ), "replica 1 drained before replica 0 converged"
    assert err.index("rollout: replica 1 ready") < err.index(
        "rollout: draining replica 2"
    ), "replica 2 drained before replica 1 converged"
    assert "rollout complete: version 2" in err, err
    assert "clean preempt (rc=75)" in err, err


def test_canary_bad_candidate_auto_rollback(tmp_path):
    """Acceptance (ISSUE 13): an injected bad candidate
    (SEIST_FAULT_SERVE_BAD_CANDIDATE — elevated error rate on the
    candidate version) is drained back to 0% automatically, the
    incumbent cohort serves 100% of traffic, clients see no failures
    (router retries rescue every canary error), and the rollback event
    is on the bus and in the trace flags."""
    spec = tmp_path / "rollout.json"
    proc, host, port = _start_fleet(
        tmp_path,
        replicas=2,
        env_extra={"SEIST_FAULT_SERVE_BAD_CANDIDATE": "2"},
        fleet_args=(
            "--router-retries", "2",
            "--request-timeout-s", "30",
            # The canary policy, not the breaker, must do the draining.
            "--breaker-failures", "100",
            "--rollout-file", str(spec),
            "--rollout-ready-timeout-s", "240",
        ),
    )
    try:
        _wait_probed_ready(host, port, 2)
        url = f"http://{host}:{port}"
        # Canary stage: roll ONE replica to the (bad) candidate version.
        spec.write_text(json.dumps({"version": 2, "replicas": [0]}))
        proc.send_signal(signal.SIGHUP)
        deadline = time.monotonic() + 240.0
        while time.monotonic() < deadline:
            _, reg = _get(host, port, "/router/replicas")
            versions = sorted(
                r.get("versions", {}).get("phasenet", 0)
                for r in reg.get("replicas", [])
                if r["probe_state"] == "ok"
            )
            if versions == [1, 2]:
                break
            time.sleep(0.25)
        else:
            raise AssertionError("canary replica never came up on v2")

        # 40% canary with a tight budget over the candidate cohort.
        conn = http.client.HTTPConnection(host, port, timeout=10)
        try:
            body = json.dumps({
                "version": 2, "percent": 40,
                "max_error_delta": 0.2, "min_requests": 8,
            }).encode()
            conn.request("POST", "/router/canary", body,
                         {"Content-Type": "application/json"})
            assert conn.getresponse().status == 200
        finally:
            conn.close()

        rc, res = _bench(
            url, tmp_path, "canary",
            "--requests", "80", "--concurrency", "8",
            "--timeout-ms", "30000",
        )
        # No client-visible failures: every candidate 500 was retried
        # onto the incumbent cohort within the request.
        assert res["errors"] == 0 and res["error_rate"] == 0.0, res

        _, canary = _get(host, port, "/router/canary")
        assert canary["state"] == "rolled_back", canary
        assert canary["percent"] == 0.0, canary
        assert "error-rate delta" in canary["rollback_reason"], canary
        assert canary["cohorts"]["candidate"]["errors"] >= 8, canary

        # Drained to 0%: the candidate replica takes not one more
        # request while the incumbent serves all of a follow-up run.
        _, reg = _get(host, port, "/router/replicas")
        cand = next(
            r for r in reg["replicas"]
            if r.get("versions", {}).get("phasenet") == 2
        )
        routed_at_rollback = cand["routed"]
        rc2, res2 = _bench(
            url, tmp_path, "post_rollback",
            "--requests", "24", "--concurrency", "6",
            "--timeout-ms", "30000",
        )
        assert res2["errors"] == 0, res2
        assert res2["by_version"] == {"1": 24}, res2
        _, reg2 = _get(host, port, "/router/replicas")
        cand2 = next(
            r for r in reg2["replicas"]
            if r.get("versions", {}).get("phasenet") == 2
        )
        assert cand2["routed"] == routed_at_rollback, (
            cand2, routed_at_rollback
        )

        # The rollback event: bus counter + flagged trace.
        _, text = _get(host, port, "/metrics")
        assert "router_canary_rollback" in text
        _, idx = _get(host, port, "/traces")
        assert any(
            "canary_rollback" in t["flags"] for t in idx["traces"]
        ), [t["flags"] for t in idx["traces"][:10]]
    finally:
        rc, err = _stop_fleet(proc, timeout=120)
    assert rc == 0, err


def test_overload_sheds_batch_tier_protects_alert_slo(tmp_path):
    """Acceptance: at ~2x the sustainable arrival rate the batch tier is
    shed with the DISTINCT 503 'shed' verdict (Retry-After semantics, not
    the queue-full 429) while the alert tier's p99 passes its SLO gate —
    both measured by the extended bench_serve."""
    proc, host, port = _start_fleet(
        tmp_path,
        env_extra={"SEIST_FAULT_SERVE_SLOW_MS": "150"},
        replicas=1,
        fleet_args=("--router-retries", "0", "--request-timeout-s", "60"),
        replica_args=(
            "--shed-batch-delay-ms", "30",
            "--shed-interactive-delay-ms", "100000",
            "--max-queue", "512",
        ),
    )
    try:
        _wait_probed_ready(host, port, 1)
        url = f"http://{host}:{port}"
        # Sustainable ~= max_batch 4 / (150 ms injected + real forward)
        # <= ~25 rps; batch offers ~4x that. The alert tier offers only
        # 5 rps — far enough under even a contended-CPU capacity that
        # its latency is pure queue-delay, i.e. exactly what shedding
        # the batch tier is supposed to protect.
        results = {}

        def run(tag, *extra):
            results[tag] = _bench(url, tmp_path, tag, *extra)

        alert = threading.Thread(
            target=run,
            args=(
                "alert",
                "--priority", "alert",
                "--arrival-rps", "5",
                "--requests", "60",
                "--concurrency", "64",
                "--timeout-ms", "30000",
                "--slo-p99-ms", "10000",
                # one refused TCP accept under the batch hammering is a
                # client-socket artifact, not a shed/latency failure
                "--max-error-rate", "0.05",
            ),
        )
        batch = threading.Thread(
            target=run,
            args=(
                "batch",
                "--priority", "batch",
                "--arrival-rps", "100",
                "--requests", "600",
                "--concurrency", "64",
                "--timeout-ms", "30000",
            ),
        )
        alert.start()
        batch.start()
        alert.join(timeout=300)
        batch.join(timeout=300)
        rc_alert, res_alert = results["alert"]
        rc_batch, res_batch = results["batch"]
        # Low tier: actually shed, with the shed taxonomy code (not 429).
        assert res_batch["by_error_code"].get("shed", 0) > 0, res_batch
        assert res_batch["by_status"].get("503", 0) > 0, res_batch
        # High tier: NEVER shed, and p99 inside the SLO (the gate's rc).
        assert res_alert["by_error_code"].get("shed", 0) == 0, res_alert
        assert rc_alert == 0, res_alert
        # Replica-side shed counters scrape via the PR 6 bus.
        _, payload = _get(host, port, "/router/replicas")
        replica_url = payload["replicas"][0]["url"]
        rhost, rport = replica_url.split(":")
        _, text = _get(rhost, int(rport), "/metrics?format=prometheus")
        assert "seist_serve_shed" in text, text[:500]
    finally:
        rc, err = _stop_fleet(proc)
    assert rc == 0, err
