"""Training-engine tests: schedule parity vs torch, train step, checkpoint
round-trip, and data-parallel sharding on the 8-device virtual CPU mesh."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import seist_tpu
from seist_tpu import taskspec
from seist_tpu.models import api
from seist_tpu.parallel import make_mesh, replicate, shard_batch
from seist_tpu.train import (
    TrainState,
    build_optimizer,
    create_train_state,
    cyclic_lr,
    jit_multi_step,
    jit_step,
    load_checkpoint,
    make_accum_train_step,
    make_eval_step,
    make_multi_train_step,
    make_train_step,
    restore_into_state,
    save_checkpoint,
)

seist_tpu.load_all()

L = 256


# --------------------------------------------------------------------- schedule
@pytest.mark.parametrize("mode", ["triangular", "triangular2", "exp_range"])
def test_cyclic_lr_matches_torch(mode):
    torch = pytest.importorskip("torch")
    base_lr, max_lr, up, down, gamma = 8e-5, 1e-3, 7, 11, 0.999
    p = torch.nn.Parameter(torch.zeros(1))
    opt = torch.optim.SGD([p], lr=base_lr)
    sched = torch.optim.lr_scheduler.CyclicLR(
        opt,
        base_lr=base_lr,
        max_lr=max_lr,
        step_size_up=up,
        step_size_down=down,
        mode=mode,
        gamma=gamma,
        cycle_momentum=False,
    )
    ours = cyclic_lr(base_lr, max_lr, up, down, mode=mode, gamma=gamma)
    torch_lrs, our_lrs = [], []
    for step in range(50):
        torch_lrs.append(opt.param_groups[0]["lr"])
        our_lrs.append(float(ours(step)))
        opt.step()
        sched.step()
    np.testing.assert_allclose(our_lrs, torch_lrs, rtol=1e-5)


# ------------------------------------------------------------------- train step
def _setup(model_name="phasenet", batch=4):
    model = api.create_model(model_name, in_samples=L)
    variables = api.init_variables(model, in_samples=L, batch_size=batch)
    tx = build_optimizer("adam", 1e-3)
    state = create_train_state(model, variables, tx)
    spec = taskspec.get_task_spec(model_name)
    loss_fn = taskspec.make_loss(model_name)
    return state, spec, loss_fn


def _fake_dpk_batch(rng, batch=4):
    x = rng.standard_normal((batch, L, 3)).astype(np.float32)
    ppk = np.zeros((batch, L), np.float32)
    ppk[:, 64] = 1.0
    spk = np.zeros((batch, L), np.float32)
    spk[:, 128] = 1.0
    non = 1.0 - ppk - spk
    y = np.stack([non, ppk, spk], axis=-1)
    return jnp.asarray(x), jnp.asarray(y)


def test_train_step_reduces_loss(rng):
    state, spec, loss_fn = _setup()
    step = jit_step(make_train_step(spec, loss_fn))
    x, y = _fake_dpk_batch(rng)
    key = jax.random.PRNGKey(0)
    state, loss0, out = step(state, x, y, key)
    assert out.shape == (4, L, 3)
    for _ in range(10):
        state, loss, _ = step(state, x, y, key)
    assert float(loss) < float(loss0)
    assert int(state.step) == 11


def test_multi_train_step_matches_sequential(rng):
    """k scanned micro-steps == k sequential single steps (same per-step
    RNG folding via state.step; see train/step.py make_multi_train_step).
    SGD keeps the comparison linear in the gradients, so the only residue
    is XLA fusion reassociation (Adam's m/sqrt(v) normalization would
    amplify ULP noise to +/-lr on step 1)."""
    k = 3
    batches = [_fake_dpk_batch(rng) for _ in range(k)]
    xs = jnp.stack([b[0] for b in batches])
    ys = jnp.stack([b[1] for b in batches])
    key = jax.random.PRNGKey(7)

    def sgd_setup():
        model = api.create_model("phasenet", in_samples=L)
        variables = api.init_variables(model, in_samples=L, batch_size=4)
        tx = build_optimizer("sgd", 1e-2)
        state = create_train_state(model, variables, tx)
        spec = taskspec.get_task_spec("phasenet")
        return state, spec, taskspec.make_loss("phasenet")

    state, spec, loss_fn = sgd_setup()
    single = jax.jit(make_train_step(spec, loss_fn))
    losses = []
    for i in range(k):
        state, loss, _ = single(state, xs[i], ys[i], key)
        losses.append(float(loss))

    state2, _, _ = sgd_setup()
    multi = jax.jit(make_multi_train_step(spec, loss_fn, steps_per_call=k))
    state2, mean_loss, _ = multi(state2, xs, ys, key)

    assert int(state2.step) == k
    np.testing.assert_allclose(float(mean_loss), np.mean(losses), rtol=1e-6)
    for a, b in zip(
        jax.tree.leaves(state.params), jax.tree.leaves(state2.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        )


def test_train_step_updates_batch_stats(rng):
    state, spec, loss_fn = _setup()
    step = jit_step(make_train_step(spec, loss_fn), donate_state=False)
    x, y = _fake_dpk_batch(rng)
    new_state, _, _ = step(state, x, y, jax.random.PRNGKey(0))
    before = jax.tree_util.tree_leaves(state.batch_stats)
    after = jax.tree_util.tree_leaves(new_state.batch_stats)
    assert any(
        not np.allclose(np.asarray(b), np.asarray(a)) for b, a in zip(before, after)
    )


def test_eval_step_is_deterministic(rng):
    state, spec, loss_fn = _setup()
    estep = jax.jit(make_eval_step(spec, loss_fn))
    x, y = _fake_dpk_batch(rng)
    mask = np.ones(x.shape[0], dtype=np.float32)
    l1, o1 = estep(state, x, y, mask)
    l2, o2 = estep(state, x, y, mask)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    assert float(l1) == float(l2)


def test_train_step_with_transforms(rng):
    # baz_network uses targets->(cos,sin) transform + CombinationLoss.
    state, spec, loss_fn = _setup("baz_network", batch=2)
    step = jit_step(make_train_step(spec, loss_fn))
    x = jnp.asarray(rng.standard_normal((2, L, 3)), jnp.float32)
    baz = jnp.asarray([[45.0], [270.0]], jnp.float32)
    state, loss, outputs = step(state, x, baz, jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))


# ------------------------------------------------------- scoped L1 (eqt hooks)
def test_eqt_l1_mask_scopes_to_ref_hooked_convs():
    """l1_param_mask selects exactly the encoder ConvBlock / decoder
    Upsampling convs the reference hooks (ref eqtransformer.py:43-51,
    388-396) — not LSTM/attention/ff/resconv params."""
    from seist_tpu.models.eqtransformer import l1_param_mask

    model = api.create_model("eqtransformer", in_samples=L)
    shapes = api.param_shapes(model, in_samples=L)["params"]
    kmask = l1_param_mask(shapes, "kernel")
    flat = jax.tree_util.tree_leaves_with_path(kmask)
    selected = {
        "/".join(str(getattr(k, "key", k)) for k in p)
        for p, v in flat
        if v
    }
    assert any(s.startswith("encoder/conv0/") for s in selected)
    assert any(s.startswith("decoder0/up0/") for s in selected)
    assert all("bilstm" not in s and "transformer" not in s for s in selected)
    assert all("resconv" not in s and "conv_out" not in s for s in selected)
    assert all(s.endswith("/kernel") for s in selected)
    # 7 encoder convs + 3 decoders x 7 ups = 28 hooked kernels.
    assert len(selected) == 28, sorted(selected)


def test_build_optimizer_applies_scoped_l1():
    from seist_tpu.models.eqtransformer import l1_param_mask

    model = api.create_model("eqtransformer", in_samples=L)
    variables = api.init_variables(model, in_samples=L, batch_size=1)
    params = variables["params"]
    alpha = 0.125
    tx0 = build_optimizer("sgd", 1.0, momentum=0.0)
    tx1 = build_optimizer(
        "sgd", 1.0, momentum=0.0,
        l1_kernel_alpha=alpha, l1_mask_fn=l1_param_mask,
    )
    grads = jax.tree.map(jnp.zeros_like, params)
    u0, _ = tx0.update(grads, tx0.init(params), params)
    u1, _ = tx1.update(grads, tx1.init(params), params)
    kmask = l1_param_mask(params, "kernel")
    diffs = jax.tree.map(
        lambda a, b, m, p: np.allclose(
            np.asarray(b - a), (alpha if m else 0.0) * -np.sign(np.asarray(p))
        ),
        u0, u1, kmask, params,
    )
    assert all(jax.tree_util.tree_leaves(diffs))


# -------------------------------------------------------------- mixed precision
@pytest.mark.parametrize(
    "model_name",
    [
        "phasenet",
        pytest.param("seist_s_dpk", marks=pytest.mark.slow),  # 2 heavy compiles
    ],
)
def test_bf16_train_step_tracks_fp32(rng, model_name):
    """bf16 compute dtype: loss close to fp32, params/stats stay fp32, and
    several steps still reduce the loss (VERDICT r1 #4)."""
    x, y = _fake_dpk_batch(rng)
    key = jax.random.PRNGKey(0)

    state32, spec, loss_fn = _setup(model_name)
    state16, _, _ = _setup(model_name)
    step32 = jit_step(make_train_step(spec, loss_fn), donate_state=False)
    step16 = jit_step(
        make_train_step(spec, loss_fn, compute_dtype="bf16"),
        donate_state=False,
    )

    s32, l32, o32 = step32(state32, x, y, key)
    s16, l16, o16 = step16(state16, x, y, key)
    # Outputs come back fp32 regardless of compute dtype.
    assert o16.dtype == jnp.float32
    # Same init => loss matches to bf16 tolerance.
    np.testing.assert_allclose(float(l16), float(l32), rtol=0.05, atol=5e-3)
    # Master params / optimizer / BN stats remain fp32.
    for leaf in jax.tree_util.tree_leaves(s16.params):
        assert leaf.dtype == jnp.float32
    for leaf in jax.tree_util.tree_leaves(s16.batch_stats):
        assert leaf.dtype == jnp.float32

    loss0 = float(l16)
    for _ in range(10):
        s16, l16, _ = step16(s16, x, y, key)
    assert float(l16) < loss0


def test_bf16_eval_step_close_to_fp32(rng):
    state, spec, loss_fn = _setup("seist_s_dpk")
    x, y = _fake_dpk_batch(rng)
    mask = np.ones(x.shape[0], dtype=np.float32)
    e32 = jax.jit(make_eval_step(spec, loss_fn))
    e16 = jax.jit(make_eval_step(spec, loss_fn, compute_dtype="bf16"))
    l32, o32 = e32(state, x, y, mask)
    l16, o16 = e16(state, x, y, mask)
    assert o16.dtype == jnp.float32
    np.testing.assert_allclose(float(l16), float(l32), rtol=0.05, atol=5e-3)
    # dpk outputs are probabilities; bf16 forward should stay within a few
    # probability points of fp32.
    assert float(jnp.abs(o16 - o32).max()) < 0.05


def test_resolve_dtype():
    from seist_tpu.train.precision import resolve_dtype

    assert resolve_dtype(None) is None
    assert resolve_dtype("fp32") is None
    assert resolve_dtype("bf16") == jnp.bfloat16
    with pytest.raises(ValueError):
        resolve_dtype("fp16")


# ------------------------------------------------------------------ parallelism
def test_dp_sharded_step_matches_single_device(rng):
    assert jax.device_count() >= 8, "conftest must provide 8 virtual devices"
    # SGD (linear in grads) so the comparison tests sharding semantics, not
    # Adam's g/sqrt(v) amplification of float reassociation noise.
    model = api.create_model("phasenet", in_samples=L)
    variables = api.init_variables(model, in_samples=L, batch_size=8)
    state = create_train_state(model, variables, build_optimizer("sgd", 1e-2))
    spec = taskspec.get_task_spec("phasenet")
    loss_fn = taskspec.make_loss("phasenet")
    x, y = _fake_dpk_batch(rng, batch=8)
    key = jax.random.PRNGKey(0)

    single = jit_step(make_train_step(spec, loss_fn), donate_state=False)
    s1, loss1, _ = single(state, x, y, key)

    mesh = make_mesh(data=8)
    state_r = replicate(mesh, state)
    xb, yb = shard_batch(mesh, (x, y))
    sharded = jit_step(make_train_step(spec, loss_fn), mesh=mesh, donate_state=False)
    s2, loss2, _ = sharded(state_r, xb, yb, key)

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(s1.params), jax.tree_util.tree_leaves(s2.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_multi_step_sharded_matches_single_device(rng):
    """jit_multi_step shards the BATCH axis (axis 1), not the micro-step
    axis: a dp-sharded 2-step call must equal the single-device one."""
    assert jax.device_count() >= 8
    model = api.create_model("phasenet", in_samples=L)
    variables = api.init_variables(model, in_samples=L, batch_size=8)
    state = create_train_state(model, variables, build_optimizer("sgd", 1e-2))
    spec = taskspec.get_task_spec("phasenet")
    loss_fn = taskspec.make_loss("phasenet")
    batches = [_fake_dpk_batch(rng, batch=8) for _ in range(2)]
    xs = jnp.stack([b[0] for b in batches])
    ys = jnp.stack([b[1] for b in batches])
    key = jax.random.PRNGKey(0)
    multi = make_multi_train_step(spec, loss_fn, steps_per_call=2)

    s1, loss1, _ = jit_multi_step(multi, donate_state=False)(state, xs, ys, key)

    mesh = make_mesh(data=8)
    state_r = replicate(mesh, state)
    from seist_tpu.parallel import shard_stacked_batch

    xb, yb = shard_stacked_batch(mesh, (xs, ys))
    assert xb.sharding.spec == (None, "data")
    s2, loss2, _ = jit_multi_step(multi, mesh=mesh, donate_state=False)(
        state_r, xb, yb, key
    )

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(s1.params), jax.tree_util.tree_leaves(s2.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_mesh_axes():
    mesh = make_mesh()
    assert mesh.axis_names == ("data", "model", "seq")
    assert mesh.devices.size == jax.device_count()


# ------------------------------------------------------------------- checkpoint
def test_checkpoint_roundtrip(tmp_path, rng):
    state, spec, loss_fn = _setup()
    step = jit_step(make_train_step(spec, loss_fn), donate_state=False)
    x, y = _fake_dpk_batch(rng)
    state, loss, _ = step(state, x, y, jax.random.PRNGKey(0))

    path = save_checkpoint(str(tmp_path / "ckpts"), state, epoch=3, loss=float(loss))
    fresh, _, _ = _setup()
    restored = load_checkpoint(path, fresh)
    assert restored["meta"]["epoch"] == 3
    resumed = restore_into_state(fresh, restored)
    assert int(resumed.step) == 1
    for a, b in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(resumed.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ----------------------------------------------------------------- l1 decay
def test_l1_sign_decay_adds_sign_to_grads():
    import optax
    from seist_tpu.train import l1_sign_decay

    params = {"a": jnp.asarray([1.0, -2.0, 0.0]), "b": jnp.asarray([3.0])}
    grads = {"a": jnp.asarray([0.1, 0.1, 0.1]), "b": jnp.asarray([0.1])}
    tx = l1_sign_decay(0.5, mask=lambda p: {"a": True, "b": False})
    updates, _ = tx.update(grads, tx.init(params), params)
    np.testing.assert_allclose(np.asarray(updates["a"]), [0.6, -0.4, 0.1])
    np.testing.assert_allclose(np.asarray(updates["b"]), [0.1])


def test_jit_eval_step_preserves_state(rng):
    from seist_tpu.train import jit_eval_step

    state, spec, loss_fn = _setup()
    estep = jit_eval_step(make_eval_step(spec, loss_fn))
    x, y = _fake_dpk_batch(rng)
    estep(state, x, y, np.ones(x.shape[0], dtype=np.float32))
    # state must remain usable (no donation)
    tstep = jit_step(make_train_step(spec, loss_fn), donate_state=False)
    tstep(state, x, y, jax.random.PRNGKey(0))


# ---------------------------------------------------------- grad accumulation
def test_accum_step_matches_big_batch(rng):
    """k accumulated micro-batch gradients == ONE big-batch gradient, for a
    BN-free model with a mean-reduced loss (make_accum_train_step's exact
    regime — with BatchNorm the stats couple samples, so accumulation
    matches small-batch BN semantics instead, covered by the smoke test
    below)."""
    from flax import linen as nn

    k, b = 4, 2

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            h = nn.gelu(nn.Dense(8)(x))
            return jax.nn.softmax(nn.Dense(3)(h), axis=-1)

    model = Tiny()
    variables = model.init(jax.random.PRNGKey(3), jnp.zeros((1, L, 3)))
    spec = taskspec.get_task_spec("phasenet")  # CE on (N, L, 3) probs
    loss_fn = taskspec.make_loss("phasenet")
    xs, ys = [], []
    for _ in range(k):
        x, y = _fake_dpk_batch(rng, batch=b)
        xs.append(x)
        ys.append(y)
    key = jax.random.PRNGKey(0)

    def fresh_state():
        return create_train_state(
            model, {"params": variables["params"]}, build_optimizer("sgd", 1e-2)
        )

    big = jax.jit(make_train_step(spec, loss_fn))
    s1, loss1, _ = big(
        fresh_state(), jnp.concatenate(xs), jnp.concatenate(ys), key
    )

    accum = jax.jit(make_accum_train_step(spec, loss_fn, accum_steps=k))
    s2, loss2, _ = accum(fresh_state(), jnp.stack(xs), jnp.stack(ys), key)

    assert int(s2.step) == 1  # ONE optimizer update
    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-6)
    for a, c in zip(
        jax.tree_util.tree_leaves(s1.params), jax.tree_util.tree_leaves(s2.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(c), rtol=1e-5, atol=1e-7
        )


def test_accum_step_bn_smoke(rng):
    """With a BatchNorm model: accumulation chains running stats through the
    micro-steps (as k separate forwards) and applies one update."""
    state, spec, loss_fn = _setup()
    stats0 = jax.tree_util.tree_leaves(state.batch_stats)
    batches = [_fake_dpk_batch(rng) for _ in range(2)]
    xs = jnp.stack([b[0] for b in batches])
    ys = jnp.stack([b[1] for b in batches])
    accum = jax.jit(make_accum_train_step(spec, loss_fn, accum_steps=2))
    state, loss, _ = accum(state, xs, ys, jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))
    assert int(state.step) == 1
    changed = any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(stats0, jax.tree_util.tree_leaves(state.batch_stats))
    )
    assert changed


def test_accum_one_is_plain_step():
    spec = taskspec.get_task_spec("phasenet")
    loss_fn = taskspec.make_loss("phasenet")
    fn = make_accum_train_step(spec, loss_fn, accum_steps=1)
    # accum_steps=1 falls back to the plain single-batch step signature.
    assert fn.__name__ == "train_step"


def test_accum_step_sharded_matches_single_device(rng):
    """jit_multi_step's stacked-batch sharding (P(None, 'data')) applies to
    the accumulation step too: a dp-sharded accumulated update must equal
    the single-device one."""
    assert jax.device_count() >= 8
    model = api.create_model("phasenet", in_samples=L)
    variables = api.init_variables(model, in_samples=L, batch_size=8)
    state = create_train_state(model, variables, build_optimizer("sgd", 1e-2))
    spec = taskspec.get_task_spec("phasenet")
    loss_fn = taskspec.make_loss("phasenet")
    batches = [_fake_dpk_batch(rng, batch=8) for _ in range(2)]
    xs = jnp.stack([b[0] for b in batches])
    ys = jnp.stack([b[1] for b in batches])
    key = jax.random.PRNGKey(0)
    accum = make_accum_train_step(spec, loss_fn, accum_steps=2)

    s1, loss1, _ = jit_multi_step(accum, donate_state=False)(state, xs, ys, key)

    mesh = make_mesh(data=8)
    state_r = replicate(mesh, state)
    from seist_tpu.parallel import shard_stacked_batch

    xb, yb = shard_stacked_batch(mesh, (xs, ys))
    s2, loss2, _ = jit_multi_step(accum, mesh=mesh, donate_state=False)(
        state_r, xb, yb, key
    )

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(s1.params), jax.tree_util.tree_leaves(s2.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)
