"""Fused pooled-KV attention kernel == einsum attention (Pallas interpreter
on CPU; the same kernel compiles for TPU)."""

import jax
import numpy as np
import pytest

from seist_tpu.ops.pallas_attention import (
    _einsum_attention,
    fused_pooled_attention,
)


def _qkv(rng, n=2, l=64, m=16, h=2, e=8):
    q = rng.normal(size=(n, l, h, e)).astype(np.float32)
    k = rng.normal(size=(n, m, h, e)).astype(np.float32)
    v = rng.normal(size=(n, m, h, e)).astype(np.float32)
    return q, k, v


@pytest.mark.parametrize("h", [1, 2, 3])
def test_forward_matches_einsum(rng, h):
    # h=3, e=8 is the real SeisT stage-0 attention shape; the in-kernel
    # head unroll slices the folded (L, H*E) feature axis per head.
    q, k, v = _qkv(rng, h=h)
    want = np.asarray(_einsum_attention(q, k, v, 1.0 / np.sqrt(q.shape[-1])))
    got = np.asarray(fused_pooled_attention(q, k, v, interpret=True))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_forward_pooled_shapes(rng):
    # L != M (pooled K/V) and E not a lane multiple.
    q, k, v = _qkv(rng, l=128, m=16, e=24)
    want = np.asarray(_einsum_attention(q, k, v, 1.0 / np.sqrt(24)))
    got = np.asarray(fused_pooled_attention(q, k, v, interpret=True))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_extreme_logits(rng):
    q, k, v = _qkv(rng)
    q *= 40.0
    want = np.asarray(_einsum_attention(q, k, v, 1.0 / np.sqrt(q.shape[-1])))
    got = np.asarray(fused_pooled_attention(q, k, v, interpret=True))
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_custom_vjp_matches_einsum_grads(rng):
    q, k, v = _qkv(rng, n=1, l=32, m=8)
    scale = 1.0 / np.sqrt(q.shape[-1])

    def loss_fused(q, k, v):
        return (fused_pooled_attention(q, k, v, interpret=True) ** 2).sum()

    def loss_einsum(q, k, v):
        return (_einsum_attention(q, k, v, scale) ** 2).sum()

    gf = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
    ge = jax.grad(loss_einsum, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, ge, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
            err_msg=f"d{name}",
        )


def test_cpu_fallback_is_einsum(rng):
    # Without interpret/force on CPU the public API silently uses einsum.
    q, k, v = _qkv(rng)
    got = np.asarray(fused_pooled_attention(q, k, v))
    want = np.asarray(_einsum_attention(q, k, v, 1.0 / np.sqrt(q.shape[-1])))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_kernel_compile_failure_falls_back(rng, monkeypatch, caplog):
    # If Mosaic rejects the kernel (simulated: pretend we're on TPU so the
    # health probe actually tries to compile the Pallas TPU kernel — which
    # genuinely fails on this CPU host, exactly like a Mosaic rejection),
    # the public API must log once and return the einsum result instead of
    # raising inside the enclosing train-step jit.
    import logging

    from seist_tpu.ops import pallas_attention as pa

    monkeypatch.setattr(pa, "_on_tpu", lambda: True)
    monkeypatch.setattr(pa, "_KERNEL_STATUS", {})
    monkeypatch.setattr(pa, "_FALLBACK_LOGGED", False)
    q, k, v = _qkv(rng)
    scale = 1.0 / np.sqrt(q.shape[-1])
    with caplog.at_level(logging.WARNING, "seist_tpu.pallas_attention"):
        got = np.asarray(fused_pooled_attention(q, k, v, scale))
        again = np.asarray(fused_pooled_attention(q, k, v, scale))
    want = np.asarray(_einsum_attention(q, k, v, scale))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(again, want, rtol=1e-6, atol=1e-6)
    fallback_logs = [
        r for r in caplog.records if "falling back" in r.getMessage()
    ]
    assert len(fallback_logs) == 1  # logged once, cached after
    assert pa._KERNEL_STATUS  # signature recorded as unusable


def test_kernel_failure_fallback_inside_jit(rng, monkeypatch):
    # The probe runs eagerly even when the call site is being traced under
    # an outer jit (the train-step case): tracing must complete and the
    # jitted function must produce the einsum result.
    from seist_tpu.ops import pallas_attention as pa

    monkeypatch.setattr(pa, "_on_tpu", lambda: True)
    monkeypatch.setattr(pa, "_KERNEL_STATUS", {})
    monkeypatch.setattr(pa, "_FALLBACK_LOGGED", False)
    q, k, v = _qkv(rng)
    scale = 1.0 / np.sqrt(q.shape[-1])
    got = np.asarray(
        jax.jit(lambda q, k, v: fused_pooled_attention(q, k, v, scale))(
            q, k, v
        )
    )
    want = np.asarray(_einsum_attention(q, k, v, scale))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_probe_aot_compiles_under_outer_jit(rng, monkeypatch):
    # The probe must escape an ambient jit trace and genuinely compile —
    # otherwise tracer leakage would mark a GOOD kernel unusable and
    # silently einsum the default TPU train path. The implementation
    # escape is AOT .lower().compile() from ShapeDtypeStructs (the old
    # ensure_compile_time_eval escape broke under the 2026 JAX trace
    # internals: constants were hoisted out of the kernel trace as
    # captured consts, then pl.program_id had no eval rule — observed on
    # live TPU 2026-08-02). This asserts that mechanism works from inside
    # an outer jit trace.
    import jax.numpy as jnp

    from seist_tpu.ops import pallas_attention as pa

    monkeypatch.setattr(pa, "_on_tpu", lambda: True)
    monkeypatch.setattr(pa, "_KERNEL_STATUS", {})
    seen = {}

    def fake_probe(l, m, he, heads, rate, dtype):
        # Mirror the real probe's AOT escape: abstract inputs, explicit
        # lower+compile — must work regardless of the ambient trace.
        x = jax.ShapeDtypeStruct((2, 2), jnp.float32)
        jax.jit(lambda a: a @ a).lower(x).compile()
        seen["compiled"] = True

    monkeypatch.setattr(pa, "_probe_kernel", fake_probe)
    # Stub the kernel so the outer jit can compile on CPU after the probe
    # reports the (pretend) kernel healthy.
    monkeypatch.setattr(
        pa, "_fused", lambda q3, k3, v3, seed, *a: q3
    )
    q, k, v = _qkv(rng)
    jax.jit(lambda q, k, v: fused_pooled_attention(q, k, v, 1.0))(q, k, v)
    assert seen.get("compiled")
    assert list(pa._KERNEL_STATUS.values()) == [True]
    # (The REAL probe body can only Mosaic-lower on a TPU backend — CPU
    # pallas_call supports interpret mode only — so its end-to-end health
    # is asserted on-chip by tools/check_attn_tpu.py instead.)


def test_transient_probe_error_not_cached(rng, monkeypatch):
    # A RESOURCE_EXHAUSTED probe failure says nothing about Mosaic's ability
    # to compile the kernel (HBM may simply be full of train state). It must
    # fall back for the call but NOT poison the per-process cache.
    from seist_tpu.ops import pallas_attention as pa

    monkeypatch.setattr(pa, "_KERNEL_STATUS", {})
    monkeypatch.setattr(pa, "_KERNEL_EVENTS", {})
    monkeypatch.setattr(pa, "_TRANSIENT_COUNTS", {})
    calls = {"n": 0}

    def flaky_probe(*a):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory on device")

    monkeypatch.setattr(pa, "_probe_kernel", flaky_probe)
    assert pa._kernel_usable(64, 16, 16, 2, 0.0, np.float32) is False
    assert pa._KERNEL_STATUS == {}  # transient -> no retry-cache entry
    # ...but the fallback is still OBSERVABLE (the trace that hit it baked
    # einsum in permanently): summary must not say "unprobed".
    s = pa.kernel_status_summary()
    assert s["overall"] == "einsum-fallback"
    assert "transient" in next(iter(s["signatures"].values()))
    assert pa._kernel_usable(64, 16, 16, 2, 0.0, np.float32) is True
    assert list(pa._KERNEL_STATUS.values()) == [True]
    # The re-probe helps future traces, but the earlier executable still
    # runs einsum — the summary must keep that history (and stay degraded)
    # rather than claim a clean fused run.
    s = pa.kernel_status_summary()
    assert s["overall"] == "einsum-fallback"
    sig = next(iter(s["signatures"].values()))
    assert sig.startswith("fused (re-probed ok") and "transient" in sig
    # A genuine Mosaic rejection IS cached.
    monkeypatch.setattr(
        pa,
        "_probe_kernel",
        lambda *a: (_ for _ in ()).throw(ValueError("Mosaic lowering failed")),
    )
    monkeypatch.setattr(pa, "_KERNEL_STATUS", {})
    monkeypatch.setattr(pa, "_KERNEL_EVENTS", {})
    assert pa._kernel_usable(64, 16, 16, 2, 0.0, np.float32) is False
    assert list(pa._KERNEL_STATUS.values()) == [False]
    assert pa.kernel_status_summary()["overall"] == "einsum-fallback"


def test_vmem_exhaustion_is_permanent(rng, monkeypatch):
    # RESOURCE_EXHAUSTED from a VMEM/scratch overflow is deterministic for
    # the shape — it must be cached as unusable, not re-probed forever
    # (advisor r4).
    from seist_tpu.ops import pallas_attention as pa

    monkeypatch.setattr(pa, "_KERNEL_STATUS", {})
    monkeypatch.setattr(pa, "_KERNEL_EVENTS", {})
    monkeypatch.setattr(pa, "_TRANSIENT_COUNTS", {})
    monkeypatch.setattr(pa, "_FALLBACK_LOGGED", False)

    def vmem_probe(*a):
        raise RuntimeError(
            "RESOURCE_EXHAUSTED: Ran out of memory in memory space vmem "
            "while allocating scratch"
        )

    monkeypatch.setattr(pa, "_probe_kernel", vmem_probe)
    assert pa._kernel_usable(64, 16, 16, 2, 0.0, np.float32) is False
    assert list(pa._KERNEL_STATUS.values()) == [False]  # cached, permanent


def test_transient_probe_cap_caches_fallback(rng, monkeypatch):
    # Genuinely-transient failures stop being re-probed after
    # _MAX_TRANSIENT_PROBES traces: cached unusable, history kept.
    from seist_tpu.ops import pallas_attention as pa

    monkeypatch.setattr(pa, "_KERNEL_STATUS", {})
    monkeypatch.setattr(pa, "_KERNEL_EVENTS", {})
    monkeypatch.setattr(pa, "_TRANSIENT_COUNTS", {})
    calls = {"n": 0}

    def always_oom(*a):
        calls["n"] += 1
        raise RuntimeError("RESOURCE_EXHAUSTED: out of memory on device")

    monkeypatch.setattr(pa, "_probe_kernel", always_oom)
    for _ in range(pa._MAX_TRANSIENT_PROBES):
        assert pa._kernel_usable(64, 16, 16, 2, 0.0, np.float32) is False
    assert calls["n"] == pa._MAX_TRANSIENT_PROBES
    assert list(pa._KERNEL_STATUS.values()) == [False]
    # No further probe compiles once capped.
    assert pa._kernel_usable(64, 16, 16, 2, 0.0, np.float32) is False
    assert calls["n"] == pa._MAX_TRANSIENT_PROBES
    sig = next(iter(pa.kernel_status_summary()["signatures"].values()))
    assert "re-probe cap" in sig and "transient" in sig


def test_kernel_status_summary(monkeypatch):
    # VERDICT r3 #4: the probe outcome must be machine-readable for bench.py
    # and the worker startup log.
    from seist_tpu.ops import pallas_attention as pa

    monkeypatch.setattr(pa, "_KERNEL_EVENTS", {})
    assert pa.kernel_status_summary()["overall"] == "unprobed"
    monkeypatch.setattr(
        pa,
        "_KERNEL_EVENTS",
        {(512, 16, 96, 8, False, "bfloat16"): "fused"},
    )
    s = pa.kernel_status_summary()
    assert s["overall"] == "fused"
    assert s["signatures"] == {"L512/M16/HE96/H8/drop=False/bfloat16": "fused"}
    monkeypatch.setattr(
        pa,
        "_KERNEL_EVENTS",
        {
            (512, 16, 96, 8, False, "bfloat16"): "fused",
            (512, 16, 96, 8, True, "bfloat16"): "einsum-fallback",
        },
    )
    s = pa.kernel_status_summary()
    assert s["overall"] == "einsum-fallback"
    assert s["signatures"]["L512/M16/HE96/H8/drop=True/bfloat16"] == (
        "einsum-fallback"
    )


def test_env_fused_bypasses_probe(rng, monkeypatch):
    # SEIST_ATTN_IMPL=fused must skip the health probe and surface the raw
    # kernel error (parity tooling wants failures loud).
    from seist_tpu.ops import pallas_attention as pa

    monkeypatch.setattr(pa, "_on_tpu", lambda: True)
    monkeypatch.setattr(pa, "_KERNEL_STATUS", {})
    monkeypatch.setenv("SEIST_ATTN_IMPL", "fused")
    q, k, v = _qkv(rng)
    with pytest.raises(Exception):
        np.asarray(fused_pooled_attention(q, k, v))


# -- in-kernel dropout -------------------------------------------------------


import jax.numpy as jnp


def _seed(v=1234):
    return jnp.asarray([v], jnp.int32)


def test_dropout_zero_rate_is_noop(rng):
    q, k, v = _qkv(rng)
    base = np.asarray(fused_pooled_attention(q, k, v, interpret=True))
    got = np.asarray(
        fused_pooled_attention(
            q, k, v, dropout_rate=0.0, dropout_seed=_seed(), interpret=True
        )
    )
    np.testing.assert_array_equal(got, base)


def test_dropout_mask_statistics(rng):
    # With h=1 and v = identity (M == E), the output IS the dropped
    # probability matrix — check drop fraction and survivor scaling.
    n, l, m, rate = 2, 128, 16, 0.25
    q = rng.normal(size=(n, l, 1, m)).astype(np.float32)
    k = rng.normal(size=(n, m, 1, m)).astype(np.float32)
    v = np.eye(m, dtype=np.float32)[None, :, None, :].repeat(n, axis=0)
    p = np.asarray(fused_pooled_attention(q, k, v, interpret=True))
    pd = np.asarray(
        fused_pooled_attention(
            q, k, v, dropout_rate=rate, dropout_seed=_seed(), interpret=True
        )
    )
    dropped = pd == 0.0
    frac = dropped.mean()
    assert abs(frac - rate) < 0.02, frac
    surv = ~dropped
    np.testing.assert_allclose(
        pd[surv], p[surv] / (1.0 - rate), rtol=1e-5, atol=1e-6
    )


def test_dropout_deterministic_per_seed(rng):
    q, k, v = _qkv(rng)
    a = np.asarray(
        fused_pooled_attention(
            q, k, v, dropout_rate=0.2, dropout_seed=_seed(7), interpret=True
        )
    )
    b = np.asarray(
        fused_pooled_attention(
            q, k, v, dropout_rate=0.2, dropout_seed=_seed(7), interpret=True
        )
    )
    c = np.asarray(
        fused_pooled_attention(
            q, k, v, dropout_rate=0.2, dropout_seed=_seed(8), interpret=True
        )
    )
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)


@pytest.mark.parametrize("h", [1, 3])
def test_dropout_kernel_matches_einsum_fallback(rng, h):
    # Kernel (interpret) and XLA fallback share the counter-based PRNG, so
    # outputs agree including which entries were dropped — per head: the
    # kernel's in-kernel pid is program_id*H + h, matching the fallback's
    # flattened (n, h) order.
    q, k, v = _qkv(rng, l=32, m=8, h=h)
    scale = 1.0 / np.sqrt(q.shape[-1])
    want = np.asarray(
        _einsum_attention(q, k, v, scale, dropout_rate=0.3, dropout_seed=_seed())
    )
    got = np.asarray(
        fused_pooled_attention(
            q, k, v, scale, dropout_rate=0.3, dropout_seed=_seed(),
            interpret=True,
        )
    )
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("h", [1, 3])
def test_dropout_custom_vjp_matches_einsum_grads(rng, h):
    q, k, v = _qkv(rng, n=1, l=32, m=8, h=h)
    scale = 1.0 / np.sqrt(q.shape[-1])

    def loss_fused(q, k, v):
        o = fused_pooled_attention(
            q, k, v, scale, dropout_rate=0.3, dropout_seed=_seed(),
            interpret=True,
        )
        return (o ** 2).sum()

    def loss_einsum(q, k, v):
        o = _einsum_attention(
            q, k, v, scale, dropout_rate=0.3, dropout_seed=_seed()
        )
        return (o ** 2).sum()

    gf = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
    ge = jax.grad(loss_einsum, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, ge, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
            err_msg=f"d{name}",
        )


# --------------------------------------------------- env surface (ISSUE 10)
class TestEnvSurface:
    """SEIST_ATTN_IMPL routing + kernel_status_summary() shape — the env
    contract worker.py/bench.py rely on, previously exercised only
    indirectly through worker runs."""

    def test_unknown_impl_value_rejected(self, rng, monkeypatch):
        monkeypatch.setenv("SEIST_ATTN_IMPL", "turbo")
        q, k, v = _qkv(rng)
        with pytest.raises(ValueError, match="unknown SEIST_ATTN_IMPL"):
            fused_pooled_attention(q, k, v)

    def test_einsum_forces_xla_path(self, rng, monkeypatch):
        # =einsum must bypass the kernel entirely, even where the kernel
        # would be chosen: a booby-trapped _fused proves it is not called.
        from seist_tpu.ops import pallas_attention as pa

        monkeypatch.setenv("SEIST_ATTN_IMPL", "einsum")
        monkeypatch.setattr(
            pa, "_fused",
            lambda *a, **k: (_ for _ in ()).throw(
                AssertionError("kernel path taken under =einsum")
            ),
        )
        q, k, v = _qkv(rng)
        want = np.asarray(
            _einsum_attention(q, k, v, 1.0 / np.sqrt(q.shape[-1]))
        )
        got = np.asarray(fused_pooled_attention(q, k, v))
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    def test_einsum_yields_to_explicit_kernel_request(self, rng, monkeypatch):
        # Parity tooling's interpret/force beats the ambient env var.
        from seist_tpu.ops import pallas_attention as pa

        monkeypatch.setenv("SEIST_ATTN_IMPL", "einsum")
        called = {}

        def spy(q3, k3, v3, seed, scale, rate, h, interpret):
            called["interpret"] = interpret
            return pa._einsum_attention(
                q3.reshape(q3.shape[0], q3.shape[1], h, -1),
                k3.reshape(k3.shape[0], k3.shape[1], h, -1),
                v3.reshape(v3.shape[0], v3.shape[1], h, -1),
                scale,
            ).reshape(q3.shape)

        monkeypatch.setattr(pa, "_fused", spy)
        q, k, v = _qkv(rng)
        fused_pooled_attention(q, k, v, interpret=True)
        assert called == {"interpret": True}

    def test_fused_forces_kernel_skipping_probe(self, rng, monkeypatch):
        # =fused must reach _fused without consulting the health probe
        # (a Mosaic rejection is supposed to surface raw).
        from seist_tpu.ops import pallas_attention as pa

        monkeypatch.setenv("SEIST_ATTN_IMPL", "fused")
        monkeypatch.setattr(pa, "_on_tpu", lambda: True)
        monkeypatch.setattr(
            pa, "_kernel_usable",
            lambda *a, **k: (_ for _ in ()).throw(
                AssertionError("probe consulted under =fused")
            ),
        )
        called = {}

        def spy(q3, k3, v3, seed, scale, rate, h, interpret):
            called["hit"] = True
            return q3

        monkeypatch.setattr(pa, "_fused", spy)
        q, k, v = _qkv(rng)
        out = fused_pooled_attention(q, k, v)
        assert called == {"hit": True}
        assert out.shape == q.shape

    def test_kernel_status_summary_unprobed(self, monkeypatch):
        from seist_tpu.ops import pallas_attention as pa

        monkeypatch.setattr(pa, "_KERNEL_EVENTS", {})
        assert pa.kernel_status_summary() == {
            "overall": "unprobed", "signatures": {},
        }

    def test_kernel_status_summary_shape_and_overall(self, monkeypatch):
        from seist_tpu.ops import pallas_attention as pa

        key_a = (512, 16, 96, 8, False, "bf16")
        key_b = (1024, 128, 24, 3, True, "f32")
        monkeypatch.setattr(
            pa, "_KERNEL_EVENTS", {key_a: "fused", key_b: "fused"}
        )
        s = pa.kernel_status_summary()
        assert set(s) == {"overall", "signatures"}
        assert s["overall"] == "fused"
        assert s["signatures"] == {
            "L512/M16/HE96/H8/drop=False/bf16": "fused",
            "L1024/M128/HE24/H3/drop=True/f32": "fused",
        }
        # ANY non-fused signature (including a transient-tagged one)
        # degrades the overall verdict — bench's `degraded` flag hangs
        # off this exact contract.
        monkeypatch.setattr(
            pa,
            "_KERNEL_EVENTS",
            {key_a: "fused",
             key_b: "einsum-fallback (transient RESOURCE_EXHAUSTED)"},
        )
        s = pa.kernel_status_summary()
        assert s["overall"] == "einsum-fallback"
        assert "transient" in s["signatures"]["L1024/M128/HE24/H3/drop=True/f32"]
