"""Stream chaos lane (`make stream-chaos`): the digital twin's mainshock
scenario replayed against a REAL 3-replica fleet (tools/twin_replica.py
behind tools/supervise_fleet.py) with a SIGKILL injected on the
station-heavy replica mid-mainshock — the ISSUE 17 acceptance run.

The twin EXPORTS its arrival schedule to a file and this lane drives the
fleet from that file, so the in-process twin gates and the chaos run
argue about the same deterministic replay. The gates:

* ZERO missed mainshock alerts: after journal restore on the survivors,
  the union of stream-response alerts and the fleet's alert WALs
  contains the mainshock (consumer model: dedup on ``alert_id``, group
  distinct events on the cell+bucket id prefix).
* BOUNDED duplicates: failover replay may re-emit, but no single
  ``alert_id`` is emitted more than a handful of times — the consumer
  double-counts nothing.
* The kill is VISIBLE end to end: the fault stamp exists, the router's
  affinity plane counted re-homes, the supervisor logged the crashed
  replica's stream homes being re-homed, and the replica relaunched.
* The client survives: reconnect-with-resume (retry the same seq)
  turns every severed packet into a success or a counted drop — no
  un-retried hard failures.

Each test prints one ``[stream-chaos] VERDICT {json}`` line so the lane
is greppable from CI logs.
"""

import glob
import http.client
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time

import pytest

pytestmark = [pytest.mark.slow, pytest.mark.chaos]

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, os.path.join(REPO, "tools"))

SUPERVISE_FLEET = os.path.join(REPO, "tools", "supervise_fleet.py")
TWIN_REPLICA = os.path.join(REPO, "tools", "twin_replica.py")
WINDOW = 256
#: twinpick's bucket programs are tiny, but three replicas share one CPU
WARM_TIMEOUT_S = 240.0

SCENARIO_ARGS = [
    "--stations", "36", "--duration-s", "30", "--window", str(WINDOW),
    "--fs", "50", "--seed", "7", "--min-stations", "4", "--workers", "4",
]


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _drain_pipe(pipe, buf):
    for line in pipe:
        buf.append(line)


def _start_fleet(base_port, replica_args, env_extra=None, replicas=3,
                 fleet_args=()):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    proc = subprocess.Popen(
        [
            sys.executable, SUPERVISE_FLEET,
            "--replicas", str(replicas),
            "--base-port", str(base_port),
            "--router-port", "0",
            "--probe-interval-s", "0.3",
            "--backoff", "0.5",
            "--drain-timeout-s", "20",
            *fleet_args,
            "--",
            sys.executable, TWIN_REPLICA, *replica_args,
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        env=env,
        text=True,
    )
    # Drain both pipes for the fleet's whole lifetime (the
    # test_serve_chaos.py lesson: an undrained inherited pipe at the
    # 64 KB kernel buffer wedges every fleet process on its next write).
    proc.fleet_err = []
    err_thread = threading.Thread(
        target=_drain_pipe, args=(proc.stderr, proc.fleet_err), daemon=True
    )
    err_thread.start()
    proc.fleet_err_thread = err_thread
    router = None
    for _ in range(50):
        line = proc.stdout.readline()
        if not line:
            break
        m = re.search(r"ROUTER=http://([\d.]+):(\d+)", line)
        if m:
            router = (m.group(1), int(m.group(2)))
            break
    if router is None:
        proc.kill()
        raise AssertionError("no ROUTER line from supervise_fleet")
    proc.fleet_out = []
    threading.Thread(
        target=_drain_pipe, args=(proc.stdout, proc.fleet_out), daemon=True
    ).start()
    return proc, router[0], router[1]


def _get(host, port, path, timeout=5.0):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        raw = resp.read()
        try:
            return resp.status, json.loads(raw)
        except ValueError:
            return resp.status, raw.decode()
    finally:
        conn.close()


def _wait_probed_ready(host, port, n, timeout_s=WARM_TIMEOUT_S):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            _, payload = _get(host, port, "/router/replicas")
            states = [
                r["probe_state"] for r in payload.get("replicas", [])
            ]
            if states.count("ok") >= n:
                return
        except OSError:
            pass
        time.sleep(0.25)
    raise AssertionError(
        f"fleet never reached {n} probed-ready replicas in {timeout_s}s"
    )


def _stop_fleet(proc, timeout=60):
    proc.send_signal(signal.SIGTERM)
    try:
        rc = proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        raise
    proc.fleet_err_thread.join(timeout=10)
    return rc, "".join(proc.fleet_err)


# ------------------------------------------------------ schedule driver
def _build_and_export(tmp_path):
    """Scenario + schedule via the twin, exported to (and re-loaded
    from) the schedule file — the file is the contract both consumers
    drive from."""
    import twin

    args = twin.get_args(SCENARIO_ARGS)
    stations, events, waves, expected = twin.build_scenario(args)
    rounds = twin.make_schedule(args, stations)
    sched = str(tmp_path / "schedule.json")
    twin.export_schedule(sched, args, stations, events, rounds)
    with open(sched) as f:
        doc = json.load(f)
    assert doc["rounds"] == rounds  # the export IS the replay
    return args, doc, waves, expected


class _StreamClient:
    """Reconnect-with-resume /stream driver over the exported schedule:
    worker threads own stations ``w::W`` (per-station order is the
    protocol invariant), a failed send retries the SAME seq — a
    success-after-retry is a 'resume', exhausted retries are a counted
    drop, never a silent one."""

    MAX_RETRIES = 4

    def __init__(self, host, port, doc, waves, workers=4,
                 round_pause_s=0.25):
        self.host, self.port = host, port
        self.doc, self.waves = doc, waves
        self.workers = workers
        self.round_pause_s = round_pause_s
        self.lock = threading.Lock()
        self.alerts = []
        self.ok = 0
        self.resumed = 0
        self.dropped = 0
        self.resume_ms = []
        self.options = {
            "ppk_threshold": 0.5, "spk_threshold": 0.95,
            "det_threshold": 0.95,
            "sampling_rate": doc["scenario"]["fs"],
        }

    def _post(self, body):
        conn = http.client.HTTPConnection(self.host, self.port, timeout=30)
        try:
            conn.request("POST", "/stream", json.dumps(body).encode(),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            return resp.status, resp.read()
        except OSError:
            return 0, b""
        finally:
            conn.close()

    def _send(self, st, pkt):
        body = {
            "model": "twinpick",
            "station": {k: st[k] for k in ("id", "network", "lat", "lon")},
            "seq": pkt["seq"],
            "options": self.options,
        }
        if pkt.get("end"):
            body["end"] = True
        else:
            body["data"] = self.waves[st["id"]][
                pkt["lo"]:pkt["hi"]].tolist()
        t0 = time.monotonic()
        for attempt in range(1 + self.MAX_RETRIES):
            status, raw = self._post(body)
            if status == 200:
                with self.lock:
                    self.ok += 1
                    if attempt:
                        self.resumed += 1
                        self.resume_ms.append(
                            (time.monotonic() - t0) * 1000.0
                        )
                    try:
                        self.alerts.extend(
                            json.loads(raw).get("alerts") or []
                        )
                    except ValueError:
                        pass
                return
            if not (status == 0 or status >= 500):
                break  # 4xx: not retryable, the packet is gone
            time.sleep(0.3 * (attempt + 1))
        with self.lock:
            self.dropped += 1

    def drive(self):
        by_id = {st["id"]: st for st in self.doc["stations"]}

        def worker(w):
            try:
                mine = {
                    st["id"]
                    for st in self.doc["stations"][w :: self.workers]
                }
                for rnd in self.doc["rounds"]:
                    for pkt in rnd:
                        if pkt["station"] in mine:
                            self._send(by_id[pkt["station"]], pkt)
                    # Pace the replay: journals get a cadence tick and
                    # the kill lands mid-stream, not post-hoc.
                    time.sleep(self.round_pause_s)
            except BaseException as e:  # noqa: BLE001
                with self.lock:
                    self.dropped += 10**6  # a dead worker fails the gate
                sys.stderr.write(f"[chaos] worker {w} died: {e!r}\n")

        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(self.workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        assert not any(t.is_alive() for t in threads), "driver wedged"


def _wal_alerts(journal_dir):
    out = []
    for path in glob.glob(os.path.join(journal_dir, "twinpick",
                                       "alerts*.wal")):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    return out


def _verdict(name, gates, detail):
    ok = all(gates.values())
    print(f"[stream-chaos] VERDICT "
          f"{json.dumps({'test': name, 'ok': ok, 'gates': gates, 'detail': detail})}",
          flush=True)
    assert ok, (gates, detail)


def test_sigkill_station_heavy_replica_exactly_once(tmp_path):
    """Acceptance: SIGKILL the replica homing the MOST stations while the
    mainshock wave is arriving. Survivors restore its stations from the
    shared journals, the router re-homes them, and the consumer-side
    alert ledger shows the mainshock exactly once."""
    from seist_tpu.serve.router import StationAffinity

    args, doc, waves, expected = _build_and_export(tmp_path)
    stations, events = doc["stations"], doc["events"]

    # Pre-compute rendezvous placement (deterministic in the replica
    # urls) to aim the kill at the station-heavy replica, and to time it
    # against the mainshock round.
    base_port = _free_port()
    urls = [f"127.0.0.1:{base_port + i}" for i in range(3)]
    aff = StationAffinity()
    by_url = {u: 0 for u in urls}
    for st in stations:
        by_url[aff.rank(st["id"], urls)[0]] += 1
    target_url = max(by_url, key=lambda u: by_url[u])
    target = urls.index(target_url)
    packet = WINDOW // 2
    fs = doc["scenario"]["fs"]
    main_round = int(events[0]["t"] * fs) // packet
    # The target's per-round packet count ~= its homed stations; fire
    # one round into the mainshock wave.
    kill_packet = by_url[target_url] * (main_round + 1)

    jd = str(tmp_path / "journals")
    stamp = str(tmp_path / "kill.stamp")
    proc, host, port = _start_fleet(
        base_port,
        replica_args=(
            "--window", str(WINDOW), "--stations", "72",
            "--min-stations", "4", "--journal-dir", jd,
            "--journal-every-s", "0.2",
        ),
        env_extra={
            "SEIST_FAULT_STREAM_KILL_PACKET": str(kill_packet),
            "SEIST_FAULT_SERVE_REPLICA": str(target),
            "SEIST_FAULT_STAMP": stamp,
        },
        fleet_args=("--router-retries", "3", "--request-timeout-s", "30"),
    )
    try:
        _wait_probed_ready(host, port, 3)
        client = _StreamClient(host, port, doc, waves)
        client.drive()

        _, reg = _get(host, port, "/router/replicas")
        stream = reg.get("stream") or {}
        # The relaunched target is back in rotation before teardown.
        _wait_probed_ready(host, port, 3, timeout_s=120.0)
    finally:
        rc, err = _stop_fleet(proc, timeout=120)

    wal = _wal_alerts(jd)
    observed = client.alerts + wal
    t_main = events[0]["t"]
    main_obs = [
        a for a in observed
        if abs(a["origin"]["t_s"] - t_main) <= 3.0
    ]
    main_ids = {a["alert_id"] for a in main_obs}
    # Consumer model: dedup on alert_id; distinct events group on the
    # cell+bucket prefix.
    emissions_per_id = {}
    for a in client.alerts:
        emissions_per_id[a["alert_id"]] = (
            emissions_per_id.get(a["alert_id"], 0) + 1
        )
    worst_dup = max(emissions_per_id.values(), default=0)

    gates = {
        "kill_fired": os.path.exists(stamp),
        "mainshock_alert_observed": len(main_ids) >= 1,
        "duplicates_bounded": worst_dup <= 3,
        "stations_rehomed": stream.get("rehomes", 0) > 0,
        "rehome_logged": "was stream home to" in err,
        "replica_relaunched": bool(
            re.search(rf"replica {target} crashed rc=-9; relaunch", err)
        ),
        "client_no_unrescued_failures": client.dropped == 0,
        "fleet_clean_exit": rc == 0,
    }
    detail = {
        "target_replica": target,
        "stations_on_target": by_url[target_url],
        "kill_packet": kill_packet,
        "alerts_seen": len(client.alerts),
        "wal_records": len(wal),
        "mainshock_ids": sorted(main_ids),
        "rehomes": stream.get("rehomes", 0),
        "resumed_packets": client.resumed,
        "resume_ms_max": round(max(client.resume_ms, default=0.0), 1),
        "worst_emissions_per_id": worst_dup,
    }
    _verdict("sigkill_station_heavy", gates, detail)
    assert gates["fleet_clean_exit"], err


def test_packet_faults_degrade_without_losing_mainshock(tmp_path):
    """SEIST_FAULT_STREAM_{DROP,DUP,REORDER}_P at a few percent on every
    replica: the plane degrades exactly as documented (gap-stitch
    absorbs drops, idempotent seqs absorb dups, late reordered packets
    fold into both) and the mainshock alert still lands."""
    args, doc, waves, expected = _build_and_export(tmp_path)
    jd = str(tmp_path / "journals")
    proc, host, port = _start_fleet(
        _free_port(),
        replica_args=(
            "--window", str(WINDOW), "--stations", "72",
            "--min-stations", "4", "--journal-dir", jd,
            "--journal-every-s", "0.2",
        ),
        env_extra={
            "SEIST_FAULT_STREAM_DROP_P": "0.03",
            "SEIST_FAULT_STREAM_DUP_P": "0.03",
            "SEIST_FAULT_STREAM_REORDER_P": "0.03",
        },
        fleet_args=("--router-retries", "3", "--request-timeout-s", "30"),
    )
    try:
        _wait_probed_ready(host, port, 3)
        client = _StreamClient(host, port, doc, waves,
                               round_pause_s=0.1)
        client.drive()
        _, reg = _get(host, port, "/router/replicas")
    finally:
        rc, err = _stop_fleet(proc, timeout=120)

    observed = client.alerts + _wal_alerts(jd)
    t_main = doc["events"][0]["t"]
    main_ids = {
        a["alert_id"] for a in observed
        if abs(a["origin"]["t_s"] - t_main) <= 3.0
    }
    gates = {
        "mainshock_alert_observed": len(main_ids) >= 1,
        "client_no_unrescued_failures": client.dropped == 0,
        "fleet_clean_exit": rc == 0,
    }
    detail = {
        "alerts_seen": len(client.alerts),
        "mainshock_ids": sorted(main_ids),
        "ok_packets": client.ok,
    }
    _verdict("packet_faults", gates, detail)
    assert gates["fleet_clean_exit"], err
