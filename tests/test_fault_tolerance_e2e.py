"""End-to-end fault-tolerance runs driven by the utils/faults harness:
NaN-loss skip + rollback (in-process), SIGKILL + supervise resume and
SIGTERM preemption (subprocess; slow lane per the tier-1 contract).

These are the ISSUE's acceptance checks: a training run must survive a
hard kill losing at most the save interval of work, continue a loss
curve seamlessly after relaunch, shrug off injected NaNs without
poisoning params, and turn SIGTERM into a durable checkpoint plus the
documented preempt exit code.
"""

import glob
import os
import signal
import subprocess
import sys
import time
from types import SimpleNamespace

import numpy as np
import pytest

import seist_tpu
from seist_tpu.utils.logger import logger

# Whole-file slow: every test here is a real (or in-process) training run
# dominated by jit compiles — the tier-1 fast lane stays fast (ISSUE
# satellite); `pytest -m slow tests/test_fault_tolerance_e2e.py` (or
# `make chaos`) runs the acceptance checks.
pytestmark = [pytest.mark.slow, pytest.mark.chaos]

seist_tpu.load_all()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_args(**over):
    d = dict(
        mode="train",
        model_name="phasenet",
        checkpoint="",
        seed=1,
        log_base="",
        log_step=100,
        use_tensorboard=False,
        save_test_results=False,
        data="",
        dataset_name="synthetic",
        data_split=True,
        train_size=0.8,
        val_size=0.1,
        shuffle=True,
        workers=2,
        in_samples=512,
        label_width=0.5,
        label_shape="gaussian",
        coda_ratio=2.0,
        norm_mode="std",
        min_snr=-float("inf"),
        p_position_ratio=-1,
        augmentation=False,
        add_event_rate=0.0,
        max_event_num=1,
        shift_event_rate=0.0,
        add_noise_rate=0.0,
        add_gap_rate=0.0,
        min_event_gap=0.5,
        drop_channel_rate=0.0,
        scale_amplitude_rate=0.0,
        pre_emphasis_rate=0.0,
        pre_emphasis_ratio=0.97,
        generate_noise_rate=0.0,
        mask_percent=0,
        noise_percent=0,
        epochs=1,
        patience=30,
        steps=0,
        start_epoch=0,
        batch_size=8,
        optim="Adam",
        momentum=0.9,
        weight_decay=0.0,
        use_lr_scheduler=True,
        lr_scheduler_mode="exp_range",
        base_lr=8e-5,
        max_lr=1e-3,
        warmup_steps=2000,
        down_steps=3000,
        time_threshold=0.1,
        min_peak_dist=1.0,
        ppk_threshold=0.3,
        spk_threshold=0.3,
        det_threshold=0.5,
        max_detect_event_num=1,
        dataset_kwargs={"num_events": 40, "trace_samples": 2048},
        # fault-tolerance knobs (cli.py defaults)
        save_interval_steps=2,
        keep_checkpoints=3,
        bad_step_guard=True,
        max_bad_steps=2,
    )
    d.update(over)
    return SimpleNamespace(**d)


# --------------------------------------------------- NaN guard (in-process)
def test_injected_nan_is_skipped_without_poisoning_params(
    tmp_path, monkeypatch
):
    """Acceptance: an injected NaN loss is skipped — the raw loss curve
    records it, params stay finite, training completes and checkpoints."""
    from seist_tpu.train.checkpoint import load_checkpoint
    from seist_tpu.train.worker import train_worker

    monkeypatch.setenv("SEIST_FAULT_NAN_STEP", "1")
    logger.set_logdir(str(tmp_path))
    ckpt = train_worker(make_args(max_bad_steps=0))  # skip-only, no rollback
    assert ckpt and os.path.exists(ckpt)
    losses = np.load(os.path.join(str(tmp_path), "train_losses.npy"))
    assert len(losses) == 4  # 32 train events / batch 8
    assert np.isnan(losses[1]), losses
    assert np.isfinite(np.delete(losses, 1)).all(), losses
    raw = load_checkpoint(ckpt)
    for leaf in __import__("jax").tree.leaves(raw["params"]):
        assert np.isfinite(np.asarray(leaf)).all()


def test_consecutive_nans_trigger_rollback_to_last_good_checkpoint(
    tmp_path, monkeypatch
):
    """Acceptance: N consecutive NaNs roll the run back to the last good
    checkpoint (params + optimizer), after which training continues."""
    from seist_tpu.train.worker import train_worker

    monkeypatch.setenv("SEIST_FAULT_NAN_STEP", "2")
    monkeypatch.setenv("SEIST_FAULT_NAN_COUNT", "2")
    logger.set_logdir(str(tmp_path))
    ckpt = train_worker(make_args(max_bad_steps=2, save_interval_steps=2))
    assert ckpt and os.path.exists(ckpt)
    with open(os.path.join(str(tmp_path), "global.log")) as f:
        log = f.read()
    # The guard's skips kept every interval checkpoint un-poisoned, so
    # "last good" is simply the newest one at rollback time.
    assert "rolling back to checkpoint step" in log, log[-2000:]
    assert os.path.exists(os.path.join(str(tmp_path), "checkpoints", "model_2"))
    assert os.path.exists(os.path.join(str(tmp_path), "checkpoints", "model_4"))


# ----------------------------------------------------- subprocess helpers
def _train_cmd(log_base, extra=()):
    return [
        sys.executable, os.path.join(REPO, "main.py"),
        "--mode", "train", "--model-name", "phasenet",
        "--dataset-name", "synthetic", "--synthetic-events", "40",
        "--in-samples", "512", "--batch-size", "8", "--epochs", "2",
        "--seed", "1", "--augmentation", "false", "--workers", "2",
        "--use-tensorboard", "false", "--save-interval-steps", "2",
        "--log-step", "100", "--log-base", log_base, *extra,
    ]


def _env(**over):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("SEIST_FAULT_NAN_STEP", None)
    env.pop("SEIST_FAULT_NAN_COUNT", None)
    env.update(over)
    return env


def _final_params(log_base, step=8):
    import jax
    import orbax.checkpoint as ocp

    paths = glob.glob(os.path.join(log_base, "*", "checkpoints",
                                   f"model_{step}", "default"))
    assert paths, f"no model_{step} under {log_base}"
    with ocp.StandardCheckpointer() as c:
        raw = c.restore(paths[0])
    return jax.tree.leaves(raw["params"]), raw["meta"]


# ------------------------------------------------- SIGKILL + supervise e2e
@pytest.mark.slow  # three subprocess training runs (compile-dominated)
def test_sigkill_midrun_supervise_resumes_with_loss_continuity(tmp_path):
    """Acceptance: SIGKILL a run mid-epoch via the fault harness, relaunch
    under tools/supervise.py, and the run resumes from the last durable
    step checkpoint: optimizer state intact, no data replayed/skipped, and
    final params matching an uninterrupted run."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from supervise import main as supervise_main

    kill_base = str(tmp_path / "kill_logs")
    stamp = str(tmp_path / "stamp")
    env = _env(SEIST_FAULT_KILL_STEP="5", SEIST_FAULT_STAMP=stamp)
    old_env = os.environ.copy()
    os.environ.update(env)
    try:
        rc = supervise_main(
            ["--retries", "2", "--backoff", "0", "--"] + _train_cmd(kill_base)
        )
    finally:
        os.environ.clear()
        os.environ.update(old_env)
    assert rc == 0
    # The kill actually happened (stamp) and the run still completed.
    with open(stamp) as f:
        assert "kill" in f.read()

    ref_base = str(tmp_path / "ref_logs")
    subprocess.run(_train_cmd(ref_base), env=_env(), check=True, timeout=600)

    killed, meta = _final_params(kill_base)
    reference, _ = _final_params(ref_base)
    assert int(meta["data_epoch"]) == 2 and int(meta["data_batch_offset"]) == 0
    # Loss-curve continuity in its strongest form: the resumed trajectory
    # lands on the same final params as the never-interrupted run (tiny
    # tolerance absorbs environment-level float noise under load).
    for a, b in zip(killed, reference):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-3, rtol=0
        )
    # "At most save_interval_steps lost": with async saves the last
    # DURABLE checkpoint trails the kill by < 2 intervals; the run dir
    # must hold a pre-kill step checkpoint that the relaunch resumed from.
    run_dir = glob.glob(os.path.join(kill_base, "*"))[0]
    steps = sorted(
        int(os.path.basename(p).split("_")[1])
        for p in glob.glob(os.path.join(run_dir, "checkpoints", "model_*"))
        if ".orbax-checkpoint-tmp-" not in p
    )
    assert steps[-1] == 8 and steps[0] >= 2


# --------------------------------------------------- SIGTERM preempt e2e
@pytest.mark.slow  # one subprocess training run
def test_sigterm_checkpoints_and_exits_preempt_code(tmp_path):
    """Acceptance: SIGTERM during training produces a checkpoint at the
    next step boundary and the documented preempt exit code (75)."""
    from seist_tpu.train.checkpoint import PREEMPT_EXIT_CODE

    log_base = str(tmp_path / "logs")
    proc = subprocess.run(
        _train_cmd(log_base),
        env=_env(SEIST_FAULT_SIGTERM_STEP="3"),
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == PREEMPT_EXIT_CODE, proc.stdout[-2000:]
    ckpts = glob.glob(os.path.join(log_base, "*", "checkpoints", "model_*"))
    committed = [c for c in ckpts if ".orbax-checkpoint-tmp-" not in c]
    assert committed, ckpts
    # The boundary checkpoint covers the SIGTERM step: step >= 4.
    assert max(
        int(os.path.basename(c).split("_")[1]) for c in committed
    ) >= 4
    assert "Preempted" in proc.stdout