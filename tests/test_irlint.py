"""irlint — IR-level static analysis (tools/irlint/).

Unit coverage per rule (positive/negative on tiny synthetic programs),
StableHLO donation/sharding parsing incl. the pruned-arg alignment,
suppression semantics at registration sites, the frontend gate, and the
acceptance pins: the full default manifest lowers + lints CLEAN against
the empty baseline, the donation audit matches ``resolve_donation``'s
decision table, the ``seist_l`` bf16 train step's matmul-FLOPs coverage
is >= 0.9, and the bf16 policy reaches the head matmuls of ALL FIVE
task-head families (dpk/pmp/emg/baz/dis), not just the trunk.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tools.irlint import ir
from tools.irlint.manifest import (
    ProgramInfo,
    ProgramSpec,
    SiteRef,
    default_manifest,
    train_programs,
    group_programs,
    stream_program,
    variant_structs,
)
from tools.irlint.rules import (
    RULES_BY_NAME,
    check_donation,
    check_padding,
    check_precision,
    check_replication,
    lint_programs,
)
from tools.irlint.__main__ import apply_site_suppressions, main as irlint_main

# Cheap unit classes carry the smoke mark individually; the manifest /
# acceptance classes trace real seist programs (tens of seconds) and must
# NOT ride into the instrumented smoke lanes (lockgraph, --tracer-leaks).
smoke = pytest.mark.smoke

_SITE = SiteRef(file="tests/test_irlint.py", line=1, text='"""irlint')


def _spec(fn, args, **kw):
    defaults = dict(
        key="test/prog", kind="train", site=_SITE, fn=fn, args=tuple(args)
    )
    defaults.update(kw)
    return ProgramSpec(**defaults)


def _f32(*shape):
    return jax.ShapeDtypeStruct(shape, jnp.float32)


def _bf16(*shape):
    return jax.ShapeDtypeStruct(shape, jnp.bfloat16)


# ------------------------------------------------------- stablehlo parsing
@smoke
class TestDonationParsing:
    def test_plain_jit_alias_detected(self):
        def f(s, x):
            return s + x.sum(), x * 2

        low = jax.jit(f, donate_argnums=(0,)).lower(_f32(), _f32(4, 4))
        audit = ir.donation_audit(low.as_text(), (_f32(), _f32(4, 4)), (0,))
        assert audit["donated_leaves"] == 1
        assert audit["aliased_leaves"] == 1
        assert audit["unaliased"] == []
        assert audit["stray_aliases"] == []

    def test_unaliasable_donation_flagged(self):
        # arg0 (scalar) matches no output shape: the lowering drops the
        # donation ("not usable") — the audit must surface it.
        def g(s, x):
            return x * 2.0

        low = jax.jit(g, donate_argnums=(0,)).lower(_f32(), _f32(4, 4))
        audit = ir.donation_audit(low.as_text(), (_f32(), _f32(4, 4)), (0,))
        assert audit["aliased_leaves"] == 0
        assert len(audit["unaliased"]) == 1

    def test_mesh_lowering_defers_to_buffer_donor(self):
        mesh = jax.sharding.Mesh(
            np.array(jax.devices()[:8]).reshape(8), ("data",)
        )
        repl = NamedSharding(mesh, P())

        def f(s, x):
            return s + x.sum(), x * 2

        low = jax.jit(
            f, donate_argnums=(0,), in_shardings=(repl, repl)
        ).lower(_f32(), _f32(8, 4))
        audit = ir.donation_audit(low.as_text(), (_f32(), _f32(8, 4)), (0,))
        # Sharded lowerings mark jax.buffer_donor and let XLA pair the
        # buffers at compile time — "deferred", neither aliased nor lost.
        assert audit["deferred_leaves"] == 1
        assert audit["unaliased"] == []

    def test_pruned_arg_alignment(self):
        # jit prunes unused args (keep_unused=False default), shifting
        # every %argN after the hole; the audit must align via
        # kept_var_idx instead of assuming identity.
        def f(unused, s, x):
            return s + x.sum(), x * 2

        args = (_f32(3, 3), _f32(), _f32(4, 4))
        jitted = jax.jit(f, donate_argnums=(1,))
        low = jitted.lower(*args)
        kept = sorted(low._lowering.compile_args["kept_var_idx"])
        assert kept == [1, 2]  # arg0 pruned
        audit = ir.donation_audit(low.as_text(), args, (1,), kept=kept)
        assert audit["aliased_leaves"] == 1
        assert audit["unaliased"] == []
        # Without the alignment the donated scalar would be looked up at
        # %arg1 (which is x) — a false "unaliased" plus a stray alias.
        naive = ir.donation_audit(low.as_text(), args, (1,))
        assert naive["unaliased"] or naive["stray_aliases"]

    def test_pruned_donated_leaf_counted(self):
        def f(s, x):
            return x * 2

        args = (_f32(4, 4), _f32(4, 4))
        low = jax.jit(f, donate_argnums=(0,)).lower(*args)
        kept = sorted(low._lowering.compile_args["kept_var_idx"])
        audit = ir.donation_audit(low.as_text(), args, (0,), kept=kept)
        assert audit["pruned_leaves"] == 1
        assert audit["unaliased"] == []


@smoke
class TestShardingParsing:
    def _mesh(self):
        return jax.sharding.Mesh(
            np.array(jax.devices()[:8]).reshape(8), ("data",)
        )

    def test_sharded_data_arg_clean(self):
        mesh = self._mesh()

        def f(w, x):
            return (x @ w).sum()

        low = jax.jit(
            f,
            in_shardings=(
                NamedSharding(mesh, P()),
                NamedSharding(mesh, P("data")),
            ),
        ).lower(_f32(4, 4), _f32(8, 4))
        audit = ir.sharding_audit(
            low.as_text(), (_f32(4, 4), _f32(8, 4)), (1,)
        )
        assert audit["sharded_leaves"] == 1
        assert audit["replicated"] == []

    def test_replicated_data_arg_flagged(self):
        mesh = self._mesh()

        def f(w, x):
            return (x @ w).sum()

        low = jax.jit(
            f,
            in_shardings=(
                NamedSharding(mesh, P()),
                NamedSharding(mesh, P()),  # the bug: batch replicated
            ),
        ).lower(_f32(4, 4), _f32(8, 4))
        audit = ir.sharding_audit(
            low.as_text(), (_f32(4, 4), _f32(8, 4)), (1,)
        )
        assert audit["sharded_leaves"] == 0
        assert len(audit["replicated"]) == 1


# ------------------------------------------------------------ matmul table
@smoke
class TestMatmulTable:
    def test_exact_flops_and_coverage(self):
        def f(a, b):
            return a @ b

        jaxpr = jax.make_jaxpr(f)(_bf16(4, 8), _bf16(8, 16))
        table = ir.matmul_dtype_table(jaxpr)
        assert len(table) == 1
        assert table[0]["flops"] == 2 * 4 * 8 * 16
        cov = ir.matmul_coverage(table, "bfloat16")
        assert cov["coverage"] == 1.0

    def test_mixed_dtype_fraction(self):
        # f32 matmul has 4x the FLOPs of the bf16 one -> coverage 0.2.
        def f(a, b, c, d):
            return (a @ b).sum() + (c @ d).astype(jnp.float32).sum()

        jaxpr = jax.make_jaxpr(f)(
            _f32(8, 8), _f32(8, 32), _bf16(8, 8), _bf16(8, 8)
        )
        cov = ir.matmul_coverage(
            ir.matmul_dtype_table(jaxpr), "bfloat16"
        )
        assert cov["coverage"] == pytest.approx(0.2)

    def test_scan_multiplies_trip_count(self):
        w = _bf16(8, 8)

        def f(w, xs):
            def body(c, x):
                return c, x @ w

            return jax.lax.scan(body, 0.0, xs)

        jaxpr = jax.make_jaxpr(f)(w, _bf16(3, 4, 8))
        table = ir.matmul_dtype_table(jaxpr)
        assert table[0]["count"] == 3
        assert table[0]["flops"] == 3 * 2 * 4 * 8 * 8

    def test_promotion_shows_mixed_operands(self):
        def f(a, b):
            return a @ b  # bf16 @ f32 promotes -> operands differ

        table = ir.matmul_dtype_table(
            jax.make_jaxpr(f)(_bf16(4, 8), _f32(8, 4))
        )
        assert ir.matmul_coverage(table, "bfloat16")["coverage"] < 1.0


@smoke
class TestHostTransfers:
    def test_callback_detected(self):
        def f(x):
            y = jax.pure_callback(
                lambda a: np.asarray(a), jax.ShapeDtypeStruct((4,), np.float32), x
            )
            return y * 2

        transfers = ir.host_transfers(jax.make_jaxpr(f)(_f32(4)))
        assert transfers and transfers[0]["prim"] == "pure_callback"

    def test_clean_program(self):
        assert ir.host_transfers(jax.make_jaxpr(lambda x: x * 2)(_f32(4))) == []


# ------------------------------------------------------------------- rules
@smoke
class TestRules:
    def test_precision_finding_fires_below_threshold(self):
        def f(v, x):
            return x @ v  # f32 matmul under a declared bf16 policy

        spec = _spec(f, (_f32(8, 8), _f32(4, 8)), policy="bf16")
        info = ProgramInfo(spec)
        findings = check_precision(info)
        assert [f.rule for f in findings] == ["f32-matmul-under-bf16-policy"]
        assert info.report["matmul"]["coverage"] == 0.0

    def test_precision_silent_for_fp32_policy(self):
        def f(v, x):
            return x @ v

        info = ProgramInfo(_spec(f, (_f32(8, 8), _f32(4, 8)), policy="fp32"))
        assert check_precision(info) == []
        assert info.report["matmul"]["coverage"] is None

    def test_precision_clean_bf16(self):
        def f(v, x):
            return x.astype(jnp.bfloat16) @ v

        info = ProgramInfo(
            _spec(f, (_bf16(8, 8), _f32(4, 8)), policy="bf16")
        )
        assert check_precision(info) == []
        assert info.report["matmul"]["coverage"] == 1.0

    def test_padding_waste_flags_sparse_ladder(self):
        def f(v, x):
            return x @ v

        spec = _spec(
            f, (_f32(8, 8), _f32(8, 8)), kind="serve", bucket=8,
            ladder=(1, 8),
        )
        info = ProgramInfo(spec)
        findings = check_padding(info)
        assert [f.rule for f in findings] == ["padding-waste"]
        assert info.report["padding"]["waste_frac_worst"] == 0.75

    def test_padding_clean_pow2_ladder(self):
        def f(v, x):
            return x @ v

        info = ProgramInfo(
            _spec(
                f, (_f32(8, 8), _f32(4, 8)), kind="serve", bucket=4,
                ladder=(1, 2, 4),
            )
        )
        assert check_padding(info) == []
        assert info.report["padding"]["waste_frac_worst"] == 0.25

    def test_replication_flags_bare_jit_under_mesh(self):
        mesh = jax.sharding.Mesh(
            np.array(jax.devices()[:8]).reshape(8), ("data",)
        )

        def f(w, x):
            return (x @ w).sum()

        args = (_f32(4, 4), _f32(8, 4))
        spec = _spec(
            f,
            args,
            jitted=jax.jit(
                f,
                in_shardings=(
                    NamedSharding(mesh, P()),
                    NamedSharding(mesh, P()),
                ),
            ),
            mesh_size=8,
            data_argnums=(1,),
        )
        findings = check_replication(ProgramInfo(spec))
        assert [f.rule for f in findings] == ["replication-audit"]

    def test_replication_skipped_single_device(self):
        def f(w, x):
            return (x @ w).sum()

        spec = _spec(
            f, (_f32(4, 4), _f32(8, 4)), mesh_size=1, data_argnums=(1,)
        )
        assert check_replication(ProgramInfo(spec)) == []

    def test_donation_unaliased_finding(self):
        def g(s, x):
            return x * 2.0  # s's scalar matches no output -> unusable

        spec = _spec(
            g,
            (_f32(), _f32(4, 4)),
            donate_intent=(0,),
            donate=(0,),
            jitted=jax.jit(g, donate_argnums=(0,), keep_unused=True),
        )
        findings = check_donation(ProgramInfo(spec))
        assert [f.rule for f in findings] == ["donation-alias-audit"]

    def test_donation_gated_is_not_a_finding(self):
        def f(s, x):
            return s + x.sum()

        spec = _spec(
            f,
            (_f32(), _f32(4,)),
            donate_intent=(0,),
            donate=(),  # resolve_donation dropped it (hazard config)
            notes={"donation_gated": True, "reason": "test"},
        )
        info = ProgramInfo(spec)
        assert check_donation(info) == []
        assert info.report["donation"]["donation_gated"] is True


# ------------------------------------------------------------ suppressions
@smoke
class TestSuppressions:
    def _write(self, tmp_path, body):
        f = tmp_path / "site.py"
        f.write_text(body)
        return "site.py"

    def _finding(self, line, rule="padding-waste"):
        from tools.jaxlint.engine import Finding

        return Finding(
            file="site.py", line=line, col=0, rule=rule,
            message="[test/prog] msg", text="def jit_thing():",
        )

    def test_rationale_suppression_silences(self, tmp_path):
        rel = self._write(
            tmp_path,
            "# irlint: disable=padding-waste -- deliberate single bucket\n"
            "def jit_thing():\n    pass\n",
        )
        out = apply_site_suppressions(
            [self._finding(2)], [rel], root=str(tmp_path), full_catalog=True
        )
        assert out == []

    def test_rationale_required(self, tmp_path):
        rel = self._write(
            tmp_path,
            "# irlint: disable=padding-waste\n"
            "def jit_thing():\n    pass\n",
        )
        out = apply_site_suppressions(
            [self._finding(2)], [rel], root=str(tmp_path), full_catalog=True
        )
        rules = sorted(f.rule for f in out)
        assert rules == ["padding-waste", "suppression-missing-rationale"]

    def test_wrong_tag_does_not_silence(self, tmp_path):
        rel = self._write(
            tmp_path,
            "# jaxlint: disable=padding-waste -- wrong analyzer's tag\n"
            "def jit_thing():\n    pass\n",
        )
        out = apply_site_suppressions(
            [self._finding(2)], [rel], root=str(tmp_path), full_catalog=True
        )
        assert [f.rule for f in out] == ["padding-waste"]

    def test_unused_suppression_reported(self, tmp_path):
        rel = self._write(
            tmp_path,
            "# irlint: disable=padding-waste -- nothing here anymore\n"
            "def jit_thing():\n    pass\n",
        )
        out = apply_site_suppressions(
            [], [rel], root=str(tmp_path), full_catalog=True
        )
        assert [f.rule for f in out] == ["unused-suppression"]

    def test_unused_not_reported_under_select(self, tmp_path):
        rel = self._write(
            tmp_path,
            "# irlint: disable=padding-waste -- subset run\n"
            "def jit_thing():\n    pass\n",
        )
        out = apply_site_suppressions(
            [], [rel], root=str(tmp_path), full_catalog=False
        )
        assert out == []


# ---------------------------------------------------------------- frontend
@smoke
class TestFrontend:
    def test_update_baseline_refused_while_empty(self):
        rc = irlint_main(["--update-baseline"])
        assert rc == 2
        with open(
            os.path.join(os.path.dirname(__file__), "..", "tools",
                         "irlint_baseline.json")
        ) as f:
            assert json.load(f)["accepted"] == {}

    def test_unknown_program_glob_exits_2(self):
        assert irlint_main(["definitely/not/a/program"]) == 2

    def test_unknown_rule_select_errors(self):
        with pytest.raises(SystemExit):
            irlint_main(["--select", "no-such-rule"])

    def test_list_rules(self, capsys):
        assert irlint_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for name in RULES_BY_NAME:
            assert name in out


# -------------------------------------------------- manifest + acceptance
class TestManifest:
    def test_variant_structs_mirror_weight_transforms(self):
        vs = {"params": {"dense": {"kernel": _f32(8, 16), "bias": _f32(16)}}}
        bf = variant_structs(vs, "bf16")
        assert bf["params"]["dense"]["kernel"].dtype == jnp.bfloat16
        i8 = variant_structs(vs, "int8")
        packed = i8["params"]["dense"]["kernel"]
        assert packed["__int8__"].dtype == jnp.int8
        assert packed["scale"].shape == (16,)  # per-out-channel
        # 1-D leaves stay fp32 (tiny, precision-critical).
        assert i8["params"]["dense"]["bias"].dtype == jnp.float32

    def test_stream_program_clean_and_transfer_free(self):
        infos = lint_programs([stream_program(window=256, n_windows=7,
                                              record_len=1024)])
        assert infos[0].findings == []
        assert infos[0].report["host_transfers"] == []

    def test_donation_decision_table_gated(self, monkeypatch):
        # The suite runs with the persistent compile cache enabled on the
        # CPU backend — exactly the hazard config resolve_donation gates,
        # so the manifest's train programs must record gated donation.
        monkeypatch.delenv("SEIST_DONATE_WITH_CACHE", raising=False)
        from seist_tpu.train.step import resolve_donation

        assert resolve_donation((0,)) == ()
        specs = train_programs(
            "phasenet", compute_dtype=None, window=128, include=("step",)
        )
        spec = specs[0]
        assert spec.donate_intent == (0,)
        assert spec.donate == ()
        assert spec.notes.get("donation_gated") is True
        info_list = lint_programs(specs, [RULES_BY_NAME["donation-alias-audit"]])
        assert info_list[0].findings == []
        assert info_list[0].report["donation"]["donation_gated"] is True

    def test_donation_decision_table_forced(self, monkeypatch):
        # SEIST_DONATE_WITH_CACHE=1 restores donation: every donated leaf
        # must then be accounted as aliased, deferred (mesh lowering) or
        # pruned — none silently lost.
        monkeypatch.setenv("SEIST_DONATE_WITH_CACHE", "1")
        specs = train_programs(
            "phasenet", compute_dtype=None, window=128, include=("step",)
        )
        spec = specs[0]
        assert spec.donate == (0,)
        info_list = lint_programs(specs, [RULES_BY_NAME["donation-alias-audit"]])
        assert info_list[0].findings == []
        audit = info_list[0].report["donation"]
        assert audit["donated_leaves"] > 0
        accounted = (
            audit["aliased_leaves"]
            + audit["deferred_leaves"]
            + audit["pruned_leaves"]
        )
        assert accounted == audit["donated_leaves"]

    def test_default_manifest_keys_cover_every_boundary(self):
        # Key-level check (no lowering): the manifest names every shipped
        # jit boundary family.
        keys = []
        manifest = default_manifest(match=lambda k: False)
        assert manifest == []  # section pruning works
        # Candidate keys are deterministic; collect via a recording match.
        default_manifest(match=lambda k: keys.append(k) or False)
        blob = "\n".join(keys)
        for needle in (
            "train/jit_step/",
            "train/jit_multi_step/",
            "train/jit_device_aug_step/",
            "train/jit_cached_call/",
            "serve/phasenet/full/",
            "serve/seist_s/trunk/",
            "serve/seist_s/head:",
            "stream/annotate/",
        ):
            assert needle in blob, f"manifest lost the {needle} boundary"


class TestAcceptance:
    def test_full_manifest_green_on_empty_baseline(self, tmp_path):
        """THE gate: every program in the default manifest lowers and
        lints with zero findings against the empty baseline, and the
        report carries the campaign numbers."""
        report = tmp_path / "irlint_report.json"
        rc = irlint_main(["--report", str(report)])
        assert rc == 0
        payload = json.loads(report.read_text())
        summary = payload["summary"]
        assert summary["programs"] >= 12
        assert summary["bf16_coverage_min"] >= 0.9
        assert summary["host_transfers_total"] == 0
        assert summary["padding_waste_worst"] <= 0.5
        # Per-program sections the trend consumers key on.
        some = payload["programs"]["train/jit_step/seist_s_dpk/bf16"]
        assert some["matmul"]["coverage"] >= 0.9
        assert "donation" in some and "sharding" in some

    def test_seist_l_bf16_train_step_coverage(self):
        """The precision-campaign headline number: the seist_l bf16 train
        step runs >= 90% of its matmul FLOPs in bf16."""
        specs = train_programs(
            "seist_l_dpk", compute_dtype="bf16", window=256,
            include=("step",),
        )
        infos = lint_programs(
            specs, [RULES_BY_NAME["f32-matmul-under-bf16-policy"]]
        )
        assert infos[0].findings == []
        cov = infos[0].report["matmul"]["coverage"]
        assert cov is not None and cov >= 0.9

    def test_policy_reaches_all_five_head_families(self):
        """Satellite: the bf16 policy must reach HEAD matmuls for every
        task family, not just the shared trunk — pinned per family via
        the head-program coverage fraction."""
        specs = group_programs(
            "seist_s",
            ("dpk", "pmp", "emg", "baz", "dis"),
            buckets=(4,),
            variants=("bf16",),
            window=256,
        )
        heads = [s for s in specs if "/head:" in s.key]
        assert len(heads) == 5
        infos = lint_programs(
            heads, [RULES_BY_NAME["f32-matmul-under-bf16-policy"]]
        )
        for info in infos:
            assert info.findings == [], info.spec.key
            cov = info.report["matmul"]["coverage"]
            assert cov is not None and cov >= 0.9, (
                f"{info.spec.key}: head matmuls not reached by the bf16 "
                f"policy (coverage {cov})"
            )
        # ... and the trunk too, for completeness.
        trunk = [s for s in specs if "/trunk/" in s.key]
        tinfo = lint_programs(
            trunk, [RULES_BY_NAME["f32-matmul-under-bf16-policy"]]
        )[0]
        assert tinfo.report["matmul"]["coverage"] >= 0.9
