"""Training-dynamics parity: torch reference vs seist_tpu (VERDICT r3 #5).

Both sides train phasenet and seist_s_dpk (all drop rates zeroed) from the
IDENTICAL initialization on
byte-identical batches in the same order under the same cyclic LR schedule
(tools/train_dynamics.py). Asserting the loss trajectories agree catches
BN-momentum / LR-schedule / optimizer-epsilon / loss-scaling drift that
single-step forward+gradient parity (tests/test_golden_parity.py) cannot see.

Ref anchor: /root/reference/training/train.py:378-468 (the epoch loop being
mirrored); validate.py:54-127 (the eval-mode val loss, which runs on BN
running stats — the BN-momentum probe).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # two full (small) training runs

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOL = os.path.join(_REPO, "tools", "train_dynamics.py")


def _run_side(side: str, model: str, tmp: str) -> dict:
    out = os.path.join(tmp, f"{side}.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [
            sys.executable,
            _TOOL,
            "--side",
            side,
            "--model",
            model,
            "--init",
            os.path.join(tmp, "init.npz"),
            "--out",
            out,
        ],
        capture_output=True,
        text=True,
        timeout=1200,
        env=env,
        cwd=_REPO,
    )
    assert r.returncode == 0, f"{side} side failed:\n{r.stdout}\n{r.stderr}"
    with open(out) as f:
        return json.load(f)


# phasenet: plain conv+BN+CE dynamics. seist_s_dpk: the flagship family —
# stems, grouped convs, pooled attention, DropPath residuals, BCE. Both
# measured 2026-07-31: max train-loss drift 1.0e-4 / 1.5e-5 respectively.
# seist_s_dpk_droppath: the dropout-ON lane (VERDICT r4 #6) — stochastic
# depth at 0.2 with per-sample uniforms INJECTED identically on both
# sides; measured 2026-08-01: max train-loss drift 8.4e-6 over 48 steps,
# 33 DropPath calls consumed per forward on each side.
# seist_s_pmp: the accuracy-metric (classification) lane. Its loss is a
# mean over just `batch` scalars from a global-pooled head, so fp-level
# noise amplifies chaotically once training moves: measured 2026-08-01,
# steps 0-10 agree to ~3e-6, then the drift grows with OSCILLATING sign
# (jax above torch at step 16, below at 28) to ~9e-2 by step 48 — the
# signature of chaotic divergence, not a systematic convention drift
# (BN momentum / LR shape / eps would bias one side early and
# monotonically). Tolerances below are per-lane, calibrated to those
# measurements.
@pytest.fixture(
    scope="module",
    params=[
        "phasenet",
        "seist_s_dpk",
        "seist_s_dpk_droppath",
        "seist_s_pmp",
        "eqtransformer",
        "magnet",
        "ditingmotion",
    ],
)
def trajectories(request, tmp_path_factory):
    tmp = str(tmp_path_factory.mktemp(f"dyn_{request.param}"))
    torch_run = _run_side("torch", request.param, tmp)  # writes init.npz
    jax_run = _run_side("jax", request.param, tmp)
    return torch_run, jax_run


# (early-window max rel drift, full-trajectory max, val max) per lane;
# early window = first quarter of the steps (pure-parity regime before
# chaotic amplification dominates).
_TOL = {
    "phasenet": (1e-3, 5e-3, 5e-3),
    "seist_s_dpk": (1e-3, 5e-3, 5e-3),
    "seist_s_dpk_droppath": (1e-3, 5e-3, 5e-3),
    "seist_s_pmp": (5e-3, 1.5e-1, 5e-2),
    # scan-BiLSTM recurrence accumulates fp drift ~20x faster than the
    # pure-conv lanes (measured 2026-08-01: first-quarter 1.1e-4, full
    # 2.0e-3, val 2.8e-3); its band keeps the file's ~10x-over-measured
    # margin so host/XLA variation cannot flake the slow lane.
    "eqtransformer": (1e-3, 2e-2, 3e-2),
    # MagNet's sum-reduced scalar objective feels Adam's sign-flips at
    # near-zero gradient coordinates immediately (init grads agree to
    # 1.2e-6 — see the MODELS['magnet'] comment in the harness);
    # measured at the lane's max_lr=3e-4: first-quarter 8.9e-3, full
    # 6.6e-2, val 6.1e-2. Band ~5x over measured.
    "magnet": (5e-2, 3e-1, 3e-1),
    # Dual-Focal multi-head lane: the tightest of all (measured full
    # drift 1.7e-6, val 5e-7).
    "ditingmotion": (1e-4, 1e-4, 1e-4),
}

# Denylist for the must-actually-learn assertion (fails safe: a lane
# added to the fixture without an entry here IS held to the 5% bar).
# ditingmotion barely moves at this toy scale (measured end/start ratio
# 0.9993 on BOTH sides — the focal objective on 2-channel 512-sample
# windows needs more steps); its purpose here is loss-family parity,
# which its 1.7e-6 drift locks, and absolute learning is covered by the
# other six lanes.
_TOO_SLOW_TO_LEARN = {"ditingmotion"}


def test_train_loss_trajectory_matches(trajectories):
    torch_run, jax_run = trajectories
    t = np.asarray(torch_run["train_loss_per_step"])
    j = np.asarray(jax_run["train_loss_per_step"])
    assert t.shape == j.shape and t.size >= 40
    # Same init + same batches: step 0 is near-exact (pure forward parity);
    # later steps accumulate fp drift through 40+ optimizer updates, BN
    # stats and the exp_range LR decay, so the band widens with depth.
    np.testing.assert_allclose(j[0], t[0], rtol=1e-5)
    # Calibrated 2026-07-31/08-01 on this host: measured max rel drift
    # 1.0e-4 over 48 optimizer steps for the dense-loss lanes (first
    # half 4.6e-5); the pmp classification lane amplifies chaotically
    # (see _TOL comment). Tolerances sit ~10-50x above the measurements
    # so only a real dynamics divergence (BN momentum, LR schedule,
    # optimizer eps, loss scaling) trips them, not fp noise.
    early_tol, full_tol, _ = _TOL[torch_run["config"]["model"]]
    rel = np.abs(j - t) / np.maximum(np.abs(t), 1e-8)
    early = rel[: len(rel) // 4]
    assert early.max() < early_tol, (
        f"early train-loss drift {early.max():.2e} exceeds {early_tol:g}"
    )
    assert rel.max() < full_tol, (
        f"train-loss drift {rel.max():.2e} exceeds {full_tol:g}"
    )
    # Both must actually LEARN (measured: 1.276 -> 1.143 over 6 epochs)
    # — except lanes explicitly exempted as too slow at toy scale.
    if torch_run["config"]["model"] not in _TOO_SLOW_TO_LEARN:
        assert t[-8:].mean() < t[:8].mean() * 0.95
        assert j[-8:].mean() < j[:8].mean() * 0.95


def test_val_loss_trajectory_matches(trajectories):
    # Eval-mode forward runs on BN *running* stats: a BN-momentum
    # convention drift shows up first here (and only here).
    torch_run, jax_run = trajectories
    t = np.asarray(torch_run["val_loss_per_epoch"])
    j = np.asarray(jax_run["val_loss_per_epoch"])
    assert t.shape == j.shape and t.size >= 4
    # Calibrated: measured max val drift 1.2e-4 across 6 epochs (dense
    # lanes); 2.3e-2 for the chaotic pmp lane (last epoch only).
    val_tol = _TOL[torch_run["config"]["model"]][2]
    rel = np.abs(j - t) / np.maximum(np.abs(t), 1e-8)
    assert rel.max() < val_tol, (
        f"val-loss drift {rel.max():.2e} exceeds {val_tol:g}"
    )


def test_val_metric_trajectory_matches(trajectories):
    # VERDICT r4 #6 (metric half): per-epoch P/S pick F1 on the val set,
    # scored by the ONE shared numpy scorer on each side's eval-mode
    # probabilities. A dynamics drift that losses average away would
    # move individual picks across the threshold/tolerance and split the
    # trajectories. Measured 2026-08-01: phasenet trajectories agree to
    # one pick (0.031 abs) per epoch; end F1 exactly equal. The seist
    # lanes sit at 0.0 F1 at this 48-step toy scale on BOTH frameworks
    # (equality still asserted); absolute dpk learning is covered by the
    # phasenet lane here and tests/test_worker_e2e.py's learning
    # regression.
    torch_run, jax_run = trajectories
    if "val_acc_per_epoch" in torch_run:
        keys = ("val_acc_per_epoch",)
    elif "val_mae_per_epoch" in torch_run:
        # MAE in magnitude units on the volatile magnet lane (measured
        # max per-epoch diff 0.026): wider band than the [0,1] scores.
        keys = ("val_mae_per_epoch",)
    else:
        keys = ("val_f1_p_per_epoch", "val_f1_s_per_epoch")
    metric_tol = 0.1 if keys == ("val_mae_per_epoch",) else 0.05
    for key in keys:
        t = np.asarray(torch_run[key])
        j = np.asarray(jax_run[key])
        assert t.shape == j.shape and t.size >= 4
        diff = np.abs(j - t)
        assert diff.max() <= metric_tol, (
            f"{key} trajectories diverge: {diff.max():.3f} (torch {t}, jax {j})"
        )
        # End-metric agreement (the r3 ask's second half).
        assert diff[-1] <= metric_tol, (
            f"end {key}: torch {t[-1]} vs jax {j[-1]}"
        )
    # The phasenet lane must actually move the metric (non-vacuous check
    # that the scorer sees learning; measured: P-F1 0.03 -> 0.47).
    if torch_run["config"]["model"] == "phasenet":
        t = np.asarray(torch_run["val_f1_p_per_epoch"])
        assert t[-1] > t[0], f"P-F1 did not improve: {t}"


def test_droppath_lane_consumed_identical_masks(trajectories):
    # Dropout-ON lane (VERDICT r4 #6): both frameworks must consume the
    # SAME number of injected DropPath rows per forward (call-order
    # symmetry), and — asserted by the trajectory tests above running on
    # this lane too — produce matching losses WITH stochastic depth
    # active. With divergent masks the train-loss drift would be O(1);
    # measured with injection: 8.4e-6.
    torch_run, jax_run = trajectories
    if not torch_run["config"]["model"].endswith("_droppath"):
        pytest.skip("injection lane only")
    # (measured: 33 calls/forward for seist_s — 2 per encoder block +
    # decoder residuals; the invariant is equal-and-consuming, not the
    # exact count, which tracks depth config)
    assert (
        torch_run["droppath_calls_per_forward"]
        == jax_run["droppath_calls_per_forward"]
        > 0
    )
