"""Training-dynamics parity: torch reference vs seist_tpu (VERDICT r3 #5).

Both sides train phasenet and seist_s_dpk (all drop rates zeroed) from the
IDENTICAL initialization on
byte-identical batches in the same order under the same cyclic LR schedule
(tools/train_dynamics.py). Asserting the loss trajectories agree catches
BN-momentum / LR-schedule / optimizer-epsilon / loss-scaling drift that
single-step forward+gradient parity (tests/test_golden_parity.py) cannot see.

Ref anchor: /root/reference/training/train.py:378-468 (the epoch loop being
mirrored); validate.py:54-127 (the eval-mode val loss, which runs on BN
running stats — the BN-momentum probe).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # two full (small) training runs

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOL = os.path.join(_REPO, "tools", "train_dynamics.py")


def _run_side(side: str, model: str, tmp: str) -> dict:
    out = os.path.join(tmp, f"{side}.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [
            sys.executable,
            _TOOL,
            "--side",
            side,
            "--model",
            model,
            "--init",
            os.path.join(tmp, "init.npz"),
            "--out",
            out,
        ],
        capture_output=True,
        text=True,
        timeout=1200,
        env=env,
        cwd=_REPO,
    )
    assert r.returncode == 0, f"{side} side failed:\n{r.stdout}\n{r.stderr}"
    with open(out) as f:
        return json.load(f)


# phasenet: plain conv+BN+CE dynamics. seist_s_dpk: the flagship family —
# stems, grouped convs, pooled attention, DropPath residuals, BCE. Both
# measured 2026-07-31: max train-loss drift 1.0e-4 / 1.5e-5 respectively.
@pytest.fixture(scope="module", params=["phasenet", "seist_s_dpk"])
def trajectories(request, tmp_path_factory):
    tmp = str(tmp_path_factory.mktemp(f"dyn_{request.param}"))
    torch_run = _run_side("torch", request.param, tmp)  # writes init.npz
    jax_run = _run_side("jax", request.param, tmp)
    return torch_run, jax_run


def test_train_loss_trajectory_matches(trajectories):
    torch_run, jax_run = trajectories
    t = np.asarray(torch_run["train_loss_per_step"])
    j = np.asarray(jax_run["train_loss_per_step"])
    assert t.shape == j.shape and t.size >= 40
    # Same init + same batches: step 0 is near-exact (pure forward parity);
    # later steps accumulate fp drift through 40+ optimizer updates, BN
    # stats and the exp_range LR decay, so the band widens with depth.
    np.testing.assert_allclose(j[0], t[0], rtol=1e-5)
    # Calibrated 2026-07-31 on this host: measured max rel drift 1.0e-4
    # over 48 optimizer steps (first half 4.6e-5). Tolerances sit ~10-50x
    # above that so only a real dynamics divergence (BN momentum, LR
    # schedule, optimizer eps, loss scaling) trips them, not fp noise.
    rel = np.abs(j - t) / np.maximum(np.abs(t), 1e-8)
    assert rel[: len(rel) // 2].max() < 1e-3, (
        f"first-half train-loss drift {rel[: len(rel) // 2].max():.2e}"
    )
    assert rel.max() < 5e-3, f"train-loss drift {rel.max():.2e} exceeds 5e-3"
    # Both must actually LEARN (measured: 1.276 -> 1.143 over 6 epochs).
    assert t[-8:].mean() < t[:8].mean() * 0.95
    assert j[-8:].mean() < j[:8].mean() * 0.95


def test_val_loss_trajectory_matches(trajectories):
    # Eval-mode forward runs on BN *running* stats: a BN-momentum
    # convention drift shows up here first (and only here).
    torch_run, jax_run = trajectories
    t = np.asarray(torch_run["val_loss_per_epoch"])
    j = np.asarray(jax_run["val_loss_per_epoch"])
    assert t.shape == j.shape and t.size >= 4
    # Calibrated: measured max val drift 1.2e-4 across 6 epochs.
    rel = np.abs(j - t) / np.maximum(np.abs(t), 1e-8)
    assert rel.max() < 5e-3, f"val-loss drift {rel.max():.2e} exceeds 5e-3"
