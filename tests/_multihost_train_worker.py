"""Subprocess body for the 2-host end-to-end training test.

Runs one epoch of phasenet on the synthetic dataset through the REAL
train_worker + validate path: per-host loader shards, global batch assembly,
mask-weighted global eval loss, cross-host metric sync, orbax multi-host
checkpoint save. Exit 0 = finished and produced a checkpoint.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

proc_id, nprocs, port, logdir = (
    int(sys.argv[1]),
    int(sys.argv[2]),
    sys.argv[3],
    sys.argv[4],
)
# Optional 5th arg: --device-aug mode ("cached" exercises the multi-host
# epoch cache — per-host addressable-slice placement + the host-sharded
# index stream that replaced the old cached->step fallback).
device_aug = sys.argv[5] if len(sys.argv) > 5 else "off"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# gloo CPU collectives: without an implementation selected the CPU
# backend refuses multiprocess computations (the seed test_multihost
# failure — see tests/_multihost_worker.py).
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(
    coordinator_address=f"localhost:{port}",
    num_processes=nprocs,
    process_id=proc_id,
)

import seist_tpu  # noqa: E402
from seist_tpu.utils.logger import logger  # noqa: E402

seist_tpu.load_all()
# ONE shared logdir for all processes — the production path guarantees
# this (cli.main_worker broadcasts the resolved dir from process 0), and
# the collective orbax save REQUIRES it: each process writes its shard
# under the primary's checkpoint directory. Divergent dirs deadlock the
# save (process 1 waits for array_metadatas under its own path forever).
logger.set_logdir(logdir)
if proc_id != 0:
    logger.enable_console(False)

sys.path.insert(0, os.path.dirname(__file__))
from test_worker_e2e import make_args  # noqa: E402

from seist_tpu.train.worker import train_worker  # noqa: E402

args = make_args(
    epochs=1,
    batch_size=4,  # per-host; global 8 over the 8-device mesh
    workers=2,
    # Shorter windows than the single-process e2e defaults: two of these
    # processes share the host's ONE cpu core, so the jit compile (the
    # dominant cost) must stay small or the test rig's timeout trips.
    in_samples=512,
    dataset_kwargs={"num_events": 30, "trace_samples": 2048},
    device_aug=device_aug,
    # One update per call keeps the scanned cached executor's compile
    # small enough for the shared-core rig.
    steps_per_call=1 if device_aug == "cached" else 0,
)
ckpt = train_worker(args)
assert ckpt and os.path.exists(ckpt), ckpt
print(f"train worker {proc_id}: OK ckpt={ckpt}")
