"""Donation/compile-cache correctness gate (train/step.py resolve_donation).

The ROADMAP open item from the PR 4 audit lane: on jax 0.4.37 CPU, an
executable DESERIALIZED from the persistent XLA compile cache
intermittently corrupts donated outputs in unsynchronized donated step
chains (state.step reads back float bits; repeated reads differ). The
mitigation gates donation out of exactly that configuration — disk cache
active AND CPU backend — so cached executables never carry input/output
aliasing. These tests pin the gate's decision table and run the original
repro chain under the previously-hazardous config, where it is now
deterministic instead of a 20-40% coin flip.
"""

import jax
import numpy as np
import pytest

import seist_tpu
from seist_tpu import taskspec
from seist_tpu.models import api
from seist_tpu.train import (
    build_optimizer,
    create_train_state,
    jit_step,
    make_train_step,
    resolve_donation,
)

seist_tpu.load_all()

L = 256
BATCH = 4


@pytest.fixture
def warm_cache_dir(tmp_path, monkeypatch):
    """A fresh persistent compile cache with no compile-time threshold, so
    the test's small programs are serialized (and deserialized on a
    re-wrap) exactly like production-sized ones."""
    monkeypatch.delenv("SEIST_DONATE_WITH_CACHE", raising=False)
    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    cache = str(tmp_path / "xla_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    yield cache
    jax.config.update("jax_compilation_cache_dir", prev_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", prev_min)


def _setup():
    model = api.create_model("phasenet", in_samples=L)
    variables = api.init_variables(model, in_samples=L, batch_size=BATCH)
    tx = build_optimizer("adam", 1e-3)
    state = create_train_state(model, variables, tx)
    spec = taskspec.get_task_spec("phasenet")
    return state, spec, taskspec.make_loss("phasenet")


def _batch(rng):
    import jax.numpy as jnp

    x = rng.standard_normal((BATCH, L, 3)).astype(np.float32)
    ppk = np.zeros((BATCH, L), np.float32)
    ppk[:, 64] = 1.0
    spk = np.zeros((BATCH, L), np.float32)
    spk[:, 128] = 1.0
    y = np.stack([1.0 - ppk - spk, ppk, spk], axis=-1)
    return jnp.asarray(x), jnp.asarray(y)


# ------------------------------------------------------------ decision table
def test_gate_drops_donation_with_cache_on_cpu(warm_cache_dir):
    assert jax.default_backend() == "cpu"
    assert resolve_donation((0,)) == ()


def test_gate_keeps_donation_without_cache(monkeypatch):
    monkeypatch.delenv("SEIST_DONATE_WITH_CACHE", raising=False)
    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    try:
        assert resolve_donation((0,)) == (0,)
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def test_gate_env_overrides(warm_cache_dir, monkeypatch):
    monkeypatch.setenv("SEIST_DONATE_WITH_CACHE", "1")
    assert resolve_donation((0,)) == (0,)
    monkeypatch.setenv("SEIST_DONATE_WITH_CACHE", "0")
    assert resolve_donation((0,)) == ()


def test_gate_passes_empty_through(warm_cache_dir):
    assert resolve_donation(()) == ()


# ------------------------------------------------------------- repro mirror
def test_deserialized_step_chain_is_correct(warm_cache_dir, rng):
    """The test_compile_budget repro, run WITH the persistent cache (the
    config that module must opt out of): warm the disk cache, re-wrap the
    step so the next call DESERIALIZES the executable, then run 4
    back-to-back unsynchronized steps. Under the donation gate the
    deserialized executable carries no aliasing, so the chain's state is
    exact every time — previously this flaked in 20-40% of processes."""
    state, spec, loss_fn = _setup()
    key = jax.random.PRNGKey(0)
    x, y = _batch(rng)

    step1 = jit_step(make_train_step(spec, loss_fn))
    state, loss, _ = step1(state, x, y, key)
    jax.block_until_ready((state, loss))  # executable now in the disk cache

    # Fresh wrap of an identical program: lowering runs again, the
    # compile is a persistent-cache hit -> deserialization path.
    step2 = jit_step(make_train_step(spec, loss_fn))
    for _ in range(4):
        state, loss, _ = step2(state, x, y, key)
    # No pre-read synchronization on purpose (the repro's trigger).
    first_read = int(state.step)
    second_read = int(state.step)
    assert first_read == second_read == 5
    leaf = jax.tree.leaves(state.params)[0]
    np.testing.assert_array_equal(np.asarray(leaf), np.asarray(leaf))
    assert np.isfinite(float(loss))
