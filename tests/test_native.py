"""Native wavekit kernels vs the numpy reference path.

Builds libwavekit.so on demand (g++ is in the image); skips if the build
fails. Parity uses fp32-accumulation tolerances.
"""

import importlib
import os
import subprocess

import numpy as np
import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")


@pytest.fixture(scope="module")
def native():
    lib = os.path.join(REPO, "seist_tpu", "native", "libwavekit.so")
    if not os.path.exists(lib):
        r = subprocess.run(["make", "native"], cwd=REPO, capture_output=True)
        if r.returncode != 0:
            pytest.skip(f"native build failed: {r.stderr.decode()[:200]}")
    import seist_tpu.native as native_mod

    native_mod = importlib.reload(native_mod)
    if not native_mod.available():
        pytest.skip("libwavekit.so not loadable")
    return native_mod


@pytest.mark.parametrize("mode", ["std", "max", ""])
def test_znorm_matches_numpy(native, mode, rng):
    data = rng.normal(3.0, 2.0, size=(3, 4096)).astype(np.float32)

    want = data - np.mean(data, axis=1, keepdims=True)
    if mode == "max":
        d = np.max(want, axis=1, keepdims=True)
        d[d == 0] = 1
        want = want / d
    elif mode == "std":
        d = np.std(want, axis=1, keepdims=True)
        d[d == 0] = 1
        want = want / d

    got = np.ascontiguousarray(data.copy())
    assert native.znorm(got, mode)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_znorm_zero_channel(native):
    data = np.zeros((2, 128), dtype=np.float32)
    got = data.copy()
    assert native.znorm(got, "std")
    assert np.all(got == 0)


def test_soft_label_matches_python(native, rng):
    from seist_tpu.data.preprocess import DataPreprocessor

    pre = DataPreprocessor(
        data_channels=["z", "n", "e"], sampling_rate=50, in_samples=1024
    )
    width = 25
    window = pre._soft_window(width, "gaussian")
    # Edge cases: negative, head-clipped, interior, tail-clipped, > L-1.
    idxs = np.array([-5, 3, 500, 1020, 1500], dtype=np.int64)

    got = np.zeros(1024)
    assert native.soft_label_add(got, idxs, window, width)

    want = np.zeros(1024)
    left = width // 2
    right = width - left
    for idx in idxs:
        if idx < 0 or idx > 1023:
            continue
        if idx - left < 0:
            want[: idx + right + 1] += window[width + 1 - (idx + right + 1) :]
        elif idx + right <= 1023:
            want[idx - left : idx + right + 1] += window
        else:
            want[-(1024 - (idx - left)) :] += window[: 1024 - (idx - left)]
    np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-12)


def test_preprocessor_uses_native_transparently(native, rng):
    """End-to-end: preprocess with the native path produces the same labels
    as the pure-python fallback."""
    from seist_tpu.data.preprocess import DataPreprocessor

    pre = DataPreprocessor(
        data_channels=["z", "n", "e"], sampling_rate=50, in_samples=2048
    )
    event = {
        "data": rng.normal(size=(3, 4096)).astype(np.float32),
        "ppks": [900],
        "spks": [1800],
        "snr": np.array([20.0, 20.0, 20.0]),
    }
    ev = pre.process(
        dict(event), augmentation=False, rng=np.random.default_rng(7), inplace=False
    )
    label = pre._generate_soft_label("ppk", ev)

    os.environ["SEIST_TPU_NATIVE"] = "0"
    try:
        import seist_tpu.native as native_mod

        importlib.reload(native_mod)
        assert not native_mod.available()
        ev2 = pre.process(
            dict(event),
            augmentation=False,
            rng=np.random.default_rng(7),
            inplace=False,
        )
        label2 = pre._generate_soft_label("ppk", ev2)
    finally:
        os.environ.pop("SEIST_TPU_NATIVE", None)
        importlib.reload(native_mod)

    np.testing.assert_allclose(
        np.asarray(ev["data"]), np.asarray(ev2["data"]), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(label, label2, rtol=1e-6, atol=1e-7)
