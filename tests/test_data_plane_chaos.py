"""Chaos e2e for the self-healing data plane (data/io_guard.py) — the
ISSUE acceptance checks, driven through REAL training runs:

* transient I/O faults (flaky reads absorbed by retries) must be
  *invisible*: final params bit-identical to a fault-free run;
* permanently-corrupt samples must be quarantined — exactly those, no
  more — reported at epoch end, and the run must still complete;
* a wedged loader (or a dead worker thread) must exit with the
  clean-preempt code within the watchdog timeout instead of hanging.

Slow lane (training runs dominated by jit compiles); `make chaos` runs
this file plus the faults unit lane.
"""

import glob
import os
import subprocess
import time

import numpy as np
import pytest

import seist_tpu
from seist_tpu.data import io_guard
from seist_tpu.utils.logger import logger

pytestmark = [pytest.mark.slow, pytest.mark.chaos]

seist_tpu.load_all()

# Shared run recipe (args factory, subprocess cmd/env helpers) with the
# PR 2 fault-tolerance e2e — one source of truth for the tiny synthetic
# training config.
from tests.test_fault_tolerance_e2e import _env, _train_cmd, make_args  # noqa: E402


def _params(ckpt_path):
    import jax

    from seist_tpu.train.checkpoint import load_checkpoint

    return jax.tree.leaves(load_checkpoint(ckpt_path)["params"])


# ------------------------------------------------- transient: bit-identical
def test_transient_io_faults_train_bit_identical(tmp_path, monkeypatch):
    """~flaky reads on half the samples, every one absorbed by a retry:
    the fault run must consume the exact same byte stream and land on
    BIT-IDENTICAL final params (the retry path returns the same data a
    clean read would — no quarantine, no fallback, no reordering)."""
    from seist_tpu.train.worker import train_worker

    logger.set_logdir(str(tmp_path / "clean"))
    ckpt_clean = train_worker(make_args())
    assert ckpt_clean

    # Deterministic per-sample selection: p=0.5 guarantees hits on a
    # 32-sample train split; each flaky read fails exactly its first
    # attempt, well inside the default 3-attempt budget.
    monkeypatch.setenv("SEIST_FAULT_IO_FLAKY_P", "0.5")
    io_guard.COUNTERS.reset()
    logger.set_logdir(str(tmp_path / "flaky"))
    ckpt_flaky = train_worker(make_args())
    assert ckpt_flaky

    snap = io_guard.COUNTERS.snapshot()
    assert snap["retries"] > 0, "injected flakiness never fired"
    assert snap["quarantined"] == 0, "transient faults must not quarantine"
    for a, b in zip(_params(ckpt_clean), _params(ckpt_flaky)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --------------------------------------------- corrupt: exact quarantine
def test_corrupt_samples_quarantined_exactly_and_reported(
    tmp_path, monkeypatch
):
    """Permanently-corrupt samples 5 and 9 (raw train indices; outside
    the 4-sample val split's index space so the count is exact): the run
    completes, quarantines exactly those two, and the epoch-end report in
    the log lists them."""
    from seist_tpu.train.worker import train_worker

    monkeypatch.setenv("SEIST_FAULT_IO_CORRUPT", "5,9")
    io_guard.COUNTERS.reset()
    logger.set_logdir(str(tmp_path))
    ckpt = train_worker(make_args(max_quarantine_frac=0.25))
    assert ckpt and os.path.exists(ckpt)

    snap = io_guard.COUNTERS.snapshot()
    assert snap["quarantined"] == 2, snap
    assert snap["fallback_reads"] >= 2, snap
    with open(os.path.join(str(tmp_path), "global.log")) as f:
        log = f.read()
    assert '"quarantined": [5, 9]' in log, log[-3000:]
    assert "quarantine report" in log
    # Replacement kept every batch full: params stayed finite, training
    # checkpointed normally.
    for leaf in _params(ckpt):
        assert np.isfinite(np.asarray(leaf)).all()


def test_corrupt_sample_zero_falls_back_from_device_aug(
    tmp_path, monkeypatch
):
    """--device-aug with a permanently-corrupt raw sample 0: the size
    probe (RawStore.estimate_bytes) refuses it, the worker falls back to
    the host path (logged), and the run completes with the sample
    quarantined there — not a crash at setup."""
    from seist_tpu.train.worker import train_worker

    monkeypatch.setenv("SEIST_FAULT_IO_CORRUPT", "0")
    io_guard.COUNTERS.reset()
    logger.set_logdir(str(tmp_path))
    ckpt = train_worker(
        make_args(device_aug="cached", max_quarantine_frac=0.25)
    )
    assert ckpt and os.path.exists(ckpt)
    with open(os.path.join(str(tmp_path), "global.log")) as f:
        log = f.read()
    assert "--device-aug cached -> off" in log, log[-3000:]
    assert io_guard.COUNTERS.snapshot()["quarantined"] >= 1


def test_rotted_dataset_aborts_loudly(tmp_path, monkeypatch):
    """Past --max-quarantine-frac the run must die (QuarantineOverflow),
    NOT train on fallbacks or preempt-relaunch."""
    from seist_tpu.train.worker import train_worker

    monkeypatch.setenv("SEIST_FAULT_IO_CORRUPT", "1,2,3,4,5,6,7,8")
    logger.set_logdir(str(tmp_path))
    with pytest.raises(io_guard.QuarantineOverflowError):
        train_worker(make_args(max_quarantine_frac=0.1))


# ------------------------------------------- packed shards: same contract
def _pack_for_chaos(tmp_path):
    """Synthetic pack matching the make_args training recipe, plus a
    train-split victim sample that is the file-tail of its shard (so a
    truncation kills exactly that sample)."""
    from seist_tpu.data.packed import PackedDataset, PackSource, pack_sources

    out = str(tmp_path / "pack")
    pack_sources(
        [
            PackSource(
                name="synthetic",
                dataset_kwargs={
                    "num_events": 40, "trace_samples": 2048, "cache": False,
                },
            )
        ],
        out,
        samples_per_shard=8,
    )
    with np.load(os.path.join(out, "index.npz"), allow_pickle=False) as z:
        shard, offset = z["shard"], z["offset"]
    ds = PackedDataset(seed=1, mode="train", data_dir=out)
    frame = ds._meta_data
    victims = [
        (pos, int(r_shard), int(r_off))
        for pos, (r_shard, r_off) in enumerate(
            zip(frame["shard"].to_numpy(), frame["offset"].to_numpy())
        )
        if r_off == offset[shard == r_shard].max()
    ]
    assert victims, "no shard-tail sample landed in the train split"
    pos, v_shard, v_off = victims[0]
    row_nbytes = int(frame["n_ch"].iloc[0]) * int(frame["n_samp"].iloc[0]) * 4
    return out, pos, v_shard, v_off + row_nbytes // 2


def _assert_quarantine_report(logdir, pos):
    with open(os.path.join(str(logdir), "global.log")) as f:
        log = f.read()
    assert "quarantine report" in log, log[-3000:]
    assert f'"quarantined": [{pos}]' in log, log[-3000:]
    assert "truncated shard" in log, log[-3000:]


def test_packed_shard_truncation_quarantined_e2e(tmp_path):
    """ISSUE acceptance: a shard truncated mid-epoch surfaces as a short
    memmap read; the sample is quarantined + deterministically replaced,
    training completes, and the epoch-end report names it — io_guard
    parity between the packed path and the HDF5 readers."""
    from seist_tpu.data.packed import shard_path
    from seist_tpu.train.worker import train_worker

    out, pos, v_shard, cut = _pack_for_chaos(tmp_path)
    with open(shard_path(out, v_shard), "r+b") as f:
        f.truncate(cut)

    io_guard.COUNTERS.reset()
    logger.set_logdir(str(tmp_path / "logs"))
    ckpt = train_worker(
        make_args(
            dataset_name="packed", data=out, dataset_kwargs={},
            max_quarantine_frac=0.25,
        )
    )
    assert ckpt and os.path.exists(ckpt)
    snap = io_guard.COUNTERS.snapshot()
    assert snap["quarantined"] == 1, snap
    assert snap["fallback_reads"] >= 1, snap
    _assert_quarantine_report(tmp_path / "logs", pos)
    for leaf in _params(ckpt):
        assert np.isfinite(np.asarray(leaf)).all()


def test_packed_truncation_direct_ingest_e2e(tmp_path):
    """The same truncation through --device-aug step + --ingest direct:
    the staging-fill fault ladder (data/ingest.py) quarantines and the
    run completes — the fast path carries the full PR 5 contract."""
    from seist_tpu.data.packed import shard_path
    from seist_tpu.train.worker import train_worker

    out, pos, v_shard, cut = _pack_for_chaos(tmp_path)
    with open(shard_path(out, v_shard), "r+b") as f:
        f.truncate(cut)

    io_guard.COUNTERS.reset()
    logger.set_logdir(str(tmp_path / "logs"))
    ckpt = train_worker(
        make_args(
            dataset_name="packed", data=out, dataset_kwargs={},
            device_aug="step", ingest="direct",
            max_quarantine_frac=0.25,
        )
    )
    assert ckpt and os.path.exists(ckpt)
    assert io_guard.COUNTERS.snapshot()["quarantined"] == 1
    _assert_quarantine_report(tmp_path / "logs", pos)
    with open(os.path.join(str(tmp_path / "logs"), "global.log")) as f:
        assert "packed direct ingest" in f.read()


# ------------------------------------------------ loader death -> preempt
def test_loader_thread_death_exits_preempt_code(tmp_path, monkeypatch):
    """A loader worker raising a non-fault exception mid-epoch surfaces
    as a checkpoint + hard preempt exit (rc 75), not a hang and not an
    opaque crash (ISSUE satellite: this behavior was undefined). The
    production path ends in io_guard.hard_exit (os._exit — sys.exit
    would join the wedged non-daemon pool threads forever); monkeypatch
    it to a raise so the in-process test survives to assert."""
    from seist_tpu.data.pipeline import SeismicDataset
    from seist_tpu.train.checkpoint import PREEMPT_EXIT_CODE
    from seist_tpu.train.worker import train_worker

    def fake_hard_exit(code):
        raise SystemExit(code)

    monkeypatch.setattr(io_guard, "hard_exit", fake_hard_exit)
    orig = SeismicDataset.__getitem__
    state = {"n": 0}

    def dying(self, idx):
        state["n"] += 1
        if state["n"] > 20:  # let a couple of batches through first
            raise RuntimeError("simulated loader bug")
        return orig(self, idx)

    monkeypatch.setattr(SeismicDataset, "__getitem__", dying)
    logger.set_logdir(str(tmp_path))
    with pytest.raises(SystemExit) as ei:
        train_worker(make_args(save_interval_steps=1))
    assert ei.value.code == PREEMPT_EXIT_CODE
    with open(os.path.join(str(tmp_path), "global.log")) as f:
        log = f.read()
    assert "Loader worker death" in log
    assert "--- thread" in log  # stack dump made it to the log
    # The preempt saved a resumable checkpoint before exiting.
    assert glob.glob(os.path.join(str(tmp_path), "checkpoints", "model_*"))


# ------------------------------------------------- stall -> watchdog e2e
def test_loader_stall_preempts_within_watchdog_timeout(tmp_path):
    """The hard acceptance check: a loader wedged mid-epoch (injected
    stall) must NOT hang the run — the watchdog dumps stacks and exits
    with the clean-preempt code within its timeout, as a real subprocess
    so the os._exit path is exercised for real."""
    from seist_tpu.train.checkpoint import PREEMPT_EXIT_CODE

    log_base = str(tmp_path / "logs")
    t0 = time.monotonic()
    proc = subprocess.run(
        _train_cmd(log_base, extra=("--data-watchdog-sec", "5")),
        env=_env(
            SEIST_FAULT_IO_STALL_BATCH="2",
            SEIST_FAULT_IO_STALL_SEC="600",
        ),
        capture_output=True,
        text=True,
        timeout=420,
    )
    elapsed = time.monotonic() - t0
    assert proc.returncode == PREEMPT_EXIT_CODE, (
        proc.returncode, proc.stdout[-2000:], proc.stderr[-2000:]
    )
    # Exited via the watchdog, not by waiting out the 600 s stall.
    assert elapsed < 400, elapsed
    assert "pipeline stall" in proc.stdout
    assert "--- thread" in proc.stdout  # stack dump
    log = glob.glob(os.path.join(log_base, "*", "global.log"))
    assert log, "run never created a log dir"
