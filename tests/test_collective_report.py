"""Regression tests for tools/collective_report.py's payload attribution
(advisor r4: gradient bytes must never silently land in the bn_stat
bucket when XLA's combiner drops op_name metadata)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from collective_report import attribute_collectives  # noqa: E402


def _op(kind, dims, nbytes, op_name=""):
    return {"kind": kind, "shape_dims": dims, "bytes": nbytes, "op_name": op_name}


PARAMS = {(64, 3, 16), (16,)}


def test_marked_gradient_allreduce_attributed():
    ops = [
        _op("all-reduce", [(64, 3, 16)], 12288, "transpose(jvp(Conv))/add"),
        _op("all-reduce", [(16,)], 64, "batch_norm/mean"),
    ]
    b = attribute_collectives(ops, PARAMS, batch=32, devices=8)
    assert b["grad_ops"] == 1 and b["grad_bytes"] == 12288
    # the BN stat all-reduce is param-shaped but unmarked -> unattributed
    assert b["unattr_ops"] == 1 and b["unattr_bytes"] == 64
    assert not b["warn_unattributed"]  # gradient ops were found


def test_unattributed_bucket_warns_when_no_gradients_found():
    """The advisor-r4 case: XLA combined the gradient all-reduces and
    dropped the transpose(jvp) metadata — the report must bucket the
    bytes as unattributed AND flag them, never claim ~0 gradient
    traffic silently."""
    ops = [
        _op("all-reduce", [(64, 3, 16), (16,)], 12352, "combined/all"),
    ]
    b = attribute_collectives(ops, PARAMS, batch=32, devices=8)
    assert b["grad_ops"] == 0
    assert b["unattr_ops"] == 1 and b["unattr_bytes"] == 12352
    assert b["other_bytes"] == 12352  # also included in the bn_stat bucket
    assert b["warn_unattributed"]


def test_activation_traffic_by_batch_leading_dim():
    ops = [
        _op("all-gather", [(32, 128, 8)], 131072, "remat/fwd"),
        _op("all-gather", [(4, 128, 8)], 16384, "remat/fwd"),  # per-shard
        _op("all-reduce", [()], 4, "loss/mean"),
    ]
    b = attribute_collectives(ops, PARAMS, batch=32, devices=8)
    assert b["act_ops"] == 2
    assert b["act_bytes"] == 131072 + 16384
    assert b["other_bytes"] == 4
    assert not b["warn_unattributed"]  # no param-shaped bytes at all


def test_param_shape_not_shadowed_by_batch_dim():
    """A param whose leading dim equals the batch size must still hit the
    unattributed bucket, not the activation heuristic."""
    params = {(32, 7)}
    ops = [_op("all-reduce", [(32, 7)], 896, "combined")]
    b = attribute_collectives(ops, params, batch=32, devices=8)
    assert b["unattr_ops"] == 1 and b["act_ops"] == 0
    assert b["warn_unattributed"]
