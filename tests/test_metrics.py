"""Tests for seist_tpu.ops.metrics — per-task semantics from the reference
(utils/metrics.py:101-332), hand fixtures + formula cross-checks."""

import numpy as np
import pytest

from seist_tpu.ops import metrics as M


def make(task, names, fs=100, thr=0.1, n=64):
    return M.Metrics(
        task=task,
        metric_names=names,
        sampling_rate=fs,
        time_threshold=thr,
        num_samples=n,
    )


class TestPhasePicking:
    def test_tp_within_tolerance(self):
        m = make("ppk", ["precision", "recall", "f1"], fs=100, thr=0.1, n=1000)
        # tolerance = 10 samples
        t = np.array([[100], [200], [300]])
        p = np.array([[105], [215], [-(10**7)]])  # hit, miss(>10), padded miss
        m.compute(t, p)
        r = m.get_all_metrics()
        assert r["precision"] == pytest.approx(1 / 2, abs=1e-4)
        assert r["recall"] == pytest.approx(1 / 3, abs=1e-4)
        f1 = 2 * (1 / 2) * (1 / 3) / (1 / 2 + 1 / 3)
        assert r["f1"] == pytest.approx(f1, abs=1e-4)

    def test_out_of_range_not_counted(self):
        m = make("ppk", ["precision", "recall"], n=100)
        t = np.array([[150]])  # target outside num_samples -> not a possp
        p = np.array([[50]])
        m.compute(t, p)
        r = m.get_all_metrics()
        assert r["recall"] == pytest.approx(0.0, abs=1e-3)

    def test_masked_residual_metrics(self):
        m = make("ppk", ["f1", "mae", "rmse"], fs=100, thr=0.1, n=1000)
        t = np.array([[100], [200]])
        p = np.array([[103], [500]])  # only first is TP -> only it contributes
        m.compute(t, p)
        r = m.get_all_metrics()
        assert r["mae"] == pytest.approx(3 / 2, abs=1e-4)  # masked sum / data_size
        assert r["rmse"] == pytest.approx(np.sqrt(9 / 2), abs=1e-4)

    def test_order_phases_matching(self):
        # Two phases predicted in swapped order still match greedily.
        m = make("ppk", ["precision", "recall"], fs=100, thr=0.1, n=1000)
        t = np.array([[100, 400]])
        p = np.array([[398, 102]])
        m.compute(t, p)
        r = m.get_all_metrics()
        assert r["precision"] == pytest.approx(1.0, abs=1e-4)
        assert r["recall"] == pytest.approx(1.0, abs=1e-4)

    def test_order_phases_function(self):
        t = np.array([[10, 50, 90]])
        p = np.array([[88, 12, 49]])
        ordered = np.asarray(M.order_phases(t, p))
        np.testing.assert_array_equal(ordered, [[12, 49, 88]])

    def test_order_phases_with_padding(self):
        # A PAD prediction (-1e7) is ~1e7 away from real targets — masked
        # cells must never be re-selected over it (divergence from the
        # reference's 1e6 mask constant, which loses the true match here).
        pad = -(10**7)
        t = np.array([[1000, 2000]])
        p = np.array([[1010, pad]])
        ordered = np.asarray(M.order_phases(t, p))
        np.testing.assert_array_equal(ordered, [[1010, pad]])
        m = make("ppk", ["precision", "recall"], fs=100, thr=0.1, n=8192)
        m.compute(t, p)
        r = m.get_all_metrics()
        assert r["recall"] == pytest.approx(1 / 2, abs=1e-4)
        assert r["precision"] == pytest.approx(1 / 1, abs=1e-4)


class TestDetection:
    def test_overlap(self):
        m = make("det", ["precision", "recall", "f1"], n=100)
        t = np.array([[20, 40], [60, 80]])
        p = np.array([[25, 45], [0, 10]])  # overlap / disjoint
        m.compute(t, p)
        c = {k: float(np.asarray(v)) for k, v in m.counters.items() if k != "data_size"}
        # row0: target covers 21, pred 21, overlap 16; row1: 21 / 11 / 0
        assert c["tp"] == 16
        assert c["predp"] == 32
        assert c["possp"] == 42

    def test_padding_pair_inert(self):
        m = make("det", ["precision", "recall"], n=100)
        t = np.array([[20, 40, 1, 0]])  # padded second interval [1,0]
        p = np.array([[20, 40, 1, 0]])
        m.compute(t, p)
        c = m.counters
        assert float(np.asarray(c["possp"])) == 21  # [1,0] adds nothing


class TestOneHot:
    def test_confusion(self):
        m = make("pmp", ["precision", "recall", "f1"])
        t = np.array([[1, 0], [0, 1], [1, 0], [0, 1]], dtype=np.float32)
        p = np.array(
            [[0.9, 0.1], [0.2, 0.8], [0.3, 0.7], [0.6, 0.4]], dtype=np.float32
        )
        m.compute(t, p)
        r = m.get_all_metrics()
        # per-class: c0 tp=1 predp=2 possp=2; c1 tp=1 predp=2 possp=2 -> macro 0.5
        assert r["precision"] == pytest.approx(0.5, abs=1e-4)
        assert r["recall"] == pytest.approx(0.5, abs=1e-4)


class TestRegression:
    def test_value_metrics(self):
        m = make("emg", ["mean", "rmse", "mae", "mape", "r2"])
        t = np.array([[2.0], [4.0], [6.0]])
        p = np.array([[2.5], [3.0], [6.0]])
        m.compute(t, p)
        r = m.get_all_metrics()
        res = t - p
        assert r["mean"] == pytest.approx(res.mean(), abs=1e-5)
        assert r["rmse"] == pytest.approx(np.sqrt((res**2).mean()), abs=1e-5)
        assert r["mae"] == pytest.approx(np.abs(res).mean(), abs=1e-5)
        ss_res = (res**2).mean(-1).sum()
        tc = t - t.mean()
        ss_tot = (tc**2).mean(-1).sum()
        assert r["r2"] == pytest.approx(1 - ss_res / (ss_tot + 1e-6), abs=1e-5)

    def test_baz_wraparound(self):
        m = make("baz", ["mae"])
        t = np.array([[359.0]])
        p = np.array([[1.0]])
        m.compute(t, p)
        assert m.get_all_metrics()["mae"] == pytest.approx(2.0, abs=1e-4)

    def test_streaming_equals_single_batch(self):
        rng = np.random.default_rng(3)
        t = rng.normal(3, 1, size=(32, 1))
        p = t + rng.normal(0, 0.5, size=(32, 1))
        whole = make("emg", ["mean", "rmse", "mae", "r2"])
        whole.compute(t, p)
        parts = make("emg", ["mean", "rmse", "mae", "r2"])
        for i in range(0, 32, 8):
            parts.compute(t[i : i + 8], p[i : i + 8])
        for k, v in whole.get_all_metrics().items():
            assert parts.get_all_metrics()[k] == pytest.approx(v, abs=1e-5), k


class TestAccumulation:
    def test_add_and_dunder_add(self):
        a = make("emg", ["mae"])
        b = make("emg", ["mae"])
        a.compute(np.array([[1.0]]), np.array([[2.0]]))
        b.compute(np.array([[5.0]]), np.array([[1.0]]))
        c = a + b
        assert c.get_all_metrics()["mae"] == pytest.approx((1 + 4) / 2, abs=1e-5)
        a.add(b)
        assert a.get_all_metrics()["mae"] == pytest.approx((1 + 4) / 2, abs=1e-5)

    def test_merge_counters_pytree(self):
        x = M.init_counters(["precision"])
        y = {k: v + 1 for k, v in x.items()}
        z = M.merge(x, y)
        assert float(np.asarray(z["tp"])) == 1.0

    def test_merge_mismatch_raises(self):
        with pytest.raises(TypeError):
            M.merge({"tp": np.zeros(())}, {"predp": np.zeros(())})
