"""jaxlint tests: every rule catches its seeded violation and stays quiet
on the clean twin; suppression, baseline gating, CLI exit codes, and the
runtime audit lane (CompileBudget mechanics + tracer-leak check) on tiny
jit programs. The real-model compile-budget regression lives in
tests/test_compile_budget.py (model compiles are too heavy for the smoke
lane)."""

import json
import os
import textwrap

import jax
import jax.numpy as jnp
import pytest

# repo root is put on sys.path by tests/conftest.py
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from tools.jaxlint import __main__ as jaxlint_cli  # noqa: E402
from tools.jaxlint.engine import Baseline, lint_source  # noqa: E402
from tools.jaxlint.runtime import (  # noqa: E402
    CompileBudget,
    tracer_leak_check,
)

HOT = "seist_tpu/train/step.py"  # a hot-path glob match
COLD = "seist_tpu/cli.py"


def rules_of(src, path=COLD):
    return [f.rule for f in lint_source(textwrap.dedent(src), path)]


def lines_of(src, rule, path=COLD):
    return [
        f.line
        for f in lint_source(textwrap.dedent(src), path)
        if f.rule == rule
    ]


# ------------------------------------------------------- host-sync-hot-path
def test_hot_path_float_in_loop_flagged():
    src = """
    def run(batch):
        acc = 0.0
        for x in batch:
            acc += float(x)
        return acc
    """
    assert rules_of(src, HOT) == ["host-sync-hot-path"]
    # identical code off the hot path is legal
    assert rules_of(src, COLD) == []


def test_hot_path_item_flagged_anywhere_in_module():
    src = """
    def summary(loss):
        return loss.item()
    """
    assert rules_of(src, HOT) == ["host-sync-hot-path"]


def test_hot_path_traced_body_flagged():
    src = """
    def train_step(state, x):
        return state, int(x.sum())
    """
    assert rules_of(src, HOT) == ["host-sync-hot-path"]


def test_hot_path_oneshot_config_coercion_ok():
    src = """
    def setup(cfg):
        lr = float(cfg.lr)
        n = int(cfg.steps)
        return lr, n
    """
    assert rules_of(src, HOT) == []


def test_hot_path_asarray_in_loop_flagged():
    src = """
    def drain(chunks, fn):
        out = []
        while chunks:
            out.append(np.asarray(fn(chunks.pop())))
        return out
    """
    assert rules_of(src, HOT) == ["host-sync-hot-path"]


# ------------------------------------------------------ host-sync-item-loop
def test_item_in_loop_flagged_everywhere():
    src = """
    def to_host(counters):
        out = {}
        for k, v in counters.items():
            out[k] = v.item()
        return out
    """
    assert rules_of(src) == ["host-sync-item-loop"]


def test_per_entry_device_get_flagged():
    src = """
    def to_host(counters):
        out = {}
        for k in counters:
            out[k] = jax.device_get(counters[k])
        return out
    """
    assert rules_of(src) == ["host-sync-item-loop"]


def test_batched_device_get_in_epoch_loop_ok():
    src = """
    def train(epochs, losses):
        for epoch in range(epochs):
            host = jax.device_get(losses)
        return host
    """
    assert rules_of(src) == []


# --------------------------------------------------------- prng-key-reuse
def test_key_dual_use_flagged():
    src = """
    def f(key):
        a = jax.random.normal(key, (3,))
        b = jax.random.uniform(key, (3,))
        return a + b
    """
    assert rules_of(src) == ["prng-key-reuse"]
    assert lines_of(src, "prng-key-reuse") == [4]  # the SECOND consumption


def test_key_split_between_uses_ok():
    src = """
    def f(key):
        k1, k2 = jax.random.split(key)
        a = jax.random.normal(k1, (3,))
        b = jax.random.uniform(k2, (3,))
        return a + b
    """
    assert rules_of(src) == []


def test_key_reassigned_between_uses_ok():
    src = """
    def f(key):
        a = jax.random.normal(key, (3,))
        key = jax.random.fold_in(key, 1)
        b = jax.random.uniform(key, (3,))
        return a + b
    """
    assert rules_of(src) == []


def test_key_reuse_across_loop_iterations_flagged():
    src = """
    def f(key, n):
        out = []
        for i in range(n):
            out.append(jax.random.normal(key, (3,)))
        return out
    """
    assert rules_of(src) == ["prng-key-reuse"]


def test_key_folded_per_iteration_ok():
    src = """
    def f(key, n):
        out = []
        for i in range(n):
            k = jax.random.fold_in(key, i)
            out.append(jax.random.normal(k, (3,)))
        return out
    """
    assert rules_of(src) == []


def test_split_iteration_ok():
    src = """
    def f(key, xs):
        for x, k in zip(xs, jax.random.split(key, len(xs))):
            yield jax.random.normal(k, x.shape)
    """
    assert rules_of(src) == []


def test_key_draws_on_exclusive_branches_ok():
    # at most one branch executes per call — not a reuse
    src = """
    def f(key, cond):
        if cond:
            x = jax.random.uniform(key, (3,))
        else:
            x = jax.random.normal(key, (3,))
        return x
    """
    assert rules_of(src) == []


def test_key_ternary_branches_ok_but_third_use_flagged():
    src = """
    def f(key, cond):
        x = jax.random.uniform(key, (3,)) if cond else jax.random.normal(key, (3,))
        y = jax.random.bernoulli(key)
        return x, y
    """
    # the ternary arms are exclusive; the draw AFTER the ternary is reuse
    assert rules_of(src) == ["prng-key-reuse"]
    assert lines_of(src, "prng-key-reuse") == [4]


def test_key_alias_import_tracked():
    src = """
    import jax.random as jr

    def f(key):
        a = jr.normal(key, (3,))
        b = jr.uniform(key, (3,))
        return a + b
    """
    assert rules_of(src) == ["prng-key-reuse"]


# ---------------------------------------------------------- jit-no-donate
def test_jit_state_step_without_donate_flagged():
    src = """
    def train_step(state, batch, rng):
        return state

    f = jax.jit(train_step)
    """
    assert rules_of(src) == ["jit-no-donate"]


def test_jit_with_donate_ok():
    src = """
    def train_step(state, batch, rng):
        return state

    f = jax.jit(train_step, donate_argnums=(0,))
    """
    assert rules_of(src) == []


def test_bare_jit_decorator_on_state_fn_flagged():
    src = """
    @jax.jit
    def update_step(state, grads):
        return state
    """
    assert rules_of(src) == ["jit-no-donate"]


def test_eval_step_without_donate_ok():
    # eval must NOT donate: the state is reused by the caller
    src = """
    def eval_step(state, batch):
        return state.apply_fn(batch)

    f = jax.jit(eval_step)
    """
    assert rules_of(src) == []


# ------------------------------------------------------- impure-call-in-jit
def test_wallclock_in_traced_step_flagged():
    src = """
    def train_step(state, x):
        started = time.time()
        return state, started
    """
    assert rules_of(src) == ["impure-call-in-jit"]


def test_np_random_in_jitted_fn_flagged():
    src = """
    @jax.jit
    def noisy(x):
        return x + np.random.rand()
    """
    assert rules_of(src) == ["impure-call-in-jit"]


def test_wallclock_in_host_fn_ok():
    src = """
    def report():
        return time.time()
    """
    assert rules_of(src) == []


# ------------------------------------------------------------- jit-in-loop
def test_jit_inside_loop_flagged():
    src = """
    def serve(models, x):
        for m in models:
            y = jax.jit(m)(x)
        return y
    """
    assert rules_of(src) == ["jit-in-loop"]


def test_jit_hoisted_ok():
    src = """
    def serve(model, xs):
        f = jax.jit(model)
        return [f(x) for x in xs]
    """
    assert rules_of(src) == []


# ------------------------------------------------------- nonhashable-static
def test_static_list_default_flagged():
    src = """
    def apply(x, dims=[0, 1]):
        return x

    f = jax.jit(apply, static_argnums=(1,))
    """
    assert rules_of(src) == ["nonhashable-static"]


def test_static_argnames_dict_default_flagged():
    src = """
    def apply(x, opts={}):
        return x

    f = jax.jit(apply, static_argnames=("opts",))
    """
    assert rules_of(src) == ["nonhashable-static"]


def test_static_tuple_default_ok():
    src = """
    def apply(x, dims=(0, 1)):
        return x

    f = jax.jit(apply, static_argnums=(1,))
    """
    assert rules_of(src) == []


# ------------------------------------------------------- wallclock-interval
def test_time_time_interval_flagged():
    src = """
    def run(work):
        t0 = time.time()
        work()
        return time.time() - t0
    """
    assert rules_of(src) == ["wallclock-interval"]


def test_wallclock_name_reassigned_to_monotonic_ok():
    # last-assignment taint: a wall-clock timestamp earlier in the scope
    # must not poison later monotonic interval math on the same name
    src = """
    def run(record, work):
        t0 = time.time()
        record["started_at"] = t0
        t0 = time.monotonic()
        work()
        return time.monotonic() - t0
    """
    assert rules_of(src) == []


def test_wallclock_name_reassigned_to_wallclock_flagged():
    src = """
    def run(work):
        t0 = time.monotonic()
        t0 = time.time()
        work()
        return time.time() - t0
    """
    assert rules_of(src) == ["wallclock-interval"]


def test_monotonic_interval_ok():
    src = """
    def run(work):
        t0 = time.monotonic()
        work()
        return time.monotonic() - t0
    """
    assert rules_of(src) == []


def test_time_time_timestamp_ok():
    src = """
    def stamp(record):
        record["ts"] = time.time()
        return record
    """
    assert rules_of(src) == []


# ------------------------------------------------------------ broad-except
def test_broad_except_without_rationale_flagged():
    src = """
    def f():
        try:
            risky()
        except Exception:
            pass
    """
    assert rules_of(src) == ["broad-except"]


def test_bare_except_flagged():
    src = """
    def f():
        try:
            risky()
        except:
            pass
    """
    assert rules_of(src) == ["broad-except"]


def test_broad_except_with_rationale_ok():
    src = """
    def f():
        try:
            risky()
        # best-effort cleanup: failure here must not mask the real error
        except Exception:
            pass
    """
    assert rules_of(src) == []


def test_broad_except_reraise_ok():
    src = """
    def f():
        try:
            risky()
        except Exception:
            cleanup()
            raise
    """
    assert rules_of(src) == []


def test_narrow_except_ok():
    src = """
    def f():
        try:
            risky()
        except ValueError:
            pass
    """
    assert rules_of(src) == []


# ------------------------------------------------------------- suppression
def test_suppression_with_rationale_silences():
    src = """
    def f(key):
        a = jax.random.normal(key, (3,))
        b = jax.random.uniform(key, (3,))  # jaxlint: disable=prng-key-reuse -- fixture wants correlated draws
        return a + b
    """
    assert rules_of(src) == []


def test_suppression_above_line_silences():
    src = """
    def f(key):
        a = jax.random.normal(key, (3,))
        # jaxlint: disable=prng-key-reuse -- fixture wants correlated draws
        b = jax.random.uniform(key, (3,))
        return a + b
    """
    assert rules_of(src) == []


def test_suppression_rationale_wrapping_onto_second_comment_line():
    # the standalone comment must cover the next CODE line, skipping the
    # wrapped continuation comment in between
    src = """
    def f(key):
        a = jax.random.normal(key, (3,))
        # jaxlint: disable=prng-key-reuse -- fixture wants correlated draws
        # (see docs/STATIC_ANALYSIS.md for why this is safe)
        b = jax.random.uniform(key, (3,))
        return a + b
    """
    assert rules_of(src) == []


def test_suppression_above_blank_line_still_covers():
    src = """
    def f(key):
        a = jax.random.normal(key, (3,))
        # jaxlint: disable=prng-key-reuse -- fixture wants correlated draws

        b = jax.random.uniform(key, (3,))
        return a + b
    """
    assert rules_of(src) == []


def test_suppression_without_rationale_is_void_and_flagged():
    src = """
    def f(key):
        a = jax.random.normal(key, (3,))
        b = jax.random.uniform(key, (3,))  # jaxlint: disable=prng-key-reuse
        return a + b
    """
    assert sorted(rules_of(src)) == [
        "prng-key-reuse",
        "suppression-missing-rationale",
    ]


def test_suppression_of_other_rule_does_not_silence():
    src = """
    def f(key):
        a = jax.random.normal(key, (3,))
        b = jax.random.uniform(key, (3,))  # jaxlint: disable=broad-except -- wrong rule on purpose
        return a + b
    """
    # the original finding survives AND the pointless suppression is called out
    assert sorted(rules_of(src)) == ["prng-key-reuse", "unused-suppression"]


# ---------------------------------------------------------------- baseline
_VIOLATION = """
def f(key):
    a = jax.random.normal(key, (3,))
    b = jax.random.uniform(key, (3,))
    return a + b
"""


def test_baseline_grandfathers_then_catches_new():
    findings = lint_source(_VIOLATION, "pkg/mod.py")
    assert len(findings) == 1
    base = Baseline.from_findings(findings)
    assert base.new_findings(findings) == []

    # a SECOND violation of the same kind on a new line is caught
    doubled = _VIOLATION + textwrap.dedent(
        """
        def g(key):
            c = jax.random.normal(key, (4,))
            d = jax.random.uniform(key, (4,))
            return c + d
        """
    )
    new = base.new_findings(lint_source(doubled, "pkg/mod.py"))
    assert [f.rule for f in new] == ["prng-key-reuse"]
    assert new[0].line > findings[0].line


def test_baseline_keys_survive_line_shifts():
    shifted = "\n\n\n\n" + _VIOLATION  # everything moves 4 lines down
    base = Baseline.from_findings(lint_source(_VIOLATION, "pkg/mod.py"))
    assert base.new_findings(lint_source(shifted, "pkg/mod.py")) == []


def test_baseline_reports_stale_entries():
    base = Baseline.from_findings(lint_source(_VIOLATION, "pkg/mod.py"))
    clean = lint_source("def f():\n    return 0\n", "pkg/mod.py")
    assert clean == []
    assert len(base.stale_keys(clean)) == 1


def test_repo_baseline_is_green():
    """The shipped gate: the package must be clean vs the checked-in
    baseline (this is exactly what `make lint` runs)."""
    rc = jaxlint_cli.main(["seist_tpu", "--root", _REPO])
    assert rc == 0


# --------------------------------------------------------------------- CLI
def test_cli_flags_seeded_violation(tmp_path, capsys):
    mod = tmp_path / "bad.py"
    mod.write_text(_VIOLATION)
    rc = jaxlint_cli.main(
        ["bad.py", "--root", str(tmp_path), "--no-baseline", "--format", "json"]
    )
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["total"] == 1
    assert out["new"][0]["rule"] == "prng-key-reuse"
    assert out["new"][0]["file"] == "bad.py"


def test_cli_update_baseline_roundtrip(tmp_path, capsys):
    mod = tmp_path / "bad.py"
    mod.write_text(_VIOLATION)
    baseline = tmp_path / "baseline.json"
    rc = jaxlint_cli.main(
        ["bad.py", "--root", str(tmp_path), "--baseline", str(baseline),
         "--update-baseline"]
    )
    assert rc == 0 and baseline.exists()
    capsys.readouterr()
    rc = jaxlint_cli.main(
        ["bad.py", "--root", str(tmp_path), "--baseline", str(baseline)]
    )
    assert rc == 0  # grandfathered


def test_cli_nonexistent_path_exits_2(tmp_path, capsys):
    rc = jaxlint_cli.main(["no_such_pkg", "--root", str(tmp_path)])
    assert rc == 2
    assert "does not exist" in capsys.readouterr().err


def test_cli_update_baseline_with_select_refused(tmp_path):
    (tmp_path / "x.py").write_text("x = 1\n")
    with pytest.raises(SystemExit):
        jaxlint_cli.main(
            ["x.py", "--root", str(tmp_path), "--select", "broad-except",
             "--update-baseline"]
        )


def test_cli_subset_update_preserves_other_files(tmp_path, capsys):
    (tmp_path / "a.py").write_text(_VIOLATION)
    (tmp_path / "b.py").write_text(_VIOLATION)
    baseline = tmp_path / "baseline.json"
    args = ["--root", str(tmp_path), "--baseline", str(baseline)]
    assert jaxlint_cli.main(["a.py", "b.py", *args, "--update-baseline"]) == 0
    # re-accepting only a.py must NOT drop b.py's accepted entry
    assert jaxlint_cli.main(["a.py", *args, "--update-baseline"]) == 0
    capsys.readouterr()
    assert jaxlint_cli.main(["a.py", "b.py", *args]) == 0


def test_update_baseline_never_accepts_suppression_hygiene(tmp_path, capsys):
    # a rationale-less suppression must keep failing the gate even after
    # a blanket `make lint-baseline`
    (tmp_path / "a.py").write_text(
        "def f(key):\n"
        "    a = jax.random.normal(key, (3,))\n"
        "    b = jax.random.uniform(key, (3,))  # jaxlint: disable=prng-key-reuse\n"
        "    return a + b\n"
    )
    baseline = tmp_path / "baseline.json"
    args = ["a.py", "--root", str(tmp_path), "--baseline", str(baseline)]
    assert jaxlint_cli.main([*args, "--update-baseline"]) == 0
    capsys.readouterr()
    rc = jaxlint_cli.main(args)
    out = capsys.readouterr().out
    assert rc == 1  # the hygiene finding still gates
    assert "suppression-missing-rationale" in out


def test_unused_suppression_reported():
    src = """
    def f(key):
        a = jax.random.normal(key, (3,))  # jaxlint: disable=prng-key-reuse -- nothing to excuse here
        return a
    """
    assert rules_of(src) == ["unused-suppression"]
    # under --select-style partial runs, un-run rules must not look unused
    from tools.jaxlint.rules import RULES_BY_NAME

    partial = lint_source(
        textwrap.dedent(src), COLD, rules=[RULES_BY_NAME["broad-except"]]
    )
    assert partial == []


def test_cli_partial_runs_do_not_report_unchecked_entries_stale(
    tmp_path, capsys
):
    (tmp_path / "a.py").write_text(_VIOLATION)
    (tmp_path / "b.py").write_text(_VIOLATION)
    baseline = tmp_path / "baseline.json"
    args = ["--root", str(tmp_path), "--baseline", str(baseline)]
    assert jaxlint_cli.main(["a.py", "b.py", *args, "--update-baseline"]) == 0
    capsys.readouterr()
    # subset path: b.py's entry was not looked for, so it is not stale
    assert jaxlint_cli.main(["a.py", *args]) == 0
    assert "no longer observed" not in capsys.readouterr().out
    # subset rules: un-run rules' entries are not stale either
    assert (
        jaxlint_cli.main(["a.py", "b.py", *args, "--select", "broad-except"])
        == 0
    )
    assert "no longer observed" not in capsys.readouterr().out
    # a REAL stale entry (violation removed) is still reported on full runs
    (tmp_path / "b.py").write_text("x = 1\n")
    assert jaxlint_cli.main(["a.py", "b.py", *args]) == 0
    assert "no longer observed" in capsys.readouterr().out


def test_cli_overlapping_paths_lint_each_file_once(tmp_path, capsys):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(_VIOLATION)
    baseline = tmp_path / "baseline.json"
    args = ["--root", str(tmp_path), "--baseline", str(baseline)]
    assert jaxlint_cli.main(["pkg", *args, "--update-baseline"]) == 0
    capsys.readouterr()
    # overlapping args must not double-count vs the accepted count of 1
    rc = jaxlint_cli.main(["pkg", "pkg/mod.py", str(pkg / "mod.py"), *args])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "1 grandfathered" in out


def test_cli_parse_error_exits_2(tmp_path):
    (tmp_path / "broken.py").write_text("def f(:\n")
    rc = jaxlint_cli.main(["broken.py", "--root", str(tmp_path)])
    assert rc == 2


def test_cli_select_unknown_rule_errors(tmp_path):
    with pytest.raises(SystemExit):
        jaxlint_cli.main(
            ["x.py", "--root", str(tmp_path), "--select", "no-such-rule"]
        )


# ------------------------------------------------- runtime: compile budget
def test_compile_budget_counts_one_compile_per_shape():
    def tiny_step(x):
        return x * 2.0

    f = jax.jit(tiny_step)
    with CompileBudget() as budget:
        f(jnp.ones((4,)))
        f(jnp.ones((4,)))  # cache hit — no new trace
        f(jnp.ones((8,)))  # second shape bucket
    assert budget.total("tiny_step") == 2
    assert len(budget.signatures("tiny_step")) == 2
    budget.assert_compiles_once("tiny_step")
    with pytest.raises(AssertionError, match="shape buckets"):
        budget.assert_compiles_once("tiny_step", max_signatures=1)


def test_compile_budget_catches_identical_shape_retrace():
    x = jnp.ones((4,))

    def make(scale):
        def rebuilt_step(v):
            return v * scale

        return rebuilt_step

    with CompileBudget() as budget:
        for _ in range(3):
            jax.jit(make(2.0))(x)  # fresh closure: retrace per call
    with pytest.raises(AssertionError, match="retrace on identical shapes"):
        budget.assert_compiles_once("rebuilt_step")


def test_compile_budget_requires_activity():
    with CompileBudget() as budget:
        pass
    with pytest.raises(AssertionError, match="saw no compiles"):
        budget.assert_compiles_once("never_ran")


def test_compile_budget_restores_log_compiles_flag():
    before = bool(jax.config.jax_log_compiles)
    with CompileBudget():
        assert bool(jax.config.jax_log_compiles) is True
    assert bool(jax.config.jax_log_compiles) is before


def test_conftest_compile_budget_fixture(compile_budget):
    """The conftest fixture variant: active for the whole test body."""

    def fixture_probe(x):
        return x + 1

    f = jax.jit(fixture_probe)
    f(jnp.ones((2,)))
    f(jnp.ones((2,)))
    compile_budget.assert_compiles_once("fixture_probe")


# --------------------------------------------------- runtime: tracer leaks
def test_tracer_leak_check_catches_seeded_leak():
    leaked = []

    @jax.jit
    def leaky(x):
        leaked.append(x)  # tracer escapes the trace
        return x * 2

    with pytest.raises(Exception, match="[Ll]eak"):
        with tracer_leak_check():
            leaky(jnp.ones((3,)))
    leaked.clear()


def test_tracer_leak_check_passes_clean_fn():
    @jax.jit
    def clean(x):
        return x * 2

    with tracer_leak_check():
        out = clean(jnp.ones((3,)))
    assert out.shape == (3,)


def test_tracer_leak_check_disabled_is_noop():
    with tracer_leak_check(enabled=False):
        pass
