"""Front-tier router units: circuit breaker state machine, registry
rotation, outcome classification, and the forward retry/hedge loop
against real (tiny, stdlib) fake replicas — no jax, no model.

The fake replicas are scriptable HTTP servers: each can answer 200,
return a canned error status, refuse connections (stopped), or black-hole
(accept + never respond) — the four behaviors the router's reliability
contract is written against.
"""

import json
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from seist_tpu.serve.router import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    CircuitBreaker,
    ReplicaRegistry,
    Router,
    RouterConfig,
    _classify,
    _Outcome,
    start_router_server,
)


# ----------------------------------------------------------- fake replicas
class _FakeReplica:
    """Scriptable replica: set ``behavior`` to one of
    'ok' | 'error:<status>[:<code>]' | 'blackhole' | 'slow:<ms>'."""

    def __init__(self):
        self.behavior = "ok"
        self.hits = 0
        self._lock = threading.Lock()
        fake = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _reply(self, status, payload):
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz/ready":
                    self._reply(200, {"status": "ok"})
                else:
                    self._reply(404, {})

            def do_POST(self):
                with fake._lock:
                    fake.hits += 1
                behavior = fake.behavior
                n = int(self.headers.get("Content-Length") or 0)
                self.rfile.read(n)
                if behavior == "blackhole":
                    time.sleep(30.0)  # hold the socket; never answer
                    return
                if behavior.startswith("slow:"):
                    time.sleep(float(behavior.split(":")[1]) / 1e3)
                    behavior = "ok"
                if behavior == "ok":
                    self._reply(200, {"ok": True, "replica": fake.url})
                else:
                    parts = behavior.split(":")
                    status = int(parts[1])
                    code = parts[2] if len(parts) > 2 else "err"
                    self._reply(status, {"error": code, "message": code})

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.server.daemon_threads = True
        self.url = f"127.0.0.1:{self.server.server_address[1]}"
        self.thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )
        self.thread.start()

    def stop(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture
def replicas():
    pair = [_FakeReplica(), _FakeReplica()]
    yield pair
    for r in pair:
        r.stop()


def _router(replicas, **overrides) -> Router:
    kw = dict(
        retries=2,
        request_timeout_s=1.0,
        breaker_failures=3,
        breaker_cooldown_s=0.2,
    )
    kw.update(overrides)
    router = Router(config=RouterConfig(**kw))
    for r in replicas:
        router.registry.add(r.url)
    return router


BODY = json.dumps({"data": [[0.0, 0.0, 0.0]], "options": {}}).encode()


# --------------------------------------------------------- circuit breaker
class TestCircuitBreaker:
    def test_opens_after_consecutive_failures_only(self):
        cb = CircuitBreaker(failures_to_open=3)
        cb.record_failure()
        cb.record_failure()
        cb.record_success()  # resets the consecutive count
        cb.record_failure()
        cb.record_failure()
        assert cb.state == CLOSED
        cb.record_failure()
        assert cb.state == OPEN
        assert not cb.allow()

    def test_half_open_probe_then_close(self):
        t = [0.0]
        cb = CircuitBreaker(
            failures_to_open=1, cooldown_s=2.0, clock=lambda: t[0]
        )
        cb.record_failure()
        assert cb.state == OPEN and not cb.allow()
        t[0] = 2.5  # cooldown elapsed: exactly one probe is granted
        assert cb.allow()
        assert cb.state == HALF_OPEN
        assert not cb.allow()  # second caller must route elsewhere
        cb.record_success()
        assert cb.state == CLOSED
        assert cb.allow()

    def test_failed_probe_reopens_with_doubled_cooldown(self):
        t = [0.0]
        cb = CircuitBreaker(
            failures_to_open=1, cooldown_s=1.0, max_cooldown_s=3.0,
            clock=lambda: t[0],
        )
        cb.record_failure()
        t[0] = 1.1
        assert cb.allow()  # half-open probe
        cb.record_failure()  # probe failed
        assert cb.state == OPEN
        assert cb.stats()["cooldown_s"] == 2.0
        t[0] = 2.0
        assert not cb.allow()  # old cooldown would have admitted here
        t[0] = 3.2
        assert cb.allow()
        cb.record_failure()
        assert cb.stats()["cooldown_s"] == 3.0  # capped at max

    def test_close_resets_cooldown_escalation(self):
        t = [0.0]
        cb = CircuitBreaker(
            failures_to_open=1, cooldown_s=1.0, clock=lambda: t[0]
        )
        cb.record_failure()
        t[0] = 1.1
        assert cb.allow()
        cb.record_failure()  # cooldown now 2.0
        t[0] = 3.5
        assert cb.allow()
        cb.record_success()  # recovered
        assert cb.stats()["cooldown_s"] == 1.0

    def test_slow_success_counts_as_failure(self):
        cb = CircuitBreaker(failures_to_open=2, latency_trip_ms=100.0)
        cb.record_success(latency_ms=500.0)
        cb.record_success(latency_ms=500.0)
        assert cb.state == OPEN

    def test_fast_success_does_not_trip(self):
        cb = CircuitBreaker(failures_to_open=2, latency_trip_ms=100.0)
        for _ in range(10):
            cb.record_success(latency_ms=5.0)
        assert cb.state == CLOSED

    def test_half_open_slow_probe_reopens_with_escalation(self):
        t = [0.0]
        cb = CircuitBreaker(
            failures_to_open=1, cooldown_s=1.0, latency_trip_ms=100.0,
            clock=lambda: t[0],
        )
        cb.record_failure()
        t[0] = 1.1
        assert cb.allow()  # half-open probe
        cb.record_success(latency_ms=500.0)  # answered, but still sick
        assert cb.state == OPEN
        assert cb.stats()["cooldown_s"] == 2.0  # escalated, not reset

    def test_lost_probe_slot_regranted_after_probe_timeout(self):
        # A probe whose outcome is never reported (attempt thread
        # outliving every drain window) must not wedge the breaker in
        # HALF_OPEN forever: after probe_timeout_s the slot re-opens.
        t = [0.0]
        cb = CircuitBreaker(
            failures_to_open=1, cooldown_s=1.0, probe_timeout_s=10.0,
            clock=lambda: t[0],
        )
        cb.record_failure()
        t[0] = 1.1
        assert cb.allow()  # probe granted... and its outcome is lost
        assert not cb.allow()  # single probe while presumed in flight
        t[0] = 5.0
        assert not cb.allow()
        t[0] = 11.2
        assert cb.allow()  # lost-probe escape: slot re-granted
        assert cb.state == HALF_OPEN
        assert not cb.allow()  # the replacement probe is single again
        cb.record_success()
        assert cb.state == CLOSED


# ----------------------------------------------------------- classification
@pytest.mark.parametrize(
    "status,code,expect_failure,expect_retry",
    [
        (0, "", True, True),       # network error
        (500, "internal", True, True),
        (429, "queue_full", False, True),
        (503, "shutting_down", False, True),
        (503, "shed", False, False),   # overload verdict: never retried
        (503, "no_replica", False, True),
        (504, "deadline_exceeded", False, False),
        (200, "", False, False),
        (400, "bad_request", False, False),
    ],
)
def test_outcome_classification(status, code, expect_failure, expect_retry):
    body = json.dumps({"error": code}).encode() if code else b""
    out = _Outcome(status, {}, body, error="refused" if status == 0 else "")
    assert _classify(out) == (expect_failure, expect_retry)


# --------------------------------------------------------------- registry
class TestRegistry:
    def test_round_robin_over_ready(self):
        reg = ReplicaRegistry()
        for u in ("a:1", "b:2", "c:3"):
            reg.add(u)
        picks = [reg.pick().url for _ in range(6)]
        assert sorted(picks[:3]) == ["a:1", "b:2", "c:3"]
        assert picks[:3] == picks[3:]  # stable rotation

    def test_mark_down_and_probe_ready_filtering(self):
        reg = ReplicaRegistry()
        reg.add("a:1")
        reg.add("b:2")
        reg.mark_down("a:1", reason="rc=-9")
        assert {reg.pick().url for _ in range(4)} == {"b:2"}
        assert reg.ready_count() == 1
        snap = {s["url"]: s for s in reg.snapshot()}
        assert snap["a:1"]["probe_state"] == "down(rc=-9)"

    def test_exclude_and_breaker_open_skipped(self):
        reg = ReplicaRegistry()
        a, b = reg.add("a:1"), reg.add("b:2")
        assert reg.pick(exclude={"b:2"}).url == "a:1"
        # open a's breaker: only b remains; with both gone, pick -> None
        for _ in range(reg.config.breaker_failures):
            a.breaker.record_failure()
        assert {reg.pick().url for _ in range(4)} == {"b:2"}
        reg.mark_down("b:2")
        assert reg.pick() is None

    def test_add_idempotent_remove_missing_false(self):
        reg = ReplicaRegistry()
        r1 = reg.add("a:1")
        assert reg.add("a:1") is r1  # same entry, breaker state kept
        assert reg.remove("a:1") is True
        assert reg.remove("a:1") is False


# ------------------------------------------------------------ forward loop
class TestForward:
    def test_success_passthrough(self, replicas):
        router = _router(replicas)
        status, _, body = router.forward("/predict", BODY)
        assert status == 200
        assert json.loads(body)["ok"] is True

    def test_dead_replica_retried_invisibly(self, replicas):
        """A stopped replica (connection refused) must cost the client
        nothing: the retry lands on the live one."""
        replicas[0].stop()
        router = _router(replicas)
        for _ in range(6):
            status, _, body = router.forward("/predict", BODY)
            assert status == 200
        # ...and the dead one's breaker opened along the way.
        snap = {s["url"]: s for s in router.registry.snapshot()}
        assert snap[replicas[0].url]["breaker"]["state"] == OPEN

    def test_500_retried_on_other_replica(self, replicas):
        replicas[0].behavior = "error:500:internal"
        replicas[1].behavior = "error:500:internal"
        router = _router(replicas, retries=1)
        status, _, body = router.forward("/predict", BODY)
        # Both replicas 500 and the budget (1 retry) is spent: the last
        # outcome is relayed, and both replicas were actually tried.
        assert status == 500
        assert replicas[0].hits + replicas[1].hits == 2

    def test_shed_503_not_retried(self, replicas):
        replicas[0].behavior = "error:503:shed"
        replicas[1].behavior = "error:503:shed"
        router = _router(replicas)
        status, _, body = router.forward("/predict", BODY)
        assert status == 503
        assert json.loads(body)["error"] == "shed"
        assert replicas[0].hits + replicas[1].hits == 1  # exactly one try

    def test_429_retried_but_breaker_untouched(self, replicas):
        replicas[0].behavior = "error:429:queue_full"
        replicas[1].behavior = "ok"
        router = _router(replicas)
        oks = sum(
            router.forward("/predict", BODY)[0] == 200 for _ in range(4)
        )
        assert oks == 4
        snap = {s["url"]: s for s in router.registry.snapshot()}
        assert snap[replicas[0].url]["breaker"]["state"] == CLOSED

    def test_no_replica_503(self):
        router = Router(config=RouterConfig())
        status, _, body = router.forward("/predict", BODY)
        assert status == 503
        assert json.loads(body)["error"] == "no_replica"

    def test_blackhole_times_out_and_opens_circuit(self, replicas):
        """The probe-invisible failure mode: accepts, answers /healthz,
        never answers /predict. Per-attempt timeouts must (a) rescue the
        client via the other replica and (b) open the circuit."""
        replicas[0].behavior = "blackhole"
        router = _router(
            replicas, request_timeout_s=0.3, breaker_failures=2, retries=2
        )
        t0 = time.monotonic()
        for _ in range(4):
            status, _, _ = router.forward("/predict", BODY)
            assert status == 200  # the live replica saves every request
        snap = {s["url"]: s for s in router.registry.snapshot()}
        assert snap[replicas[0].url]["breaker"]["state"] == OPEN
        assert time.monotonic() - t0 < 5.0

    def test_hedge_rescues_slow_replica(self, replicas):
        replicas[0].behavior = "slow:800"
        replicas[1].behavior = "slow:800"
        router = _router(replicas, hedge_ms=100.0, request_timeout_s=3.0)
        # Make exactly one replica fast; whichever the rotation picks
        # first, the race must finish in ~fast time.
        replicas[1].behavior = "ok"
        t0 = time.monotonic()
        status, _, body = router.forward("/predict", BODY)
        elapsed = time.monotonic() - t0
        assert status == 200
        assert elapsed < 0.7, f"hedge did not rescue the tail: {elapsed:.2f}s"

    def test_client_timeout_budget_respected(self, replicas):
        """options.timeout_ms bounds the whole routing attempt chain."""
        replicas[0].behavior = "blackhole"
        replicas[1].behavior = "blackhole"
        router = _router(replicas, request_timeout_s=5.0, retries=4)
        body = json.dumps(
            {"data": [[0.0] * 3], "options": {"timeout_ms": 400}}
        ).encode()
        t0 = time.monotonic()
        status, _, _ = router.forward("/predict", body)
        elapsed = time.monotonic() - t0
        assert status in (502, 504)
        assert elapsed < 2.5, f"routing budget overrun: {elapsed:.2f}s"


# ----------------------------------------------------------- prober + HTTP
class TestProberAndHTTP:
    def test_prober_drops_dead_and_readmits(self, replicas):
        router = _router(
            replicas, probe_interval_s=0.1, probe_timeout_s=0.5
        )
        server = start_router_server(router, port=0)
        try:
            port = server.server_address[1]
            replicas[0].stop()
            deadline = time.monotonic() + 5.0
            snap = {}
            while time.monotonic() < deadline:
                snap = {
                    s["url"]: s for s in router.registry.snapshot()
                }
                if not snap[replicas[0].url]["ready"]:
                    break
                time.sleep(0.05)
            assert not snap[replicas[0].url]["ready"], (
                "prober never dropped the dead replica"
            )
            assert snap[replicas[1].url]["ready"]

            # The router's own health + registry endpoints.
            import http.client

            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            assert resp.status == 200  # one replica still ready
            assert json.loads(resp.read())["ready_replicas"] == 1
            conn.request(
                "POST", "/router/register",
                json.dumps({"url": "127.0.0.1:59999"}).encode(),
                {"Content-Type": "application/json"},
            )
            assert conn.getresponse().read() and len(
                router.registry.replicas()
            ) == 3
            conn.request(
                "POST", "/router/deregister",
                json.dumps({"url": "127.0.0.1:59999"}).encode(),
                {"Content-Type": "application/json"},
            )
            conn.getresponse().read()
            assert len(router.registry.replicas()) == 2
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            text = resp.read().decode()
            assert "seist_router_replicas" in text
            conn.close()
        finally:
            server.shutdown()
            server.server_close()
            router.stop()

    def test_413_sends_connection_close_header(self, replicas):
        from seist_tpu.serve.router import MAX_BODY_BYTES

        router = _router(replicas)
        server = start_router_server(router, port=0)
        try:
            import http.client

            conn = http.client.HTTPConnection(
                "127.0.0.1", server.server_address[1], timeout=5
            )
            conn.putrequest("POST", "/predict")
            conn.putheader("Content-Type", "application/json")
            conn.putheader("Content-Length", str(MAX_BODY_BYTES + 1))
            conn.endheaders()
            resp = conn.getresponse()
            assert resp.status == 413
            # An HTTP/1.1 client must be TOLD the connection is done;
            # without the header it assumes keep-alive and pipelines its
            # next request onto a dead socket.
            assert (resp.getheader("Connection") or "").lower() == "close"
            resp.read()
            conn.close()
        finally:
            server.shutdown()
            server.server_close()
            router.stop()

    def test_forward_counters_on_bus(self, replicas):
        from seist_tpu.obs.bus import BUS

        replicas[0].stop()
        router = _router(replicas)
        router.forward("/predict", BODY)
        snap = BUS.snapshot()
        assert any(
            k.startswith("router_requests") for k in snap["counters"]
        )
        assert any(
            k.startswith("router_retries") for k in snap["counters"]
        )
        assert snap["collectors"].get("router_replicas") == 2.0
        router.stop()
        # stop() unregisters the collector: a torn-down router must not
        # keep reporting on later scrapes.
        assert "router_replicas" not in BUS.snapshot()["collectors"]


# ----------------------------------------------------------- import hygiene
def test_front_tier_imports_no_jax():
    """The front tier (router, shed, fleet supervisor) must start on a
    box with no accelerator stack: importing and constructing it must
    never pull jax. The package roots (seist_tpu, seist_tpu.utils)
    resolve their jax-importing submodules lazily for exactly this; a
    new eager import anywhere in the chain regresses it."""
    import os
    import subprocess
    import sys

    repo_root = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    script = (
        "import sys\n"
        "import seist_tpu.serve.router as router\n"
        "import seist_tpu.serve.shed  # noqa: F401\n"
        "r = router.Router(config=router.RouterConfig())  # pulls obs.bus\n"
        "r.stop()\n"
        "sys.path.insert(0, 'tools')\n"
        "import supervise_fleet  # noqa: F401\n"
        "assert 'jax' not in sys.modules, 'front tier imported jax'\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, cwd=repo_root, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr


# ------------------------------------------------------- stream affinity
def _stream_body(sid="CI.ST01"):
    return json.dumps(
        {"station": {"id": sid}, "seq": 1,
         "data": [[0.0, 0.0, 0.0]], "options": {}}
    ).encode()


class TestStationAffinity:
    def test_rank_deterministic_and_spread(self):
        from seist_tpu.serve.router import StationAffinity

        aff = StationAffinity()
        urls = [f"127.0.0.1:{9000 + i}" for i in range(3)]
        sids = [f"CI.S{i:03d}" for i in range(300)]
        homes = {}
        for sid in sids:
            rank = aff.rank(sid, urls)
            assert rank == aff.rank(sid, list(reversed(urls)))
            homes[sid] = rank[0]
        counts = [sum(1 for h in homes.values() if h == u) for u in urls]
        # Rendezvous hashing spreads ~uniformly: no replica starves.
        assert min(counts) > 50

    def test_rank_minimal_disruption_on_removal(self):
        from seist_tpu.serve.router import StationAffinity

        aff = StationAffinity()
        urls = [f"127.0.0.1:{9000 + i}" for i in range(3)]
        sids = [f"CI.S{i:03d}" for i in range(300)]
        before = {sid: aff.rank(sid, urls)[0] for sid in sids}
        dead = urls[0]
        survivors = urls[1:]
        moved = 0
        for sid in sids:
            after = aff.rank(sid, survivors)[0]
            if before[sid] == dead:
                # Orphans land on their rank-2 replica...
                assert after == aff.rank(sid, urls)[1]
                moved += 1
            else:
                # ...and nobody else moves (the rendezvous property).
                assert after == before[sid]
        assert moved > 0

    def test_note_counts_rehomes(self):
        from seist_tpu.serve.router import StationAffinity

        aff = StationAffinity()
        assert aff.note("S1", "a") is None  # first home, not a re-home
        assert aff.note("S1", "a") is None  # steady state
        assert aff.note("S1", "b") == "a"   # failover
        snap = aff.snapshot()
        assert snap == {"stations": 1, "rehomes": 1, "by_replica": {"b": 1}}

    @pytest.mark.parametrize("body,want", [
        (_stream_body("CI.ST01"), "CI.ST01"),
        (b'{"station":{"network":"CI","id":"A\\"B"},"data":[]}', 'A"B'),
        (b'{"data":[[0,0,0]]}', None),
        (b'{"station":{"network":"CI"},"data":[]}', None),
        (b'not json at all', None),
    ])
    def test_station_id_extraction(self, body, want):
        assert Router._station_id(body) == want


class TestStreamForward:
    def test_same_station_pins_to_one_replica(self, replicas):
        router = _router(replicas)
        for _ in range(6):
            status, _, _ = router.forward("/stream", _stream_body())
            assert status == 200
        hits = sorted(r.hits for r in replicas)
        assert hits == [0, 6], "stream packets must never round-robin"
        assert router.status()["stream"]["stations"] == 1
        assert router.status()["stream"]["rehomes"] == 0

    def test_failover_rehomes_to_survivor(self, replicas):
        router = _router(replicas)
        router.forward("/stream", _stream_body())
        home = next(r for r in replicas if r.hits == 1)
        other = next(r for r in replicas if r is not home)
        home.behavior = "error:500:boom"
        status, _, payload = router.forward("/stream", _stream_body())
        assert status == 200
        assert json.loads(payload)["replica"] == other.url
        stream = router.status()["stream"]
        assert stream["rehomes"] == 1
        assert stream["by_replica"] == {other.url: 1}

    def test_shutting_down_503_retried_on_survivor(self, replicas):
        router = _router(replicas)
        router.forward("/stream", _stream_body())
        home = next(r for r in replicas if r.hits == 1)
        other = next(r for r in replicas if r is not home)
        # The failover handoff: a draining/MuxClosed replica answers 503
        # shutting_down, which IS retryable -> survivor adopts.
        home.behavior = "error:503:shutting_down"
        status, _, payload = router.forward("/stream", _stream_body())
        assert status == 200
        assert json.loads(payload)["replica"] == other.url
        assert router.status()["stream"]["rehomes"] == 1

    def test_shed_503_not_retried_for_stream(self, replicas):
        router = _router(replicas)
        router.forward("/stream", _stream_body())
        home = next(r for r in replicas if r.hits == 1)
        other = next(r for r in replicas if r is not home)
        home.behavior = "error:503:shed"
        status, _, payload = router.forward("/stream", _stream_body())
        assert status == 503
        assert json.loads(payload)["error"] == "shed"
        assert other.hits == 0, "shed is a policy verdict, not a failure"

    def test_stream_without_station_falls_back_to_round_robin(self, replicas):
        router = _router(replicas)
        body = json.dumps({"data": [[0.0, 0.0, 0.0]]}).encode()
        for _ in range(4):
            status, _, _ = router.forward("/stream", body)
            assert status == 200
        assert sorted(r.hits for r in replicas) == [2, 2]
