"""int8 quantization ladder (ISSUE 18): format-v3 packs, round-trip
error bounds, dtype-mix refusal, and io_guard parity on the stage_raw
device-dequant ingest path.

The contract under test: archive bytes stay int8 from disk to the
device boundary (single-memcpy staging + resident per-row scales), the
HOST dequant lanes (PackedDataset events, PackedRawStore default fill)
reproduce ``q * scale`` exactly, and every fault the float shards
survive — truncation, poisoned rows, corrupt scales — the int8 shards
survive through the SAME quarantine/fallback ladder.
"""

import json
import os

import numpy as np
import pytest

import seist_tpu
from seist_tpu import taskspec
from seist_tpu.data import io_guard, pipeline
from seist_tpu.data.ingest import PackedRawStore
from seist_tpu.data.packed import (
    INT8_POISON,
    DtypeMixError,
    PackSource,
    pack_sources,
    quantize_rows,
    shard_path,
)

seist_tpu.load_all()

N_EVENTS = 20
L_TRACE = 512
WINDOW = 256

#: One spec per label kind the pipeline serves off packed rows: soft
#: pick curves (dpk), ONEHOT (pmp), and the three VALUE heads
#: (emg/baz/dis) — labels ride the index, never the quantized bytes.
TASK_SPECS = ("seist_s_dpk", "seist_s_pmp", "magnet", "seist_s_baz",
              "seist_s_dis")


def _pack(root, dtype, n_events=N_EVENTS, trace=L_TRACE, sps=6, workers=0):
    return pack_sources(
        [PackSource(
            name="synthetic",
            dataset_kwargs={
                "num_events": n_events,
                "trace_samples": trace,
                "cache": False,
            },
        )],
        str(root),
        samples_per_shard=sps,
        num_workers=workers,
        dtype=dtype,
    )


@pytest.fixture(scope="module")
def pack_pair(tmp_path_factory):
    """(fp32 dir, int8 dir, int8 pack stats) of the same source."""
    root = tmp_path_factory.mktemp("quant_pair")
    s32 = _pack(root / "f32", "float32")
    s8 = _pack(root / "i8", "int8")
    return s32["out"], s8["out"], s8


def _sds(packed_dir, model="seist_s_dpk", **kw):
    kw.setdefault("shuffle", False)
    kw.setdefault("data_split", False)
    return pipeline.from_task_spec(
        taskspec.get_task_spec(model), "packed", "train", seed=0,
        in_samples=WINDOW, augmentation=False, data_dir=packed_dir, **kw,
    )


# ------------------------------------------------------------ round trip
@pytest.mark.parametrize("model", TASK_SPECS)
def test_int8_roundtrip_bounds_per_task(pack_pair, model):
    """pack -> ingest -> dequant vs the fp32 source, per label kind:
    waveforms within the half-LSB bound 0.5 * scale, EXACTLY equal to
    re-applying the pack-time quantizer, labels bit-identical."""
    f32_dir, i8_dir, _ = pack_pair
    st32 = PackedRawStore.build(_sds(f32_dir, model), batch_size=4)
    st8 = PackedRawStore.build(_sds(i8_dir, model), batch_size=4)
    idx = np.arange(st32.n_raw)
    r32 = st32.row_batch(idx)
    r8 = st8.row_batch(idx)
    assert r8["data"].dtype == np.float32
    for j in range(st32.n_raw):
        q, scale = quantize_rows(r32["data"][j])
        # Host dequant is exactly q * scale (shared quantizer — the
        # tolerance can't drift from the format).
        np.testing.assert_array_equal(
            r8["data"][j], q.astype(np.float32) * scale[:, None]
        )
        err = np.abs(r8["data"][j] - r32["data"][j])
        bound = 0.5 * scale[:, None] + 1e-7
        assert (err <= bound).all(), (model, j, float(err.max()))
    for k in r32:
        if k == "data":
            continue
        if isinstance(r32[k], dict):  # values / onehots sub-columns
            for name in r32[k]:
                np.testing.assert_array_equal(r8[k][name], r32[k][name])
        else:
            np.testing.assert_array_equal(r8[k], r32[k])


def test_int8_parallel_pack_bit_identical(tmp_path):
    """2-worker int8 pack == serial pack, byte for byte, scale sidecar
    included — the plan-first contract extends to format v3."""
    from tests.test_packed import _dir_fingerprint

    a = _pack(tmp_path / "serial", "int8")
    b = _pack(tmp_path / "par", "int8", workers=2)
    assert a["shards"] == b["shards"] > 1
    fp_a = _dir_fingerprint(a["out"])
    assert "scale_0" in fp_a["index.npz"]
    assert fp_a == _dir_fingerprint(b["out"])


def test_int8_pack_bytes_verdict(pack_pair):
    """The pack stats report measured on-disk bytes vs fp32 (the CLI's
    one-line JSON verdict) and meet the <=0.55x acceptance ceiling."""
    f32_dir, i8_dir, s8 = pack_pair
    def shard_bytes(d):
        return sum(
            os.path.getsize(os.path.join(d, f))
            for f in os.listdir(d) if f.endswith(".bin")
        )
    assert s8["on_disk_bytes"] == shard_bytes(i8_dir)
    assert s8["bytes_vs_fp32"] == pytest.approx(
        shard_bytes(i8_dir) / shard_bytes(f32_dir)
    )
    assert s8["bytes_vs_fp32"] <= 0.55


# ------------------------------------------------------- dtype-mix refusal
def test_dtype_mix_refused_both_directions(tmp_path):
    _pack(tmp_path / "i8", "int8")
    with pytest.raises(DtypeMixError) as ei:
        _pack(tmp_path / "i8", "float32")
    assert ei.value.existing == "int8"
    assert ei.value.requested == "float32"
    _pack(tmp_path / "f32", "bfloat16")
    with pytest.raises(DtypeMixError) as ei:
        _pack(tmp_path / "f32", "int8")
    assert ei.value.existing == "bfloat16"
    assert ei.value.requested == "int8"


def test_pack_dataset_cli_structured_mix_refusal(tmp_path, capsys):
    """tools/pack_dataset.py surfaces DtypeMixError as a machine-
    readable one-line JSON verdict with exit code 2."""
    from tools.pack_dataset import main as pack_main

    out = str(tmp_path / "pack")
    kwargs = json.dumps({
        "num_events": 6, "trace_samples": 128, "cache": False,
    })
    base = ["--dataset", "synthetic", "--dataset-kwargs", kwargs,
            "--out", out, "--samples-per-shard", "3"]
    assert pack_main(base + ["--dtype", "int8"]) == 0
    capsys.readouterr()
    assert pack_main(base + ["--dtype", "float32"]) == 2
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict == {
        "ok": False,
        "error": "dtype_mix",
        "existing_dtype": "int8",
        "requested_dtype": "float32",
        "out": out,
        "detail": verdict["detail"],
    }
    assert "scale sidecar" in verdict["detail"]


# ------------------------------------------------------------ fault parity
@pytest.mark.faults
@pytest.mark.parametrize("stage_raw", (False, True))
def test_int8_poison_byte_quarantined(tmp_path, stage_raw):
    """A -128 byte (symmetric quantization never emits it) is permanent
    corruption: quarantine + deterministic fallback, on both the host-
    dequant and the stage_raw lanes."""
    out = _pack(tmp_path / "pack", "int8", sps=50)["out"]  # one shard
    sds = _sds(out)
    store = PackedRawStore.build(sds, batch_size=4, stage_raw=stage_raw)
    poison = 3
    with open(shard_path(out, 0), "r+b") as f:
        f.seek(int(store._offsets[poison]))
        f.write(np.full(8, INT8_POISON, np.int8).tobytes())
    io_guard.COUNTERS.reset()
    rows = store.row_batch_at(
        np.array([poison, 0]), epoch=0, idx=np.array([poison, 0])
    )
    assert io_guard.COUNTERS.snapshot()["quarantined"] == 1
    assert poison in sds.quarantine
    if stage_raw:
        assert rows["data"].dtype == np.int8
        assert not (rows["data"] == INT8_POISON).any()
        assert np.isfinite(rows["data_scale"]).all()
    else:
        assert np.isfinite(rows["data"]).all()


@pytest.mark.faults
def test_int8_corrupt_scale_sidecar_quarantined(tmp_path):
    """A non-finite scale in the v3 sidecar (truncated/garbled index
    column) kills the row through the same CorruptSampleError ladder —
    never a NaN waveform downstream."""
    out = _pack(tmp_path / "pack", "int8", sps=50)["out"]
    idx_path = os.path.join(out, "index.npz")
    with np.load(idx_path, allow_pickle=False) as z:
        cols = {k: z[k].copy() for k in z.files}
    victim = 2
    cols["scale_0"][victim] = np.nan
    np.savez(idx_path, **cols)
    sds = _sds(out)
    store = PackedRawStore.build(sds, batch_size=4)
    io_guard.COUNTERS.reset()
    rows = store.row_batch_at(
        np.array([victim, 0]), epoch=0, idx=np.array([victim, 0])
    )
    assert io_guard.COUNTERS.snapshot()["quarantined"] == 1
    assert victim in sds.quarantine
    assert np.isfinite(rows["data"]).all()


@pytest.mark.faults
def test_truncated_int8_shard_stage_raw_falls_back(tmp_path):
    """Truncated v3 shard through the stage_raw fill: short read ->
    quarantine -> the replacement row is the deterministic candidate's
    CONTENT (int8 bytes + its scale row stay consistent)."""
    out = _pack(tmp_path / "pack", "int8", sps=5)["out"]
    sds = _sds(out)
    store = PackedRawStore.build(sds, batch_size=4, stage_raw=True)
    last_shard = int(store._shards.max())
    p = shard_path(out, last_shard)
    size = os.path.getsize(p)
    with open(p, "r+b") as f:
        f.truncate(size - store.row_nbytes // 2)
    victims = np.flatnonzero(
        (store._shards == last_shard)
        & (store._offsets + store.row_nbytes > size - store.row_nbytes // 2)
    )
    bad = int(victims[0])
    io_guard.COUNTERS.reset()
    raw_idx = np.array([bad, 0, 1])
    rows = store.row_batch_at(raw_idx, epoch=0, idx=raw_idx)
    snap = io_guard.COUNTERS.snapshot()
    assert snap["quarantined"] == 1
    assert snap["fallback_reads"] == 1
    assert bad in sds.quarantine
    cand = next(
        c
        for c in sds.quarantine.candidates(bad, seed=0, epoch=0, idx=bad)
        if c != bad
    )
    expect = store.row_batch_at(np.array([cand]), epoch=0,
                                idx=np.array([cand]))
    np.testing.assert_array_equal(rows["data"][0], expect["data"][0])
    np.testing.assert_array_equal(
        rows["data_scale"][0], expect["data_scale"][0]
    )


# ------------------------------------------------- device-dequant parity
def test_stage_raw_device_dequant_matches_host(pack_pair):
    """The engine's in-program dequant (batch/engine.dequant_rows) over
    the staged int8 rows + resident scales reproduces the host dequant
    lane exactly, and the raw lane counts its rows on the obs bus."""
    from seist_tpu.batch.engine import dequant_rows
    from seist_tpu.obs.bus import BUS

    _, i8_dir, _ = pack_pair
    host = PackedRawStore.build(_sds(i8_dir), batch_size=4)
    raw = PackedRawStore.build(_sds(i8_dir), batch_size=4, stage_raw=True)
    idx = np.arange(4)
    before = BUS.counter("data_ingest_int8_rows").value
    r_host = host.row_batch_at(idx, epoch=0, idx=idx)
    r_raw = raw.row_batch_at(idx, epoch=0, idx=idx)
    assert BUS.counter("data_ingest_int8_rows").value == before + 8
    assert r_raw["data"].dtype == np.int8
    assert r_raw["data_scale"].shape == (4, raw.n_ch)
    deq = np.asarray(dequant_rows(r_raw["data"], r_raw["data_scale"]))
    np.testing.assert_array_equal(deq, r_host["data"])
