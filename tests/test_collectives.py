"""Collective-traffic accounting from compiled HLO (parallel/collectives.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from seist_tpu.parallel import (
    collective_stats,
    format_collective_stats,
    make_mesh,
)

_FAKE_HLO = """
  %ar = f32[128,4]{1,0} all-reduce(f32[128,4]{1,0} %p0), replica_groups={}
  %ag.1 = (f32[8]{0}, f32[64]{0}) all-gather-start(f32[8]{0} %p1), dimensions={0}
  %ag.2 = f32[64]{0} all-gather-done((f32[8]{0}, f32[64]{0}) %ag.1)
  %cp = bf16[2,16]{1,0} collective-permute(bf16[2,16]{1,0} %p2)
  %add = f32[4]{0} add(f32[4]{0} %a, f32[4]{0} %b)
"""

# Real-TPU shape: async start with tiled layouts (nested parens in the
# lhs tuple) and u32 context scalars on a permute.
_TPU_HLO = """
  %ars = (f32[388778]{0:T(1024)}, f32[388778]{0:T(1024)}) all-reduce-start(f32[388778]{0:T(1024)} %fusion.1)
  %ard = f32[388778]{0:T(1024)} all-reduce-done((f32[388778]{0:T(1024)}, f32[388778]{0:T(1024)}) %ars)
  %cps = (bf16[2,64]{1,0:T(8,128)(2,1)}, bf16[2,64]{1,0:T(8,128)(2,1)}, u32[]{:T(128)}, u32[]{:T(128)}) collective-permute-start(bf16[2,64]{1,0:T(8,128)(2,1)} %x)
"""


def test_parses_kinds_and_bytes():
    stats = collective_stats(_FAKE_HLO)
    assert stats["all-reduce"] == {"count": 1, "bytes": 128 * 4 * 4}
    # -start counted once, payload = LARGEST tuple shape (the output;
    # summing would double-count aliased in/out buffers); -done skipped.
    assert stats["all-gather"] == {"count": 1, "bytes": 64 * 4}
    assert stats["collective-permute"] == {"count": 1, "bytes": 2 * 16 * 2}
    assert "add" not in stats


def test_parses_tpu_async_tiled_layouts():
    stats = collective_stats(_TPU_HLO)
    assert stats["all-reduce"] == {"count": 1, "bytes": 388778 * 4}
    # permute payload = the bf16 block, not the u32 context scalars
    assert stats["collective-permute"] == {"count": 1, "bytes": 2 * 64 * 2}


_COMBINED_HLO = """
  %arc = (f32[24,16]{1,0}, f32[5,8,16]{2,1,0}, f32[32]{0}) all-reduce(%g0, %g1, %g2), channel_id=1, metadata={op_name="jit(train_step)/transpose(jvp(SeismogramTransformer))/grad"}
  %ag = f32[16,32,16]{2,1,0} all-gather(%act), channel_id=2, dimensions={0}, metadata={op_name="jit(train_step)/transpose(jvp(SeismogramTransformer))/stage1_block1/conv1/conv"}
"""

# XLA prints /*index=N*/ comments inside long tuples — the `=` inside them
# truncated the round-3 lhs regex, dropping most combined-gradient tensors.
_INDEXED_TUPLE_HLO = """
  %arc = (f32[64]{0}, f32[96]{0}, f32[96,64]{1,0}, f32[5,32,64]{2,1,0}, f32[128]{0}, /*index=5*/f32[128,64]{1,0}, f32[64]{0}) all-reduce(%a, %b, %c, %d, %e, %f, %g), channel_id=3
"""


def test_indexed_tuple_lhs_not_truncated():
    stats = collective_stats(_INDEXED_TUPLE_HLO)
    want = (64 + 96 + 96 * 64 + 5 * 32 * 64 + 128 + 128 * 64 + 64) * 4
    assert stats["all-reduce"] == {"count": 1, "bytes": want}


# TPU async form of a COMBINED all-reduce: the start op's lhs aliases the
# whole (inputs, outputs) pair, so payload = sum/2 — the max rule would
# collapse it to the largest gradient tensor (the round-3 sync bug, async
# edition).
_COMBINED_ASYNC_HLO = """
  %ars = ((f32[388778]{0}, f32[1024]{0}), (f32[388778]{0}, f32[1024]{0})) all-reduce-start(%g0, %g1)
  %ard = (f32[388778]{0}, f32[1024]{0}) all-reduce-done(%ars)
"""


def test_combined_async_all_reduce_start_sums_half():
    stats = collective_stats(_COMBINED_ASYNC_HLO)
    assert stats["all-reduce"] == {
        "count": 1,
        "bytes": (388778 + 1024) * 4,
    }


# Non-TPU XLA paths can emit all-reduce-start with the bare result shape
# (no aliased-input tuple); the sum/2 rule would halve it (advisor r4).
_BARE_ASYNC_HLO = """
  %ars = f32[388778]{0} all-reduce-start(f32[388778]{0} %g0)
  %ard = f32[388778]{0} all-reduce-done(%ars)
"""


def test_bare_async_all_reduce_start_not_halved():
    stats = collective_stats(_BARE_ASYNC_HLO)
    assert stats["all-reduce"] == {"count": 1, "bytes": 388778 * 4}


def test_combined_tuple_all_reduce_sums_elements():
    # XLA's all-reduce combiner merges many gradient tensors into ONE
    # tuple-shaped sync op; every element is a distinct transferred buffer
    # and must be SUMMED (round 3 took the max, undercounting ~50x).
    stats = collective_stats(_COMBINED_HLO)
    want = (24 * 16 + 5 * 8 * 16 + 32) * 4
    assert stats["all-reduce"] == {"count": 1, "bytes": want}


def test_collective_ops_detail():
    from seist_tpu.parallel.collectives import collective_ops

    ops = collective_ops(_COMBINED_HLO)
    assert len(ops) == 2
    ar, ag = ops
    assert ar["kind"] == "all-reduce"
    assert ar["shape_dims"] == [(24, 16), (5, 8, 16), (32,)]
    assert "transpose(jvp" in ar["op_name"]
    assert ag["kind"] == "all-gather"
    assert ag["bytes"] == 16 * 32 * 16 * 4
    assert "stage1_block1/conv1" in ag["op_name"]


def test_format_and_empty():
    assert format_collective_stats({}) == "no collectives"
    s = format_collective_stats(collective_stats(_FAKE_HLO))
    assert "all-reduce x1" in s and "total" in s


@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8 virtual devices")
def test_live_psum_shows_all_reduce():
    mesh = make_mesh(data=8)
    from jax.sharding import NamedSharding, PartitionSpec as P

    @jax.jit
    def f(x):
        y = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("data"))
        )
        return (y * 2).sum()

    x = jnp.ones((16, 4))
    hlo = f.lower(x).compile().as_text()
    stats = collective_stats(hlo)
    # The cross-shard sum must appear as some reduction collective.
    assert any(
        k in stats for k in ("all-reduce", "reduce-scatter", "all-gather")
    ), stats


def test_live_ppermute_bytes():
    mesh = make_mesh(data=1, seq=min(8, jax.device_count()))
    n_seq = mesh.shape["seq"]
    if n_seq < 2:
        pytest.skip("needs a seq axis")
    from seist_tpu.ops.ring_attention import ring_attention

    rng = np.random.default_rng(0)
    q = rng.standard_normal((2, 8 * n_seq, 1, 8)).astype(np.float32)
    f = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))
    hlo = f.lower(q, q, q).compile().as_text()
    stats = collective_stats(hlo)
    assert "collective-permute" in stats
    assert stats["collective-permute"]["bytes"] > 0
