"""tools/supervise.py: relaunch-on-failure with checkpoint resume and the
preemption exit-code contract.

The reference has no automatic failure recovery (SURVEY.md §5 — resume is
a manual relaunch with --checkpoint, ref train.py:255-264); these tests
pin the wrapper's contract using a stub trainer that crashes until it is
handed a checkpoint.
"""

import os
import sys
import textwrap
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")
)

from supervise import (  # noqa: E402
    PREEMPT_EXIT_CODE,
    checkpoint_step,
    find_newest_checkpoint,
    main,
    with_checkpoint,
)


def _make_ckpt(base, run, name, t):
    d = os.path.join(base, run, "checkpoints", name)
    os.makedirs(d)
    os.utime(d, (t, t))
    return d


class TestHelpers:
    def test_find_newest(self, tmp_path):
        base = str(tmp_path)
        _make_ckpt(base, "run_a", "model-3", 100)
        newest = _make_ckpt(base, "run_b", "model-1", 200)
        assert find_newest_checkpoint(base) == newest

    def test_find_none(self, tmp_path):
        assert find_newest_checkpoint(str(tmp_path)) is None

    def test_skips_orbax_inprogress_tmp_dirs(self, tmp_path):
        """A crash mid-save leaves model-N.orbax-checkpoint-tmp-<ts> with
        the newest mtime; resume must pick the last COMMITTED one."""
        base = str(tmp_path)
        committed = _make_ckpt(base, "run", "model-6", 100)
        _make_ckpt(base, "run", "model-7.orbax-checkpoint-tmp-123", 200)
        assert find_newest_checkpoint(base) == committed

    def test_interrupted_manager_save_layout(self, tmp_path):
        """Satellite regression: the step-granular manager layout after a
        crash mid-async-save — committed `model_<step>` dirs plus one
        `.orbax-checkpoint-tmp-` in-progress dir. Only the exact orbax
        marker disqualifies; the old `"tmp" in d` substring match is gone
        (it would also have rejected any legitimately-named dir whose
        name happened to contain those three letters)."""
        base = str(tmp_path)
        _make_ckpt(base, "run", "model_2", 100)
        committed = _make_ckpt(base, "run", "model_4", 200)
        _make_ckpt(base, "run", "model_6.orbax-checkpoint-tmp-1722", 300)
        assert find_newest_checkpoint(base) == committed

    def test_step_number_breaks_mtime_ties(self, tmp_path):
        """Two async saves can finalize within mtime granularity; the
        higher step must win."""
        base = str(tmp_path)
        _make_ckpt(base, "run", "model_4", 100)
        newest = _make_ckpt(base, "run", "model_6", 100)
        assert find_newest_checkpoint(base) == newest

    def test_non_checkpoint_dirs_ignored(self, tmp_path):
        base = str(tmp_path)
        committed = _make_ckpt(base, "run", "model-3", 100)
        _make_ckpt(base, "run", "model-best", 200)  # no step number
        _make_ckpt(base, "run", "other-5", 300)
        assert find_newest_checkpoint(base) == committed

    def test_checkpoint_step_parsing(self):
        assert checkpoint_step("model-7") == 7
        assert checkpoint_step("model_123") == 123
        assert checkpoint_step("/a/b/checkpoints/model_9") == 9
        assert checkpoint_step("model_9.orbax-checkpoint-tmp-1") is None
        assert checkpoint_step("model-best") is None

    def test_preempt_code_matches_trainer(self):
        """supervise.py is stdlib-only, so the constant is duplicated
        from seist_tpu.train.checkpoint — pin them together."""
        from seist_tpu.train.checkpoint import (
            PREEMPT_EXIT_CODE as trainer_code,
        )

        assert PREEMPT_EXIT_CODE == trainer_code == 75

    def test_with_checkpoint_appends_and_replaces(self):
        cmd = ["python", "main.py", "--mode", "train"]
        out = with_checkpoint(cmd, "/c1")
        assert out[-2:] == ["--checkpoint", "/c1"]
        assert with_checkpoint(out, "/c2")[-2:] == ["--checkpoint", "/c2"]

    def test_equals_form(self, tmp_path):
        from supervise import _arg_value

        cmd = ["python", "main.py", "--log-base=logs/r1", "--checkpoint=/old"]
        assert _arg_value(cmd, "--log-base") == "logs/r1"
        assert with_checkpoint(cmd, "/new")[-1] == "--checkpoint=/new"


class TestEndToEnd:
    def _stub(self, tmp_path):
        """Trainer that crashes unless given --checkpoint; writes a ckpt dir
        on its first (failing) run, like a real run that died mid-epoch."""
        log_base = tmp_path / "logs"
        script = tmp_path / "trainer.py"
        script.write_text(textwrap.dedent(f"""
            import os, sys
            log_base = {str(log_base)!r}
            if "--checkpoint" in sys.argv:
                sys.exit(0)
            os.makedirs(os.path.join(log_base, "run", "checkpoints", "model-0"),
                        exist_ok=True)
            sys.exit(1)
        """))
        return script, log_base

    def test_resumes_from_checkpoint_and_succeeds(self, tmp_path):
        script, log_base = self._stub(tmp_path)
        rc = main([
            "--retries", "2", "--backoff", "0", "--",
            sys.executable, str(script), "--log-base", str(log_base),
        ])
        assert rc == 0

    def test_gives_up_after_retries(self, tmp_path):
        script = tmp_path / "always_fail.py"
        script.write_text("import sys; sys.exit(7)\n")
        rc = main([
            "--retries", "1", "--backoff", "0", "--",
            sys.executable, str(script),
        ])
        assert rc == 7

    def test_clean_preempt_relaunches_immediately_without_budget(
        self, tmp_path
    ):
        """rc=75 with checkpoint progress: immediate relaunch (no
        backoff sleep) and the retry budget untouched — retries=0 still
        completes."""
        log_base = tmp_path / "logs"
        script = tmp_path / "trainer.py"
        script.write_text(textwrap.dedent(f"""
            import os, sys
            log_base = {str(log_base)!r}
            if "--checkpoint" in sys.argv:
                sys.exit(0)  # resumed run finishes the job
            os.makedirs(os.path.join(log_base, "run", "checkpoints", "model_4"),
                        exist_ok=True)
            sys.exit({PREEMPT_EXIT_CODE})  # preempted after checkpointing
        """))
        t0 = time.monotonic()
        rc = main([
            "--retries", "0", "--backoff", "30", "--",
            sys.executable, str(script), "--log-base", str(log_base),
        ])
        assert rc == 0
        # No 30 s backoff was paid: the preempt path relaunches at once.
        assert time.monotonic() - t0 < 20.0

    def test_preempt_without_progress_consumes_budget(self, tmp_path):
        """An exit-75 loop that never advances a checkpoint must not
        relaunch forever: without progress it's treated as a crash."""
        script = tmp_path / "fake_preempt.py"
        script.write_text(
            f"import sys; sys.exit({PREEMPT_EXIT_CODE})\n"
        )
        rc = main([
            "--retries", "1", "--backoff", "0", "--",
            sys.executable, str(script),
        ])
        assert rc == PREEMPT_EXIT_CODE

    def test_checkpoint_progress_resets_crash_budget(self, tmp_path):
        """Crashes WITH forward progress (newer checkpoint each attempt)
        keep resetting the budget: retries=1 survives 2 crashes because
        each one advanced the checkpoint (tpu_outage_r4.log ate 4 outages
        in one night — a long healthy run must outlive them)."""
        log_base = tmp_path / "logs"
        script = tmp_path / "progressing.py"
        script.write_text(textwrap.dedent(f"""
            import glob, os, sys
            log_base = {str(log_base)!r}
            ck = os.path.join(log_base, "run", "checkpoints")
            n = len(glob.glob(os.path.join(ck, "model_*")))
            os.makedirs(os.path.join(ck, f"model_{{2 * (n + 1)}}"),
                        exist_ok=True)
            sys.exit(0 if n >= 2 else 1)
        """))
        rc = main([
            "--retries", "1", "--backoff", "0", "--",
            sys.executable, str(script), "--log-base", str(log_base),
        ])
        assert rc == 0
