"""tools/supervise.py: relaunch-on-failure with checkpoint resume.

The reference has no automatic failure recovery (SURVEY.md §5 — resume is
a manual relaunch with --checkpoint, ref train.py:255-264); these tests
pin the wrapper's contract using a stub trainer that crashes until it is
handed a checkpoint.
"""

import os
import sys
import textwrap

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")
)

from supervise import find_newest_checkpoint, main, with_checkpoint  # noqa: E402


def _make_ckpt(base, run, name, t):
    d = os.path.join(base, run, "checkpoints", name)
    os.makedirs(d)
    os.utime(d, (t, t))
    return d


class TestHelpers:
    def test_find_newest(self, tmp_path):
        base = str(tmp_path)
        _make_ckpt(base, "run_a", "model-3", 100)
        newest = _make_ckpt(base, "run_b", "model-1", 200)
        assert find_newest_checkpoint(base) == newest

    def test_find_none(self, tmp_path):
        assert find_newest_checkpoint(str(tmp_path)) is None

    def test_skips_orbax_inprogress_tmp_dirs(self, tmp_path):
        """A crash mid-save leaves model-N.orbax-checkpoint-tmp-<ts> with
        the newest mtime; resume must pick the last COMMITTED one."""
        base = str(tmp_path)
        committed = _make_ckpt(base, "run", "model-6", 100)
        _make_ckpt(base, "run", "model-7.orbax-checkpoint-tmp-123", 200)
        assert find_newest_checkpoint(base) == committed

    def test_with_checkpoint_appends_and_replaces(self):
        cmd = ["python", "main.py", "--mode", "train"]
        out = with_checkpoint(cmd, "/c1")
        assert out[-2:] == ["--checkpoint", "/c1"]
        assert with_checkpoint(out, "/c2")[-2:] == ["--checkpoint", "/c2"]

    def test_equals_form(self, tmp_path):
        from supervise import _arg_value

        cmd = ["python", "main.py", "--log-base=logs/r1", "--checkpoint=/old"]
        assert _arg_value(cmd, "--log-base") == "logs/r1"
        assert with_checkpoint(cmd, "/new")[-1] == "--checkpoint=/new"


class TestEndToEnd:
    def _stub(self, tmp_path):
        """Trainer that crashes unless given --checkpoint; writes a ckpt dir
        on its first (failing) run, like a real run that died mid-epoch."""
        log_base = tmp_path / "logs"
        script = tmp_path / "trainer.py"
        script.write_text(textwrap.dedent(f"""
            import os, sys
            log_base = {str(log_base)!r}
            if "--checkpoint" in sys.argv:
                sys.exit(0)
            os.makedirs(os.path.join(log_base, "run", "checkpoints", "model-0"),
                        exist_ok=True)
            sys.exit(1)
        """))
        return script, log_base

    def test_resumes_from_checkpoint_and_succeeds(self, tmp_path):
        script, log_base = self._stub(tmp_path)
        rc = main([
            "--retries", "2", "--backoff", "0", "--",
            sys.executable, str(script), "--log-base", str(log_base),
        ])
        assert rc == 0

    def test_gives_up_after_retries(self, tmp_path):
        script = tmp_path / "always_fail.py"
        script.write_text("import sys; sys.exit(7)\n")
        rc = main([
            "--retries", "1", "--backoff", "0", "--",
            sys.executable, str(script),
        ])
        assert rc == 7
