"""serve subsystem: micro-batcher contracts (pure threads, no jax) and an
end-to-end in-process service on a tiny phasenet.

The e2e class is the ISSUE's acceptance check: N concurrent single-trace
requests must be served by < N forwards (coalescing observable via
/metrics), with per-task outputs identical to the offline path
(ops/postprocess + ops/stream.annotate — what tools/predict.py runs).
"""

import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from seist_tpu.serve.batcher import (
    BatcherConfig,
    MicroBatcher,
    default_buckets,
)
from seist_tpu.serve.protocol import (
    BadRequest,
    DeadlineExceeded,
    QueueFull,
    ShuttingDown,
)
from seist_tpu.utils.meters import LatencyHistogram


# --------------------------------------------------------------- unit: knobs
def test_default_buckets_powers_of_two_plus_max():
    assert default_buckets(8) == (1, 2, 4, 8)
    assert default_buckets(5) == (1, 2, 4, 5)
    assert default_buckets(1) == (1,)


def test_bad_buckets_rejected():
    with pytest.raises(ValueError):
        BatcherConfig(max_batch=8, buckets=(1, 2)).resolved_buckets()


def test_bad_option_values_rejected():
    from seist_tpu.serve.protocol import PredictOptions

    for bad in (
        {"timeout_ms": -1000},  # would become an unbounded lock wait
        {"timeout_ms": 0},
        {"timeout_ms": "soon"},
        {"ppk_threshold": True},
        {"sampling_rate": 0},
        {"max_events": 0},
        {"stride": -1},
        {"combine": "median"},
        {"norm_mode": 3},
        {"timeout_ms": float("nan")},  # NaN passes every range check
        {"min_peak_dist": float("inf")},
        {"stride": 2.5},  # int field, non-integral
        {"max_events": 8.5},
    ):
        with pytest.raises(BadRequest):
            PredictOptions.from_dict(bad)
    assert PredictOptions.from_dict({"timeout_ms": 250}).timeout_ms == 250


def test_latency_histogram_percentiles():
    h = LatencyHistogram()
    for v in [1.5, 3.0, 7.0, 15.0, 40.0, 80.0, 150.0, 400.0, 900.0, 1800.0]:
        h.observe(v)
    s = h.summary()
    assert s["count"] == 10
    assert 0 < s["p50"] <= s["p90"] <= s["p99"] <= s["max"] == 1800.0
    assert h.percentile(0.0) == 0.0 or h.percentile(0.0) <= s["p50"]


# ----------------------------------------------------------- unit: batcher
def _make(forward, **kw):
    return MicroBatcher(forward, BatcherConfig(**kw), name="test")


def test_bucket_padding_and_per_item_slicing():
    """3 concurrent requests with buckets (1,2,4): one forward at the
    padded bucket-4 shape, each caller getting its own slice back."""
    shapes = []

    def forward(batch):
        shapes.append(batch.shape)
        return batch * 2.0

    b = _make(forward, max_batch=4, max_delay_ms=30.0)
    xs = [np.full((5, 3), i, np.float32) for i in range(3)]
    with ThreadPoolExecutor(3) as ex:
        outs = list(ex.map(lambda x: b.submit(x, timeout_ms=5000), xs))
    assert shapes == [(4, 5, 3)]  # padded to the bucket, single forward
    for i, out in enumerate(outs):
        assert out.shape == (1, 5, 3)
        np.testing.assert_allclose(out, xs[i][None] * 2.0)
    stats = b.stats()
    assert stats["forwards"] == 1
    assert stats["batch_fill_ratio"] == pytest.approx(3 / 4)
    b.shutdown()


def test_full_batch_flushes_before_max_delay():
    """max_batch simultaneous requests must not wait out max_delay_ms."""
    b = _make(lambda x: x, max_batch=4, max_delay_ms=60_000.0)
    t0 = time.monotonic()
    with ThreadPoolExecutor(4) as ex:
        list(ex.map(
            lambda i: b.submit(np.zeros((2,), np.float32), timeout_ms=10_000),
            range(4),
        ))
    assert time.monotonic() - t0 < 30.0  # nowhere near the 60 s delay cap
    assert b.stats()["forwards"] == 1
    b.shutdown()


def test_max_delay_flushes_partial_batch():
    """A lone request is served after ~max_delay_ms, not never."""
    b = _make(lambda x: x, max_batch=64, max_delay_ms=20.0)
    out = b.submit(np.ones((2,), np.float32), timeout_ms=10_000)
    assert out.shape == (1, 2)
    stats = b.stats()
    assert stats["forwards"] == 1 and stats["completed"] == 1
    b.shutdown()


def test_tuple_outputs_sliced_per_item():
    b = _make(lambda x: (x + 1.0, x.sum(axis=1)), max_batch=2,
              max_delay_ms=10.0)
    out = b.submit(np.ones((3,), np.float32), timeout_ms=5000)
    assert isinstance(out, tuple) and out[0].shape == (1, 3)
    np.testing.assert_allclose(out[1], [3.0])
    b.shutdown()


def test_deadline_expiry_while_queued():
    """With the worker pinned on a slow forward, a short-deadline request
    expires in the queue and raises DeadlineExceeded."""
    release = threading.Event()

    def slow_forward(batch):
        release.wait(timeout=10.0)
        return batch

    b = _make(slow_forward, max_batch=1, max_delay_ms=1.0, max_queue=8)
    with ThreadPoolExecutor(2) as ex:
        first = ex.submit(
            lambda: b.submit(np.zeros((1,), np.float32), timeout_ms=10_000)
        )
        time.sleep(0.1)  # worker is now inside slow_forward with request A
        with pytest.raises(DeadlineExceeded):
            b.submit(np.zeros((1,), np.float32), timeout_ms=100)
        release.set()
        assert first.result(timeout=10).shape == (1, 1)
    assert b.stats()["expired"] >= 1
    b.shutdown()


def test_bounded_queue_rejects_with_queue_full():
    release = threading.Event()
    entered = threading.Event()

    def slow_forward(batch):
        entered.set()
        release.wait(timeout=10.0)
        return batch

    b = _make(slow_forward, max_batch=1, max_delay_ms=1.0, max_queue=2)
    results = []
    with ThreadPoolExecutor(4) as ex:
        futs = [ex.submit(
            lambda: b.submit(np.zeros((1,), np.float32), timeout_ms=10_000)
        )]
        assert entered.wait(timeout=5.0)  # A popped; queue now empty
        for _ in range(2):  # B, C fill the bounded queue
            futs.append(ex.submit(
                lambda: b.submit(np.zeros((1,), np.float32),
                                 timeout_ms=10_000)
            ))
        deadline = time.monotonic() + 5.0
        while b.stats()["queue_depth"] < 2:
            assert time.monotonic() < deadline, "queue never filled"
            time.sleep(0.01)
        with pytest.raises(QueueFull):  # D bounces
            b.submit(np.zeros((1,), np.float32), timeout_ms=10_000)
        release.set()
        results = [f.result(timeout=10) for f in futs]
    assert len(results) == 3
    stats = b.stats()
    assert stats["rejected"] == 1 and stats["completed"] == 3
    b.shutdown()


def test_rank_ordered_flush_takes_alerts_first():
    """A later-arriving rank-0 (alert) request is flushed ahead of the
    rank-2 (batch) backlog already queued; FIFO holds within a rank."""
    release = threading.Event()
    entered = threading.Event()
    order = []

    def gated_forward(batch):
        if not entered.is_set():
            entered.set()
            release.wait(timeout=10.0)
        order.append(float(batch[0, 0]))
        return batch

    b = _make(gated_forward, max_batch=1, max_delay_ms=1.0, max_queue=8)
    with ThreadPoolExecutor(4) as ex:
        futs = [ex.submit(
            lambda: b.submit(np.full((1,), 0.0, np.float32),
                             timeout_ms=10_000, rank=2)
        )]
        assert entered.wait(timeout=5.0)  # worker pinned on request 0.0
        for val, rank in [(1.0, 2), (2.0, 2), (3.0, 0)]:
            futs.append(ex.submit(
                lambda v=val, r=rank: b.submit(
                    np.full((1,), v, np.float32), timeout_ms=10_000, rank=r)
            ))
            deadline = time.monotonic() + 5.0
            while b.stats()["queue_depth"] < len(futs) - 1:
                assert time.monotonic() < deadline, "request never queued"
                time.sleep(0.005)
        release.set()
        for f in futs:
            f.result(timeout=10)
    assert order == [0.0, 3.0, 1.0, 2.0]
    b.shutdown()


def test_shutdown_drains_queued_requests():
    release = threading.Event()

    def gated_forward(batch):
        release.wait(timeout=10.0)
        return batch

    b = _make(gated_forward, max_batch=1, max_delay_ms=1.0, max_queue=8)
    with ThreadPoolExecutor(3) as ex:
        futs = [
            ex.submit(lambda: b.submit(np.zeros((1,), np.float32),
                                       timeout_ms=20_000))
            for _ in range(3)
        ]
        time.sleep(0.1)
        release.set()
        b.shutdown(drain=True)  # returns once the queue is served
        for f in futs:
            assert f.result(timeout=10).shape == (1, 1)
    with pytest.raises(ShuttingDown):
        b.submit(np.zeros((1,), np.float32))
    assert b.stats()["completed"] == 3


def test_timeout_during_forward_counted_once():
    """A caller abandoning mid-forward is expired, NOT also completed:
    submitted == completed + expired + rejected + failed must hold."""
    release = threading.Event()
    entered = threading.Event()

    def slow_forward(batch):
        entered.set()
        release.wait(timeout=10.0)
        return batch

    b = _make(slow_forward, max_batch=1, max_delay_ms=1.0)
    with pytest.raises(DeadlineExceeded):
        b.submit(np.zeros((1,), np.float32), timeout_ms=150)
    assert entered.is_set()  # the worker had collected the request
    release.set()
    b.shutdown(drain=True)
    stats = b.stats()
    assert stats["submitted"] == 1
    assert stats["expired"] == 1
    assert stats["completed"] == 0  # not double-counted
    assert stats["submitted"] == (
        stats["completed"] + stats["expired"]
        + stats["rejected"] + stats["failed"]
    )


def test_forward_failure_propagates_not_kills_worker():
    calls = []

    def flaky(batch):
        calls.append(batch.shape[0])
        if len(calls) == 1:
            raise RuntimeError("boom")
        return batch

    b = _make(flaky, max_batch=1, max_delay_ms=1.0)
    from seist_tpu.serve.protocol import ServeError

    with pytest.raises(ServeError):
        b.submit(np.zeros((1,), np.float32), timeout_ms=5000)
    # Worker survived; next request succeeds.
    out = b.submit(np.zeros((1,), np.float32), timeout_ms=5000)
    assert out.shape == (1, 1)
    b.shutdown()


# ------------------------------------------------------------ e2e: service
WINDOW = 256
N_CONCURRENT = 6


@pytest.fixture(scope="module")
def service():
    from seist_tpu.serve import BatcherConfig as BC
    from seist_tpu.serve import ModelPool, ServeService

    pool = ModelPool([("phasenet", "")], window=WINDOW)
    svc = ServeService(
        pool, BC(max_batch=4, max_delay_ms=25.0, max_queue=32)
    )
    yield svc
    svc.shutdown()


@pytest.fixture(scope="module")
def traces():
    rng = np.random.default_rng(0)
    return [
        rng.standard_normal((WINDOW, 3)).astype(np.float32)
        for _ in range(N_CONCURRENT)
    ]


class TestServiceEndToEnd:
    def test_concurrent_requests_coalesce_into_fewer_forwards(
        self, service, traces
    ):
        before = service.metrics()["models"]["phasenet"]["forwards"]
        opts = {"ppk_threshold": 0.05, "spk_threshold": 0.05}
        with ThreadPoolExecutor(N_CONCURRENT) as ex:
            results = list(ex.map(
                lambda t: service.predict(t.tolist(), options=opts), traces
            ))
        assert len(results) == N_CONCURRENT
        stats = service.metrics()["models"]["phasenet"]
        forwards = stats["forwards"] - before
        assert 0 < forwards < N_CONCURRENT  # the acceptance criterion
        assert stats["completed"] >= N_CONCURRENT
        assert 0 < stats["batch_fill_ratio"] <= 1.0
        assert stats["latency_ms"]["count"] >= N_CONCURRENT

    def test_predict_matches_offline_postprocess(self, service, traces):
        """Serve output == the offline path (normalize -> forward ->
        ops/postprocess.process_outputs) on the same input."""
        from seist_tpu.data.preprocess import normalize
        from seist_tpu.ops.postprocess import process_outputs
        from seist_tpu.serve.protocol import PredictOptions

        entry = service.pool.get("phasenet")
        opts = PredictOptions(ppk_threshold=0.05, spk_threshold=0.05)
        for trace in traces[:2]:
            served = service.predict(
                trace.tolist(),
                options={"ppk_threshold": 0.05, "spk_threshold": 0.05},
            )
            x = np.asarray(normalize(trace, "std", axis=0), np.float32)
            raw = entry.forward(x[None])
            offline = process_outputs(
                raw,
                entry.spec.labels,
                opts.sampling_rate,
                ppk_threshold=opts.ppk_threshold,
                spk_threshold=opts.spk_threshold,
                det_threshold=opts.det_threshold,
                min_peak_dist=opts.min_peak_dist,
                max_detect_event_num=opts.max_events,
            )
            for kind in ("ppk", "spk"):
                want = np.asarray(offline[kind])[0]
                want = [int(i) for i in want[want >= 0]]
                got = [p["sample"] for p in served[kind]]
                assert got == want

    def test_annotate_matches_offline_stream(self, service):
        """/annotate == direct ops/stream.annotate with the same warm
        forward — the tools/predict.py code path."""
        from seist_tpu.ops.stream import annotate

        rng = np.random.default_rng(1)
        record = rng.standard_normal((700, 3)).astype(np.float32)
        entry = service.pool.get("phasenet")
        served = service.annotate(
            record.tolist(),
            options={"ppk_threshold": 0.05, "det_threshold": 0.05},
        )
        offline = annotate(
            entry.forward,
            record,
            window=WINDOW,
            batch_size=service.buckets[-1],
            ppk_threshold=0.05,
            det_threshold=0.05,
            combine="max",
            channel0=entry.channel0,
            jitted=True,
        )
        assert [p["sample"] for p in served["ppk"]] == [
            int(i) for i in offline["ppk"]
        ]
        assert [p["sample"] for p in served["spk"]] == [
            int(i) for i in offline["spk"]
        ]
        assert served["windows"] > 1

    def test_short_trace_padded_long_trace_rejected(self, service):
        rng = np.random.default_rng(2)
        short = service.predict(
            rng.standard_normal((WINDOW // 2, 3)).astype(np.float32).tolist(),
            options={"ppk_threshold": 0.05},
        )
        assert short["task"] == "picking"
        # Nothing decoded from the zero-padding the client never sent.
        for kind in ("ppk", "spk"):
            assert all(p["sample"] < WINDOW // 2 for p in short[kind])
        assert all(
            d["onset"] < WINDOW // 2 and d["offset"] < WINDOW // 2
            for d in short.get("det", [])
        )
        with pytest.raises(BadRequest):
            service.predict(
                rng.standard_normal((WINDOW * 2, 3)).tolist()
            )

    def test_http_roundtrip(self, service):
        import http.client

        from seist_tpu.serve import start_http_server

        server = start_http_server(service, port=0)
        host, port = server.server_address[:2]
        try:
            def call(method, path, payload=None):
                conn = http.client.HTTPConnection(host, port, timeout=120)
                body = json.dumps(payload) if payload is not None else None
                conn.request(method, path, body)
                resp = conn.getresponse()
                out = json.loads(resp.read())
                conn.close()
                return resp.status, out

            rng = np.random.default_rng(3)
            trace = rng.standard_normal((3, WINDOW)).tolist()  # (C, L) ok
            status, out = call("POST", "/predict", {
                "data": trace, "options": {"ppk_threshold": 0.05},
            })
            assert status == 200 and out["model"] == "phasenet"
            status, out = call("GET", "/healthz")
            assert status == 200 and out["status"] == "ok"
            status, out = call("GET", "/metrics")
            assert status == 200 and "phasenet" in out["models"]
            assert out["models"]["phasenet"]["latency_ms"]["count"] > 0
            status, out = call("POST", "/predict", {"data": [[1, 2], [3, 4]]})
            assert status == 400 and out["error"] == "bad_request"
            status, out = call("POST", "/predict", {
                "model": "nope", "data": trace,
            })
            assert status == 404 and out["error"] == "unknown_model"
            status, _ = call("GET", "/nope")
            assert status == 404

            # Prometheus exposition (docs/OBSERVABILITY.md): the obs-bus
            # render — batcher stats appear as labeled serve_batcher
            # series; bare /metrics above stayed JSON (back-compat).
            conn = http.client.HTTPConnection(host, port, timeout=120)
            conn.request("GET", "/metrics?format=prometheus")
            resp = conn.getresponse()
            text = resp.read().decode()
            ctype = resp.getheader("Content-Type", "")
            conn.close()
            assert resp.status == 200
            assert ctype.startswith("text/plain")
            assert 'seist_serve_batcher_submitted{model="phasenet"}' in text
            assert "seist_serve_requests_predict" in text
        finally:
            server.shutdown()


# -------------------------------------------- robustness: health + watchdog
class TestHealthAndWatchdog:
    def test_live_and_ready_endpoints(self, service):
        import http.client

        from seist_tpu.serve import start_http_server

        server = start_http_server(service, port=0)
        host, port = server.server_address[:2]
        try:
            def call(path):
                conn = http.client.HTTPConnection(host, port, timeout=60)
                conn.request("GET", path)
                resp = conn.getresponse()
                out = json.loads(resp.read())
                conn.close()
                return resp.status, out

            status, out = call("/healthz/live")
            assert status == 200 and out["status"] == "ok"
            status, out = call("/healthz/ready")
            assert status == 200 and out["ready"] is True
            status, out = call("/healthz")
            assert status == 200 and out["live"] and out["ready"]

            # SIGTERM drain window: not-ready (503) but still live (200).
            service.begin_drain()
            try:
                status, out = call("/healthz/ready")
                assert status == 503 and out["status"] == "draining"
                status, _ = call("/healthz/live")
                assert status == 200
                with pytest.raises(ShuttingDown):
                    service.predict(np.zeros((WINDOW, 3)).tolist())
            finally:
                service._draining = False  # restore the shared fixture
            status, _ = call("/healthz/ready")
            assert status == 200
        finally:
            server.shutdown()

    def test_async_warmup_reports_not_ready_then_ready(self, service):
        """warmup_async: readiness flips only after the pool pre-compile
        finishes (the pool here is already warm, so 'compile' is instant —
        the test pins the state machine, not the compile time)."""
        from seist_tpu.serve import BatcherConfig as BC
        from seist_tpu.serve import ServeService

        svc = ServeService(
            service.pool,
            BC(max_batch=4, max_delay_ms=5.0, max_queue=8),
            warmup_async=True,
        )
        try:
            deadline = time.monotonic() + 60
            while not svc.ready() and time.monotonic() < deadline:
                time.sleep(0.01)
            assert svc.ready() and svc.alive()
        finally:
            svc.shutdown()

    def test_dead_flush_thread_fails_liveness_and_watchdog_exits(self):
        """A batcher whose flush loop dies must (a) fail fast on submit,
        (b) drop liveness, and (c) make the server watchdog return 1 —
        the server process then exits non-zero instead of hanging."""
        from types import SimpleNamespace

        from seist_tpu.serve.protocol import ServeError
        from seist_tpu.serve.server import watch_until_shutdown

        b = _make(lambda x: x, max_batch=2, max_delay_ms=5.0)
        assert b.healthy

        def boom(pending):
            raise RuntimeError("flush machinery broke")

        b._run_batch = boom  # fails OUTSIDE the per-request try/except
        with pytest.raises(ServeError, match="flush thread died"):
            b.submit(np.zeros((2,), np.float32), timeout_ms=2000)
        deadline = time.monotonic() + 5
        while b.healthy and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not b.healthy
        assert b.stats()["healthy"] is False
        # Fast-fail for later submitters (no deadline wait).
        with pytest.raises(ServeError, match="flush thread died"):
            b.submit(np.zeros((2,), np.float32), timeout_ms=60_000)

        svc = SimpleNamespace(_batchers={"m": b}, alive=lambda: b.healthy)
        rc = watch_until_shutdown(svc, threading.Event(), poll_s=0.01)
        assert rc == 1

    def test_watchdog_returns_zero_on_stop(self, service):
        from seist_tpu.serve.server import watch_until_shutdown

        stop = threading.Event()
        stop.set()
        assert watch_until_shutdown(service, stop, poll_s=0.01) == 0

    def test_failed_warmup_never_reports_ready(self):
        """A warm-up that raises (compile OOM, bad bucket) must not flip
        the service to ready; liveness drops and the watchdog exits 1 —
        the async equivalent of the sync path's crash."""
        from types import SimpleNamespace

        from seist_tpu.serve import BatcherConfig as BC
        from seist_tpu.serve import ServeService
        from seist_tpu.serve.server import watch_until_shutdown

        class BoomPool:
            warmup_report = []

            def names(self):
                return ["m"]

            def get(self, name):
                return SimpleNamespace(forward=lambda x: x)

            def warmup(self, buckets):
                raise RuntimeError("compile boom")

        svc = ServeService(
            BoomPool(), BC(max_batch=2, max_delay_ms=5.0),
            warmup_async=True,
        )
        try:
            deadline = time.monotonic() + 10
            while svc._warmup_error is None and time.monotonic() < deadline:
                time.sleep(0.01)
            assert svc._warmup_error is not None
            assert not svc.ready() and not svc.alive()
            rc = watch_until_shutdown(svc, threading.Event(), poll_s=0.01)
            assert rc == 1
            # Sync construction of the same pool crashes loudly.
            with pytest.raises(RuntimeError, match="compile boom"):
                ServeService(BoomPool(), BC(max_batch=2, max_delay_ms=5.0))
        finally:
            svc.shutdown(drain=False)


# =================================================================== shedding
class TestAdmissionControl:
    """serve/shed.py units: tier order, hysteresis, Retry-After, stats."""

    def _ctl(self, delay, **kw):
        from seist_tpu.serve.shed import AdmissionController, ShedConfig

        box = {"ms": delay}
        ctl = AdmissionController(
            lambda: box["ms"], ShedConfig(**kw), model="t"
        )
        return ctl, box

    def test_batch_shed_first_alert_never(self):
        from seist_tpu.serve.protocol import Overloaded

        ctl, box = self._ctl(100.0)  # > batch 50, < interactive 250
        try:
            with pytest.raises(Overloaded):
                ctl.admit("batch")
            ctl.admit("interactive")
            ctl.admit("alert")
            box["ms"] = 1e6  # grotesque overload
            with pytest.raises(Overloaded):
                ctl.admit("interactive")
            ctl.admit("alert")  # inf threshold: alerts ride to the 429
            assert ctl.shed_level() == 2
        finally:
            ctl.close()

    def test_hysteresis_sticky_until_half_threshold(self):
        from seist_tpu.serve.protocol import Overloaded

        ctl, box = self._ctl(60.0, batch_delay_ms=50.0, hysteresis=0.5)
        try:
            with pytest.raises(Overloaded):
                ctl.admit("batch")  # 60 > 50: flips to shedding
            box["ms"] = 30.0  # below threshold but above 25 = 50*0.5
            with pytest.raises(Overloaded):
                ctl.admit("batch")  # sticky
            box["ms"] = 20.0
            ctl.admit("batch")  # readmitted below the hysteresis floor
            assert ctl.shed_level() == 0
        finally:
            ctl.close()

    def test_retry_after_scales_with_delay_and_is_integral(self):
        from seist_tpu.serve.protocol import Overloaded

        ctl, box = self._ctl(4000.0)
        try:
            with pytest.raises(Overloaded) as ei:
                ctl.admit("batch")
            e = ei.value
            assert e.status == 503 and e.code == "shed"
            assert e.retry_after_s == pytest.approx(8.0)  # 2x delay
            assert e.headers() == {"Retry-After": "8"}
            assert e.payload()["retry_after_s"] == 8.0
        finally:
            ctl.close()

    def test_sub_second_min_retry_after_is_honored(self):
        """ShedConfig.min_retry_after_s owns the floor: Overloaded must
        not re-clamp a configured sub-second value back up to 1 s."""
        from seist_tpu.serve.protocol import Overloaded

        e = Overloaded("x", retry_after_s=0.2)
        assert e.retry_after_s == pytest.approx(0.2)
        assert e.payload()["retry_after_s"] == 0.2
        # Retry-After stays integral per RFC 9110 (ceil, not clamp).
        assert e.headers() == {"Retry-After": "1"}

    def test_shed_distinct_from_queue_full(self):
        """The two overload responses must stay distinguishable: policy
        shed = 503 'shed' (+Retry-After), hard bound = 429 'queue_full'."""
        from seist_tpu.serve.protocol import Overloaded, QueueFull

        shed, full = Overloaded("x", 2.0), QueueFull("y")
        assert (shed.status, shed.code) == (503, "shed")
        assert (full.status, full.code) == (429, "queue_full")
        assert "Retry-After" in shed.headers()

    def test_stats_on_bus_and_close_unregisters(self):
        from seist_tpu.obs.bus import BUS

        ctl, box = self._ctl(100.0)
        try:
            ctl.admit("alert")
        finally:
            ctl.close()
        snap = ctl.stats()
        assert snap["tiers"]["alert"]["admitted"] == 1
        assert snap["queue_delay_ms"] == 100.0
        # Only THIS controller's collector is gone; other live services'
        # shed collectors (e.g. the module fixture's) remain untouched.
        assert all(
            'model="t"' not in k
            for k in BUS.snapshot()["collectors"]
            if k.startswith("serve_shed")
        )

    def test_unknown_priority_rejected_at_protocol(self):
        from seist_tpu.serve.protocol import BadRequest, PredictOptions

        with pytest.raises(BadRequest, match="priority"):
            PredictOptions.from_dict({"priority": "urgent"})
        assert PredictOptions.from_dict({}).priority == "interactive"
        assert PredictOptions.from_dict(
            {"priority": "alert"}
        ).priority == "alert"


def test_queue_delay_estimate_tracks_backlog():
    """queue_delay_ms: 0 when idle; grows with a held queue; prices queued
    flush waves by the service-time EWMA once one flush has completed."""
    gate = threading.Event()

    def blocked_forward(batch):
        gate.wait(5.0)
        return np.asarray(batch)

    b = MicroBatcher(
        blocked_forward,
        BatcherConfig(max_batch=2, max_delay_ms=1.0, max_queue=64),
    )
    try:
        assert b.queue_delay_ms() == 0.0
        results = []
        pool = ThreadPoolExecutor(6)
        for _ in range(6):
            results.append(
                pool.submit(
                    b.submit,
                    np.zeros((4, 3), np.float32),
                    timeout_ms=5000.0,
                )
            )
        deadline = time.monotonic() + 2.0
        while b.queue_delay_ms() == 0.0 and time.monotonic() < deadline:
            time.sleep(0.005)
        est = b.queue_delay_ms()
        assert est > 0.0, "held queue must read a positive delay"
        time.sleep(0.05)
        assert b.queue_delay_ms() > est, "estimate must grow while held"
        gate.set()
        for r in results:
            r.result(timeout=5.0)
        pool.shutdown()
        assert b.queue_delay_ms() == 0.0  # drained: no backlog, no delay
        assert b.stats()["queue_delay_ms"] == 0.0
    finally:
        gate.set()
        b.shutdown(drain=False)


# ====================================================== faults: 504 + shed
class TestServeFaultPaths:
    """SEIST_FAULT_SERVE_* driving the deadline and shed branches through
    the REAL predict path (phasenet pool fixture)."""

    def test_slow_model_forces_504_deadline(self, service):
        """Satellite: the predict 504 branch had no direct test. An
        injected in-forward sleep (SEIST_FAULT_SERVE_SLOW_MS) longer than
        the request deadline must surface as DeadlineExceeded (HTTP 504),
        and the service must stay healthy for later requests."""
        from seist_tpu.serve import BatcherConfig as BC
        from seist_tpu.serve import ServeService
        from seist_tpu.serve.protocol import DeadlineExceeded
        from seist_tpu.utils.faults import (
            ServeFaultInjector,
            ServeFaultPlan,
        )

        svc = ServeService(
            service.pool,
            BC(max_batch=2, max_delay_ms=5.0, max_queue=16),
            faults=ServeFaultInjector(ServeFaultPlan(slow_ms=400.0)),
        )
        try:
            trace = np.zeros((WINDOW, 3), np.float32)
            with pytest.raises(DeadlineExceeded) as ei:
                svc.predict(trace, options={"timeout_ms": 120.0})
            assert ei.value.status == 504
            # The injected slowness is per-flush, not a crash: a patient
            # request still succeeds afterwards.
            out = svc.predict(trace, options={"timeout_ms": 10_000.0})
            assert "picks" in out or isinstance(out, dict)
        finally:
            svc.shutdown(drain=False)

    def test_slow_env_plan_parses(self, monkeypatch):
        from seist_tpu.utils.faults import ServeFaultInjector

        monkeypatch.setenv("SEIST_FAULT_SERVE_SLOW_MS", "250")
        inj = ServeFaultInjector.from_env()
        assert inj.enabled and inj.plan.slow_ms == 250.0

    def test_overload_sheds_batch_tier_in_predict(self, service):
        """Back-pressure e2e at service level: a slow flush builds queue
        delay; a batch-tier request is then shed 503 while alert-tier
        requests keep being admitted (they may be slow, never refused)."""
        from seist_tpu.serve import BatcherConfig as BC
        from seist_tpu.serve import ServeService
        from seist_tpu.serve.protocol import Overloaded
        from seist_tpu.serve.shed import ShedConfig
        from seist_tpu.utils.faults import (
            ServeFaultInjector,
            ServeFaultPlan,
        )

        svc = ServeService(
            service.pool,
            BC(max_batch=1, max_delay_ms=1.0, max_queue=64),
            shed_config=ShedConfig(
                batch_delay_ms=50.0, interactive_delay_ms=1e9
            ),
            faults=ServeFaultInjector(ServeFaultPlan(slow_ms=150.0)),
        )
        try:
            trace = np.zeros((WINDOW, 3), np.float32)
            pool = ThreadPoolExecutor(8)
            futures = [
                pool.submit(
                    svc.predict, trace,
                    options={"timeout_ms": 30_000.0},
                )
                for _ in range(8)
            ]
            # Let the backlog age past the 50 ms batch budget.
            deadline = time.monotonic() + 5.0
            batcher = svc._batchers["phasenet"]
            while (
                batcher.queue_delay_ms() < 200.0
                and time.monotonic() < deadline
            ):
                time.sleep(0.01)
            with pytest.raises(Overloaded) as ei:
                svc.predict(
                    trace,
                    options={"timeout_ms": 30_000.0, "priority": "batch"},
                )
            assert ei.value.status == 503
            assert "Retry-After" in ei.value.headers()
            # Alert tier still admitted under the same backlog.
            out = svc.predict(
                trace, options={"timeout_ms": 30_000.0, "priority": "alert"}
            )
            assert isinstance(out, dict)
            for f in futures:
                f.result(timeout=30.0)
            pool.shutdown()
            shed_stats = svc.metrics()["shed"]["phasenet"]
            assert shed_stats["tiers"]["batch"]["shed"] >= 1
            assert shed_stats["tiers"]["alert"]["shed"] == 0
        finally:
            svc.shutdown(drain=False)


# ================================================== lifecycle state machine
def test_lifecycle_states_published_to_events_and_gauge(service, tmp_path):
    """Satellite: warming -> ok -> draining transitions must land in
    events.jsonl and on the serve_state_code bus gauge so the router,
    flight recorder and operators watch the same state machine."""
    from seist_tpu.obs.bus import BUS, EventLog
    from seist_tpu.serve import BatcherConfig as BC
    from seist_tpu.serve import ServeService
    from seist_tpu.serve.server import STATE_CODES

    log_path = str(tmp_path / "events.jsonl")
    events = EventLog(log_path)
    svc = ServeService(
        service.pool,
        BC(max_batch=2, max_delay_ms=5.0),
        event_log=events,
    )
    try:
        svc.begin_drain()
        assert BUS.snapshot()["gauges"]["serve_state_code"] == (
            STATE_CODES["draining"]
        )
    finally:
        svc.shutdown(drain=False)
        events.close()
    with open(log_path) as f:
        recs = [json.loads(line) for line in f if line.strip()]
    states = [r["state"] for r in recs if r["event"] == "serve_state"]
    assert states == ["warming", "ok", "draining"]
    transitions = [
        (r["prev"], r["state"]) for r in recs if r["event"] == "serve_state"
    ]
    assert transitions[0] == (None, "warming")
    assert transitions[1] == ("warming", "ok")


# ------------------------------------------- distributed tracing (ISSUE 11)
class TestDistributedTracing:
    """The serve half of obs/trace.py: a /predict over real HTTP carries a
    Server-Timing breakdown + traceparent echo, and GET /traces/<id>
    exposes the admission/parse/queue-wait/forward/decode decomposition
    with the AOT program key."""

    def test_http_predict_traced_end_to_end(self, service):
        import http.client

        from seist_tpu.obs import trace as obs_trace
        from seist_tpu.serve import start_http_server

        obs_trace.BUFFER.reset()
        server = start_http_server(service, port=0)
        host, port = server.server_address[:2]
        try:
            header = obs_trace.mint_traceparent()
            tid, client_span = obs_trace.parse_traceparent(header)
            rng = np.random.default_rng(7)
            body = json.dumps({
                "data": rng.standard_normal((WINDOW, 3)).tolist(),
                "options": {"ppk_threshold": 0.05},
            }).encode()
            conn = http.client.HTTPConnection(host, port, timeout=120)
            try:
                conn.request("POST", "/predict", body,
                             {"Content-Type": "application/json",
                              "traceparent": header})
                resp = conn.getresponse()
                payload = json.loads(resp.read())
                timing = resp.getheader("Server-Timing", "")
                echoed = resp.getheader("traceparent", "")
            finally:
                conn.close()
            assert resp.status == 200 and payload["model"] == "phasenet"
            # Same trace id back; the replica's root span id, not ours.
            e_tid, e_span = obs_trace.parse_traceparent(echoed)
            assert e_tid == tid and e_span != client_span
            assert timing.startswith("total;dur=")
            for seg in ("admission", "parse", "queue_wait", "forward",
                        "decode"):
                assert f"{seg};dur=" in timing, timing

            # The span segments are fetchable by the client-minted id.
            conn = http.client.HTTPConnection(host, port, timeout=60)
            try:
                conn.request("GET", f"/traces/{tid}")
                trace = json.loads(conn.getresponse().read())
            finally:
                conn.close()
            spans = {s["name"]: s for s in trace["spans"]}
            root = spans["server:/predict"]
            assert root["parent_id"] == client_span
            assert root["annotations"]["status"] == 200
            assert root["annotations"]["model"] == "phasenet"
            admission = spans["admission"]
            assert admission["annotations"]["verdict"] == "admitted"
            qw = spans["queue_wait"]
            assert qw["annotations"]["bucket"] >= 1
            fwd = spans["forward"]
            # The device program that served it, AOT by construction.
            assert "phasenet/full/b" in fwd["annotations"]["program"]
            assert fwd["annotations"]["aot"] is True
            # /metrics.json (the fleet aggregator's scrape payload) is
            # servable and carries bucketed histograms.
            from seist_tpu.obs.bus import BUS

            BUS.histogram("trace_probe_ms").observe(1.0)
            conn = http.client.HTTPConnection(host, port, timeout=60)
            try:
                conn.request("GET", "/metrics.json")
                snap = json.loads(conn.getresponse().read())
            finally:
                conn.close()
            assert "counters" in snap and "histograms" in snap
            # Raw buckets ride along for the fleet aggregator's
            # bucket-wise merge.
            assert "bucket_counts" in snap["histograms"]["trace_probe_ms"]
        finally:
            server.shutdown()
            obs_trace.BUFFER.reset()

    def test_shed_request_flagged_with_verdict_span(self, service):
        """A shed 503 rides the trace: admission span carries the
        verdict, the trace is flagged 'shed' (always retained), and the
        error flag is NOT set (policy, not failure)."""
        from seist_tpu.obs import trace as obs_trace
        from seist_tpu.serve.protocol import Overloaded

        obs_trace.BUFFER.reset()
        shedder = service._shedders["phasenet"]
        orig = shedder._delay_ms
        shedder._delay_ms = lambda: 1e9  # force overload
        rt = obs_trace.RequestTrace(None, name="server:/predict")
        try:
            with pytest.raises(Overloaded):
                service.predict(
                    np.zeros((WINDOW, 3)).tolist(),
                    options={"priority": "batch"},
                    trace=rt,
                )
            rt.flag("shed")  # the HTTP handler's part
            rt.finish(503)
            payload = obs_trace.BUFFER.get(rt.trace_id)
            assert payload["flags"] == ["shed"]
            spans = {s["name"]: s for s in payload["spans"]}
            assert spans["admission"]["annotations"]["verdict"] == "shed"
            assert "retry_after_s" in spans["admission"]["annotations"]
        finally:
            shedder._delay_ms = orig
            # un-stick the shed hysteresis for later fixture users
            for state in shedder._tiers.values():
                state.shedding = False
            obs_trace.BUFFER.reset()


# ------------------------------------------- serve-plane flight dumps
class TestServeFlightDumps:
    """ISSUE 11 satellite: the serve plane's remaining death paths leave
    flight-recorder dumps like the train worker's (PR 6)."""

    @pytest.fixture
    def recorder(self, tmp_path, monkeypatch):
        from seist_tpu.obs import flight
        from seist_tpu.utils.logger import logger

        monkeypatch.setattr(logger, "_logdir", str(tmp_path),
                            raising=False)
        # The cross-test dedup window is module state; a previous test's
        # dump must not swallow this test's.
        monkeypatch.setattr(flight, "_LAST_DUMP_MONO", None)
        rec = flight.FlightRecorder(capacity=16)
        prev = flight.install(rec)
        yield tmp_path
        flight.install(prev)

    def _dumps(self, tmp_path, reason):
        import glob
        import os

        return glob.glob(
            os.path.join(str(tmp_path), "flight", f"flight_{reason}_*")
        )

    def test_batcher_flush_death_dumps_flight(self, recorder):
        from seist_tpu.serve.batcher import BatcherConfig, MicroBatcher
        from seist_tpu.serve.protocol import ServeError

        b = MicroBatcher(lambda x: x,
                         BatcherConfig(max_batch=2, max_delay_ms=5.0),
                         name="doomed")

        def boom(pending):
            raise RuntimeError("flush machinery broke")

        b._run_batch = boom
        # Generous timing margins: the flush thread must get scheduled
        # to die AND serialize the dump (a full bus snapshot, sizable
        # late in a suite run) — under full-suite contention on a
        # 1-core host either can overshoot a tight budget.
        with pytest.raises(ServeError, match="flush thread died"):
            b.submit(np.zeros((2,), np.float32), timeout_ms=10_000)
        deadline = time.monotonic() + 30
        while not self._dumps(recorder, "batcher_flush_death"):
            if time.monotonic() > deadline:
                raise AssertionError("no batcher_flush_death flight dump")
            time.sleep(0.01)
        dump = json.loads(
            open(self._dumps(recorder, "batcher_flush_death")[0]).read()
        )
        assert dump["reason"] == "batcher_flush_death"
        assert dump["batcher"] == "doomed"
        assert "RuntimeError" in dump["error"]

    def test_handler_exception_dumps_flight(self, recorder, service):
        """An uncaught HTTP-handler exception (a handler BUG, not a
        ServeError) must 500 the request AND leave a flight record."""
        import http.client

        from seist_tpu.serve import start_http_server

        server = start_http_server(service, port=0)
        host, port = server.server_address[:2]
        orig = service.predict
        service.predict = lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("handler bug")
        )
        try:
            conn = http.client.HTTPConnection(host, port, timeout=60)
            try:
                conn.request("POST", "/predict",
                             json.dumps({"data": [[0.0] * 3]}).encode(),
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                out = json.loads(resp.read())
            finally:
                conn.close()
            assert resp.status == 500 and out["error"] == "internal"
            dumps = self._dumps(recorder, "serve_handler_exception")
            assert dumps, "no serve_handler_exception flight dump"
            dump = json.loads(open(dumps[0]).read())
            assert dump["request_path"] == "/predict"
            assert "RuntimeError" in dump["error"]
        finally:
            service.predict = orig
            server.shutdown()

    def test_unhealthy_watchdog_exit_dumps_flight(self, recorder):
        from types import SimpleNamespace

        from seist_tpu.serve.server import watch_until_shutdown

        dead = SimpleNamespace(healthy=False)
        svc = SimpleNamespace(
            alive=lambda: False,
            _batchers={"m": dead},
            _warmup_error=None,
        )
        rc = watch_until_shutdown(svc, threading.Event(), poll_s=0.01)
        assert rc == 1
        dumps = self._dumps(recorder, "serve_unhealthy")
        assert dumps, "no serve_unhealthy flight dump"
        assert "flush thread" in json.loads(open(dumps[0]).read())["detail"]
