"""utils/faults injection harness + the train/step.py bad-update guard
(unit level; the end-to-end kill/NaN/preempt runs live in
tests/test_fault_tolerance_e2e.py)."""

import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

import seist_tpu
from seist_tpu import taskspec
from seist_tpu.train import (
    build_optimizer,
    create_train_state,
    make_accum_train_step,
    make_multi_train_step,
    make_train_step,
)
from seist_tpu.utils.faults import FaultInjector, FaultPlan

seist_tpu.load_all()

pytestmark = pytest.mark.faults  # `make chaos` lane (-m 'chaos or faults')

L = 64


# ------------------------------------------------------------ plan parsing
def test_plan_from_env_defaults_inert():
    plan = FaultPlan.from_env({})
    assert not plan.enabled
    assert plan.nan_step == -1 and plan.kill_step == -1


def test_plan_from_env_parses_knobs():
    plan = FaultPlan.from_env({
        "SEIST_FAULT_NAN_STEP": "12",
        "SEIST_FAULT_NAN_COUNT": "3",
        "SEIST_FAULT_KILL_STEP": "40",
        "SEIST_FAULT_SIGTERM_STEP": "7",
        "SEIST_FAULT_SLOW_MS": "1.5",
        "SEIST_FAULT_SLOW_STEP": "2",
        "SEIST_FAULT_STAMP": "/tmp/stamp",
    })
    assert plan.enabled
    assert plan.nan_step == 12 and plan.nan_count == 3
    assert plan.kill_step == 40 and plan.sigterm_step == 7
    assert plan.slow_ms == 1.5 and plan.slow_step == 2
    assert plan.stamp_path == "/tmp/stamp"


def test_plan_rejects_garbage():
    with pytest.raises(ValueError):
        FaultPlan.from_env({"SEIST_FAULT_NAN_STEP": "soon"})


# --------------------------------------------------------------- injection
def test_corrupt_inputs_only_in_window():
    inj = FaultInjector(FaultPlan(nan_step=2, nan_count=2))
    x = {"a": np.ones((3, 4), np.float32)}
    assert inj.corrupt_inputs(1, x) is x  # untouched outside the window
    x2 = inj.corrupt_inputs(2, x)
    assert np.isnan(np.asarray(x2["a"])).all()
    x3 = inj.corrupt_inputs(3, x)
    assert np.isnan(np.asarray(x3["a"])).all()
    assert inj.corrupt_inputs(4, x) is x


def test_corrupt_inputs_packed_window_overlap():
    """Packed paths hand one call covering [step, step+n); any overlap
    with the NaN window corrupts the stacked batch."""
    inj = FaultInjector(FaultPlan(nan_step=5, nan_count=1))
    x = np.ones((2, 3), np.float32)
    assert inj.corrupt_inputs(0, x, n_steps=4) is x  # [0,4) misses 5
    out = inj.corrupt_inputs(4, x, n_steps=4)  # [4,8) hits 5
    assert np.isnan(np.asarray(out)).all()


def test_stamp_file_makes_faults_fire_once_across_restarts(tmp_path):
    stamp = str(tmp_path / "stamp")
    plan = FaultPlan(nan_step=3, stamp_path=stamp)
    inj = FaultInjector(plan)
    x = np.ones(2, np.float32)
    assert np.isnan(np.asarray(inj.corrupt_inputs(3, x))).all()
    # Same process: already fired.
    assert inj.corrupt_inputs(3, x) is x
    # "Relaunched" process reads the stamp and stays inert.
    inj2 = FaultInjector(plan)
    assert inj2.corrupt_inputs(3, x) is x


def test_sigterm_and_kill_fire_via_os_kill(monkeypatch):
    sent = []
    monkeypatch.setattr(os, "kill", lambda pid, sig: sent.append((pid, sig)))
    inj = FaultInjector(FaultPlan(sigterm_step=2, kill_step=5))
    inj.on_step(1)
    assert sent == []
    inj.on_step(2)
    assert sent == [(os.getpid(), signal.SIGTERM)]
    inj.on_step(2)  # once only
    assert len(sent) == 1
    inj.on_step(5)
    assert sent[-1] == (os.getpid(), signal.SIGKILL)


def test_on_step_window_covers_packed_calls(monkeypatch):
    """Packed train paths only visit kpack boundaries; a kill scheduled
    mid-call must still fire (window semantics, like corrupt_inputs)."""
    sent = []
    monkeypatch.setattr(os, "kill", lambda pid, sig: sent.append(sig))
    inj = FaultInjector(FaultPlan(kill_step=5))
    inj.on_step(0, n_steps=4)  # [0, 4) misses 5
    assert sent == []
    inj.on_step(4, n_steps=4)  # [4, 8) hits 5
    assert sent == [signal.SIGKILL]


def test_slow_step_sleeps(monkeypatch):
    import seist_tpu.utils.faults as faults_mod

    naps = []
    monkeypatch.setattr(faults_mod.time, "sleep", lambda s: naps.append(s))
    inj = FaultInjector(FaultPlan(slow_ms=250.0, slow_step=3))
    inj.on_step(2)
    assert naps == []
    inj.on_step(3)
    assert naps == [0.25]
    # slow_step=-1 means every step.
    inj_all = FaultInjector(FaultPlan(slow_ms=100.0))
    inj_all.on_step(0)
    inj_all.on_step(1)
    assert naps == [0.25, 0.1, 0.1]


# -------------------------------------------------------- bad-update guard
class Tiny(nn.Module):
    @nn.compact
    def __call__(self, x, train=False):
        h = nn.gelu(nn.Dense(8)(x))
        return jax.nn.softmax(nn.Dense(3)(h), axis=-1)


def _tiny_setup():
    model = Tiny()
    variables = model.init(jax.random.PRNGKey(3), jnp.zeros((1, L, 3)))
    state = create_train_state(
        model, {"params": variables["params"]}, build_optimizer("adam", 1e-2)
    )
    spec = taskspec.get_task_spec("phasenet")  # CE on (N, L, 3) probs
    return state, spec, taskspec.make_loss("phasenet")


def _tiny_batch(rng, batch=4):
    x = rng.standard_normal((batch, L, 3)).astype(np.float32)
    ppk = np.zeros((batch, L), np.float32)
    ppk[:, 16] = 1.0
    spk = np.zeros((batch, L), np.float32)
    spk[:, 32] = 1.0
    y = np.stack([1.0 - ppk - spk, ppk, spk], axis=-1)
    return jnp.asarray(x), jnp.asarray(y)


def test_guarded_step_skips_nonfinite_update(rng):
    state, spec, loss_fn = _tiny_setup()
    step = jax.jit(make_train_step(spec, loss_fn, guard=True))
    x, y = _tiny_batch(rng)
    key = jax.random.PRNGKey(0)

    s1, loss1, out1, d1 = step(state, x, y, key)
    assert int(d1["applied"]) == 1
    assert np.isfinite(float(d1["grad_norm"]))
    assert int(s1.step) == 1

    xnan = x * np.float32("nan")
    s2, loss2, _, d2 = step(s1, xnan, y, key)
    assert int(d2["applied"]) == 0
    assert not np.isfinite(float(loss2))
    # The poisoned update touched NOTHING: params, opt_state, step.
    assert int(s2.step) == 1
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(
        jax.tree.leaves(s1.opt_state), jax.tree.leaves(s2.opt_state)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # ...and training continues cleanly afterwards.
    s3, loss3, _, d3 = step(s2, x, y, key)
    assert int(d3["applied"]) == 1 and np.isfinite(float(loss3))
    assert int(s3.step) == 2


def test_guarded_step_matches_unguarded_on_clean_data(rng):
    state, spec, loss_fn = _tiny_setup()
    x, y = _tiny_batch(rng)
    key = jax.random.PRNGKey(0)
    plain = jax.jit(make_train_step(spec, loss_fn))
    guarded = jax.jit(make_train_step(spec, loss_fn, guard=True))
    s1, l1, _ = plain(state, x, y, key)
    s2, l2, _, _ = guarded(state, x, y, key)
    assert float(l1) == float(l2)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_guarded_multi_step_counts_applied(rng):
    """k=3 scanned updates with the middle batch NaN: 2 applied, and the
    mean loss is over the finite micro-steps only."""
    state, spec, loss_fn = _tiny_setup()
    k = 3
    batches = [_tiny_batch(rng) for _ in range(k)]
    xs = jnp.stack([b[0] for b in batches])
    ys = jnp.stack([b[1] for b in batches])
    xs = xs.at[1].set(jnp.nan)
    multi = jax.jit(
        make_multi_train_step(spec, loss_fn, steps_per_call=k, guard=True)
    )
    s, mean_loss, _, diag = multi(state, xs, ys, jax.random.PRNGKey(7))
    # Ordered per-micro-step mask: the worker's consecutive-bad tracking
    # needs skip POSITIONS, not just the count.
    np.testing.assert_array_equal(np.asarray(diag["applied"]), [1, 0, 1])
    assert np.isfinite(float(mean_loss))
    # Skipped micro-steps do not advance state.step either.
    assert int(s.step) == 2


def test_guarded_accum_step_skips_whole_update(rng):
    """One NaN micro-batch poisons the summed gradient: the single
    accumulated update is skipped entirely."""
    state, spec, loss_fn = _tiny_setup()
    batches = [_tiny_batch(rng) for _ in range(2)]
    xs = jnp.stack([b[0] for b in batches]).at[0].set(jnp.nan)
    ys = jnp.stack([b[1] for b in batches])
    accum = jax.jit(
        make_accum_train_step(spec, loss_fn, accum_steps=2, guard=True)
    )
    s, loss, _, diag = accum(state, xs, ys, jax.random.PRNGKey(0))
    assert int(diag["applied"]) == 0
    assert int(s.step) == 0
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(s.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------- worker monitor
def test_bad_update_monitor_consecutive_and_lag():
    from seist_tpu.train.worker import _BadUpdateMonitor

    m = _BadUpdateMonitor(max_bad=3, lag=2)
    # Flags evaluate `lag` pushes late.
    assert m.push(0) is False  # nothing evaluated yet
    assert m.push(0) is False
    assert m.push(0) is False  # first 0 evaluated -> run=1
    assert m.bad_run == 1
    assert m.push(0) is False  # run=2
    assert m.push(0) is True   # run=3 -> rollback
    m.reset()
    assert m.bad_run == 0 and m.push(0) is False
    # A good step clears the run.
    m2 = _BadUpdateMonitor(max_bad=2, lag=0)
    assert m2.push(0) is False and m2.bad_run == 1
    assert m2.push(1) is False and m2.bad_run == 0
    assert m2.push(0) is False and m2.push(0) is True
    assert m2.flush() is True
    # Packed calls push the ordered applied mask: all-skipped accumulates,
    # a call ENDING in a success breaks the run even with earlier skips,
    # and trailing skips start a fresh run.
    m3 = _BadUpdateMonitor(max_bad=4, lag=0)
    assert m3.push([0, 0, 0]) is False and m3.bad_run == 3
    assert m3.push([0, 0, 1]) is False and m3.bad_run == 0  # run broken
    assert m3.push([1, 0, 0]) is False and m3.bad_run == 2
    assert m3.push([0, 0, 0]) is True  # 2 + 3 >= 4
    assert m3.total_skipped == 3 + 2 + 2 + 3


def test_monitor_disabled_when_max_bad_zero():
    from seist_tpu.train.worker import _BadUpdateMonitor

    m = _BadUpdateMonitor(max_bad=0, lag=0)
    for _ in range(10):
        assert m.push(0) is False
    assert m.flush() is False
    assert m.total_skipped == 10


# ------------------------------------------------------------- serve plane
class TestServeFaults:
    """ServeFaultPlan/Injector units (the serving-plane knobs; the e2e
    runs driving a real fleet live in tests/test_serve_chaos.py)."""

    def test_plan_from_env_defaults_inert(self):
        from seist_tpu.utils.faults import ServeFaultInjector, ServeFaultPlan

        plan = ServeFaultPlan.from_env(env={})
        assert not plan.enabled
        inj = ServeFaultInjector(plan)
        assert not inj.enabled
        inj.on_request(10**9)  # no fault scheduled: must be a no-op
        inj.forward_delay()

    def test_plan_parses_all_knobs(self):
        from seist_tpu.utils.faults import ServeFaultPlan

        plan = ServeFaultPlan.from_env(env={
            "SEIST_FAULT_SERVE_KILL_REQ": "7",
            "SEIST_FAULT_SERVE_SLOW_MS": "12.5",
            "SEIST_FAULT_SERVE_BLACKHOLE_AFTER": "3",
            "SEIST_FAULT_SERVE_BLACKHOLE_COUNT": "2",
            "SEIST_FAULT_SERVE_BLACKHOLE_HOLD_S": "0.01",
            "SEIST_FAULT_SERVE_REPLICA": "1",
            "SEIST_FAULT_STAMP": "/tmp/x",
        })
        assert plan.enabled
        assert (plan.kill_req, plan.slow_ms) == (7, 12.5)
        assert (plan.blackhole_after, plan.blackhole_count) == (3, 2)
        assert plan.replica == 1 and plan.stamp_path == "/tmp/x"

    def test_replica_targeting_gates_enabled(self):
        from seist_tpu.utils.faults import ServeFaultInjector, ServeFaultPlan

        plan = ServeFaultPlan(slow_ms=5.0, replica=1)
        assert not ServeFaultInjector(plan, replica_index=0).enabled
        assert ServeFaultInjector(plan, replica_index=1).enabled
        # replica=-1 fires anywhere, including outside a fleet.
        anywhere = ServeFaultPlan(slow_ms=5.0, replica=-1)
        assert ServeFaultInjector(anywhere, replica_index=-1).enabled

    def test_kill_fires_once_at_threshold_with_stamp(
        self, tmp_path, monkeypatch
    ):
        from seist_tpu.utils import faults as faults_mod
        from seist_tpu.utils.faults import ServeFaultInjector, ServeFaultPlan

        kills = []
        monkeypatch.setattr(
            faults_mod.os, "kill", lambda pid, sig: kills.append(sig)
        )
        stamp = str(tmp_path / "stamp")
        plan = ServeFaultPlan(kill_req=5, stamp_path=stamp)
        inj = ServeFaultInjector(plan, replica_index=-1)
        inj.on_request(4)
        assert not kills
        # >= threshold (not ==): concurrent arrivals can't skip past it.
        inj.on_request(6)
        assert kills == [signal.SIGKILL]
        # The stamp was written BEFORE the (here neutered) kill, so a
        # relaunched injector must never fire again.
        inj2 = ServeFaultInjector(plan, replica_index=-1)
        inj2.on_request(100)
        assert kills == [signal.SIGKILL]

    def test_blackhole_window_then_recovery(self, monkeypatch):
        from seist_tpu.utils import faults as faults_mod
        from seist_tpu.utils.faults import ServeFaultInjector, ServeFaultPlan

        held = []
        monkeypatch.setattr(
            faults_mod.time, "sleep", lambda s: held.append(s)
        )
        plan = ServeFaultPlan(
            blackhole_after=2, blackhole_count=3, blackhole_hold_s=9.0
        )
        inj = ServeFaultInjector(plan, replica_index=-1)
        for n in range(1, 9):
            inj.on_request(n)
        # Requests 3,4,5 held; 6+ recovered (finite count).
        assert held == [9.0, 9.0, 9.0]

    def test_forward_delay_sleeps_only_when_enabled(self, monkeypatch):
        from seist_tpu.utils import faults as faults_mod
        from seist_tpu.utils.faults import ServeFaultInjector, ServeFaultPlan

        slept = []
        monkeypatch.setattr(
            faults_mod.time, "sleep", lambda s: slept.append(s)
        )
        ServeFaultInjector(
            ServeFaultPlan(slow_ms=40.0), replica_index=-1
        ).forward_delay()
        assert slept == [0.04]
        ServeFaultInjector(
            ServeFaultPlan(slow_ms=40.0, replica=2), replica_index=0
        ).forward_delay()
        assert slept == [0.04]  # mistargeted: no extra sleep


class TestStreamFaults:
    """StreamFaultPlan/Injector units (packet fates, journal verdicts,
    kill arming; the real-fleet runs live in tests/test_stream_chaos.py)."""

    def test_plan_from_env_defaults_inert(self):
        from seist_tpu.utils.faults import StreamFaultInjector, StreamFaultPlan

        plan = StreamFaultPlan.from_env(env={})
        assert not plan.enabled
        inj = StreamFaultInjector(plan)
        assert not inj.enabled
        inj.on_packet(10**9)  # nothing scheduled: must be a no-op
        assert inj.packet_fate("ST01", 1) == "ok"
        assert inj.corrupt_journal("ST01") is False

    def test_plan_parses_all_knobs(self):
        from seist_tpu.utils.faults import StreamFaultPlan

        plan = StreamFaultPlan.from_env(env={
            "SEIST_FAULT_STREAM_DROP_P": "0.1",
            "SEIST_FAULT_STREAM_DUP_P": "0.2",
            "SEIST_FAULT_STREAM_REORDER_P": "0.05",
            "SEIST_FAULT_STREAM_KILL_PACKET": "40",
            "SEIST_FAULT_STREAM_JOURNAL_CORRUPT_P": "0.3",
            "SEIST_FAULT_SERVE_REPLICA": "2",
            "SEIST_FAULT_STAMP": "/tmp/x",
        })
        assert plan.enabled
        assert (plan.drop_p, plan.dup_p, plan.reorder_p) == (0.1, 0.2, 0.05)
        assert plan.kill_packet == 40
        assert plan.journal_corrupt_p == 0.3
        assert plan.replica == 2 and plan.stamp_path == "/tmp/x"

    def test_replica_targeting_gates_enabled(self):
        from seist_tpu.utils.faults import StreamFaultInjector, StreamFaultPlan

        plan = StreamFaultPlan(drop_p=0.5, replica=1)
        assert not StreamFaultInjector(plan, replica_index=0).enabled
        assert StreamFaultInjector(plan, replica_index=1).enabled
        anywhere = StreamFaultPlan(drop_p=0.5, replica=-1)
        assert StreamFaultInjector(anywhere, replica_index=-1).enabled

    def test_packet_fate_deterministic_and_exclusive(self):
        from seist_tpu.utils.faults import StreamFaultInjector, StreamFaultPlan

        plan = StreamFaultPlan(drop_p=0.1, dup_p=0.1, reorder_p=0.1)
        a = StreamFaultInjector(plan, replica_index=-1)
        b = StreamFaultInjector(plan, replica_index=-1)
        fates = {}
        for seq in range(1, 400):
            f = a.packet_fate("CI.ST01", seq)
            assert f == b.packet_fate("CI.ST01", seq), "replay must match"
            fates[f] = fates.get(f, 0) + 1
        # All four fates fire at roughly their configured rates.
        assert set(fates) == {"ok", "drop", "dup", "reorder"}
        assert fates["ok"] > 200
        # No-seq packets are never faulted (no dup/gap semantics).
        assert a.packet_fate("CI.ST01", None) == "ok"

    def test_packet_fate_varies_by_station(self):
        from seist_tpu.utils.faults import StreamFaultInjector, StreamFaultPlan

        inj = StreamFaultInjector(
            StreamFaultPlan(drop_p=0.3), replica_index=-1
        )
        seqs = range(1, 60)
        a = [inj.packet_fate("CI.AAA", s) for s in seqs]
        b = [inj.packet_fate("CI.BBB", s) for s in seqs]
        assert a != b, "fates hash (station, seq), not seq alone"

    def test_corrupt_journal_one_verdict_per_station(self):
        from seist_tpu.utils.faults import StreamFaultInjector, StreamFaultPlan

        inj = StreamFaultInjector(
            StreamFaultPlan(journal_corrupt_p=0.4), replica_index=-1
        )
        sids = [f"CI.S{i:03d}" for i in range(100)]
        verdicts = {sid: inj.corrupt_journal(sid) for sid in sids}
        # Stable across calls: every write for a chosen station tears.
        assert all(inj.corrupt_journal(s) == v for s, v in verdicts.items())
        hit = sum(verdicts.values())
        assert 10 < hit < 70  # ~40% of stations selected

    def test_kill_stamp_fires_once_across_restarts(
        self, tmp_path, monkeypatch
    ):
        from seist_tpu.utils import faults as faults_mod
        from seist_tpu.utils.faults import StreamFaultInjector, StreamFaultPlan

        sent = []
        monkeypatch.setattr(
            faults_mod.os, "kill", lambda pid, sig: sent.append(sig)
        )
        stamp = str(tmp_path / "stamp")
        plan = StreamFaultPlan(kill_packet=3, stamp_path=stamp)
        inj = StreamFaultInjector(plan, replica_index=-1)
        inj.on_packet(2)
        assert not sent
        inj.on_packet(5)  # >= threshold (concurrent arrivals can skip ==)
        assert sent == [signal.SIGKILL]
        # "Relaunched" process: the stamp disarms the kill permanently.
        again = StreamFaultInjector(plan, replica_index=-1)
        again.on_packet(10)
        assert sent == [signal.SIGKILL]

    def test_stream_faults_singleton_parses_env_once(self, monkeypatch):
        from seist_tpu.utils import faults as faults_mod

        monkeypatch.setattr(faults_mod, "_STREAM_FAULTS", None)
        monkeypatch.setenv("SEIST_FAULT_STREAM_DROP_P", "0.25")
        inj = faults_mod.stream_faults()
        assert inj.plan.drop_p == 0.25
        assert faults_mod.stream_faults() is inj
        monkeypatch.setattr(faults_mod, "_STREAM_FAULTS", None)
