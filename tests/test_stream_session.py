"""Streaming <-> offline parity pin (seist_tpu/stream/session.py).

A StreamSession fed one record in ANY packet schedule must emit exactly
the picks offline ``ops/stream.annotate`` produces on the concatenated
record — same P/S indices, same detection intervals (order-insensitive
for det: annotate returns duration-sorted rows, the session emits in
positional order). The property holds across packet sizes (1 sample,
primes, whole windows), both combine modes, both channel0 conventions,
tail/no-tail record lengths, and the short-record pad-and-trim edge.
"""

import numpy as np
import pytest

from seist_tpu.ops.stream import annotate
from seist_tpu.stream.session import SessionConfig, StreamSession


def _fake_apply(x):
    """Deterministic per-window 'model': P prob from the normalized |z|
    envelope. Elementwise per window -> batch-size invariant, so offline
    (batched) and streaming (one window at a time) forwards are bitwise
    identical — isolating the parity pin to the session's own math."""
    import jax.numpy as jnp

    a = jnp.abs(x[..., 0])
    p = a / (a.max(axis=1, keepdims=True) + 1e-9)
    s = jnp.clip(jnp.abs(x[..., 1]) / 3.0, 0.0, 1.0)
    return jnp.stack([1.0 - p, p, s], axis=-1)


def _det_apply(x):
    """'det' convention model: channel 0 IS event probability."""
    import jax.numpy as jnp

    a = jnp.abs(x[..., 0])
    p = a / (a.max(axis=1, keepdims=True) + 1e-9)
    d = jnp.clip(p * 1.5, 0.0, 1.0)
    return jnp.stack([d, p, jnp.zeros_like(p)], axis=-1)


def _record(length, seed=0, events=()):
    rng = np.random.default_rng(seed)
    rec = (rng.standard_normal((length, 3)) * 0.1).astype(np.float32)
    for e in events:
        rec[e : e + 4, 0] += 40.0
        rec[min(e + 30, length - 1), 1] += 6.0
    return rec


def _stream_picks(apply_fn, rec, cfg, packets):
    """Drive a session with the given packet schedule; return the union
    of emitted picks plus emission bookkeeping."""
    import jax.numpy as jnp

    sess = StreamSession(cfg)
    emitted_before_finish = {"ppk": 0, "spk": 0, "det": 0}
    pos = 0
    for size in packets:
        for w in sess.push(rec[pos : pos + size]):
            probs = np.asarray(apply_fn(jnp.asarray(w.data[None])))[0]
            got = sess.integrate(w.offset, probs)
            for k in emitted_before_finish:
                emitted_before_finish[k] += len(got[k])
        pos += size
    assert pos == len(rec)
    for w in sess.finish():
        probs = np.asarray(apply_fn(jnp.asarray(w.data[None])))[0]
        sess.integrate(w.offset, probs)
    sess.finalize()
    return sess, emitted_before_finish


def _schedules(length):
    return {
        "single-sample": [1] * length,
        "prime-7": [7] * (length // 7) + ([length % 7] if length % 7 else []),
        "prime-13": [13] * (length // 13) + ([length % 13] if length % 13 else []),
        "whole-window": [64] * (length // 64) + ([length % 64] if length % 64 else []),
        "one-shot": [length],
    }


def _assert_parity(sess, offline):
    got = sess.picks
    np.testing.assert_array_equal(
        np.sort(np.asarray(got["ppk"], np.int64)), np.sort(np.asarray(offline["ppk"]))
    )
    np.testing.assert_array_equal(
        np.sort(np.asarray(got["spk"], np.int64)), np.sort(np.asarray(offline["spk"]))
    )
    mine = sorted((int(a), int(b)) for a, b in got["det"])
    theirs = sorted((int(a), int(b)) for a, b in np.asarray(offline["det"]))
    assert mine == theirs


CFG = dict(window=64, stride=32, sampling_rate=50, min_peak_dist=0.1)


class TestParity:
    @pytest.mark.parametrize("schedule", ["single-sample", "prime-7", "prime-13",
                                          "whole-window", "one-shot"])
    @pytest.mark.parametrize("length", [64, 200, 256, 331])
    def test_non_mean(self, schedule, length):
        rec = _record(length, seed=length, events=[40, length // 2])
        offline = annotate(
            _fake_apply, rec, window=64, stride=32, batch_size=4,
            sampling_rate=50, min_peak_dist=0.1, channel0="non",
            max_events=min(length // 2, 512),
        )
        cfg = SessionConfig(channel0="non", combine="mean", **CFG)
        sess, _ = _stream_picks(_fake_apply, rec, cfg, _schedules(length)[schedule])
        _assert_parity(sess, offline)

    @pytest.mark.parametrize("schedule", ["single-sample", "prime-13", "one-shot"])
    def test_non_max_combine(self, schedule):
        length = 300
        rec = _record(length, seed=3, events=[50, 180])
        offline = annotate(
            _fake_apply, rec, window=64, stride=32, batch_size=4,
            sampling_rate=50, min_peak_dist=0.1, combine="max", channel0="non",
            max_events=min(length // 2, 512),
        )
        cfg = SessionConfig(channel0="non", combine="max", **CFG)
        sess, _ = _stream_picks(_fake_apply, rec, cfg, _schedules(length)[schedule])
        _assert_parity(sess, offline)

    @pytest.mark.parametrize("combine", ["mean", "max"])
    def test_det_channel0(self, combine):
        length = 220
        rec = _record(length, seed=9, events=[70])
        offline = annotate(
            _det_apply, rec, window=64, stride=32, batch_size=4,
            sampling_rate=50, min_peak_dist=0.1, combine=combine, channel0="det",
            max_events=min(length // 2, 512),
        )
        cfg = SessionConfig(channel0="det", combine=combine, **CFG)
        sess, _ = _stream_picks(_det_apply, rec, cfg, _schedules(length)["prime-7"])
        _assert_parity(sess, offline)

    def test_nms_adversarial_chain(self):
        """A comb of near-threshold peaks mpd apart exercises the greedy
        NMS component closure — the hardest part of incremental parity."""
        length = 400
        rng = np.random.default_rng(11)
        rec = (rng.standard_normal((length, 3)) * 0.05).astype(np.float32)
        for i, p in enumerate(range(30, 370, 9)):
            rec[p, 0] = 20.0 + (7.0 if i % 3 else -3.0) + 0.3 * i
        offline = annotate(
            _fake_apply, rec, window=64, stride=32, batch_size=4,
            sampling_rate=50, min_peak_dist=0.2, channel0="non",  # mpd=10 > 9
            max_events=min(length // 2, 512),
        )
        cfg = SessionConfig(window=64, stride=32, sampling_rate=50,
                            min_peak_dist=0.2, channel0="non")
        for schedule in ("single-sample", "prime-7", "one-shot"):
            sess, _ = _stream_picks(_fake_apply, rec, cfg,
                                    _schedules(length)[schedule])
            _assert_parity(sess, offline)

    def test_short_record_pad_and_trim(self):
        """Records shorter than one window: both sides pad to one window,
        score, and trim — and still agree."""
        for length in (5, 33, 63):
            rec = _record(length, seed=length, events=[min(10, length - 4)])
            offline = annotate(
                _fake_apply, rec, window=64, stride=32, batch_size=1,
                sampling_rate=50, min_peak_dist=0.1, channel0="non",
                max_events=32,  # <= detect_events capacity of the padded window
            )
            assert offline["prob"].shape == (length, 3)
            cfg = SessionConfig(channel0="non", **CFG)
            sess, _ = _stream_picks(_fake_apply, rec, cfg, [length])
            _assert_parity(sess, offline)
            assert all(p < length for p in sess.picks["ppk"])
            assert all(off <= length - 1 for _, off in sess.picks["det"])


class TestLiveness:
    def test_emits_before_finish(self):
        """Picks in the interior must come out mid-stream (alert latency),
        not be hoarded until finish()."""
        length = 1024
        rec = _record(length, seed=2, events=[100, 400, 700])
        cfg = SessionConfig(channel0="non", **CFG)
        sess, before = _stream_picks(
            _fake_apply, rec, cfg, _schedules(length)["whole-window"]
        )
        assert before["ppk"] >= 2  # interior events emitted mid-stream
        assert len(sess.picks["ppk"]) >= 3

    def test_state_is_bounded(self):
        """Ring buffer and curve stay O(window + stride) on a long quiet
        stream — the whole point of a *streaming* session."""
        cfg = SessionConfig(channel0="non", **CFG)
        sess = StreamSession(cfg)
        rng = np.random.default_rng(0)
        for _ in range(200):
            for w in sess.push(rng.standard_normal((97, 3)).astype(np.float32)):
                probs = np.zeros((cfg.window, 3), np.float32)
                probs[:, 0] = 1.0
                sess.integrate(w.offset, probs)
        assert sess.context_samples <= cfg.window + cfg.stride
        assert sess._hits.shape[0] <= 8 * cfg.window  # trimmed, not O(stream)

    def test_push_after_finish_raises(self):
        sess = StreamSession(SessionConfig(channel0="non", **CFG))
        sess.finish()
        with pytest.raises(RuntimeError):
            sess.push(np.zeros((1, 3), np.float32))

    def test_empty_stream(self):
        sess = StreamSession(SessionConfig(channel0="non", **CFG))
        assert sess.finish() == []
        assert sess.finalize() == {"ppk": [], "spk": [], "det": []}


class TestConfig:
    def test_bad_channel0(self):
        with pytest.raises(ValueError):
            SessionConfig(channel0="noise")

    def test_bad_stride(self):
        with pytest.raises(ValueError):
            SessionConfig(window=64, stride=0)

    def test_bad_packet_shape(self):
        sess = StreamSession(SessionConfig(channel0="non", **CFG))
        with pytest.raises(ValueError):
            sess.push(np.zeros((4, 2), np.float32))


class TestSnapshotRestore:
    """Crash-parity pin: a session serialized at ANY packet boundary and
    restored into a fresh process-worth of state must emit the identical
    pick stream — same picks, same emission order, bit-for-bit — as the
    uninterrupted session. This is what makes journal failover invisible
    to the alert plane."""

    @staticmethod
    def _drive(apply_fn, rec, cfg, packets, restore_at=None):
        import jax.numpy as jnp

        from seist_tpu.stream.journal import (
            state_from_bytes,
            state_to_bytes,
        )

        sess = StreamSession(cfg)
        emitted = {"ppk": [], "spk": [], "det": []}
        pos = 0
        for k, size in enumerate(packets):
            if restore_at is not None and k == restore_at:
                # Full codec roundtrip (bytes, not just dicts): exactly
                # what the journal writes and the survivor reads.
                blob = state_to_bytes(sess.snapshot())
                sess = StreamSession.restore(state_from_bytes(blob))
            for w in sess.push(rec[pos : pos + size]):
                probs = np.asarray(apply_fn(jnp.asarray(w.data[None])))[0]
                got = sess.integrate(w.offset, probs)
                for ph in emitted:
                    emitted[ph].extend(got[ph])
            pos += size
        for w in sess.finish():
            probs = np.asarray(apply_fn(jnp.asarray(w.data[None])))[0]
            got = sess.integrate(w.offset, probs)
            for ph in emitted:
                emitted[ph].extend(got[ph])
        fin = sess.finalize()
        for ph in emitted:
            emitted[ph].extend(fin[ph])
        return sess, emitted

    @pytest.mark.parametrize("combine", ["mean", "max"])
    def test_restore_every_packet_boundary(self, combine):
        length = 331
        rec = _record(length, seed=17, events=[60, 170, 290])
        cfg = SessionConfig(channel0="non", combine=combine, **CFG)
        packets = _schedules(length)["prime-13"]
        _, ref_emitted = self._drive(_fake_apply, rec, cfg, packets)
        for k in range(1, len(packets)):
            # The emission stream is the pin. Cumulative ``picks``
            # history is deliberately NOT journaled (it is O(stream);
            # those picks were already delivered downstream), so only
            # what each session EMITS is compared — and it must match
            # element-for-element across the crash point.
            _, emitted = self._drive(
                _fake_apply, rec, cfg, packets, restore_at=k
            )
            assert emitted == ref_emitted, f"boundary {k} diverged"

    def test_restore_det_channel0(self):
        length = 220
        rec = _record(length, seed=9, events=[70, 150])
        cfg = SessionConfig(channel0="det", combine="mean", **CFG)
        packets = _schedules(length)["prime-7"]
        _, ref = self._drive(_det_apply, rec, cfg, packets)
        for k in (1, len(packets) // 2, len(packets) - 1):
            _, emitted = self._drive(
                _det_apply, rec, cfg, packets, restore_at=k
            )
            assert emitted == ref

    def test_snapshot_with_pending_raises(self):
        sess = StreamSession(SessionConfig(channel0="non", **CFG))
        wins = sess.push(_record(64, seed=1))
        assert wins  # one due window, not yet integrated
        with pytest.raises(RuntimeError):
            sess.snapshot()

    def test_restore_rejects_version_skew(self):
        sess = StreamSession(SessionConfig(channel0="non", **CFG))
        state = sess.snapshot()
        state["meta"]["version"] = 999
        with pytest.raises(ValueError):
            StreamSession.restore(state)

    def test_restore_roundtrips_config(self):
        cfg = SessionConfig(channel0="non", combine="max", **CFG)
        sess = StreamSession(cfg)
        got = StreamSession.restore(sess.snapshot())
        assert got.config == cfg


class TestAbandon:
    def test_abandon_unwedges_frontier(self):
        """A window whose forward pass was lost (transport refusal,
        crash) is zero-filled so the finality frontier advances — the
        stream keeps emitting instead of wedging forever."""
        cfg = SessionConfig(channel0="non", **CFG)
        rec = _record(640, seed=5, events=[80, 420])
        import jax.numpy as jnp

        sess = StreamSession(cfg)
        dropped = False
        n_emitted = 0
        pos = 0
        for _ in range(10):
            for w in sess.push(rec[pos : pos + 64]):
                if not dropped and w.offset >= 128:
                    dropped = True
                    sess.abandon(w.offset)
                    continue
                probs = np.asarray(_fake_apply(jnp.asarray(w.data[None])))[0]
                got = sess.integrate(w.offset, probs)
                n_emitted += sum(len(v) for v in got.values())
            pos += 64
        assert dropped
        # The frontier moved past the hole: the event at 420 (after the
        # abandoned window) still produced picks mid-stream.
        assert any(p > 300 for p in sess.picks["ppk"])

    def test_abandoned_hole_emits_no_phantom_detections(self):
        """A mean-combined 'non' coverage hole renders as pure noise
        (prob 1.0 on channel 0), not as an all-zero row that the
        detector would read as a strength-1 event. Non-overlapping
        stride makes the abandoned window a true hits==0 hole."""
        cfg = SessionConfig(window=64, stride=64, sampling_rate=50,
                            min_peak_dist=0.1, channel0="non",
                            combine="mean")
        sess = StreamSession(cfg)
        quiet = (np.random.default_rng(3).standard_normal((192, 3))
                 * 0.05).astype(np.float32)

        def all_noise(n):
            probs = np.zeros((n, 3), np.float32)
            probs[:, 0] = 1.0  # pure noise verdict
            return probs

        for w in sess.push(quiet):
            if w.offset == 64:
                sess.abandon(w.offset)
                continue
            sess.integrate(w.offset, all_noise(w.data.shape[0]))
        for w in sess.finish():
            sess.integrate(w.offset, all_noise(w.data.shape[0]))
        sess.finalize()
        assert sess.picks["det"] == []
        assert sess.picks["ppk"] == []
