"""Direct shard->device ingest (seist_tpu/data/ingest.py) + the packed
data plane's determinism contracts:

* PackedRawStore row parity with RawStore.build (same phases/labels/
  waveforms, no Event decode);
* O(1) mid-epoch resume: (seed, epoch, host, start_batch) pins the
  remaining batch stream byte-identical, 1-host and 2-host (union
  coverage + per-position disjointness);
* io_guard parity on the fast path: truncation / NaN poison / injected
  SEIST_FAULT_IO_* faults quarantine + deterministically replace exactly
  like the HDF5 readers;
* temperature-weighted mixture sampling determinism.
"""

import os

import numpy as np
import pytest

import seist_tpu
from seist_tpu import taskspec
from seist_tpu.data import io_guard, pipeline
from seist_tpu.data.ingest import PackedRawStore, packed_dataset_of
from seist_tpu.data.packed import PackSource, pack_sources, shard_path
from seist_tpu.obs.bus import BUS

seist_tpu.load_all()

N_EVENTS = 28
L_TRACE = 640
WINDOW = 512


def _pack_synthetic(root, n_events=N_EVENTS, trace=L_TRACE, sps=5):
    return pack_sources(
        [
            PackSource(
                name="synthetic",
                dataset_kwargs={
                    "num_events": n_events,
                    "trace_samples": trace,
                    "cache": False,
                },
            )
        ],
        str(root),
        samples_per_shard=sps,
    )["out"]


@pytest.fixture(scope="module")
def packed_dir(tmp_path_factory):
    return _pack_synthetic(tmp_path_factory.mktemp("ingest_pack"))


def _sds(packed_dir, *, model="seist_s_dpk", augmentation=True, seed=3, **kw):
    spec = taskspec.get_task_spec(model)
    return pipeline.from_task_spec(
        spec,
        "packed",
        "train",
        seed=seed,
        in_samples=WINDOW,
        augmentation=augmentation,
        data_dir=packed_dir,
        **kw,
    )


# ----------------------------------------------------------------- row parity
def test_packed_raw_store_matches_raw_store(packed_dir):
    """The metadata-only build + memmap batch fill must reproduce
    RawStore.build's rows bit-for-bit — phases, counts, and waveforms."""
    sds = _sds(packed_dir)
    ref = pipeline.RawStore.build(sds)
    fast = PackedRawStore.build(sds, batch_size=8)
    assert packed_dataset_of(sds) is not None
    assert fast.n_raw == ref.n_raw
    assert fast.raw_len == ref.raw_len == L_TRACE
    assert fast.phase_slots == ref.phase_slots
    assert fast.augmentation == ref.augmentation
    for k in ("ppks", "np_p", "spks", "np_s"):
        np.testing.assert_array_equal(fast.arrays[k], ref.arrays[k])
    idx = np.array([0, 5, 3, fast.n_raw - 1])
    rows_ref = ref.row_batch(idx)
    rows_fast = fast.row_batch(idx)
    for k in rows_ref:
        np.testing.assert_array_equal(rows_fast[k], rows_ref[k])


def test_packed_raw_store_value_onehot_labels(packed_dir):
    """VALUE (emg) labels come from the index columns, matching the
    Event-decode path."""
    sds = _sds(packed_dir, model="magnet")
    ref = pipeline.RawStore.build(sds)
    fast = PackedRawStore.build(sds)
    assert "values" in fast.arrays
    for name in ref.arrays["values"]:
        np.testing.assert_array_equal(
            fast.arrays["values"][name], ref.arrays["values"][name]
        )


def test_ingest_counters_account_batches(packed_dir):
    sds = _sds(packed_dir)
    fast = PackedRawStore.build(sds, batch_size=4)
    before = BUS.counter("data_ingest_samples").value
    fast.row_batch(np.arange(4))
    assert BUS.counter("data_ingest_samples").value == before + 4
    assert BUS.counter("data_ingest_bytes").value > 0


# ------------------------------------------------------- mid-epoch resume
def _collect(store, *, epoch, start_batch, num_shards=1, shard_index=0,
             batch_size=4, seed=3):
    out = []
    for rows, idx, aug in pipeline.iter_raw_batches(
        store,
        epoch,
        seed=seed,
        shuffle=True,
        batch_size=batch_size,
        num_shards=num_shards,
        shard_index=shard_index,
        start_batch=start_batch,
    ):
        out.append((
            {k: np.array(v) for k, v in rows.items() if k != "values"},
            np.array(idx),
            np.array(aug),
        ))
    return out


def _assert_streams_equal(a, b):
    assert len(a) == len(b)
    for (ra, ia, ga), (rb, ib, gb) in zip(a, b):
        np.testing.assert_array_equal(ia, ib)
        np.testing.assert_array_equal(ga, gb)
        assert ra.keys() == rb.keys()
        for k in ra:
            np.testing.assert_array_equal(ra[k], rb[k])


def test_mid_epoch_resume_byte_identical_one_host(packed_dir):
    """Kill at batch k, resume at start_batch=k: the remaining stream is
    byte-identical to the uninterrupted run (ISSUE acceptance)."""
    sds = _sds(packed_dir)
    store = PackedRawStore.build(sds, batch_size=4)
    full = _collect(store, epoch=1, start_batch=0)
    assert len(full) >= 4
    k = len(full) // 2
    resumed = _collect(store, epoch=1, start_batch=k)
    _assert_streams_equal(full[k:], resumed)


def test_mid_epoch_resume_two_host_union_and_disjoint(packed_dir):
    """Simulated 2-host split: per-host streams resume byte-identically,
    every global batch position is disjoint across hosts, and the union
    covers the head-wrapped global order."""
    sds = _sds(packed_dir)
    store = PackedRawStore.build(sds, batch_size=4)
    hosts = [
        _collect(store, epoch=2, start_batch=0, num_shards=2, shard_index=h)
        for h in (0, 1)
    ]
    # Resume each host at batch k: identical remainder.
    k = len(hosts[0]) // 2
    for h in (0, 1):
        resumed = _collect(
            store, epoch=2, start_batch=k, num_shards=2, shard_index=h
        )
        _assert_streams_equal(hosts[h][k:], resumed)
    # Disjointness per position + union coverage of the global order.
    n_logical = len(store)
    global_order = pipeline.epoch_indices(
        n_logical, seed=3, epoch=2, shuffle=True
    )
    target = -(-n_logical // 2) * 2
    wrapped = np.concatenate(
        [global_order, global_order[: target - n_logical]]
    )
    seen = []
    for (_, ia, _), (_, ib, _) in zip(*hosts):
        # n_logical divides evenly here: no head-wrap duplicates, so the
        # two hosts' rows must be strictly disjoint at every position.
        assert not (set(ia.tolist()) & set(ib.tolist()))
        seen.extend(ia.tolist())
        seen.extend(ib.tolist())
    n_batches = len(hosts[0])
    interleaved = np.stack(
        [wrapped[0::2][: n_batches * 4], wrapped[1::2][: n_batches * 4]]
    )
    np.testing.assert_array_equal(
        np.sort(np.asarray(seen)),
        np.sort(interleaved.ravel()),
    )


def test_host_loader_resume_byte_identical(packed_dir):
    """The host Loader path honors the same contract via
    set_start_batch (checkpoint restore's mid-epoch hook)."""
    sds = _sds(packed_dir, augmentation=False)
    loader = pipeline.Loader(
        sds, batch_size=4, shuffle=True, drop_last=True, num_workers=2,
        seed=3,
    )
    try:
        loader.set_epoch(1)
        full = [b.inputs for b in loader]
        k = len(full) // 2
        loader.set_epoch(1)
        loader.set_start_batch(k)
        resumed = [b.inputs for b in loader]
        assert len(resumed) == len(full) - k
        for a, b in zip(full[k:], resumed):
            np.testing.assert_array_equal(a, b)
    finally:
        loader.close()


# ------------------------------------------------------------ fault parity
def test_truncated_shard_quarantines_and_falls_back(tmp_path):
    """A truncated shard_XXXXX.bin surfaces as a short read: the sample
    is quarantined and deterministically replaced — batch shapes hold,
    the replacement is the first cleanly-reading candidate of the
    (seed, epoch, idx) fallback sequence (io_guard parity)."""
    out = _pack_synthetic(tmp_path / "pack", sps=5)
    sds = _sds(out, augmentation=False, seed=0, shuffle=False,
               data_split=False)
    store = PackedRawStore.build(sds, batch_size=4)
    # Truncate the LAST shard mid-sample: its final sample dies.
    last_shard = int(store._shards.max())
    p = shard_path(out, last_shard)
    size = os.path.getsize(p)
    with open(p, "r+b") as f:
        f.truncate(size - store.row_nbytes // 2)
    victims = np.flatnonzero(
        (store._shards == last_shard)
        & (store._offsets + store.row_nbytes > size - store.row_nbytes // 2)
    )
    assert victims.size == 1
    bad = int(victims[0])

    io_guard.COUNTERS.reset()
    raw_idx = np.array([bad, 0, 1, 2])
    rows = store.row_batch_at(raw_idx, epoch=0, idx=raw_idx)
    snap = io_guard.COUNTERS.snapshot()
    assert snap["quarantined"] == 1
    assert snap["fallback_reads"] == 1
    assert bad in sds.quarantine
    # The replacement row is the deterministic candidate's content.
    cand = next(
        c
        for c in sds.quarantine.candidates(bad, seed=0, epoch=0, idx=bad)
        if c != bad
    )
    expect = store.row_batch_at(np.array([cand]), epoch=0,
                                idx=np.array([cand]))
    np.testing.assert_array_equal(rows["data"][0], expect["data"][0])
    np.testing.assert_array_equal(rows["ppks"][0], expect["ppks"][0])
    assert np.isfinite(rows["data"]).all()


def test_nan_poisoned_waveform_quarantined(tmp_path):
    out = _pack_synthetic(tmp_path / "pack", sps=50)  # one shard
    sds = _sds(out, augmentation=False, seed=0)
    store = PackedRawStore.build(sds, batch_size=4)
    poison = 3
    with open(shard_path(out, 0), "r+b") as f:
        f.seek(int(store._offsets[poison]))
        f.write(np.full(8, np.nan, np.float32).tobytes())
    io_guard.COUNTERS.reset()
    rows = store.row_batch_at(
        np.array([poison, 0]), epoch=0, idx=np.array([poison, 0])
    )
    assert io_guard.COUNTERS.snapshot()["quarantined"] == 1
    assert poison in sds.quarantine
    assert np.isfinite(rows["data"]).all()


def test_injected_flaky_reads_are_invisible(tmp_path, monkeypatch):
    """SEIST_FAULT_IO_FLAKY_P transient faults: absorbed by retries, the
    byte stream is identical to a clean run — the same contract the
    HDF5 readers pin in the chaos lane."""
    out = _pack_synthetic(tmp_path / "pack")
    clean_sds = _sds(out, augmentation=False, seed=0)
    clean = PackedRawStore.build(clean_sds, batch_size=4).row_batch(
        np.arange(8)
    )

    monkeypatch.setenv("SEIST_FAULT_IO_FLAKY_P", "0.5")
    monkeypatch.setenv("SEIST_IO_BACKOFF_MS", "1")
    io_guard.COUNTERS.reset()
    flaky_sds = _sds(out, augmentation=False, seed=0)
    assert flaky_sds.io_faults.enabled
    flaky = PackedRawStore.build(flaky_sds, batch_size=4).row_batch_at(
        np.arange(8), epoch=0, idx=np.arange(8)
    )
    snap = io_guard.COUNTERS.snapshot()
    assert snap["retries"] > 0, "injected flakiness never fired"
    assert snap["quarantined"] == 0
    for k in ("data", "ppks", "np_p", "spks", "np_s"):
        np.testing.assert_array_equal(flaky[k], clean[k])


def test_injected_corrupt_sample_env_knob(tmp_path, monkeypatch):
    monkeypatch.setenv("SEIST_FAULT_IO_CORRUPT", "2")
    out = _pack_synthetic(tmp_path / "pack")
    sds = _sds(out, augmentation=False, seed=0)
    store = PackedRawStore.build(sds, batch_size=4)
    io_guard.COUNTERS.reset()
    rows = store.row_batch_at(np.array([2, 0]), epoch=0, idx=np.array([2, 0]))
    assert io_guard.COUNTERS.snapshot()["quarantined"] == 1
    assert 2 in sds.quarantine
    assert np.isfinite(rows["data"]).all()


# -------------------------------------------------- archive-tail edge cases
def test_partial_final_batch_fill(packed_dir, monkeypatch):
    """A final batch smaller than the staging ring's capacity (every
    archive tail in the repick loop): the fill slices the slab, rows
    match full-batch reads, and a following full batch is unaffected by
    the short fill (ring rotation stays sound)."""
    monkeypatch.setenv("SEIST_INGEST_REUSE_STAGING", "1")
    sds = _sds(packed_dir, augmentation=False, seed=0, shuffle=False,
               data_split=False)
    store = PackedRawStore.build(sds, batch_size=8)
    assert store._reuse  # the ring path is what this test exercises
    n = store.n_raw
    tail = np.arange(n - 3, n)
    short = store.row_batch_at(tail, epoch=0, idx=tail)
    assert short["data"].shape == (3, store.n_ch, store.raw_len)
    one_by_one = [
        store.row_batch_at(np.array([r]), epoch=0, idx=np.array([r]))
        for r in tail
    ]
    for j in range(3):
        np.testing.assert_array_equal(
            short["data"][j], one_by_one[j]["data"][0]
        )
    # Full batch straight after the short one: correct and full-shape.
    full_idx = np.arange(8)
    full = store.row_batch_at(full_idx, epoch=0, idx=full_idx)
    assert full["data"].shape == (8, store.n_ch, store.raw_len)
    ref = PackedRawStore.build(
        _sds(packed_dir, augmentation=False, seed=0, shuffle=False,
             data_split=False),
        batch_size=8, reuse_staging=False,
    ).row_batch_at(full_idx, epoch=0, idx=full_idx)
    np.testing.assert_array_equal(full["data"], ref["data"])


def test_empty_selection_and_untouched_shards(packed_dir):
    """An empty index selection (a work unit with zero assigned rows)
    fills a (0, C, L) batch without error, and shards no row ever
    touched never open a memmap."""
    sds = _sds(packed_dir, augmentation=False, seed=0, shuffle=False,
               data_split=False)
    store = PackedRawStore.build(sds, batch_size=4)
    empty = np.empty(0, np.int64)
    rows = store.row_batch_at(empty, epoch=0, idx=empty)
    assert rows["data"].shape == (0, store.n_ch, store.raw_len)
    assert rows["ppks"].shape[0] == 0
    assert store._mmaps == {}  # nothing read -> nothing mapped
    first_shard_rows = np.flatnonzero(store._shards == 0)[:2]
    store.row_batch_at(first_shard_rows, epoch=0, idx=first_shard_rows)
    assert set(store._mmaps) == {0}  # only the touched shard mapped


def test_empty_split_refuses_build(tmp_path):
    """A split that maps to ZERO rows (an empty archive selection from
    the repick worker's perspective) refuses LOUDLY at pipeline
    construction (the quarantine registry needs a positive population)
    — nothing downstream can silently iterate over nothing. The store's
    own 'empty packed split' refusal is the second line of defense for
    duck-typed callers."""
    out = _pack_synthetic(tmp_path / "pack", n_events=6, sps=4)
    spec = taskspec.get_task_spec("seist_s_dpk")
    # int(0.1 * 6) == 0 -> the val split holds zero rows.
    with pytest.raises(ValueError, match="positive"):
        pipeline.from_task_spec(
            spec, "packed", "val", seed=0, in_samples=WINDOW,
            data_dir=out, train_size=0.8, val_size=0.1,
        )


# --------------------------------------------------- bf16 shard variant
def test_bf16_pack_read_parity(tmp_path):
    """--dtype bf16 shard variant: half the on-disk bytes, readers
    (PackedDataset and PackedRawStore) upcast on fill to exactly
    float32(bfloat16(x)) of the f32 pack, labels bit-identical."""
    import ml_dtypes

    kwargs = {"num_events": 10, "trace_samples": L_TRACE, "cache": False}
    out16 = pack_sources(
        [PackSource(name="synthetic", dataset_kwargs=dict(kwargs))],
        str(tmp_path / "bf16"), samples_per_shard=4, dtype="bf16",
    )["out"]
    out32 = pack_sources(
        [PackSource(name="synthetic", dataset_kwargs=dict(kwargs))],
        str(tmp_path / "f32"), samples_per_shard=4,
    )["out"]
    assert os.path.getsize(shard_path(out16, 0)) * 2 == os.path.getsize(
        shard_path(out32, 0)
    )
    sds16 = _sds(out16, augmentation=False, seed=0, shuffle=False,
                 data_split=False)
    sds32 = _sds(out32, augmentation=False, seed=0, shuffle=False,
                 data_split=False)
    st16 = PackedRawStore.build(sds16, batch_size=4)
    st32 = PackedRawStore.build(sds32, batch_size=4)
    assert st16.row_nbytes * 2 == st32.row_nbytes
    idx = np.arange(4)
    b16 = st16.row_batch_at(idx, epoch=0, idx=idx)
    b32 = st32.row_batch_at(idx, epoch=0, idx=idx)
    assert b16["data"].dtype == np.float32
    expect = b32["data"].astype(ml_dtypes.bfloat16).astype(np.float32)
    np.testing.assert_array_equal(b16["data"], expect)
    for k in ("ppks", "np_p", "spks", "np_s"):
        np.testing.assert_array_equal(b16[k], b32[k])
    # Event-reader lane upcasts identically (shared contract).
    e16, _ = sds16._dataset[1]
    e32, _ = sds32._dataset[1]
    np.testing.assert_array_equal(
        e16["data"], e32["data"].astype(ml_dtypes.bfloat16).astype(np.float32)
    )


def test_bf16_resume_dtype_switch_repacks(tmp_path):
    """Resuming a pack with a different --dtype must repack every shard
    (storage dtype is part of the sidecar plan identity), never mix
    itemsizes inside one directory."""
    kwargs = {"num_events": 8, "trace_samples": 128, "cache": False}
    out = str(tmp_path / "pack")
    pack_sources(
        [PackSource(name="synthetic", dataset_kwargs=dict(kwargs))],
        out, samples_per_shard=4, dtype="bf16",
    )
    stats = pack_sources(
        [PackSource(name="synthetic", dataset_kwargs=dict(kwargs))],
        out, samples_per_shard=4, dtype="float32",
    )
    assert stats["shards_skipped"] == 0  # dtype switch -> full repack
    # Same dtype resumes cleanly.
    stats = pack_sources(
        [PackSource(name="synthetic", dataset_kwargs=dict(kwargs))],
        out, samples_per_shard=4, dtype="float32",
    )
    assert stats["shards_skipped"] == stats["shards"]


def test_non_packed_dataset_refused():
    spec = taskspec.get_task_spec("seist_s_dpk")
    sds = pipeline.from_task_spec(
        spec, "synthetic", "train", seed=0, in_samples=256,
        dataset_kwargs={"num_events": 8, "trace_samples": 256},
    )
    with pytest.raises(ValueError, match="packed"):
        PackedRawStore.build(sds)


# ------------------------------------------------------------- mixture order
def _mixture_ids():
    return np.concatenate([np.zeros(300, int), np.ones(100, int)])


def test_mixture_epoch_indices_deterministic_and_valid():
    sids = _mixture_ids()
    a = pipeline.mixture_epoch_indices(
        sids, seed=7, epoch=2, temperature=1.0
    )
    b = pipeline.mixture_epoch_indices(
        sids, seed=7, epoch=2, temperature=1.0
    )
    np.testing.assert_array_equal(a, b)
    c = pipeline.mixture_epoch_indices(
        sids, seed=7, epoch=3, temperature=1.0
    )
    assert not np.array_equal(a, c)
    assert a.shape == (400,)  # epoch length preserved -> resume contract
    # Every slot's sample really belongs to the drawn source.
    assert set(a.tolist()) <= set(range(400))


def test_mixture_temperature_shifts_source_shares():
    sids = _mixture_ids()
    t1 = pipeline.mixture_epoch_indices(sids, seed=1, epoch=0, temperature=1.0)
    t8 = pipeline.mixture_epoch_indices(sids, seed=1, epoch=0, temperature=8.0)
    share_small_t1 = np.mean(sids[t1] == 1)
    share_small_t8 = np.mean(sids[t8] == 1)
    # T=1 ~ proportional (25%); T=8 pulls toward uniform (50%).
    assert abs(share_small_t1 - 0.25) < 0.08
    assert share_small_t8 > share_small_t1 + 0.1


def test_mixture_sharding_matches_contract():
    sids = _mixture_ids()
    full = pipeline.mixture_epoch_indices(
        sids, seed=5, epoch=1, temperature=2.0
    )
    shards = [
        pipeline.mixture_epoch_indices(
            sids, seed=5, epoch=1, temperature=2.0,
            num_shards=2, shard_index=h,
        )
        for h in (0, 1)
    ]
    np.testing.assert_array_equal(shards[0], full[0::2])
    np.testing.assert_array_equal(shards[1], full[1::2])


def test_mixture_loader_end_to_end(tmp_path):
    """Loader over a 2-source mixture pack: deterministic epochs, small
    source oversampled at high temperature, resume byte-identical."""
    out = str(tmp_path / "mix")
    srcs = [
        PackSource(
            name="synthetic",
            dataset_kwargs={"num_events": n, "trace_samples": 256,
                            "cache": False},
        )
        for n in (24, 8)
    ]
    pack_sources(srcs, out, samples_per_shard=6)
    sds = _sds(out, augmentation=False, seed=1, shuffle=False,
               data_split=False)
    assert sds.source_ids() is not None
    loader = pipeline.Loader(
        sds, batch_size=4, shuffle=True, drop_last=True, num_workers=2,
        seed=1, mixture_temperature=4.0,
    )
    try:
        loader.set_epoch(0)
        a = [np.array(b.inputs) for b in loader]
        loader.set_epoch(0)
        b = [np.array(x.inputs) for x in loader]
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
    finally:
        loader.close()
    # Temperature on a source-less dataset is a config error, not a
    # silent no-op.
    plain = pipeline.from_task_spec(
        taskspec.get_task_spec("seist_s_dpk"), "synthetic", "train",
        seed=0, in_samples=256,
        dataset_kwargs={"num_events": 8, "trace_samples": 256},
    )
    with pytest.raises(ValueError, match="mixture"):
        pipeline.Loader(plain, batch_size=4, mixture_temperature=0.5)
