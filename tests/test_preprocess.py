"""Preprocessor behavior-parity tests against hand-computed fixtures.

Encodes the quirks checklist from SURVEY.md Appendix A
(ref: training/preprocess.py:16-821).
"""

import numpy as np
import pytest

from seist_tpu.data.preprocess import DataPreprocessor, pad_array, pad_phases

FS = 50
L_IN = 1024


def make_pp(**kw):
    defaults = dict(
        data_channels=["z", "n", "e"],
        sampling_rate=FS,
        in_samples=L_IN,
        min_snr=float("-inf"),
        p_position_ratio=-1.0,
        coda_ratio=1.4,
        norm_mode="std",
        soft_label_shape="gaussian",
        soft_label_width=20,
        max_event_num=1,
    )
    defaults.update(kw)
    return DataPreprocessor(**defaults)


def make_event(ppks=(100,), spks=(200,), length=L_IN, nch=3, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "data": rng.normal(size=(nch, length)).astype(np.float64),
        "ppks": list(ppks),
        "spks": list(spks),
        "emg": 3.5,
        "smg": 3.1,
        "pmp": [1],
        "clr": [0],
        "baz": 123.0,
        "dis": 42.0,
        "snr": np.array([10.0, 12.0, 8.0]),
    }


# ----------------------------------------------------------------- pad_phases
def test_pad_phases_matched_pair_unchanged():
    assert pad_phases([100], [200], 10, L_IN) == ([100], [200])


def test_pad_phases_trailing_p_gets_virtual_s():
    ppks, spks = pad_phases([100, 300], [200], 10, L_IN)
    assert ppks == [100, 300]
    assert spks == [200, L_IN + 10]


def test_pad_phases_leading_s_gets_virtual_p():
    ppks, spks = pad_phases([], [200], 10, L_IN)
    assert ppks == [-10]
    assert spks == [200]


def test_pad_phases_abs_padding_idx():
    ppks, spks = pad_phases([], [200], -10, L_IN)
    assert ppks == [-10]


def test_pad_array():
    out = pad_array([1, 2], 4, -7)
    np.testing.assert_array_equal(out, [1, 2, -7, -7])
    with pytest.raises(ValueError):
        pad_array([1, 2, 3], 2, 0)


# ------------------------------------------------------------------ is_noise
def test_is_noise_rules():
    pp = make_pp()
    ev = make_event()
    assert not pp._is_noise(ev["data"], [100], [200], ev["snr"])
    assert pp._is_noise(ev["data"], [], [], ev["snr"])  # no phases
    assert pp._is_noise(ev["data"], [100], [], ev["snr"])  # mismatch
    assert pp._is_noise(ev["data"], [-1], [200], ev["snr"])  # negative
    assert pp._is_noise(ev["data"], [100], [L_IN + 5], ev["snr"])  # out of range
    assert pp._is_noise(ev["data"], [200], [100], ev["snr"])  # P after S


def test_min_snr_default_never_marks_noise():
    # min_snr default -inf => all(snr < min_snr) is never True
    # (ref: main.py:81-82, preprocess.py:160-167).
    pp = make_pp()
    ev = make_event()
    assert not pp._is_noise(ev["data"], [100], [200], np.array([0.001, 0.001, 0.001]))


def test_min_snr_set_marks_noise():
    pp = make_pp(min_snr=3.0)
    ev = make_event()
    assert pp._is_noise(ev["data"], [100], [200], np.array([1.0, 2.0, 2.5]))
    assert not pp._is_noise(ev["data"], [100], [200], np.array([1.0, 5.0, 2.5]))


# ----------------------------------------------------------------- normalize
def test_normalize_std():
    pp = make_pp()
    data = np.random.default_rng(0).normal(3.0, 5.0, size=(3, 256))
    out = pp._normalize(data.copy(), "std")
    # fp32 tolerance: the native wavekit path computes in float32.
    np.testing.assert_allclose(out.mean(axis=1), 0, atol=1e-6)
    np.testing.assert_allclose(out.std(axis=1), 1, atol=1e-6)


def test_normalize_max_zero_guard():
    pp = make_pp()
    data = np.zeros((3, 64))
    out = pp._normalize(data.copy(), "max")
    assert np.isfinite(out).all()


def test_normalize_empty_mode_only_demeans():
    pp = make_pp()
    data = np.random.default_rng(0).normal(3.0, 5.0, size=(3, 64))
    out = pp._normalize(data.copy(), "")
    np.testing.assert_allclose(out.mean(axis=1), 0, atol=1e-6)
    assert out.std() > 1.5  # not scaled


# ---------------------------------------------------------------- cut_window
def test_cut_window_crop_keeps_phases(rng):
    pp = make_pp()
    data = np.zeros((3, 4096))
    ppks, spks = [1000], [1500]
    out, p2, s2 = pp._cut_window(data, ppks, spks, L_IN, rng)
    assert out.shape == (3, L_IN)
    # crop start is random in [0, min(ppks + [L-W]) - gap) => P stays in-window
    assert len(p2) == 1 and 0 <= p2[0] < L_IN


def test_cut_window_pads_short_input(rng):
    pp = make_pp()
    data = np.ones((3, 500))
    out, _, _ = pp._cut_window(data, [100], [200], L_IN, rng)
    assert out.shape == (3, L_IN)
    np.testing.assert_array_equal(out[:, 500:], 0)


def test_cut_window_p_position_ratio_pins_p(rng):
    pp = make_pp(p_position_ratio=0.25)
    data = np.random.default_rng(0).normal(size=(3, 4096))
    ppk = 2000
    out, p2, s2 = pp._cut_window(data, [ppk], [2100], L_IN, rng)
    assert out.shape == (3, L_IN)
    assert p2 == [int(L_IN * 0.25)]
    assert s2 == [int(L_IN * 0.25) + 100]


def test_p_position_ratio_disables_augments():
    pp = make_pp(
        p_position_ratio=0.5,
        add_event_rate=0.5,
        shift_event_rate=0.5,
        generate_noise_rate=0.5,
    )
    assert pp.add_event_rate == 0.0
    assert pp.shift_event_rate == 0.0
    assert pp.generate_noise_rate == 0.0


# ---------------------------------------------------------------- soft labels
def test_gaussian_soft_label_sigma_is_fixed_10():
    # The gaussian sigma ignores label_width (ref quirk: preprocess.py:576-578).
    pp = make_pp(soft_label_width=40)
    ev = make_event(ppks=[500], spks=[700])
    label = pp._generate_soft_label("ppk", ev)
    assert label.shape == (L_IN,)
    assert label[500] == pytest.approx(1.0)
    assert label[490] == pytest.approx(np.exp(-(10**2) / (2 * 10**2)), rel=1e-5)
    assert label[479] == 0.0  # outside window extent (width 40 => left 20)
    assert label[521] == 0.0


def test_soft_label_left_edge():
    pp = make_pp(soft_label_width=20)
    ev = make_event(ppks=[5], spks=[700])
    label = pp._generate_soft_label("ppk", ev)
    # idx-left < 0 branch: window right-aligned at idx+right+1
    assert label[5] == pytest.approx(1.0)
    assert label[0] == pytest.approx(np.exp(-(5**2) / 200), rel=1e-5)


def test_soft_label_right_edge():
    pp = make_pp(soft_label_width=20)
    ev = make_event(ppks=[L_IN - 5], spks=[L_IN - 2])
    label = pp._generate_soft_label("ppk", ev)
    assert label[L_IN - 5] == pytest.approx(1.0)
    assert label[L_IN - 1] == pytest.approx(np.exp(-(4**2) / 200), rel=1e-5)


def test_triangle_box_sigmoid_shapes():
    for shape in ["triangle", "box", "sigmoid"]:
        pp = make_pp(soft_label_shape=shape)
        ev = make_event(ppks=[500], spks=[700])
        label = pp._generate_soft_label("ppk", ev)
        assert label.max() == pytest.approx(1.0)
        assert label.min() >= 0.0


def test_unknown_label_shape_raises():
    pp = make_pp(soft_label_shape="bogus")
    ev = make_event()
    with pytest.raises(NotImplementedError):
        pp._generate_soft_label("ppk", ev)


def test_non_label_is_one_minus_p_minus_s_clipped():
    pp = make_pp()
    ev = make_event(ppks=[500], spks=[520])
    non = pp._generate_soft_label("non", ev)
    p = pp._generate_soft_label("ppk", ev)
    s = pp._generate_soft_label("spk", ev)
    expected = np.clip(1.0 - p - s, 0, None)
    np.testing.assert_allclose(non, expected, atol=1e-6)


def test_det_label_box_with_coda():
    pp = make_pp()
    ev = make_event(ppks=[100], spks=[200])
    det = pp._generate_soft_label("det", ev)
    # box spans [ppk, spk + 1.4*(spk-ppk)) = [100, 340)
    assert det[100] == 1.0
    assert det[339] == 1.0
    assert det[120] == 1.0
    assert det[341] < 1.0  # soft tail
    assert det[50] == 0.0
    assert det.max() == 1.0


def test_det_label_unmatched_s_uses_padded_virtual_p():
    # 'det' uses phase lists padded with soft_label_width (preprocess.py:621-626)
    pp = make_pp(soft_label_width=20)
    ev = make_event(ppks=[], spks=[200])
    det = pp._generate_soft_label("det", ev)
    # virtual P at -20 => box [0(clipped), 200+1.4*220=508)
    assert det[0] == 1.0
    assert det[507] == 1.0


def test_ppk_plus_label_steps_to_one():
    pp = make_pp()
    ev = make_event(ppks=[500], spks=[700])
    lab = pp._generate_soft_label("ppk+", ev)
    assert lab[499] < 1.0
    np.testing.assert_allclose(lab[500:], 1.0, atol=1e-6)


def test_waveform_and_diff_items():
    pp = make_pp()
    ev = make_event()
    z = pp._generate_soft_label("z", ev)
    np.testing.assert_allclose(z, ev["data"][0].astype(np.float32))
    dz = pp._generate_soft_label("dz", ev)
    assert dz[0] == 0.0
    np.testing.assert_allclose(
        dz[1:], np.diff(ev["data"][0]).astype(np.float32), atol=1e-6
    )


# ---------------------------------------------------------------- io assembly
def test_grouped_io_item_is_channels_last():
    pp = make_pp()
    ev = make_event()
    item = pp.get_io_item(("z", "n", "e"), ev)
    assert item.shape == (L_IN, 3)
    np.testing.assert_allclose(item[:, 1], ev["data"][1].astype(np.float32))


def test_onehot_item():
    pp = make_pp()
    ev = make_event()
    pmp = pp.get_io_item("pmp", ev)
    np.testing.assert_array_equal(pmp, [0, 1])
    assert pmp.dtype == np.int64


def test_value_item():
    pp = make_pp()
    ev = make_event()
    assert pp.get_io_item("emg", ev) == pytest.approx(3.5)


# ------------------------------------------------------------ metrics targets
def test_metrics_targets_ppk_padding():
    pp = make_pp()
    ev = make_event(ppks=[100], spks=[200])
    t = pp.get_targets_for_metrics(ev, max_event_num=3, task_names=["ppk", "spk"])
    np.testing.assert_array_equal(t["ppk"], [100, int(-1e7), int(-1e7)])
    assert t["ppk"].dtype == np.int64


def test_metrics_targets_det_expected_num():
    pp = make_pp(add_event_rate=0.5, shift_event_rate=0.0, max_event_num=1)
    ev = make_event(ppks=[100], spks=[200])
    t = pp.get_targets_for_metrics(ev, max_event_num=1, task_names=["det"])
    # expected_num = 1 + 1(add_event) + 0 + 0 = 2 pairs, padded with [1, 0]
    assert pp.expected_det_num() == 2
    np.testing.assert_array_equal(t["det"], [100, 340, 1, 0])


def test_process_noise_event_cleared(rng):
    pp = make_pp()
    ev = make_event(ppks=[200], spks=[100])  # P after S => noise
    out = pp.process(ev, augmentation=False, rng=rng)
    # phases cleared then padded to empty lists
    assert out["ppks"] == [] and out["spks"] == []
    assert out["data"].shape == (3, L_IN)


def test_process_normalizes(rng):
    pp = make_pp()
    ev = make_event(length=2048)
    out = pp.process(ev, augmentation=False, rng=rng)
    np.testing.assert_allclose(out["data"].mean(axis=1), 0, atol=1e-7)
    np.testing.assert_allclose(out["data"].std(axis=1), 1, atol=1e-6)


# --------------------------------------------------------------- augmentation
def test_augmentation_preserves_shapes(rng):
    pp = make_pp(
        add_event_rate=1.0,
        add_noise_rate=1.0,
        add_gap_rate=1.0,
        drop_channel_rate=1.0,
        scale_amplitude_rate=1.0,
        pre_emphasis_rate=1.0,
        shift_event_rate=1.0,
        max_event_num=2,
    )
    ev = make_event(length=4096, ppks=[1000], spks=[1200])
    out = pp.process(ev, augmentation=True, rng=rng)
    assert out["data"].shape == (3, L_IN)
    assert len(out["ppks"]) == len(out["spks"])


def test_generate_noise_clears_labels(rng):
    pp = make_pp(generate_noise_rate=1.0)
    ev = make_event(length=2048)
    out = pp.process(ev, augmentation=True, rng=rng)
    assert out["ppks"] == [] and out["spks"] == []
    assert out["emg"] == 0


def test_shift_event_rolls_phases():
    pp = make_pp()
    rng = np.random.default_rng(42)
    data = np.arange(3 * 100, dtype=np.float64).reshape(3, 100)
    d2, p2, s2 = pp._shift_event(data.copy(), [10], [20], rng)
    shift = int(np.where(d2[0] == 0)[0][0])
    assert p2 == [(10 + shift) % 100]
    assert s2 == [(20 + shift) % 100]


def test_drop_channel_keeps_at_least_one():
    pp = make_pp()
    for seed in range(10):
        data = np.ones((3, 64))
        out = pp._drop_channel(data, np.random.default_rng(seed))
        zeroed = int((np.abs(out).max(axis=1) == 0).sum())
        assert 1 <= zeroed <= 2


def test_pre_emphasis_formula():
    pp = make_pp()
    data = np.random.default_rng(0).normal(size=(2, 32))
    orig = data.copy()
    out = pp._pre_emphasis(data, 0.97)
    np.testing.assert_allclose(out[:, 0], orig[:, 0])
    np.testing.assert_allclose(out[:, 1:], orig[:, 1:] - 0.97 * orig[:, :-1])


def test_add_event_appends_sorted(rng):
    pp = make_pp(max_event_num=3)
    data = np.random.default_rng(1).normal(size=(3, 4096))
    d2, p2, s2 = pp._add_event(data, [100], [200], 0, rng)
    assert len(p2) == 2 and p2 == sorted(p2)
    assert s2[1] - p2[1] == 100  # same P-S gap
